"""Deterministic image corpus for the inference-metric oracle fixtures —
shared by the stored-score test (tests/image/test_inference_fixture.py) and
the generator (scripts/make_image_oracle.py).

Fully seeded: any environment reproduces the SAME image sets, so scores
stored by one environment (e.g. one with network access, pretrained
weights, and the torch-fidelity / official LPIPS packages) pin every other
environment unconditionally — the PESQ stored-corpus pattern
(tests/audio/pesq_corpus.py) applied to FID/KID/IS and LPIPS.
"""
from typing import Tuple

import numpy as np

N_IMAGES = 20
HW = 96


def _structured(rng: np.random.Generator, n: int) -> np.ndarray:
    """Smooth, structured uint8 images: soft blobs + gradients (the 'real'
    distribution)."""
    yy, xx = np.mgrid[0:HW, 0:HW].astype(np.float32) / HW
    imgs = []
    for _ in range(n):
        base = np.zeros((HW, HW, 3), np.float32)
        for _ in range(4):
            cx, cy, r = rng.uniform(0.2, 0.8, 3)
            col = rng.uniform(0.3, 1.0, 3)
            blob = np.exp(-(((xx - cx) ** 2 + (yy - cy) ** 2) / (0.05 + 0.1 * r)))
            base += blob[..., None] * col[None, None, :]
        base += 0.3 * np.stack([xx, yy, 1 - xx], -1)
        base /= max(base.max(), 1e-6)
        imgs.append((base * 255).astype(np.uint8))
    return np.stack(imgs).transpose(0, 3, 1, 2)  # NCHW uint8


def _textured(rng: np.random.Generator, n: int) -> np.ndarray:
    """Noise-textured variants (the 'fake' distribution): structured base
    plus strong high-frequency noise."""
    base = _structured(rng, n).astype(np.float32)
    noise = rng.integers(-60, 60, base.shape).astype(np.float32)
    return np.clip(base + noise, 0, 255).astype(np.uint8)


def fid_sets() -> Tuple[np.ndarray, np.ndarray]:
    """(real, fake) uint8 NCHW image sets for FID/KID/IS."""
    rng = np.random.default_rng(2024)
    return _structured(rng, N_IMAGES), _textured(rng, N_IMAGES)


def lpips_pairs() -> Tuple[np.ndarray, np.ndarray]:
    """(img1, img2) float NCHW pairs in [-1, 1] for LPIPS."""
    rng = np.random.default_rng(4048)
    a = _structured(rng, 8).astype(np.float32) / 127.5 - 1.0
    jitter = rng.normal(0, 0.15, a.shape).astype(np.float32)
    b = np.clip(a + jitter, -1, 1)
    return a, b
