"""Modular SDR / SI-SDR.

Behavior parity with /root/reference/torchmetrics/audio/sdr.py:25-221.
"""
from typing import Any, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.audio.sdr import (
    scale_invariant_signal_distortion_ratio,
    signal_distortion_ratio,
)

Array = jax.Array


class SignalDistortionRatio(Metric):
    """Mean signal-to-distortion ratio (BSS-eval) over all seen signals, in dB.

    Args:
        use_cg_iter: solve the distortion filter with this many conjugate-
            gradient iterations instead of the dense Toeplitz solve.
        filter_length: allowed distortion-filter length (default 512).
        zero_mean: subtract time-axis means before computing.
        load_diag: diagonal loading for near-singular systems.

    Example:
        >>> import numpy as np
        >>> rng = np.random.RandomState(0)
        >>> preds = jnp.asarray(rng.randn(8000))
        >>> target = jnp.asarray(rng.randn(8000))
        >>> sdr = SignalDistortionRatio()
        >>> float(sdr(preds, target)) < 0  # random signals are uncorrelated
        True
    """

    is_differentiable = True
    higher_is_better = True

    def __init__(
        self,
        use_cg_iter: Optional[int] = None,
        filter_length: int = 512,
        zero_mean: bool = False,
        load_diag: Optional[float] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.use_cg_iter = use_cg_iter
        self.filter_length = filter_length
        self.zero_mean = zero_mean
        self.load_diag = load_diag
        self.add_state("sum_sdr", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")

    def _update(self, preds: Array, target: Array) -> None:
        sdr_batch = signal_distortion_ratio(
            preds, target, self.use_cg_iter, self.filter_length, self.zero_mean, self.load_diag
        )
        self.sum_sdr = self.sum_sdr + jnp.sum(sdr_batch)
        self.total = self.total + sdr_batch.size

    def _compute(self) -> Array:
        return self.sum_sdr / self.total


class ScaleInvariantSignalDistortionRatio(Metric):
    """Mean scale-invariant SDR over all seen signals, in dB.

    Args:
        zero_mean: subtract time-axis means before computing.

    Example:
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> si_sdr = ScaleInvariantSignalDistortionRatio()
        >>> si_sdr(preds, target)
        Array(18.402992, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = True

    def __init__(self, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.zero_mean = zero_mean
        self.add_state("sum_si_sdr", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")

    def _update(self, preds: Array, target: Array) -> None:
        si_sdr_batch = scale_invariant_signal_distortion_ratio(preds, target, zero_mean=self.zero_mean)
        self.sum_si_sdr = self.sum_si_sdr + jnp.sum(si_sdr_batch)
        self.total = self.total + si_sdr_batch.size

    def _compute(self) -> Array:
        return self.sum_si_sdr / self.total
