"""Async double-buffered update pipeline (ISSUE 7 tentpole).

Pins the AsyncUpdateHandle contract: bit-identical final states vs the
blocking fused path across sum/max/mean/custom reducers, the three
backpressure policies (block/drop/error), bounded-staleness ``compute()``
semantics, worker-exception re-raise with the originating batch index,
``flush()`` idempotence, reset/add_metrics invalidation, no thread leak
after ``close()``, in-flight byte accounting, and the exactly-one-
``enqueue``-event-per-accepted-batch observability guard.

Every wait in this file is bounded (handle drains use internal timeouts),
so a deadlocked queue fails the test instead of hanging tier-1.
"""
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from metrics_tpu import MetricCollection
from metrics_tpu.classification import Accuracy, ConfusionMatrix
from metrics_tpu.core.metric import Metric
from metrics_tpu.core.pipeline import AsyncQueueFull, AsyncUpdateHandle, AsyncWorkerError
from metrics_tpu.observability import get_recorder
from metrics_tpu.utils.exceptions import MetricsUserError

#: per-batch worker delay for the backpressure/staleness tests — long
#: enough to dominate scheduling jitter, short enough to keep the file fast
_SLOW = 0.05


@pytest.fixture
def recorder():
    rec = get_recorder()
    rec.reset()
    rec.enable()
    try:
        yield rec
    finally:
        rec.disable()
        rec.reset()


def _cls_batch(rng, n=64, c=3):
    preds = rng.rand(n, c).astype(np.float32)
    preds /= preds.sum(-1, keepdims=True)
    return jnp.asarray(preds), jnp.asarray(rng.randint(0, c, n))


class _MaxAbs(Metric):
    """max-reduced state."""

    def __init__(self):
        super().__init__()
        self.add_state("biggest", default=jnp.asarray(0.0), dist_reduce_fx="max")

    def _update(self, preds, target):
        self.biggest = jnp.maximum(self.biggest, jnp.max(jnp.abs(preds)))

    def _compute(self):
        return self.biggest


class _RunningMean(Metric):
    """mean-reduced state — exercises the in-kernel `_n_updates` bump."""

    def __init__(self):
        super().__init__()
        self.add_state("avg", default=jnp.asarray(0.0), dist_reduce_fx="mean")

    def _update(self, preds, target):
        self.avg = (self.avg + jnp.mean(preds)) / 2

    def _compute(self):
        return self.avg


def _colsum(stacked):
    return jnp.sum(stacked, axis=0)


class _CustomReduced(Metric):
    """custom-callable reducer over a vector state."""

    def __init__(self):
        super().__init__()
        self.add_state("cols", default=jnp.zeros(3), dist_reduce_fx=_colsum)

    def _update(self, preds, target):
        self.cols = self.cols + jnp.sum(preds, axis=0)

    def _compute(self):
        return self.cols


class _SlowSum(Metric):
    """Counts applied batches with a deliberately slow eager update — the
    controllable consumer for the backpressure and staleness tests."""

    __jit_unsafe__ = True

    def __init__(self, delay=_SLOW):
        super().__init__()
        self.delay = delay
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")

    def _update(self, preds, target):
        time.sleep(self.delay)
        self.total = self.total + 1.0

    def _compute(self):
        return self.total


class _ProbeFail(Metric):
    """Passes every static fusibility filter (no ``__jit_unsafe__``, no
    wrapper children, no list state) but fails the runtime ``eval_shape``
    probe: a host branch on a traced value. The fused path demotes it to
    the eager fallback — its buffers are never donated."""

    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.zeros(64), dist_reduce_fx="sum")

    def _update(self, preds, target):
        if float(jnp.max(preds)) >= 0:  # host readback: unfusible
            self.total = self.total + jnp.sum(preds) + jnp.zeros(64)

    def _compute(self):
        return self.total


class _ExplodingSum(Metric):
    """Raises when fed the poison marker (first element negative)."""

    __jit_unsafe__ = True

    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")

    def _update(self, preds, target):
        if float(preds.reshape(-1)[0]) < 0:
            raise ValueError("poison batch")
        self.total = self.total + 1.0

    def _compute(self):
        return self.total


def _reducer_collection():
    return MetricCollection(
        [
            Accuracy(),
            ConfusionMatrix(num_classes=3),
            _MaxAbs(),
            _RunningMean(),
            _CustomReduced(),
        ]
    )


def _state_items(col):
    for name, m in col.items(keep_base=True):
        for sname in m._defaults:
            yield f"{name}.{sname}", np.asarray(getattr(m, sname))


# ---------------------------------------------------------------------------
# parity vs the blocking fused path
# ---------------------------------------------------------------------------

class TestParity:
    def test_bit_identical_states_across_reducers(self):
        rng = np.random.RandomState(0)
        batches = [_cls_batch(rng) for _ in range(6)]
        blocking, asynchronous = _reducer_collection(), _reducer_collection()
        blocking.update(*batches[0])  # discovery
        asynchronous.update(*batches[0])
        blocking.compile_update()
        handle = asynchronous.compile_update_async(queue_depth=2)
        for b in batches[1:]:
            blocking.update(*b)
            assert handle.update_async(*b) is True
        handle.flush()
        for (ka, va), (kb, vb) in zip(
            _state_items(asynchronous), _state_items(blocking)
        ):
            assert ka == kb
            assert np.array_equal(va, vb), f"{ka}: async {va} != blocking {vb}"
        res_b, res_a = blocking.compute(), asynchronous.compute()
        assert res_b.keys() == res_a.keys()
        for key in res_b:
            assert bool(jnp.array_equal(res_b[key], res_a[key])), key
        handle.close()

    def test_blocking_update_interleaves_fifo(self):
        rng = np.random.RandomState(1)
        batches = [_cls_batch(rng) for _ in range(5)]
        reference, mixed = _reducer_collection(), _reducer_collection()
        reference.update(*batches[0])
        mixed.update(*batches[0])
        reference.compile_update()
        handle = mixed.compile_update_async()
        for i, b in enumerate(batches[1:]):
            reference.update(*b)
            if i % 2 == 0:
                handle.update_async(*b)
            else:
                mixed.update(*b)  # routes through the handle, FIFO-ordered
        handle.flush()
        for (ka, va), (kb, vb) in zip(_state_items(mixed), _state_items(reference)):
            assert np.array_equal(va, vb), ka
        handle.close()

    def test_compute_default_drains_everything(self):
        rng = np.random.RandomState(2)
        col = MetricCollection([_SlowSum(delay=0.01)])
        col.update(*_cls_batch(rng))
        handle = col.compile_update_async(queue_depth=4)
        for _ in range(4):
            handle.update_async(*_cls_batch(rng))
        # no explicit flush: default max_staleness=0 drains then computes
        assert float(col.compute()["_SlowSum"]) == 5.0
        assert handle.pending == 0
        handle.close()


# ---------------------------------------------------------------------------
# backpressure policies
# ---------------------------------------------------------------------------

class TestBackpressure:
    def test_block_policy_is_lossless_and_blocks(self):
        rng = np.random.RandomState(3)
        col = MetricCollection([_SlowSum()])
        col.update(*_cls_batch(rng))
        handle = col.compile_update_async(queue_depth=1, policy="block")
        t0 = time.perf_counter()
        for _ in range(4):
            handle.update_async(*_cls_batch(rng))
        elapsed = time.perf_counter() - t0
        # depth-1 queue + slow worker: the later puts must have waited
        assert elapsed >= _SLOW, f"update_async never blocked ({elapsed:.3f}s)"
        handle.flush()
        assert handle.enqueued == 4
        assert handle.applied == 4
        assert handle.dropped == 0
        assert float(col.compute()["_SlowSum"]) == 5.0
        handle.close()

    def test_drop_policy_discards_and_counts(self):
        rng = np.random.RandomState(4)
        col = MetricCollection([_SlowSum()])
        col.update(*_cls_batch(rng))
        handle = col.compile_update_async(queue_depth=1, policy="drop")
        accepted = sum(handle.update_async(*_cls_batch(rng)) for _ in range(8))
        handle.flush()
        assert accepted < 8, "a depth-1 queue with a slow worker must drop"
        assert handle.dropped == 8 - accepted
        assert handle.enqueued == accepted
        assert handle.applied == accepted
        # exactly the accepted batches landed in the state (plus discovery)
        assert float(col.compute()["_SlowSum"]) == accepted + 1
        handle.close()

    def test_error_policy_raises_queue_full(self):
        rng = np.random.RandomState(5)
        col = MetricCollection([_SlowSum()])
        col.update(*_cls_batch(rng))
        handle = col.compile_update_async(queue_depth=1, policy="error")
        with pytest.raises(AsyncQueueFull):
            for _ in range(10):
                handle.update_async(*_cls_batch(rng))
        handle.flush()  # the accepted prefix still drains cleanly
        handle.close()

    def test_block_policy_raises_when_worker_dead(self):
        """A dead worker (realistically: interpreter teardown — every
        in-loop failure poisons the handle instead) must surface as an
        error at the producer, never an unbounded queue-slot wait."""
        from metrics_tpu.core.pipeline import _SHUTDOWN

        rng = np.random.RandomState(34)
        col = MetricCollection([_SlowSum(delay=0.0)])
        col.update(*_cls_batch(rng))
        handle = col.compile_update_async(queue_depth=1, policy="block")
        handle.flush()
        handle._queue.put(_SHUTDOWN)  # kill the worker out-of-band
        handle._thread.join(timeout=5.0)
        assert not handle._thread.is_alive()
        assert handle.update_async(*_cls_batch(rng))  # empty queue: accepted
        with pytest.raises(MetricsUserError):
            handle.update_async(*_cls_batch(rng))  # full queue, dead worker
        # a draining close on the full queue must ALSO not deadlock: the
        # sentinel put is liveness-guarded (an atexit/finally close() is
        # exactly where a dead worker shows up)
        handle.close()
        assert handle.closed

    def test_invalid_policy_and_depth_rejected(self):
        col = MetricCollection([Accuracy()])
        with pytest.raises(ValueError):
            col.compile_update_async(policy="spill")
        with pytest.raises(ValueError):
            col.compile_update_async(queue_depth=0)
        with pytest.raises(ValueError):
            col.compile_update_async(max_staleness=-1)
        # the failed constructions must not leave a live handle behind
        if col.async_update is not None:
            col.async_update.close()


# ---------------------------------------------------------------------------
# bounded-staleness compute
# ---------------------------------------------------------------------------

class TestStaleness:
    def test_bounded_staleness_returns_early(self):
        rng = np.random.RandomState(6)
        delay = 0.1  # big enough that blocking for the full drain (0.6s+)
        # is clearly separable from the bounded wait (~2 applications plus
        # at most one in-flight dispatch's state-lock hold plus jitter)
        col = MetricCollection([_SlowSum(delay=delay)])
        col.update(*_cls_batch(rng))
        handle = col.compile_update_async(queue_depth=8, max_staleness=0)
        for _ in range(6):
            handle.update_async(*_cls_batch(rng))
        t0 = time.perf_counter()
        res = handle.compute(max_staleness=4)
        t_bounded = time.perf_counter() - t0
        assert float(res["_SlowSum"]) >= 3.0  # discovery + at least 2 applied
        assert handle.pending <= 4
        t1 = time.perf_counter()
        handle.flush()
        t_flush = time.perf_counter() - t1
        # waited for AT MOST (6 - 4) applications, never the full drain:
        # either the bounded wait released quickly, or — when the whole box
        # is scheduler-stalled and wall bounds lie — real drain work
        # demonstrably remained for flush() afterwards. A compute() that
        # wrongly blocked for the full drain fails BOTH (long wait AND an
        # instant residual flush).
        assert t_bounded < 5 * delay or t_flush > delay, (
            f"bounded compute drained fully"
            f" (bounded={t_bounded:.3f}s, residual flush={t_flush:.3f}s)"
        )
        # the default bound (0) then gives the exact drained answer
        assert float(handle.compute()["_SlowSum"]) == 7.0
        assert handle.pending == 0
        handle.close()

    def test_stale_compute_cache_invalidated_by_inflight_batches(self):
        """A bounded-staleness compute overlapping in-flight batches must
        not leave its stale value in the `_computed` cache: each install
        clears the cache, but a compute FINISHING afterwards writes the old
        snapshot back with no later update to clear it — the next (drained)
        compute would then serve the stale answer."""

        class _SlowCompute(Metric):
            __jit_unsafe__ = True

            def __init__(self):
                super().__init__()
                self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")

            def _update(self, preds, target):
                time.sleep(0.02)
                self.total = self.total + 1.0

            def _compute(self):
                snap = self.total  # snapshot BEFORE the slow part
                time.sleep(0.15)   # batches land while this compute runs
                return snap

        rng = np.random.RandomState(22)
        col = MetricCollection([_SlowCompute()])
        col.update(*_cls_batch(rng))
        handle = col.compile_update_async(queue_depth=8)
        for _ in range(6):
            handle.update_async(*_cls_batch(rng))
        stale = float(handle.compute(max_staleness=4)["_SlowCompute"])
        assert stale <= 7.0
        handle.flush()
        # the drained compute must reflect every batch, not the cache
        assert float(col.compute()["_SlowCompute"]) == 7.0
        handle.close()

    def test_compute_never_overlaps_inflight_dispatch(self):
        """On donating backends a dispatch's old state buffers are dead
        until the new ones are installed — reading them raises, it does not
        return stale values. A bounded-staleness compute() whose bound is
        already satisfied must therefore still wait out an in-flight
        dispatch's ownership window (stale reads are allowed, deleted reads
        are not)."""
        rng = np.random.RandomState(30)
        col = MetricCollection([Accuracy()])
        col.update(*_cls_batch(rng))
        handle = col.compile_update_async(queue_depth=4, max_staleness=8)
        in_dispatch = threading.Event()
        release = threading.Event()
        real = handle._fused.dispatch

        def gated(args, kwargs):
            in_dispatch.set()
            assert release.wait(5), "test gate never released"
            real(args, kwargs)

        handle._fused.dispatch = gated
        try:
            handle.update_async(*_cls_batch(rng))
            assert in_dispatch.wait(5)
            # pending (1) is already within the bound (8): compute must
            # block on the dispatch window, not interleave with it
            out = {}
            t = threading.Thread(target=lambda: out.setdefault("res", col.compute()))
            t.start()
            t.join(0.3)
            assert t.is_alive(), "compute() overlapped an in-flight dispatch"
            release.set()
            t.join(5)
            assert not t.is_alive() and "res" in out
        finally:
            release.set()
            handle._fused.dispatch = real
        handle.flush()
        handle.close()

    def test_stale_handle_compute_rejected(self):
        # the collection consults ITS current handle for the staleness
        # bound; a per-call override on a replaced handle would be silently
        # ignored and hand back a staler snapshot than the caller asked for
        rng = np.random.RandomState(43)
        col = MetricCollection([_SlowSum(delay=0.0)])
        col.update(*_cls_batch(rng))
        h1 = col.compile_update_async()
        h2 = col.compile_update_async()  # drains + replaces h1
        with pytest.raises(MetricsUserError):
            h1.compute(max_staleness=0)
        assert "_SlowSum" in h2.compute()
        h2.close()
        with pytest.raises(MetricsUserError):
            h2.compute()  # closed is stale too

    def test_negative_bound_rejected(self):
        rng = np.random.RandomState(7)
        col = MetricCollection([Accuracy()])
        col.update(*_cls_batch(rng))
        handle = col.compile_update_async()
        with pytest.raises(ValueError):
            handle.compute(max_staleness=-2)
        handle.close()


# ---------------------------------------------------------------------------
# worker-exception propagation
# ---------------------------------------------------------------------------

class TestWorkerErrors:
    def _poison_batch(self, rng):
        preds, target = _cls_batch(rng)
        return preds.at[0, 0].set(-1.0), target

    def test_reraise_with_batch_index_and_cause(self):
        rng = np.random.RandomState(8)
        col = MetricCollection([_ExplodingSum()])
        col.update(*_cls_batch(rng))
        handle = col.compile_update_async(queue_depth=8)
        # the error surfaces at the NEXT call site after the worker hits the
        # poison — usually flush(), but a fast worker may beat a later
        # enqueue to it; both are the documented contract
        with pytest.raises(AsyncWorkerError) as err:
            for i in range(5):
                batch = self._poison_batch(rng) if i == 3 else _cls_batch(rng)
                handle.update_async(*batch)
            handle.flush()
        assert err.value.batch_index == 3
        assert isinstance(err.value.__cause__, ValueError)
        # sticky poison: the next ingest raises too, and queued batches
        # after the failure were discarded, never half-applied
        with pytest.raises(AsyncWorkerError):
            handle.update_async(*_cls_batch(rng))
        assert handle.applied == 3
        handle.close()

    def test_compute_also_reraises(self):
        rng = np.random.RandomState(9)
        col = MetricCollection([_ExplodingSum()])
        col.update(*_cls_batch(rng))
        handle = col.compile_update_async()
        handle.update_async(*self._poison_batch(rng))
        with pytest.raises(AsyncWorkerError):
            col.compute()
        handle.close()

    def test_recompile_surfaces_pending_worker_error(self):
        rng = np.random.RandomState(38)
        col = MetricCollection([_ExplodingSum()])
        col.update(*_cls_batch(rng))
        handle = col.compile_update_async(queue_depth=8)
        handle.update_async(*self._poison_batch(rng))
        deadline = time.monotonic() + 5
        while handle.pending and time.monotonic() < deadline:
            time.sleep(0.005)
        # periodic re-compile without reset(): the captured error must
        # surface here, not vanish into a close() that never raises while
        # the poisoned worker silently discards the queued batches
        with pytest.raises(AsyncWorkerError) as err:
            col.compile_update_async()
        assert err.value.batch_index == 0
        # reset() is the documented recovery: discard, then re-arm cleanly
        col.reset()
        h2 = col.compile_update_async()
        assert h2 is not handle and not h2.closed
        h2.close()


# ---------------------------------------------------------------------------
# flush / close / lifecycle invalidation
# ---------------------------------------------------------------------------

class TestLifecycle:
    def test_flush_is_idempotent(self):
        rng = np.random.RandomState(10)
        col = _reducer_collection()
        col.update(*_cls_batch(rng))
        handle = col.compile_update_async()
        for _ in range(3):
            handle.update_async(*_cls_batch(rng))
        assert handle.flush() >= 0
        assert handle.flush() == 0  # drained: returns immediately
        assert handle.flush() == 0
        assert handle.applied == 3
        handle.close()

    def test_no_thread_leak_after_close(self):
        rng = np.random.RandomState(11)
        before = threading.active_count()
        col = _reducer_collection()
        col.update(*_cls_batch(rng))
        handle = col.compile_update_async()
        assert threading.active_count() == before + 1
        handle.update_async(*_cls_batch(rng))
        handle.close()
        assert threading.active_count() == before
        handle.close()  # idempotent
        assert threading.active_count() == before

    def test_close_drains_by_default(self):
        rng = np.random.RandomState(12)
        col = MetricCollection([_SlowSum(delay=0.01)])
        col.update(*_cls_batch(rng))
        handle = col.compile_update_async(queue_depth=8)
        for _ in range(4):
            handle.update_async(*_cls_batch(rng))
        handle.close()  # drain=True
        assert handle.applied == 4
        assert float(col.compute()["_SlowSum"]) == 5.0

    def test_worker_discards_when_flagged(self):
        """close(drain=False) may lose the queue race to the worker; the
        worker must then discard the item it won, never apply it — queued
        batches landing on reset/add_metrics would be nondeterministic."""
        rng = np.random.RandomState(32)
        col = MetricCollection([_SlowSum(delay=0.0)])
        col.update(*_cls_batch(rng))
        before = float(col.compute()["_SlowSum"])
        handle = col.compile_update_async(queue_depth=4)
        handle._discard = True  # the close(drain=False) race window
        handle.update_async(*_cls_batch(rng))
        handle.flush()
        assert handle.applied == 0
        handle._discard = False
        assert float(col.compute()["_SlowSum"]) == before
        handle.close()

    def test_abandoned_handle_does_not_leak_worker(self):
        """A handle dropped WITHOUT close() must not be pinned forever by
        its own parked worker: the thread holds only a weakref, and a GC
        finalizer wakes the ``queue.get()`` park so it exits — N abandoned
        per-job collections would otherwise leak N daemon threads plus
        every collection's device state."""
        import gc

        rng = np.random.RandomState(33)
        before = threading.active_count()
        col = _reducer_collection()
        col.update(*_cls_batch(rng))
        handle = col.compile_update_async()
        handle.update_async(*_cls_batch(rng))
        handle.flush()
        thread = handle._thread
        del handle, col  # abandoned: no close(), refs dropped
        gc.collect()
        thread.join(timeout=5.0)
        assert not thread.is_alive()
        assert threading.active_count() == before

    def test_closed_handle_rejects_updates(self):
        rng = np.random.RandomState(13)
        col = _reducer_collection()
        col.update(*_cls_batch(rng))
        handle = col.compile_update_async()
        handle.close()
        with pytest.raises(MetricsUserError):
            handle.update_async(*_cls_batch(rng))
        # the collection falls back to the blocking fused path
        col.update(*_cls_batch(rng))

    def test_reset_invalidates_and_discards(self):
        rng = np.random.RandomState(14)
        before = threading.active_count()
        col = MetricCollection([_SlowSum()])
        col.update(*_cls_batch(rng))
        handle = col.compile_update_async(queue_depth=8)
        for _ in range(4):
            handle.update_async(*_cls_batch(rng))
        col.reset()
        assert col.async_update is None
        assert handle.closed
        assert threading.active_count() == before
        with pytest.raises(MetricsUserError):
            handle.update_async(*_cls_batch(rng))
        # states are pristine: only post-reset updates count
        col.update(*_cls_batch(rng))
        assert float(col.compute()["_SlowSum"]) == 1.0

    def test_add_metrics_invalidates(self):
        rng = np.random.RandomState(15)
        col = _reducer_collection()
        col.update(*_cls_batch(rng))
        handle = col.compile_update_async()
        col.add_metrics({"extra": _SlowSum(delay=0.0)})
        assert col.async_update is None
        assert handle.closed
        assert col.fused_update is None  # same invalidation as compile_update

    def test_clone_drops_handle(self):
        rng = np.random.RandomState(16)
        col = _reducer_collection()
        col.update(*_cls_batch(rng))
        handle = col.compile_update_async()
        clone = col.clone(prefix="c_")
        assert clone.async_update is None
        assert clone.fused_update is None
        clone.update(*_cls_batch(rng))  # eager path works on the clone
        handle.close()

    def test_setitem_invalidates_handles(self):
        # mc["name"] = metric is the dict-style membership change: it must
        # invalidate exactly like add_metrics(), or the worker keeps
        # writing through the stale fused kernel in the background
        rng = np.random.RandomState(41)
        col = MetricCollection([_SlowSum(delay=0.0)])
        col.update(*_cls_batch(rng))  # discovers groups for the old set
        handle = col.compile_update_async()
        col["extra"] = _MaxAbs()
        assert handle.closed
        assert col.async_update is None and col.fused_update is None
        with pytest.raises(MetricsUserError):
            col.update_async(*_cls_batch(rng))
        # the compute groups were reseeded, NOT merged from the pre-insert
        # set: the new member must keep receiving updates after rediscovery
        col.update(*_cls_batch(rng))  # re-discovery pass
        col.update(*_cls_batch(rng))  # grouped pass
        assert any("extra" in cg for cg in col.compute_groups.values())
        assert float(col.compute()["extra"]) > 0.0

    def test_compile_update_config_change_rejected_while_async_open(self):
        # a config-changing rebuild under a live worker would install a
        # second fused handle the async path never routes to (and racing
        # dispatches on the same state arrays); same-config warm reuse is
        # fine, and a closed handle lifts the restriction
        rng = np.random.RandomState(44)
        col = MetricCollection([_SlowSum(delay=0.0)])
        col.update(*_cls_batch(rng))
        handle = col.compile_update_async()
        assert col.compile_update() is col.fused_update  # matching config
        with pytest.raises(MetricsUserError):
            col.compile_update(use_manifest=False)
        handle.close()
        assert col.compile_update(use_manifest=False) is col.fused_update

    def test_update_async_without_handle_raises(self):
        col = _reducer_collection()
        # same typed misuse error as the handle's own methods, so callers
        # can catch the package's user-error type uniformly
        with pytest.raises(MetricsUserError):
            col.update_async(jnp.zeros((2, 3)), jnp.zeros(2, jnp.int32))

    def test_epoch_resume_reuses_warm_fused_handle(self):
        # reset(); compile_update_async() must NOT discard the warm compile
        # cache — an epoch loop would otherwise pay a fresh XLA build of the
        # fused kernel every epoch while the blocking path resumed for free
        rng = np.random.RandomState(30)
        col = _reducer_collection()
        col.update(*_cls_batch(rng))
        h1 = col.compile_update_async()
        fused1 = col.fused_update
        h1.update_async(*_cls_batch(rng))
        col.reset()
        h2 = col.compile_update_async()
        assert h2 is not h1 and h1.closed
        assert col.fused_update is fused1
        h2.update_async(*_cls_batch(rng))
        h2.flush()
        h2.close()
        # a runtime stale-manifest demotion flips the live flag but not the
        # REQUEST: warm reuse must keep matching, or every epoch rebuilds a
        # fresh manifest-trusting handle that re-hits the stale manifest
        fused1._use_manifest = False
        assert col.compile_update() is fused1
        # a different requested config is a real rebuild, never a stale reuse
        f2 = col.compile_update(use_manifest=False)
        assert f2 is not fused1


# ---------------------------------------------------------------------------
# in-flight byte accounting (the state_footprint undercount fix)
# ---------------------------------------------------------------------------

class TestInFlightAccounting:
    def test_deleted_arrays_pin_no_footprint(self):
        # a donated buffer mid-dispatch is DELETED (XLA aliases it into the
        # kernel output) — its metadata nbytes must count 0, or
        # total_state_bytes() double-books the bytes the handle already
        # reports as donated in-flight state
        from metrics_tpu.observability.recorder import _nbytes

        x = jnp.arange(16, dtype=jnp.float32)
        assert _nbytes(x) == 64
        x.delete()
        assert _nbytes(x) == 0

    def test_total_state_bytes_includes_queued_batches(self):
        rng = np.random.RandomState(17)
        col = MetricCollection([_SlowSum()])
        col.update(*_cls_batch(rng))
        base = col.total_state_bytes()
        handle = col.compile_update_async(queue_depth=8)
        batch = _cls_batch(rng)
        batch_bytes = sum(int(np.asarray(b).nbytes) for b in batch)
        for _ in range(3):
            handle.update_async(*batch)
        inflated = col.total_state_bytes()
        assert handle.in_flight_bytes >= batch_bytes  # >=1 batch still queued
        assert inflated >= base + handle.in_flight_bytes - 1
        handle.flush()
        assert handle.in_flight_bytes == 0
        assert col.total_state_bytes() == base
        handle.close()

    def test_donated_state_bytes_dedups_groups_and_skips_eager(self):
        from metrics_tpu.classification import Precision, Recall

        rng = np.random.RandomState(29)
        col = MetricCollection(
            [
                Precision(num_classes=3, average="macro"),
                Recall(num_classes=3, average="macro"),
                _SlowSum(delay=0.0),  # jit-unsafe: buffers never donated
            ]
        )
        col.update(*_cls_batch(rng))  # group discovery
        fused = col.compile_update()
        assert col._groups_checked and any(len(cg) > 1 for cg in col._groups.values())
        donated = fused.donated_state_bytes()
        leaders = [cg[0] for cg in col._groups.values()]
        expect = sum(
            col._metrics[n].total_state_bytes()
            for n in leaders
            if not getattr(col._metrics[n], "__jit_unsafe__", False)
        )
        assert donated == expect
        # strictly less than the naive per-metric sum the worker used to
        # book: group members would double-count the leader's arrays and
        # the eager member's buffers are never owned by the kernel
        assert donated < sum(m.total_state_bytes() for m in col.values())

    def test_donated_state_bytes_excludes_probe_failed_members(self):
        """A member that passes the static filters but fails the runtime
        eval_shape probe updates eagerly — its buffers stay alive through
        the whole batch, so counting them as dispatch-owned would book the
        same bytes twice (live state + donated in-flight) on every batch."""
        rng = np.random.RandomState(39)
        col = MetricCollection([_MaxAbs(), _ProbeFail()])
        col.update(*_cls_batch(rng))  # group discovery
        fused = col.compile_update()
        naive = fused.donated_state_bytes()  # probe hasn't run yet
        col.update(*_cls_batch(rng))  # fused path probes, demotes _ProbeFail
        assert "_ProbeFail" in fused._eager_names
        donated = fused.donated_state_bytes()
        assert donated == naive - col._metrics["_ProbeFail"].total_state_bytes()
        assert donated == col._metrics["_MaxAbs"].total_state_bytes()

    def test_footprint_hwm_carries_async_label(self, recorder):
        from metrics_tpu.observability.recorder import ASYNC_IN_FLIGHT_LABEL

        rng = np.random.RandomState(18)
        col = MetricCollection([_SlowSum(delay=0.01)])
        col.update(*_cls_batch(rng))
        handle = col.compile_update_async(queue_depth=4)
        for _ in range(4):
            handle.update_async(*_cls_batch(rng))
        handle.flush()
        hwm = recorder.footprint_high_water_marks()
        assert hwm.get(ASYNC_IN_FLIGHT_LABEL, 0) > 0
        handle.close()


# ---------------------------------------------------------------------------
# observability guard
# ---------------------------------------------------------------------------

class TestObservability:
    def test_exactly_one_enqueue_event_per_accepted_batch(self, recorder):
        rng = np.random.RandomState(19)
        col = _reducer_collection()
        col.update(*_cls_batch(rng))
        handle = col.compile_update_async(queue_depth=2)
        n = 5
        for _ in range(n):
            handle.update_async(*_cls_batch(rng))
        handle.flush()
        events = recorder.events()
        assert sum(1 for e in events if e["type"] == "enqueue") == n
        assert sum(1 for e in events if e["type"] == "dequeue") == n
        assert sum(1 for e in events if e["type"] == "flush") >= 1
        totals = recorder.async_totals()
        assert totals["enqueued"] == n
        assert totals["applied"] == n
        assert totals["dropped"] == 0
        assert totals["max_in_flight_bytes"] > 0
        handle.close()

    def test_dropped_batches_counted_not_evented(self, recorder):
        rng = np.random.RandomState(20)
        col = MetricCollection([_SlowSum()])
        col.update(*_cls_batch(rng))
        handle = col.compile_update_async(queue_depth=1, policy="drop")
        accepted = sum(handle.update_async(*_cls_batch(rng)) for _ in range(8))
        handle.flush()
        events = recorder.events()
        assert sum(1 for e in events if e["type"] == "enqueue") == accepted
        totals = recorder.async_totals()
        assert totals["dropped"] == 8 - accepted
        assert totals["dropped"] > 0
        handle.close()

    def test_dropped_batch_index_never_reused(self, recorder):
        """A dropped batch consumes its index (monotonic attempt counter):
        an operator correlating the event stream must never see one
        batch_index both dropped and applied."""
        rng = np.random.RandomState(35)
        col = MetricCollection([_SlowSum(delay=0.0)])
        col.update(*_cls_batch(rng))
        handle = col.compile_update_async(queue_depth=1, policy="drop")
        in_dispatch = threading.Event()
        release = threading.Event()
        real = handle._fused.dispatch

        def gated(args, kwargs):
            in_dispatch.set()
            assert release.wait(5), "test gate never released"
            return real(args, kwargs)

        handle._fused.dispatch = gated
        assert handle.update_async(*_cls_batch(rng))  # idx 0: worker takes it
        assert in_dispatch.wait(5)
        assert handle.update_async(*_cls_batch(rng))  # idx 1: queued (full)
        assert not handle.update_async(*_cls_batch(rng))  # idx 2: dropped
        release.set()
        handle.flush()
        assert handle.update_async(*_cls_batch(rng))  # idx 3, NOT a reused 2
        handle.flush()
        events = recorder.events()
        enq = [e["batch_index"] for e in events if e["type"] == "enqueue"]
        deq = [e["batch_index"] for e in events if e["type"] == "dequeue"]
        assert enq == [0, 1, 3] == deq  # the dropped batch consumed index 2
        assert handle.dropped == 1 and handle.enqueued == 3
        handle.close()

    def test_discard_close_is_not_a_flush(self, recorder):
        rng = np.random.RandomState(31)
        col = MetricCollection([_SlowSum()])
        col.update(*_cls_batch(rng))
        handle = col.compile_update_async(queue_depth=8)
        handle.update_async(*_cls_batch(rng))
        handle.flush()
        assert recorder.async_totals()["flushes"] == 1
        # per-batch blocking updates drain but are NOT epoch-boundary
        # flushes — counting them would make the counter track batch count
        col.update(*_cls_batch(rng))
        assert recorder.async_totals()["flushes"] == 1
        handle.update_async(*_cls_batch(rng))
        # reset() -> close(drain=False): batches are DISCARDED, so counting
        # it as a flush would report deterministic drains that never happened
        col.reset()
        assert recorder.async_totals()["flushes"] == 1
        # a draining close IS a deterministic drain and does count
        h2 = col.compile_update_async()
        h2.close(drain=True)
        assert recorder.async_totals()["flushes"] == 2

    def test_prometheus_and_aggregate_carry_async_counters(self, recorder):
        from metrics_tpu.observability import aggregate_across_hosts

        rng = np.random.RandomState(21)
        col = _reducer_collection()
        col.update(*_cls_batch(rng))
        handle = col.compile_update_async()
        handle.update_async(*_cls_batch(rng))
        handle.flush()
        page = recorder.render_prometheus()
        # terminal outcomes stay disjoint (applied|dropped); ingress and
        # flush operations are their own families so sum() over the batch
        # family never double-counts
        assert 'metrics_tpu_async_batches_total{outcome="applied"} 1' in page
        assert 'outcome="enqueued"' not in page
        assert 'outcome="flushes"' not in page
        assert "metrics_tpu_async_enqueued_total 1" in page
        assert "metrics_tpu_async_flushes_total 1" in page
        assert "metrics_tpu_async_queue_depth" in page
        assert "metrics_tpu_async_in_flight_bytes" in page
        agg = aggregate_across_hosts(recorder)
        assert agg["async_totals"]["enqueued"] == 1
        assert agg["async_totals"]["applied"] == 1
        handle.close()


# ---------------------------------------------------------------------------
# checkpoint / copy guards — state access drains the open handle
# ---------------------------------------------------------------------------

class TestStateAccessGuards:
    def test_state_dict_drains_open_handle(self):
        """A mid-epoch checkpoint must include every accepted batch — and on
        a donating backend, must not serialize the dispatch window's dead
        arrays ('Array has been deleted')."""
        rng = np.random.RandomState(36)
        col = MetricCollection([_SlowSum(delay=0.02)])
        col.update(*_cls_batch(rng))
        handle = col.compile_update_async(queue_depth=8)
        for _ in range(4):
            handle.update_async(*_cls_batch(rng))
        sd = col.state_dict()
        assert handle.pending == 0
        assert float(np.asarray(sd["_SlowSum.total"])) == 5.0
        handle.close()

    def test_load_state_dict_applies_queued_batches_first(self):
        """Accepted-but-queued batches land on the OLD state before the load
        replaces it — the ordering a blocking loop would have produced; a
        stale batch applied on top of freshly loaded state is corruption."""
        rng = np.random.RandomState(37)
        clean = MetricCollection([_SlowSum(delay=0.0)]).state_dict()
        col = MetricCollection([_SlowSum(delay=0.02)])
        col.update(*_cls_batch(rng))
        handle = col.compile_update_async(queue_depth=8)
        for _ in range(3):
            handle.update_async(*_cls_batch(rng))
        col.load_state_dict(clean)
        assert handle.pending == 0  # drained BEFORE the load, not after
        assert float(col.compute()["_SlowSum"]) == 0.0
        handle.close()

    def test_to_device_and_set_dtype_drain(self):
        # both replace every state array: queued batches must land on the
        # pre-move state, never race the worker's donation window
        import jax

        rng = np.random.RandomState(42)
        col = MetricCollection([_SlowSum(delay=0.02)])
        col.update(*_cls_batch(rng))
        handle = col.compile_update_async(queue_depth=8)
        for _ in range(3):
            handle.update_async(*_cls_batch(rng))
        col.set_dtype(jnp.float32)
        assert handle.pending == 0
        for _ in range(2):
            handle.update_async(*_cls_batch(rng))
        col.to_device(jax.devices()[0])
        assert handle.pending == 0
        assert float(col.compute()["_SlowSum"]) == 6.0
        handle.close()

    def test_clone_drains_open_handle(self):
        rng = np.random.RandomState(40)
        col = MetricCollection([_SlowSum(delay=0.02)])
        col.update(*_cls_batch(rng))
        handle = col.compile_update_async(queue_depth=8)
        for _ in range(3):
            handle.update_async(*_cls_batch(rng))
        mc = col.clone()
        # the copy carries every accepted batch and no live handle/thread
        assert mc.async_update is None
        assert float(mc.compute()["_SlowSum"]) == 4.0
        handle.close()
