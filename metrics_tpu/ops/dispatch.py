"""Shared kernel dispatch layer for the ``ops/`` Pallas suite.

``box_iou_pallas.py`` proved the pattern — a host wrapper that routes
between a Pallas TPU kernel and a jnp fallback on backend/shape/dtype
heuristics — but kept it private. This module extracts the pattern into a
registry every hot op shares, so the routing policy, the escape hatches,
and the observability are written once:

* **Registry** — :func:`register_kernel` binds an op name to a Pallas
  implementation, a jnp fallback, and a ``route`` predicate (the
  shape/dtype heuristic deciding whether the Pallas path wins). jnp-only
  ops register with ``pallas_fn=None`` and always take the fallback —
  they still exist in the registry so their dispatch traffic is counted
  and a kernel can be slotted in later without touching callers.
* **Routing** — :func:`dispatch` picks the backend per call: the Pallas
  kernel runs only on a real TPU backend, when the op's ``route``
  predicate accepts the arguments, and when the escape hatch is off.
  Everything else takes the jnp fallback, so CPU-only CI and exotic
  dtypes are always correct.
* **Escape hatch** — setting the environment variable
  ``METRICS_TPU_NO_PALLAS`` (to any non-empty value) forces every op to
  its jnp fallback, beating both the route predicate and a forced mode.
  This is the production kill switch for a suspect kernel: no redeploy,
  values stay dispatch-invariant by the parity contract.
* **Interpret parity mode** — :func:`forced_backend` is the test-side
  lever: ``with forced_backend("interpret")`` routes every dispatch
  through the REAL Pallas kernel bodies in interpreter mode on CPU, which
  is how the ``tests/ops/`` parity suite pins kernel-vs-fallback
  agreement without TPU hardware.
* **Observability** — every dispatch bumps a ``(op, backend)`` counter on
  the default telemetry recorder (one ``enabled`` bool check when
  telemetry is off), exported as the Prometheus family
  ``metrics_tpu_ops_dispatch_total{op,backend}`` and summed across hosts
  by ``aggregate_across_hosts`` — the fleet view of which backends
  actually ran kernels vs fallbacks.

Dispatch decisions are made in host Python at trace time (backend, env,
and shapes are all static under ``jit``), so a dispatched op inside a
fused/jitted update costs nothing at execution time. Jitted callers that
cache traces (e.g. the sketch ``_absorb`` kernel) must key their cache on
:func:`dispatch_mode` so a forced interpret test or a flipped env var
cannot be shadowed by a stale trace.
"""
from __future__ import annotations

import contextlib
import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

import jax

__all__ = [
    "NO_PALLAS_ENV",
    "KernelSpec",
    "register_kernel",
    "get_kernel",
    "kernel_names",
    "pallas_disabled",
    "forced_backend",
    "dispatch_mode",
    "dispatch",
]

#: environment escape hatch: any non-empty value forces every registered
#: op to its jnp fallback (kill switch for a suspect kernel)
NO_PALLAS_ENV = "METRICS_TPU_NO_PALLAS"


@dataclass(frozen=True)
class KernelSpec:
    """One registered op: a Pallas kernel, its jnp fallback, and the
    routing predicate that decides (from the call's arguments) whether
    the Pallas path is expected to win on TPU.

    ``pallas_fn`` receives the call's arguments plus an ``interpret``
    keyword; ``jnp_fn`` receives the arguments verbatim. ``route`` must be
    a cheap, host-side shape/dtype predicate — it runs on every dispatch.
    """

    name: str
    pallas_fn: Optional[Callable[..., Any]]
    jnp_fn: Callable[..., Any]
    route: Callable[..., bool]


_REGISTRY: Dict[str, KernelSpec] = {}
_REGISTRY_LOCK = threading.Lock()

# test-side forced mode ("interpret" | "jnp" | None); thread-local so a
# parity test forcing interpret cannot leak into a concurrent async worker
_FORCED = threading.local()


def register_kernel(
    name: str,
    *,
    pallas_fn: Optional[Callable[..., Any]],
    jnp_fn: Callable[..., Any],
    route: Optional[Callable[..., bool]] = None,
) -> KernelSpec:
    """Register (or replace) an op in the dispatch registry."""
    if not callable(jnp_fn):
        raise TypeError(f"kernel {name!r}: jnp_fn must be callable (the always-correct fallback)")
    if route is None:
        route = (lambda *a, **k: True) if pallas_fn is not None else (lambda *a, **k: False)
    spec = KernelSpec(name=name, pallas_fn=pallas_fn, jnp_fn=jnp_fn, route=route)
    with _REGISTRY_LOCK:
        _REGISTRY[name] = spec
    return spec


def get_kernel(name: str) -> KernelSpec:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"no kernel {name!r} in the ops dispatch registry; registered: {sorted(_REGISTRY)}"
        ) from None


def kernel_names() -> Tuple[str, ...]:
    return tuple(sorted(_REGISTRY))


def pallas_disabled() -> bool:
    """True when the ``METRICS_TPU_NO_PALLAS`` kill switch is set."""
    return bool(os.environ.get(NO_PALLAS_ENV))


@contextlib.contextmanager
def forced_backend(mode: Optional[str]) -> Iterator[None]:
    """Force every dispatch in this thread to ``"interpret"`` (the real
    Pallas kernel bodies under the interpreter — the CPU parity mode) or
    ``"jnp"`` (the fallback) until the context exits. ``None`` restores
    normal routing. The ``METRICS_TPU_NO_PALLAS`` hatch still wins over
    a forced ``"interpret"`` — the kill switch must be absolute."""
    if mode not in (None, "interpret", "jnp"):
        raise ValueError(f"forced_backend mode must be 'interpret', 'jnp', or None, got {mode!r}")
    prev = getattr(_FORCED, "mode", None)
    _FORCED.mode = mode
    try:
        yield
    finally:
        _FORCED.mode = prev


def dispatch_mode() -> Tuple[Optional[str], bool, str]:
    """The (forced_mode, hatch_set, default_backend) triple a jitted
    caller must fold into its trace-cache key: any component changing can
    change which backend :func:`dispatch` picks inside the trace."""
    return (getattr(_FORCED, "mode", None), pallas_disabled(), jax.default_backend())


_RECORDER: Any = None


def _recorder() -> Any:
    """The default telemetry recorder, imported lazily: ``utils/data.py``
    (imported by nearly everything) calls into this module, so a module-
    level recorder import would cycle through ``observability``."""
    global _RECORDER
    if _RECORDER is None:
        from metrics_tpu.observability.recorder import _DEFAULT_RECORDER

        _RECORDER = _DEFAULT_RECORDER
    return _RECORDER


def _count(op: str, backend: str) -> None:
    rec = _recorder()
    if rec.enabled:
        rec.record_ops_dispatch(op, backend)


def choose_backend(spec: KernelSpec, *args: Any, **kwargs: Any) -> str:
    """The routing decision alone (``"pallas" | "interpret" | "jnp"``),
    without running anything — what :func:`dispatch` executes and what the
    routing tests assert on."""
    if pallas_disabled():
        return "jnp"
    forced = getattr(_FORCED, "mode", None)
    if forced == "jnp":
        return "jnp"
    if forced == "interpret":
        return "interpret" if spec.pallas_fn is not None else "jnp"
    if (
        spec.pallas_fn is not None
        and jax.default_backend() == "tpu"
        and spec.route(*args, **kwargs)
    ):
        return "pallas"
    return "jnp"


def dispatch(name: str, *args: Any, **kwargs: Any) -> Any:
    """Run op ``name`` on the routed backend and count the dispatch."""
    spec = get_kernel(name)
    backend = choose_backend(spec, *args, **kwargs)
    _count(name, backend)
    if backend == "pallas":
        return spec.pallas_fn(*args, **kwargs)
    if backend == "interpret":
        return spec.pallas_fn(*args, interpret=True, **kwargs)
    return spec.jnp_fn(*args, **kwargs)
