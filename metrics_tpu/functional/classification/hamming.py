"""Hamming distance functional kernel.

Behavior parity with /root/reference/torchmetrics/functional/classification/
hamming.py:22-100.
"""
from typing import Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _input_format_classification

Array = jax.Array


def _hamming_distance_update(preds: Array, target: Array, threshold: float = 0.5) -> Tuple[Array, int]:
    """Reference hamming.py:22-41."""
    preds, target, _ = _input_format_classification(preds, target, threshold=threshold)
    correct = jnp.sum(preds == target)
    total = preds.size
    return correct, total


def _hamming_distance_compute(correct: Array, total: Union[int, Array]) -> Array:
    """Reference hamming.py:44-59."""
    return 1 - correct.astype(jnp.float32) / total


def hamming_distance(preds: Array, target: Array, threshold: float = 0.5) -> Array:
    """Average Hamming distance (a.k.a. Hamming loss). Reference hamming.py:62-100.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([[0, 1], [1, 1]])
        >>> preds = jnp.array([[0, 1], [0, 1]])
        >>> hamming_distance(preds, target)
        Array(0.25, dtype=float32)
    """
    correct, total = _hamming_distance_update(preds, target, threshold)
    return _hamming_distance_compute(correct, total)
