"""Property-based fuzz of the fixed-capacity exact-curve kernels: generated
score/label mixes (extreme ties, constant scores, class imbalance) must
match sklearn at 1e-6 and behave sanely at the degenerate edges."""
import numpy as np
from hypothesis import assume, given, settings, strategies as st
from sklearn.metrics import average_precision_score, roc_auc_score

import jax.numpy as jnp

from metrics_tpu.functional.classification.exact_curve import (
    binary_auroc_fixed,
    binary_average_precision_fixed,
    curve_buffer_init,
    curve_buffer_update,
)

_settings = settings(max_examples=60, deadline=None)


@st.composite
def _scored_labels(draw):
    n = draw(st.integers(4, 64))
    quant = draw(st.sampled_from([None, 2, 10]))  # None=continuous, else tie-heavy
    rng = np.random.default_rng(draw(st.integers(0, 2**31)))
    scores = rng.random(n).astype(np.float32)
    if quant:
        scores = np.round(scores * quant) / quant
    labels = (rng.random(n) < draw(st.floats(0.1, 0.9))).astype(np.int32)
    return scores, labels


@given(_scored_labels())
@_settings
def test_auroc_ap_match_sklearn(data):
    scores, labels = data
    assume(0 < labels.sum() < len(labels))
    state = curve_buffer_init(128)
    state = curve_buffer_update(state, jnp.asarray(scores), jnp.asarray(labels))
    auroc = float(binary_auroc_fixed(state["preds"], state["target"], state["valid"]))
    ap = float(binary_average_precision_fixed(state["preds"], state["target"], state["valid"]))
    np.testing.assert_allclose(auroc, roc_auc_score(labels, scores), atol=1e-6)
    np.testing.assert_allclose(ap, average_precision_score(labels, scores), atol=1e-6)


@given(_scored_labels(), st.integers(1, 5))
@_settings
def test_split_updates_equal_single(data, n_chunks):
    scores, labels = data
    assume(0 < labels.sum() < len(labels))
    one = curve_buffer_update(curve_buffer_init(128), jnp.asarray(scores), jnp.asarray(labels))
    many = curve_buffer_init(128)
    for s, l in zip(np.array_split(scores, n_chunks), np.array_split(labels, n_chunks)):
        if len(s):
            many = curve_buffer_update(many, jnp.asarray(s), jnp.asarray(l))
    a1 = float(binary_auroc_fixed(one["preds"], one["target"], one["valid"]))
    a2 = float(binary_auroc_fixed(many["preds"], many["target"], many["valid"]))
    np.testing.assert_allclose(a1, a2, atol=1e-7)


@given(st.integers(4, 32))
@_settings
def test_constant_scores_give_half_auroc(n):
    """All-tied scores: AUROC must be exactly 0.5 (the chance diagonal)."""
    labels = np.zeros(n, np.int32)
    labels[: n // 2] = 1
    state = curve_buffer_update(
        curve_buffer_init(64), jnp.full(n, 0.7, jnp.float32), jnp.asarray(labels)
    )
    auroc = float(binary_auroc_fixed(state["preds"], state["target"], state["valid"]))
    np.testing.assert_allclose(auroc, 0.5, atol=1e-7)


# ---------------------------------------------------------------------------
# multiclass / multilabel one-vs-rest kernels
# ---------------------------------------------------------------------------

from sklearn.metrics import precision_recall_curve as sk_prc

from metrics_tpu.functional.classification.exact_curve import (
    binary_precision_recall_curve_fixed,
    multiclass_average_precision_fixed,
    multiclass_roc_fixed,
)

# fixed buffer capacity so every Hypothesis example hits the same compiled
# kernel shapes (only the class count, 2-5, varies the shape — without this
# each example pays a fresh XLA compile and the suite takes minutes)
_CAP = 64


def _pad_rows(scores, labels):
    n, c = scores.shape
    preds_buf = np.zeros((_CAP, c), np.float32)
    preds_buf[:n] = scores
    target_buf = np.zeros((_CAP,) + labels.shape[1:], labels.dtype)
    target_buf[:n] = labels
    valid = np.zeros(_CAP, bool)
    valid[:n] = True
    return jnp.asarray(preds_buf), jnp.asarray(target_buf), jnp.asarray(valid)


@st.composite
def _multiclass_data(draw):
    n = draw(st.integers(6, 48))
    c = draw(st.integers(2, 5))
    quant = draw(st.sampled_from([None, 4]))  # tie-heavy variant
    rng = np.random.default_rng(draw(st.integers(0, 2**31)))
    scores = rng.random((n, c)).astype(np.float32)
    if quant:
        scores = np.round(scores * quant) / quant
    labels = rng.integers(0, c, n).astype(np.int32)
    return scores, labels, c


@given(_multiclass_data())
@_settings
def test_multiclass_ap_matches_sklearn_where_defined(data):
    """Per-class AP equals sklearn for present classes and is NaN for absent
    ones; macro averages exactly the defined classes."""
    scores, labels, c = data
    jp, jt, jv = _pad_rows(scores, labels)
    per_class = np.asarray(
        multiclass_average_precision_fixed(jp, jt, jv, c, average="none")
    )
    onehot = np.eye(c, dtype=int)[labels]
    defined = onehot.sum(0) > 0
    for k in range(c):
        if defined[k]:
            np.testing.assert_allclose(
                per_class[k], average_precision_score(onehot[:, k], scores[:, k]), atol=1e-6
            )
        else:
            assert np.isnan(per_class[k])
    macro = float(multiclass_average_precision_fixed(jp, jt, jv, c, average="macro"))
    np.testing.assert_allclose(macro, np.nanmean(per_class), atol=1e-6)
    # weighted: defined classes weighted by positive count
    weighted = float(multiclass_average_precision_fixed(jp, jt, jv, c, average="weighted"))
    w = np.where(defined, onehot.sum(0), 0).astype(float)
    want_w = np.sum(np.where(defined, per_class, 0.0) * w) / max(w.sum(), 1.0)
    np.testing.assert_allclose(weighted, want_w, atol=1e-6)
    # micro: flattened one-vs-rest indicator problem
    micro = float(multiclass_average_precision_fixed(jp, jt, jv, c, average="micro"))
    np.testing.assert_allclose(
        micro, average_precision_score(onehot.ravel(), scores.ravel()), atol=1e-6
    )


@given(_multiclass_data())
@_settings
def test_multiclass_padded_roc_matches_sklearn(data):
    """Per-class ROC points from the padded buffer (invalid rows masked)
    match sklearn's one-vs-rest curves exactly."""
    from sklearn.metrics import roc_curve as sk_roc

    scores, labels, c = data
    jp, jt, jv = _pad_rows(scores, labels)
    fpr, tpr, _, mask = multiclass_roc_fixed(jp, jt, jv, c)
    for k in range(c):
        tgt_k = (labels == k).astype(int)
        if 0 < tgt_k.sum() < len(tgt_k):
            sk_fpr, sk_tpr, _ = sk_roc(tgt_k, scores[:, k], drop_intermediate=False)
            np.testing.assert_allclose(np.asarray(fpr[k])[np.asarray(mask[k])], sk_fpr, atol=1e-6)
            np.testing.assert_allclose(np.asarray(tpr[k])[np.asarray(mask[k])], sk_tpr, atol=1e-6)


@given(_multiclass_data())
@_settings
def test_multilabel_indicator_targets_match_multiclass_onehot(data):
    """multilabel=True with the one-hot indicator matrix must equal the
    multiclass label path — the two target layouts describe the same data."""
    scores, labels, c = data
    onehot = np.eye(c, dtype=np.int32)[labels]
    jp, jt_ml, jv = _pad_rows(scores, onehot)
    _, jt_mc, _ = _pad_rows(scores, labels)
    for avg in ("none", "macro", "micro"):
        ml = np.asarray(
            multiclass_average_precision_fixed(jp, jt_ml, jv, c, average=avg, multilabel=True)
        )
        mc = np.asarray(multiclass_average_precision_fixed(jp, jt_mc, jv, c, average=avg))
        np.testing.assert_allclose(ml, mc, atol=1e-7, equal_nan=True)


@given(_scored_labels())
@_settings
def test_prc_truncation_matches_reference_convention(data):
    """The PRC point set equals sklearn's re-truncated to the reference
    convention (exactly one leading full-recall point) for ANY input mix —
    the property form of the review-found truncation fix."""
    scores, labels = data
    assume(0 < labels.sum() < len(labels))
    state = curve_buffer_update(curve_buffer_init(128), jnp.asarray(scores), jnp.asarray(labels))
    precision, recall, thr, mask, last = (
        np.asarray(v)
        for v in binary_precision_recall_curve_fixed(
            state["preds"], state["target"], state["valid"]
        )
    )
    got_rec = np.concatenate([recall[mask][::-1], [last[1]]])
    sk_p, sk_r, _ = sk_prc(labels, scores)
    k = 0
    while k + 1 < len(sk_r) and sk_r[k + 1] == 1.0:
        k += 1
    np.testing.assert_allclose(got_rec, sk_r[k:], atol=1e-6)
    got_prec = np.concatenate([precision[mask][::-1], [last[0]]])
    np.testing.assert_allclose(got_prec, sk_p[k:], atol=1e-6)
    assert (got_rec[:-1] == 1.0).sum() == 1  # exactly one full-recall point kept
