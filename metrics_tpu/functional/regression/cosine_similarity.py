"""Cosine similarity.

Behavior parity with /root/reference/torchmetrics/functional/regression/
cosine_similarity.py:22-102.
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _check_same_shape

Array = jax.Array


def _cosine_similarity_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    _check_same_shape(preds, target)
    return preds.astype(jnp.float32), target.astype(jnp.float32)


def _cosine_similarity_compute(preds: Array, target: Array, reduction: Optional[str] = "sum") -> Array:
    dot_product = jnp.sum(preds * target, axis=-1)
    preds_norm = jnp.linalg.norm(preds, axis=-1)
    target_norm = jnp.linalg.norm(target, axis=-1)
    similarity = dot_product / (preds_norm * target_norm)
    if reduction == "sum":
        return jnp.sum(similarity)
    if reduction == "mean":
        return jnp.mean(similarity)
    if reduction in ("none", None):
        return similarity
    raise ValueError(f"Expected reduction to be one of ['sum', 'mean', 'none', None] but got {reduction}")


def cosine_similarity(preds: Array, target: Array, reduction: Optional[str] = "sum") -> Array:
    """Computes cosine similarity between rows of preds and target.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([[1., 2., 3., 4.], [1., 2., 3., 4.]])
        >>> preds = jnp.array([[1., 2., 3., 4.], [-1., -2., -3., -4.]])
        >>> cosine_similarity(preds, target, 'none')
        Array([ 0.99999994, -0.99999994], dtype=float32)
    """
    preds, target = _cosine_similarity_update(preds, target)
    return _cosine_similarity_compute(preds, target, reduction)
