"""Universal Image Quality Index.

Behavior parity with /root/reference/torchmetrics/functional/image/uqi.py:25-160
(SSIM with c1 = c2 = 0).
"""
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.functional.image.helper import _depthwise_conv2d, _gaussian_kernel
from metrics_tpu.functional.image.ssim import _ssim_check_kernel
from metrics_tpu.parallel.distributed import reduce
from metrics_tpu.utils.checks import _check_same_shape

Array = jax.Array


def _uqi_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    if preds.dtype != target.dtype:
        raise TypeError(
            "Expected `preds` and `target` to have the same data type."
            f" Got preds: {preds.dtype} and target: {target.dtype}."
        )
    _check_same_shape(preds, target)
    if preds.ndim != 4:
        raise ValueError(
            "Expected `preds` and `target` to have BxCxHxW shape."
            f" Got preds: {preds.shape} and target: {target.shape}."
        )
    return preds, target


def _uqi_compute(
    preds: Array,
    target: Array,
    kernel_size: Sequence[int] = (11, 11),
    sigma: Sequence[float] = (1.5, 1.5),
    reduction: str = "elementwise_mean",
    data_range: Optional[float] = None,
) -> Array:
    _ssim_check_kernel(kernel_size, sigma)

    channel = preds.shape[1]
    dtype = preds.dtype
    kernel = _gaussian_kernel(channel, kernel_size, sigma, dtype)
    pad_h = (kernel_size[0] - 1) // 2
    pad_w = (kernel_size[1] - 1) // 2

    pad_cfg = ((0, 0), (0, 0), (pad_h, pad_h), (pad_w, pad_w))
    preds = jnp.pad(preds, pad_cfg, mode="reflect")
    target = jnp.pad(target, pad_cfg, mode="reflect")

    input_list = jnp.concatenate([preds, target, preds * preds, target * target, preds * target])
    outputs = _depthwise_conv2d(input_list, kernel)
    n = preds.shape[0]
    output_list = [outputs[i * n:(i + 1) * n] for i in range(5)]

    mu_pred_sq = jnp.square(output_list[0])
    mu_target_sq = jnp.square(output_list[1])
    mu_pred_target = output_list[0] * output_list[1]

    sigma_pred_sq = output_list[2] - mu_pred_sq
    sigma_target_sq = output_list[3] - mu_target_sq
    sigma_pred_target = output_list[4] - mu_pred_target

    upper = 2 * sigma_pred_target
    lower = sigma_pred_sq + sigma_target_sq

    uqi_idx = ((2 * mu_pred_target) * upper) / ((mu_pred_sq + mu_target_sq) * lower)
    uqi_idx = uqi_idx[..., pad_h:-pad_h, pad_w:-pad_w] if pad_h and pad_w else uqi_idx

    return reduce(uqi_idx, reduction)


def universal_image_quality_index(
    preds: Array,
    target: Array,
    kernel_size: Sequence[int] = (11, 11),
    sigma: Sequence[float] = (1.5, 1.5),
    reduction: str = "elementwise_mean",
    data_range: Optional[float] = None,
) -> Array:
    """Computes the Universal Image Quality Index.

    Example:
        >>> import jax
        >>> preds = jax.random.uniform(jax.random.PRNGKey(0), (8, 3, 16, 16))
        >>> target = preds * 0.75
        >>> bool(universal_image_quality_index(preds, target) > 0.9)
        True
    """
    preds, target = _uqi_update(preds, target)
    return _uqi_compute(preds, target, kernel_size, sigma, reduction, data_range)
