"""Stored-oracle fixtures for the image inference metrics (the PESQ
stored-corpus pattern, scripts/make_image_oracle.py).

Unconditional: the deterministic corpus (tests/image/inference_corpus.py)
scored with the seed-0 random-weight extractor must match the committed
csv — pinning the Inception stem forward and the FID/KID/IS statistic
machinery (f64 eigh trace-sqrtm, MMD subsets, entropy splits) against
numeric drift; any change must regenerate the fixture deliberately.

Conditional-from-storage: when a networked environment has run the
generator with real weights (and torch_fidelity), the stored
real-weight/official csvs are compared here WITHOUT needing weights or
packages locally.
"""
import csv
import os

import pytest

import jax
import jax.numpy as jnp

from tests.image.inference_corpus import engine_scores, lpips_pairs

_FIXDIR = os.path.join(os.path.dirname(__file__), "fixtures")


def _read(name):
    path = os.path.join(_FIXDIR, name)
    if not os.path.exists(path):
        return None
    with open(path) as fh:
        return {row["metric"]: float(row["value"]) for row in csv.DictReader(fh)}


def test_stored_engine_scores_fixture():
    pinned = _read("image_engine_scores.csv")
    assert pinned is not None, "run scripts/make_image_oracle.py to create the fixture"

    got = engine_scores()  # the generator's own scoring definition
    assert set(got) == set(pinned)
    for key, val in got.items():
        # conv accumulation order differs slightly across backends/hosts
        assert val == pytest.approx(pinned[key], abs=2e-3), key

    # separated distributions must register: the pin is not a degenerate zero
    assert pinned["fid"] > 0.1 and pinned["kid_mean"] > 1e-3


def test_stored_real_weight_scores_when_present():
    """A networked environment's generator run pins real-weight parity for
    every environment afterwards: ours-with-real-weights vs the official
    implementations over the SAME corpus, compared from storage."""
    ours = _read("image_real_weight_scores.csv")
    official = _read("image_official_scores.csv")
    if ours is None or official is None:
        pytest.skip(
            "real-weight/official fixtures not generated"
            " (scripts/make_image_oracle.py --weights-dir in a networked env)"
        )
    assert ours["fid"] == pytest.approx(official["fid"], rel=1e-2)
    assert ours["kid_mean"] == pytest.approx(official["kid_mean"], abs=1e-3)
    assert ours["is_mean"] == pytest.approx(official["is_mean"], rel=1e-2)


def test_lpips_corpus_deterministic_contract():
    """LPIPS over the corpus with a seeded random-weight net: symmetric in
    its inputs' roles where the spec demands, zero on identical pairs, and
    strictly positive on jittered pairs — the behavioral envelope that
    holds for ANY weights, asserted on the same corpus the stored-oracle
    generator uses for real-weight runs."""
    from metrics_tpu.image import LearnedPerceptualImagePatchSimilarity
    from metrics_tpu.models.lpips import LPIPSNet

    a, b = lpips_pairs()
    net_mod = LPIPSNet()
    variables = net_mod.init(
        jax.random.PRNGKey(1), jnp.zeros((1, 3, 64, 64)), jnp.zeros((1, 3, 64, 64))
    )
    net = jax.jit(lambda x, y: net_mod.apply(variables, x, y))

    m_same = LearnedPerceptualImagePatchSimilarity(net=net)
    m_same.update(jnp.asarray(a), jnp.asarray(a))
    assert float(m_same.compute()) == pytest.approx(0.0, abs=1e-6)

    m_diff = LearnedPerceptualImagePatchSimilarity(net=net)
    m_diff.update(jnp.asarray(a), jnp.asarray(b))
    d_ab = float(m_diff.compute())
    # random 1x1 heads can sign-flip the stage sums, so assert non-zero
    # response rather than positivity (real weights are positive-headed)
    assert abs(d_ab) > 1e-6

    m_flip = LearnedPerceptualImagePatchSimilarity(net=net)
    m_flip.update(jnp.asarray(b), jnp.asarray(a))
    assert float(m_flip.compute()) == pytest.approx(d_ab, abs=1e-5)
