"""Training-loop integration behaviors (analog of the reference's Lightning
suite, /root/reference/integrations/test_lightning.py:30-297): metrics
accumulate within an epoch, reset between epochs, forward returns
batch-local values while accumulation continues, and collections ride a
real gradient loop."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from metrics_tpu import Accuracy, MeanSquaredError, MetricCollection, SumMetric


def test_metric_accumulates_across_epoch_and_resets():
    """Reference test_metric_lightning (test_lightning.py:30-61): per-epoch
    sums through a step loop, reset between epochs."""
    metric = SumMetric()
    epoch_totals = []
    for epoch in range(2):
        for step in range(8):
            metric.update(float(epoch * 8 + step))
        epoch_totals.append(float(metric.compute()))
        metric.reset()
    assert epoch_totals[0] == sum(range(8))
    assert epoch_totals[1] == sum(range(8, 16))


def test_forward_batch_value_while_accumulating():
    """forward returns the batch metric; compute returns the accumulation."""
    metric = MeanSquaredError()
    batch_vals = []
    rng = np.random.default_rng(0)
    chunks = [(rng.standard_normal(8).astype(np.float32),
               rng.standard_normal(8).astype(np.float32)) for _ in range(4)]
    for p, t in chunks:
        batch_vals.append(float(metric(jnp.asarray(p), jnp.asarray(t))))
    for (p, t), v in zip(chunks, batch_vals):
        np.testing.assert_allclose(v, np.mean((p - t) ** 2), rtol=1e-5)
    all_p = np.concatenate([p for p, _ in chunks])
    all_t = np.concatenate([t for _, t in chunks])
    np.testing.assert_allclose(float(metric.compute()), np.mean((all_p - all_t) ** 2), rtol=1e-5)


def test_collection_in_gradient_loop_converges_and_tracks():
    """A real SGD loop on a toy linear model: the collection's epoch metrics
    improve and match a recomputation from scratch."""
    rng = np.random.default_rng(1)
    num_classes, dim, n = 4, 8, 512
    w_true = rng.standard_normal((dim, num_classes))
    x = rng.standard_normal((n, dim)).astype(np.float32)
    y = np.argmax(x @ w_true + 0.3 * rng.standard_normal((n, num_classes)), -1).astype(np.int32)

    params = jnp.zeros((dim, num_classes))
    metrics = MetricCollection([Accuracy()])

    @jax.jit
    def grad_step(params, xb, yb):
        def loss_fn(p):
            probs = jax.nn.softmax(xb @ p)
            return jnp.mean((probs - jax.nn.one_hot(yb, num_classes)) ** 2), probs

        (loss, probs), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        return params - 1.0 * grads, probs

    epoch_accs = []
    for epoch in range(3):
        for lo in range(0, n, 64):
            xb = jnp.asarray(x[lo : lo + 64])
            yb = jnp.asarray(y[lo : lo + 64])
            params, probs = grad_step(params, xb, yb)
            metrics.update(probs, yb)
        vals = metrics.compute()
        epoch_accs.append(float(vals["Accuracy"]))
        metrics.reset()
    assert epoch_accs[-1] > epoch_accs[0]
    assert epoch_accs[-1] > 0.7


def test_state_dict_checkpoint_resume_mid_epoch():
    """Checkpoint/resume: state_dict saved mid-epoch restores accumulation
    exactly (reference persistence semantics, SURVEY §5)."""
    rng = np.random.default_rng(2)
    a = MeanSquaredError()
    chunks = [(rng.standard_normal(8).astype(np.float32),
               rng.standard_normal(8).astype(np.float32)) for _ in range(4)]
    for p, t in chunks[:2]:
        a.update(jnp.asarray(p), jnp.asarray(t))
    saved = a.state_dict()

    b = MeanSquaredError()
    b.load_state_dict(saved)
    for p, t in chunks[2:]:
        b.update(jnp.asarray(p), jnp.asarray(t))

    c = MeanSquaredError()
    for p, t in chunks:
        c.update(jnp.asarray(p), jnp.asarray(t))
    np.testing.assert_allclose(float(b.compute()), float(c.compute()), rtol=1e-6)


def test_exact_curves_mesh_example_runs():
    """examples/exact_curves_mesh.py end-to-end on the 8-virtual-device mesh:
    per-device scanned capacity updates + one gather reproduce the eager
    global AUROC/AP exactly (the example asserts mesh == eager itself)."""
    import importlib.util
    import pathlib

    path = pathlib.Path(__file__).resolve().parents[2] / "examples" / "exact_curves_mesh.py"
    spec = importlib.util.spec_from_file_location("exact_curves_mesh_example", path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    module.main()
