"""Layout manifest (ISSUE 20 tentpole): freshness, schema, the path
universe, and the runtime consultation fast paths.

The committed ``scripts/layout_manifest.json`` is a build artifact of
``python scripts/tracelint.py --manifest`` (same walk, same freshness gate
as the fusibility manifest) that TWO runtime consumers trust:

* ``sliced/sharding.py`` answers partition specs / shardings from it with
  no per-leaf array probe — so the fast path must be BIT-identical to the
  probe on a real multi-device mesh, observable (probe-skip counter), and
  must fall back to the probe whenever the manifest cannot vouch for the
  live object (stale file, statically invisible registrations);
* ``parallel/distributed.py`` audits sharded-claimed sync leaves against
  the manifest's shard-axis index under ``METRICS_TPU_VERIFY_MANIFEST``.
"""
import json
import pathlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import metrics_tpu  # noqa: F401
from metrics_tpu import MeanSquaredError
from metrics_tpu.analysis import (
    build_layout_manifest,
    layout_for_class,
    leaf_may_shard,
    leaf_shard_axes,
    load_layout_manifest,
    render_layout_manifest,
    shard_path_universe,
)
from metrics_tpu.analysis import layout as layout_mod
from metrics_tpu.classification import Accuracy
from metrics_tpu.parallel.distributed import (
    layout_verify_counters,
    reset_layout_verify_counters,
    sync_pytree_in_mesh,
)
from metrics_tpu.sliced import SlicedMetric, shard_sliced_states, sliced_partition_specs
from metrics_tpu.sliced.sharding import (
    manifest_consultation_counters,
    reset_manifest_consultation_counters,
    slice_partition_rules,
)
from metrics_tpu.utils.compat import shard_map

REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]
MANIFEST_PATH = REPO_ROOT / layout_mod.DEFAULT_LAYOUT_MANIFEST

AXES = {layout_mod.AXIS_SLICE, layout_mod.AXIS_RING, layout_mod.AXIS_REPLICATED}
RESHARDS = {
    layout_mod.RESHARD_RESHAPE,
    layout_mod.RESHARD_FOLD,
    layout_mod.RESHARD_GATHER,
    layout_mod.RESHARD_OPAQUE,
}
LEAF_FIELDS = (
    "reducer",
    "shard_axis",
    "partition_spec",
    "reshard",
    "container",
    "dtype",
    "shape",
    "wire",
)


@pytest.fixture(scope="module")
def committed():
    data = load_layout_manifest(MANIFEST_PATH)
    assert data is not None, f"missing/invalid committed layout manifest at {MANIFEST_PATH}"
    return data


@pytest.fixture(autouse=True)
def _clean_consultation_state():
    """Counters and manifest caches are process-global; tests that doctor
    the manifest path or env flags must not leak into each other."""
    layout_mod.invalidate_layout_cache()
    reset_manifest_consultation_counters()
    reset_layout_verify_counters()
    yield
    layout_mod.invalidate_layout_cache()
    reset_manifest_consultation_counters()
    reset_layout_verify_counters()


def _mesh():
    return Mesh(np.asarray(jax.devices()[:8]), ("slices",))


# ---------------------------------------------------------------------------
# freshness + determinism (the byte-level CI gate)
# ---------------------------------------------------------------------------

class TestFreshness:
    def test_committed_manifest_is_byte_fresh(self):
        """Byte-for-byte: the committed file equals a fresh full-package
        build — exactly what CI's `tracelint --manifest --check` enforces
        (for BOTH manifests, this one included)."""
        assert render_layout_manifest(build_layout_manifest()) == MANIFEST_PATH.read_text()

    def test_build_is_deterministic(self):
        assert render_layout_manifest(build_layout_manifest()) == render_layout_manifest(
            build_layout_manifest()
        )


# ---------------------------------------------------------------------------
# schema
# ---------------------------------------------------------------------------

class TestSchema:
    def test_header(self, committed):
        assert committed["version"] == layout_mod.LAYOUT_VERSION == 1
        assert committed["tool"] == "tracelint"
        assert committed["classes"]

    def test_leaf_records(self, committed):
        for key, entry in committed["classes"].items():
            assert isinstance(entry.get("sliceable"), bool), key
            for name, rec in entry["leaves"].items():
                for field in LEAF_FIELDS:
                    assert field in rec, (key, name, field)
                assert rec["shard_axis"] in AXES, (key, name)
                assert rec["reshard"] in RESHARDS, (key, name)
                assert isinstance(rec["partition_spec"], list), (key, name)
                # the reshard recipe is a function of axis + reducer:
                # slice axes re-split, fold-reducible leaves re-fold,
                # cat lists gather, opaque reducers stay opaque
                if rec["shard_axis"] == layout_mod.AXIS_SLICE:
                    assert rec["reshard"] == layout_mod.RESHARD_RESHAPE, (key, name)
                    assert rec["partition_spec"] == [layout_mod.SLICE_AXIS_NAME], (key, name)
                elif rec["reducer"] in layout_mod.FOLD_REDUCERS:
                    assert rec["reshard"] == layout_mod.RESHARD_FOLD, (key, name)
                    assert rec["partition_spec"] == [], (key, name)

    def test_synthetic_sliced_metric_entry(self, committed):
        entry = committed["classes"][layout_mod.SLICED_METRIC_KEY]
        assert entry["dynamic_leaves"] == "template-broadcast"
        rows = entry["leaves"][layout_mod.SLICE_ROWS]
        assert rows["shard_axis"] == layout_mod.AXIS_SLICE
        assert rows["dtype"] == "int32"

    def test_prefix_constants_agree_with_runtime(self):
        """layout.py mirrors the runtime footprint/axis constants instead
        of importing them (stdlib-only contract) — pin the mirror."""
        from metrics_tpu.observability.recorder import (
            SKETCH_FOOTPRINT_PREFIX,
            SLICED_FOOTPRINT_PREFIX,
            WINDOWED_FOOTPRINT_PREFIX,
        )
        from metrics_tpu.sliced.metric import SLICE_ROWS
        from metrics_tpu.sliced.sharding import SLICE_AXIS

        assert layout_mod.SLICED_PREFIX == SLICED_FOOTPRINT_PREFIX
        assert layout_mod.SKETCH_PREFIX == SKETCH_FOOTPRINT_PREFIX
        assert layout_mod.WINDOWED_PREFIX == WINDOWED_FOOTPRINT_PREFIX
        assert layout_mod.SLICE_ROWS == SLICE_ROWS
        assert layout_mod.SLICE_AXIS_NAME == SLICE_AXIS

    def test_runtime_class_lookup(self, committed):
        entry = layout_for_class(MeanSquaredError)
        assert entry is not None and entry["sliceable"] is True
        assert set(entry["leaves"]) == {"sum_squared_error", "total"}
        # loop-registered states (StatScores' `for s in ...: add_state(s)`)
        # are statically invisible — Accuracy's entry must NOT pretend to
        # cover them (the runtime consultation falls back on the mismatch)
        acc = layout_for_class(Accuracy)
        if acc is not None:
            assert "tp" not in acc["leaves"]


# ---------------------------------------------------------------------------
# path universe + shard-axis verdicts
# ---------------------------------------------------------------------------

class TestPathUniverse:
    def test_sliced_prefix_carries_slice_axis(self, committed):
        universe = shard_path_universe(committed)
        assert layout_mod.AXIS_SLICE in universe["sliced/sum_squared_error"]
        # a BARE name belongs to an unwrapped metric whose leading axis
        # must still reduce — named-axis specs on it are the PR 8 bug
        assert universe["sum_squared_error"] == set()
        assert universe["total"] == set()
        assert universe[layout_mod.SLICE_ROWS] == {layout_mod.AXIS_SLICE}

    def test_leaf_may_shard_verdicts(self):
        assert leaf_may_shard(layout_mod.SLICE_ROWS) is True
        assert leaf_may_shard("sliced/total") is True
        # bare [S] names: legitimate in name-keyed spec dicts — no verdict
        assert leaf_may_shard("total") is None
        # never-registered names: no verdict either way
        assert leaf_may_shard("no_such_leaf_anywhere") is None
        # ring rows shard per-slot in either spelling
        assert leaf_may_shard("_ring_rows") is True

    def test_known_replicated_leaf_is_refutable(self, committed):
        name = next(
            name
            for entry in committed["classes"].values()
            for name, rec in entry["leaves"].items()
            if rec["shard_axis"] == layout_mod.AXIS_REPLICATED
            and not leaf_shard_axes(name)
        )
        assert leaf_may_shard(name) is False

    def test_no_manifest_env_disables_verdicts(self, monkeypatch):
        monkeypatch.setenv("METRICS_TPU_NO_MANIFEST", "1")
        layout_mod.invalidate_layout_cache()
        assert leaf_may_shard(layout_mod.SLICE_ROWS) is None
        assert leaf_shard_axes("total") == set()


# ---------------------------------------------------------------------------
# runtime consultation: bit parity with the probe on an 8-device mesh
# ---------------------------------------------------------------------------

class TestConsultation:
    def _probe_specs(self, monkeypatch, m, mesh):
        """The probe's answer with consultation disabled entirely."""
        with monkeypatch.context() as mp:
            mp.setenv("METRICS_TPU_NO_MANIFEST", "1")
            layout_mod.invalidate_layout_cache()
            specs = sliced_partition_specs(m, mesh)
        layout_mod.invalidate_layout_cache()
        return specs

    def test_sliced_specs_bit_identical_and_probe_skipped(self, monkeypatch):
        mesh = _mesh()
        m = SlicedMetric(MeanSquaredError(), num_slices=64)
        reset_manifest_consultation_counters()
        fast = sliced_partition_specs(m, mesh)
        counters = manifest_consultation_counters()
        assert counters["probe_skips"] == 1 and counters["stale_fallbacks"] == 0
        assert fast == self._probe_specs(monkeypatch, m, mesh)
        assert all(s == P("slices") for s in fast.values())
        assert layout_mod.SLICE_ROWS in fast

    def test_nondivisible_num_slices_replicates(self, monkeypatch):
        mesh = _mesh()
        m = SlicedMetric(MeanSquaredError(), num_slices=13)  # 13 % 8 != 0
        fast = sliced_partition_specs(m, mesh)
        assert all(s == P() for s in fast.values())
        assert fast == self._probe_specs(monkeypatch, m, mesh)
        assert manifest_consultation_counters()["probe_skips"] >= 1

    def test_plain_metric_replicates_from_manifest(self, monkeypatch):
        mesh = _mesh()
        m = MeanSquaredError()
        fast = sliced_partition_specs(m, mesh)
        assert all(s == P() for s in fast.values())
        assert fast == self._probe_specs(monkeypatch, m, mesh)
        assert manifest_consultation_counters()["probe_skips"] >= 1

    def test_statically_invisible_class_falls_back(self):
        """StatScores registers its leaves through a loop variable, so
        Accuracy's manifest entry cannot cover the live state dict — the
        consultation must refuse to vouch and count a stale fallback."""
        mesh = _mesh()
        m = Accuracy(num_classes=3)
        reset_manifest_consultation_counters()
        specs = sliced_partition_specs(m, mesh)
        counters = manifest_consultation_counters()
        assert counters["stale_fallbacks"] == 1 and counters["probe_skips"] == 0
        assert all(s == P() for s in specs.values())

    def test_shard_sliced_states_fast_path_parity(self, monkeypatch):
        mesh = _mesh()
        m_fast = SlicedMetric(MeanSquaredError(), num_slices=64)
        reset_manifest_consultation_counters()
        fast = shard_sliced_states(m_fast, mesh)
        assert manifest_consultation_counters()["probe_skips"] == 1
        with monkeypatch.context() as mp:
            mp.setenv("METRICS_TPU_NO_MANIFEST", "1")
            layout_mod.invalidate_layout_cache()
            m_probe = SlicedMetric(MeanSquaredError(), num_slices=64)
            probed = shard_sliced_states(m_probe, mesh)
        layout_mod.invalidate_layout_cache()
        assert fast == probed  # NamedSharding equality: same mesh, same spec
        assert all(s == NamedSharding(mesh, P("slices")) for s in fast.values())
        # and the placed metrics stay bit-identical through an update
        ids = jnp.arange(64)
        preds = jnp.arange(64, dtype=jnp.float32)
        target = jnp.zeros(64)
        m_fast.update(ids, preds, target)
        m_probe.update(ids, preds, target)
        assert bool(jnp.array_equal(m_fast.sum_squared_error, m_probe.sum_squared_error))
        assert m_fast.sum_squared_error.sharding.spec == P("slices")

    def test_custom_rules_always_probe(self):
        mesh = _mesh()
        m = SlicedMetric(MeanSquaredError(), num_slices=64)
        reset_manifest_consultation_counters()
        shard_sliced_states(m, mesh, rules=slice_partition_rules())
        assert manifest_consultation_counters()["probe_skips"] == 0

    def test_verify_mode_cross_checks_and_agrees(self, monkeypatch):
        mesh = _mesh()
        m = SlicedMetric(MeanSquaredError(), num_slices=64)
        monkeypatch.setenv("METRICS_TPU_VERIFY_MANIFEST", "1")
        reset_manifest_consultation_counters()
        specs = sliced_partition_specs(m, mesh)
        counters = manifest_consultation_counters()
        # verify mode runs the probe and compares: no skip, no mismatch
        assert counters["verify_mismatches"] == 0
        assert counters["probe_skips"] == 0
        assert all(s == P("slices") for s in specs.values())

    def test_verify_mode_catches_divergence_and_trusts_probe(self, monkeypatch):
        """Force fast-path/probe disagreement (doctored num_slices: the
        manifest math sees 13, the live arrays still have 64 rows) — the
        cross-check must warn, count, and return the PROBE's answer."""
        mesh = _mesh()
        m = SlicedMetric(MeanSquaredError(), num_slices=64)
        m.num_slices = 13
        monkeypatch.setenv("METRICS_TPU_VERIFY_MANIFEST", "1")
        reset_manifest_consultation_counters()
        with pytest.warns(UserWarning, match="disagree with the probe"):
            specs = sliced_partition_specs(m, mesh)
        assert manifest_consultation_counters()["verify_mismatches"] == 1
        assert all(s == P("slices") for s in specs.values())  # the probe's verdict

    def test_stale_manifest_file_falls_back(self, monkeypatch, tmp_path):
        """A manifest whose MSE entry lost a leaf cannot vouch for the
        live object: the consultation counts a stale fallback and the
        probe still answers correctly."""
        doctored = json.loads(MANIFEST_PATH.read_text())
        del doctored["classes"]["regression/mse.py::MeanSquaredError"]["leaves"]["total"]
        stale = tmp_path / "layout_manifest.json"
        stale.write_text(json.dumps(doctored))
        monkeypatch.setenv(layout_mod.ENV_LAYOUT_MANIFEST_PATH, str(stale))
        layout_mod.invalidate_layout_cache()
        mesh = _mesh()
        m = SlicedMetric(MeanSquaredError(), num_slices=64)
        reset_manifest_consultation_counters()
        specs = sliced_partition_specs(m, mesh)
        counters = manifest_consultation_counters()
        assert counters["stale_fallbacks"] == 1 and counters["probe_skips"] == 0
        assert all(s == P("slices") for s in specs.values())


# ---------------------------------------------------------------------------
# sync-path plausibility audit (parallel/distributed.py)
# ---------------------------------------------------------------------------

class TestSyncVerify:
    def _sync(self, leaf_name):
        mesh = _mesh()
        leaf = jnp.arange(16, dtype=jnp.float32)

        def body(x):
            out = sync_pytree_in_mesh(
                {"m": {leaf_name: x}},
                {"m": {leaf_name: "sum"}},
                "slices",
                partition_specs={"m": {leaf_name: P("slices")}},
            )
            return out["m"][leaf_name]

        return jax.jit(
            shard_map(body, mesh=mesh, in_specs=(P("slices"),), out_specs=P("slices"))
        )(leaf)

    def test_audit_off_by_default(self):
        reset_layout_verify_counters()
        out = self._sync("data_leaf_unknown")
        assert layout_verify_counters() == {"claims_checked": 0, "implausible_claims": 0}
        assert bool(jnp.array_equal(out, jnp.arange(16, dtype=jnp.float32)))

    def test_plausible_claim_passes_audit(self, monkeypatch):
        monkeypatch.setenv("METRICS_TPU_VERIFY_MANIFEST", "1")
        reset_layout_verify_counters()
        self._sync(layout_mod.SLICE_ROWS)
        counters = layout_verify_counters()
        assert counters["claims_checked"] >= 1
        assert counters["implausible_claims"] == 0

    def test_implausible_claim_warns_but_behavior_unchanged(self, monkeypatch, committed):
        replicated_name = next(
            name
            for entry in committed["classes"].values()
            for name, rec in entry["leaves"].items()
            if rec["shard_axis"] == layout_mod.AXIS_REPLICATED
            and not leaf_shard_axes(name)
        )
        monkeypatch.setenv("METRICS_TPU_VERIFY_MANIFEST", "1")
        reset_layout_verify_counters()
        with pytest.warns(UserWarning, match="knows it only as replicated"):
            out = self._sync(replicated_name)
        assert layout_verify_counters()["implausible_claims"] >= 1
        # the spec stays authoritative: passthrough identity, no reduction
        assert bool(jnp.array_equal(out, jnp.arange(16, dtype=jnp.float32)))
