"""SNR / SI-SNR parity vs the reference implementation (pure torch host code,
imported from /root/reference — its usual external oracle ``mir_eval`` is not
installed in this environment)."""
from functools import partial

import numpy as np
import pytest

from metrics_tpu.audio import ScaleInvariantSignalNoiseRatio, SignalNoiseRatio
from metrics_tpu.functional.audio import scale_invariant_signal_noise_ratio, signal_noise_ratio
from tests.helpers.reference import load_reference_module
from tests.helpers.testers import MetricTester

NUM_BATCHES, BATCH_SIZE, TIME = 4, 8, 500

_rng = np.random.RandomState(42)
_preds = _rng.randn(NUM_BATCHES, BATCH_SIZE, TIME).astype(np.float32)
_target = _rng.randn(NUM_BATCHES, BATCH_SIZE, TIME).astype(np.float32)


def _ref_snr(preds, target, zero_mean):
    import torch

    ref = load_reference_module("torchmetrics.functional.audio.snr")
    val = ref.signal_noise_ratio(torch.tensor(np.asarray(preds)), torch.tensor(np.asarray(target)), zero_mean)
    return val.mean().numpy()


def _ref_si_snr(preds, target):
    import torch

    ref = load_reference_module("torchmetrics.functional.audio.snr")
    val = ref.scale_invariant_signal_noise_ratio(torch.tensor(np.asarray(preds)), torch.tensor(np.asarray(target)))
    return val.mean().numpy()


@pytest.mark.parametrize("zero_mean", [False, True])
class TestSNR(MetricTester):
    atol = 1e-3

    def test_snr_class(self, zero_mean):
        self.run_class_metric_test(
            preds=_preds,
            target=_target,
            metric_class=SignalNoiseRatio,
            sk_metric=partial(_ref_snr, zero_mean=zero_mean),
            metric_args={"zero_mean": zero_mean},
        )

    def test_snr_functional(self, zero_mean):
        self.run_functional_metric_test(
            preds=_preds,
            target=_target,
            metric_functional=lambda p, t, zero_mean: signal_noise_ratio(p, t, zero_mean).mean(),
            sk_metric=partial(_ref_snr, zero_mean=zero_mean),
            metric_args={"zero_mean": zero_mean},
        )


class TestSISNR(MetricTester):
    atol = 1e-3

    def test_si_snr_class(self):
        self.run_class_metric_test(
            preds=_preds,
            target=_target,
            metric_class=ScaleInvariantSignalNoiseRatio,
            sk_metric=_ref_si_snr,
        )

    def test_si_snr_functional(self):
        self.run_functional_metric_test(
            preds=_preds,
            target=_target,
            metric_functional=lambda p, t: scale_invariant_signal_noise_ratio(p, t).mean(),
            sk_metric=_ref_si_snr,
        )


def test_snr_shape_mismatch_raises():
    with pytest.raises(RuntimeError, match="same shape"):
        signal_noise_ratio(np.zeros((2, 10)), np.zeros((2, 11)))
