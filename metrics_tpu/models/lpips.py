"""Flax LPIPS networks (AlexNet / VGG16 backbones + linear heads).

TPU-native replacement for the `lpips` torch package the reference wraps
(/root/reference/torchmetrics/image/lpip.py:28-41): the fixed input scaling
layer, the backbone feature stages, channel-unit-normalized squared
differences, 1x1 linear heads, and spatial averaging — expressed in Flax.

Weights are NOT bundled (no network access): convert a locally available
`lpips` package state_dict with ``convert_lpips_weights`` and pass the saved
``.npz``. Constructing the bundled net without weights raises (LPIPS values
from random weights are meaningless).
"""
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

try:
    import flax.linen as nn

    _FLAX_AVAILABLE = True
except ImportError:  # pragma: no cover
    _FLAX_AVAILABLE = False

Array = jax.Array

# fixed normalization constants from the LPIPS scaling layer
_SHIFT = (-0.030, -0.088, -0.188)
_SCALE = (0.458, 0.448, 0.450)

# backbone stage layouts: (out_channels, kernel, stride, padding, pool_before)
_ALEX_STAGES = (
    ((64, 11, 4, 2, False),),
    ((192, 5, 1, 2, True),),
    ((384, 3, 1, 1, True),),
    ((256, 3, 1, 1, False),),
    ((256, 3, 1, 1, False),),
)
_VGG_STAGES = (
    ((64, 3, 1, 1, False), (64, 3, 1, 1, False)),
    ((128, 3, 1, 1, True), (128, 3, 1, 1, False)),
    ((256, 3, 1, 1, True), (256, 3, 1, 1, False), (256, 3, 1, 1, False)),
    ((512, 3, 1, 1, True), (512, 3, 1, 1, False), (512, 3, 1, 1, False)),
    ((512, 3, 1, 1, True), (512, 3, 1, 1, False), (512, 3, 1, 1, False)),
)
_NET_STAGES = {"alex": _ALEX_STAGES, "vgg": _VGG_STAGES}


if _FLAX_AVAILABLE:

    class _Backbone(nn.Module):
        """Feature stages of AlexNet / VGG16, returning each stage's ReLU output."""

        stages: Tuple
        pool_window: int  # 3 for AlexNet, 2 for VGG

        @nn.compact
        def __call__(self, x: Array) -> List[Array]:
            outputs = []
            for stage in self.stages:
                for out_ch, kernel, stride, pad, pool_before in stage:
                    if pool_before:
                        x = nn.max_pool(x, (self.pool_window, self.pool_window), strides=(2, 2))
                    x = nn.Conv(out_ch, (kernel, kernel), strides=(stride, stride), padding=pad)(x)
                    x = nn.relu(x)
                outputs.append(x)
            return outputs

    class LPIPSNet(nn.Module):
        """Full LPIPS: scaling -> backbone stages -> normalized diff -> heads.

        Input images are NCHW in [-1, 1] (the reference's contract,
        lpip.py:37-39).
        """

        net_type: str = "alex"

        @nn.compact
        def __call__(self, img1: Array, img2: Array) -> Array:
            shift = jnp.asarray(_SHIFT).reshape(1, 1, 1, 3)
            scale = jnp.asarray(_SCALE).reshape(1, 1, 1, 3)

            def prep(x: Array) -> Array:
                x = jnp.transpose(x.astype(jnp.float32), (0, 2, 3, 1))  # NCHW -> NHWC
                return (x - shift) / scale

            backbone = _Backbone(
                stages=_NET_STAGES[self.net_type], pool_window=3 if self.net_type == "alex" else 2
            )
            feats1 = backbone(prep(img1))
            feats2 = backbone(prep(img2))

            total = 0.0
            for k, (f1, f2) in enumerate(zip(feats1, feats2)):
                f1 = f1 / (jnp.linalg.norm(f1, axis=-1, keepdims=True) + 1e-10)
                f2 = f2 / (jnp.linalg.norm(f2, axis=-1, keepdims=True) + 1e-10)
                diff = (f1 - f2) ** 2
                head = nn.Conv(1, (1, 1), use_bias=False, name=f"lin{k}")(diff)
                total = total + jnp.mean(head, axis=(1, 2))  # spatial average
            return total[:, 0]  # [N]


def convert_lpips_weights(state_dict: Any, net_type: str = "alex") -> dict:
    """Map an `lpips` package ``LPIPS(net=...)`` state_dict onto the Flax tree.

    Torch keys: ``net.sliceK.I.weight/bias`` (backbone convs, OIHW) and
    ``linK.model.1.weight`` (1x1 heads). Persist with
    ``np.savez(path, variables=np.asarray(variables, dtype=object))``.
    """
    import numpy as np

    from metrics_tpu.utils.data import torch_to_numpy

    def _np(t: Any) -> np.ndarray:
        return np.asarray(torch_to_numpy(t), dtype=np.float32)

    sd = {k.replace("module.", ""): v for k, v in dict(state_dict).items()}
    stages = _NET_STAGES[net_type]

    # backbone conv indices per slice, mirroring the lpips package's
    # torchvision slicing: within each sliceK the convs appear at positions
    # (pool/convs/relus interleaved); enumerate conv layers in order
    params: dict = {"_Backbone_0": {}}
    conv_idx = 0
    for k, stage in enumerate(stages):
        torch_slice = f"net.slice{k + 1}"
        conv_keys = sorted(
            {key.split(".")[2] for key in sd if key.startswith(torch_slice + ".") and key.endswith(".weight")},
            key=int,
        )
        if len(conv_keys) != len(stage):
            raise KeyError(
                f"Expected {len(stage)} convs under {torch_slice}, found {len(conv_keys)}"
            )
        for layer_idx in conv_keys:
            kernel = _np(sd[f"{torch_slice}.{layer_idx}.weight"]).transpose(2, 3, 1, 0)
            bias = _np(sd[f"{torch_slice}.{layer_idx}.bias"])
            params["_Backbone_0"][f"Conv_{conv_idx}"] = {"kernel": kernel, "bias": bias}
            conv_idx += 1

    for k in range(len(stages)):
        head = _np(sd[f"lin{k}.model.1.weight"]).transpose(2, 3, 1, 0)  # [1,C,1,1] -> [1,1,C,1]
        params[f"lin{k}"] = {"kernel": head}
    return {"params": params}


def build_lpips(net_type: str = "alex", weights_path: Optional[str] = None) -> Callable[[Array, Array], Array]:
    """Build a jitted ``(img1, img2) -> [N]`` LPIPS scorer from saved weights."""
    if not _FLAX_AVAILABLE:
        raise ModuleNotFoundError("The bundled LPIPS net requires `flax` to be installed.")
    if net_type not in _NET_STAGES:
        raise ValueError(f"Argument `net_type` must be one of {tuple(_NET_STAGES)}, but got {net_type}.")
    if weights_path is None:
        raise ValueError(
            "The bundled LPIPS net needs pretrained weights for meaningful values and none"
            " are bundled (no network access). Provide `weights_path` (an .npz produced by"
            " `metrics_tpu.models.lpips.convert_lpips_weights`), or pass a callable `net`."
        )
    import numpy as np

    model = LPIPSNet(net_type=net_type)
    loaded = dict(np.load(weights_path, allow_pickle=True))
    variables = jax.tree_util.tree_map(jnp.asarray, loaded["variables"].item())
    return jax.jit(lambda img1, img2: model.apply(variables, img1, img2))
