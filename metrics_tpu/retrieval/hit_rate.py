"""RetrievalHitRate.

Behavior parity with /root/reference/torchmetrics/retrieval/hit_rate.py:22-112.
"""
from typing import Any, Optional

import jax

from metrics_tpu.functional.retrieval.hit_rate import retrieval_hit_rate
from metrics_tpu.functional.retrieval.padded import hit_rate_row
from metrics_tpu.retrieval.base import RetrievalMetric
from metrics_tpu.utils.checks import _check_retrieval_k

Array = jax.Array


class RetrievalHitRate(RetrievalMetric):
    """Mean hit rate@k over queries.

    Default state is the fixed-capacity per-query table (fusible /
    async / mesh-synced; ``max_queries`` / ``max_docs`` size it);
    ``exact=True`` restores the unbounded cat-state reference path.
    """

    _padded_metric = staticmethod(hit_rate_row)

    @property
    def _padded_k(self):
        return self.k

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        k: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(empty_target_action=empty_target_action, ignore_index=ignore_index, **kwargs)
        _check_retrieval_k(k)
        self.k = k

    def _metric(self, preds: Array, target: Array) -> Array:
        return retrieval_hit_rate(preds, target, k=self.k)
