"""Torch-tensor inputs work everywhere a reference user would pass them.

Migration contract: the reference's users feed torch tensors; this
framework coerces them to jax arrays at the ``update``/``forward`` boundary
(core/metric.py ``_coerce_foreign``) — including structured detection
inputs and torch.bfloat16 — so switching frameworks requires no data-
pipeline changes. Strings and native types pass through untouched.
"""
import numpy as np
import pytest

import jax.numpy as jnp

torch = pytest.importorskip("torch")

from metrics_tpu import Accuracy, MeanSquaredError, MetricCollection
from metrics_tpu.classification import ConfusionMatrix


def test_basic_metrics_accept_torch_tensors():
    m = Accuracy()
    m.update(torch.tensor([1, 0, 1]), torch.tensor([1, 0, 0]))
    np.testing.assert_allclose(float(m.compute()), 2 / 3, atol=1e-6)

    mse = MeanSquaredError()
    batch_val = mse(torch.tensor([1.0, 2.0]), torch.tensor([1.0, 0.0]))  # forward path
    assert float(batch_val) == 2.0

    cm = ConfusionMatrix(num_classes=3)
    cm.update(torch.tensor([0, 1, 2, 1]), torch.tensor([0, 2, 2, 1]))
    assert np.asarray(cm.compute()).sum() == 4


def test_torch_bfloat16_inputs_coerced():
    m = MeanSquaredError()
    m.update(
        torch.tensor([1.0, 3.0], dtype=torch.bfloat16),
        torch.tensor([1.0, 1.0], dtype=torch.bfloat16),
    )
    np.testing.assert_allclose(float(m.compute()), 2.0, atol=1e-2)


def test_collection_and_mixed_inputs():
    col = MetricCollection([Accuracy()])
    # torch preds, numpy target — each leaf coerced independently
    col.update(torch.tensor([1, 0]), np.asarray([1, 1]))
    out = col.compute()
    np.testing.assert_allclose(float(out["Accuracy"]), 0.5, atol=1e-6)


def test_detection_structured_torch_inputs():
    from metrics_tpu.detection import MeanAveragePrecision

    preds = [
        dict(
            boxes=torch.tensor([[0.0, 0.0, 10.0, 10.0]]),
            scores=torch.tensor([0.9]),
            labels=torch.tensor([1]),
        )
    ]
    target = [dict(boxes=torch.tensor([[0.0, 0.0, 10.0, 10.0]]), labels=torch.tensor([1]))]
    m = MeanAveragePrecision()
    m.update(preds, target)
    out = m.compute()
    np.testing.assert_allclose(float(out["map"]), 1.0, atol=1e-6)


def test_text_string_inputs_untouched():
    from metrics_tpu.text import WordErrorRate

    m = WordErrorRate()
    m.update(["hello world"], ["hello there world"])
    assert float(m.compute()) > 0.0


def test_capacity_mode_accepts_torch():
    from metrics_tpu import AUROC
    from sklearn.metrics import roc_auc_score

    rng = np.random.default_rng(3)
    preds = rng.random(50).astype(np.float32)
    target = (rng.random(50) < 0.5).astype(np.int64)
    m = AUROC(capacity=64)
    m.update(torch.from_numpy(preds), torch.from_numpy(target))
    np.testing.assert_allclose(float(m.compute()), roc_auc_score(target, preds), atol=1e-6)


def test_multioutput_wrapper_forward_with_torch():
    """MultioutputWrapper slices raw inputs before child updates run; its
    forward path must coerce torch tensors too (review-found gap)."""
    from metrics_tpu.wrappers import MultioutputWrapper

    w = MultioutputWrapper(MeanSquaredError(), num_outputs=2)
    out = w(torch.tensor([[1.0, 2.0], [3.0, 4.0]]), torch.tensor([[1.0, 0.0], [3.0, 0.0]]))
    np.testing.assert_allclose(np.asarray(out).ravel(), [0.0, 10.0], atol=1e-6)
    # direct .forward() (bypassing __call__) also works
    w2 = MultioutputWrapper(MeanSquaredError(), num_outputs=2)
    out2 = w2.forward(torch.tensor([[1.0, 2.0]]), torch.tensor([[1.0, 0.0]]))
    np.testing.assert_allclose(np.asarray(out2).ravel(), [0.0, 4.0], atol=1e-6)
