"""tracelint — static analysis for the framework's trace-safety invariants.

The runtime enforces this codebase's contracts late: a host round-trip in
an ``update`` kernel surfaces as a failed ``eval_shape`` fusibility probe
(silent eager fallback), a Python scalar in a jitted-signature position as
a recompile storm the telemetry recorder warns about, a stray collective
as a multi-host hang. ``tracelint`` moves those checks to review time: an
AST-based engine with a pluggable rule registry, per-line suppression
pragmas (``# tracelint: disable=RULE-ID``), a checked-in baseline for
grandfathered violations, and text/JSON reporters.

Rule catalog (see ``docs/static_analysis.md`` for rationale + fix recipes):

* **TL-TRACE** — host round-trips (``float()``/``int()``/``bool()``/
  ``.item()``/``np.asarray``/``jax.device_get``/``.block_until_ready()``)
  and Python ``if``/``while`` on traced values inside ``update``/``compute``
  of metrics not declared ``__jit_unsafe__``, and inside functional kernels.
* **TL-RECOMPILE** — Python-scalar / ``.shape``-derived values flowing into
  jitted-signature positions (the hazard the fused-update 0-d-array
  coercion guards against).
* **TL-STATE** — registered-state attributes assigned outside
  update/reset/sync contexts, ``add_state`` with an unknown
  ``dist_reduce_fx``, and list-state / wrapper metrics missing an explicit
  ``__jit_unsafe__`` declaration.
* **TL-COLLECTIVE** — raw ``jax.lax.p*`` / ``process_allgather`` collectives
  outside ``metrics_tpu/parallel/`` and ``observability/aggregate.py``.
* **TL-PRINT** — raw ``print()`` / bare ``warnings.warn()`` in library code
  (absorbs ``scripts/check_no_print.py``; the script remains as an alias).
* **TL-DECL** — ``__jit_unsafe__`` declarations contradicted or made
  redundant by the abstract interpreter's verdict (``interp.py``): a stale
  ``True`` silently forces the eager path; a wrong ``False`` crashes the
  fused build instead of falling back.
* **TL-FLOW** — state-lifecycle dataflow (``stateflow.py``): a ``"sum"``-
  reduced leaf mutated by anything other than additive assignment, an
  overriding ``reset`` that misses a leaf, a registered-but-dead leaf.

v2 adds the **interprocedural abstract interpreter** (``interp.py``): calls
from metric updates resolve into ``metrics_tpu/functional/`` and ``utils/``,
a taint/None-ness/bool-ness lattice classifies every metric as ``fusible`` /
``unsafe(cat-growth | host-sync | data-dependent-shape)`` / ``unknown``, and
``scripts/tracelint.py --manifest`` serializes the verdicts plus per-leaf
shape/dtype/reduction abstractions to ``scripts/fusibility_manifest.json``
(``manifest.py``) — which ``core/fused.py`` consults at runtime to skip the
``eval_shape`` fusibility probe for ``fusible``-verdict metrics.

Run ``python scripts/tracelint.py`` (stdlib-only, no jax import) or
``python -m metrics_tpu.analysis``.

This package is deliberately stdlib-only so the CLI scripts can load it
without importing the (jax-heavy) parent package.
"""
from .engine import (  # noqa: F401
    FileContext,
    LintResult,
    Violation,
    analyze_paths,
    analyze_source,
    default_package_root,
    file_suppressed_rules,
    package_relpath,
    suppressed_rules,
)
from .baseline import load_baseline, save_baseline, split_by_baseline  # noqa: F401
from .reporters import render_json, render_text  # noqa: F401
from .rules import RULE_REGISTRY, Rule, all_rules, get_rules, register_rule  # noqa: F401
from .interp import (  # noqa: F401
    Project,
    Signal,
    StateEntry,
    Verdict,
    classify,
    class_facts,
    summarize_function,
    verdict_from_signals,
)
from .manifest import (  # noqa: F401
    build_manifest,
    class_key,
    load_manifest,
    lookup_class,
    manifest_verdict,
    render_manifest,
    runtime_manifest,
)
from .stateflow import analyze_class as analyze_state_flows  # noqa: F401

__all__ = [
    "FileContext",
    "LintResult",
    "Project",
    "RULE_REGISTRY",
    "Rule",
    "Signal",
    "StateEntry",
    "Verdict",
    "Violation",
    "all_rules",
    "analyze_paths",
    "analyze_source",
    "analyze_state_flows",
    "build_manifest",
    "class_facts",
    "class_key",
    "classify",
    "default_package_root",
    "file_suppressed_rules",
    "get_rules",
    "load_baseline",
    "load_manifest",
    "lookup_class",
    "manifest_verdict",
    "package_relpath",
    "register_rule",
    "render_json",
    "render_manifest",
    "render_text",
    "runtime_manifest",
    "save_baseline",
    "split_by_baseline",
    "suppressed_rules",
    "summarize_function",
    "verdict_from_signals",
]
