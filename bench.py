"""Benchmark driver: prints ONE JSON line with the headline metric.

Headline (BASELINE.md config 1→2 ladder): multiclass Accuracy update
throughput on ImageNet-1k-shaped logits, jit-compiled on the available
accelerator, compared against the reference TorchMetrics implementation
running on torch-CPU (the reference publishes no numbers of its own —
BASELINE.md — so the baseline is measured live from /root/reference).
"""
import json
import sys
import time

import numpy as np

BATCH = 4096
NUM_CLASSES = 1000
WARMUP = 3
ITERS = 20


def _make_data():
    rng = np.random.RandomState(42)
    preds = rng.rand(BATCH, NUM_CLASSES).astype(np.float32)
    target = rng.randint(0, NUM_CLASSES, size=(BATCH,)).astype(np.int64)
    return preds, target


def bench_tpu() -> float:
    """Samples/sec through jitted Accuracy update+compute on device."""
    import jax
    import jax.numpy as jnp
    from metrics_tpu.classification import Accuracy

    preds_np, target_np = _make_data()
    preds = jnp.asarray(preds_np)
    target = jnp.asarray(target_np, dtype=jnp.int32)

    metric = Accuracy(num_classes=NUM_CLASSES, average="micro", multiclass=True)
    state = metric.init_state()

    @jax.jit
    def step(state, preds, target):
        new_state = metric.update_state(state, preds, target)
        return new_state, metric.compute_state(new_state)

    state, value = step(state, preds, target)  # compile
    jax.block_until_ready((state, value))
    for _ in range(WARMUP):
        state, value = step(state, preds, target)
    jax.block_until_ready((state, value))

    t0 = time.perf_counter()
    for _ in range(ITERS):
        state, value = step(state, preds, target)
    jax.block_until_ready((state, value))
    dt = time.perf_counter() - t0
    return BATCH * ITERS / dt


def bench_reference() -> float:
    """Samples/sec through the reference TorchMetrics Accuracy on torch-CPU."""
    if "pkg_resources" not in sys.modules:
        # modern setuptools dropped pkg_resources; the reference needs a stub
        import types

        stub = types.ModuleType("pkg_resources")

        class DistributionNotFound(Exception):
            pass

        def get_distribution(name):
            raise DistributionNotFound(name)

        stub.DistributionNotFound = DistributionNotFound
        stub.get_distribution = get_distribution
        sys.modules["pkg_resources"] = stub

    sys.path.insert(0, "/root/reference")
    try:
        import torch
        from torchmetrics import Accuracy as TorchAccuracy

        preds_np, target_np = _make_data()
        preds = torch.from_numpy(preds_np)
        target = torch.from_numpy(target_np)

        metric = TorchAccuracy(num_classes=NUM_CLASSES, average="micro")
        metric.update(preds, target)
        metric.compute()
        metric.reset()

        t0 = time.perf_counter()
        iters = max(ITERS // 4, 3)
        for _ in range(iters):
            metric.update(preds, target)
            metric.compute()
            metric._computed = None
        dt = time.perf_counter() - t0
        return BATCH * iters / dt
    finally:
        sys.path.pop(0)


def main() -> None:
    tpu_sps = bench_tpu()
    try:
        ref_sps = bench_reference()
    except Exception:
        ref_sps = None

    print(
        json.dumps(
            {
                "metric": "accuracy_update_throughput",
                "value": round(tpu_sps, 1),
                "unit": "samples/sec",
                "vs_baseline": round(tpu_sps / ref_sps, 3) if ref_sps else None,
            }
        )
    )


if __name__ == "__main__":
    main()
