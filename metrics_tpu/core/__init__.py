from metrics_tpu.core.fused import FUSED_ENTRY, FusedUpdate  # noqa: F401
from metrics_tpu.core.metric import CompositionalMetric, Metric  # noqa: F401

__all__ = ["CompositionalMetric", "FUSED_ENTRY", "FusedUpdate", "Metric"]
