"""Mean squared log error.

Behavior parity with /root/reference/torchmetrics/functional/regression/log_mse.py.
"""
from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _check_same_shape

Array = jax.Array


def _mean_squared_log_error_update(preds: Array, target: Array) -> Tuple[Array, int]:
    _check_same_shape(preds, target)
    diff = jnp.log1p(preds) - jnp.log1p(target)
    sum_squared_log_error = jnp.sum(diff * diff)
    return sum_squared_log_error, target.size


def _mean_squared_log_error_compute(sum_squared_log_error: Array, n_obs: Array) -> Array:
    return sum_squared_log_error / n_obs


def mean_squared_log_error(preds: Array, target: Array) -> Array:
    """Computes mean squared log error.

    Example:
        >>> import jax.numpy as jnp
        >>> x = jnp.array([0., 1., 2., 3.])
        >>> y = jnp.array([0., 1., 2., 2.])
        >>> mean_squared_log_error(x, y)
        Array(0.02069024, dtype=float32)
    """
    sum_squared_log_error, n_obs = _mean_squared_log_error_update(preds, target)
    return _mean_squared_log_error_compute(sum_squared_log_error, n_obs)
