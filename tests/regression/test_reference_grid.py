"""Reference-parity sweep for the regression domain.

Breadth parity with /root/reference/tests/regression/ (per-metric files,
single + multioutput shape parametrization, argument corners): every module
metric x {1-D, multioutput 2-D} inputs through the full MetricTester
lifecycle against the reference implementation, plus the argument axes the
sklearn-oracle file (test_regression.py) does not sweep — R2
adjusted/multioutput modes, ExplainedVariance multioutput modes, Tweedie
powers, squared-vs-rmse MSE — and validation-error paths.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from metrics_tpu.regression import (
    CosineSimilarity,
    ExplainedVariance,
    MeanAbsoluteError,
    MeanAbsolutePercentageError,
    MeanSquaredError,
    MeanSquaredLogError,
    PearsonCorrCoef,
    R2Score,
    SpearmanCorrCoef,
    SymmetricMeanAbsolutePercentageError,
    TweedieDevianceScore,
)
from tests.helpers.reference import ref_oracle
from tests.helpers.testers import BATCH_SIZE, NUM_BATCHES, MetricTester

torch = pytest.importorskip("torch")

_rng = np.random.default_rng(91)

_single = (
    _rng.random((NUM_BATCHES, BATCH_SIZE)).astype(np.float32) + 0.05,
    _rng.random((NUM_BATCHES, BATCH_SIZE)).astype(np.float32) + 0.05,
)
_multi = (
    _rng.random((NUM_BATCHES, BATCH_SIZE, 3)).astype(np.float32) + 0.05,
    _rng.random((NUM_BATCHES, BATCH_SIZE, 3)).astype(np.float32) + 0.05,
)

# (metric class, reference functional name, args, supports multioutput 2-D)
GRID = [
    (MeanSquaredError, "mean_squared_error", {}, True),
    (MeanSquaredError, "mean_squared_error", {"squared": False}, True),
    (MeanAbsoluteError, "mean_absolute_error", {}, True),
    (MeanSquaredLogError, "mean_squared_log_error", {}, True),
    (MeanAbsolutePercentageError, "mean_absolute_percentage_error", {}, True),
    (SymmetricMeanAbsolutePercentageError, "symmetric_mean_absolute_percentage_error", {}, True),
    (ExplainedVariance, "explained_variance", {}, True),
    (ExplainedVariance, "explained_variance", {"multioutput": "raw_values"}, True),
    (ExplainedVariance, "explained_variance", {"multioutput": "variance_weighted"}, True),
    (R2Score, "r2_score", {}, False),
    (PearsonCorrCoef, "pearson_corrcoef", {}, False),
    (SpearmanCorrCoef, "spearman_corrcoef", {}, False),
    (CosineSimilarity, "cosine_similarity", {}, False),
    (TweedieDevianceScore, "tweedie_deviance_score", {"power": 0.0}, False),
    (TweedieDevianceScore, "tweedie_deviance_score", {"power": 1.0}, False),
    (TweedieDevianceScore, "tweedie_deviance_score", {"power": 2.0}, False),
]
GRID_IDS = [
    f"{cls.__name__}{''.join(f'-{k}={v}' for k, v in args.items())}" for cls, _, args, _ in GRID
]


@pytest.mark.parametrize("cls, ref_name, args, multi_ok", GRID, ids=GRID_IDS)
class TestRegressionReferenceGrid(MetricTester):
    atol = 1e-5

    def test_single_output(self, cls, ref_name, args, multi_ok):
        preds, target = _single
        self.run_class_metric_test(
            preds=preds,
            target=target,
            metric_class=cls,
            sk_metric=ref_oracle(ref_name, **args),
            metric_args=args,
            dist_sync_on_step=True,
        )

    def test_multioutput(self, cls, ref_name, args, multi_ok):
        if not multi_ok:
            pytest.skip("metric is single-output (matches the reference contract)")
        preds, target = _multi
        self.run_class_metric_test(
            preds=preds,
            target=target,
            metric_class=cls,
            sk_metric=ref_oracle(ref_name, **args),
            metric_args=args,
        )


# CosineSimilarity operates on [N, d] vectors; sweep its reductions
@pytest.mark.parametrize("reduction", ["mean", "sum", "none"])
def test_cosine_similarity_reductions(reduction):
    preds, target = _multi
    ours = CosineSimilarity(reduction=reduction)
    oracle = ref_oracle("cosine_similarity", reduction=reduction)
    for i in range(preds.shape[0]):
        ours.update(jnp.asarray(preds[i]), jnp.asarray(target[i]))
    want = oracle(preds.reshape(-1, 3), target.reshape(-1, 3))
    np.testing.assert_allclose(np.asarray(ours.compute()), want, atol=1e-5)


@pytest.mark.parametrize("adjusted", [0, 3])
@pytest.mark.parametrize("multioutput", ["uniform_average", "raw_values", "variance_weighted"])
def test_r2_adjusted_multioutput_grid(adjusted, multioutput):
    preds, target = _multi
    args = {"adjusted": adjusted, "multioutput": multioutput}
    ours = R2Score(num_outputs=3, **args)
    oracle = ref_oracle("r2_score", **args)
    for i in range(preds.shape[0]):
        ours.update(jnp.asarray(preds[i]), jnp.asarray(target[i]))
    want = oracle(preds.reshape(-1, 3), target.reshape(-1, 3))
    np.testing.assert_allclose(np.asarray(ours.compute()), want, atol=1e-5)


def test_regression_validation_errors():
    with pytest.raises(ValueError, match="adjusted"):
        R2Score(adjusted=-1)
    with pytest.raises(ValueError, match="multioutput"):
        R2Score(multioutput="bad")
    with pytest.raises(ValueError, match="power"):
        TweedieDevianceScore(power=0.5)  # (0, 1) is invalid for Tweedie
    m = MeanSquaredError()
    with pytest.raises(RuntimeError, match="same shape"):
        m.update(jnp.zeros(3), jnp.zeros(4))


# every regression module metric raises the reference's shape-mismatch error
# (the per-file `test_error_on_different_shape` the reference repeats in each
# of tests/regression/test_*.py)
_ALL_REGRESSION = [
    CosineSimilarity,
    ExplainedVariance,
    MeanAbsoluteError,
    MeanAbsolutePercentageError,
    MeanSquaredError,
    MeanSquaredLogError,
    PearsonCorrCoef,
    R2Score,
    SpearmanCorrCoef,
    SymmetricMeanAbsolutePercentageError,
    TweedieDevianceScore,
]


@pytest.mark.parametrize("cls", _ALL_REGRESSION, ids=[c.__name__ for c in _ALL_REGRESSION])
def test_error_on_different_shape(cls):
    m = cls()
    with pytest.raises(RuntimeError, match="Predictions and targets are expected to have the same shape"):
        m.update(jnp.ones(50) * 0.5, jnp.ones(100) * 0.5)


@pytest.mark.parametrize("cls", [PearsonCorrCoef, SpearmanCorrCoef])
def test_error_on_multidim_correlation(cls):
    """Pearson/Spearman accept 1-D series only (reference test_pearson.py:92,
    test_spearman.py:114)."""
    m = cls()
    with pytest.raises(ValueError, match="Expected both predictions and target to be 1 dimensional tensors."):
        m.update(jnp.ones((5, 2)) * 0.5, jnp.ones((5, 2)) * 0.5)


def test_r2_error_and_warning_matrix():
    """R2's full edge matrix (reference test_r2.py:127-163): >2-D inputs
    rejected, <2 samples rejected, and the two adjusted-fallback warnings."""
    m = R2Score()
    with pytest.raises(ValueError, match="1D or 2D"):
        m.update(jnp.ones((2, 2, 2)), jnp.ones((2, 2, 2)))
    few = R2Score()
    few.update(jnp.asarray([0.5]), jnp.asarray([0.7]))
    with pytest.raises(ValueError, match="Needs at least two samples to calculate r2 score."):
        few.compute()

    x = jnp.asarray(_rng.standard_normal(10).astype(np.float32))
    with pytest.warns(UserWarning, match="More independent regressions than data points"):
        R2Score(adjusted=10)(x, x + 0.1)
    y = jnp.asarray(_rng.standard_normal(11).astype(np.float32))
    with pytest.warns(UserWarning, match="Division by zero in adjusted r2 score"):
        R2Score(adjusted=10)(y, y + 0.1)


def test_tweedie_input_domain_errors():
    """Runtime input-domain validation per power (reference
    test_tweedie_deviance.py:120-139), both argument positions."""
    neg = jnp.asarray([-1.0, 2.0, 3.0])
    pos = jnp.asarray(_rng.random(3).astype(np.float32) + 0.05)

    m1 = TweedieDevianceScore(power=1)
    with pytest.raises(
        ValueError, match="For power=1, 'preds' has to be strictly positive and 'targets' cannot be negative."
    ):
        m1(neg, pos)
    with pytest.raises(
        ValueError, match="For power=1, 'preds' has to be strictly positive and 'targets' cannot be negative."
    ):
        m1(pos, neg)

    m2 = TweedieDevianceScore(power=2)
    with pytest.raises(ValueError, match="For power=2, both 'preds' and 'targets' have to be strictly positive."):
        m2(neg, pos)
    with pytest.raises(ValueError, match="For power=2, both 'preds' and 'targets' have to be strictly positive."):
        m2(pos, neg)


def test_mape_zero_target_epsilon_matches_reference():
    """MAPE clamps |target| from below with the reference epsilon rather
    than dividing by zero."""
    preds = np.asarray([1.0, 2.0, 3.0], np.float32)
    target = np.asarray([0.0, 2.0, 3.0], np.float32)
    ours = MeanAbsolutePercentageError()
    ours.update(jnp.asarray(preds), jnp.asarray(target))
    want = ref_oracle("mean_absolute_percentage_error")(preds, target)
    np.testing.assert_allclose(float(ours.compute()), want, rtol=1e-5)


def test_pearson_merge_uses_parallel_moments():
    """Pearson's cross-rank merge (the parallel-variance formula) agrees
    with single-pass computation — the moment-metric merge template."""
    preds, target = _single
    whole = PearsonCorrCoef()
    flat_p, flat_t = preds.reshape(-1), target.reshape(-1)
    whole.update(jnp.asarray(flat_p), jnp.asarray(flat_t))

    m = PearsonCorrCoef()
    a = m.update_state(m.init_state(), jnp.asarray(preds[0]), jnp.asarray(target[0]))
    for i in range(1, NUM_BATCHES):
        b = m.update_state(m.init_state(), jnp.asarray(preds[i]), jnp.asarray(target[i]))
        a = m.merge_states(a, b)
    np.testing.assert_allclose(float(m.compute_state(a)), float(whole.compute()), atol=1e-5)
