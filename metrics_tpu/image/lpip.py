"""Learned Perceptual Image Patch Similarity (LPIPS).

Behavior parity with /root/reference/torchmetrics/image/lpip.py:43-165:
sum/count scalar states, [-1, 1] NCHW input validation, mean/sum reduction.
``net`` accepts any callable ``(img1, img2) -> [N]`` scores (JAX), or the
bundled Flax AlexNet/VGG LPIPS with locally converted weights
(metrics_tpu/models/lpips.py — the reference wraps the `lpips` torch
package, which needs a download this environment cannot perform).
"""
from typing import Any, Callable, Optional, Union

import jax
import jax.numpy as jnp

from metrics_tpu.core.metric import Metric

Array = jax.Array


def _valid_img(img: Array) -> bool:
    return img.ndim == 4 and img.shape[1] == 3 and float(img.min()) >= -1.0 and float(img.max()) <= 1.0


class LearnedPerceptualImagePatchSimilarity(Metric):
    """Average LPIPS between image batches (lower = perceptually closer).

    Args:
        net_type: 'alex' or 'vgg' for the bundled Flax net (requires
            ``net_weights_path``), ignored when ``net`` is given.
        net: a callable ``(img1, img2) -> [N]`` LPIPS scorer.
        reduction: 'mean' or 'sum' over all accumulated image pairs.
        net_weights_path: npz produced by
            ``metrics_tpu.models.lpips.convert_lpips_weights``.
    """

    __jit_unsafe__ = True
    is_differentiable = True
    higher_is_better = False

    def __init__(
        self,
        net_type: str = "alex",
        reduction: str = "mean",
        net: Optional[Callable] = None,
        net_weights_path: Optional[str] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)

        if net is not None:
            if not callable(net):
                raise TypeError("Argument `net` must be callable")
            self.net = net
        else:
            from metrics_tpu.models.lpips import build_lpips

            self.net = build_lpips(net_type, net_weights_path)

        valid_reduction = ("mean", "sum")
        if reduction not in valid_reduction:
            raise ValueError(f"Argument `reduction` must be one of {valid_reduction}, but got {reduction}")
        self.reduction = reduction

        self.add_state("sum_scores", jnp.asarray(0.0, jnp.float32), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0.0, jnp.float32), dist_reduce_fx="sum")

    def _update(self, img1: Array, img2: Array) -> None:
        if not (_valid_img(img1) and _valid_img(img2)):
            raise ValueError(
                "Expected both input arguments to be normalized tensors (all values in range [-1,1])"
                f" and to have shape [N, 3, H, W] but `img1` have shape {img1.shape} with values in"
                f" range {[float(img1.min()), float(img1.max())]} and `img2` have shape {img2.shape}"
                f" with value in range {[float(img2.min()), float(img2.max())]}"
            )
        loss = jnp.squeeze(self.net(img1, img2))
        self.sum_scores = self.sum_scores + jnp.sum(loss)
        self.total = self.total + img1.shape[0]

    def _compute(self) -> Array:
        if self.reduction == "mean":
            return self.sum_scores / self.total
        return self.sum_scores
