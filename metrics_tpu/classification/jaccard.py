"""Modular JaccardIndex (IoU), subclass of ConfusionMatrix.

Behavior parity with /root/reference/torchmetrics/classification/jaccard.py:23-106.
"""
from typing import Any, Optional

import jax

from metrics_tpu.classification.confusion_matrix import ConfusionMatrix
from metrics_tpu.functional.classification.jaccard import _jaccard_from_confmat

Array = jax.Array


class JaccardIndex(ConfusionMatrix):
    """Computes the Jaccard index (intersection over union).

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([1, 1, 0, 0])
        >>> preds = jnp.array([0, 1, 0, 0])
        >>> jaccard = JaccardIndex(num_classes=2)
        >>> jaccard(preds, target)
        Array(0.5833334, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True

    def __init__(
        self,
        num_classes: int,
        ignore_index: Optional[int] = None,
        absent_score: float = 0.0,
        threshold: float = 0.5,
        reduction: str = "elementwise_mean",
        **kwargs: Any,
    ) -> None:
        super().__init__(
            num_classes=num_classes,
            normalize=None,
            threshold=threshold,
            **kwargs,
        )
        self.reduction = reduction
        self.ignore_index = ignore_index
        self.absent_score = absent_score

    def _compute(self) -> Array:
        return _jaccard_from_confmat(
            self.confmat, self.num_classes, self.ignore_index, self.absent_score, self.reduction
        )
