"""Average precision (area under the PR curve via the step interpolation).

Behavior parity with /root/reference/torchmetrics/functional/classification/
average_precision.py:26-233.
"""
from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.precision_recall_curve import (
    _precision_recall_curve_compute,
    _precision_recall_curve_update,
)
from metrics_tpu.utils.data import _bincount
from metrics_tpu.utils.prints import rank_zero_warn

Array = jax.Array


def _average_precision_update(
    preds: Array,
    target: Array,
    num_classes: Optional[int] = None,
    pos_label: Optional[int] = None,
    average: Optional[str] = "macro",
) -> Tuple[Array, Array, int, Optional[int]]:
    preds, target, num_classes, pos_label = _precision_recall_curve_update(preds, target, num_classes, pos_label)
    if average == "micro":
        if preds.ndim == target.ndim:
            # treat each element of the label indicator matrix as a label
            preds = preds.flatten()
            target = target.flatten()
            num_classes = 1
        else:
            raise ValueError("Cannot use `micro` average with multi-class input")
    return preds, target, num_classes, pos_label


def _average_precision_compute_with_precision_recall(
    precision: Union[Array, List[Array]],
    recall: Union[Array, List[Array]],
    num_classes: int,
    average: Optional[str] = "macro",
    weights: Optional[Array] = None,
) -> Union[List[Array], Array]:
    if num_classes == 1:
        return -jnp.sum((recall[1:] - recall[:-1]) * precision[:-1])

    res = []
    for p, r in zip(precision, recall):
        res.append(-jnp.sum((r[1:] - r[:-1]) * p[:-1]))

    if average in ("macro", "weighted"):
        res = jnp.stack(res)
        if bool(jnp.any(jnp.isnan(res))):
            rank_zero_warn(
                "Average precision score for one or more classes was `nan`. Ignoring these classes in average",
                UserWarning,
            )
        if average == "macro":
            return jnp.mean(res[~jnp.isnan(res)])
        weights = jnp.where(jnp.isnan(res), 0.0, weights)
        return jnp.sum(jnp.where(jnp.isnan(res), 0.0, res) * weights / jnp.sum(weights))
    if average is None or average == "none":
        return res
    allowed_average = ("micro", "macro", "weighted", "none", None)
    raise ValueError(f"Expected argument `average` to be one of {allowed_average} but got {average}")


def _average_precision_compute(
    preds: Array,
    target: Array,
    num_classes: int,
    pos_label: Optional[int] = None,
    average: Optional[str] = "macro",
    sample_weights: Optional[Sequence] = None,
) -> Union[List[Array], Array]:
    precision, recall, _ = _precision_recall_curve_compute(preds, target, num_classes, pos_label, sample_weights)
    if average == "weighted":
        if preds.ndim == target.ndim and target.ndim > 1:
            weights = jnp.sum(target, axis=0).astype(jnp.float32)
        else:
            weights = _bincount(target.astype(jnp.int32), minlength=num_classes).astype(jnp.float32)
        weights = weights / jnp.sum(weights)
    else:
        weights = None
    return _average_precision_compute_with_precision_recall(precision, recall, num_classes, average, weights)


def average_precision(
    preds: Array,
    target: Array,
    num_classes: Optional[int] = None,
    pos_label: Optional[int] = None,
    average: Optional[str] = "macro",
    sample_weights: Optional[Sequence] = None,
) -> Union[List[Array], Array]:
    """Computes the average precision score.

    Example:
        >>> import jax.numpy as jnp
        >>> pred = jnp.array([0., 1., 2., 3.])
        >>> target = jnp.array([0, 1, 1, 1])
        >>> average_precision(pred, target, pos_label=1)
        Array(1., dtype=float32)
    """
    preds, target, num_classes, pos_label = _average_precision_update(preds, target, num_classes, pos_label, average)
    return _average_precision_compute(preds, target, num_classes, pos_label, average, sample_weights)
