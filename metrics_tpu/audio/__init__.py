"""Audio metrics.

SNR, SI-SNR, SDR, SI-SDR, PIT, and STOI/eSTOI are implemented TPU-native
(reference audio/{snr,sdr,pit,stoi}.py; STOI's DSP is a JAX implementation
of the published algorithm since pystoi is unavailable here). PESQ keeps
the reference's metric surface with an injectable ITU-T P.862 scorer — the
~5k-LoC licensed C DSP the reference merely wraps (audio/pesq.py:25,
SURVEY §2.9) is not re-implemented; the `pesq` package slots in when
installed.
"""
from metrics_tpu.audio.pit import PermutationInvariantTraining  # noqa: F401
from metrics_tpu.audio.sdr import ScaleInvariantSignalDistortionRatio, SignalDistortionRatio  # noqa: F401
from metrics_tpu.audio.snr import ScaleInvariantSignalNoiseRatio, SignalNoiseRatio  # noqa: F401
from metrics_tpu.audio.pesq import PerceptualEvaluationSpeechQuality  # noqa: F401
from metrics_tpu.audio.stoi import ShortTimeObjectiveIntelligibility  # noqa: F401
