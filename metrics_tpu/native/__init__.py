"""Native (C++) host runtime components.

The reference keeps zero native code in-repo and leans on external C++
(scipy, torchvision, pesq — SURVEY §2.9). Where a host-side algorithm
genuinely benefits, this package ships our OWN C++ compiled on demand with
the system toolchain and bound via ctypes (no pybind11 dependency), with a
pure-Python/scipy fallback when no compiler is available.

Current components:
- ``lsap``: batched linear sum assignment (shortest-augmenting-path
  Hungarian), used by PIT's large-speaker path.
"""
import ctypes
import os
import subprocess
import tempfile
from pathlib import Path
from typing import Optional

import numpy as np

_SRC = Path(__file__).with_name("lsap.cpp")
_LIB_PATH = Path(__file__).with_name("_lsap.so")
_lib: Optional[ctypes.CDLL] = None
_native_failed = False


def _load_library() -> Optional[ctypes.CDLL]:
    """Compile (once, cached next to the source) and load the solver."""
    global _lib, _native_failed
    if _lib is not None:
        return _lib
    if _native_failed:
        return None
    try:
        if not _LIB_PATH.exists() or _LIB_PATH.stat().st_mtime < _SRC.stat().st_mtime:
            with tempfile.NamedTemporaryFile(
                suffix=".so", dir=str(_LIB_PATH.parent), delete=False
            ) as tmp:
                tmp_path = tmp.name
            try:
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", str(_SRC), "-o", tmp_path],
                    check=True,
                    capture_output=True,
                )
                os.replace(tmp_path, _LIB_PATH)  # atomic under concurrent builds
            finally:
                if os.path.exists(tmp_path):  # failed/interrupted build
                    os.unlink(tmp_path)
        lib = ctypes.CDLL(str(_LIB_PATH))
        lib.lsap_batch.restype = ctypes.c_int
        lib.lsap_batch.argtypes = [
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_int,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_int32),
        ]
        _lib = lib
        return _lib
    except Exception:
        _native_failed = True
        return None


def native_lsap_available() -> bool:
    return _load_library() is not None


def lsap(costs: np.ndarray, maximize: bool = False) -> np.ndarray:
    """Batched square linear sum assignment: ``[B, N, N] -> [B, N]`` columns.

    Uses the in-repo C++ solver when the toolchain is available, otherwise
    scipy's ``linear_sum_assignment`` (identical optima; assignments may
    differ between equally-optimal solutions).
    """
    costs = np.ascontiguousarray(costs, dtype=np.float64)
    if costs.ndim == 2:
        costs = costs[None]
    if costs.ndim != 3 or costs.shape[1] != costs.shape[2]:
        raise ValueError(f"Expected [batch, n, n] square cost matrices, got {costs.shape}")
    if not np.isfinite(costs).all():
        # non-finite costs hang the augmenting-path solver / poison potentials
        raise ValueError("cost matrix contains invalid numeric entries (inf or nan)")
    batch, n = costs.shape[0], costs.shape[1]

    lib = _load_library()
    if lib is None:
        from scipy.optimize import linear_sum_assignment

        return np.stack([linear_sum_assignment(m, maximize=maximize)[1] for m in costs]).astype(np.int32)

    work = -costs if maximize else costs
    out = np.empty((batch, n), dtype=np.int32)
    rc = lib.lsap_batch(
        work.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        batch,
        n,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    if rc != 0:
        raise RuntimeError(f"native lsap_batch failed with code {rc}")
    return out
