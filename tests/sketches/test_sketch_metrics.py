"""Sketch-backed metric conversions: parity, fusion, sync, observability.

The acceptance surface of the cat-state conversion: converted classes run
sketch-backed by DEFAULT with fixed-shape states; ``exact=True`` reproduces
the old default bit-for-bit; inside the lossless window the sketch default
is itself bit-equal to exact; beyond it, errors stay inside the advertised
envelopes; and the fused / bucketed / async / mesh-sync / merge machinery
built for sum-state metrics serves the converted classes unchanged.
"""
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from metrics_tpu import (
    AUROC,
    Accuracy,
    AveragePrecision,
    CalibrationError,
    CosineSimilarity,
    MetricCollection,
    PrecisionRecallCurve,
    ROC,
    SpearmanCorrCoef,
)
from metrics_tpu.image.kid import KernelInceptionDistance
from metrics_tpu.observability import get_recorder
from metrics_tpu.parallel.distributed import sync_pytree_in_mesh
from metrics_tpu.utils.compat import shard_map

_rng = np.random.RandomState(7)
N_BATCHES, BS = 4, 32
_preds = _rng.rand(N_BATCHES, BS).astype(np.float32)
_target = _rng.randint(0, 2, (N_BATCHES, BS))
_preds_mc = _rng.rand(N_BATCHES, BS, 5).astype(np.float32)
_preds_mc /= _preds_mc.sum(-1, keepdims=True)
_target_mc = _rng.randint(0, 5, (N_BATCHES, BS))
_target_ml = _rng.randint(0, 2, (N_BATCHES, BS, 5))


def _exact(cls, **kwargs):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return cls(exact=True, **kwargs)


def _feed(metric, preds, target):
    for i in range(preds.shape[0]):
        metric.update(jnp.asarray(preds[i]), jnp.asarray(target[i]))
    return metric


def _tree_equal(a, b):
    if isinstance(a, (list, tuple)):
        assert len(a) == len(b)
        for x, y in zip(a, b):
            _tree_equal(x, y)
    else:
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# lossless-window bit parity: sketch default == exact=True == old default
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "cls,kwargs,preds,target",
    [
        (AUROC, {}, _preds, _target),
        (AUROC, {"num_classes": 5, "average": "macro"}, _preds_mc, _target_mc),
        (AUROC, {"num_classes": 5, "average": "micro"}, _preds_mc, _target_ml),
        (AveragePrecision, {"pos_label": 1}, _preds, _target),
        (AveragePrecision, {"num_classes": 5, "average": "macro"}, _preds_mc, _target_mc),
        (ROC, {"pos_label": 1}, _preds, _target),
        (ROC, {"num_classes": 5}, _preds_mc, _target_mc),
        (ROC, {"num_classes": 5}, _preds_mc, _target_ml),
        (PrecisionRecallCurve, {"pos_label": 1}, _preds, _target),
        (PrecisionRecallCurve, {"num_classes": 5}, _preds_mc, _target_mc),
        (SpearmanCorrCoef, {}, _preds, (_preds * 0.5 + 0.1).astype(np.float32)),
        (CosineSimilarity, {"reduction": "mean"}, _preds_mc, np.abs(_preds_mc) + 0.1),
    ],
    ids=[
        "auroc-bin", "auroc-mc", "auroc-ml-micro", "ap-bin", "ap-mc",
        "roc-bin", "roc-mc", "roc-ml", "prc-bin", "prc-mc", "spearman", "cosine",
    ],
)
def test_sketch_default_bit_equal_to_exact_in_window(cls, kwargs, preds, target):
    sketch = _feed(cls(**kwargs), preds, target)
    exact = _feed(_exact(cls, **kwargs), preds, target)
    _tree_equal(sketch.compute(), exact.compute())


def test_calibration_binned_default_matches_exact_within_float_order():
    for norm in ("l1", "l2", "max"):
        sketch = _feed(CalibrationError(norm=norm), _preds, _target)
        exact = _feed(_exact(CalibrationError, norm=norm), _preds, _target)
        np.testing.assert_allclose(
            float(sketch.compute()), float(exact.compute()), atol=1e-6
        )


def test_calibration_bit_exact_on_bin_aligned_scores():
    """Scores that are exact binary fractions keep every per-bin float sum
    exactly representable, so the binned streaming state reproduces the
    exact cat-state compute BIT-FOR-BIT."""
    preds = (_rng.randint(0, 9, (3, 64)) / 8.0).astype(np.float32)
    target = _rng.randint(0, 2, (3, 64))
    for norm in ("l1", "max"):
        sketch = _feed(CalibrationError(n_bins=8, norm=norm), preds, target)
        exact = _feed(_exact(CalibrationError, n_bins=8, norm=norm), preds, target)
        assert float(sketch.compute()) == float(exact.compute())


def test_kid_reservoir_default_bit_equal_to_exact_in_window():
    feats = _rng.rand(6, 20, 8).astype(np.float32)

    def identity(x):
        return jnp.asarray(x)

    sk = KernelInceptionDistance(feature=identity, subsets=5, subset_size=10, seed=11)
    ex = _exact(KernelInceptionDistance, feature=identity, subsets=5, subset_size=10, seed=11)
    for i in range(6):
        real = i % 2 == 0
        sk.update(jnp.asarray(feats[i]), real=real)
        ex.update(jnp.asarray(feats[i]), real=real)
    sk_mean, sk_std = sk.compute()
    ex_mean, ex_std = ex.compute()
    assert float(sk_mean) == float(ex_mean) and float(sk_std) == float(ex_std)


def test_kid_reservoir_bounds_state_beyond_window():
    def identity(x):
        return jnp.asarray(x)

    m = KernelInceptionDistance(
        feature=identity, subsets=4, subset_size=16, reservoir_size=32, seed=0
    )
    for _ in range(20):
        m.update(jnp.asarray(_rng.rand(16, 4).astype(np.float32)), real=True)
        m.update(jnp.asarray(_rng.rand(16, 4).astype(np.float32)), real=False)
    bytes_now = m.total_state_bytes()
    m.update(jnp.asarray(_rng.rand(16, 4).astype(np.float32)), real=True)
    assert m.total_state_bytes() == bytes_now  # O(k), not O(N)
    mean, std = m.compute()
    assert np.isfinite(float(mean)) and np.isfinite(float(std))


# ---------------------------------------------------------------------------
# accuracy beyond the lossless window
# ---------------------------------------------------------------------------


def test_sketched_auroc_tolerance_on_large_stream():
    sk_metrics = pytest.importorskip("sklearn.metrics")
    n, cap = 50_000, 1024
    preds = _rng.rand(n).astype(np.float32)
    target = (_rng.rand(n) < 0.35).astype(np.int32)
    m = AUROC(sketch_capacity=cap)
    for lo in range(0, n, 2000):
        m.update(jnp.asarray(preds[lo : lo + 2000]), jnp.asarray(target[lo : lo + 2000]))
    got = float(m.compute())
    want = sk_metrics.roc_auc_score(target, preds)
    # curve error tracks the sketch's relative rank error (~eps/capacity)
    assert abs(got - want) < 5e-3, (got, want)
    # and the state stayed O(capacity)
    assert m.total_state_bytes() < 64 * cap


def test_sketched_average_precision_tolerance_on_large_stream():
    sk_metrics = pytest.importorskip("sklearn.metrics")
    n, cap = 50_000, 1024
    preds = _rng.rand(n).astype(np.float32)
    target = (_rng.rand(n) < 0.25).astype(np.int32)
    m = AveragePrecision(pos_label=1, sketch_capacity=cap)
    for lo in range(0, n, 2000):
        m.update(jnp.asarray(preds[lo : lo + 2000]), jnp.asarray(target[lo : lo + 2000]))
    got = float(m.compute())
    want = sk_metrics.average_precision_score(target, preds)
    assert abs(got - want) < 5e-3, (got, want)


# ---------------------------------------------------------------------------
# warnings: exact-only (satellite — the unconditional warn is gone)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("cls", [AUROC, SpearmanCorrCoef, ROC, PrecisionRecallCurve, AveragePrecision])
def test_buffer_warning_only_on_exact_path(cls):
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        cls()  # sketch default: NO large-memory warning
    with pytest.warns(UserWarning, match="memory footprint"):
        cls(exact=True)


def test_kid_buffer_warning_only_on_exact_path():
    def identity(x):
        return jnp.asarray(x)

    with warnings.catch_warnings():
        warnings.simplefilter("error")
        KernelInceptionDistance(feature=identity)
    with pytest.warns(UserWarning, match="memory footprint"):
        KernelInceptionDistance(feature=identity, exact=True)


# ---------------------------------------------------------------------------
# merge / sync plumbing
# ---------------------------------------------------------------------------


def test_merge_states_virtual_ranks_match_full_stream():
    m = AUROC()
    states = []
    for rank in range(2):
        state = m.init_state()
        for i in range(rank, N_BATCHES, 2):
            state = m.update_state(state, jnp.asarray(_preds[i]), jnp.asarray(_target[i]))
        states.append(state)
    merged = m.merge_states(states[0], states[1])
    got = float(m.compute_state(merged))
    full = _feed(AUROC(), _preds[[0, 2, 1, 3]], _target[[0, 2, 1, 3]])
    assert got == float(full.compute())  # rank-order concat, bit-for-bit


def test_dist_sync_fn_gather_merges_sketch_states():
    other = _feed(AUROC(), _preds[2:], _target[2:])
    other_states = iter([{k: jnp.asarray(getattr(other, k)) for k in other._defaults}])

    def fake_gather(x, group=None):
        return [x, next(iter(other_states.__next__().values())) if False else x]

    # a simple two-rank gather: rank 0 = local, rank 1 = `other`'s state
    states = {k: jnp.asarray(getattr(other, k)) for k in other._defaults}
    per_state = {k: iter([states[k]]) for k in states}

    def gather(x, group=None):
        for k, it in per_state.items():
            if jnp.asarray(x).shape == states[k].shape and jnp.asarray(x).dtype == states[k].dtype:
                try:
                    return [x, next(it)]
                except StopIteration:
                    return [x, x]
        return [x, x]

    m = _feed(AUROC(dist_sync_fn=gather), _preds[:2], _target[:2])
    synced = float(m.compute())
    full = _feed(AUROC(), _preds, _target)
    assert synced == float(full.compute())


def test_sketch_states_mesh_merge_sync():
    n_dev = 8
    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("rank",))
    per_rank = []
    template = AUROC(sketch_capacity=256)
    for r in range(n_dev):
        m = AUROC(sketch_capacity=256)
        m.update(jnp.asarray(_rng.rand(20).astype(np.float32)), jnp.asarray(_rng.randint(0, 2, 20)))
        per_rank.append({k: jnp.asarray(getattr(m, k)) for k in m._defaults})
    reductions = template.state_reductions()
    stacked = {k: jnp.stack([s[k] for s in per_rank]) for k in per_rank[0]}

    def body(csk, nseen):
        out = sync_pytree_in_mesh({"csketch": csk[0], "n_seen": nseen[0][0]}, reductions, "rank")
        return out["csketch"], out["n_seen"]

    synced_csk, synced_n = jax.jit(
        shard_map(body, mesh=mesh, in_specs=(P("rank"), P("rank")), out_specs=(P(), P()))
    )(stacked["csketch"], stacked["n_seen"][:, None])
    ref = reductions["csketch"](stacked["csketch"])
    np.testing.assert_allclose(np.asarray(synced_csk), np.asarray(ref), atol=1e-6)
    assert int(synced_n) == n_dev * 20
    # the synced state is still inside the lossless window: computing from it
    # equals the exact value over the union of all ranks' streams
    template.update(jnp.asarray(_preds[0][:1]), jnp.asarray(_target[0][:1]))  # lock mode
    object.__setattr__(template, "csketch", synced_csk)
    object.__setattr__(template, "n_seen", synced_n)
    template._computed = None
    assert np.isfinite(float(template.compute()))


# ---------------------------------------------------------------------------
# fused dispatch / bucketing / async
# ---------------------------------------------------------------------------


def _ragged_stream(n_shapes=(40, 64, 52)):
    for n in n_shapes:
        yield _rng.rand(n).astype(np.float32), _rng.randint(0, 2, n)


def test_fused_bucketed_single_compile_bit_parity():
    col = MetricCollection([Accuracy(), AUROC(), CalibrationError()])
    handle = col.compile_update(buckets=(64,))
    eager = {"acc": Accuracy(), "auroc": AUROC(), "ce": CalibrationError()}
    for p, t in _ragged_stream():
        col.update(jnp.asarray(p), jnp.asarray(t))
        for m in eager.values():
            m.update(jnp.asarray(p), jnp.asarray(t))
    assert handle.n_compiles == 1, handle.n_compiles  # 3 ragged shapes, ONE compile
    got = col.compute()
    assert float(got["AUROC"]) == float(eager["auroc"].compute())
    assert float(got["CalibrationError"]) == float(eager["ce"].compute())
    assert float(got["Accuracy"]) == float(eager["acc"].compute())


def test_fused_bucketed_spearman_single_compile_bit_parity():
    # Spearman takes float (pred, target) pairs, so it buckets in its own
    # collection (the curve family consumes int targets)
    col = MetricCollection([SpearmanCorrCoef()])
    handle = col.compile_update(buckets=(64,))
    eager = SpearmanCorrCoef()
    for p, _ in _ragged_stream():
        t = (p * 0.5 + 0.1).astype(np.float32)
        col.update(jnp.asarray(p), jnp.asarray(t))
        eager.update(jnp.asarray(p), jnp.asarray(t))
    assert handle.n_compiles == 1, handle.n_compiles
    assert float(col.compute()["SpearmanCorrCoef"]) == float(eager.compute())


def test_fused_manifest_probe_skip_for_sketch_classes():
    col = MetricCollection([AUROC(), CalibrationError()])
    handle = col.compile_update()
    p, t = _preds[0], _target[0]
    col.update(jnp.asarray(p), jnp.asarray(t))
    assert handle.manifest_probe_skips >= 1  # fusible verdicts skipped eval_shape


def test_exact_instances_stay_off_the_fused_path():
    col = MetricCollection([Accuracy(), _exact(AUROC)])
    col.compile_update()
    for i in range(2):
        col.update(jnp.asarray(_preds[i]), jnp.asarray(_target[i]))
    exact = _feed(_exact(AUROC), _preds[:2], _target[:2])
    got = col.compute()
    assert float(got["AUROC"]) == float(exact.compute())


def test_async_pipeline_parity_with_sketch_metrics():
    col = MetricCollection([Accuracy(), AUROC()])
    handle = col.compile_update_async(queue_depth=2)
    blocking = MetricCollection([Accuracy(), AUROC()])
    for i in range(N_BATCHES):
        col.update_async(jnp.asarray(_preds[i]), jnp.asarray(_target[i]))
        blocking.update(jnp.asarray(_preds[i]), jnp.asarray(_target[i]))
    handle.flush()
    got, want = col.compute(), blocking.compute()
    assert float(got["AUROC"]) == float(want["AUROC"])
    col.reset()


# ---------------------------------------------------------------------------
# lifecycle: state_dict / reset / forward / set_dtype
# ---------------------------------------------------------------------------


def test_state_dict_roundtrip_mid_stream():
    m = _feed(AUROC(), _preds[:2], _target[:2])
    restored = AUROC()
    restored.load_state_dict(m.state_dict())
    restored = _feed(restored, _preds[2:], _target[2:])
    full = _feed(AUROC(), _preds, _target)
    assert float(restored.compute()) == float(full.compute())


def test_reset_restores_empty_sketch():
    m = _feed(AUROC(), _preds, _target)
    m.reset()
    assert float(jnp.sum(m.csketch)) == 0.0 and int(m.n_seen) == 0
    m = _feed(m, _preds, _target)
    full = _feed(AUROC(), _preds, _target)
    assert float(m.compute()) == float(full.compute())


def test_forward_batch_value_and_accumulation():
    m = AUROC()
    batch_val = m(jnp.asarray(_preds[0]), jnp.asarray(_target[0]))
    single = _feed(AUROC(), _preds[:1], _target[:1])
    assert float(batch_val) == float(single.compute())
    m.update(jnp.asarray(_preds[1]), jnp.asarray(_target[1]))
    two = _feed(AUROC(), _preds[:2], _target[:2])
    assert float(m.compute()) == float(two.compute())


def test_mode_change_raises_like_exact_path():
    m = _feed(AUROC(), _preds[:1], _target[:1])
    with pytest.raises(ValueError, match="should be constant"):
        m.update(jnp.asarray(_preds_mc[0]), jnp.asarray(_target_mc[0]))


def test_case_inference_rebuilds_before_first_insert():
    # multilabel inputs to a default-constructed ROC with num_classes: the
    # canonicalizer infers the case from the first batch, like the old path
    m = ROC(num_classes=5)
    m.update(jnp.asarray(_preds_mc[0]), jnp.asarray(_target_ml[0]))
    exact = _exact(ROC, num_classes=5)
    exact.update(jnp.asarray(_preds_mc[0]), jnp.asarray(_target_ml[0]))
    _tree_equal(list(m.compute()), list(exact.compute()))


# ---------------------------------------------------------------------------
# observability: footprint prefix, fill ratios, Prometheus, aggregation
# ---------------------------------------------------------------------------


def test_footprint_reports_sketch_prefix_and_fill_ratio():
    m = _feed(AUROC(sketch_capacity=256), _preds[:1], _target[:1])
    fp = m.state_footprint()
    assert "sketch/csketch" in fp and "n_seen" in fp
    ratios = m.sketch_fill_ratios()
    assert ratios["csketch"] == pytest.approx(32 / 256)


def test_sketch_telemetry_families_and_aggregate():
    rec = get_recorder()
    rec.reset().enable(footprint_warn_bytes=1 << 40)
    try:
        m = _feed(AUROC(sketch_capacity=256), _preds[:2], _target[:2])
        m.compute()  # records fill ratio from the cold path
        state = {k: jnp.asarray(getattr(m, k)) for k in m._defaults}
        m.merge_states(state, state)  # one pairwise sketch merge
        totals = rec.sketch_totals()
        assert totals["merges"] >= 1
        assert totals["max_fill_ratio"] == pytest.approx(64 / 256)
        hwm = rec.footprint_high_water_marks()
        assert "AUROC[sketch]" in hwm and hwm["AUROC[sketch]"] > 0
        from metrics_tpu.observability.aggregate import aggregate_across_hosts
        from metrics_tpu.observability.exporters import render_prometheus

        agg = aggregate_across_hosts(rec)
        assert agg["sketch_totals"]["merges"] >= 1
        page = render_prometheus(rec, aggregate=agg)
        assert "metrics_tpu_sketch_merges_total" in page
        assert "metrics_tpu_sketch_fill_ratio" in page
    finally:
        rec.disable()
        rec.reset()


def test_state_bytes_bounded_at_stream_scale():
    cap = 512
    m = AUROC(sketch_capacity=cap)
    m.update(jnp.asarray(_rng.rand(600).astype(np.float32)), jnp.asarray(_rng.randint(0, 2, 600)))
    bytes_after_overflow = m.total_state_bytes()
    for _ in range(10):
        m.update(jnp.asarray(_rng.rand(600).astype(np.float32)), jnp.asarray(_rng.randint(0, 2, 600)))
    assert m.total_state_bytes() == bytes_after_overflow  # O(capacity) forever


# ---------------------------------------------------------------------------
# sliced composition: binned CalibrationError is sum-state, so it slices
# ---------------------------------------------------------------------------


def test_sliced_calibration_error_per_tenant():
    from metrics_tpu.sliced import SlicedMetric

    s = SlicedMetric(CalibrationError(n_bins=10), num_slices=4)
    ids = _rng.randint(0, 4, 64)
    preds = _rng.rand(64).astype(np.float32)
    target = _rng.randint(0, 2, 64)
    s.update(jnp.asarray(ids), jnp.asarray(preds), jnp.asarray(target))
    per_slice = s.compute()
    for tenant in range(4):
        ref = CalibrationError(n_bins=10)
        mask = ids == tenant
        ref.update(jnp.asarray(preds[mask]), jnp.asarray(target[mask]))
        np.testing.assert_allclose(
            float(np.asarray(per_slice)[tenant]), float(ref.compute()), atol=1e-6
        )


def test_sliced_rejects_merge_leaf_metrics_with_clear_error():
    from metrics_tpu.sliced import SlicedMetric
    from metrics_tpu.utils.exceptions import MetricsUserError

    with pytest.raises(MetricsUserError, match="csketch"):
        SlicedMetric(AUROC(), num_slices=4)


# ---------------------------------------------------------------------------
# review-pass regressions
# ---------------------------------------------------------------------------


def test_kid_checkpoint_restores_before_first_update_callable_extractor():
    """A fresh KID with a callable extractor learns its reservoir layout
    from the restored leaf's column count — load-then-compute must equal
    the saved metric (the lazy registration used to silently drop every
    saved key)."""

    def identity(x):
        return jnp.asarray(x)

    k = KernelInceptionDistance(feature=identity, subsets=3, subset_size=5, seed=1)
    for i in range(4):
        k.update(jnp.asarray(_rng.rand(10, 6).astype(np.float32)), real=(i % 2 == 0))
    saved = k.state_dict()
    k2 = KernelInceptionDistance(feature=identity, subsets=3, subset_size=5, seed=1)
    k2.load_state_dict(saved)
    k2._update_called = True
    m1, s1 = k.compute()
    m2, s2 = k2.compute()
    assert float(m1) == float(m2) and float(s1) == float(s2)


def test_auroc_max_fpr_multiclass_raises_past_the_window_too():
    """The exact path raises for max_fpr + multiclass; the approximate
    (post-compaction) path must stay equally loud instead of silently
    returning the full-range AUROC."""
    m = AUROC(num_classes=3, max_fpr=0.5, sketch_capacity=16)
    pm = _rng.rand(200, 3).astype(np.float32)
    tm = _rng.randint(0, 3, 200)
    m.update(jnp.asarray(pm), jnp.asarray(tm))
    with pytest.raises(ValueError, match="Partial AUC"):
        m.compute()
