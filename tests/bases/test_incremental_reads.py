"""Incremental read plane: bit-parity and cache accounting (ISSUE 17).

The plane's contract has two halves, and each gets its property test here:

* **Bit parity** — an interleaved update/read sequence served through the
  incremental caches (epoch-keyed result cache, dirty-slice folds, window
  fold memos, epoch-keyed retrieval layouts) returns results BIT-identical
  to a cold full fold of the same state. "Cold" is forced through
  ``_mark_state_written()`` — the out-of-band degrade hook — on a lockstep
  twin, so the reference never benefits from a warm cache.
* **Accounting** — every read entry point reports honest ``cache_hit`` /
  partial-fold fan-in through the PR 16 recorder: a repeat read at the same
  write epoch is a hit; any write degrades it back to a (partial) fold.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from metrics_tpu.aggregation import SumMetric
from metrics_tpu.observability import get_recorder
from metrics_tpu.regression import MeanSquaredError
from metrics_tpu.retrieval import RetrievalMAP
from metrics_tpu.retrieval import base as retrieval_base
from metrics_tpu.sliced import SlicedMetric
from metrics_tpu.windowed import WindowedMetric


@pytest.fixture
def recorder():
    rec = get_recorder()
    rec.reset()
    rec.enable(recompile_threshold=rec.DEFAULT_RECOMPILE_THRESHOLD, footprint_warn_bytes=None)
    try:
        yield rec
    finally:
        rec.disable()
        rec.reset()


def _bits_equal(a, b):
    a, b = np.asarray(a), np.asarray(b)
    assert a.shape == b.shape and a.dtype == b.dtype
    assert a.tobytes() == b.tobytes()


def _tree_bits_equal(a, b):
    if isinstance(a, dict):
        assert set(a) == set(b)
        for k in a:
            _bits_equal(a[k], b[k])
    elif isinstance(a, (list, tuple)):
        assert len(a) == len(b)
        for x, y in zip(a, b):
            _tree_bits_equal(x, y)
    else:
        _bits_equal(a, b)


# ---------------------------------------------------------------------------
# core: epoch-keyed result cache
# ---------------------------------------------------------------------------


def test_epoch_cache_serves_hit_until_any_write(recorder):
    m = SumMetric()
    m.update(jnp.asarray([1.0, 2.0]))
    v1 = m.compute()
    v2 = m.compute()  # same epoch: cached
    _bits_equal(v1, v2)
    reads = [e for e in recorder.events() if e["type"] == "read" and e["kind"] == "compute"]
    assert [e["cache_hit"] for e in reads] == [False, True]

    m.update(jnp.asarray([3.0]))
    m.compute()
    reads = [e for e in recorder.events() if e["type"] == "read" and e["kind"] == "compute"]
    assert [e["cache_hit"] for e in reads] == [False, True, False]

    # out-of-band install degrades too, even though the value is unchanged
    m._mark_state_written()
    m.compute()
    reads = [e for e in recorder.events() if e["type"] == "read" and e["kind"] == "compute"]
    assert reads[-1]["cache_hit"] is False


# ---------------------------------------------------------------------------
# sliced: dirty-set folds vs cold, S=1k
# ---------------------------------------------------------------------------


def test_sliced_interleaved_reads_bit_identical_to_cold():
    S = 1000
    rng = np.random.default_rng(17)
    inc = SlicedMetric(MeanSquaredError(), num_slices=S)
    cold = SlicedMetric(MeanSquaredError(), num_slices=S)

    for step in range(30):
        # update a small random id set (~0.5-3% of the axis) on both twins
        n = int(rng.integers(4, 32))
        ids = jnp.asarray(rng.integers(0, S, n))
        preds = jnp.asarray(rng.random(n, dtype=np.float32))
        target = jnp.asarray(rng.random(n, dtype=np.float32))
        inc.update(ids, preds, target)
        cold.update(ids, preds, target)

        kind = step % 3
        cold._mark_state_written()  # force the reference to a full cold fold
        if kind == 0:
            req = jnp.asarray(rng.choice(S, size=int(rng.integers(1, 40)), replace=False))
            _tree_bits_equal(inc.compute(slice_ids=req), cold.compute(slice_ids=req))
        elif kind == 1:
            _tree_bits_equal(inc.compute(), cold.compute())
        else:
            k = int(rng.integers(1, 9))
            ids_i, vals_i = inc.compute(top_k=k)
            ids_c, vals_c = cold.compute(top_k=k)
            _bits_equal(ids_i, ids_c)
            _tree_bits_equal(vals_i, vals_c)


def test_sliced_repeat_subset_read_is_pure_cache_hit(recorder):
    S = 64
    rng = np.random.default_rng(5)
    m = SlicedMetric(MeanSquaredError(), num_slices=S)
    ids = jnp.asarray(rng.integers(0, S, 32))
    m.update(ids, jnp.asarray(rng.random(32, dtype=np.float32)), jnp.asarray(rng.random(32, dtype=np.float32)))
    req = jnp.asarray([3, 7, 11])
    v1 = m.compute(slice_ids=req)
    v2 = m.compute(slice_ids=req)  # nothing written since: zero slices folded
    _tree_bits_equal(v1, v2)
    reads = [e for e in recorder.events() if e["type"] == "read" and e["kind"] == "sliced"]
    assert reads[0]["cache_hit"] is False and reads[0]["fanin"] >= 1
    assert reads[1]["cache_hit"] is True and reads[1].get("fanin", 0) == 0

    # a write to ONE requested slice refolds only the dirty part
    m.update(jnp.asarray([7]), jnp.asarray([0.5]), jnp.asarray([0.25]))
    m.compute(slice_ids=req)
    reads = [e for e in recorder.events() if e["type"] == "read" and e["kind"] == "sliced"]
    assert reads[-1]["cache_hit"] is False and reads[-1]["fanin"] == 1


# ---------------------------------------------------------------------------
# windowed: ring fold memos vs cold, incl. wrap/self-eviction
# ---------------------------------------------------------------------------


def test_windowed_interleaved_reads_bit_identical_to_cold():
    R, K = 6, 2
    rng = np.random.default_rng(23)
    inc = WindowedMetric(MeanSquaredError(), window=R, updates_per_bucket=K)
    cold = WindowedMetric(MeanSquaredError(), window=R, updates_per_bucket=K)

    # 3x more updates than the ring holds: the fold memos must survive
    # rotation and self-eviction without ever serving an evicted bucket
    for step in range(3 * R * K):
        preds = jnp.asarray(rng.random(8, dtype=np.float32))
        target = jnp.asarray(rng.random(8, dtype=np.float32))
        inc.update(preds, target)
        cold.update(preds, target)

        cold._mark_state_written()
        _tree_bits_equal(inc.window_state(), cold.window_state())
        w = int(rng.integers(1, R + 1))
        filled = (step + 1 + K - 1) // K
        # a window ending `b` back must not reach past the ring span: w+b<=R
        b = int(rng.integers(0, R - w + 1))
        if filled - b >= 1:
            cold._mark_state_written()
            _tree_bits_equal(
                inc.window_state(w, before=b), cold.window_state(w, before=b)
            )
            cold._mark_state_written()
            _bits_equal(inc.compute(window=w), cold.compute(window=w))


def test_windowed_same_clock_read_is_pure_cache_hit(recorder):
    m = WindowedMetric(MeanSquaredError(), window=4, updates_per_bucket=2)
    rng = np.random.default_rng(2)
    for _ in range(6):
        m.update(jnp.asarray(rng.random(4, dtype=np.float32)), jnp.asarray(rng.random(4, dtype=np.float32)))
    s1 = m.window_state()
    s2 = m.window_state()  # same ring clock: memo hit, zero merges
    _tree_bits_equal(s1, s2)
    reads = [e for e in recorder.events() if e["type"] == "read" and e["kind"] == "window"]
    assert reads[0]["cache_hit"] is False and reads[0]["fanin"] >= 1
    assert reads[1]["cache_hit"] is True and reads[1].get("fanin", 0) == 0

    # the next update completes bucket 2 and starts bucket 3: the refold
    # extends the memoized prefix by the newly completed bucket and merges
    # the still-filling one on top — two merges, never the whole window
    m.update(jnp.asarray(rng.random(4, dtype=np.float32)), jnp.asarray(rng.random(4, dtype=np.float32)))
    m.window_state()
    reads = [e for e in recorder.events() if e["type"] == "read" and e["kind"] == "window"]
    assert reads[-1]["cache_hit"] is False and reads[-1]["fanin"] == 2
    assert reads[-1]["fanin"] < reads[0]["fanin"]  # first cold fold paid 3


# ---------------------------------------------------------------------------
# retrieval: epoch-keyed layout cache vs cold
# ---------------------------------------------------------------------------


def test_retrieval_interleaved_reads_bit_identical_to_cold():
    rng = np.random.default_rng(31)
    inc = RetrievalMAP(max_queries=64, max_docs=16)
    cold = RetrievalMAP(max_queries=64, max_docs=16)
    for _ in range(12):
        n = 24
        idx = jnp.asarray(rng.integers(0, 40, n))
        preds = jnp.asarray(rng.random(n, dtype=np.float32))
        target = jnp.asarray(rng.integers(0, 2, n))
        inc.update(preds, target, indexes=idx)
        cold.update(preds, target, indexes=idx)

        v_inc = inc.compute()  # epoch-keyed layout reuse across epochs
        retrieval_base._LAYOUT_CACHE.clear()  # reference unpacks from scratch
        cold._mark_state_written()
        v_cold = cold.compute()
        _bits_equal(v_inc, v_cold)


def test_retrieval_layout_cache_hit_accounting(recorder):
    rng = np.random.default_rng(7)
    m = RetrievalMAP(max_queries=32, max_docs=8)
    idx = jnp.asarray(rng.integers(0, 16, 20))
    preds = jnp.asarray(rng.random(20, dtype=np.float32))
    target = jnp.asarray(rng.integers(0, 2, 20))
    m.update(preds, target, indexes=idx)

    m.compute()  # cold: unpack + fold
    m._computed = None  # drop the value cache, keep the epoch-keyed layout
    m.compute()  # layout served from the epoch key
    reads = [e for e in recorder.events() if e["type"] == "read" and e["kind"] == "compute"]
    assert reads[0]["cache_hit"] is False
    assert reads[1]["cache_hit"] is True  # the layout memo's hit flag

    m.update(preds, target, indexes=idx)  # write: epoch key moves on
    m.compute()
    reads = [e for e in recorder.events() if e["type"] == "read" and e["kind"] == "compute"]
    assert reads[-1]["cache_hit"] is False


def test_retrieval_layout_cache_stays_bounded():
    rng = np.random.default_rng(11)
    m = RetrievalMAP(max_queries=32, max_docs=8)
    preds = jnp.asarray(rng.random(16, dtype=np.float32))
    target = jnp.asarray(rng.integers(0, 2, 16))
    idx = jnp.asarray(rng.integers(0, 12, 16))
    for _ in range(3 * retrieval_base._LAYOUT_CACHE_MAX):
        m.update(preds, target, indexes=idx)
        m.compute()
    assert len(retrieval_base._LAYOUT_CACHE) <= retrieval_base._LAYOUT_CACHE_MAX


# ---------------------------------------------------------------------------
# deferred telemetry housekeeping + AOT reader fast-path probe
# ---------------------------------------------------------------------------


def test_recorder_tick_folds_pending_telemetry(recorder):
    # no registry attached: tick is a no-op, never an error
    assert recorder.tick() == 0

    # wide buckets so no rotation happens mid-test; every observe lands as
    # a pending value (well under the inline-flush threshold)
    registry = recorder.attach_timeseries(bucket_seconds=60.0, n_buckets=4, sketch_capacity=64)
    for v in range(10):
        registry.observe("probe_ms", float(v))

    assert recorder.tick() == 10  # folds exactly the pending values
    assert recorder.tick() == 0  # nothing left pending after the fold

    # the fold is compaction, not truncation: the values still count
    payload = registry.payload()["probe_ms"]
    assert sum(b["c"] for b in payload["buckets"]) == 10

    recorder.detach_timeseries()
    assert recorder.tick() == 0


def test_reader_cache_fast_probe_tracks_get_and_clear():
    from metrics_tpu.core.readers import ReaderCache

    cache = ReaderCache()
    assert cache.fast("double", 8) is None  # cold: no signature-free entry

    x = jnp.arange(8, dtype=jnp.float32)
    fn = cache.get("double", lambda: lambda a: a * 2.0, x, bucket=8)
    assert cache.fast("double", 8) is fn  # get() populated the probe
    assert cache.fast("double", 64) is None  # other buckets stay cold
    np.testing.assert_array_equal(np.asarray(fn(x)), np.arange(8, dtype=np.float32) * 2.0)

    cache.clear()  # the set_dtype contract: mutations drop BOTH maps
    assert cache.fast("double", 8) is None
    assert len(cache) == 0
