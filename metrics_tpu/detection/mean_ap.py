"""Modular MeanAveragePrecision (COCO mAP/mAR) for object detection.

Behavior parity with /root/reference/torchmetrics/detection/map.py:133-735
(pycocotools-style evaluation, the reference's heaviest CPU-bound path,
SURVEY §3.4).  The compute pipeline is re-architected TPU-first: the
per-(image, class, area, threshold) Python matching loops become one jitted
static-shape kernel (see metrics_tpu/functional/detection/mean_ap.py).
"""
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.detection.mean_ap import (
    _calculate_precision_recall,
    _match_units_kernel_packed,
    _pack_units,
    _summarize,
    _unpack_bool_bits,
)

Array = jax.Array

# cap on chunk_size * D * G: bounds the device IoU buffer at ~16 MB f32
_UNIT_CHUNK_ELEMS = 1 << 22

_BBOX_AREA_RANGES = {
    # reference map.py:254-259
    "all": (0.0, 1e10),
    "small": (0.0, 32.0 ** 2),
    "medium": (32.0 ** 2, 96.0 ** 2),
    "large": (96.0 ** 2, 1e10),
}


def _input_validator(preds: Sequence[dict], targets: Sequence[dict]) -> None:
    """Validate the list-of-dicts input format (reference map.py:83-123)."""
    if not isinstance(preds, Sequence):
        raise ValueError("Expected argument `preds` to be of type Sequence")
    if not isinstance(targets, Sequence):
        raise ValueError("Expected argument `target` to be of type Sequence")
    if len(preds) != len(targets):
        raise ValueError("Expected argument `preds` and `target` to have the same length")

    for k in ["boxes", "scores", "labels"]:
        if any(k not in p for p in preds):
            raise ValueError(f"Expected all dicts in `preds` to contain the `{k}` key")
    for k in ["boxes", "labels"]:
        if any(k not in p for p in targets):
            raise ValueError(f"Expected all dicts in `target` to contain the `{k}` key")

    def _is_arr(x: Any) -> bool:
        return isinstance(x, (jnp.ndarray, np.ndarray))

    if any(not _is_arr(p["boxes"]) for p in preds):
        raise ValueError("Expected all boxes in `preds` to be of type Tensor")
    if any(not _is_arr(p["scores"]) for p in preds):
        raise ValueError("Expected all scores in `preds` to be of type Tensor")
    if any(not _is_arr(p["labels"]) for p in preds):
        raise ValueError("Expected all labels in `preds` to be of type Tensor")
    if any(not _is_arr(t["boxes"]) for t in targets):
        raise ValueError("Expected all boxes in `target` to be of type Tensor")
    if any(not _is_arr(t["labels"]) for t in targets):
        raise ValueError("Expected all labels in `target` to be of type Tensor")

    for i, item in enumerate(targets):
        n_boxes = item["boxes"].shape[0] if item["boxes"].ndim > 1 else len(item["boxes"])
        if n_boxes != len(item["labels"]):
            raise ValueError(
                f"Input boxes and labels of sample {i} in targets have a"
                f" different length (expected {n_boxes} labels, got {len(item['labels'])})"
            )
    for i, item in enumerate(preds):
        n_boxes = item["boxes"].shape[0] if item["boxes"].ndim > 1 else len(item["boxes"])
        if not (n_boxes == len(item["labels"]) == len(item["scores"])):
            raise ValueError(
                f"Input boxes, labels and scores of sample {i} in predictions have a"
                f" different length (expected {n_boxes} labels and scores,"
                f" got {len(item['labels'])} labels and {len(item['scores'])} scores)"
            )


def _to_xyxy_np(boxes: Any, box_format: str) -> np.ndarray:
    """Normalize a per-image box array to host float32 ``[n, 4]`` xyxy.

    Host numpy on purpose: per-image boxes are tiny and ragged, and keeping
    them on device would mean hundreds of latency-bound host↔device
    transfers at pack time (the packed static buffers are shipped to the
    device in one piece instead).
    """
    boxes = np.asarray(boxes, dtype=np.float32)
    if boxes.size == 0:
        return np.zeros((0, 4), np.float32)
    boxes = boxes.reshape(-1, 4)
    if box_format == "xyxy":
        return boxes
    a, b, c, d = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    if box_format == "xywh":
        return np.stack([a, b, a + c, b + d], axis=1)
    return np.stack([a - c / 2, b - d / 2, a + c / 2, b + d / 2], axis=1)  # cxcywh


class MeanAveragePrecision(Metric):
    """Computes COCO-style Mean Average Precision / Recall for object detection.

    Inputs are per-image dicts: predictions with ``boxes`` ``[n, 4]``,
    ``scores`` ``[n]``, ``labels`` ``[n]``; targets with ``boxes`` and
    ``labels`` (reference map.py:271-313).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.detection import MeanAveragePrecision
        >>> preds = [dict(
        ...     boxes=jnp.array([[258.0, 41.0, 606.0, 285.0]]),
        ...     scores=jnp.array([0.536]),
        ...     labels=jnp.array([0]))]
        >>> target = [dict(
        ...     boxes=jnp.array([[214.0, 41.0, 562.0, 285.0]]),
        ...     labels=jnp.array([0]))]
        >>> metric = MeanAveragePrecision()
        >>> metric.update(preds, target)
        >>> float(metric.compute()["map"])  # doctest: +ELLIPSIS
        0.6000...
    """

    __jit_unsafe__ = True  # ragged host-side inputs; compute() jit-dispatches internally
    is_differentiable = False
    higher_is_better = True

    detection_boxes: List[Array]
    detection_scores: List[Array]
    detection_labels: List[Array]
    groundtruth_boxes: List[Array]
    groundtruth_labels: List[Array]

    def __init__(
        self,
        box_format: str = "xyxy",
        iou_thresholds: Optional[List[float]] = None,
        rec_thresholds: Optional[List[float]] = None,
        max_detection_thresholds: Optional[List[int]] = None,
        class_metrics: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)

        allowed_box_formats = ("xyxy", "xywh", "cxcywh")
        if box_format not in allowed_box_formats:
            raise ValueError(f"Expected argument `box_format` to be one of {allowed_box_formats} but got {box_format}")
        self.box_format = box_format
        # defaults: reference map.py:250-253
        self.iou_thresholds = list(iou_thresholds) if iou_thresholds else [
            0.5 + 0.05 * i for i in range(10)
        ]
        self.rec_thresholds = list(rec_thresholds) if rec_thresholds else [
            0.01 * i for i in range(101)
        ]
        self.max_detection_thresholds = sorted(max_detection_thresholds or [1, 10, 100])
        self.bbox_area_ranges = dict(_BBOX_AREA_RANGES)

        if not isinstance(class_metrics, bool):
            raise ValueError("Expected argument `class_metrics` to be a boolean")
        self.class_metrics = class_metrics

        self.add_state("detection_boxes", default=[], dist_reduce_fx=None)
        self.add_state("detection_scores", default=[], dist_reduce_fx=None)
        self.add_state("detection_labels", default=[], dist_reduce_fx=None)
        self.add_state("groundtruth_boxes", default=[], dist_reduce_fx=None)
        self.add_state("groundtruth_labels", default=[], dist_reduce_fx=None)

    def _update(self, preds: Sequence[dict], target: Sequence[dict]) -> None:
        _input_validator(preds, target)

        # states are host numpy: ragged per-image data never round-trips the
        # device; only the packed static buffers do (once, at compute time)
        for item in preds:
            self.detection_boxes.append(_to_xyxy_np(item["boxes"], self.box_format))
            self.detection_labels.append(np.asarray(item["labels"]).reshape(-1).astype(np.int32))
            self.detection_scores.append(np.asarray(item["scores"]).reshape(-1).astype(np.float32))
        for item in target:
            self.groundtruth_boxes.append(_to_xyxy_np(item["boxes"], self.box_format))
            self.groundtruth_labels.append(np.asarray(item["labels"]).reshape(-1).astype(np.int32))

    def _get_classes(self) -> List[int]:
        """Sorted unique class ids across detections and ground truths (map.py:329-333)."""
        labels = self.detection_labels + self.groundtruth_labels
        if not labels:
            return []
        cat = np.concatenate([np.asarray(l).reshape(-1) for l in labels]) if labels else np.zeros(0)
        return sorted(int(c) for c in np.unique(cat))

    def _compute(self) -> Dict[str, Array]:
        classes = self._get_classes()
        num_classes = len(classes)
        area_ranges = list(self.bbox_area_ranges.values())
        num_areas = len(area_ranges)
        T = len(self.iou_thresholds)
        R = len(self.rec_thresholds)
        M = len(self.max_detection_thresholds)
        last_max_det = self.max_detection_thresholds[-1]

        packed = _pack_units(
            [np.asarray(b) for b in self.detection_boxes],
            [np.asarray(s, np.float64) for s in self.detection_scores],
            [np.asarray(l) for l in self.detection_labels],
            [np.asarray(b) for b in self.groundtruth_boxes],
            [np.asarray(l) for l in self.groundtruth_labels],
            classes,
            last_max_det,
        )

        if packed is None:
            precision = -np.ones((T, R, num_classes, num_areas, M))
            recall = -np.ones((T, num_classes, num_areas, M))
        else:
            # chunk units through the kernel so peak device memory is bounded
            # by chunk*D*G regardless of dataset size (COCO-scale U can reach
            # ~10^5 units; the [U, D, G] IoU buffer must not scale with it)
            U = packed.det_boxes.shape[0]
            chunk = max(1, _UNIT_CHUNK_ELEMS // max(packed.det_boxes.shape[1] * packed.gt_boxes.shape[1], 1))
            dm_parts, dao_parts, npig_parts = [], [], []
            iou_thrs = jnp.asarray(self.iou_thresholds, jnp.float32)
            areas_arr = jnp.asarray(np.asarray(area_ranges, np.float32))
            for lo in range(0, U, chunk):
                hi = min(lo + chunk, U)
                n = hi - lo
                pad = chunk - n if U > chunk else 0  # keep one compiled shape
                dm, dao, npig_c = _match_units_kernel_packed(
                    jnp.asarray(np.pad(packed.det_boxes[lo:hi], ((0, pad), (0, 0), (0, 0)))),
                    jnp.asarray(np.pad(packed.det_valid[lo:hi], ((0, pad), (0, 0)))),
                    jnp.asarray(np.pad(packed.gt_boxes[lo:hi], ((0, pad), (0, 0), (0, 0)))),
                    jnp.asarray(np.pad(packed.gt_valid[lo:hi], ((0, pad), (0, 0)))),
                    iou_thrs,
                    areas_arr,
                )
                max_det_dim = packed.det_boxes.shape[1]
                dm_parts.append(_unpack_bool_bits(np.asarray(dm)[:n], max_det_dim))
                dao_parts.append(_unpack_bool_bits(np.asarray(dao)[:n], max_det_dim))
                npig_parts.append(np.asarray(npig_c)[:n])
            det_matches = np.concatenate(dm_parts)
            det_area_out = np.concatenate(dao_parts)
            npig = np.concatenate(npig_parts)
            precision, recall = _calculate_precision_recall(
                packed,
                det_matches,
                det_area_out,
                npig,
                num_classes,
                num_areas,
                self.iou_thresholds,
                self.rec_thresholds,
                self.max_detection_thresholds,
            )

        area_keys = list(self.bbox_area_ranges.keys())

        def summ(avg_prec: bool, iou_thr: Optional[float] = None, area: str = "all", mdet: int = last_max_det,
                 prec: np.ndarray = precision, rec: np.ndarray = recall) -> float:
            return _summarize(
                prec, rec, avg_prec, self.iou_thresholds,
                iou_threshold=iou_thr,
                area_idx=area_keys.index(area),
                mdet_idx=self.max_detection_thresholds.index(mdet),
            )

        # the reference's top-level `map` summarize call keeps _summarize's
        # hardcoded max_dets=100 default (map.py:484,591) — with custom
        # thresholds lacking 100 the selection is empty and the value is -1
        has_100 = 100 in self.max_detection_thresholds

        results: Dict[str, Array] = {}
        results["map"] = jnp.asarray(summ(True, mdet=100) if has_100 else -1.0, jnp.float32)
        results["map_50"] = jnp.asarray(
            summ(True, iou_thr=0.5) if 0.5 in self.iou_thresholds else -1.0, jnp.float32
        )
        results["map_75"] = jnp.asarray(
            summ(True, iou_thr=0.75) if 0.75 in self.iou_thresholds else -1.0, jnp.float32
        )
        results["map_small"] = jnp.asarray(summ(True, area="small"), jnp.float32)
        results["map_medium"] = jnp.asarray(summ(True, area="medium"), jnp.float32)
        results["map_large"] = jnp.asarray(summ(True, area="large"), jnp.float32)
        for mdet in self.max_detection_thresholds:
            results[f"mar_{mdet}"] = jnp.asarray(summ(False, mdet=mdet), jnp.float32)
        results["mar_small"] = jnp.asarray(summ(False, area="small"), jnp.float32)
        results["mar_medium"] = jnp.asarray(summ(False, area="medium"), jnp.float32)
        results["mar_large"] = jnp.asarray(summ(False, area="large"), jnp.float32)

        # per-class metrics (reference map.py:713-728)
        map_per_class = [-1.0]
        mar_per_class = [-1.0]
        if self.class_metrics and num_classes:
            map_per_class = []
            mar_per_class = []
            for k in range(num_classes):
                cls_prec = precision[:, :, k : k + 1]
                cls_rec = recall[:, k : k + 1]
                map_per_class.append(summ(True, mdet=100, prec=cls_prec, rec=cls_rec) if has_100 else -1.0)
                mar_per_class.append(summ(False, mdet=last_max_det, prec=cls_prec, rec=cls_rec))
        results["map_per_class"] = jnp.asarray(map_per_class, jnp.float32)
        results[f"mar_{last_max_det}_per_class"] = jnp.asarray(mar_per_class, jnp.float32)
        return results
