"""Modular ROC (cat-state, exact sorted mode).

Behavior parity with /root/reference/torchmetrics/classification/roc.py:24-150.
"""
from typing import Any, List, Optional, Tuple, Union

import jax

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.roc import _roc_compute, _roc_update
from metrics_tpu.utils.data import dim_zero_cat

Array = jax.Array


class ROC(Metric):
    """Computes the Receiver Operating Characteristic curve.

    Example:
        >>> import jax.numpy as jnp
        >>> pred = jnp.array([0., 1., 2., 3.])
        >>> target = jnp.array([0, 1, 1, 1])
        >>> roc = ROC(pos_label=1)
        >>> fpr, tpr, thresholds = roc(pred, target)
        >>> fpr
        Array([0., 0., 0., 0., 1.], dtype=float32)
    """

    __jit_unsafe__ = True
    is_differentiable = False

    def __init__(
        self,
        num_classes: Optional[int] = None,
        pos_label: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.pos_label = pos_label
        self.add_state("preds", default=[], dist_reduce_fx="cat")
        self.add_state("target", default=[], dist_reduce_fx="cat")

    def _update(self, preds: Array, target: Array) -> None:
        preds, target, num_classes, pos_label = _roc_update(preds, target, self.num_classes, self.pos_label)
        self.preds.append(preds)
        self.target.append(target)
        self.num_classes = num_classes
        self.pos_label = pos_label

    def _compute(self) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _roc_compute(preds, target, self.num_classes, self.pos_label)
