"""String-native metric test harness for the text domain.

Parity in spirit with the reference TextTester
(/root/reference/tests/text/helpers.py:226-430): per-batch and accumulated
parity vs an oracle, pickle round-trip, hashability, and — replacing the
2-process Gloo pool — a virtual-rank merge-parity check via the pure state
API (the same substitution tests/helpers/testers.py makes for array
domains; real-collective coverage lives in tests/bases).
"""
import pickle
from typing import Any, Callable, Optional, Sequence

import numpy as np

NUM_PROCESSES = 2


def _assert_allclose(result: Any, oracle: Any, atol: float) -> None:
    if isinstance(result, dict):
        for key in result:
            np.testing.assert_allclose(
                np.asarray(result[key]), np.asarray(oracle[key]), atol=atol, rtol=1e-5, err_msg=f"key={key}"
            )
    else:
        np.testing.assert_allclose(np.asarray(result), np.asarray(oracle), atol=atol, rtol=1e-5)


def _flatten(batches: Sequence[Sequence[Any]]) -> list:
    return [item for batch in batches for item in batch]


class TextTester:
    """Base class for text metric tests; fixtures are lists of string batches."""

    atol: float = 1e-4

    def run_class_metric_test(
        self,
        preds: Sequence[Sequence[str]],
        targets: Sequence[Any],
        metric_class: type,
        sk_metric: Callable,
        metric_args: Optional[dict] = None,
        check_batch: bool = True,
        check_merge: bool = True,
        atol: Optional[float] = None,
        key: Optional[str] = None,
    ) -> None:
        """``key`` selects one entry of a dict-valued metric for comparison
        against a scalar oracle (the ROUGE pattern)."""
        atol = self.atol if atol is None else atol
        metric_args = metric_args or {}
        metric = metric_class(**metric_args)

        def _select(value: Any) -> Any:
            return value[key] if key is not None else value

        for i, (pred_batch, target_batch) in enumerate(zip(preds, targets)):
            batch_result = metric(pred_batch, target_batch)
            if i == 0:
                clone = pickle.loads(pickle.dumps(metric))
                assert type(clone) is type(metric)
            if check_batch:
                _assert_allclose(_select(batch_result), sk_metric(pred_batch, target_batch), atol=atol)

        result = _select(metric.compute())
        full_oracle = sk_metric(_flatten(preds), _flatten(targets))
        _assert_allclose(result, full_oracle, atol=atol)
        assert isinstance(hash(metric), int)

        # virtual-rank merge parity: ranks stride batches, states merge via
        # each state's declared reducer, merged compute == full-corpus value
        if check_merge and len(preds) >= NUM_PROCESSES:
            states = []
            for rank in range(NUM_PROCESSES):
                m = metric_class(**metric_args)
                state = m.init_state()
                for i in range(rank, len(preds), NUM_PROCESSES):
                    state = m.update_state(state, preds[i], targets[i])
                states.append(state)
            merged = metric.merge_states(states[0], states[1])
            _assert_allclose(_select(metric.compute_state(merged)), full_oracle, atol=atol)

    def run_functional_metric_test(
        self,
        preds: Sequence[Sequence[str]],
        targets: Sequence[Any],
        metric_functional: Callable,
        sk_metric: Callable,
        metric_args: Optional[dict] = None,
        atol: Optional[float] = None,
    ) -> None:
        atol = self.atol if atol is None else atol
        metric_args = metric_args or {}
        for pred_batch, target_batch in zip(preds, targets):
            result = metric_functional(pred_batch, target_batch, **metric_args)
            _assert_allclose(result, sk_metric(pred_batch, target_batch), atol=atol)
        result = metric_functional(_flatten(preds), _flatten(targets), **metric_args)
        _assert_allclose(result, sk_metric(_flatten(preds), _flatten(targets)), atol=atol)
