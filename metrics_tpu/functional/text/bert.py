"""BERTScore (contextual-embedding cosine matching).

Behavior parity with /root/reference/torchmetrics/functional/text/bert.py:40-680:
tokenize, embed with a (HF) encoder, L2-normalize, zero out [CLS]/[SEP] via
the processed attention mask, greedy cosine matching (row/column max),
IDF weighting computed on the TARGET corpus, optional all-layers output and
baseline rescaling.

TPU-native departures:
- the encoder is a **Flax** transformers model (or any user callable
  ``(input_ids, attention_mask) -> [batch, seq, dim]`` jnp array) and the
  similarity/matching math is jnp under jit;
- batches keep ONE static padded length (the reference sorts by length and
  re-trims every batch — dynamic shapes that would retrace under XLA; the
  attention mask makes the results identical). Scores are returned in INPUT
  order (the reference returns them in length-sorted order as a side effect
  of its dataloader);
- no network: ``model_name_or_path`` must be a local path, and baselines
  load from ``baseline_path`` only.
"""
import csv
import math
from collections import Counter
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


def _process_attention_mask_for_special_tokens(attention_mask: Array) -> Array:
    """Zero the [CLS] (first) and [SEP] (last attended) positions."""
    attention_mask = attention_mask.at[:, 0].set(0)
    sep_pos = jnp.argmax(jnp.cumsum(attention_mask - 0.1, axis=-1), axis=-1)
    return attention_mask.at[jnp.arange(attention_mask.shape[0]), sep_pos].set(0)


def _tokens_idf(input_ids: np.ndarray) -> Dict[int, float]:
    """log((N+1)/(df+1)) inverse document frequencies over a corpus
    (reference bert.py:189-206); unseen tokens default to log(N+1)."""
    num_sentences = len(input_ids)
    counter: Counter = Counter()
    for ids in input_ids:
        # the reference deliberately counts ALL input_ids incl. padding
        # (bert.py:209-211), so the pad token gets df = num_sentences
        counter.update(set(ids.tolist()))
    idf = {idx: math.log((num_sentences + 1) / (df + 1)) for idx, df in counter.items()}
    default = math.log(num_sentences + 1)
    return {"__default__": default, **idf}


def _idf_matrix(input_ids: np.ndarray, idf: Dict[int, float]) -> np.ndarray:
    default = idf["__default__"]
    lookup = np.vectorize(lambda t: idf.get(int(t), default))
    return lookup(input_ids).astype(np.float32)


def _default_forward(model: Any, num_layers: Optional[int], all_layers: bool) -> Callable:
    """Forward through a Flax transformers model, selecting hidden layers."""

    def forward(input_ids: Array, attention_mask: Array) -> Array:
        out = model(input_ids=input_ids, attention_mask=attention_mask, output_hidden_states=True)
        hidden = out.hidden_states
        if all_layers:
            return jnp.stack(hidden, axis=1)  # [B, L, S, D]
        layer = hidden[num_layers if num_layers is not None else -1]
        return layer[:, None]  # [B, 1, S, D]

    return forward


def _embed_corpus(
    input_ids: np.ndarray,
    attention_mask: np.ndarray,
    forward: Callable,
    batch_size: int,
    idf_weights: Optional[np.ndarray],
) -> Tuple[Array, Array]:
    """Normalized, special-token-masked embeddings + per-token weight scale."""
    embeddings = []
    scales = []
    for lo in range(0, len(input_ids), batch_size):
        ids = jnp.asarray(input_ids[lo : lo + batch_size])
        mask = jnp.asarray(attention_mask[lo : lo + batch_size])
        out = forward(ids, mask)
        if out.ndim == 3:  # user forward fn returns [B, S, D]
            if out.shape[:2] != ids.shape[:2]:
                raise ValueError(
                    "The model output must be of shape [batch_size, seq_len, model_dim],"
                    f" i.e. [{ids.shape[0]}, {ids.shape[1]}, model_dim], but got {out.shape}."
                )
            out = out[:, None]
        out = out / jnp.clip(jnp.linalg.norm(out, axis=-1, keepdims=True), 1e-30, None)
        processed_mask = _process_attention_mask_for_special_tokens(mask)
        out = jnp.einsum("blsd,bs->blsd", out, processed_mask.astype(out.dtype))
        embeddings.append(out)

        if idf_weights is not None:
            scale = jnp.asarray(idf_weights[lo : lo + batch_size]) * processed_mask
        else:
            scale = processed_mask.astype(out.dtype)
        scale = scale / jnp.clip(scale.sum(-1, keepdims=True), 1e-30, None)
        scales.append(scale)
    return jnp.concatenate(embeddings), jnp.concatenate(scales)


@jax.jit
def _greedy_cosine_scores(
    preds_embeddings: Array, target_embeddings: Array, preds_scale: Array, target_scale: Array
) -> Tuple[Array, Array, Array]:
    """Greedy matching: precision = row max, recall = column max, weighted."""
    cos_sim = jnp.einsum("blpd,blrd->blpr", preds_embeddings, target_embeddings)
    precision = jnp.einsum("bls,bs->bl", cos_sim.max(axis=3), preds_scale)
    recall = jnp.einsum("bls,bs->bl", cos_sim.max(axis=2), target_scale)
    f1 = 2 * precision * recall / (precision + recall)
    f1 = jnp.where(jnp.isnan(f1), 0.0, f1)
    # [B, L] -> [L, B] to match the original BERTScore layout, squeezed below
    return precision.T, recall.T, f1.T


def _read_baseline_csv(baseline_path: str) -> np.ndarray:
    with open(baseline_path) as handle:
        rows = [[float(item) for item in row] for idx, row in enumerate(csv.reader(handle)) if idx > 0]
    return np.asarray(rows, np.float32)[:, 1:]


def _rescale_with_baseline(
    precision: Array, recall: Array, f1: Array, baseline: np.ndarray, num_layers: Optional[int], all_layers: bool
) -> Tuple[Array, Array, Array]:
    if num_layers is None and not all_layers:
        num_layers = -1
    stacked = jnp.stack([precision, recall, f1], axis=-1)
    scale = jnp.asarray(baseline)[:, None] if all_layers else jnp.asarray(baseline)[num_layers]
    stacked = (stacked - scale) / (1 - scale)
    return stacked[..., 0], stacked[..., 1], stacked[..., 2]


def _tokenize(
    texts: List[str], tokenizer: Any, max_length: int, own_tokenizer: bool, truncation: bool = True
) -> Dict[str, np.ndarray]:
    """HF-style tokenizers are called with padding/truncation kwargs (the
    reference does the same even for user tokenizers, bert.py:72-75); plain
    ``(texts, max_length)`` callables are supported as a fallback."""
    if not own_tokenizer:
        encoded = tokenizer(texts, padding=True, max_length=max_length, truncation=truncation, return_tensors="np")
    else:
        try:
            encoded = tokenizer(texts, padding=True, max_length=max_length, truncation=truncation, return_tensors="np")
        except TypeError:
            try:
                encoded = tokenizer(texts, max_length)
            except BaseException as ex:  # reference bert.py:77-80
                raise BaseException(f"Tokenization was not successful: {ex}")
    return {
        "input_ids": np.asarray(encoded["input_ids"]),
        "attention_mask": np.asarray(encoded["attention_mask"]),
    }


def bert_score(
    preds: Union[List[str], Dict[str, Any]],
    target: Union[List[str], Dict[str, Any]],
    model_name_or_path: Optional[str] = None,
    num_layers: Optional[int] = None,
    all_layers: bool = False,
    model: Optional[Callable] = None,
    user_tokenizer: Any = None,
    user_forward_fn: Optional[Callable] = None,
    idf: bool = False,
    max_length: int = 512,
    batch_size: int = 64,
    return_hash: bool = False,
    lang: str = "en",
    rescale_with_baseline: bool = False,
    baseline_path: Optional[str] = None,
    **_ignored: Any,
) -> Dict[str, Union[List[float], str]]:
    """BERTScore precision/recall/F1 per sentence pair.

    ``model`` may be a Flax transformers model or any callable
    ``(input_ids, attention_mask) -> [batch, seq, dim]``; with
    ``model_name_or_path`` a LOCAL transformers checkpoint is loaded
    (this environment has no network; the reference defaults to downloading
    roberta-large).
    """
    if len(preds) != len(target):
        raise ValueError("Number of predicted and reference sententes must be the same!")

    empty_lists = all(isinstance(t, list) and len(t) == 0 for t in (preds, target))
    if empty_lists:
        output: Dict[str, Union[List[float], str]] = {"precision": [0.0], "recall": [0.0], "f1": [0.0]}
        if return_hash:
            output["hash"] = f"{model_name_or_path}_L{num_layers}{'_idf' if idf else '_no-idf'}"
        return output

    tokenizer = user_tokenizer
    if model is None:
        if model_name_or_path is None:
            raise ValueError(
                "`bert_score` needs either a `model` callable or a LOCAL `model_name_or_path`"
                " transformers checkpoint — this environment cannot download the default model."
            )
        from transformers import AutoTokenizer, FlaxAutoModel

        tokenizer = AutoTokenizer.from_pretrained(model_name_or_path)
        model = FlaxAutoModel.from_pretrained(model_name_or_path)
    elif user_forward_fn is None and not callable(getattr(model, "__call__", None)):
        raise ValueError("`model` must be callable or `user_forward_fn` must be provided.")

    valid_lists = all(isinstance(t, list) and len(t) > 0 and isinstance(t[0], str) for t in (preds, target))
    if valid_lists:
        if tokenizer is None:
            raise ValueError("A tokenizer is required for string inputs (pass `user_tokenizer`).")
        target_tok = _tokenize(target, tokenizer, max_length, own_tokenizer=user_tokenizer is not None)
        preds_tok = _tokenize(preds, tokenizer, max_length, own_tokenizer=user_tokenizer is not None)
    elif all(isinstance(t, dict) and "input_ids" in t for t in (preds, target)):
        target_tok = {k: np.asarray(target[k]) for k in ("input_ids", "attention_mask")}
        preds_tok = {k: np.asarray(preds[k]) for k in ("input_ids", "attention_mask")}
    else:
        raise ValueError("Invalid input provided.")

    idf_dict = _tokens_idf(target_tok["input_ids"]) if idf else None
    preds_idf = _idf_matrix(preds_tok["input_ids"], idf_dict) if idf else None
    target_idf = _idf_matrix(target_tok["input_ids"], idf_dict) if idf else None

    if user_forward_fn is not None:
        if all_layers:
            raise ValueError("The option `all_layers=True` can be used only with default `transformers` models.")
        forward = lambda ids, mask: user_forward_fn(model, {"input_ids": ids, "attention_mask": mask})
    elif callable(model) and not hasattr(model, "config"):
        forward = lambda ids, mask: model(ids, mask)
    else:
        forward = _default_forward(model, num_layers, all_layers)

    target_embeddings, target_scale = _embed_corpus(
        target_tok["input_ids"], target_tok["attention_mask"], forward, batch_size, target_idf
    )
    preds_embeddings, preds_scale = _embed_corpus(
        preds_tok["input_ids"], preds_tok["attention_mask"], forward, batch_size, preds_idf
    )

    precision, recall, f1 = _greedy_cosine_scores(
        preds_embeddings, target_embeddings, preds_scale, target_scale
    )
    if precision.shape[0] == 1:  # single-layer: squeeze to [B]
        precision, recall, f1 = precision[0], recall[0], f1[0]

    if rescale_with_baseline:
        if baseline_path is None:
            raise ValueError(
                "`rescale_with_baseline=True` requires `baseline_path` (no network access to fetch baselines)."
            )
        precision, recall, f1 = _rescale_with_baseline(
            precision, recall, f1, _read_baseline_csv(baseline_path), num_layers, all_layers
        )

    output = {
        "precision": np.atleast_1d(np.asarray(precision)).tolist(),
        "recall": np.atleast_1d(np.asarray(recall)).tolist(),
        "f1": np.atleast_1d(np.asarray(f1)).tolist(),
    }
    if return_hash:
        output["hash"] = f"{model_name_or_path}_L{num_layers}{'_idf' if idf else '_no-idf'}"
    return output
