"""SNR / SI-SNR (parity: /root/reference/torchmetrics/functional/audio/snr.py).

Pure jnp elementwise/reduction math — fully jittable, batched over leading
dims, MXU-free (bandwidth-bound reductions XLA fuses into one pass).
"""
import jax
import jax.numpy as jnp

from metrics_tpu.functional.audio.sdr import scale_invariant_signal_distortion_ratio
from metrics_tpu.utils.checks import _check_same_shape

Array = jax.Array


def signal_noise_ratio(preds: Array, target: Array, zero_mean: bool = False) -> Array:
    """Signal-to-noise ratio: 10·log10(‖target‖² / ‖target − preds‖²) (snr.py:22-68).

    Args:
        preds: estimate, shape ``[..., time]``.
        target: reference, shape ``[..., time]``.
        zero_mean: subtract the time-axis mean from both signals first.

    Returns:
        SNR in dB, shape ``[...]``.

    Example:
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> signal_noise_ratio(preds, target)
        Array(16.180481, dtype=float32)
    """
    _check_same_shape(preds, target)
    eps = jnp.finfo(preds.dtype).eps

    if zero_mean:
        target = target - jnp.mean(target, axis=-1, keepdims=True)
        preds = preds - jnp.mean(preds, axis=-1, keepdims=True)

    noise = target - preds
    snr_value = (jnp.sum(target**2, axis=-1) + eps) / (jnp.sum(noise**2, axis=-1) + eps)
    return 10 * jnp.log10(snr_value)


def scale_invariant_signal_noise_ratio(preds: Array, target: Array) -> Array:
    """Scale-invariant SNR — SI-SDR with zero-mean inputs (snr.py:71-95).

    Example:
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> scale_invariant_signal_noise_ratio(preds, target)
        Array(15.091757, dtype=float32)
    """
    return scale_invariant_signal_distortion_ratio(preds, target, zero_mean=True)
