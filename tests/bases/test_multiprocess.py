"""REAL multi-process distributed sync (jax.distributed over 2 CPU processes).

The reference tests its cross-process path by spawning 2 Gloo workers
(/root/reference/tests/helpers/testers.py:35-59, tests/bases/test_ddp.py);
this is the jax analog: two OS processes join a jax.distributed coordinator
and exercise `gather_all_arrays` (even + UNEVEN shapes, scalar), the
`multihost_utils.process_allgather` branch, and a full Metric.sync() —
the one code path virtual-device tests cannot reach.
"""
import os
import socket
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys
os.environ.pop("JAX_PLATFORMS", None)
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=os.environ["COORD"],
    num_processes=2,
    process_id=int(os.environ["PROC_ID"]),
)
sys.path.insert(0, os.environ["REPO"])
import numpy as np
import jax.numpy as jnp
from metrics_tpu.parallel.distributed import distributed_available, gather_all_arrays

rank = jax.process_index()
assert jax.process_count() == 2
assert distributed_available()

# scalar gather
out = gather_all_arrays(jnp.asarray(float(rank + 1)))
assert len(out) == 2 and float(out[0]) == 1.0 and float(out[1]) == 2.0, out

# even-shape gather
out = gather_all_arrays(jnp.full((2, 3), rank, jnp.float32))
assert [o.shape for o in out] == [(2, 3), (2, 3)]
assert float(out[0][0, 0]) == 0.0 and float(out[1][0, 0]) == 1.0

# UNEVEN shapes: rank 0 has 2 rows, rank 1 has 4 (pad-to-max + trim contract)
rows = 2 if rank == 0 else 4
out = gather_all_arrays(jnp.arange(rows * 3, dtype=jnp.float32).reshape(rows, 3))
assert [o.shape for o in out] == [(2, 3), (4, 3)], [o.shape for o in out]
assert float(out[1][3, 2]) == 11.0

# full metric lifecycle: per-rank updates, compute() syncs to the global value
from metrics_tpu import MeanSquaredError
m = MeanSquaredError()
if rank == 0:
    m.update(jnp.asarray([1.0, 2.0]), jnp.asarray([1.0, 4.0]))   # sse=4, n=2
else:
    m.update(jnp.asarray([0.0, 1.0, 2.0]), jnp.asarray([6.0, 1.0, 2.0]))  # sse=36, n=3
val = float(m.compute())
assert abs(val - (4.0 + 36.0) / 5.0) < 1e-6, val
# local state restored after the sync context
assert float(m.total) == (2 if rank == 0 else 3)

# capacity-mode AUROC: the fixed [capacity] buffer triple (cat states +
# summed overflow tally) syncs across REAL processes; every rank computes
# the exact global value
from metrics_tpu import AUROC
from metrics_tpu.functional.classification.exact_curve import binary_auroc_fixed

rng = np.random.default_rng(7)
preds_all = rng.random(12).astype(np.float32)
target_all = (rng.random(12) < 0.5).astype(np.int32)
target_all[:2] = [0, 1]  # both classes present
lo, hi = (0, 6) if rank == 0 else (6, 12)
cap_m = AUROC(capacity=16)  # partially filled: padding participates in the gather
cap_m.update(jnp.asarray(preds_all[lo:hi]), jnp.asarray(target_all[lo:hi]))
got = float(cap_m.compute())
want = float(binary_auroc_fixed(
    jnp.asarray(preds_all), jnp.asarray(target_all), jnp.ones(12, bool)
))
assert abs(got - want) < 1e-6, (got, want)
# local (pre-sync) buffer restored afterwards
assert int(jnp.sum(cap_m.valid)) == 6

# unbounded list-state AUROC: the pre-cat + all-gather path across processes
unb = AUROC()
unb.update(jnp.asarray(preds_all[lo:hi]), jnp.asarray(target_all[lo:hi]))
got_unb = float(unb.compute())
assert abs(got_unb - want) < 1e-6, (got_unb, want)

print(f"RANK{rank}_OK")
"""


def test_two_process_distributed_sync(tmp_path):
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]

    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    worker_file = tmp_path / "worker.py"
    worker_file.write_text(_WORKER)

    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update({
            "COORD": f"localhost:{port}",
            "PROC_ID": str(rank),
            "REPO": repo,
            "XLA_FLAGS": "",  # no virtual devices: one real CPU device per process
        })
        procs.append(
            subprocess.Popen(
                [sys.executable, str(worker_file)],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
        )

    try:
        outs = [p.communicate(timeout=240) for p in procs]
    finally:
        for p in procs:  # never leak workers wedged in jax.distributed.initialize
            if p.returncode is None:
                p.kill()
    for rank, (p, (stdout, stderr)) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{stderr[-2000:]}"
        assert f"RANK{rank}_OK" in stdout
