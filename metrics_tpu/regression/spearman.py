"""Modular SpearmanCorrCoef (cat-state + vectorized rank transform).

Behavior parity with /root/reference/torchmetrics/regression/spearman.py:25-92.
"""
from typing import Any

import jax

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.regression.spearman import _spearman_corrcoef_compute, _spearman_corrcoef_update
from metrics_tpu.utils.data import dim_zero_cat
from metrics_tpu.utils.prints import rank_zero_warn

Array = jax.Array


class SpearmanCorrCoef(Metric):
    """Computes the Spearman rank correlation coefficient.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([3., -0.5, 2., 7.])
        >>> preds = jnp.array([2.5, 0.0, 2., 8.])
        >>> spearman = SpearmanCorrCoef()
        >>> spearman(preds, target)
        Array(0.9999992, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    #: list-append update traces; the cat states exclude it from fusion anyway
    __jit_unsafe__ = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        rank_zero_warn(
            "Metric `SpearmanCorrcoef` will save all targets and predictions in the buffer."
            " For large datasets, this may lead to a large memory footprint."
        )
        self.add_state("preds", default=[], dist_reduce_fx="cat")
        self.add_state("target", default=[], dist_reduce_fx="cat")

    def _update(self, preds: Array, target: Array) -> None:
        preds, target = _spearman_corrcoef_update(preds, target)
        self.preds.append(preds)
        self.target.append(target)

    def _compute(self) -> Array:
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _spearman_corrcoef_compute(preds, target)
