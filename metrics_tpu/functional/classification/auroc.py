"""Area under the ROC curve.

Behavior parity with /root/reference/torchmetrics/functional/classification/
auroc.py:27-277, including the weighted-average empty-class exclusion and the
``max_fpr`` partial-AUC McClish correction.
"""
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.auc import _auc_compute_without_check
from metrics_tpu.functional.classification.roc import roc
from metrics_tpu.utils.checks import (
    _input_format_classification,
    _is_concrete,
    _score_mode_static,
)
from metrics_tpu.utils.prints import rank_zero_warn
from metrics_tpu.utils.data import _bincount, stable_sort_with_payloads
from metrics_tpu.utils.enums import AverageMethod, DataType

Array = jax.Array


def _auroc_update(preds: Array, target: Array) -> Tuple[Array, Array, DataType]:
    # concrete inputs take the fully-validating formatter; under tracing the
    # mode comes from the shape-only deduction (value validation is host
    # work by contract — the capacity-buffer split, now shared by the
    # sketch-backed update so it stays jit-safe)
    if _is_concrete(preds, target):
        # use _input_format_classification for validating the input and getting the mode
        _, _, mode = _input_format_classification(preds, target)
    else:
        mode = _score_mode_static(preds, target)

    if mode == DataType.MULTIDIM_MULTICLASS:
        n_classes = preds.shape[1]
        preds = jnp.swapaxes(preds, 0, 1).reshape(n_classes, -1).T
        target = target.flatten()
    if mode == DataType.MULTILABEL and preds.ndim > 2:
        n_classes = preds.shape[1]
        preds = jnp.swapaxes(preds, 0, 1).reshape(n_classes, -1).T
        target = jnp.swapaxes(target, 0, 1).reshape(n_classes, -1).T

    return preds, target, mode


def _auroc_compute(
    preds: Array,
    target: Array,
    mode: DataType,
    num_classes: Optional[int] = None,
    pos_label: Optional[int] = None,
    average: Optional[str] = "macro",
    max_fpr: Optional[float] = None,
    sample_weights: Optional[Sequence] = None,
) -> Array:
    # binary mode overrides num_classes
    if mode == DataType.BINARY:
        num_classes = 1

    if max_fpr is not None:
        if not isinstance(max_fpr, float) or not 0 < max_fpr <= 1:
            raise ValueError(f"`max_fpr` should be a float in range (0, 1], got: {max_fpr}")
        if mode != DataType.BINARY:
            raise ValueError(
                "Partial AUC computation not available in multilabel/multiclass setting,"
                f" 'max_fpr' must be set to `None`, received `{max_fpr}`."
            )

    if mode == DataType.MULTILABEL:
        if average == AverageMethod.MICRO:
            fpr, tpr, _ = roc(preds.flatten(), target.flatten(), 1, pos_label, sample_weights)
        elif num_classes:
            output = [
                roc(preds[:, i], target[:, i], num_classes=1, pos_label=1, sample_weights=sample_weights)
                for i in range(num_classes)
            ]
            fpr = [o[0] for o in output]
            tpr = [o[1] for o in output]
        else:
            raise ValueError("Detected input to be `multilabel` but you did not provide `num_classes` argument")
    else:
        if mode != DataType.BINARY:
            if num_classes is None:
                raise ValueError("Detected input to `multiclass` but you did not provide `num_classes` argument")
            if average == AverageMethod.WEIGHTED and len(jnp.unique(target)) < num_classes:
                # classes with 0 observations are excluded (their weight is 0)
                target_bool_mat = jnp.zeros((len(target), num_classes), dtype=bool)
                target_bool_mat = target_bool_mat.at[jnp.arange(len(target)), target.astype(jnp.int32)].set(True)
                class_observed = jnp.sum(target_bool_mat, axis=0) > 0
                for c in range(num_classes):
                    if not bool(class_observed[c]):
                        rank_zero_warn(f"Class {c} had 0 observations, omitted from AUROC calculation", UserWarning)
                preds = preds[:, class_observed]
                target_bool_mat = target_bool_mat[:, class_observed]
                target = jnp.nonzero(target_bool_mat)[1]
                num_classes = int(jnp.sum(class_observed))
                if num_classes == 1:
                    raise ValueError("Found 1 non-empty class in `multiclass` AUROC calculation")
        fpr, tpr, _ = roc(preds, target, num_classes, pos_label, sample_weights)

    if max_fpr is None or max_fpr == 1:
        if mode == DataType.MULTILABEL and average == AverageMethod.MICRO:
            pass
        elif num_classes != 1:
            auc_scores = [_auc_compute_without_check(x, y, 1.0) for x, y in zip(fpr, tpr)]
            if average == AverageMethod.NONE:
                return jnp.stack(auc_scores)
            if average == AverageMethod.MACRO:
                return jnp.mean(jnp.stack(auc_scores))
            if average == AverageMethod.WEIGHTED:
                if mode == DataType.MULTILABEL:
                    support = jnp.sum(target, axis=0)
                else:
                    support = _bincount(target.flatten().astype(jnp.int32), minlength=num_classes)
                return jnp.sum(jnp.stack(auc_scores) * support / jnp.sum(support))
            allowed_average = (AverageMethod.NONE.value, AverageMethod.MACRO.value, AverageMethod.WEIGHTED.value)
            raise ValueError(
                f"Argument `average` expected to be one of the following: {allowed_average} but got {average}"
            )
        return _auc_compute_without_check(fpr, tpr, 1.0)

    # partial AUC needs both classes present: the roc kernel zero-fills the
    # degenerate axis (roc.py:45-55), which the interpolation below would
    # silently turn into NaN (no negatives) or a meaningless value (no
    # positives) — raise instead
    if not bool(fpr[-1] > 0):
        raise ValueError(
            "Partial AUC (`max_fpr`) is undefined when `target` contains no negative samples."
        )
    if not bool(tpr[-1] > 0):
        raise ValueError(
            "Partial AUC (`max_fpr`) is undefined when `target` contains no positive samples."
        )

    max_area = jnp.asarray(max_fpr, dtype=jnp.float32)
    # add a single point at max_fpr by linear interpolation
    stop = int(jnp.searchsorted(fpr, max_area, side="right"))
    weight = (max_area - fpr[stop - 1]) / (fpr[stop] - fpr[stop - 1])
    interp_tpr = tpr[stop - 1] + weight * (tpr[stop] - tpr[stop - 1])
    tpr = jnp.concatenate([tpr[:stop], interp_tpr.reshape(1)])
    fpr = jnp.concatenate([fpr[:stop], max_area.reshape(1)])

    partial_auc = _auc_compute_without_check(fpr, tpr, 1.0)

    # McClish correction: 0.5 if non-discriminant, 1 if maximal
    min_area = 0.5 * max_area**2
    return 0.5 * (1 + (partial_auc - min_area) / (max_area - min_area))


def _sorted_mean_ranks(sorted_x: Array) -> Array:
    """Tie-averaged 1-based ranks of an ALREADY row-sorted ``[C, N]``
    (ascending along the LAST axis).

    The mean rank of a tie group is (first + last position)/2 + 1, computed
    from run boundaries with cummax/cummin — no vmapped scatters or
    segment-sums (those serialize per class on TPU). The rank axis is the
    MINOR one: XLA's TPU sort and these cumulative scans both want the
    batched dimension major, which is where the 6x win over the
    column-layout version came from (round-5 on-chip A/B).
    """
    c, n = sorted_x.shape
    pos = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32)[None, :], sorted_x.shape)
    change = sorted_x[:, 1:] != sorted_x[:, :-1]
    is_start = jnp.concatenate([jnp.ones((c, 1), bool), change], axis=1)
    is_last = jnp.concatenate([change, jnp.ones((c, 1), bool)], axis=1)
    start = jax.lax.cummax(jnp.where(is_start, pos, 0), axis=1)
    end = jax.lax.cummin(jnp.where(is_last, pos, n - 1), axis=1, reverse=True)
    return (start + end).astype(jnp.float32) / 2 + 1


def auroc_rank_multiclass_masked(
    preds: Array,
    target: Array,
    valid: Array,
    num_classes: int,
    average: Optional[str] = "macro",
) -> Array:
    """``auroc_rank_multiclass`` over a fixed-capacity buffer with a validity
    mask (jit-safe; the stateful exact multiclass mode).

    Invalid rows get ``-inf`` scores so they sort strictly below every real
    score; their rank block (1..n_invalid) is subtracted from the positive
    rank sums, which reproduces the ranks computed among valid rows alone.
    Real ``-inf`` scores in ``preds`` would tie with the padding and are not
    supported.
    """
    if preds.ndim != 2 or preds.shape[1] != num_classes:
        raise ValueError(f"Expected `preds` of shape [capacity, {num_classes}], got {preds.shape}")

    n = preds.shape[0]
    # class-major [C, N] layout with ONE multi-operand lax.sort along the
    # minor axis, carrying the positive mask through the permutation —
    # replaces argsort + two axis-0 gathers (6x slower on-chip: TPU sort
    # and the midrank scans want the batch dimension major)
    scores_t = jnp.where(valid[None, :], preds.astype(jnp.float32).T, -jnp.inf)  # [C, N]
    masked_target = jnp.where(valid, target, -1)
    pos_in = (masked_target[None, :] == jnp.arange(num_classes)[:, None]).astype(jnp.float32)
    sorted_scores, pos_sorted = stable_sort_with_payloads(scores_t, pos_in)
    # within-tie permutation is free: midranks are constant across a tie run
    mean_rank_sorted = _sorted_mean_ranks(sorted_scores)  # [C, N]

    n_pos = jnp.sum(pos_in, axis=1)
    n_valid = jnp.sum(valid).astype(jnp.float32)
    n_invalid = n - n_valid
    n_neg = n_valid - n_pos

    rank_sum_pos = jnp.sum(mean_rank_sorted * pos_sorted, axis=1) - n_pos * n_invalid
    u = rank_sum_pos - n_pos * (n_pos + 1) / 2
    defined = (n_pos > 0) & (n_neg > 0)
    auc_per_class = jnp.where(defined, u / jnp.where(defined, n_pos * n_neg, 1.0), jnp.nan)

    if average in (None, "none", AverageMethod.NONE):
        return auc_per_class
    # NaN (not 0) when NO class is defined — a blanked valid mask (overflow
    # poisoning, or a never-updated buffer) must never yield a plausible value
    any_defined = jnp.any(defined)
    if average == AverageMethod.MACRO:
        macro = jnp.sum(jnp.where(defined, auc_per_class, 0.0)) / jnp.maximum(jnp.sum(defined), 1)
        return jnp.where(any_defined, macro, jnp.nan)
    if average == AverageMethod.WEIGHTED:
        w = jnp.where(defined, n_pos, 0.0)
        weighted = jnp.sum(jnp.where(defined, auc_per_class, 0.0) * w) / jnp.maximum(jnp.sum(w), 1.0)
        return jnp.where(any_defined, weighted, jnp.nan)
    raise ValueError(f"Argument `average` expected to be one of ('macro', 'weighted', 'none') but got {average}")


def auroc_rank_multiclass(
    preds: Array,
    target: Array,
    num_classes: int,
    average: Optional[str] = "macro",
) -> Array:
    """Exact one-vs-rest multiclass AUROC via the Mann-Whitney U statistic —
    the TPU-native fast path (no reference analog).

    The curve-based ``auroc`` sorts per class host-side with data-dependent
    shapes. This kernel computes the identical value (trapezoidal AUC of the
    exact ROC equals the tie-corrected rank statistic) as one static-shape,
    jit-compatible pass: midranks per class column (sort + segment-mean, see
    spearman's ``_rank_data``), then

        auc_c = (sum of positive midranks - n_pos(n_pos+1)/2) / (n_pos n_neg)

    Classes with no positives or no negatives are excluded from the average.
    (AUROC is undefined there; note this differs from both sklearn, which
    raises for such inputs, and the torch reference, which warns and scores
    the class 0 — exclusion keeps the average unbiased on sharded eval
    batches where tail classes may be absent.)

    Args:
        preds: ``[N, C]`` scores (any monotone transform of probabilities).
        target: ``[N]`` integer labels.
        num_classes: number of classes ``C`` (static).
        average: 'macro' | 'weighted' | 'none'/None.
    """
    n = preds.shape[0]
    return auroc_rank_multiclass_masked(
        preds, target, jnp.ones((n,), bool), num_classes, average=average
    )


def auroc(
    preds: Array,
    target: Array,
    num_classes: Optional[int] = None,
    pos_label: Optional[int] = None,
    average: Optional[str] = "macro",
    max_fpr: Optional[float] = None,
    sample_weights: Optional[Sequence] = None,
) -> Array:
    """Computes the Area Under the Receiver Operating Characteristic Curve.

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.array([0.13, 0.26, 0.08, 0.19, 0.34])
        >>> target = jnp.array([0, 0, 1, 1, 1])
        >>> auroc(preds, target, pos_label=1)
        Array(0.5, dtype=float32)
    """
    preds, target, mode = _auroc_update(preds, target)
    return _auroc_compute(preds, target, mode, num_classes, pos_label, average, max_fpr, sample_weights)
