"""Modular PerceptualEvaluationSpeechQuality.

Parity surface with /root/reference/torchmetrics/audio/pesq.py:25-118
(fs/mode validation, per-utterance scoring, sum/count averaging states). The
default scorer is the external ``pesq`` C binding when installed (bit-exact
ITU conformance, what the reference wraps), otherwise the IN-REPO ITU-T
P.862 engine (:mod:`metrics_tpu.functional.audio._pesq_engine`) — the metric
always computes. ``pesq_fn`` stays injectable.
"""
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.audio.pesq import perceptual_evaluation_speech_quality

Array = jax.Array


class PerceptualEvaluationSpeechQuality(Metric):
    """Average PESQ MOS-LQO over accumulated utterances (host-side P.862 DSP).

    Args:
        fs: sampling frequency (8000 for narrow-band, 16000 for wide-band).
        mode: 'nb' (narrow-band) or 'wb' (wide-band; requires fs=16000).
        pesq_fn: optional scorer override ``(ref, deg, fs, mode) -> float``;
            defaults to the ``pesq`` C binding when installed, else the
            in-repo P.862 engine.
    """

    is_differentiable = False
    higher_is_better = True
    __jit_unsafe__ = True  # per-utterance host DSP

    def __init__(self, fs: int, mode: str, pesq_fn: Optional[Callable] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if fs not in (8000, 16000):
            raise ValueError(f"Expected argument `fs` to either be 8000 or 16000 but got {fs}")
        self.fs = fs
        if mode not in ("wb", "nb"):
            raise ValueError(f"Expected argument `mode` to either be 'wb' or 'nb' but got {mode}")
        if mode == "wb" and fs == 8000:
            raise ValueError("Wide-band PESQ ('wb') requires fs=16000")
        self.mode = mode
        self.pesq_fn = pesq_fn

        self.add_state("sum_pesq", default=jnp.asarray(0.0, jnp.float32), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0, jnp.int32), dist_reduce_fx="sum")

    def _update(self, preds: Array, target: Array) -> None:
        scores = perceptual_evaluation_speech_quality(
            preds, target, self.fs, self.mode, self.pesq_fn
        )
        self.sum_pesq = self.sum_pesq + jnp.sum(scores)
        self.total = self.total + scores.size

    def _compute(self) -> Array:
        return self.sum_pesq / self.total
