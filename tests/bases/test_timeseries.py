"""Windowed telemetry time-series tests (ISSUE 11 tentpole): ring-of-buckets
semantics, sketch-backed windowed quantiles vs the advertised rank-error
bound, cross-host payload merge (the acceptance pin), and the recorder feed
wiring for every standard series."""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import MeanSquaredError, MetricCollection
from metrics_tpu.aggregation import MeanMetric
from metrics_tpu.classification import AUROC
from metrics_tpu.observability import (
    aggregate_across_hosts,
    counter_payload,
    get_recorder,
    merge_payloads,
)
from metrics_tpu.observability.recorder import (
    SERIES_ASYNC_AGE_MS,
    SERIES_ASYNC_APPLY_MS,
    SERIES_ASYNC_DROPPED,
    SERIES_ASYNC_ENQUEUED,
    SERIES_ASYNC_QUEUE_DEPTH,
    SERIES_FUSED_DISPATCH_MS,
    SERIES_HOT_SLICE_SHARE,
    SERIES_INGEST_ROWS,
    SERIES_RECOMPILES,
    SERIES_SKETCH_FILL,
    SERIES_SLICED_ROWS,
    SERIES_UPDATE_MS,
)
from metrics_tpu.observability.timeseries import (
    TelemetrySeries,
    TimeSeriesRegistry,
    merge_registry_payloads,
    registry_from_payload,
    series_from_payload,
)
from metrics_tpu.sketches.quantile import rank_error_bound
from metrics_tpu.sliced import SlicedMetric

T0 = 10_000.0  # explicit timestamps: no test below depends on the wall clock


@pytest.fixture
def recorder():
    """Default recorder enabled with a windowed registry attached; ALWAYS
    disabled + detached + reset after (the session guard pins it)."""
    rec = get_recorder()
    rec.reset()
    rec.enable()
    rec.attach_timeseries(bucket_seconds=1.0, n_buckets=60, sketch_capacity=64)
    try:
        yield rec
    finally:
        rec.disable()
        rec.detach_timeseries()
        rec.reset()


# ---------------------------------------------------------------------------
# ring / window semantics
# ---------------------------------------------------------------------------

def test_windowed_scalar_stats():
    s = TelemetrySeries("lat", bucket_seconds=1.0, n_buckets=10)
    for i, v in enumerate([10.0, 20.0, 30.0, 40.0]):
        s.record(v, t=T0 + i)  # one value per bucket
    now = T0 + 3.5
    assert s.count(None, now=now) == 4
    assert s.count(2.0, now=now) == 2  # only the last two buckets
    assert s.total(2.0, now=now) == 70.0
    assert s.mean(2.0, now=now) == 35.0
    assert s.value_min(2.0, now=now) == 30.0
    assert s.value_max(None, now=now) == 40.0
    assert s.rate(2.0, now=now) == pytest.approx(35.0)


def test_bucket_expiry_is_ring_capacity():
    s = TelemetrySeries("lat", bucket_seconds=1.0, n_buckets=5)
    s.record(1.0, t=T0)
    assert s.count(None, now=T0) == 1
    # 5 buckets later the slot's index has left the ring span
    assert s.count(None, now=T0 + 10) == 0
    # and a write that wraps onto the slot resets it rather than mixing eras
    s.record(2.0, t=T0 + 5)  # same ring position as T0 (5 % 5)
    assert s.total(None, now=T0 + 5) == 2.0


def test_sub_bucket_window_includes_current_bucket():
    # a window narrower than one bucket must still see the current bucket:
    # a health rule tuned tighter than the bucket width would otherwise
    # read an empty window and silently never fire
    s = TelemetrySeries("lat", bucket_seconds=1.0, n_buckets=10)
    s.record(100.0, t=T0 + 0.55)
    assert s.count(0.5, now=T0 + 0.6) == 1
    assert s.value_max(0.25, now=T0 + 0.9) == 100.0
    assert s.quantile(0.5, window_s=0.25, now=T0 + 0.9) == pytest.approx(100.0)


def test_empty_window_returns_none():
    s = TelemetrySeries("lat")
    assert s.mean(10, now=T0) is None
    assert s.value_max(10, now=T0) is None
    assert s.quantile(0.5, window_s=10, now=T0) is None


def test_counter_series_rejects_quantiles():
    s = TelemetrySeries("ops", kind="counter")
    s.record(5, t=T0)
    s.record(3, t=T0 + 0.5)
    assert s.total(10, now=T0 + 1) == 8.0
    with pytest.raises(ValueError, match="counter"):
        s.quantile(0.5, window_s=10, now=T0 + 1)


def test_validation():
    with pytest.raises(ValueError, match="kind"):
        TelemetrySeries("x", kind="gauge")
    with pytest.raises(ValueError, match="bucket_seconds"):
        TelemetrySeries("x", bucket_seconds=0)
    with pytest.raises(ValueError, match="n_buckets"):
        TelemetrySeries("x", n_buckets=1)
    with pytest.raises(ValueError, match="sketch_capacity"):
        TelemetrySeries("x", sketch_capacity=4)


# ---------------------------------------------------------------------------
# windowed quantiles: accuracy contract
# ---------------------------------------------------------------------------

def _rank_err(values: np.ndarray, estimate: float, q: float) -> float:
    return abs(np.sum(values <= estimate) / len(values) - q)


def test_quantiles_lossless_window_exact():
    s = TelemetrySeries("lat", bucket_seconds=1.0, n_buckets=10, sketch_capacity=64)
    vals = np.arange(40, dtype=np.float64)  # fits capacity: zero rank error
    for v in vals:
        s.record(float(v), t=T0 + (v % 4))
    for q in (0.1, 0.5, 0.9):
        est = s.quantile(q, window_s=10, now=T0 + 4)
        assert est in vals  # the estimate is an actual sample
        assert _rank_err(vals, est, q) <= 1.0 / len(vals) + 1e-9


def test_quantiles_within_advertised_rank_error_past_capacity():
    rng = np.random.default_rng(7)
    cap = 64
    s = TelemetrySeries("lat", bucket_seconds=1.0, n_buckets=20, sketch_capacity=cap)
    vals = rng.uniform(0.0, 100.0, 3000)
    for i, v in enumerate(vals):
        s.record(float(v), t=T0 + (i % 10))
    # per-bucket sketches each hold ~300 inserts -> merged error is bounded
    # by the advertised envelope for the pooled count
    bound = rank_error_bound(len(vals), cap) / len(vals)
    now = T0 + 10
    qs = (0.5, 0.95, 0.99)
    ests = s.quantiles(qs, window_s=20, now=now)
    for q, est in zip(qs, ests):
        assert _rank_err(vals, est, q) <= bound, (q, est)


def test_quantile_windowing_excludes_old_buckets():
    s = TelemetrySeries("lat", bucket_seconds=1.0, n_buckets=30, sketch_capacity=64)
    for i in range(100):
        s.record(1000.0, t=T0 + 0.5)  # old spike
    for i in range(100):
        s.record(float(i % 10), t=T0 + 8.0)
    est = s.quantile(0.99, window_s=3.0, now=T0 + 9.0)
    assert est < 100  # the spike is outside the window
    est_all = s.quantile(0.99, window_s=None, now=T0 + 9.0)
    assert est_all >= 900  # whole-ring query still sees it


def test_inline_flush_bound_many_values_one_bucket():
    s = TelemetrySeries("lat", bucket_seconds=1.0, n_buckets=4, sketch_capacity=16)
    vals = np.arange(5000, dtype=np.float64)
    for v in vals:
        s.record(float(v), t=T0)  # all in ONE bucket; pending flushes inline
    assert s.count(None, now=T0) == 5000
    est = s.quantile(0.5, window_s=None, now=T0)
    assert _rank_err(vals, est, 0.5) <= rank_error_bound(5000, 16) / 5000


# ---------------------------------------------------------------------------
# payloads and cross-host merge (the aggregate_across_hosts acceptance pin)
# ---------------------------------------------------------------------------

def test_payload_roundtrip_preserves_queries():
    s = TelemetrySeries("lat", bucket_seconds=1.0, n_buckets=10, sketch_capacity=64)
    rng = np.random.default_rng(3)
    vals = rng.normal(50, 10, 500)
    for i, v in enumerate(vals):
        s.record(float(v), t=T0 + (i % 5))
    clone = series_from_payload(s.to_payload())
    now = T0 + 5
    assert clone.count(10, now=now) == s.count(10, now=now)
    assert clone.total(10, now=now) == pytest.approx(s.total(10, now=now))
    assert clone.quantile(0.95, window_s=10, now=now) == pytest.approx(
        s.quantile(0.95, window_s=10, now=now), rel=0.05
    )


def test_merged_hosts_quantiles_within_bound_of_pooled():
    """THE acceptance pin: quantiles over the cross-host-merged series stay
    within the sketch's advertised rank-error bound of the same quantiles
    over the pooled raw observations."""
    rng = np.random.default_rng(0)
    cap = 64
    hosts = []
    pooled = []
    for h in range(3):  # three "hosts" with skewed distributions
        reg = TimeSeriesRegistry(bucket_seconds=1.0, n_buckets=20, sketch_capacity=cap)
        vals = rng.uniform(h * 40.0, h * 40.0 + 100.0, 700)
        for i, v in enumerate(vals):
            reg.observe("lat_ms", float(v), t=T0 + (i % 8))
        hosts.append(reg.payload())
        pooled.append(vals)
    pooled = np.concatenate(pooled)
    merged = registry_from_payload(merge_registry_payloads(hosts))
    s = merged.get("lat_ms")
    now = T0 + 8
    assert s.count(20, now=now) == len(pooled)
    assert s.total(20, now=now) == pytest.approx(float(pooled.sum()), rel=1e-5)
    bound = rank_error_bound(len(pooled), cap) / len(pooled)
    for q in (0.5, 0.95, 0.99):
        est = s.quantile(q, window_s=20, now=now)
        assert _rank_err(pooled, est, q) <= bound, (q, est)


def test_merge_registry_payloads_heterogeneous_series_sets():
    """A host missing a series (mixed-version fleet) contributes identity,
    never an error."""
    a = TimeSeriesRegistry(bucket_seconds=1.0, n_buckets=8)
    a.observe("only_a", 1.0, t=T0)
    a.observe("shared", 2.0, t=T0)
    b = TimeSeriesRegistry(bucket_seconds=1.0, n_buckets=8)
    b.observe("shared", 3.0, t=T0)
    merged = merge_registry_payloads([a.payload(), b.payload(), {}])
    reg = registry_from_payload(merged)
    assert reg.get("only_a").count(None, now=T0) == 1
    assert reg.get("shared").count(None, now=T0) == 2
    assert reg.get("shared").total(None, now=T0) == 5.0


def test_merge_stale_host_payload_does_not_evict_fresh_buckets():
    """A straggler host whose buckets fell out of the ring span must not
    wipe another host's live buckets sharing the same ring position."""
    fresh = TimeSeriesRegistry(bucket_seconds=1.0, n_buckets=10)
    fresh.observe("s", 5.0, t=T0 + 100)
    stale = TimeSeriesRegistry(bucket_seconds=1.0, n_buckets=10)
    stale.observe("s", 7.0, t=T0 + 90)  # same ring position, 10 buckets older
    for order in ([fresh, stale], [stale, fresh]):
        merged = registry_from_payload(
            merge_registry_payloads([r.payload() for r in order])
        )
        s = merged.get("s")
        assert s.count(5, now=T0 + 100) == 1
        assert s.total(5, now=T0 + 100) == 5.0


def test_registry_get_or_create_and_reset():
    reg = TimeSeriesRegistry(bucket_seconds=0.5, n_buckets=8)
    s1 = reg.series("a")
    assert reg.series("a") is s1  # get-or-create
    assert s1.bucket_seconds == 0.5  # geometry inherited
    reg.observe("a", 1.0, t=T0)
    reg.observe("b", 1.0, kind="counter", t=T0)
    assert reg.names() == ["a", "b"]
    reg.reset()
    assert reg.names() == ["a", "b"]  # registrations survive
    assert reg.get("a").count(None, now=T0) == 0  # data does not


# ---------------------------------------------------------------------------
# recorder feed wiring
# ---------------------------------------------------------------------------

def test_lifecycle_and_recompile_feeds(recorder):
    m = MeanMetric()
    m.update(jnp.ones((4,)))
    m.update(jnp.ones((6,)))  # second distinct signature
    float(m.compute())
    ts = recorder.timeseries
    assert ts.get(SERIES_UPDATE_MS).count(None) == 2
    assert ts.get("compute_ms").count(None) == 1
    # both signatures were new -> two compilation triggers
    assert ts.get(SERIES_RECOMPILES).total(None) == 2.0
    assert ts.get(SERIES_RECOMPILES).kind == "counter"


def test_disabled_recorder_feeds_nothing():
    rec = get_recorder()
    rec.reset()
    registry = rec.attach_timeseries(bucket_seconds=1.0, n_buckets=8)
    try:
        assert not rec.enabled
        m = MeanMetric()
        m.update(jnp.ones((4,)))
        float(m.compute())
        assert registry.names() == []  # hooks never ran: one-bool-check off path
    finally:
        rec.detach_timeseries()
        rec.reset()


def test_detach_stops_feeding(recorder):
    m = MeanMetric()
    m.update(jnp.ones((4,)))
    recorder.detach_timeseries()
    m.update(jnp.ones((4,)))  # recorded as events, not as series points
    assert recorder.timeseries is None
    assert len(recorder.events()) >= 2


def test_reset_clears_series_data_but_keeps_attachment(recorder):
    m = MeanMetric()
    m.update(jnp.ones((4,)))
    registry = recorder.timeseries
    assert registry.get(SERIES_UPDATE_MS).count(None) == 1
    recorder.reset()
    assert recorder.timeseries is registry
    assert registry.get(SERIES_UPDATE_MS).count(None) == 0


def test_fused_and_async_feeds(recorder):
    col = MetricCollection({"mse": MeanSquaredError(), "mean": MeanMetric()})
    handle = col.compile_update_async(queue_depth=2, policy="drop")
    x = jnp.ones((16,))
    try:
        for _ in range(5):
            col.update_async(x, x)
        handle.flush()
    finally:
        handle.close()
    ts = recorder.timeseries
    assert ts.get(SERIES_ASYNC_ENQUEUED).total(None) >= 1
    applied = recorder.async_totals()["applied"]
    assert ts.get(SERIES_ASYNC_APPLY_MS).count(None) == applied
    assert ts.get(SERIES_ASYNC_AGE_MS).count(None) == applied
    assert ts.get(SERIES_ASYNC_QUEUE_DEPTH).count(None) >= applied
    assert ts.get(SERIES_FUSED_DISPATCH_MS).count(None) == applied
    # ingest_rows: 16 rows per applied fused dispatch
    assert ts.get(SERIES_INGEST_ROWS).total(None) == 16.0 * applied
    dropped = recorder.async_totals()["dropped"]
    if dropped:
        assert ts.get(SERIES_ASYNC_DROPPED).total(None) == float(dropped)


def test_sliced_hot_share_feed(recorder):
    m = SlicedMetric(MeanSquaredError(), num_slices=8)
    ids = jnp.asarray([0, 0, 0, 1], jnp.int32)  # 75% of rows hit slice 0
    x = jnp.ones((4,), jnp.float32)
    m.update(ids, x, x)
    ts = recorder.timeseries
    assert ts.get(SERIES_SLICED_ROWS).total(None) == 4.0
    share = ts.get(SERIES_HOT_SLICE_SHARE)
    assert share.count(None) == 1
    assert share.value_max(None) == pytest.approx(0.75)
    # without a registry attached the skew bincount (a device readback) is
    # skipped entirely — counters-only telemetry must not pay for it
    recorder.detach_timeseries()
    m.update(ids, x, x)
    scatter_events = [e for e in recorder.events() if e["type"] == "sliced_scatter"]
    assert "hot_rows" in scatter_events[0] and "hot_rows" not in scatter_events[1]


def test_sliced_hot_slices_api():
    m = SlicedMetric(MeanSquaredError(), num_slices=8)
    ids = jnp.asarray([3, 3, 3, 1], jnp.int32)
    x = jnp.ones((4,), jnp.float32)
    m.update(ids, x, x)
    top_ids, shares = m.hot_slices(2)
    assert int(top_ids[0]) == 3
    assert float(shares[0]) == pytest.approx(0.75)


def test_sketch_fill_feed(recorder):
    auroc = AUROC(pos_label=1, sketch_capacity=64)
    rng = np.random.default_rng(0)
    preds = jnp.asarray(rng.random(48, dtype=np.float32))
    target = jnp.asarray((rng.random(48) > 0.5).astype(np.int32))
    auroc.update(preds, target)
    float(auroc.compute())  # fill recorded from the cold compute path
    s = recorder.timeseries.get(SERIES_SKETCH_FILL)
    assert s is not None and s.count(None) >= 1
    assert 0.0 < s.value_max(None) <= 1.0


# ---------------------------------------------------------------------------
# aggregate_across_hosts integration (+ heterogeneous-payload satellite)
# ---------------------------------------------------------------------------

def test_aggregate_payload_carries_timeseries(recorder):
    m = MeanMetric()
    m.update(jnp.ones((4,)))
    agg = aggregate_across_hosts(recorder)
    assert agg["world_size"] == 1
    assert SERIES_UPDATE_MS in agg["timeseries"]
    reg = registry_from_payload(agg["timeseries"])
    assert reg.get(SERIES_UPDATE_MS).count(None) == 1


def test_merge_payloads_sums_timeseries_across_hosts(recorder):
    m = MeanMetric()
    m.update(jnp.ones((4,)))
    local = counter_payload(recorder)
    merged = merge_payloads([local, local])  # two identical "hosts"
    reg = registry_from_payload(merged["timeseries"])
    assert reg.get(SERIES_UPDATE_MS).count(None) == 2


def test_merge_payloads_heterogeneous_families_are_identity():
    """ISSUE 11 satellite: a mixed-version fleet where a host is missing
    whole counter families must merge as zero/identity, not raise."""
    full = {
        "process": 1,
        "call_counts": {"A|update": 3},
        "call_times": {"A|update": 0.5},
        "signature_counts": {"A.update": 2},
        "sync_totals": {"sync_events": 1, "gather_bytes": 10, "pad_waste_bytes": 0},
        "footprint_hwm": {"A": 128},
        "compile_counts": {"A.update": 1},
        "compile_times": {"A.update": 0.2},
        "export_errors": 2,
        "dropped_events": 1,
    }
    bare = {"process": 0}  # an ancient build: no families at all
    merged = merge_payloads([bare, full])
    assert merged["call_counts"] == {("A", "update"): 3}
    assert merged["sync_totals"]["gather_bytes"] == 10
    assert merged["footprint_hwm"] == {"A": 128}
    assert merged["signature_counts"] == {"A.update": 2}
    assert merged["export_errors"] == 2
    assert merged["dropped_events"] == 1
    assert merged["async_totals"].get("enqueued", 0) == 0
    assert merged["timeseries"] == {}
    # and the renderers accept the heterogeneous per-process payloads
    from metrics_tpu.observability.exporters import render_prometheus

    page = render_prometheus(aggregate=merged)
    assert 'metrics_tpu_calls_total{metric="A",phase="update"} 3' in page
    # ISSUE 13 satellite: provenance (host/t/seq) merges as identity too —
    # the `full` payload above predates it entirely, and a provenance-less
    # rank renders without host/publisher labels rather than raising
    assert merged.get("fleet_totals", {}).get("absorbed", 0) == 0
    assert 'host="' not in page


def test_counter_payload_carries_snapshot_provenance():
    """ISSUE 13 satellite: every payload is stamped with hostname, wall
    clock, and a monotonic per-process sequence number (survives recorder
    resets) — what fleet collectors key liveness and dedup on."""
    import socket
    import time

    rec = get_recorder()
    rec.reset()
    rec.enable()
    try:
        before = time.time()
        p1 = counter_payload(rec)
        p2 = counter_payload(rec)
        assert p1["host"] == socket.gethostname()
        assert before <= p1["t"] <= time.time()
        assert p2["seq"] == p1["seq"] + 1  # monotonic
        rec.reset()
        p3 = counter_payload(rec)
        assert p3["seq"] > p2["seq"]  # reset does NOT rewind provenance
        # provenance-stamped payloads render with host (and publisher,
        # when a collector annotated one) labels on the per-rank families
        from metrics_tpu.observability.exporters import render_prometheus

        merged = merge_payloads([p1, {**p2, "publisher": "svc0"}])
        page = render_prometheus(aggregate=merged)
        assert f'host="{p1["host"]}"' in page
        assert 'publisher="svc0"' in page
    finally:
        rec.disable()
        rec.reset()


# ---------------------------------------------------------------------------
# window_sketch + empty-bucket skip (ISSUE 12 satellite)
# ---------------------------------------------------------------------------

def test_window_sketch_and_empty_window_returns_none():
    s = TelemetrySeries("lat", bucket_seconds=1.0, n_buckets=8, clock=lambda: 0.0)
    assert s.window_sketch(4.0, now=100.0) is None  # empty window: None, never NaN
    for i in range(10):
        s.record(float(i), t=100.0 + i * 0.1)
    sk = s.window_sketch(4.0, now=101.0)
    assert sk is not None
    from metrics_tpu.sketches.quantile import qsketch_total_weight

    assert float(qsketch_total_weight(sk)) == 10.0
    with pytest.raises(ValueError, match="counter"):
        TelemetrySeries("c", kind="counter").window_sketch(4.0)


def test_quantile_skips_zero_mass_buckets_instead_of_folding_nan():
    """A payload-merged bucket can carry counts with zero-weight sketch
    rows (a masked peer); the quantile query must skip the empty mass and
    answer None — never fold the empty-sketch NaN sentinel into a number."""
    s = TelemetrySeries("lat", bucket_seconds=1.0, n_buckets=8, clock=lambda: 0.0)
    s.load_payload(
        {
            "buckets": [
                {"i": 100, "c": 3, "s": 0.0, "mn": 0.0, "mx": 0.0, "sk": [[0.0, 1.0]]}
            ]
        }
    )
    assert s.quantile(0.5, window_s=4.0, now=100.5) is None
    assert s.window_sketch(4.0, now=100.5) is None
    # a real observation restores real answers
    s.record(2.5, t=100.2)
    assert s.quantile(0.5, window_s=4.0, now=100.5) == 2.5
