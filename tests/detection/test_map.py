"""MeanAveragePrecision parity tests.

Oracles:
1. The official pycocotools values hard-coded in the reference test suite
   (/root/reference/tests/detection/test_map.py:103-160), at the reference's
   own atol=1e-1.
2. The reference torchmetrics implementation itself, imported from
   /root/reference with minimal torch box-op shims standing in for the absent
   torchvision dependency — randomized fixtures at atol=1e-6.
"""
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.detection import MeanAveragePrecision
from metrics_tpu.functional.detection.box_ops import box_area, box_convert, box_iou

# ---------------------------------------------------------------------------
# the COCO subset fixture (reference tests/detection/test_map.py:26-100;
# data from pycocotools' instances_val2014_fakebbox100 results)
# ---------------------------------------------------------------------------
_PREDS = [
    [
        dict(boxes=[[258.15, 41.29, 606.41, 285.07]], scores=[0.236], labels=[4]),
        dict(
            boxes=[[61.00, 22.75, 565.00, 632.42], [12.66, 3.32, 281.26, 275.23]],
            scores=[0.318, 0.726],
            labels=[3, 2],
        ),
    ],
    [
        dict(
            boxes=[
                [87.87, 276.25, 384.29, 379.43],
                [0.00, 3.66, 142.15, 316.06],
                [296.55, 93.96, 314.97, 152.79],
                [328.94, 97.05, 342.49, 122.98],
                [356.62, 95.47, 372.33, 147.55],
                [464.08, 105.09, 495.74, 146.99],
                [276.11, 103.84, 291.44, 150.72],
            ],
            scores=[0.546, 0.3, 0.407, 0.611, 0.335, 0.805, 0.953],
            labels=[4, 1, 0, 0, 0, 0, 0],
        ),
        dict(boxes=[[0.00, 2.87, 601.00, 421.52]], scores=[0.699], labels=[5]),
    ],
]
_TARGET = [
    [
        dict(boxes=[[214.1500, 41.2900, 562.4100, 285.0700]], labels=[4]),
        dict(
            boxes=[[13.00, 22.75, 548.98, 632.42], [1.66, 3.32, 270.26, 275.23]],
            labels=[2, 2],
        ),
    ],
    [
        dict(
            boxes=[
                [61.87, 276.25, 358.29, 379.43],
                [2.75, 3.66, 162.15, 316.06],
                [295.55, 93.96, 313.97, 152.79],
                [326.94, 97.05, 340.49, 122.98],
                [356.62, 95.47, 372.33, 147.55],
                [462.08, 105.09, 493.74, 146.99],
                [277.11, 103.84, 292.44, 150.72],
            ],
            labels=[4, 1, 0, 0, 0, 0, 0],
        ),
        dict(boxes=[[13.99, 2.87, 640.00, 421.52]], labels=[5]),
    ],
]

_PYCOCO_EXPECTED = {
    "map": 0.706,
    "map_50": 0.901,
    "map_75": 0.846,
    "map_small": 0.689,
    "map_medium": 0.800,
    "map_large": 0.701,
    "mar_1": 0.592,
    "mar_10": 0.716,
    "mar_100": 0.716,
    "mar_small": 0.767,
    "mar_medium": 0.800,
    "mar_large": 0.700,
    "map_per_class": [0.725, 0.800, 0.454, -1.000, 0.650, 0.900],
    "mar_100_per_class": [0.780, 0.800, 0.450, -1.000, 0.650, 0.900],
}


def _as_jnp(sample: dict) -> dict:
    out = {k: jnp.asarray(np.asarray(v, np.float32)) for k, v in sample.items() if k != "labels"}
    out["labels"] = jnp.asarray(np.asarray(sample["labels"], np.int32))
    return out


def test_map_pycocotools_parity():
    """Full-dataset values vs official pycocotools numbers (reference atol=1e-1)."""
    metric = MeanAveragePrecision(class_metrics=True)
    for preds_batch, target_batch in zip(_PREDS, _TARGET):
        metric.update([_as_jnp(p) for p in preds_batch], [_as_jnp(t) for t in target_batch])
    result = metric.compute()
    for key, expected in _PYCOCO_EXPECTED.items():
        np.testing.assert_allclose(
            np.asarray(result[key]), np.asarray(expected, np.float32), atol=1e-1,
            err_msg=f"mismatch for {key}",
        )


# ---------------------------------------------------------------------------
# reference-implementation oracle (random fixtures, tight tolerance)
# ---------------------------------------------------------------------------
def _load_reference_map():
    """Import the reference MeanAveragePrecision, shimming torchvision ops."""
    torch = pytest.importorskip("torch")
    if "/root/reference" not in sys.path:
        sys.path.insert(0, "/root/reference")
    if "pkg_resources" not in sys.modules:
        # this env's setuptools no longer ships pkg_resources; the reference
        # only needs these two names for optional-dependency probing
        import types

        stub = types.ModuleType("pkg_resources")

        class DistributionNotFound(Exception):
            pass

        def get_distribution(name):
            raise DistributionNotFound(name)

        stub.DistributionNotFound = DistributionNotFound
        stub.get_distribution = get_distribution
        sys.modules["pkg_resources"] = stub
    try:
        import torchmetrics.detection.map as ref_map
    except Exception as err:  # pragma: no cover
        pytest.skip(f"reference torchmetrics unavailable: {err}")

    def t_area(boxes):
        return (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])

    def t_iou(b1, b2):
        area1, area2 = t_area(b1), t_area(b2)
        lt = torch.max(b1[:, None, :2], b2[None, :, :2])
        rb = torch.min(b1[:, None, 2:], b2[None, :, 2:])
        wh = (rb - lt).clamp(min=0)
        inter = wh[..., 0] * wh[..., 1]
        union = area1[:, None] + area2[None, :] - inter
        return torch.where(union > 0, inter / union, torch.zeros_like(inter))

    def t_convert(boxes, in_fmt, out_fmt):
        if in_fmt == out_fmt:
            return boxes
        a, b, c, d = boxes.unbind(-1)
        if in_fmt == "xywh":
            x1, y1, x2, y2 = a, b, a + c, b + d
        elif in_fmt == "cxcywh":
            x1, y1, x2, y2 = a - c / 2, b - d / 2, a + c / 2, b + d / 2
        else:
            x1, y1, x2, y2 = a, b, c, d
        if out_fmt == "xyxy":
            vals = (x1, y1, x2, y2)
        elif out_fmt == "xywh":
            vals = (x1, y1, x2 - x1, y2 - y1)
        else:
            vals = ((x1 + x2) / 2, (y1 + y2) / 2, x2 - x1, y2 - y1)
        return torch.stack(vals, dim=-1)

    ref_map.box_area = t_area
    ref_map.box_iou = t_iou
    ref_map.box_convert = t_convert
    ref_map._TORCHVISION_GREATER_EQUAL_0_8 = True
    return ref_map.MeanAveragePrecision


def _random_sample(rng, n_classes=6, max_boxes=8, with_scores=True):
    n = int(rng.integers(1, max_boxes + 1))
    x1 = rng.uniform(0, 300, n)
    y1 = rng.uniform(0, 300, n)
    w = rng.uniform(5, 200, n)
    h = rng.uniform(5, 200, n)
    boxes = np.stack([x1, y1, x1 + w, y1 + h], axis=1).astype(np.float32)
    sample = dict(boxes=boxes, labels=rng.integers(0, n_classes, n).astype(np.int32))
    if with_scores:
        sample["scores"] = rng.uniform(0.05, 1.0, n).astype(np.float32)
    return sample


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("class_metrics", [False, True])
def test_map_reference_parity_random(seed, class_metrics):
    """Randomized inputs vs the actual reference implementation (atol=1e-6)."""
    import torch

    RefMAP = _load_reference_map()
    rng = np.random.default_rng(seed)
    n_imgs = 8
    preds = [_random_sample(rng) for _ in range(n_imgs)]
    target = [_random_sample(rng, with_scores=False) for _ in range(n_imgs)]

    ours = MeanAveragePrecision(class_metrics=class_metrics)
    ours.update([_as_jnp(p) for p in preds], [_as_jnp(t) for t in target])
    got = ours.compute()

    ref = RefMAP(class_metrics=class_metrics)
    ref.update(
        [{k: torch.as_tensor(v) for k, v in p.items()} for p in preds],
        [{k: torch.as_tensor(v) for k, v in t.items()} for t in target],
    )
    want = ref.compute()

    for key, val in want.items():
        np.testing.assert_allclose(
            np.asarray(got[key], np.float64).reshape(-1),
            np.asarray(val.numpy(), np.float64).reshape(-1),
            atol=1e-6,
            err_msg=f"mismatch for {key} (seed={seed})",
        )


@pytest.mark.parametrize("max_dets", [[1, 10], [5, 50, 500]])
def test_map_custom_max_detections_vs_reference(max_dets):
    import torch

    RefMAP = _load_reference_map()
    rng = np.random.default_rng(7)
    preds = [_random_sample(rng, max_boxes=20) for _ in range(4)]
    target = [_random_sample(rng, max_boxes=20, with_scores=False) for _ in range(4)]

    ours = MeanAveragePrecision(max_detection_thresholds=max_dets)
    ours.update([_as_jnp(p) for p in preds], [_as_jnp(t) for t in target])
    got = ours.compute()

    ref = RefMAP(max_detection_thresholds=max_dets)
    ref.update(
        [{k: torch.as_tensor(v) for k, v in p.items()} for p in preds],
        [{k: torch.as_tensor(v) for k, v in t.items()} for t in target],
    )
    want = ref.compute()
    for key, val in want.items():
        np.testing.assert_allclose(
            np.asarray(got[key], np.float64).reshape(-1),
            np.asarray(val.numpy(), np.float64).reshape(-1),
            atol=1e-6,
            err_msg=f"mismatch for {key}",
        )


# ---------------------------------------------------------------------------
# lifecycle and edge cases (reference tests/detection/test_map.py:194-343)
# ---------------------------------------------------------------------------
def test_accumulation_matches_single_update():
    """Two updates accumulate identically to one combined update."""
    flat_preds = [_as_jnp(p) for batch in _PREDS for p in batch]
    flat_target = [_as_jnp(t) for batch in _TARGET for t in batch]

    m1 = MeanAveragePrecision()
    m1.update(flat_preds, flat_target)
    m2 = MeanAveragePrecision()
    for preds_batch, target_batch in zip(_PREDS, _TARGET):
        m2.update([_as_jnp(p) for p in preds_batch], [_as_jnp(t) for t in target_batch])
    r1, r2 = m1.compute(), m2.compute()
    for key in r1:
        np.testing.assert_allclose(np.asarray(r1[key]), np.asarray(r2[key]))


def test_error_on_wrong_init():
    MeanAveragePrecision()  # no error
    with pytest.raises(ValueError, match="Expected argument `class_metrics` to be a boolean"):
        MeanAveragePrecision(class_metrics=0)
    with pytest.raises(ValueError, match="Expected argument `box_format`"):
        MeanAveragePrecision(box_format="xxyy")


def test_empty_preds():
    metric = MeanAveragePrecision()
    metric.update(
        [dict(boxes=jnp.zeros((0, 4)), scores=jnp.zeros((0,)), labels=jnp.zeros((0,), jnp.int32))],
        [dict(boxes=jnp.asarray([[214.15, 41.29, 562.41, 285.07]]), labels=jnp.asarray([4]))],
    )
    metric.compute()


def test_empty_ground_truths():
    metric = MeanAveragePrecision()
    metric.update(
        [
            dict(
                boxes=jnp.asarray([[214.15, 41.29, 562.41, 285.07]]),
                scores=jnp.asarray([0.5]),
                labels=jnp.asarray([4]),
            )
        ],
        [dict(boxes=jnp.zeros((0, 4)), labels=jnp.zeros((0,), jnp.int32))],
    )
    metric.compute()


def test_empty_metric():
    metric = MeanAveragePrecision()
    result = metric.compute()
    assert float(result["map"]) == -1.0


def test_reset_clears_state():
    # streaming default: the table empties and the index cursor rewinds
    metric = MeanAveragePrecision()
    metric.update([_as_jnp(p) for p in _PREDS[0]], [_as_jnp(t) for t in _TARGET[0]])
    metric.reset()
    assert int(metric.images_seen) == 0
    assert not bool(jnp.any(metric.table[:, 0] > -jnp.inf))
    assert float(metric.compute()["map"]) == -1.0

    # exact mode: the reference's list states empty
    metric = MeanAveragePrecision(exact=True)
    metric.update([_as_jnp(p) for p in _PREDS[0]], [_as_jnp(t) for t in _TARGET[0]])
    metric.reset()
    assert metric.detection_boxes == []
    assert float(metric.compute()["map"]) == -1.0


def test_error_on_wrong_input():
    metric = MeanAveragePrecision()
    metric.update([], [])  # no error

    with pytest.raises(ValueError, match="Expected argument `preds` to be of type Sequence"):
        metric.update(jnp.zeros(()), [])
    with pytest.raises(ValueError, match="Expected argument `target` to be of type Sequence"):
        metric.update([], jnp.zeros(()))
    with pytest.raises(ValueError, match="Expected argument `preds` and `target` to have the same length"):
        metric.update([dict()], [dict(), dict()])
    with pytest.raises(ValueError, match="Expected all dicts in `preds` to contain the `boxes` key"):
        metric.update(
            [dict(scores=jnp.zeros((0,)), labels=jnp.zeros((0,)))],
            [dict(boxes=jnp.zeros((0, 4)), labels=jnp.zeros((0,)))],
        )
    with pytest.raises(ValueError, match="Expected all dicts in `preds` to contain the `scores` key"):
        metric.update(
            [dict(boxes=jnp.zeros((0, 4)), labels=jnp.zeros((0,)))],
            [dict(boxes=jnp.zeros((0, 4)), labels=jnp.zeros((0,)))],
        )
    with pytest.raises(ValueError, match="Expected all dicts in `target` to contain the `labels` key"):
        metric.update(
            [dict(boxes=jnp.zeros((0, 4)), scores=jnp.zeros((0,)), labels=jnp.zeros((0,)))],
            [dict(boxes=jnp.zeros((0, 4)))],
        )
    with pytest.raises(ValueError, match="Expected all boxes in `preds` to be of type Tensor"):
        metric.update(
            [dict(boxes=[], scores=jnp.zeros((0,)), labels=jnp.zeros((0,)))],
            [dict(boxes=jnp.zeros((0, 4)), labels=jnp.zeros((0,)))],
        )


# ---------------------------------------------------------------------------
# box ops vs shim formulas
# ---------------------------------------------------------------------------
def test_box_ops():
    rng = np.random.default_rng(0)
    b1 = _random_sample(rng)["boxes"]
    b2 = _random_sample(rng)["boxes"]
    np.testing.assert_allclose(
        np.asarray(box_area(b1)), (b1[:, 2] - b1[:, 0]) * (b1[:, 3] - b1[:, 1]), rtol=1e-6
    )
    iou = np.asarray(box_iou(b1, b2))
    assert iou.shape == (len(b1), len(b2))
    assert (iou >= 0).all() and (iou <= 1).all()
    # identity boxes have IoU 1 on the diagonal
    np.testing.assert_allclose(np.diag(np.asarray(box_iou(b1, b1))), 1.0, rtol=1e-6)

    xywh = np.stack(
        [b1[:, 0], b1[:, 1], b1[:, 2] - b1[:, 0], b1[:, 3] - b1[:, 1]], axis=1
    )
    np.testing.assert_allclose(np.asarray(box_convert(xywh, "xywh", "xyxy")), b1, rtol=1e-5)
    cxcywh = np.asarray(box_convert(b1, "xyxy", "cxcywh"))
    np.testing.assert_allclose(np.asarray(box_convert(cxcywh, "cxcywh", "xyxy")), b1, rtol=1e-5, atol=1e-3)


# ---------------------------------------------------------------------------
# distributed sync over the five list states (exact mode; VERDICT r2 weak #6)
# ---------------------------------------------------------------------------


def _elementwise_gather_from(other: MeanAveragePrecision):
    """Build a simulated 2-rank gather fn: each state element of the calling
    metric is paired with the corresponding element of ``other``'s state, in
    the deterministic order ``_sync_dist`` visits them (state-registry order,
    list elements in sequence). This mirrors real DDP semantics, where each
    rank issues the same sequence of all_gathers (reference metric.py:302-327
    gathers per-element; ranks must update with the same number of images)."""
    order = ["detection_boxes", "detection_scores", "detection_labels",
             "groundtruth_boxes", "groundtruth_labels"]
    seq = []
    for attr in order:
        seq.extend(getattr(other, attr))
    it = iter(seq)

    def gather(x, group=None):
        return [x, next(it)]

    return gather


def test_map_ddp_two_rank_union():
    """Two virtual ranks with different images: synced compute == union compute."""
    rng = np.random.default_rng(7)
    n_per_rank = 4
    preds_r0 = [_random_sample(rng) for _ in range(n_per_rank)]
    target_r0 = [_random_sample(rng, with_scores=False) for _ in range(n_per_rank)]
    preds_r1 = [_random_sample(rng) for _ in range(n_per_rank)]
    target_r1 = [_random_sample(rng, with_scores=False) for _ in range(n_per_rank)]

    rank1 = MeanAveragePrecision(exact=True)
    rank1.update(preds_r1, target_r1)

    rank0 = MeanAveragePrecision(exact=True, dist_sync_fn=_elementwise_gather_from(rank1))
    rank0.update(preds_r0, target_r0)

    union = MeanAveragePrecision(exact=True)
    union.update(preds_r0 + preds_r1, target_r0 + target_r1)

    synced = rank0.compute()
    expected = union.compute()
    for key in expected:
        np.testing.assert_allclose(
            np.asarray(synced[key]), np.asarray(expected[key]), atol=1e-6, err_msg=key
        )

    # local (pre-sync) state must be restored after compute's sync context
    assert len(rank0.detection_boxes) == n_per_rank
    r0_local = MeanAveragePrecision(exact=True)
    r0_local.update(preds_r0, target_r0)
    local_after = rank0._compute()
    local_expected = r0_local.compute()
    for key in local_expected:
        np.testing.assert_allclose(
            np.asarray(local_after[key]), np.asarray(local_expected[key]), atol=1e-6, err_msg=key
        )


def test_map_sync_unsync_state_machine():
    """Manual sync()/unsync() over the list states: gathered count doubles,
    unsync restores the local view (reference test_ddp.py pattern)."""
    rng = np.random.default_rng(11)
    preds = [_random_sample(rng) for _ in range(3)]
    target = [_random_sample(rng, with_scores=False) for _ in range(3)]

    other = MeanAveragePrecision(exact=True)
    other.update(preds, target)

    m = MeanAveragePrecision(exact=True)
    m.update(preds, target)
    m.sync(dist_sync_fn=_elementwise_gather_from(other), distributed_available=lambda: True)
    assert len(m.detection_boxes) == 6  # 3 local + 3 gathered
    m.unsync()
    assert len(m.detection_boxes) == 3


def test_vectorized_pack_equals_loop_pack():
    """The global-lexsort packing must reproduce the per-image loop packing
    EXACTLY (unit order and within-unit tie order feed the PR reduction's
    mergesort tie-breaking)."""
    from metrics_tpu.functional.detection.mean_ap import _pack_units, _pack_units_loop

    rng = np.random.default_rng(0)
    for trial in range(10):
        n_imgs = int(rng.integers(1, 25))
        det_b, det_s, det_l, gt_b, gt_l = [], [], [], [], []
        for _ in range(n_imgs):
            nd = int(rng.integers(0, 12))
            ng = int(rng.integers(0, 8))
            det_b.append(rng.uniform(0, 100, (nd, 4)).astype(np.float32))
            det_s.append(np.round(rng.uniform(0, 1, nd), 1))  # score ties
            det_l.append(rng.integers(0, 5, nd).astype(np.int32))
            gt_b.append(rng.uniform(0, 100, (ng, 4)).astype(np.float32))
            gt_l.append(rng.integers(0, 5, ng).astype(np.int32))
        labels = np.concatenate(det_l + gt_l)
        classes = sorted(int(c) for c in np.unique(labels)) if labels.size else []
        max_det = int(rng.choice([1, 3, 100]))
        fast = _pack_units(det_b, det_s, det_l, gt_b, gt_l, classes, max_det)
        slow = _pack_units_loop(det_b, det_s, det_l, gt_b, gt_l, classes, max_det)
        assert (fast is None) == (slow is None)
        if fast is None:
            continue
        for name in fast._fields:
            np.testing.assert_array_equal(
                getattr(fast, name), getattr(slow, name), err_msg=f"trial {trial}: {name}"
            )


@pytest.mark.parametrize(
    "iou_thresholds, rec_thresholds",
    [
        # NOTE: grids must keep 0.5 and 0.75 — the reference's summarize
        # unconditionally looks them up and raises ValueError otherwise
        # (map.py:507); ours returns -1 for absent thresholds instead
        # (documented divergence, detection/mean_ap.py).
        ([0.3, 0.5, 0.75], None),
        (None, [0.0, 0.2, 0.6, 1.0]),
        ([0.5, 0.75], [0.0, 0.5, 1.0]),
    ],
)
def test_map_custom_thresholds_vs_reference(iou_thresholds, rec_thresholds):
    """Custom IoU/recall threshold grids must track the reference exactly
    (reference map.py:250-253 defaults overridden)."""
    import torch

    RefMAP = _load_reference_map()
    rng = np.random.default_rng(21)
    preds = [_random_sample(rng) for _ in range(6)]
    target = [_random_sample(rng, with_scores=False) for _ in range(6)]

    kwargs = {"iou_thresholds": iou_thresholds, "rec_thresholds": rec_thresholds}

    ours = MeanAveragePrecision(**kwargs)
    ours.update(preds, target)
    got = ours.compute()

    ref = RefMAP(**kwargs)
    ref.update(
        [{k: torch.as_tensor(np.asarray(v)) for k, v in p.items()} for p in preds],
        [{k: torch.as_tensor(np.asarray(v)) for k, v in t.items()} for t in target],
    )
    want = ref.compute()
    for key in want:
        np.testing.assert_allclose(
            np.asarray(got[key], np.float64).reshape(-1),
            np.asarray(want[key].numpy(), np.float64).reshape(-1),
            atol=1e-6,
            err_msg=key,
        )


def test_map_absent_summary_thresholds_return_minus_one():
    """The documented divergence from the reference: with custom grids
    lacking 0.5/0.75 the reference CRASHES (map.py:507 list lookup); ours
    returns -1 for the unavailable summary entries (detection/mean_ap.py)."""
    rng = np.random.default_rng(3)
    preds = [_random_sample(rng) for _ in range(3)]
    target = [_random_sample(rng, with_scores=False) for _ in range(3)]
    m = MeanAveragePrecision(iou_thresholds=[0.3, 0.6])
    m.update(preds, target)
    out = m.compute()
    assert float(out["map_50"]) == -1.0
    assert float(out["map_75"]) == -1.0
    assert float(out["map"]) >= -1.0  # overall map still computed (mdet=100 present)
