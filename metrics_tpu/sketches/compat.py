"""Exact-mode compatibility shims for sketch-converted metrics.

Converted metrics keep yesterday's unbounded cat-state behavior behind
``exact=True``. The registration lives HERE, as a module-level function,
on purpose: the tracelint abstract interpreter classifies a metric class
from the ``self.add_state(...)`` calls in its class-body AST, and the
exact mode's list states belong to an opt-in configuration the class-level
verdict must not describe (the class contract — declared via
``__exact_mode_attr__`` — is that the DEFAULT mode is the fixed-shape
sketch one). Exact instances are still fully guarded at runtime: they
carry live list states and flip instance-level ``__jit_unsafe__`` to
True, which ``FusedUpdate._static_unfusible`` checks BEFORE consulting
the manifest — a stale-looking ``fusible`` class verdict can never put an
exact instance on the fused path.
"""
from typing import Sequence

from metrics_tpu.utils.prints import rank_zero_warn


def register_exact_list_states(
    metric, names: Sequence[str], dist_reduce_fx: str = "cat"
) -> None:
    """Register the opt-in exact mode's unbounded list states and mark the
    instance jit-unsafe (list growth cannot trace; the instance flag keeps
    exact metrics on the eager path whatever the class-level verdict says)."""
    for name in names:
        metric.add_state(name, default=[], dist_reduce_fx=dist_reduce_fx)
    metric.__dict__["__jit_unsafe__"] = True


def warn_exact_buffer(cls_name: str, what: str = "targets and predictions") -> None:
    """The reference's large-memory-footprint warning — fired only for
    ``exact=True`` instances (the sketch default is O(capacity))."""
    rank_zero_warn(
        f"Metric `{cls_name}` with `exact=True` will save all {what} in buffer."
        " For large datasets this may lead to large memory footprint."
    )
