"""tracelint rule registry and the built-in rule set.

Every rule encodes a real invariant of this codebase (module docstrings of
``core/metric.py``, ``core/fused.py``, ``parallel/distributed.py`` are the
source of truth); the catalog with rationale and fix recipes lives in
``docs/static_analysis.md``. Rules are registered via :func:`register_rule`
so downstream projects (or later PRs) can add their own without touching
the engine.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set

from .engine import FileContext, Violation
from .interp import _always_raises, _is_not_concrete_test

RULE_REGISTRY: Dict[str, "Rule"] = {}


class Rule:
    """Base class for tracelint rules. Subclasses set ``id``/``description``
    and implement ``check(ctx) -> Iterator[Violation]``."""

    id: str = ""
    description: str = ""

    def check(self, ctx: FileContext) -> Iterator[Violation]:  # pragma: no cover - interface
        raise NotImplementedError

    def violation(self, ctx: FileContext, node: ast.AST, message: str) -> Violation:
        return ctx.violation(self.id, node, message)


def register_rule(cls):
    """Class decorator: instantiate and add to the registry (id-keyed)."""
    instance = cls()
    if not instance.id:
        raise ValueError(f"rule {cls.__name__} must set an id")
    RULE_REGISTRY[instance.id] = instance
    return cls


def all_rules() -> List[Rule]:
    return [RULE_REGISTRY[k] for k in sorted(RULE_REGISTRY)]


def get_rules(ids: Optional[Iterable[str]] = None) -> List[Rule]:
    if ids is None:
        return all_rules()
    out = []
    for rule_id in ids:
        key = rule_id.strip().upper()
        if key not in RULE_REGISTRY:
            raise KeyError(f"unknown tracelint rule {rule_id!r}; known: {sorted(RULE_REGISTRY)}")
        out.append(RULE_REGISTRY[key])
    return out


# ---------------------------------------------------------------------------
# shared AST helpers
# ---------------------------------------------------------------------------

def _last_name(node: ast.AST) -> Optional[str]:
    """Rightmost identifier of a Name / dotted Attribute chain."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _attr_chain(node: ast.AST) -> List[str]:
    """``jax.lax.psum`` -> ["jax", "lax", "psum"]; empty if not a pure chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return []


#: string reducers ``add_state`` accepts (core/metric.py:244-272)
KNOWN_REDUCERS = {"sum", "mean", "max", "min", "cat", "merge", "ring", "decay"}

#: methods whose bodies are trace-scoped (the jit/fusion surface)
TRACED_METHODS = {"_update", "_compute", "update", "compute", "update_state", "compute_state"}

#: method-name patterns allowed to assign registered state
_STATE_WRITE_TOKENS = (
    "update", "reset", "sync", "bind", "restore", "merge", "load", "init", "insert",
)
_STATE_WRITE_METHODS = {"__init__", "set_dtype", "to_device", "shard_states", "state_dict"}

#: the epoch-keyed result-cache fields (core/metric.py): the write-epoch
#: clock and the cached compute value/epoch stamp. Outside the lifecycle,
#: mutating them directly bypasses ``_mark_state_written()`` — the hook
#: subclasses override to degrade their incremental read caches (dirty
#: slices, window fold memos) — so a bare ``self._write_epoch += 1``
#: silently leaves a partial-fold cache claiming to be current.
_CACHE_PLANE_FIELDS = {"_computed", "_computed_epoch", "_write_epoch"}

#: method-name patterns additionally allowed to touch the cache-plane
#: fields: the compute cycle itself stamps them, and the ``_mark_*`` hooks
#: ARE the sanctioned out-of-band write path
_CACHE_PLANE_TOKENS = _STATE_WRITE_TOKENS + ("compute", "mark")

#: host-side incremental-read bookkeeping: epoch/dirty-set counters, fold
#: memos, per-slice value caches, last-read stats. These are NOT registered
#: state — they never enter ``_defaults``, sync, or merge; they live on the
#: host and the read plane rebuilds them from real state on any degrade —
#: so writing them from ANY method (including traced ones, where they are
#: Python-level trace-time no-ops) is legal. TL-STATE must never flag them;
#: the carve-out is pinned by tests/analysis fixtures.
HOST_COUNTER_ATTRS = {
    "_dirty",
    "_svc",
    "_fold_memo",
    "_wstate_memo",
    "_borrowed_epoch",
    "_last_fold_fanin",
    "_last_fold_buckets",
    "_last_fold_oldest_wall",
    "_last_read_cache_hit",
    "_last_layout_cache_hit",
    "_last_table_rows",
    "_readers",
}

#: attributes that are static under tracing — touching them is NOT a host
#: round-trip (shape/dtype-derived control flow compiles away)
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}

#: jnp/np module members that are host-static METADATA predicates, not
#: array producers: branching on `jnp.issubdtype(x.dtype, ...)` or comparing
#: `jnp.result_type(...)`s compiles away exactly like a `.dtype` read
_STATIC_MODULE_CALLS = {"issubdtype", "result_type"}

#: builtins whose results are host/static values, not traced reads
_STATIC_CALLS = {"isinstance", "len", "getattr", "hasattr", "type", "range", "enumerate", "zip"}


class ClassInfo:
    """Per-class facts the stateful rules share."""

    def __init__(self, node: ast.ClassDef) -> None:
        self.node = node
        self.name = node.name
        self.base_names = [n for n in (_last_name(b) for b in node.bases) if n]
        self.state_names: Set[str] = set()
        self.list_state_names: Set[str] = set()
        self.has_list_state = False
        self.add_state_calls: List[ast.Call] = []
        self.jit_unsafe_declared = False
        self.jit_unsafe_truthy = False
        self._scan()

    def _scan(self) -> None:
        for stmt in self.node.body:
            target = None
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                target = _last_name(stmt.targets[0]) if isinstance(stmt.targets[0], ast.Name) else None
            elif isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
                target = stmt.target.id
            if target == "__jit_unsafe__":
                self._record_decl(getattr(stmt, "value", None))
        for node in ast.walk(self.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                # self.__jit_unsafe__ = ... (instance-level declaration)
                if (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                    and tgt.attr == "__jit_unsafe__"
                ):
                    self._record_decl(node.value)
                # self.__dict__["__jit_unsafe__"] = ... (shadows the class attr)
                if (
                    isinstance(tgt, ast.Subscript)
                    and isinstance(tgt.value, ast.Attribute)
                    and isinstance(tgt.value.value, ast.Name)
                    and tgt.value.value.id == "self"
                    and tgt.value.attr == "__dict__"
                    and isinstance(tgt.slice, ast.Constant)
                    and tgt.slice.value == "__jit_unsafe__"
                ):
                    self._record_decl(node.value)
            if isinstance(node, ast.Call):
                func = node.func
                if (
                    isinstance(func, ast.Attribute)
                    and func.attr == "add_state"
                    and isinstance(func.value, ast.Name)
                    and func.value.id == "self"
                ):
                    self.add_state_calls.append(node)
                    if node.args and isinstance(node.args[0], ast.Constant) and isinstance(node.args[0].value, str):
                        self.state_names.add(node.args[0].value)
                    default = None
                    if len(node.args) >= 2:
                        default = node.args[1]
                    for kw in node.keywords:
                        if kw.arg == "default":
                            default = kw.value
                    if isinstance(default, ast.List):
                        self.has_list_state = True
                        if node.args and isinstance(node.args[0], ast.Constant):
                            self.list_state_names.add(node.args[0].value)

    def _record_decl(self, value: Optional[ast.AST]) -> None:
        self.jit_unsafe_declared = True
        if isinstance(value, ast.Constant):
            self.jit_unsafe_truthy = self.jit_unsafe_truthy or bool(value.value)
        else:
            # a computed declaration: treat as possibly-unsafe (exempts
            # TL-TRACE conservatively; still counts as declared for TL-STATE)
            self.jit_unsafe_truthy = True

    def methods(self) -> Iterator[ast.FunctionDef]:
        for stmt in self.node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield stmt


def collect_classes(ctx: FileContext) -> Dict[str, ClassInfo]:
    return {
        node.name: ClassInfo(node)
        for node in ctx.tree.body
        if isinstance(node, ast.ClassDef)
    }


def _is_metric_like(info: ClassInfo, classes: Dict[str, ClassInfo], _seen: Optional[Set[str]] = None) -> bool:
    """Metric subclass by name heuristic + in-module transitive bases; any
    class registering state via ``add_state`` counts regardless of name."""
    if info.add_state_calls:
        return True
    _seen = _seen or set()
    for base in info.base_names:
        if base == "Metric" or base.endswith("Metric"):
            return True
        if base in classes and base not in _seen:
            _seen.add(base)
            if _is_metric_like(classes[base], classes, _seen):
                return True
    return False


def _resolved(info: ClassInfo, classes: Dict[str, ClassInfo], attr: str) -> bool:
    """OR-fold a boolean ClassInfo attribute over in-module ancestors."""
    seen: Set[str] = set()

    def walk(ci: ClassInfo) -> bool:
        if getattr(ci, attr):
            return True
        for base in ci.base_names:
            if base in classes and base not in seen:
                seen.add(base)
                if walk(classes[base]):
                    return True
        return False

    return walk(info)


def _resolved_states(info: ClassInfo, classes: Dict[str, ClassInfo], attr: str = "state_names") -> Set[str]:
    names: Set[str] = set()
    seen: Set[str] = set()

    def walk(ci: ClassInfo) -> None:
        names.update(getattr(ci, attr))
        for base in ci.base_names:
            if base in classes and base not in seen:
                seen.add(base)
                walk(classes[base])

    walk(info)
    return names


def _mentions_concrete_guard(node: ast.AST) -> bool:
    """True when an expression calls the ``_is_concrete`` eager-only guard
    (utils/checks.py) — the codebase's sanctioned pattern for host-side
    checks that tracing skips."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and _last_name(sub.func) == "_is_concrete":
            return True
    return False


class _TracedNames:
    """Conservative taint set: function parameters, locals assigned from
    definitely-traced expressions, and ``self.<registered-state>`` reads.

    Deliberately strict — a call to an unknown (host) helper BREAKS taint,
    so host metadata derived from arrays (input-format modes, shape cases)
    never flags. The cost is missing round-trips laundered through helper
    returns; the fused path's runtime ``eval_shape`` probe still owns those.
    """

    def __init__(self, params: Set[str], states: Set[str], list_states: Set[str], ctx: FileContext) -> None:
        self.names = set(params)
        self.states = states - list_states  # list states are host containers
        self.ctx = ctx

    def mentions(self, node: ast.AST) -> bool:
        """Does ``node`` read a definitely-traced value OTHER than via static
        attrs (``.shape``/``.ndim``/``.dtype``/``.size``), static builtins,
        or identity (``is``/``is not``) comparisons?"""
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            if isinstance(node.value, ast.Name) and node.value.id == "self":
                return node.attr in self.states
            return self.mentions(node.value)
        if isinstance(node, ast.Name):
            return node.id in self.names
        if isinstance(node, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn)) for op in node.ops
        ):
            # identity and container-membership (dict-key) checks are host
            # structure reads, never value concretizations
            return False
        if isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Name) and func.id in _STATIC_CALLS:
                return False
            # a jnp.* call produces a traced array by construction — whether
            # spelled via the module alias or a direct member import
            # (`from jax.numpy import concatenate`) — EXCEPT the dtype/shape
            # metadata predicates, which are host-static by definition
            if (
                isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id in self.ctx.jnp_aliases
            ):
                return func.attr not in _STATIC_MODULE_CALLS
            if isinstance(func, ast.Name) and func.id in self.ctx.jnp_member_imports:
                # the member-import spelling must exempt the same static
                # predicates as the alias spelling, keyed on the ORIGINAL
                # member name (`from jax.numpy import issubdtype as isd`)
                return self.ctx.jnp_member_imports[func.id] not in _STATIC_MODULE_CALLS
            # a method on a traced object (x.astype, x.at[...].set) is traced;
            # any OTHER call (host helper) breaks taint on purpose
            if isinstance(func, ast.Attribute) and self.mentions(func.value):
                return True
            return False
        return any(self.mentions(child) for child in ast.iter_child_nodes(node))

    def absorb_assign(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.Assign) and self.mentions(stmt.value):
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name):
                    self.names.add(tgt.id)
                elif isinstance(tgt, ast.Tuple):
                    for el in tgt.elts:
                        if isinstance(el, ast.Name):
                            self.names.add(el.id)
        elif isinstance(stmt, ast.AugAssign) and isinstance(stmt.target, ast.Name):
            if self.mentions(stmt.value):
                self.names.add(stmt.target.id)


# ---------------------------------------------------------------------------
# TL-TRACE
# ---------------------------------------------------------------------------

@register_rule
class TraceRule(Rule):
    """Host round-trips / concrete control flow on traced values inside the
    jit-traced surface (``update``/``compute`` of non-``__jit_unsafe__``
    metrics, and functional kernels).

    A ``float()``/``.item()``/``np.asarray`` on a traced value forces a
    device->host sync per batch and fails the ``FusedUpdate`` eval_shape
    fusibility probe, silently demoting the whole collection to the eager
    path; Python ``if``/``while`` on traced data raises
    ``ConcretizationTypeError`` under jit. Host checks that tracing must
    skip belong under an ``if _is_concrete(...)`` guard (utils/checks.py) —
    guarded blocks are exempt.
    """

    id = "TL-TRACE"
    description = (
        "host round-trip or concrete control flow on a traced value inside update/compute"
    )

    _HOST_SYNC_METHODS = {"item", "block_until_ready"}
    _CAST_BUILTINS = {"float", "int", "bool"}

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        classes = collect_classes(ctx)
        for info in classes.values():
            if not _is_metric_like(info, classes):
                continue
            if _resolved(info, classes, "jit_unsafe_truthy"):
                continue  # declared host-side: the eager path is its contract
            states = _resolved_states(info, classes)
            list_states = _resolved_states(info, classes, "list_state_names")
            for method in info.methods():
                if method.name in TRACED_METHODS:
                    yield from self._scan_function(ctx, method, states, list_states)
        # functional kernels: the pure (state, batch) -> state surface. Only
        # the unambiguous syncs are flagged here — host-side reference
        # kernels (text tokenizers, audio DSP engines) legitimately use
        # float()/np on Python data.
        if ctx.relpath.startswith("functional/"):
            for node in ctx.tree.body:
                if isinstance(node, ast.FunctionDef):
                    yield from self._scan_hard_syncs(ctx, node)

    # -- metric-method scan ------------------------------------------------
    def _scan_function(
        self, ctx: FileContext, fn: ast.FunctionDef, states: Set[str], list_states: Set[str]
    ) -> Iterator[Violation]:
        params = {a.arg for a in list(fn.args.args) + list(fn.args.kwonlyargs) if a.arg != "self"}
        if fn.args.vararg:
            params.add(fn.args.vararg.arg)
        if fn.args.kwarg:
            params.add(fn.args.kwarg.arg)
        traced = _TracedNames(params, states, list_states, ctx)
        yield from self._scan_stmts(ctx, fn.body, traced)

    def _scan_stmts(self, ctx: FileContext, stmts: Sequence[ast.stmt], traced: _TracedNames) -> Iterator[Violation]:
        for stmt in stmts:
            if isinstance(stmt, ast.If):
                if _mentions_concrete_guard(stmt.test):
                    # eager-only branch: host syncs here are the sanctioned
                    # pattern; the else branch is the traced path
                    yield from self._scan_stmts(ctx, stmt.orelse, traced)
                    if _is_not_concrete_test(stmt.test) and _always_raises(stmt.body):
                        # `if not _is_concrete(...): raise` — everything after
                        # this statement is eager-only by construction (the
                        # sketch-compute host-readback idiom)
                        return
                    continue
                # isinstance-bearing tests are host type-dispatch (the
                # list-vs-array state idiom), not value reads
                is_type_dispatch = any(
                    isinstance(sub, ast.Call) and _last_name(sub.func) == "isinstance"
                    for sub in ast.walk(stmt.test)
                )
                if not is_type_dispatch and traced.mentions(stmt.test):
                    yield self.violation(
                        ctx,
                        stmt,
                        "Python `if` on a traced value concretizes under jit; use jnp.where/"
                        "lax.cond, hoist to a static (shape/dtype) check, or guard with "
                        "`if _is_concrete(...)`",
                    )
                yield from self._scan_expr_container(ctx, stmt.test, traced)
                yield from self._scan_stmts(ctx, stmt.body, traced)
                yield from self._scan_stmts(ctx, stmt.orelse, traced)
            elif isinstance(stmt, ast.While):
                if traced.mentions(stmt.test):
                    yield self.violation(
                        ctx,
                        stmt,
                        "Python `while` on a traced value concretizes under jit; use "
                        "lax.while_loop or restructure to static bounds",
                    )
                yield from self._scan_expr_container(ctx, stmt.test, traced)
                yield from self._scan_stmts(ctx, stmt.body, traced)
                yield from self._scan_stmts(ctx, stmt.orelse, traced)
            elif isinstance(stmt, (ast.For, ast.With, ast.Try)):
                for field_name in ("body", "orelse", "finalbody"):
                    yield from self._scan_stmts(ctx, getattr(stmt, field_name, []) or [], traced)
                for handler in getattr(stmt, "handlers", []) or []:
                    yield from self._scan_stmts(ctx, handler.body, traced)
                if isinstance(stmt, ast.For):
                    yield from self._scan_expr_container(ctx, stmt.iter, traced)
                if isinstance(stmt, ast.With):
                    for item in stmt.items:
                        yield from self._scan_expr_container(ctx, item.context_expr, traced)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._scan_stmts(ctx, stmt.body, traced)
            else:
                yield from self._scan_expr_container(ctx, stmt, traced)
                traced.absorb_assign(stmt)

    def _scan_expr_container(self, ctx: FileContext, node: ast.AST, traced: _TracedNames) -> Iterator[Violation]:
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            func = sub.func
            if isinstance(func, ast.Attribute) and func.attr in self._HOST_SYNC_METHODS:
                yield self.violation(
                    ctx,
                    sub,
                    f"`.{func.attr}()` forces a device->host sync inside a traced "
                    "update/compute; keep the value on device (jnp ops) or move the "
                    "readback to the caller",
                )
            elif _last_name(func) == "device_get":
                yield self.violation(
                    ctx,
                    sub,
                    "`jax.device_get` inside update/compute blocks on a host transfer "
                    "per batch; return the array and let the caller fetch it",
                )
            elif isinstance(func, ast.Name) and func.id in self._CAST_BUILTINS:
                if any(traced.mentions(a) for a in sub.args):
                    yield self.violation(
                        ctx,
                        sub,
                        f"`{func.id}()` on a traced value is a host round-trip that "
                        "breaks FusedUpdate fusion (forces `__jit_unsafe__`); keep it "
                        "as a 0-d jnp array",
                    )
            elif (
                isinstance(func, ast.Attribute)
                and func.attr in {"asarray", "array"}
                and isinstance(func.value, ast.Name)
                and func.value.id in ctx.numpy_aliases
                and func.value.id not in ctx.jnp_aliases
            ):
                if any(traced.mentions(a) for a in sub.args) or any(
                    traced.mentions(kw.value) for kw in sub.keywords
                ):
                    yield self.violation(
                        ctx,
                        sub,
                        f"`{func.value.id}.{func.attr}` on a traced value pulls it to "
                        "host; use jnp.asarray so the kernel stays fusible",
                    )
            elif (
                isinstance(func, ast.Name)
                and ctx.numpy_member_imports.get(func.id) in {"asarray", "array"}
                and func.id not in ctx.jnp_member_imports
            ):
                # direct-member import form: `from numpy import asarray`
                if any(traced.mentions(a) for a in sub.args) or any(
                    traced.mentions(kw.value) for kw in sub.keywords
                ):
                    yield self.violation(
                        ctx,
                        sub,
                        f"`{func.id}` (imported from numpy) on a traced value pulls "
                        "it to host; use jnp.asarray so the kernel stays fusible",
                    )

    # -- functional-kernel scan (hard syncs only) --------------------------
    def _scan_hard_syncs(self, ctx: FileContext, fn: ast.FunctionDef) -> Iterator[Violation]:
        guarded: Set[int] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.If) and _mentions_concrete_guard(node.test):
                for sub in ast.walk(node):
                    guarded.add(id(sub))
        for node in ast.walk(fn):
            if id(node) in guarded or not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Attribute) and func.attr in self._HOST_SYNC_METHODS:
                yield self.violation(
                    ctx,
                    node,
                    f"`.{func.attr}()` in a functional kernel forces a host sync; "
                    "functional kernels must stay pure (state, batch) -> state",
                )
            elif _last_name(func) == "device_get":
                yield self.violation(
                    ctx,
                    node,
                    "`jax.device_get` in a functional kernel forces a host sync; "
                    "return the array instead",
                )


# ---------------------------------------------------------------------------
# TL-RECOMPILE
# ---------------------------------------------------------------------------

class _JitStaticSpec:
    """Which argument positions/names of a jitted callable are STATIC.

    Only static args key the compile cache by value (an ordinary Python
    scalar passed dynamically traces as a weak-typed 0-d array and shares
    one compilation), so the rule confines itself to them. ``unknown`` is
    set when the static spec exists but cannot be parsed statically — then
    every scalar-hazard arg is flagged (conservative).
    """

    def __init__(self) -> None:
        self.argnums: Set[int] = set()
        self.argnames: Set[str] = set()
        self.unknown = False

    def absorb(self, call: ast.Call, params: Optional[List[str]] = None) -> None:
        for kw in call.keywords:
            if kw.arg not in ("static_argnums", "static_argnames"):
                continue
            values: List = []
            node = kw.value
            elts = node.elts if isinstance(node, (ast.Tuple, ast.List)) else [node]
            for el in elts:
                if isinstance(el, ast.Constant):
                    values.append(el.value)
                else:
                    self.unknown = True
            if kw.arg == "static_argnums":
                self.argnums.update(v for v in values if isinstance(v, int))
            else:
                names = [v for v in values if isinstance(v, str)]
                self.argnames.update(names)
                if params is not None:
                    # map names to positions so positional call sites
                    # (the stoi idiom) are covered too
                    self.argnums.update(params.index(n) for n in names if n in params)

    def is_static(self, index: Optional[int], name: Optional[str]) -> bool:
        if self.unknown:
            return True
        if index is not None and index in self.argnums:
            return True
        return name is not None and name in self.argnames

    @property
    def has_static(self) -> bool:
        return self.unknown or bool(self.argnums) or bool(self.argnames)


@register_rule
class RecompileRule(Rule):
    """Python-scalar / shape-derived values flowing into jitted STATIC
    signature positions.

    A ``.shape[0]`` / ``len(x)`` / ``int(...)`` value in a
    ``static_argnums``/``static_argnames`` position is part of the compile
    signature: every new value compiles a fresh executable — the
    unbounded-recompile storm ``FusedUpdate``'s 0-d-array coercion
    (core/fused.py) exists to prevent. Pass ``jnp.asarray(value)`` into a
    dynamic position so the scalar traces instead (dynamic Python scalars
    already trace and are not flagged).
    """

    id = "TL-RECOMPILE"
    description = "Python scalar or .shape-derived value in a jitted static-arg position"

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        jitted = self._jitted_specs(ctx)
        if not jitted:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = node.func.id if isinstance(node.func, ast.Name) else None
            spec = jitted.get(name)
            if spec is None:
                continue
            flagged = [
                (arg, i, None) for i, arg in enumerate(node.args)
            ] + [(kw.value, None, kw.arg) for kw in node.keywords]
            for arg, index, kwname in flagged:
                if not spec.is_static(index, kwname):
                    continue
                hazard = self._scalar_hazard(arg)
                if hazard:
                    yield self.violation(
                        ctx,
                        arg,
                        f"{hazard} flows into a STATIC position of jitted `{name}` and "
                        "keys the compile cache per value; pass jnp.asarray(...) through "
                        "a dynamic position so it traces",
                    )

    @staticmethod
    def _jitted_specs(ctx: FileContext) -> Dict[str, _JitStaticSpec]:
        jitted: Dict[str, _JitStaticSpec] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and isinstance(node.targets[0], ast.Name):
                value = node.value
                if isinstance(value, ast.Call) and _last_name(value.func) == "jit":
                    spec = _JitStaticSpec()
                    spec.absorb(value)
                    if spec.has_static:
                        jitted[node.targets[0].id] = spec
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                params = [a.arg for a in node.args.args]
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call) and (
                        _last_name(dec.func) == "jit"
                        or (_last_name(dec.func) == "partial" and dec.args and _last_name(dec.args[0]) == "jit")
                    ):
                        spec = _JitStaticSpec()
                        spec.absorb(dec, params)
                        if spec.has_static:
                            jitted[node.name] = spec
        return jitted

    @staticmethod
    def _scalar_hazard(arg: ast.AST) -> Optional[str]:
        if isinstance(arg, ast.Subscript) and isinstance(arg.value, ast.Attribute) and arg.value.attr == "shape":
            return "a `.shape[...]` int"
        if isinstance(arg, ast.Attribute) and arg.attr in {"ndim"}:
            return "a `.ndim` int"
        if isinstance(arg, ast.Call) and isinstance(arg.func, ast.Name):
            if arg.func.id == "len":
                return "a `len(...)` int"
            if arg.func.id in {"int", "float"}:
                return f"a concretized `{arg.func.id}(...)` scalar"
        return None


# ---------------------------------------------------------------------------
# TL-STATE
# ---------------------------------------------------------------------------

@register_rule
class StateRule(Rule):
    """State-registry discipline.

    Registered states carry a ``dist_reduce_fx`` contract that sync, merge,
    and the fused kernel all trust; writing one outside an
    update/reset/sync context desynchronizes ``_defaults``/``_cache``
    bookkeeping (a ``_compute`` that assigns state breaks compute-caching
    and double-update ``forward``). List-state and wrapper metrics must
    declare ``__jit_unsafe__`` explicitly — the fused path excludes them
    either way, but the declaration is the reviewed, documented decision
    (and the MetricTester keys its jit checks on it).
    """

    id = "TL-STATE"
    description = "metric state registry discipline (writes, reducers, declarations)"

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        classes = collect_classes(ctx)
        for info in classes.values():
            if not _is_metric_like(info, classes):
                continue
            yield from self._check_reducers(ctx, info)
            yield from self._check_state_writes(ctx, info, classes)
            yield from self._check_cache_plane_writes(ctx, info)
            yield from self._check_declarations(ctx, info, classes)

    def _check_reducers(self, ctx: FileContext, info: ClassInfo) -> Iterator[Violation]:
        for call in info.add_state_calls:
            fx = None
            if len(call.args) >= 3:
                fx = call.args[2]
            for kw in call.keywords:
                if kw.arg == "dist_reduce_fx":
                    fx = kw.value
            if isinstance(fx, ast.Constant) and isinstance(fx.value, str) and fx.value not in KNOWN_REDUCERS:
                yield self.violation(
                    ctx,
                    call,
                    f"add_state with unknown dist_reduce_fx {fx.value!r}; use one of "
                    f"{sorted(KNOWN_REDUCERS)}, None, or a callable",
                )

    def _check_state_writes(self, ctx: FileContext, info: ClassInfo, classes: Dict[str, ClassInfo]) -> Iterator[Violation]:
        states = _resolved_states(info, classes)
        if not states:
            return
        for method in info.methods():
            name = method.name
            if name in _STATE_WRITE_METHODS or any(tok in name for tok in _STATE_WRITE_TOKENS):
                continue
            for node in ast.walk(method):
                targets: List[ast.AST] = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    targets = [node.target]
                for tgt in targets:
                    if (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                        and tgt.attr in states
                        # host-side epoch/dirty/memo counters are legal
                        # non-leaf writes anywhere (see HOST_COUNTER_ATTRS)
                        and tgt.attr not in HOST_COUNTER_ATTRS
                    ):
                        yield self.violation(
                            ctx,
                            node,
                            f"registered state `{tgt.attr}` assigned in `{name}`, outside "
                            "the update/reset/sync lifecycle; state writes elsewhere "
                            "desync the reset defaults and the sync cache",
                        )

    def _check_cache_plane_writes(self, ctx: FileContext, info: ClassInfo) -> Iterator[Violation]:
        for method in info.methods():
            name = method.name
            if name in _STATE_WRITE_METHODS or any(tok in name for tok in _CACHE_PLANE_TOKENS):
                continue
            for node in ast.walk(method):
                targets: List[ast.AST] = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    targets = [node.target]
                for tgt in targets:
                    if (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                        and tgt.attr in _CACHE_PLANE_FIELDS
                    ):
                        yield self.violation(
                            ctx,
                            node,
                            f"epoch-cache field `{tgt.attr}` assigned in `{name}`, outside "
                            "the compute/update/reset lifecycle; call "
                            "`_mark_state_written()` (or `_mark_fused_written()`) instead "
                            "so subclass incremental read caches degrade with the epoch",
                        )

    def _check_declarations(self, ctx: FileContext, info: ClassInfo, classes: Dict[str, ClassInfo]) -> Iterator[Violation]:
        # a subclass that registers no list state itself inherits the
        # ancestor's declaration (or the ancestor is flagged on its own)
        is_wrapper = ctx.relpath.startswith("wrappers/")
        if not (is_wrapper or info.has_list_state):
            return
        if not _resolved(info, classes, "jit_unsafe_declared"):
            kind = "wrapper metric" if is_wrapper else "list-state metric"
            yield self.violation(
                ctx,
                info.node,
                f"{kind} `{info.name}` must declare `__jit_unsafe__` explicitly "
                "(True if update cannot trace, False if it can); the fused path "
                "and MetricTester key on the declaration",
            )


# ---------------------------------------------------------------------------
# TL-BLOCK
# ---------------------------------------------------------------------------

@register_rule
class BlockRule(Rule):
    """Host-blocking readbacks on the async-ingest hot path.

    The async update pipeline's contract (``core/pipeline.py``) is that the
    serving loop never stalls on metrics accounting: ``update_async`` must
    return in microseconds and the worker must hand batches to XLA's async
    dispatch without waiting on device completion. One ``.item()`` /
    ``jax.device_get`` / ``block_until_ready`` / ``float()``/``int()``-on-a-
    device-value there silently turns the pipeline back into the blocking
    path it exists to replace — per batch, invisibly. Scope: every function
    named ``*_async`` anywhere in the package, plus the worker/enqueue/drain
    paths of ``core/pipeline.py`` (method-name keyed). Deliberate blocking
    entry points (``flush``, ``close``, ``update_blocking``) are outside the
    scope by naming convention; intentional hits take the standard
    ``# tracelint: disable=TL-BLOCK`` pragma or a baseline entry.
    """

    id = "TL-BLOCK"
    description = (
        "host-blocking readback on the async hot path (*_async functions, "
        "core/pipeline.py worker/enqueue paths)"
    )

    _SYNC_METHODS = {"item", "block_until_ready"}
    _CAST_BUILTINS = {"float", "int"}
    _HOT_FILE = "core/pipeline.py"
    _HOT_NAME_TOKENS = ("worker", "enqueue", "drain")

    def _is_hot(self, ctx: FileContext, fn: ast.FunctionDef) -> bool:
        if fn.name.endswith("_async"):
            return True
        return ctx.relpath == self._HOT_FILE and any(
            tok in fn.name for tok in self._HOT_NAME_TOKENS
        )

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        hot = [
            node
            for node in ast.walk(ctx.tree)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and self._is_hot(ctx, node)
        ]
        hot_ids = {id(fn) for fn in hot}
        for fn in hot:
            # a hot function nested inside another hot function is scanned
            # once, as its own entry
            yield from self._scan(ctx, fn, hot_ids)

    def _scan(self, ctx: FileContext, fn: ast.FunctionDef, hot_ids: Set[int]) -> Iterator[Violation]:
        params = {a.arg for a in list(fn.args.args) + list(fn.args.kwonlyargs) if a.arg != "self"}
        if fn.args.vararg:
            params.add(fn.args.vararg.arg)
        if fn.args.kwarg:
            params.add(fn.args.kwarg.arg)
        tainted = _TracedNames(params, set(), set(), ctx)
        skip: Set[int] = set()
        for node in ast.walk(fn):
            if id(node) in skip:
                continue
            if (
                isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node is not fn
                and id(node) in hot_ids
            ):
                for sub in ast.walk(node):
                    skip.add(id(sub))
                continue
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                tainted.absorb_assign(node)
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Attribute) and func.attr in self._SYNC_METHODS:
                yield self.violation(
                    ctx,
                    node,
                    f"`.{func.attr}()` blocks the host on device completion inside the"
                    " async hot path — the serving loop stalls on every batch; keep"
                    " readbacks out of update_async/worker code (flush() is the"
                    " sanctioned drain point)",
                )
            elif _last_name(func) == "device_get":
                yield self.violation(
                    ctx,
                    node,
                    "`jax.device_get` forces a device->host transfer inside the async"
                    " hot path; enqueue the array and let the caller (or an exporter)"
                    " fetch it after flush()",
                )
            elif isinstance(func, ast.Name) and func.id in self._CAST_BUILTINS:
                if any(tainted.mentions(a) for a in node.args):
                    yield self.violation(
                        ctx,
                        node,
                        f"`{func.id}()` on a batch-derived value concretizes it — a"
                        " blocking readback per batch on the async hot path; keep it"
                        " as an array (or move the cast behind flush())",
                    )


# ---------------------------------------------------------------------------
# TL-COLLECTIVE
# ---------------------------------------------------------------------------

@register_rule
class CollectiveRule(Rule):
    """Raw XLA collectives outside the transport layer.

    ``parallel/distributed.py`` owns gather-byte/pad-waste telemetry, the
    VMA-clean all-gather, and reduction-fusion; ``observability/
    aggregate.py`` owns the host-level counter allgather. A raw
    ``jax.lax.p*`` anywhere else bypasses that accounting and couples metric
    code to mesh-axis names — route through ``sync_in_mesh`` /
    ``gather_all_arrays`` instead.
    """

    id = "TL-COLLECTIVE"
    description = "raw collective outside metrics_tpu/parallel or observability/aggregate.py"

    COLLECTIVES = {
        "psum",
        "pmean",
        "pmax",
        "pmin",
        "psum_scatter",
        "ppermute",
        "pshuffle",
        "pgather",
        "all_gather",
        "all_to_all",
    }
    ALLOWED_PREFIXES = ("parallel/",)
    ALLOWED_FILES = {"observability/aggregate.py"}

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        rel = ctx.relpath
        if rel.startswith(self.ALLOWED_PREFIXES) or rel in self.ALLOWED_FILES:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            chain = _attr_chain(func)
            name = chain[-1] if chain else None
            if name in self.COLLECTIVES:
                # jax.lax.psum / lax.psum / from jax.lax import psum / a
                # same-file rebinding (`mylax = jax.lax`; engine alias maps)
                rooted_in_lax = (
                    "lax" in chain[:-1]
                    or (len(chain) > 1 and chain[0] in ctx.lax_aliases)
                    or (isinstance(func, ast.Name) and func.id in ctx.lax_from_imports)
                )
                if rooted_in_lax:
                    yield self.violation(
                        ctx,
                        node,
                        f"raw collective `{'.'.join(chain)}` outside the transport layer; "
                        "route through parallel.distributed (sync_in_mesh/"
                        "all_gather_replicated) so byte accounting and axis naming stay "
                        "centralized",
                    )
            elif name == "process_allgather":
                yield self.violation(
                    ctx,
                    node,
                    "raw `process_allgather` outside the transport layer; use "
                    "parallel.distributed.gather_all_arrays or observability."
                    "aggregate.aggregate_across_hosts",
                )


# ---------------------------------------------------------------------------
# TL-PRINT
# ---------------------------------------------------------------------------

@register_rule
class PrintRule(Rule):
    """Raw ``print()`` / bare ``warnings.warn()`` in library code.

    Multi-host jobs run one Python process per host: an unguarded print
    emits once per process. All user-facing output must route through the
    rank-zero helpers in ``utils/prints.py`` (the one module allowed to
    touch print/warnings directly). Absorbs ``scripts/check_no_print.py``,
    which remains as a thin alias over this rule.
    """

    id = "TL-PRINT"
    description = "raw print()/warnings.warn() in library code (use rank-zero helpers)"

    ALLOWED_FILES = {"utils/prints.py"}

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        if ctx.relpath in self.ALLOWED_FILES:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Name) and func.id == "print":
                yield self.violation(
                    ctx,
                    node,
                    "raw print() in library code; use rank_zero_print/rank_zero_info "
                    "from metrics_tpu.utils.prints",
                )
            elif (
                isinstance(func, ast.Attribute)
                and func.attr == "warn"
                and isinstance(func.value, ast.Name)
                and func.value.id in ctx.warnings_aliases
            ):
                yield self.violation(
                    ctx,
                    node,
                    "bare warnings.warn() in library code; use rank_zero_warn from "
                    "metrics_tpu.utils.prints",
                )
            elif isinstance(func, ast.Name) and func.id in ctx.warn_fn_aliases:
                yield self.violation(
                    ctx,
                    node,
                    "bare warn() in library code; use rank_zero_warn from "
                    "metrics_tpu.utils.prints",
                )


# ---------------------------------------------------------------------------
# TL-DECL
# ---------------------------------------------------------------------------

@register_rule
class DeclRule(Rule):
    """``__jit_unsafe__`` declarations cross-checked against the abstract
    interpreter's verdict (analysis/interp.py).

    The declaration is the reviewed contract the fused path and MetricTester
    key on — and PR-by-PR it goes stale in both directions: a metric
    declared ``True`` whose update became pure and fixed-shape (ROADMAP
    item 2 replaces cat-state with sketches) silently keeps paying the
    eager path, and a metric declared ``False`` that grew a host sync
    crashes the fused kernel build instead of falling back. Both are
    findings; ``unknown`` verdicts never fire (the runtime probe stays the
    authority), and cat-growth never contradicts ``False`` (list states are
    excluded from fusion by a separate runtime check, not the declaration).
    """

    id = "TL-DECL"
    description = "__jit_unsafe__ declaration contradicted or made redundant by the static verdict"

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        from . import interp

        classes = collect_classes(ctx)
        project = _shared_project()
        for info in classes.values():
            if not _is_metric_like(info, classes):
                continue
            verdict, facts = interp.classify(project, ctx, info.node)
            if facts.declared_here is None or facts.declared_computed:
                continue  # undeclared or computed declarations are not auditable
            if facts.declared_here and verdict.status == interp.VERDICT_FUSIBLE:
                yield self.violation(
                    ctx,
                    info.node,
                    f"`{info.name}` declares `__jit_unsafe__ = True` but its update is "
                    "statically fusible (pure, fixed-shape through every resolved call); "
                    "the stale declaration forces the eager path — remove it or document "
                    "the dynamic case the analysis cannot see with a pragma",
                )
            elif (
                not facts.declared_here
                and verdict.status == interp.VERDICT_UNSAFE
                and verdict.reason in (interp.REASON_HOST_SYNC, interp.REASON_DATA_SHAPE)
            ):
                yield self.violation(
                    ctx,
                    info.node,
                    f"`{info.name}` declares `__jit_unsafe__ = False` but its update is "
                    f"statically unsafe ({verdict.reason}): {verdict.detail}; the fused "
                    "kernel build will fail instead of falling back — fix the update or "
                    "declare True",
                )


#: one Project per process: parse-once resolution shared by TL-DECL/TL-FLOW
#: and the manifest builder (file contexts are immutable once parsed)
_PROJECT = None


def _shared_project():
    global _PROJECT
    if _PROJECT is None:
        from .interp import Project

        _PROJECT = Project()
    return _PROJECT


# ---------------------------------------------------------------------------
# TL-FLOW
# ---------------------------------------------------------------------------

@register_rule
class FlowRule(Rule):
    """State-lifecycle dataflow (analysis/stateflow.py): reducer-consistent
    accumulation, reset restoration, and live leaves.

    A ``"sum"``-reduced leaf mutated by anything other than additive
    assignment breaks the cross-rank reduction contract sync and
    ``merge_states`` trust; an overriding ``reset`` that misses a leaf
    leaks accumulation across epochs; a registered-but-never-touched leaf
    is dead sync weight. TL-STATE checks WHERE states are written — this
    rule checks WHAT the writes mean.
    """

    id = "TL-FLOW"
    description = "state write inconsistent with its dist_reduce_fx / reset / liveness contract"

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        from . import stateflow

        classes = collect_classes(ctx)
        for info in classes.values():
            if not _is_metric_like(info, classes):
                continue
            for finding in stateflow.analyze_class(ctx, info.node):
                yield self.violation(ctx, finding.node, finding.message)


# Layout/collective soundness rules (TL-SHARD, TL-MERGE, TL-WIRE, TL-LOCK)
# live in their own module but register into the same registry; imported
# last so they can reuse this module's helpers without circularity.
from . import layout_rules  # noqa: E402,F401
