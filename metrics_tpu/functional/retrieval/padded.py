"""Padded fixed-shape per-query retrieval kernels (TPU-native compute path).

The reference evaluates retrieval metrics with a Python loop over query
groups (/root/reference/torchmetrics/retrieval/base.py:115-150 over
``get_group_indexes``, utilities/data.py:229-253 — SURVEY §3.6 flags it as a
hot spot). Here the ragged (query, documents) structure is packed ONCE into
static ``[num_queries, max_docs]`` buffers host-side (vectorized numpy, no
per-element Python), and every per-query metric plus the empty-query policy
and the final mean run as ONE jitted vmapped kernel on device.

Row kernels replicate the single-query functional kernels' semantics exactly
(functional/retrieval/*.py, themselves parity ports of the reference):
padded slots carry ``preds=-inf`` (sort last), ``target=0``, ``mask=False``.
"""
import functools
import weakref
from collections import OrderedDict
from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.utils.data import dim_zero_cat, stable_sort_with_payloads

Array = jax.Array


@jax.jit
def _segment_layout(indexes: Array) -> Tuple[Array, Array, Array]:
    """Stable sort by query id -> (order, dense row id, within-row column).

    The stable sort preserves within-query document order, so tie-breaking in
    the downstream per-row argsort matches the reference's group-loop path.
    """
    order = jnp.argsort(indexes, stable=True)
    sorted_idx = indexes[order]
    change = jnp.concatenate(
        [jnp.zeros(1, bool), sorted_idx[1:] != sorted_idx[:-1]]
    )
    row = jnp.cumsum(change.astype(jnp.int32))
    pos = jnp.arange(sorted_idx.shape[0], dtype=jnp.int32)
    seg_start = jax.lax.cummax(jnp.where(change, pos, 0))
    col = pos - seg_start
    return order, row, col


@functools.partial(jax.jit, static_argnums=(5, 6))
def _scatter_pack(
    preds: Array, target: Array, order: Array, row: Array, col: Array, num_queries: int, max_docs: int
) -> Tuple[Array, Array, Array]:
    padded_preds = jnp.full((num_queries, max_docs), -jnp.inf, jnp.float32).at[row, col].set(
        preds[order].astype(jnp.float32)
    )
    padded_target = jnp.zeros((num_queries, max_docs), jnp.float32).at[row, col].set(
        target[order].astype(jnp.float32)
    )
    mask = jnp.zeros((num_queries, max_docs), bool).at[row, col].set(True)
    return padded_preds, padded_target, mask


def pack_queries(
    indexes: Array, preds: Array, target: Array, max_expand: Optional[int] = None
) -> Optional[Tuple[Array, Array, Array]]:
    """Pack ragged (indexes, preds, target) into padded [Q, Dmax] device buffers.

    Everything stays on device (sort, segment layout, scatter); only TWO
    scalars (the number of queries and the max docs-per-query, needed as
    static shapes) cross to the host. This matters: on tunneled/remote
    accelerators bulk host<->device copies are the bottleneck, and the raw
    ragged data never leaves the device here.

    Returns None (before allocating anything) when the padded layout would
    exceed ``max_expand`` times the raw element count — heavily skewed query
    sizes (one huge query among many small ones) make dense padding blow up.
    """
    indexes = jnp.asarray(indexes).reshape(-1)
    preds = jnp.asarray(preds).reshape(-1)
    target = jnp.asarray(target).reshape(-1)
    if indexes.size == 0:
        raise ValueError(
            "`indexes` is empty — the retrieval metric has no accumulated samples;"
            " call `update` before `compute`."
        )

    order, row, col = _segment_layout(indexes)
    # ONE device->host transfer for both static shapes (each separate scalar
    # fetch costs a full accelerator-link round trip)
    shape_info = np.asarray(jnp.stack([row[-1], jnp.max(col)]))
    num_queries = int(shape_info[0]) + 1
    max_docs = int(shape_info[1]) + 1
    if max_expand is not None and num_queries * max_docs > max_expand * indexes.size:
        return None
    return _scatter_pack(preds, target, order, row, col, num_queries, max_docs)


# ---------------------------------------------------------------------------
# shared-pack cache: one pack feeds every metric over the same state
# ---------------------------------------------------------------------------

#: (state-array identities, max_expand) -> packed buffers. MetricCollection
#: compute groups share their cat-list states BY REFERENCE across member
#: metrics, and jax arrays are immutable, so object identity of every list
#: element is a sound equality key — an NDCG+MAP collection then packs its
#: (identical) ragged states once instead of once per metric. The cache does
#: NOT keep the state arrays alive: a weakref finalizer on every keyed array
#: purges the entry (and its packed buffers) the moment any of them is
#: collected, so deleting/resetting the metric frees the device memory and a
#: recycled id() can never produce a stale hit.
_PACK_CACHE: "OrderedDict[tuple, object]" = OrderedDict()
_PACK_CACHE_MAX = 4
_NO_PACK = object()  # cached "pack_queries returned None" (skew fallback)


def pack_queries_cached(
    indexes_list: List[Array],
    preds_list: List[Array],
    target_list: List[Array],
    max_expand: Optional[int] = None,
) -> Optional[Tuple[Array, Array, Array]]:
    """:func:`pack_queries` over cat-list states, memoized on array identity
    (the shared ``_memoized`` contract; the skew fallback ``None`` is cached
    under a sentinel so repeated computes on the same state skip the device
    argsort + shape readback)."""
    if not indexes_list:
        raise ValueError(
            "`indexes` is empty — the retrieval metric has no accumulated samples;"
            " call `update` before `compute`."
        )

    def compute():
        packed = pack_queries(
            dim_zero_cat(indexes_list), dim_zero_cat(preds_list), dim_zero_cat(target_list),
            max_expand=max_expand,
        )
        return _NO_PACK if packed is None else packed

    result = _memoized(
        _PACK_CACHE,
        (*indexes_list, *preds_list, *target_list),
        compute,
        # list lengths disambiguate which list each id belongs to
        extra_key=(len(indexes_list), len(preds_list), max_expand),
        max_entries=_PACK_CACHE_MAX,
    )
    return None if result is _NO_PACK else result


def _row_sort(preds: Array, target: Array, mask: Array) -> Tuple[Array, Array]:
    """Target and mask reordered by descending preds (padding sorts last).

    One stable multi-operand ``lax.sort`` carries target and mask through
    the permutation — measured 3.2x faster on-chip than argsort + two
    gathers at MSLR shape (round 5; same layout lesson as the AUROC rank
    kernel), and bit-identical (stable sort == stable argsort order).
    """
    _, st, sm = stable_sort_with_payloads(preds, target, mask, descending=True)
    return st, sm


def _positions(d: int) -> Array:
    return jnp.arange(1, d + 1, dtype=jnp.float32)


# ---------------------------------------------------------------------------
# sorted-row kernels: the math AFTER the shared per-row argsort. Each public
# row kernel wraps one of these; the collection compute path sorts ONCE per
# pack (sorted_row_layout below) and feeds every metric's sorted kernel —
# an NDCG+MAP collection then pays one argsort, not one per metric.
# `st` = target by descending score, `sm` = mask likewise, `ideal` = target
# sorted descending by itself (NDCG's ideal ranking).
# ---------------------------------------------------------------------------


def _ap_sorted(st: Array, sm: Array, ideal: Array, k: Optional[int] = None) -> Array:
    num_pos = jnp.sum(st)
    terms = st * jnp.cumsum(st) / _positions(st.shape[0])
    return jnp.where(num_pos > 0, jnp.sum(terms) / jnp.maximum(num_pos, 1.0), 0.0)


def _rr_sorted(st: Array, sm: Array, ideal: Array, k: Optional[int] = None) -> Array:
    num_pos = jnp.sum(st)
    first = jnp.argmax(st > 0)
    return jnp.where(num_pos > 0, 1.0 / (first + 1.0), 0.0)


def _precision_sorted(st: Array, sm: Array, ideal: Array, k: Optional[int] = None) -> Array:
    num_pos = jnp.sum(st)
    if k is None:
        # k defaults to the per-query document count (reference precision.py)
        n_docs = jnp.sum(sm)
        return jnp.where(num_pos > 0, num_pos / jnp.maximum(n_docs, 1.0), 0.0)
    in_k = _positions(st.shape[0]) <= k
    return jnp.where(num_pos > 0, jnp.sum(st * in_k) / k, 0.0)


def _recall_sorted(st: Array, sm: Array, ideal: Array, k: Optional[int] = None) -> Array:
    num_pos = jnp.sum(st)
    in_k = _positions(st.shape[0]) <= (k if k is not None else st.shape[0])
    return jnp.where(num_pos > 0, jnp.sum(st * in_k) / jnp.maximum(num_pos, 1.0), 0.0)


def _r_precision_sorted(st: Array, sm: Array, ideal: Array, k: Optional[int] = None) -> Array:
    num_pos = jnp.sum(st)
    in_r = _positions(st.shape[0]) <= num_pos
    return jnp.where(num_pos > 0, jnp.sum(st * in_r) / jnp.maximum(num_pos, 1.0), 0.0)


def _hit_rate_sorted(st: Array, sm: Array, ideal: Array, k: Optional[int] = None) -> Array:
    in_k = _positions(st.shape[0]) <= (k if k is not None else st.shape[0])
    return (jnp.sum(st * in_k) > 0).astype(jnp.float32)


def _fall_out_sorted(st: Array, sm: Array, ideal: Array, k: Optional[int] = None) -> Array:
    neg = (1.0 - st) * sm
    num_neg = jnp.sum(neg)
    in_k = _positions(st.shape[0]) <= (k if k is not None else st.shape[0])
    return jnp.where(num_neg > 0, jnp.sum(neg * in_k) / jnp.maximum(num_neg, 1.0), 0.0)


def _ndcg_sorted(st: Array, sm: Array, ideal: Array, k: Optional[int] = None) -> Array:
    pos = _positions(st.shape[0])
    in_k = pos <= (k if k is not None else st.shape[0])
    discount = jnp.log2(pos + 1.0)
    target_dcg = jnp.sum(st * in_k / discount)
    ideal_dcg = jnp.sum(ideal * in_k / discount)
    return jnp.where(ideal_dcg > 0, target_dcg / jnp.maximum(ideal_dcg, 1e-38), 0.0)


_ndcg_sorted.needs_ideal = True  # the only kernel consuming the ideal ranking


def _make_row_kernel(name: str, sorted_fn: Callable, doc: str) -> Callable:
    needs_ideal = getattr(sorted_fn, "needs_ideal", False)

    def kernel(preds: Array, target: Array, mask: Array, k: Optional[int] = None) -> Array:
        st, sm = _row_sort(preds, target, mask)
        # padding zeros sort last in the ideal ranking; only NDCG consumes it
        ideal = -jnp.sort(-target) if needs_ideal else st
        return sorted_fn(st, sm, ideal, k)

    kernel.__name__ = kernel.__qualname__ = name
    kernel.__doc__ = doc
    kernel.sorted_fn = sorted_fn  # the shared-sort path dispatches on this
    return kernel


average_precision_row = _make_row_kernel(
    "average_precision_row",
    _ap_sorted,
    "functional/retrieval/average_precision.py semantics on a padded row.",
)
reciprocal_rank_row = _make_row_kernel("reciprocal_rank_row", _rr_sorted, "MRR on a padded row.")
precision_row = _make_row_kernel("precision_row", _precision_sorted, "Precision@k on a padded row.")
recall_row = _make_row_kernel("recall_row", _recall_sorted, "Recall@k on a padded row.")
r_precision_row = _make_row_kernel(
    "r_precision_row", _r_precision_sorted, "R-precision on a padded row."
)
hit_rate_row = _make_row_kernel("hit_rate_row", _hit_rate_sorted, "HitRate@k on a padded row.")
fall_out_row = _make_row_kernel(
    "fall_out_row",
    _fall_out_sorted,
    "Top-k fraction of NON-relevant docs; padding must not count as negative.",
)
ndcg_row = _make_row_kernel(
    "ndcg_row", _ndcg_sorted, "Graded-target nDCG@k (functional/retrieval/ndcg.py semantics)."
)


#: (identity of every input array) -> cached device result; entries die with
#: their arrays (weakref finalizers), mirroring _PACK_CACHE's contract
_SORT_CACHE: "OrderedDict[tuple, Tuple[Array, Array]]" = OrderedDict()


@jax.jit
def _sorted_layout(padded_preds: Array, padded_target: Array, mask: Array):
    # _row_sort is rank-polymorphic (sorts the minor axis); no vmap needed
    return _row_sort(padded_preds, padded_target, mask)


def _memoized(
    cache: "OrderedDict", key_arrays: tuple, compute: Callable, extra_key: tuple = (), max_entries: int = 4
):
    """Identity-keyed device-result memoization: the key is the id() of every
    input array (immutable jax arrays; weakref finalizers purge the entry —
    and make id recycling impossible — the moment any of them is collected),
    plus any hashable ``extra_key``. Non-weakref-able inputs skip caching.

    Entries are stored as ``(result, finalizer_handles)`` and every eviction
    path — LRU cap, array collection, explicit pop — detaches the entry's
    finalizers, so an evicted-then-recomputed key never accumulates orphan
    registrations on long-lived arrays."""
    key = tuple(map(id, key_arrays)) + extra_key
    hit = cache.get(key)
    if hit is not None:
        cache.move_to_end(key)
        return hit[0]
    result = compute()
    finalizers = []
    try:
        for a in key_arrays:
            finalizers.append(weakref.finalize(a, _evict, cache, key))
    except TypeError:
        for f in finalizers:
            f.detach()
        return result
    cache[key] = (result, finalizers)
    while len(cache) > max_entries:
        _, (_, old_fins) = cache.popitem(last=False)
        for f in old_fins:
            f.detach()
    return result


def _evict(cache: "OrderedDict", key: tuple) -> None:
    """Finalizer callback: drop the entry and detach its sibling finalizers
    (detaching the already-fired one is a documented no-op)."""
    entry = cache.pop(key, None)
    if entry is not None:
        for f in entry[1]:
            f.detach()


def sorted_row_layout(
    padded_preds: Array, padded_target: Array, mask: Array
) -> Tuple[Array, Array]:
    """``(sorted_target, sorted_mask)`` — the one per-row argsort every
    retrieval kernel shares, memoized on the identity of ALL THREE pack
    arrays: metrics computing over the same padded buffers (a compute-group
    collection) sort once and each run only their own sorted kernel."""
    return _memoized(
        _SORT_CACHE,
        (padded_preds, padded_target, mask),
        lambda: _sorted_layout(padded_preds, padded_target, mask),
    )


@functools.lru_cache(maxsize=None)
def _padded_compute_fn(
    kernel: Callable, k: Optional[int], empty_target_action: str, weighted: bool = False
):
    """One jitted function: vmapped per-query SORTED kernel + empty policy +
    mean, over the shared sorted layout. Kernels that consume the ideal
    ranking (NDCG) derive it INSIDE this jit from the raw padded target —
    lazy for the seven kernels that never read it, and no extra device
    launch for the one that does.

    ``weighted=True`` is the fixed-capacity table-state entry
    (retrieval/base.py::_compute_table): the padded layout has a STATIC
    ``max_queries`` row count, so the run function takes an extra
    per-row weight vector (0 for unoccupied rows) that multiplies into
    the empty-policy mean. The unweighted exact-path signature is kept
    verbatim — its jitted cache entries and bit behavior are untouched."""
    sorted_fn = getattr(kernel, "sorted_fn", None)
    needs_ideal = getattr(sorted_fn, "needs_ideal", False)

    def _body(st: Array, sm: Array, padded_target: Array, empty: Array, row_w) -> Array:
        if needs_ideal:
            ideal = -jnp.sort(-padded_target, axis=-1)
            vals = jax.vmap(lambda a, b, c: sorted_fn(a, b, c, k))(st, sm, ideal)
        else:
            vals = jax.vmap(lambda a, b: sorted_fn(a, b, a, k))(st, sm)
        return _reduce_with_empty_policy(vals, empty, empty_target_action, row_w)

    if weighted:

        @jax.jit
        def run(st: Array, sm: Array, padded_target: Array, empty: Array, row_w: Array) -> Array:
            return _body(st, sm, padded_target, empty, row_w)

    else:

        @jax.jit
        def run(st: Array, sm: Array, padded_target: Array, empty: Array) -> Array:
            return _body(st, sm, padded_target, empty, None)

    return run


@functools.lru_cache(maxsize=None)
def _padded_compute_fn_raw(
    kernel: Callable, k: Optional[int], empty_target_action: str, weighted: bool = False
):
    """Legacy path for user-supplied row kernels without a sorted variant:
    vmapped raw kernel over the padded buffers (``weighted`` as above)."""

    def _body(padded_preds: Array, padded_target: Array, mask: Array, empty: Array, row_w) -> Array:
        vals = jax.vmap(lambda p, t, m: kernel(p, t, m, k))(padded_preds, padded_target, mask)
        return _reduce_with_empty_policy(vals, empty, empty_target_action, row_w)

    if weighted:

        @jax.jit
        def run(padded_preds: Array, padded_target: Array, mask: Array, empty: Array, row_w: Array) -> Array:
            return _body(padded_preds, padded_target, mask, empty, row_w)

    else:

        @jax.jit
        def run(padded_preds: Array, padded_target: Array, mask: Array, empty: Array) -> Array:
            return _body(padded_preds, padded_target, mask, empty, None)

    return run


def _reduce_with_empty_policy(
    vals: Array, empty: Array, empty_target_action: str, row_valid: Optional[Array] = None
) -> Array:
    """Empty-query policy + mean. ``row_valid`` (the table-state path)
    zero-weights structurally absent rows — padding rows of the fixed
    ``[max_queries]`` layout — before the policy weights apply."""
    if empty_target_action == "pos":
        vals = jnp.where(empty, 1.0, vals)
        weights = jnp.ones_like(vals)
    elif empty_target_action == "neg":
        vals = jnp.where(empty, 0.0, vals)
        weights = jnp.ones_like(vals)
    elif empty_target_action == "skip":
        weights = (~empty).astype(vals.dtype)
    else:  # "error" is raised host-side before this runs
        weights = jnp.ones_like(vals)
    if row_valid is not None:
        weights = weights * row_valid.astype(vals.dtype)
    total = jnp.sum(weights)
    return jnp.where(total > 0, jnp.sum(vals * weights) / jnp.maximum(total, 1.0), 0.0)
