"""Exact streaming moments: sum / outer-product-sum / count leaves.

The FID trick (and the general one behind every "cat-state that only
feeds a mean + covariance"): the Gaussian fit in ``compute()`` depends on
the features ONLY through

    ``feat_sum  = Σ x_i``             ``[d]``
    ``outer_sum = Σ x_i x_iᵀ``        ``[d, d]``
    ``count     = N``                 scalar

so a fixed-capacity state of those three leaves is EXACT forever — no
window, no admission policy, no accuracy knob. Unlike the packed sketch
leaves (quantile/reservoir), moment leaves are element-wise summable:
the cross-rank merge IS addition, batches commute, and the fused
bucketing path needs no pad correction beyond masking pad rows out of
the per-batch delta.

``moments_merge_fx()`` tags such leaves for the merge plumbing
(``merge_like`` so ``merge_states`` folds stacked per-rank leaves through
the reducer, ``sketch_kind = "moments"`` so occupancy telemetry knows
there is no fill ratio to report) while the tracelint ``moments``
reducer teaching holds them to the full additive write contract.

Numerics: accumulate in float32 on device. ``Σ x x ᵀ`` loses precision to
cancellation when ``‖μ‖ ≫ σ`` — for InceptionV3 pool features (entries
``O(1)``, N ≤ 1e6) the covariance identity stays well within float32 for
FID purposes; the ``exact=True`` hatch keeps the float64 host path for
certification runs. See ``docs/image_detection_states.md``.
"""
import jax
import jax.numpy as jnp

Array = jax.Array


def moments_init(dim: int) -> tuple:
    """Fresh ``(feat_sum [dim], outer_sum [dim, dim], count)`` leaves."""
    if not (isinstance(dim, int) and dim > 0):
        raise ValueError(f"feature dim must be a positive int, got {dim}")
    return (
        jnp.zeros((dim,), jnp.float32),
        jnp.zeros((dim, dim), jnp.float32),
        jnp.zeros((), jnp.float32),
    )


def moments_update(
    feat_sum: Array, outer_sum: Array, count: Array, feats: Array
) -> tuple:
    """Fold a ``[B, d]`` feature batch into the three moment leaves."""
    feats = jnp.asarray(feats, jnp.float32)
    return (
        feat_sum + jnp.sum(feats, axis=0),
        outer_sum + feats.T @ feats,
        count + feats.shape[0],
    )


def mean_cov_from_moments(
    feat_sum: Array, outer_sum: Array, count: Array
) -> tuple:
    """``(mean [d], unbiased covariance [d, d])`` via the covariance
    identity ``cov = (Σxxᵀ − N μμᵀ) / (N − 1)`` — the same estimator the
    cat-state path computes from raw features."""
    n = jnp.maximum(count, 1.0)
    mean = feat_sum / n
    cov = (outer_sum - n * jnp.outer(mean, mean)) / jnp.maximum(n - 1.0, 1.0)
    return mean, cov


class _MomentsReduce:
    """``dist_reduce_fx`` summing stacked per-rank moment leaves
    ``[world, ...] -> [...]`` — tagged ``merge_like`` so the merge
    plumbing routes it like the sketch reducers, but the merge itself is
    plain addition (moment leaves are element-wise summable)."""

    merge_like = True
    sketch_kind = "moments"
    __name__ = "moments_reduce"

    def __call__(self, stacked: Array) -> Array:
        return jnp.sum(jnp.asarray(stacked), axis=0)


_MOMENTS_REDUCE = _MomentsReduce()


def moments_merge_fx() -> _MomentsReduce:
    """The shared streaming-moment ``dist_reduce_fx`` (see
    :class:`_MomentsReduce`)."""
    return _MOMENTS_REDUCE
