"""Char Error Rate (parity: /root/reference/torchmetrics/functional/text/cer.py)."""
from typing import List, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.helper import _edit_distance

Array = jax.Array


def _cer_update(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Tuple[Array, Array]:
    """Sum character-level edit operations and reference char counts (cer.py:22-47)."""
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [target]
    errors = 0
    total = 0
    for pred, tgt in zip(preds, target):
        errors += _edit_distance(list(pred), list(tgt))
        total += len(tgt)
    return jnp.asarray(errors, jnp.float32), jnp.asarray(total, jnp.float32)


def _cer_compute(errors: Array, total: Array) -> Array:
    return errors / total


def char_error_rate(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Array:
    """Character error rate of transcription(s); 0 is perfect.

    Example:
        >>> preds = ["this is the prediction", "there is an other sample"]
        >>> target = ["this is the reference", "there is another one"]
        >>> char_error_rate(preds=preds, target=target)
        Array(0.34146342, dtype=float32)
    """
    errors, total = _cer_update(preds, target)
    return _cer_compute(errors, total)
