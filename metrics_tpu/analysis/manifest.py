"""Fusibility manifest: tracelint's static verdicts as a runtime input.

``scripts/tracelint.py --manifest`` serializes the abstract interpreter's
per-metric verdicts (``interp.classify``), state-leaf shape/dtype/reduction
abstractions, and declared ``__jit_unsafe__`` flags to
``scripts/fusibility_manifest.json``. The fused update path
(``core/fused.py``) consults the committed manifest to pre-seed its
fusibility cache: a ``fusible``-verdict metric skips the per-(metric,
signature) ``jax.eval_shape`` probe entirely; ``unsafe``/``unknown``
metrics keep the runtime probe as the authority. Static analysis stops
being a linter and becomes an input to the hot path.

Schema v1 (deterministic serialization — byte-stable for CI freshness
checks)::

    {
      "version": 1,
      "tool": "tracelint",
      "metrics": {
        "classification/confusion_matrix.py::ConfusionMatrix": {
          "verdict": "fusible",
          "reason": null,                  # unsafe only: cat-growth |
                                           #   host-sync | data-dependent-shape
          "detail": null,
          "declared_jit_unsafe": null,     # explicit __jit_unsafe__ (null =
                                           #   undeclared, inherits False)
          "states": {
            "confmat": {"container": "array",
                         "shape": ["num_classes", "num_classes"],
                         "dtype": "int32", "dist_reduce_fx": "sum"}
          }
        }, ...
      }
    }

State shapes are abstract: dims are concrete ints or constructor-parameter
symbols (``"num_classes"``), ``"?"`` for unresolvable dims, ``null`` for an
unknown rank — the inventory ROADMAP items 1 (sharded slice states need
every leaf's shape before an axis can be prepended) and 2 (the jit-unsafe
set, with machine reasons) both consume.

Runtime lookups key on the CLASS, derived from ``cls.__module__`` /
``cls.__qualname__``; classes outside ``metrics_tpu`` (user subclasses,
test fixtures) have no entry and fall back to the probe. Env overrides:
``METRICS_TPU_MANIFEST=<path>`` points at an alternate manifest,
``METRICS_TPU_NO_MANIFEST=1`` disables consultation entirely.

Stdlib-only, like the rest of the analysis package.
"""
from __future__ import annotations

import json
import os
import pathlib
from typing import Dict, Optional

from .engine import PACKAGE_NAME, default_package_root
from . import interp

MANIFEST_VERSION = 1

#: repo-root-relative location of the committed manifest
DEFAULT_MANIFEST = "scripts/fusibility_manifest.json"

#: env var naming an alternate manifest file
ENV_MANIFEST_PATH = "METRICS_TPU_MANIFEST"
#: env var disabling manifest consultation (runtime probes only)
ENV_NO_MANIFEST = "METRICS_TPU_NO_MANIFEST"
#: env var enabling the probe cross-check of manifest verdicts
ENV_VERIFY_MANIFEST = "METRICS_TPU_VERIFY_MANIFEST"


# ---------------------------------------------------------------------------
# build (analysis side)
# ---------------------------------------------------------------------------

def build_manifest(project: Optional[interp.Project] = None) -> Dict[str, object]:
    """Classify every metric-like class in the package into a manifest dict.

    Always a FULL-package analysis (partial-path manifests would silently
    drop entries, and freshness checks diff the whole file).
    """
    project = project or interp.Project()
    root = project.root
    metrics: Dict[str, Dict[str, object]] = {}
    for path in sorted(root.rglob("*.py")):
        rel = "/".join(path.relative_to(root).parts)
        if rel.startswith("analysis/"):
            continue  # the analyzer does not classify itself
        ctx = project.ctx(rel)
        if ctx is None:
            continue
        for node in interp.iter_metric_classes(ctx):
            verdict, facts = interp.classify(project, ctx, node)
            if not facts.is_metric:
                continue
            key = f"{rel}::{node.name}"
            metrics[key] = {
                "verdict": verdict.status,
                "reason": verdict.reason,
                "detail": verdict.detail,
                "declared_jit_unsafe": facts.declared,
                "states": {e.name: e.to_dict() for e in facts.entries},
            }
    return {
        "version": MANIFEST_VERSION,
        "tool": "tracelint",
        "metrics": {k: metrics[k] for k in sorted(metrics)},
    }


def render_manifest(manifest: Dict[str, object]) -> str:
    """Deterministic, diff-friendly serialization (sorted keys, newline-
    terminated) — ``--manifest --check`` compares these bytes."""
    return json.dumps(manifest, indent=2, sort_keys=True) + "\n"


def load_manifest(path: pathlib.Path) -> Optional[Dict[str, object]]:
    """Parse a manifest file; None when missing/invalid/wrong version."""
    path = pathlib.Path(path)
    if not path.is_file():
        return None
    try:
        data = json.loads(path.read_text())
    except (json.JSONDecodeError, UnicodeDecodeError):
        return None
    if not isinstance(data, dict) or data.get("version") != MANIFEST_VERSION:
        return None
    return data


# ---------------------------------------------------------------------------
# runtime consumption (imported by core/fused.py — keep import-light)
# ---------------------------------------------------------------------------

def default_manifest_path() -> pathlib.Path:
    override = os.environ.get(ENV_MANIFEST_PATH)
    if override:
        return pathlib.Path(override)
    return default_package_root().parent / DEFAULT_MANIFEST


_runtime_cache: Dict[str, Optional[Dict[str, object]]] = {}


def runtime_manifest(path: Optional[pathlib.Path] = None) -> Dict[str, Dict[str, object]]:
    """The committed manifest's metrics map, cached per path; empty when the
    file is absent (installed package without the repo checkout) or
    ``METRICS_TPU_NO_MANIFEST`` is set — every metric then reads as
    ``unknown`` and the runtime probe keeps full authority."""
    if os.environ.get(ENV_NO_MANIFEST):
        return {}
    path = pathlib.Path(path) if path is not None else default_manifest_path()
    key = str(path)
    if key not in _runtime_cache:
        _runtime_cache[key] = load_manifest(path)
    data = _runtime_cache[key]
    if data is None:
        return {}
    metrics = data.get("metrics")
    return metrics if isinstance(metrics, dict) else {}


def invalidate_runtime_cache() -> None:
    """Drop cached manifest files (tests and long-lived sessions that
    regenerate the manifest on disk)."""
    _runtime_cache.clear()


def class_key(cls: type) -> Optional[str]:
    """Manifest key for a metric class, or None when the class lives outside
    the package (or is not a top-level class)."""
    module = getattr(cls, "__module__", "") or ""
    qualname = getattr(cls, "__qualname__", "") or ""
    if not module.startswith(PACKAGE_NAME + ".") or "." in qualname:
        return None
    rel = module[len(PACKAGE_NAME) + 1:].replace(".", "/") + ".py"
    return f"{rel}::{qualname}"


def lookup_class(cls: type, path: Optional[pathlib.Path] = None) -> Optional[Dict[str, object]]:
    """The manifest entry for ``cls`` (exact class only — verdicts do not
    inherit: a subclass may override update with different behavior)."""
    key = class_key(cls)
    if key is None:
        return None
    return runtime_manifest(path).get(key)


def manifest_verdict(cls: type, path: Optional[pathlib.Path] = None) -> str:
    """``fusible`` / ``unsafe`` / ``unknown`` for a class; absent entries
    read as ``unknown`` (probe decides)."""
    entry = lookup_class(cls, path)
    if not entry:
        return interp.VERDICT_UNKNOWN
    verdict = entry.get("verdict")
    if verdict in (interp.VERDICT_FUSIBLE, interp.VERDICT_UNSAFE):
        return str(verdict)
    return interp.VERDICT_UNKNOWN
