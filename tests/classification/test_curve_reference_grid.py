"""Reference-parity sweep for the curve family's argument corners.

Breadth parity with /root/reference/tests/classification/test_{auroc,
average_precision,roc,precision_recall_curve}.py: multilabel AUROC, AUROC
max_fpr x input cases, AveragePrecision average modes, multiclass/multilabel
ROC and PRC list outputs — with the reference implementation as oracle
(sklearn ground-truths for these live in test_curves.py; this grid pins the
canonicalization and averaging corners).
"""
import numpy as np
import pytest

import jax.numpy as jnp

from metrics_tpu import AUROC, AveragePrecision, PrecisionRecallCurve, ROC
from metrics_tpu.functional import auroc as mt_auroc
from metrics_tpu.functional import average_precision as mt_average_precision
from metrics_tpu.functional import precision_recall_curve as mt_prc
from metrics_tpu.functional import roc as mt_roc
from tests.classification.inputs import (
    _input_binary_prob,
    _input_binary_prob_plausible,
    _input_multiclass_prob,
    _input_multidim_multiclass_prob,
    _input_multilabel_prob,
)
from tests.helpers.reference import assert_accumulated_parity, ref_oracle as _ref_oracle
from tests.helpers.testers import NUM_CLASSES, MetricTester

torch = pytest.importorskip("torch")


# ---------------------------------------------------------------------------
# AUROC: multilabel modes + max_fpr sweep + weighted/none averages
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("average", ["micro", "macro", "weighted"])
class TestAurocMultilabelReferenceGrid(MetricTester):
    atol = 1e-5

    def test_auroc_multilabel(self, average):
        fixture = _input_multilabel_prob
        args = {"num_classes": NUM_CLASSES, "average": average}
        self.run_class_metric_test(
            preds=fixture.preds,
            target=fixture.target,
            metric_class=AUROC,
            sk_metric=_ref_oracle("auroc", **args),
            metric_args=args,
            check_merge=False,  # cat-list state merge covered by capacity tests
            check_jit=False,
            check_batch=False,  # batch AUROC can be degenerate per batch
        )

    def test_auroc_multilabel_functional(self, average):
        fixture = _input_multilabel_prob
        args = {"num_classes": NUM_CLASSES, "average": average}
        self.run_functional_metric_test(
            preds=fixture.preds,
            target=fixture.target,
            metric_functional=mt_auroc,
            sk_metric=_ref_oracle("auroc", **args),
            metric_args=args,
            atol=1e-5,
        )


@pytest.mark.parametrize("max_fpr", [0.1, 0.5, 0.9, None])
@pytest.mark.parametrize(
    "fixture", [_input_binary_prob, _input_binary_prob_plausible], ids=["prob", "plausible"]
)
def test_auroc_max_fpr_reference_grid(max_fpr, fixture):
    args = {"max_fpr": max_fpr}
    assert_accumulated_parity(AUROC(**args), fixture, _ref_oracle("auroc", **args), atol=1e-5)


def test_auroc_multiclass_none_average_per_class():
    fixture = _input_multiclass_prob
    args = {"num_classes": NUM_CLASSES, "average": "none"}
    assert_accumulated_parity(
        AUROC(**args), fixture, _ref_oracle("auroc", num_classes=NUM_CLASSES, average=None), atol=1e-5
    )


# ---------------------------------------------------------------------------
# AveragePrecision: average modes over multiclass + mdmc
# ---------------------------------------------------------------------------


def test_average_precision_micro_multiclass_raises():
    """`micro` with label targets is rejected (reference average_precision.py
    raises the identical error)."""
    with pytest.raises(ValueError, match="Cannot use `micro` average with multi-class"):
        mt_average_precision(
            jnp.asarray(_input_multiclass_prob.preds[0]),
            jnp.asarray(_input_multiclass_prob.target[0]),
            num_classes=NUM_CLASSES,
            average="micro",
        )


@pytest.mark.parametrize("average", ["macro", "weighted", None])
@pytest.mark.parametrize(
    "fixture, nc",
    [(_input_multiclass_prob, NUM_CLASSES), (_input_multidim_multiclass_prob, NUM_CLASSES)],
    ids=["multiclass", "mdmc"],
)
def test_average_precision_averages_reference_grid(average, fixture, nc):
    args = {"num_classes": nc, "average": average}
    assert_accumulated_parity(
        AveragePrecision(**args), fixture, _ref_oracle("average_precision", **args), atol=1e-5
    )


# ---------------------------------------------------------------------------
# ROC / PRC: multiclass and multilabel list outputs
# ---------------------------------------------------------------------------


def _assert_curves_equal(got, want, atol=1e-5):
    assert len(got) == len(want)
    for g_arr, w_arr in zip(got, want):
        if isinstance(g_arr, list):
            _assert_curves_equal(g_arr, w_arr, atol=atol)
        else:
            np.testing.assert_allclose(np.asarray(g_arr), np.asarray(w_arr), atol=atol)


@pytest.mark.parametrize(
    "metric_class, functional, ref_name",
    [(ROC, mt_roc, "roc"), (PrecisionRecallCurve, mt_prc, "precision_recall_curve")],
    ids=["roc", "prc"],
)
def test_curve_multiclass_list_outputs(metric_class, functional, ref_name):
    fixture = _input_multiclass_prob
    args = {"num_classes": NUM_CLASSES}
    oracle = _ref_oracle(ref_name, **args)
    m = metric_class(**args)
    for i in range(fixture.preds.shape[0]):
        m.update(jnp.asarray(fixture.preds[i]), jnp.asarray(fixture.target[i]))
    want = oracle(
        fixture.preds.reshape(-1, NUM_CLASSES), fixture.target.reshape(-1)
    )
    _assert_curves_equal(list(m.compute()), list(want))

    got_fn = functional(
        jnp.asarray(fixture.preds[0]), jnp.asarray(fixture.target[0]), **args
    )
    want_fn = oracle(fixture.preds[0], fixture.target[0])
    _assert_curves_equal(list(got_fn), list(want_fn))


@pytest.mark.parametrize(
    "metric_class, ref_name",
    [(ROC, "roc"), (PrecisionRecallCurve, "precision_recall_curve")],
    ids=["roc", "prc"],
)
def test_curve_multilabel_list_outputs(metric_class, ref_name):
    fixture = _input_multilabel_prob
    args = {"num_classes": NUM_CLASSES}
    oracle = _ref_oracle(ref_name, **args)
    m = metric_class(**args)
    for i in range(fixture.preds.shape[0]):
        m.update(jnp.asarray(fixture.preds[i]), jnp.asarray(fixture.target[i]))
    want = oracle(
        fixture.preds.reshape(-1, NUM_CLASSES),
        fixture.target.reshape(-1, NUM_CLASSES),
    )
    _assert_curves_equal(list(m.compute()), list(want))


@pytest.mark.parametrize("pos_label", [0, 1])
def test_curve_binary_pos_label(pos_label):
    fixture = _input_binary_prob
    for metric_class, ref_name in ((ROC, "roc"), (PrecisionRecallCurve, "precision_recall_curve")):
        args = {"pos_label": pos_label}
        oracle = _ref_oracle(ref_name, **args)
        m = metric_class(**args)
        for i in range(fixture.preds.shape[0]):
            m.update(jnp.asarray(fixture.preds[i]), jnp.asarray(fixture.target[i]))
        want = oracle(fixture.preds.reshape(-1), fixture.target.reshape(-1))
        _assert_curves_equal(list(m.compute()), list(want))


def test_average_precision_pos_label_zero():
    fixture = _input_binary_prob
    args = {"pos_label": 0}
    oracle = _ref_oracle("average_precision", **args)
    assert_accumulated_parity(AveragePrecision(**args), fixture, oracle, atol=1e-5)
    want = oracle(fixture.preds.reshape(-1), fixture.target.reshape(-1))
    got_fn = mt_average_precision(
        jnp.asarray(fixture.preds.reshape(-1)), jnp.asarray(fixture.target.reshape(-1)), **args
    )
    np.testing.assert_allclose(np.asarray(got_fn), want, atol=1e-5)
