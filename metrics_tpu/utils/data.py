"""Array helpers: dim-zero reducers, one-hot, top-k selection, collection mapping.

Behavior parity with /root/reference/torchmetrics/utilities/data.py:24-253,
re-expressed in JAX. The dim-zero reducers are the per-state reduction
functions applied after a cross-process gather (``dist_reduce_fx``).
"""
from typing import Any, Callable, List, Mapping, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

METRIC_EPS = 1e-6


def dim_zero_cat(x: Union[Array, List[Array], Tuple[Array, ...]]) -> Array:
    """Concatenation along dim 0; accepts a single array or a (possibly nested) list."""
    if not isinstance(x, (list, tuple)):
        return jnp.asarray(x)
    x = [jnp.atleast_1d(jnp.asarray(el)) for el in x]
    if not x:
        raise ValueError("No samples to concatenate")
    return jnp.concatenate(x, axis=0)


def dim_zero_sum(x: Array) -> Array:
    return jnp.sum(jnp.asarray(x), axis=0)


def dim_zero_mean(x: Array) -> Array:
    return jnp.mean(jnp.asarray(x), axis=0)


def dim_zero_max(x: Array) -> Array:
    return jnp.max(jnp.asarray(x), axis=0)


def dim_zero_min(x: Array) -> Array:
    return jnp.min(jnp.asarray(x), axis=0)


def _flatten(x: Sequence) -> list:
    return [item for sublist in x for item in sublist]


def torch_to_numpy(t: Any) -> np.ndarray:
    """Convert a torch tensor (duck-typed: detach/cpu/numpy) to a numpy
    array; anything else goes through ``np.asarray``. Handles dtypes numpy
    cannot express (torch.bfloat16) by round-tripping through float32."""
    if hasattr(t, "detach") and hasattr(t, "cpu") and hasattr(t, "numpy"):
        detached = t.detach().cpu()
        try:
            return detached.numpy()
        except Exception:
            return detached.float().numpy()
    return np.asarray(t)


def to_onehot(label_tensor: Array, num_classes: Optional[int] = None) -> Array:
    """Convert integer labels ``(N, ...)`` to one-hot ``(N, C, ...)``.

    Parity with /root/reference/torchmetrics/utilities/data.py:70-101.
    """
    label_tensor = jnp.asarray(label_tensor)
    if label_tensor.ndim == 2 and jnp.issubdtype(label_tensor.dtype, jnp.floating):
        # already (N, C) probabilities/onehot
        return label_tensor
    if num_classes is None:
        num_classes = int(jnp.max(label_tensor)) + 1
    onehot = jax.nn.one_hot(label_tensor, num_classes, dtype=jnp.int32)
    # one_hot appends class dim last -> move to position 1
    return jnp.moveaxis(onehot, -1, 1)


def select_topk(prob_tensor: Array, topk: int = 1, dim: int = 1) -> Array:
    """Binary int mask selecting the ``topk`` highest entries along ``dim``.

    Parity with /root/reference/torchmetrics/utilities/data.py:104-132.
    """
    prob_tensor = jnp.asarray(prob_tensor)
    moved = jnp.moveaxis(prob_tensor, dim, -1)
    _, idx = jax.lax.top_k(moved, topk)
    mask = jnp.sum(jax.nn.one_hot(idx, moved.shape[-1], dtype=jnp.int32), axis=-2)
    mask = jnp.clip(mask, 0, 1)
    return jnp.moveaxis(mask, -1, dim).astype(jnp.int32)


def to_categorical(tensor: Array, argmax_dim: int = 1) -> Array:
    """Probabilities/logits -> integer labels by argmax.

    Parity with /root/reference/torchmetrics/utilities/data.py:135-155.
    """
    return jnp.argmax(jnp.asarray(tensor), axis=argmax_dim)


def apply_to_collection(
    data: Any,
    dtype: Union[type, tuple],
    function: Callable,
    *args: Any,
    wrong_dtype: Optional[Union[type, tuple]] = None,
    **kwargs: Any,
) -> Any:
    """Recursively apply ``function`` to all ``dtype`` elements of a collection.

    Parity with /root/reference/torchmetrics/utilities/data.py:179-226.
    """
    elem_type = type(data)
    if isinstance(data, dtype) and (wrong_dtype is None or not isinstance(data, wrong_dtype)):
        return function(data, *args, **kwargs)
    if isinstance(data, Mapping):
        return elem_type(
            {k: apply_to_collection(v, dtype, function, *args, wrong_dtype=wrong_dtype, **kwargs) for k, v in data.items()}
        )
    if isinstance(data, tuple) and hasattr(data, "_fields"):  # namedtuple
        return elem_type(*(apply_to_collection(d, dtype, function, *args, wrong_dtype=wrong_dtype, **kwargs) for d in data))
    if isinstance(data, Sequence) and not isinstance(data, str):
        return elem_type([apply_to_collection(d, dtype, function, *args, wrong_dtype=wrong_dtype, **kwargs) for d in data])
    return data


def get_group_indexes(indexes: Array) -> List[Array]:
    """Group positions by value; returns one index array per distinct group id.

    Contract parity with /root/reference/torchmetrics/utilities/data.py:229-253,
    but vectorized: the reference loops a Python dict over every element (a
    known hot spot, SURVEY.md §3.4); here one stable argsort + split does the
    grouping in O(N log N). Within each group, positions keep their original
    order (stable sort); groups are ordered by id rather than first
    appearance, which no consumer depends on (results are averaged).
    """
    indexes = np.asarray(indexes)
    order = np.argsort(indexes, kind="stable")
    boundaries = np.nonzero(np.diff(indexes[order]))[0] + 1
    return [jnp.asarray(g, dtype=jnp.int32) for g in np.split(order, boundaries)]


def _safe_divide(num: Array, denom: Array) -> Array:
    """Division that returns num/1 where denom == 0 (parity with reference
    /root/reference/torchmetrics/functional/classification/f_beta.py:24-27)."""
    denom = jnp.where(denom == 0, 1, denom)
    return num / denom


def _bincount(x: Array, minlength: int) -> Array:
    """Static-length bincount (jit-safe), routed through the ops kernel
    registry: the tiled one-hot MXU scatter kernel on TPU, ``jnp.bincount``
    elsewhere. The dispatch boundary also hardens the inputs — float
    indices raise, host-side negative indices raise, and device/traced
    negatives deterministically DROP instead of riding XLA scatter's
    silent clip-into-bin-0 semantics; see
    :func:`metrics_tpu.ops.bincount_dispatch`. ``x`` is passed through
    un-coerced so host-resident inputs keep their free validation. Lazy
    import: this module is imported by nearly every metric, ``ops`` only
    by its users."""
    from metrics_tpu.ops import bincount_dispatch

    return bincount_dispatch(x, minlength)


def stable_sort_with_payloads(
    key: Array, *payloads: Array, descending: bool = False
) -> Tuple[Array, ...]:
    """Stable sort of ``key`` along its MINOR axis, carrying ``payloads``
    through the permutation in the SAME ``lax.sort`` call.

    The TPU sort-layout convention shared by the rank/curve/retrieval
    kernels (one multi-operand sort instead of argsort + per-payload
    gathers — measured 3-6x faster on-chip, round 5): descending order is a
    key negation (identical permutation to ``argsort(-key, stable=True)``),
    and bool payloads ride as int32 (lax.sort operand dtype restriction)
    and come back as bool. Returns ``(sorted_key, *sorted_payloads)``.

    Dtype contract for ``descending=True``: the key must be floating or
    signed-integer — negation is meaningless for unsigned keys (wraps
    modulo 2**n) and raises here. Two data-dependent caveats negation
    cannot guard statically: a signed-int key containing ``INT_MIN``
    overflows (``-INT_MIN == INT_MIN``) and would sort first instead of
    last, and ``-0.0``/``+0.0`` float keys swap relative to a true
    descending comparator (they compare equal everywhere else, so only
    sign-bit-sensitive consumers would notice).
    """
    if descending and not (
        jnp.issubdtype(key.dtype, jnp.floating) or jnp.issubdtype(key.dtype, jnp.signedinteger)
    ):
        raise ValueError(
            "stable_sort_with_payloads(descending=True) requires a floating or"
            f" signed-integer key (negation-based descending order); got dtype {key.dtype}."
            " Cast unsigned/bool keys to a signed or floating dtype first."
        )
    work_key = -key if descending else key
    is_bool = [p.dtype == jnp.bool_ for p in payloads]
    ops = (work_key,) + tuple(
        p.astype(jnp.int32) if b else p for p, b in zip(payloads, is_bool)
    )
    out = jax.lax.sort(ops, dimension=key.ndim - 1, num_keys=1)
    sorted_key = -out[0] if descending else out[0]
    return (sorted_key,) + tuple(
        o.astype(jnp.bool_) if b else o for o, b in zip(out[1:], is_bool)
    )


def _squeeze_if_scalar(data: Any) -> Any:
    """Recursively squeeze single-element arrays to 0-d.

    Parity with /root/reference/torchmetrics/utilities/data.py:256-261.
    """

    def _sq(x: Array) -> Array:
        return x.reshape(()) if x.size == 1 else x

    return apply_to_collection(data, (jnp.ndarray,), _sq)
