"""Modular CohenKappa.

Behavior parity with /root/reference/torchmetrics/classification/cohen_kappa.py:23-110.
"""
from typing import Any, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.cohen_kappa import _cohen_kappa_compute, _cohen_kappa_update

Array = jax.Array


class CohenKappa(Metric):
    """Computes Cohen's kappa (inter-annotator agreement).

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([1, 1, 0, 0])
        >>> preds = jnp.array([0, 1, 0, 0])
        >>> cohenkappa = CohenKappa(num_classes=2)
        >>> cohenkappa(preds, target)
        Array(0.5, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True

    def __init__(
        self,
        num_classes: int,
        weights: Optional[str] = None,
        threshold: float = 0.5,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.weights = weights
        self.threshold = threshold

        allowed_weights = ("linear", "quadratic", "none", None)
        if weights not in allowed_weights:
            raise ValueError(f"Argument weights needs to one of the following: {allowed_weights}")

        self.add_state("confmat", default=jnp.zeros((num_classes, num_classes), dtype=jnp.int32), dist_reduce_fx="sum")

    def _update(self, preds: Array, target: Array) -> None:
        confmat = _cohen_kappa_update(preds, target, self.num_classes, self.threshold)
        self.confmat = self.confmat + confmat

    def _compute(self) -> Array:
        return _cohen_kappa_compute(self.confmat, None if self.weights == "none" else self.weights)
