"""Mean-error family vs sklearn oracles (MSE/MAE/MSLE/MAPE/SMAPE/Tweedie).

Mirrors /root/reference/tests/regression/test_mean_error.py in spirit.
"""
from functools import partial

import numpy as np
import pytest
from sklearn.metrics import (
    mean_absolute_error as sk_mae,
    mean_absolute_percentage_error as sk_mape,
    mean_squared_error as sk_mse,
    mean_squared_log_error as sk_msle,
    mean_tweedie_deviance as sk_tweedie,
)

from metrics_tpu.functional import (
    mean_absolute_error,
    mean_absolute_percentage_error,
    mean_squared_error,
    mean_squared_log_error,
    symmetric_mean_absolute_percentage_error,
    tweedie_deviance_score,
)
from metrics_tpu.regression import (
    MeanAbsoluteError,
    MeanAbsolutePercentageError,
    MeanSquaredError,
    MeanSquaredLogError,
    SymmetricMeanAbsolutePercentageError,
    TweedieDevianceScore,
)
from tests.helpers.testers import BATCH_SIZE, NUM_BATCHES, MetricTester

_rng = np.random.RandomState(42)
_preds = _rng.rand(NUM_BATCHES, BATCH_SIZE).astype(np.float32) + 0.1
_target = _rng.rand(NUM_BATCHES, BATCH_SIZE).astype(np.float32) + 0.1


def _sk_smape(preds, target):
    preds, target = np.asarray(preds, np.float64), np.asarray(target, np.float64)
    return np.mean(2 * np.abs(preds - target) / np.clip(np.abs(target) + np.abs(preds), 1.17e-06, None))


def _sk(fn, preds, target, **kw):
    return fn(np.asarray(target, np.float64), np.asarray(preds, np.float64), **kw)


@pytest.mark.parametrize(
    "metric_class, metric_functional, sk_metric, metric_args",
    [
        (MeanSquaredError, mean_squared_error, partial(_sk, sk_mse), {}),
        (
            MeanSquaredError,
            mean_squared_error,
            lambda p, t: np.sqrt(_sk(sk_mse, p, t)),
            {"squared": False},
        ),
        (MeanAbsoluteError, mean_absolute_error, partial(_sk, sk_mae), {}),
        (MeanSquaredLogError, mean_squared_log_error, partial(_sk, sk_msle), {}),
        (MeanAbsolutePercentageError, mean_absolute_percentage_error, partial(_sk, sk_mape), {}),
        (SymmetricMeanAbsolutePercentageError, symmetric_mean_absolute_percentage_error, _sk_smape, {}),
        (TweedieDevianceScore, tweedie_deviance_score, partial(_sk, sk_tweedie, power=0), {"power": 0}),
        (TweedieDevianceScore, tweedie_deviance_score, partial(_sk, sk_tweedie, power=1), {"power": 1}),
        (TweedieDevianceScore, tweedie_deviance_score, partial(_sk, sk_tweedie, power=1.5), {"power": 1.5}),
        (TweedieDevianceScore, tweedie_deviance_score, partial(_sk, sk_tweedie, power=2), {"power": 2}),
    ],
)
class TestMeanError(MetricTester):
    atol = 1e-5

    def test_mean_error_class(self, metric_class, metric_functional, sk_metric, metric_args):
        def sk_wrapped(preds, target):
            return sk_metric(preds, target)

        self.run_class_metric_test(
            preds=_preds,
            target=_target,
            metric_class=metric_class,
            sk_metric=sk_wrapped,
            metric_args=metric_args,
        )

    def test_mean_error_functional(self, metric_class, metric_functional, sk_metric, metric_args):
        self.run_functional_metric_test(
            _preds,
            _target,
            metric_functional=metric_functional,
            sk_metric=lambda p, t: sk_metric(p, t),
            metric_args=metric_args,
        )

    def test_mean_error_differentiability(self, metric_class, metric_functional, sk_metric, metric_args):
        self.run_differentiability_test(
            _preds, _target, metric_class=metric_class, metric_functional=metric_functional, metric_args=metric_args
        )


def test_tweedie_invalid_power():
    with pytest.raises(ValueError):
        TweedieDevianceScore(power=0.5)
    import jax.numpy as jnp

    with pytest.raises(ValueError):
        tweedie_deviance_score(jnp.array([1.0, 2.0]), jnp.array([1.0, 2.0]), power=0.5)
