"""Mean absolute error.

Behavior parity with /root/reference/torchmetrics/functional/regression/mae.py.
"""
from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _check_same_shape

Array = jax.Array


def _mean_absolute_error_update(preds: Array, target: Array) -> Tuple[Array, int]:
    _check_same_shape(preds, target)
    sum_abs_error = jnp.sum(jnp.abs(preds - target))
    return sum_abs_error, target.size


def _mean_absolute_error_compute(sum_abs_error: Array, n_obs: Array) -> Array:
    return sum_abs_error / n_obs


def mean_absolute_error(preds: Array, target: Array) -> Array:
    """Computes mean absolute error.

    Example:
        >>> import jax.numpy as jnp
        >>> x = jnp.array([0., 1., 2., 3.])
        >>> y = jnp.array([0., 1., 2., 1.])
        >>> mean_absolute_error(x, y)
        Array(0.5, dtype=float32)
    """
    sum_abs_error, n_obs = _mean_absolute_error_update(preds, target)
    return _mean_absolute_error_compute(sum_abs_error, n_obs)
