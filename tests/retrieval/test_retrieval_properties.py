"""Property-based fuzz: padded device retrieval kernels vs the host group
loop on GENERATED ragged layouts (singleton groups, empty-positive groups,
duplicate scores, interleaved ids)."""
import numpy as np
from hypothesis import given, settings, strategies as st

import jax.numpy as jnp

from metrics_tpu import RetrievalMAP, RetrievalNormalizedDCG, RetrievalPrecision

_settings = settings(max_examples=40, deadline=None)


@st.composite
def _ragged_queries(draw):
    n_groups = draw(st.integers(1, 12))
    rng = np.random.default_rng(draw(st.integers(0, 2**31)))
    sizes = [draw(st.integers(1, 9)) for _ in range(n_groups)]
    # interleave group ids (ids need not arrive grouped)
    idx = rng.permutation(np.repeat(np.arange(n_groups), sizes))
    n = len(idx)
    preds = np.round(rng.random(n) * draw(st.sampled_from([1, 4, 100]))) / 100
    target = (rng.random(n) < 0.4).astype(np.int32)
    return idx.astype(np.int64), preds.astype(np.float32), target


@given(_ragged_queries(), st.sampled_from(["neg", "pos", "skip"]))
@_settings
def test_padded_equals_host_loop_generated(data, action):
    idx, preds, target = data
    for cls, kwargs in [
        (RetrievalMAP, {}),
        (RetrievalNormalizedDCG, {"k": 3}),
        (RetrievalPrecision, {"k": 2}),
    ]:
        m = cls(empty_target_action=action, **kwargs)
        m.update(jnp.asarray(preds), jnp.asarray(target), indexes=jnp.asarray(idx))
        np.testing.assert_allclose(
            np.asarray(m._compute()), np.asarray(m._compute_host_loop()), atol=1e-6,
            err_msg=f"{cls.__name__} action={action}",
        )
