"""metrics_tpu — a TPU-native (JAX/XLA) machine-learning metrics framework.

Capability parity target: TorchMetrics v0.8.0dev (/root/reference). Exports
grow as domains land; see SURVEY.md §2.8 for the full target inventory.
"""
import logging

_logger = logging.getLogger("metrics_tpu")
_logger.addHandler(logging.StreamHandler())
_logger.setLevel(logging.INFO)

__version__ = "0.20.0"

from metrics_tpu.core.metric import CompositionalMetric, Metric  # noqa: E402
from metrics_tpu.classification import (  # noqa: E402
    AUC,
    AUROC,
    Accuracy,
    AveragePrecision,
    BinnedAveragePrecision,
    BinnedPrecisionRecallCurve,
    BinnedRecallAtFixedPrecision,
    CalibrationError,
    CohenKappa,
    ConfusionMatrix,
    F1Score,
    FBetaScore,
    HammingDistance,
    HingeLoss,
    JaccardIndex,
    KLDivergence,
    MatthewsCorrCoef,
    Precision,
    PrecisionRecallCurve,
    ROC,
    Recall,
    Specificity,
    StatScores,
)
from metrics_tpu.aggregation import (  # noqa: E402
    CatMetric,
    MaxMetric,
    MeanMetric,
    MinMetric,
    SumMetric,
)
from metrics_tpu.collections import MetricCollection  # noqa: E402
from metrics_tpu.wrappers import (  # noqa: E402
    BootStrapper,
    ClasswiseWrapper,
    MetricTracker,
    MinMaxMetric,
    MultioutputWrapper,
)
from metrics_tpu.image import (  # noqa: E402
    MultiScaleStructuralSimilarityIndexMeasure,
    PeakSignalNoiseRatio,
    StructuralSimilarityIndexMeasure,
    UniversalImageQualityIndex,
)
from metrics_tpu.retrieval import (  # noqa: E402
    RetrievalFallOut,
    RetrievalHitRate,
    RetrievalMAP,
    RetrievalMRR,
    RetrievalNormalizedDCG,
    RetrievalPrecision,
    RetrievalRPrecision,
    RetrievalRecall,
)
from metrics_tpu.regression import (  # noqa: E402
    CosineSimilarity,
    ExplainedVariance,
    MeanAbsoluteError,
    MeanAbsolutePercentageError,
    MeanSquaredError,
    MeanSquaredLogError,
    PearsonCorrCoef,
    R2Score,
    SpearmanCorrCoef,
    SymmetricMeanAbsolutePercentageError,
    TweedieDevianceScore,
)
from metrics_tpu.audio import (  # noqa: E402
    PermutationInvariantTraining,
    ScaleInvariantSignalDistortionRatio,
    ScaleInvariantSignalNoiseRatio,
    SignalDistortionRatio,
    SignalNoiseRatio,
)
from metrics_tpu.text import (  # noqa: E402
    BLEUScore,
    CharErrorRate,
    CHRFScore,
    ExtendedEditDistance,
    MatchErrorRate,
    ROUGEScore,
    SacreBLEUScore,
    SQuAD,
    TranslationEditRate,
    WordErrorRate,
    WordInfoLost,
    WordInfoPreserved,
)
from metrics_tpu.observability import MetricRecorder, get_recorder  # noqa: E402
from metrics_tpu.sliced import SlicedMetric  # noqa: E402
from metrics_tpu.windowed import WindowedMetric  # noqa: E402
from metrics_tpu import sketches  # noqa: E402  (fixed-capacity streaming sketch states)

__all__ = [
    "Accuracy",
    "AUC",
    "AUROC",
    "AveragePrecision",
    "BinnedAveragePrecision",
    "BinnedPrecisionRecallCurve",
    "BinnedRecallAtFixedPrecision",
    "BLEUScore",
    "BootStrapper",
    "CalibrationError",
    "CatMetric",
    "CharErrorRate",
    "CHRFScore",
    "ClasswiseWrapper",
    "CohenKappa",
    "CompositionalMetric",
    "ConfusionMatrix",
    "CosineSimilarity",
    "ExplainedVariance",
    "ExtendedEditDistance",
    "functional",
    "F1Score",
    "FBetaScore",
    "HammingDistance",
    "HingeLoss",
    "JaccardIndex",
    "KLDivergence",
    "MatchErrorRate",
    "MatthewsCorrCoef",
    "MaxMetric",
    "MeanAbsoluteError",
    "MeanAbsolutePercentageError",
    "MeanMetric",
    "MeanSquaredError",
    "MeanSquaredLogError",
    "Metric",
    "MetricCollection",
    "MetricRecorder",
    "MetricTracker",
    "get_recorder",
    "MinMaxMetric",
    "MinMetric",
    "MultioutputWrapper",
    "MultiScaleStructuralSimilarityIndexMeasure",
    "PeakSignalNoiseRatio",
    "PearsonCorrCoef",
    "PermutationInvariantTraining",
    "Precision",
    "PrecisionRecallCurve",
    "R2Score",
    "Recall",
    "RetrievalFallOut",
    "RetrievalHitRate",
    "RetrievalMAP",
    "RetrievalMRR",
    "RetrievalNormalizedDCG",
    "RetrievalPrecision",
    "RetrievalRecall",
    "RetrievalRPrecision",
    "ROC",
    "ROUGEScore",
    "SacreBLEUScore",
    "ScaleInvariantSignalDistortionRatio",
    "ScaleInvariantSignalNoiseRatio",
    "SignalDistortionRatio",
    "SignalNoiseRatio",
    "SlicedMetric",
    "WindowedMetric",
    "SpearmanCorrCoef",
    "Specificity",
    "SQuAD",
    "StatScores",
    "StructuralSimilarityIndexMeasure",
    "SumMetric",
    "SymmetricMeanAbsolutePercentageError",
    "TranslationEditRate",
    "TweedieDevianceScore",
    "UniversalImageQualityIndex",
    "WordErrorRate",
    "WordInfoLost",
    "WordInfoPreserved",
]
