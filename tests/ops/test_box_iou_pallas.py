"""Pallas box-IoU tile kernels vs the jnp broadcast implementation.

Runs the REAL kernel bodies in Pallas interpret mode on CPU; the
``test_compiled_*`` cases run the COMPILED kernels and only execute on a
real TPU backend: ``METRICS_TPU_TEST_ON_TPU=1 pytest tests/ops/`` (the
env var disables the conftest's forced-CPU setup — without it the suite
pins JAX to CPU and these cases skip). The batched unit kernel is the one
the detection matching kernel dispatches to
(functional/detection/mean_ap.py:84).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from metrics_tpu.functional.detection.box_ops import box_iou
from metrics_tpu.ops import box_iou_dispatch, box_iou_tiled
from metrics_tpu.ops.box_iou_pallas import box_iou_batched_tiled


def _boxes(rng, n):
    x1 = rng.uniform(0, 500, n)
    y1 = rng.uniform(0, 500, n)
    w = rng.uniform(1, 200, n)
    h = rng.uniform(1, 200, n)
    return np.stack([x1, y1, x1 + w, y1 + h], 1).astype(np.float32)


@pytest.mark.parametrize("n,m", [(1, 1), (7, 13), (128, 128), (130, 257), (300, 40)])
def test_tiled_matches_jnp(n, m):
    rng = np.random.default_rng(n * 1000 + m)
    b1, b2 = _boxes(rng, n), _boxes(rng, m)
    got = np.asarray(box_iou_tiled(jnp.asarray(b1), jnp.asarray(b2), interpret=True))
    want = np.asarray(box_iou(b1, b2))
    assert got.shape == (n, m)
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_tiled_identity_diagonal():
    rng = np.random.default_rng(0)
    b = _boxes(rng, 50)
    got = np.asarray(box_iou_tiled(jnp.asarray(b), jnp.asarray(b), interpret=True))
    np.testing.assert_allclose(np.diag(got), 1.0, atol=1e-6)


def test_degenerate_boxes_zero_not_nan():
    b1 = jnp.asarray([[0.0, 0.0, 0.0, 0.0], [0.0, 0.0, 10.0, 10.0]])
    b2 = jnp.asarray([[0.0, 0.0, 0.0, 0.0]])
    got = np.asarray(box_iou_tiled(b1, b2, interpret=True))
    assert np.all(np.isfinite(got))
    np.testing.assert_allclose(got, 0.0)


def test_dispatch_falls_back_off_tpu():
    rng = np.random.default_rng(1)
    b1, b2 = _boxes(rng, 20), _boxes(rng, 30)
    got = np.asarray(box_iou_dispatch(jnp.asarray(b1), jnp.asarray(b2)))
    np.testing.assert_allclose(got, np.asarray(box_iou(b1, b2)), atol=1e-6)


def _batched_boxes(rng, u, n):
    return np.stack([_boxes(rng, n) for _ in range(u)]).astype(np.float32)


@pytest.mark.parametrize("u,d,g", [(1, 1, 1), (3, 9, 5), (4, 128, 32), (2, 130, 140)])
def test_batched_tiled_matches_jnp(u, d, g):
    """The unit-grid kernel (the mAP matching kernel's dispatch target)
    matches the batched jnp broadcast, odd shapes and padding included."""
    rng = np.random.default_rng(u * 7 + d + g)
    b1, b2 = _batched_boxes(rng, u, d), _batched_boxes(rng, u, g)
    got = np.asarray(box_iou_batched_tiled(jnp.asarray(b1), jnp.asarray(b2), interpret=True))
    want = np.asarray(box_iou(jnp.asarray(b1), jnp.asarray(b2)))
    assert got.shape == (u, d, g)
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_batched_degenerate_zero_not_nan():
    b1 = jnp.zeros((2, 3, 4))
    b2 = jnp.zeros((2, 5, 4))
    got = np.asarray(box_iou_batched_tiled(b1, b2, interpret=True))
    assert np.all(np.isfinite(got))
    np.testing.assert_allclose(got, 0.0)


_ON_TPU = jax.default_backend() == "tpu"


@pytest.mark.skipif(not _ON_TPU, reason="compiled Pallas path needs a real TPU backend")
def test_compiled_tiled_on_tpu():
    rng = np.random.default_rng(2)
    b1, b2 = _boxes(rng, 200), _boxes(rng, 150)
    got = np.asarray(box_iou_tiled(jnp.asarray(b1), jnp.asarray(b2)))  # compiled
    np.testing.assert_allclose(got, np.asarray(box_iou(b1, b2)), atol=1e-5)


@pytest.mark.skipif(not _ON_TPU, reason="compiled Pallas path needs a real TPU backend")
def test_compiled_batched_on_tpu():
    rng = np.random.default_rng(3)
    b1, b2 = _batched_boxes(rng, 64, 100), _batched_boxes(rng, 64, 33)
    got = np.asarray(box_iou_batched_tiled(jnp.asarray(b1), jnp.asarray(b2)))  # compiled
    want = np.asarray(box_iou(jnp.asarray(b1), jnp.asarray(b2)))
    np.testing.assert_allclose(got, want, atol=1e-5)
    # the dispatch picks the compiled kernel at this density and agrees
    big1 = jnp.asarray(np.concatenate([b1] * 8))
    big2 = jnp.asarray(np.concatenate([b2] * 8))
    via_dispatch = np.asarray(box_iou_dispatch(big1, big2, min_elems=1))
    np.testing.assert_allclose(
        via_dispatch, np.asarray(box_iou(big1, big2)), atol=1e-5
    )


def test_no_pallas_env_forces_jnp_fallback(monkeypatch):
    """The METRICS_TPU_NO_PALLAS kill switch: on a (fake) TPU backend at a
    density the route would send to the tile kernel, the env var must force
    the jnp fallback — on CPU an attempted real pallas_call would crash, so
    a correct result proves the routing (same proof shape as the f64
    test)."""
    from metrics_tpu.ops import NO_PALLAS_ENV

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setenv(NO_PALLAS_ENV, "1")
    rng = np.random.default_rng(5)
    b1, b2 = _boxes(rng, 64), _boxes(rng, 48)
    got = box_iou_dispatch(jnp.asarray(b1), jnp.asarray(b2), min_elems=1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(box_iou(b1, b2)), atol=1e-6)


def test_registry_reroute_keeps_interpret_parity():
    """box_iou through the shared registry's interpret mode agrees with the
    jnp broadcast — the re-route must not change the kernel the dispatch
    reaches."""
    from metrics_tpu import ops

    rng = np.random.default_rng(6)
    b1, b2 = _boxes(rng, 40), _boxes(rng, 70)
    with ops.forced_backend("interpret"):
        got = box_iou_dispatch(jnp.asarray(b1), jnp.asarray(b2))
    np.testing.assert_allclose(np.asarray(got), np.asarray(box_iou(b1, b2)), atol=1e-5)


def test_dispatch_routes_float64_to_jnp_fallback(monkeypatch):
    """Under x64, float64 boxes must take the jnp fallback on BOTH dispatch
    shapes — the Pallas kernels compute in f32 and would silently downgrade
    precision (ADVICE round 5). The fake-TPU backend proves the routing: if
    the f64 guard were missing, the dispatch would attempt a real TPU
    pallas_call on CPU and crash."""
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    jax.config.update("jax_enable_x64", True)
    try:
        rng = np.random.default_rng(7)
        b1 = jnp.asarray(_boxes(rng, 16), jnp.float64)
        b2 = jnp.asarray(_boxes(rng, 8), jnp.float64)
        got = box_iou_dispatch(b1, b2, min_elems=1)  # 2-D path, above threshold
        assert got.dtype == jnp.float64
        np.testing.assert_allclose(np.asarray(got), np.asarray(box_iou(b1, b2)))

        bb1 = jnp.asarray(_batched_boxes(rng, 4, 16), jnp.float64)
        bb2 = jnp.asarray(_batched_boxes(rng, 4, 64), jnp.float64)
        got_b = box_iou_dispatch(bb1, bb2, min_elems=1)  # batched path
        assert got_b.dtype == jnp.float64
        np.testing.assert_allclose(np.asarray(got_b), np.asarray(box_iou(bb1, bb2)))
    finally:
        jax.config.update("jax_enable_x64", False)
