"""Sketch-backed streaming mode for the curve metric classes.

The DEFAULT mode of AUROC / ROC / PrecisionRecallCurve / AveragePrecision:
instead of appending unbounded cat-lists, canonicalized batches stream into
one packed quantile-sketch leaf (``metrics_tpu/sketches/quantile.py``) —
O(capacity) memory, fixed-shape jit-safe update (so the metric fuses,
buckets via the ``n_valid`` pad-mask contract, and rides the async
pipeline), and a ``"merge"`` reducer that syncs across ranks in the
existing collective round.

Row layouts (column 0 is always the weight):

* binary:       ``[capacity, 3]``       — (w, score, y)
* per-class:    ``[capacity, 2 + 2C]``  — (w, max-score key, C scores,
  C one-hot/indicator columns)

Targets are stored as (possibly fractional, post-compaction) positive-class
indicator mass: pair collapse preserves every weighted TP/FP functional
exactly, so only score displacement inside a collapsed pair — the quantile
sketch's bounded rank error — degrades the curves.

**Lossless window / bit parity.** Until the first compaction
(``fill == n_seen``) the sketch holds the exact canonicalized stream in
arrival order; compute reconstructs the arrays and runs the SAME unbounded
kernels as ``exact=True``, reproducing yesterday's default bit-for-bit.
Past capacity the weighted kernels (``functional/classification/
sketch_curve.py``) take over under the advertised rank-error envelope.

``__exact_mode_attr__ = "_exact"`` declares the mode split to the
tracelint abstract interpreter: the class-level verdict describes THIS
default mode; ``exact=True`` instances register list states through
``sketches.compat`` and flip instance-level ``__jit_unsafe__``, which the
fused path's structural check guards before any manifest lookup.
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.core.readers import ReaderCache, round_up_bucket
from metrics_tpu.sketches.quantile import (
    qsketch_fill,
    qsketch_init,
    qsketch_insert,
    sketch_merge_fx,
)
from metrics_tpu.utils.exceptions import MetricsUserError

try:
    from metrics_tpu.utils.checks import _is_concrete
except ImportError:  # pragma: no cover
    def _is_concrete(*arrays):
        return True

Array = jax.Array

#: default quantile-sketch capacity for the curve family — 3 float32
#: columns at 8192 rows is ~96 KiB (binary case) for <0.05% relative rank
#: error, and every stream that fits stays bit-exact
DEFAULT_SKETCH_CAPACITY = 8192


class SketchCurveMixin:
    """Adds the sketch-backed default mode. Call ``_init_sketch_curve`` in
    ``__init__`` for the default (non-exact, non-capacity) configuration;
    guard ``_update``/``_compute`` with ``self._sketch_capacity``."""

    _sketch_capacity: Optional[int] = None
    _sketch_cols: Optional[int] = None  # None = binary; C = per-class rows
    _sketch_tgt_kind: Optional[str] = None  # "int" (one-hot) | "indicator"
    _exact: bool = False
    _shape_stable_reads: bool = False

    def _init_sketch_curve(
        self,
        sketch_capacity: int,
        num_classes: Optional[int],
        shape_stable_reads: bool = False,
    ) -> None:
        if not (isinstance(sketch_capacity, int) and sketch_capacity > 0):
            raise ValueError(
                f"Argument `sketch_capacity` must be a positive int, got {sketch_capacity}"
            )
        self._sketch_capacity = sketch_capacity
        self._shape_stable_reads = bool(shape_stable_reads)
        # AOT reader cache for the weighted compute path (one pre-lowered
        # executable per shape bucket — see core/readers.py)
        self._readers = ReaderCache()
        self._sketch_cols = num_classes if (num_classes is not None and num_classes >= 2) else None
        payload = 1 if self._sketch_cols is None else 2 * self._sketch_cols
        self.add_state(
            "csketch",
            default=qsketch_init(sketch_capacity, payload_cols=payload),
            dist_reduce_fx=sketch_merge_fx(),
        )
        self.add_state("n_seen", default=jnp.asarray(0, jnp.int32), dist_reduce_fx="sum")

    _sketch_case_locked: bool = False

    def _rebuild_sketch_case(self, num_cols: Optional[int]) -> None:
        """Re-register the sketch for the case the first batch actually has
        (mirrors the unbounded path's first-update mode inference). Only
        legal before any row landed: the host-side case lock (set by the
        first successful insert) raises the unbounded path's mode-change
        error afterwards, and a concretely non-empty sketch (e.g. restored
        from a checkpoint) refuses too."""
        if self._sketch_case_locked:
            raise ValueError(
                "The mode of data (binary, multi-label, multi-class) should be constant,"
                " but changed between batches"
            )
        fill = qsketch_fill(self.csketch)
        if _is_concrete(fill) and int(fill) > 0:
            raise ValueError(
                "The mode of data (binary, multi-label, multi-class) should be constant,"
                " but changed between batches"
            )
        self._sketch_cols = num_cols
        self._sketch_tgt_kind = None
        payload = 1 if num_cols is None else 2 * num_cols
        self.add_state(
            "csketch",
            default=qsketch_init(self._sketch_capacity, payload_cols=payload),
            dist_reduce_fx=sketch_merge_fx(),
        )

    # ------------------------------------------------------------------
    # update
    # ------------------------------------------------------------------
    def _sketch_insert_canonical(
        self,
        preds: Array,
        target: Array,
        pos_label: Optional[int],
        n_valid: Optional[Array] = None,
    ) -> None:
        """Insert one canonicalized batch (the `_*_update` kernel outputs:
        flat binary scores + integer targets, or ``[N, C]`` score rows with
        integer labels / indicator rows)."""
        preds = jnp.asarray(preds)
        target = jnp.asarray(target)
        if preds.ndim == 1:
            if self._sketch_cols is not None:
                self._rebuild_sketch_case(None)
            pl = 1 if pos_label is None else pos_label
            y = (target == pl).astype(jnp.float32)
            self.csketch = qsketch_insert(
                self.csketch, preds, payload=y[:, None], n_valid=n_valid
            )
        else:
            c = preds.shape[1]
            if self._sketch_cols != c:
                self._rebuild_sketch_case(c)
            if target.ndim == 1:
                tgt_kind = "int"
                ytab = (
                    target[:, None] == jnp.arange(c, dtype=target.dtype)[None, :]
                ).astype(jnp.float32)
            else:
                tgt_kind = "indicator"
                ytab = target.astype(jnp.float32)
            if self._sketch_tgt_kind is not None and self._sketch_tgt_kind != tgt_kind:
                raise ValueError(
                    "The mode of data (binary, multi-label, multi-class) should be"
                    " constant, but changed between batches"
                )
            self._sketch_tgt_kind = tgt_kind
            key = jnp.max(preds.astype(jnp.float32), axis=1)
            payload = jnp.concatenate([preds.astype(jnp.float32), ytab], axis=1)
            self.csketch = qsketch_insert(self.csketch, key, payload=payload, n_valid=n_valid)
        self.n_seen = self.n_seen + preds.shape[0]
        # host-side case lock: later batches of a DIFFERENT case raise the
        # mode-change error even where the fill count is not concretely
        # readable (inside jit)
        self._sketch_case_locked = True

    # ------------------------------------------------------------------
    # compute-side views (host only — the readbacks the update path never pays)
    # ------------------------------------------------------------------
    def _sketch_is_lossless(self) -> bool:
        """No compaction has ever dropped a row: the sketch IS the stream
        (weights 1, arrival order), so the exact kernels apply bit-for-bit."""
        fill = qsketch_fill(self.csketch)
        n_seen = jnp.asarray(self.n_seen)
        if not _is_concrete(fill, n_seen):
            raise MetricsUserError(
                "sketch-backed curve compute reads the occupancy on the host and cannot"
                " run under jit; compute eagerly (update_state/FusedUpdate remain jit-safe)"
            )
        return int(fill) == int(n_seen)

    def _sketch_reads_exact(self) -> bool:
        """Should this read take the lossless exact-kernel path?  Yes inside
        the lossless window — unless ``shape_stable_reads`` is on, in which
        case only the EMPTY sketch keeps today's empty-stream behavior and
        every non-empty read rides the fixed-shape weighted kernels instead.

        ``shape_stable_reads=True`` is the serving/poll-path trade: the
        exact kernels have data-dependent output shapes (they cannot be
        bucketed or jitted), so each new fill count re-traces every eager
        curve op — ~1s per read on a growing stream.  The weighted kernels
        see O(log capacity) bucketed shapes total, at the cost of giving up
        the lossless window's bit-parity with ``exact=True`` (unit-weight
        rows keep the result within float-accumulation distance; past the
        window the two paths coincide anyway)."""
        if not self._sketch_is_lossless():
            return False
        if not self._shape_stable_reads:
            return True
        return int(jnp.asarray(self.n_seen)) == 0

    def _sketch_rows(self):
        """Occupied rows as ``(w, key, payload)`` host-sliced arrays."""
        leaf = jnp.asarray(self.csketch)
        n = int(qsketch_fill(leaf))
        rows = leaf[:n]
        return rows[:, 0], rows[:, 1], rows[:, 2:]

    def _sketch_exact_arrays(self):
        """Reconstruct the canonicalized stream inside the lossless window:
        ``(preds, target, pos_label_for_compute)`` exactly as the unbounded
        path would have accumulated them (targets come back as the stored
        indicators, so the positive class is 1 by construction)."""
        _, key, payload = self._sketch_rows()
        if self._sketch_cols is None:
            return key, payload[:, 0].astype(jnp.int32), 1
        c = self._sketch_cols
        scores = payload[:, :c]
        ytab = payload[:, c:]
        if self._sketch_tgt_kind == "indicator":
            return scores, ytab.astype(jnp.int32), 1
        return scores, jnp.argmax(ytab, axis=1).astype(jnp.int32), None

    def _sketch_weighted_arrays(self):
        """Post-compaction view: ``(scores, y, w)`` with y the (possibly
        fractional) per-row positive mass; per-class case returns
        ``([n, C] scores, [n, C] y, [n] w)``.

        Rows are padded up to a shape BUCKET with zero-weight rows (the
        sketch packs occupied rows first, so the tail past the fill count
        is already ``w == 0``): the weighted kernels sort invalid rows
        last and weight every cumulant, so pad rows are no-ops by design —
        and the downstream jitted kernels see O(log capacity) distinct
        shapes instead of one retrace per fill count. The LOSSLESS path
        (:meth:`_sketch_exact_arrays`) stays exact-sliced: it feeds the
        unbounded exact kernels whose bit-parity is pinned per shape."""
        leaf = jnp.asarray(self.csketch)
        n = int(qsketch_fill(leaf))
        b = round_up_bucket(max(n, 1), leaf.shape[0])
        rows = leaf[:b]
        w, key, payload = rows[:, 0], rows[:, 1], rows[:, 2:]
        if self._sketch_cols is None:
            return key, payload[:, 0], w
        c = self._sketch_cols
        return payload[:, :c], payload[:, c:], w
