"""Input-deduction matrix for ``_input_format_classification``.

Coverage parity with /root/reference/tests/classification/test_inputs.py:
the "usual cases" grid (deduced case + exact canonical preds/target for every
input style, including the multiclass-flag overrides in both directions and
batch_size=1), threshold semantics, and the incorrect-input / incorrect-top_k
rejection matrices.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from tests.classification.inputs import (
    Input,
    _input_binary as _bin,
    _input_binary_prob as _bin_prob,
    _input_multiclass as _mc,
    _input_multiclass_prob as _mc_prob,
    _input_multidim_multiclass as _mdmc,
    _input_multidim_multiclass_prob as _mdmc_prob,
    _input_multilabel as _ml,
    _input_multilabel_multidim as _mlmd,
    _input_multilabel_multidim_prob as _mlmd_prob,
    _input_multilabel_prob as _ml_prob,
)
from tests.helpers.testers import BATCH_SIZE, EXTRA_DIM, NUM_BATCHES, NUM_CLASSES, THRESHOLD
from metrics_tpu.utils.checks import _input_format_classification
from metrics_tpu.utils.data import select_topk, to_onehot
from metrics_tpu.utils.enums import DataType

_rng = np.random.default_rng(42)

# Additional special-case fixtures (reference test_inputs.py:38-54)
_ml_prob_half = Input(_ml_prob.preds.astype(np.float16), _ml_prob.target)

_mc_prob_2cls_preds = _rng.random((NUM_BATCHES, BATCH_SIZE, 2)).astype(np.float32)
_mc_prob_2cls_preds /= _mc_prob_2cls_preds.sum(axis=2, keepdims=True)
_mc_prob_2cls = Input(_mc_prob_2cls_preds, _rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE)))

_mdmc_prob_many_dims_preds = _rng.random(
    (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES, EXTRA_DIM, EXTRA_DIM)
).astype(np.float32)
_mdmc_prob_many_dims_preds /= _mdmc_prob_many_dims_preds.sum(axis=2, keepdims=True)
_mdmc_prob_many_dims = Input(
    _mdmc_prob_many_dims_preds,
    _rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE, EXTRA_DIM, EXTRA_DIM)),
)

_mdmc_prob_2cls_preds = _rng.random((NUM_BATCHES, BATCH_SIZE, 2, EXTRA_DIM)).astype(np.float32)
_mdmc_prob_2cls_preds /= _mdmc_prob_2cls_preds.sum(axis=2, keepdims=True)
_mdmc_prob_2cls = Input(_mdmc_prob_2cls_preds, _rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE, EXTRA_DIM)))


# Post-transformation helpers (reference test_inputs.py:59-121)
def _idn(x):
    return jnp.asarray(x)


def _usq(x):
    return jnp.expand_dims(jnp.asarray(x), -1)


def _thrs(x):
    return jnp.asarray(x) >= THRESHOLD


def _rshp1(x):
    x = jnp.asarray(x)
    return x.reshape(x.shape[0], -1)


def _rshp2(x):
    x = jnp.asarray(x)
    return x.reshape(x.shape[0], x.shape[1], -1)


def _onehot(x):
    return to_onehot(jnp.asarray(x).astype(jnp.int32), NUM_CLASSES)


def _onehot2(x):
    return to_onehot(jnp.asarray(x).astype(jnp.int32), 2)


def _top1(x):
    return select_topk(jnp.asarray(x), 1)


def _top2(x):
    return select_topk(jnp.asarray(x), 2)


def _ml_preds_tr(x):
    return _rshp1(_thrs(x))


def _onehot_rshp1(x):
    return _onehot(_rshp1(x))


def _onehot2_rshp1(x):
    return _onehot2(_rshp1(x))


def _top1_rshp2(x):
    return _top1(_rshp2(x))


def _top2_rshp2(x):
    return _top2(_rshp2(x))


def _probs_to_mc_preds_tr(x):
    return _onehot2(_thrs(x))


def _mlmd_prob_to_mc_preds_tr(x):
    return _onehot2(_rshp1(_thrs(x)))


@pytest.mark.parametrize(
    "inputs, num_classes, multiclass, top_k, exp_mode, post_preds, post_target",
    [
        # usual expected cases (reference test_inputs.py:130-148)
        (_bin, None, False, None, "multi-class", _usq, _usq),
        (_bin, 1, False, None, "multi-class", _usq, _usq),
        (_bin_prob, None, None, None, "binary", lambda x: _usq(_thrs(x)), _usq),
        (_ml_prob, None, None, None, "multi-label", _thrs, _idn),
        (_ml, None, False, None, "multi-dim multi-class", _idn, _idn),
        (_ml_prob, None, None, None, "multi-label", _ml_preds_tr, _rshp1),
        (_ml_prob, None, None, 2, "multi-label", _top2, _rshp1),
        (_mlmd, None, False, None, "multi-dim multi-class", _rshp1, _rshp1),
        (_mc, NUM_CLASSES, None, None, "multi-class", _onehot, _onehot),
        (_mc_prob, None, None, None, "multi-class", _top1, _onehot),
        (_mc_prob, None, None, 2, "multi-class", _top2, _onehot),
        (_mdmc, NUM_CLASSES, None, None, "multi-dim multi-class", _onehot, _onehot),
        (_mdmc_prob, None, None, None, "multi-dim multi-class", _top1_rshp2, _onehot),
        (_mdmc_prob, None, None, 2, "multi-dim multi-class", _top2_rshp2, _onehot),
        (_mdmc_prob_many_dims, None, None, None, "multi-dim multi-class", _top1_rshp2, _onehot_rshp1),
        (_mdmc_prob_many_dims, None, None, 2, "multi-dim multi-class", _top2_rshp2, _onehot_rshp1),
        # special cases (reference test_inputs.py:151-170)
        # half precision converts to full precision
        (_ml_prob_half, None, None, None, "multi-label", lambda x: _ml_preds_tr(np.asarray(x, np.float32)), _rshp1),
        # binary as multiclass
        (_bin, None, None, None, "multi-class", _onehot2, _onehot2),
        # binary probs as multiclass
        (_bin_prob, None, True, None, "binary", _probs_to_mc_preds_tr, _onehot2),
        # multilabel as multiclass
        (_ml, None, True, None, "multi-dim multi-class", _onehot2, _onehot2),
        # multilabel probs as multiclass
        (_ml_prob, None, True, None, "multi-label", _probs_to_mc_preds_tr, _onehot2),
        # multidim multilabel as multiclass
        (_mlmd, None, True, None, "multi-dim multi-class", _onehot2_rshp1, _onehot2_rshp1),
        # multidim multilabel probs as multiclass
        (_mlmd_prob, None, True, None, "multi-label", _mlmd_prob_to_mc_preds_tr, _onehot2_rshp1),
        # multiclass probs with 2 classes as binary
        (_mc_prob_2cls, None, False, None, "multi-class", lambda x: _top1(x)[:, [1]], _usq),
        # multidim multiclass probs with 2 classes as multilabel
        (_mdmc_prob_2cls, None, False, None, "multi-dim multi-class", lambda x: _top1(x)[:, 1], _idn),
    ],
)
def test_usual_cases(inputs, num_classes, multiclass, top_k, exp_mode, post_preds, post_target):
    for mode_probe in (exp_mode, DataType(exp_mode)):
        for batch_slice in (np.s_[:], np.s_[[0], ...]):
            preds_in = inputs.preds[0][batch_slice]
            target_in = inputs.target[0][batch_slice]
            preds_out, target_out, mode = _input_format_classification(
                preds=jnp.asarray(preds_in),
                target=jnp.asarray(target_in),
                threshold=THRESHOLD,
                num_classes=num_classes,
                multiclass=multiclass,
                top_k=top_k,
            )
            assert mode == mode_probe
            np.testing.assert_array_equal(
                np.asarray(preds_out), np.asarray(post_preds(preds_in)).astype(np.int32)
            )
            np.testing.assert_array_equal(
                np.asarray(target_out), np.asarray(post_target(target_in)).astype(np.int32)
            )


def test_threshold():
    target = jnp.asarray([1, 1, 1], dtype=jnp.int32)
    preds_probs = jnp.asarray([0.5 - 1e-5, 0.5, 0.5 + 1e-5])
    preds_out, _, _ = _input_format_classification(preds_probs, target, threshold=0.5)
    np.testing.assert_array_equal(np.asarray(preds_out).squeeze(), [0, 1, 1])


def _ri(*shape, low=0, high=2):
    return jnp.asarray(_rng.integers(low, high, shape))


def _rf(*shape):
    return jnp.asarray(_rng.random(shape).astype(np.float32))


@pytest.mark.parametrize(
    "preds, target, num_classes, multiclass",
    [
        # target not integer
        (_ri(7), _ri(7).astype(jnp.float32), None, None),
        # target negative
        (_ri(7), -_ri(7) - 1, None, None),
        # preds negative integers
        (-_ri(7) - 1, _ri(7), None, None),
        # multiclass=False and target > 1
        (_rf(7), _ri(7, low=2, high=4), None, False),
        # multiclass=False and preds integers with > 1
        (_ri(7, low=2, high=4), _ri(7), None, False),
        # wrong batch size
        (_ri(8), _ri(7), None, None),
        # completely wrong shape
        (_ri(7), _ri(7, 4), None, None),
        # same #dims, different shape
        (_ri(7, 3), _ri(7, 4), None, None),
        # same shape, preds float, target not binary
        (_rf(7, 3), _ri(7, 3, low=2, high=4), None, None),
        # #dims preds = 1 + #dims target, C not second or last
        (_rf(7, 3, 4, 3), _ri(7, 3, 3, high=4), None, None),
        # #dims preds = 1 + #dims target, preds not float
        (_ri(7, 3, 3, 4), _ri(7, 3, 3, high=4), None, None),
        # multiclass=False with C dimension > 2
        (jnp.asarray(_mc_prob.preds[0]), _ri(BATCH_SIZE), None, False),
        # max target >= C dimension
        (jnp.asarray(_mc_prob.preds[0]), _ri(BATCH_SIZE, low=NUM_CLASSES + 1, high=100), None, None),
        # C dimension != num_classes
        (jnp.asarray(_mc_prob.preds[0]), jnp.asarray(_mc_prob.target[0]), NUM_CLASSES + 1, None),
        # max target > num_classes (#dims preds = 1 + #dims target)
        (jnp.asarray(_mc_prob.preds[0]), _ri(BATCH_SIZE, NUM_CLASSES, low=NUM_CLASSES + 1, high=100), 4, None),
        # max target > num_classes (#dims preds = #dims target)
        (_ri(7, 3, high=4), _ri(7, 3, low=5, high=7), 4, None),
        # num_classes=1 but multiclass not false
        (_ri(7), _ri(7), 1, None),
        # multiclass=False but implied class dim != num_classes
        (_ri(7, 3, 3), _ri(7, 3, 3), 4, False),
        # multilabel input with implied class dim != num_classes
        (_rf(7, 3, 3), _ri(7, 3, 3), 4, False),
        # multilabel input with multiclass=True but num_classes != 2
        (_rf(7, 3), _ri(7, 3), 4, True),
        # binary input, num_classes > 2
        (_rf(7), _ri(7), 4, None),
        # binary input, num_classes == 2, multiclass not True
        (_rf(7), _ri(7), 2, None),
        (_rf(7), _ri(7), 2, False),
        # binary input, num_classes == 1, multiclass=True
        (_rf(7), _ri(7), 1, True),
    ],
)
def test_incorrect_inputs(preds, target, num_classes, multiclass):
    with pytest.raises(ValueError):
        _input_format_classification(
            preds=preds, target=target, threshold=THRESHOLD, num_classes=num_classes, multiclass=multiclass
        )


@pytest.mark.parametrize(
    "preds, target, num_classes, multiclass, top_k",
    [
        # top_k set with non-(md)mc or ml prob data
        (jnp.asarray(_bin.preds[0]), jnp.asarray(_bin.target[0]), None, None, 2),
        (jnp.asarray(_bin_prob.preds[0]), jnp.asarray(_bin_prob.target[0]), None, None, 2),
        (jnp.asarray(_mc.preds[0]), jnp.asarray(_mc.target[0]), None, None, 2),
        (jnp.asarray(_ml.preds[0]), jnp.asarray(_ml.target[0]), None, None, 2),
        (jnp.asarray(_mlmd.preds[0]), jnp.asarray(_mlmd.target[0]), None, None, 2),
        (jnp.asarray(_mdmc.preds[0]), jnp.asarray(_mdmc.target[0]), None, None, 2),
        # top_k = 0
        (jnp.asarray(_mc_prob_2cls.preds[0]), jnp.asarray(_mc_prob_2cls.target[0]), None, None, 0),
        # top_k = float
        (jnp.asarray(_mc_prob_2cls.preds[0]), jnp.asarray(_mc_prob_2cls.target[0]), None, None, 0.123),
        # top_k = 2 with 2 classes, multiclass=False
        (jnp.asarray(_mc_prob_2cls.preds[0]), jnp.asarray(_mc_prob_2cls.target[0]), None, False, 2),
        # top_k = number of classes
        (jnp.asarray(_mc_prob.preds[0]), jnp.asarray(_mc_prob.target[0]), None, None, NUM_CLASSES),
        # multiclass=True for ml prob inputs, top_k set
        (jnp.asarray(_ml_prob.preds[0]), jnp.asarray(_ml_prob.target[0]), None, True, 2),
        # top_k = num_classes for ml prob inputs
        (jnp.asarray(_ml_prob.preds[0]), jnp.asarray(_ml_prob.target[0]), None, True, NUM_CLASSES),
    ],
)
def test_incorrect_inputs_topk(preds, target, num_classes, multiclass, top_k):
    with pytest.raises(ValueError):
        _input_format_classification(
            preds=preds,
            target=target,
            threshold=THRESHOLD,
            num_classes=num_classes,
            multiclass=multiclass,
            top_k=top_k,
        )
