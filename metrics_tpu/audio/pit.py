"""Modular PermutationInvariantTraining.

Behavior parity with /root/reference/torchmetrics/audio/pit.py:22-108.
"""
from typing import Any, Callable

import jax
import jax.numpy as jnp

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.audio.pit import permutation_invariant_training

Array = jax.Array


class PermutationInvariantTraining(Metric):
    """Mean of a pairwise metric evaluated under the best speaker permutation.

    Args:
        metric_func: batched pairwise metric,
            ``metric_func(preds[:, j], target[:, i], **kwargs) -> [batch]``.
        eval_func: ``"max"`` (higher better) or ``"min"``.
        kwargs: additional args; those matching ``metric_func``'s signature
            are forwarded to it.

    Example:
        >>> from metrics_tpu.functional.audio.sdr import scale_invariant_signal_distortion_ratio
        >>> preds = jnp.array([[[-0.0579,  0.3560, -0.9604], [-0.1719,  0.3205,  0.2951]]])
        >>> target = jnp.array([[[ 1.0958, -0.1648,  0.5228], [-0.4100,  1.1942, -0.5103]]])
        >>> pit = PermutationInvariantTraining(scale_invariant_signal_distortion_ratio, 'max')
        >>> pit(preds, target)
        Array(-5.1091003, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = True

    def __init__(self, metric_func: Callable, eval_func: str = "max", **kwargs: Any) -> None:
        base_kwargs = {
            k: kwargs.pop(k)
            for k in list(kwargs)
            if k in ("dist_sync_on_step", "process_group", "dist_sync_fn", "compute_on_step")
        }
        super().__init__(**base_kwargs)
        if eval_func not in ("max", "min"):
            raise ValueError(f'eval_func can only be "max" or "min" but got {eval_func}')
        self.metric_func = metric_func
        self.eval_func = eval_func
        self.kwargs = kwargs
        self.add_state("sum_pit_metric", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")

    def _update(self, preds: Array, target: Array) -> None:
        pit_metric = permutation_invariant_training(
            preds, target, self.metric_func, self.eval_func, **self.kwargs
        )[0]
        self.sum_pit_metric = self.sum_pit_metric + jnp.sum(pit_metric)
        self.total = self.total + pit_metric.size

    def _compute(self) -> Array:
        return self.sum_pit_metric / self.total
