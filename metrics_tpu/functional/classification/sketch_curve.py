"""Weighted curve kernels for sketch-backed threshold metrics.

The quantile-sketch conversion (``metrics_tpu/sketches/``) leaves curve
metrics holding WEIGHTED rows ``(score, y, w)`` where ``y`` may be
fractional (pair collapse averages indicator payloads — first moments are
preserved exactly, see sketches/quantile.py). These kernels generalize the
exact-curve cumulant machinery (``exact_curve.py``) from counts to weight
masses: ``tps = cumsum(w * y)``, ``fps = cumsum(w * (1 - y))``, with the
same descending-score sort, tie-run deduplication, and reference endpoint
conventions — at unit weights and crisp labels they reduce bit-for-bit to
the unweighted kernels.

Only the sketch compute paths call these (the lossless window runs the
exact unbounded kernels instead); they are shape-polymorphic jnp programs
usable both eagerly on host-sliced rows and under jit on masked buffers.

:func:`coco_precision_recall_grid` is the detection twin: the same
sort-then-cumulate reduction, but integrated onto COCO's fixed recall
grid with the reference's float64 / mergesort / zigzag-removal semantics
(host numpy — detection AP parity is pinned bit-for-bit against the
reference, which never leaves float64). ``detection/mean_ap.py`` folds
every (class, area, max_det) cell through it instead of duplicating the
cumsum logic.
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.utils.data import stable_sort_with_payloads

Array = jax.Array

#: reference map.py:651 denominator epsilon (torch.finfo(torch.float64).eps)
_COCO_EPS = float(np.finfo(np.float64).eps)


def coco_precision_recall_grid(
    scores: np.ndarray,
    matches: np.ndarray,
    ignore: np.ndarray,
    npig: int,
    rec_thrs: np.ndarray,
) -> Tuple[np.ndarray, np.ndarray]:
    """COCO PR integration for one (class, area, max_det) cell.

    ``scores [nd]`` in unit-major arrival order, ``matches``/``ignore``
    ``[T, nd]`` bool over the IoU-threshold axis, ``npig`` the number of
    non-ignored ground truths, ``rec_thrs [R]`` the fixed recall grid.
    Returns ``(precision [T, R], recall [T])`` float64 with the
    reference's exact semantics: descending mergesort (Matlab-consistent
    tie order, map.py:632-634), float64 cumulative TP/FP masses, the
    right-to-left running max that is the fixed point of the iterative
    zigzag removal (map.py:657-662), left ``searchsorted`` onto the
    recall grid with first-out-of-bounds truncation (map.py:664-666).
    """
    T = matches.shape[0]
    R = rec_thrs.shape[0]
    nd = scores.shape[0]
    precision = np.zeros((T, R))
    recall = np.zeros((T,))
    if nd == 0:
        return precision, recall

    inds = np.argsort(-scores, kind="mergesort")
    matches = matches[:, inds]
    ignore = ignore[:, inds]

    tps = np.cumsum(matches & ~ignore, axis=1, dtype=np.float64)
    fps = np.cumsum(~matches & ~ignore, axis=1, dtype=np.float64)

    # all T thresholds at once: the per-t arithmetic and the zigzag
    # fixed point vectorize over the leading axis; only searchsorted
    # stays per-t (each row has its own sorted recall grid)
    rc_all = tps / npig  # [T, nd]
    pr_all = tps / (fps + tps + _COCO_EPS)
    recall[:] = rc_all[:, -1]
    pr_all = np.maximum.accumulate(pr_all[:, ::-1], axis=1)[:, ::-1]
    for t in range(T):
        r_inds = np.searchsorted(rc_all[t], rec_thrs, side="left")
        num = int(r_inds.argmax()) if r_inds.max() >= nd else R
        precision[t, :num] = pr_all[t, r_inds[:num]]
    return precision, recall


def _weighted_sorted_cumulants(
    scores: Array, y: Array, w: Array
) -> Tuple[Array, Array, Array, Array, Array, Array]:
    """Descending-score sort (zero-weight rows last) with weighted run-end
    cumulants; the weighted twin of ``exact_curve._masked_sorted_cumulants``."""
    valid = w > 0
    key = jnp.where(valid, scores.astype(jnp.float32), -jnp.inf)
    sorted_key, sorted_wy, sorted_w = stable_sort_with_payloads(
        key, (w * y).astype(jnp.float32), jnp.where(valid, w, 0.0).astype(jnp.float32), descending=True
    )
    tps = jnp.cumsum(sorted_wy)
    fps = jnp.cumsum(sorted_w - sorted_wy)

    n = sorted_key.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    boundary = sorted_key[1:] != sorted_key[:-1]
    is_run_last = jnp.concatenate([boundary, jnp.ones(1, bool)])
    is_run_first = jnp.concatenate([jnp.ones(1, bool), boundary])
    run_end = jax.lax.cummin(jnp.where(is_run_last, idx, n - 1)[::-1])[::-1]
    run_start = jax.lax.cummax(jnp.where(is_run_first, idx, 0))
    return sorted_key, sorted_w > 0, tps, fps, run_end, run_start


def binary_auroc_weighted(scores: Array, y: Array, w: Array) -> Array:
    """Weighted binary AUROC (trapezoid over run-end ROC points); NaN when
    either class carries no weight."""
    _, _, tps, fps, run_end, _ = _weighted_sorted_cumulants(scores, y, w)
    total_pos, total_neg = tps[-1], fps[-1]
    tpr = tps[run_end] / jnp.clip(total_pos, 1e-12, None)
    fpr = fps[run_end] / jnp.clip(total_neg, 1e-12, None)
    first = 0.5 * tpr[0] * fpr[0]
    rest = jnp.sum(0.5 * (tpr[1:] + tpr[:-1]) * (fpr[1:] - fpr[:-1]))
    return jnp.where((total_pos > 0) & (total_neg > 0), first + rest, jnp.nan)


def binary_auroc_max_fpr_weighted(scores: Array, y: Array, w: Array, max_fpr: float) -> Array:
    """Weighted partial AUC with the reference's McClish standardization
    (functional/classification/auroc.py max_fpr tail): the ROC is linearly
    interpolated at ``max_fpr``, integrated on ``[0, max_fpr]``, and mapped
    to ``0.5 * (1 + (pauc - min) / (max - min))``."""
    _, valid, tps, fps, run_end, _ = _weighted_sorted_cumulants(scores, y, w)
    total_pos, total_neg = tps[-1], fps[-1]
    tpr = jnp.concatenate([jnp.zeros(1), tps[run_end] / jnp.clip(total_pos, 1e-12, None)])
    fpr = jnp.concatenate([jnp.zeros(1), fps[run_end] / jnp.clip(total_neg, 1e-12, None)])
    is_point = jnp.concatenate([jnp.ones(1, bool), (run_end == jnp.arange(run_end.shape[0])) & valid])
    # clamp the curve to fpr <= max_fpr: points beyond collapse onto the
    # interpolated boundary point, so the trapezoid over ALL points equals
    # the truncated integral (non-points repeat their run-end neighbor)
    fpr_m = jnp.where(is_point, fpr, -jnp.inf)
    fpr_mono = jax.lax.cummax(fpr_m)  # carry last real point forward
    tpr_mono = jnp.where(is_point, tpr, 0.0)
    tpr_mono = jax.lax.cummax(tpr_mono)  # tpr is nondecreasing along points
    below = fpr_mono <= max_fpr
    # interpolated tpr at max_fpr between the straddling points
    idx_hi = jnp.clip(jnp.sum(below), 1, fpr_mono.shape[0] - 1)
    f_lo, f_hi = fpr_mono[idx_hi - 1], fpr_mono[idx_hi]
    t_lo, t_hi = tpr_mono[idx_hi - 1], tpr_mono[idx_hi]
    t_at = jnp.where(
        f_hi > f_lo, t_lo + (t_hi - t_lo) * (max_fpr - f_lo) / jnp.clip(f_hi - f_lo, 1e-12, None), t_lo
    )
    fpr_c = jnp.where(below, fpr_mono, max_fpr)
    tpr_c = jnp.where(below, tpr_mono, t_at)
    area = jnp.sum(0.5 * (tpr_c[1:] + tpr_c[:-1]) * (fpr_c[1:] - fpr_c[:-1]))
    min_area = 0.5 * max_fpr * max_fpr
    max_area = max_fpr
    pauc = 0.5 * (1.0 + (area - min_area) / jnp.clip(max_area - min_area, 1e-12, None))
    return jnp.where((total_pos > 0) & (total_neg > 0), pauc, jnp.nan)


def binary_roc_weighted(
    scores: Array, y: Array, w: Array
) -> Tuple[Array, Array, Array, Array]:
    """Weighted ROC points ``(fpr, tpr, thresholds, point_mask)`` in the
    fixed-kernel layout (leading implicit (0, 0) at ``thresholds[0] + 1``)."""
    sorted_key, valid, tps, fps, run_end, _ = _weighted_sorted_cumulants(scores, y, w)
    total_pos, total_neg = tps[-1], fps[-1]
    idx = jnp.arange(sorted_key.shape[0])
    is_threshold = (run_end == idx) & valid
    tpr = jnp.concatenate([jnp.zeros(1), tps / jnp.clip(total_pos, 1e-12, None)])
    fpr = jnp.concatenate([jnp.zeros(1), fps / jnp.clip(total_neg, 1e-12, None)])
    thresholds = jnp.concatenate([sorted_key[:1] + 1.0, sorted_key])
    point_mask = jnp.concatenate([jnp.any(valid)[None], is_threshold])
    return fpr, tpr, thresholds, point_mask


def binary_prc_weighted(
    scores: Array, y: Array, w: Array
) -> Tuple[Array, Array, Array, Array]:
    """Weighted precision-recall points ``(precision, recall, thresholds,
    point_mask)`` in descending-score order, with the reference's
    full-recall truncation; callers reverse and append ``(1, 0)``."""
    sorted_key, valid, tps, fps, run_end, run_start = _weighted_sorted_cumulants(scores, y, w)
    total_pos = tps[-1]
    idx = jnp.arange(sorted_key.shape[0])
    is_threshold = (run_end == idx) & valid
    prev_end_tps = jnp.where(run_start > 0, tps[jnp.maximum(run_start - 1, 0)], 0.0)
    # strict comparison needs a tolerance under weighted (inexact) masses
    is_threshold = is_threshold & (
        (prev_end_tps < total_pos - 1e-6 * jnp.clip(total_pos, 1.0, None)) | (run_start == 0)
    )
    precision = tps / jnp.clip(tps + fps, 1e-12, None)
    recall = jnp.where(total_pos > 0, tps / jnp.clip(total_pos, 1e-12, None), jnp.nan)
    return precision, recall, sorted_key, is_threshold


def binary_average_precision_weighted(scores: Array, y: Array, w: Array) -> Array:
    """Weighted average precision (step-wise sum over deduped thresholds)."""
    _, valid, tps, fps, run_end, _ = _weighted_sorted_cumulants(scores, y, w)
    total_pos = tps[-1]
    precision = tps / jnp.clip(tps + fps, 1e-12, None)
    contributions = jnp.diff(tps, prepend=0.0) * precision[run_end] * valid
    return jnp.where(total_pos > 0, jnp.sum(contributions) / jnp.clip(total_pos, 1e-12, None), jnp.nan)


def weighted_class_supports(y_cols: Array, w: Array) -> Array:
    """Per-class positive weight mass ``[C]`` for weighted averaging."""
    return jnp.sum(w[:, None] * y_cols, axis=0)


def average_class_scores(
    scores_per_class: Array, supports: Array, average: Optional[str]
) -> Array:
    """macro / weighted / none averaging over per-class scalar scores,
    excluding classes with zero positive mass (the capacity-mode
    convention: absent tail classes must not poison sharded evals)."""
    defined = supports > 0
    any_defined = jnp.any(defined)
    if average in (None, "none"):
        return scores_per_class
    if average == "macro":
        val = jnp.sum(jnp.where(defined, scores_per_class, 0.0)) / jnp.maximum(jnp.sum(defined), 1)
        return jnp.where(any_defined, val, jnp.nan)
    if average == "weighted":
        wts = jnp.where(defined, supports, 0.0)
        val = jnp.sum(jnp.where(defined, scores_per_class, 0.0) * wts) / jnp.clip(jnp.sum(wts), 1e-12, None)
        return jnp.where(any_defined, val, jnp.nan)
    raise ValueError(
        f"Argument `average` expected to be one of ('macro', 'weighted', 'none', None) but got {average}"
    )
