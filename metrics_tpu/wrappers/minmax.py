"""MinMaxMetric — tracks the running min/max of a wrapped metric's compute.

Behavior parity with /root/reference/torchmetrics/wrappers/minmax.py:23-120.
"""
from typing import Any, Dict, Union

import jax
import jax.numpy as jnp

from metrics_tpu.core.metric import Metric

Array = jax.Array


class MinMaxMetric(Metric):
    """Tracks the min and max of a scalar base metric across compute calls.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Accuracy
        >>> minmax = MinMaxMetric(Accuracy())
        >>> out = minmax(jnp.array([1, 0, 1, 1]), jnp.array([1, 1, 1, 1]))
        >>> sorted(out.keys())
        ['max', 'min', 'raw']
    """

    #: delegates to the child metric's full eager lifecycle (telemetry,
    #: coercion); the child registry already excludes it from fusion
    __jit_unsafe__ = True

    def __init__(self, base_metric: Metric, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(base_metric, Metric):
            raise ValueError(
                f"Expected base metric to be an instance of `metrics_tpu.Metric` but received {base_metric}"
            )
        self._base_metric = base_metric
        # NOT add_state: min/max accumulate across compute() calls and must
        # survive forward()'s snapshot/restore cycle (reference keeps them as
        # buffers outside the state registry for the same reason); they are
        # checkpointed via the state_dict override below
        self.min_val = jnp.asarray(jnp.inf)
        self.max_val = jnp.asarray(-jnp.inf)

    def forward(self, *args: Any, **kwargs: Any) -> Dict[str, Array]:
        # the base double-update cycle resets this wrapper (clearing min/max)
        # to get the batch value; merge the pre-existing extremes back in so
        # min/max track every compute() ever made (reference doctest behavior)
        prev_min, prev_max = self.min_val, self.max_val
        out = super().forward(*args, **kwargs)
        self.min_val = jnp.minimum(prev_min, out["min"])
        self.max_val = jnp.maximum(prev_max, out["max"])
        self._forward_cache = {"raw": out["raw"], "min": self.min_val, "max": self.max_val}
        return self._forward_cache

    def _update(self, *args: Any, **kwargs: Any) -> None:
        self._base_metric.update(*args, **kwargs)

    def _compute(self) -> Dict[str, Array]:
        val = self._base_metric.compute()
        if not self._is_suitable_val(val):
            raise RuntimeError(
                f"Returned value from base metric should be a scalar (int, float or tensor of size 1, but got {val}"
            )
        self.max_val = jnp.maximum(self.max_val, val)
        self.min_val = jnp.minimum(self.min_val, val)
        return {"raw": val, "max": self.max_val, "min": self.min_val}

    def reset(self) -> None:
        super().reset()
        self._base_metric.reset()
        self.min_val = jnp.asarray(jnp.inf)
        self.max_val = jnp.asarray(-jnp.inf)

    def state_dict(self, destination=None, prefix: str = ""):
        destination = super().state_dict(destination, prefix=prefix)
        destination[prefix + "min_val"] = jnp.asarray(self.min_val)
        destination[prefix + "max_val"] = jnp.asarray(self.max_val)
        return destination

    def load_state_dict(self, state_dict, prefix: str = "") -> None:
        super().load_state_dict(state_dict, prefix=prefix)
        if prefix + "min_val" in state_dict:
            self.min_val = jnp.asarray(state_dict[prefix + "min_val"])
        if prefix + "max_val" in state_dict:
            self.max_val = jnp.asarray(state_dict[prefix + "max_val"])

    @staticmethod
    def _is_suitable_val(val: Union[int, float, Array]) -> bool:
        if isinstance(val, (int, float)):
            return True
        if isinstance(val, jnp.ndarray):
            return val.size == 1
        return False
