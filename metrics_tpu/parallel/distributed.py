"""Distributed state synchronization — the TPU-native equivalent of the
reference's ``torch.distributed`` backend.

The reference (/root/reference/torchmetrics/utilities/distributed.py:96-145)
implements ``gather_all_tensors`` as: barrier -> gather per-rank shapes ->
pad to elementwise-max -> ``all_gather`` -> trim, over NCCL/Gloo process
groups. Here the same contract is provided two ways, both XLA-native:

* **Host-level** (`gather_all_arrays`): cross-process gather using a one-shot
  pjit'ed ``all_gather`` over the global device mesh (ICI within a host/pod
  slice, DCN across hosts via ``jax.distributed``). Uneven per-rank shapes
  are handled with the same pad-to-max + trim contract, with the shape
  exchange done host-side (it is outside any jit region, mirroring the
  reference where the gather is likewise eager).
* **In-jit** (`sync_in_mesh` / `reduce_state`): for metric state living
  inside a pjit/shard_map region, reductions map directly onto XLA
  collectives over a named mesh axis — ``psum``/``pmean``/``pmax``/``pmin``
  for scalar-reduced states and ``all_gather(tiled=True)`` for concat
  states. This is cheaper than gather-then-reduce (the reference's only
  strategy) because the reduction rides the ICI all-reduce.

``process_group`` in the reference maps to a *mesh axis name* (or a subset
axis) here.
"""
import os
from typing import Any, Callable, Dict, List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.observability.recorder import _DEFAULT_RECORDER as _TELEMETRY
from metrics_tpu.observability.recorder import _nbytes
from metrics_tpu.observability.trace import span as _span
from metrics_tpu.utils.prints import rank_zero_warn

Array = jax.Array

# suppresses double counting while sync_in_mesh (which records its own
# aggregate sync event) calls all_gather_replicated internally; per-thread
# so concurrent traces can neither cross-suppress nor leak events
import threading as _threading

_MESH_SYNC_LOCAL = _threading.local()


def distributed_available() -> bool:
    """True when more than one process participates (multi-host JAX)."""
    try:
        return jax.process_count() > 1
    except Exception:
        return False


def world_size(group: Optional[Any] = None) -> int:
    try:
        return jax.process_count()
    except Exception:
        return 1


def process_index() -> int:
    try:
        return jax.process_index()
    except Exception:
        return 0


# ---------------------------------------------------------------------------
# Host-level gather (cross-process, outside jit)
# ---------------------------------------------------------------------------

def _process_allgather(x: Array) -> List[Array]:
    """All-gather ``x`` across processes; returns a list of per-process arrays."""
    if not distributed_available():
        return [jnp.asarray(x)]
    from jax.experimental import multihost_utils

    stacked = multihost_utils.process_allgather(np.asarray(x), tiled=False)
    return [jnp.asarray(stacked[i]) for i in range(stacked.shape[0])]


def gather_all_arrays(result: Array, group: Optional[Any] = None) -> List[Array]:
    """Gather an array from all processes, supporting uneven dim sizes.

    Contract parity with the reference ``gather_all_tensors``
    (/root/reference/torchmetrics/utilities/distributed.py:96-145): returns a
    list of arrays, one per process, each with its true (untrimmed) shape.
    """
    result = jnp.asarray(result)
    if not distributed_available():
        return [result]

    world = world_size(group)
    itemsize = jnp.dtype(result.dtype).itemsize

    # the whole cross-process exchange is one trace span (shape exchange,
    # padding, and the allgather itself), nesting under the calling
    # metric's `.sync` span when the recorder is enabled
    with _span("gather_all_arrays", world_size=world):
        if result.ndim == 0:
            gathered = _process_allgather(result)
            if _TELEMETRY.enabled:
                _TELEMETRY.record_sync(
                    "gather_all_arrays", gather_bytes=itemsize * world, world_size=world
                )
            return gathered

        # exchange shapes host-side, pad to elementwise max, gather, trim
        local_shape = np.asarray(result.shape, dtype=np.int64)
        all_shapes = _process_allgather(jnp.asarray(local_shape))
        all_shapes = [np.asarray(s) for s in all_shapes]
        max_shape = np.max(np.stack(all_shapes), axis=0)

        if all((s == all_shapes[0]).all() for s in all_shapes):
            gathered = _process_allgather(result)
            if _TELEMETRY.enabled:
                _TELEMETRY.record_sync(
                    "gather_all_arrays",
                    gather_bytes=int(result.size) * itemsize * world,
                    world_size=world,
                )
            return gathered

        pad_width = [(0, int(m - s)) for s, m in zip(result.shape, max_shape)]
        padded = jnp.pad(result, pad_width)
        gathered = _process_allgather(padded)
        if _TELEMETRY.enabled:
            # the uneven contract moves world_size pad-to-max slabs; the
            # padding beyond each rank's true shape is pure waste the
            # accounting exposes
            moved = int(padded.size) * itemsize * world
            true_bytes = int(sum(int(np.prod(s)) for s in all_shapes)) * itemsize
            _TELEMETRY.record_sync(
                "gather_all_arrays",
                gather_bytes=moved,
                world_size=world,
                pad_waste_bytes=moved - true_bytes,
            )
        return [g[tuple(slice(0, int(d)) for d in shp)] for g, shp in zip(gathered, all_shapes)]


# ---------------------------------------------------------------------------
# In-jit collectives over a named mesh axis
# ---------------------------------------------------------------------------

def _axis_size(axis_name: str) -> int:
    try:
        return jax.lax.axis_size(axis_name)
    except AttributeError:  # older jax
        return jax.lax.psum(1, axis_name)


def all_gather_replicated(x: Array, axis_name: str, tiled: bool = True) -> Array:
    """All-gather whose output is *replicated* (VMA-clean) across the axis.

    Implemented as a psum of the local shard scattered into its slot — the
    same bytes over ICI as a ring all-gather, but the output is provably
    identical on every device, so ``shard_map`` can emit it with
    ``PartitionSpec()`` without ``check_vma=False``.
    """
    x = jnp.asarray(x)
    n = _axis_size(axis_name)
    if _TELEMETRY.enabled and not getattr(_MESH_SYNC_LOCAL, "active", False):
        # recorded at TRACE time (once per compilation, not per step): the
        # shapes are static so the byte accounting is exact
        _TELEMETRY.record_sync(
            "all_gather_replicated",
            gather_bytes=_nbytes(x) * n,
            world_size=n,
            axis=axis_name,
            in_jit=True,
        )
    # the span times the TRACE of the collective (host work, once per
    # compilation), nesting under sync_in_mesh's span on the internal path
    with _span("all_gather_replicated", axis=axis_name, in_jit=True):
        idx = jax.lax.axis_index(axis_name)
        work_dtype = jnp.int32 if x.dtype == jnp.bool_ else x.dtype
        buf = jnp.zeros((n,) + x.shape, work_dtype).at[idx].set(x.astype(work_dtype))
        out = jax.lax.psum(buf, axis_name)
        if x.dtype == jnp.bool_:
            out = out.astype(jnp.bool_)
        if tiled:
            out = out.reshape((n * x.shape[0],) + x.shape[1:]) if x.ndim >= 1 else out
        return out


def sync_in_mesh(
    state: Dict[str, Union[Array, list]],
    reductions: Dict[str, Union[str, Callable, None]],
    axis_name: str,
) -> Dict[str, Union[Array, list]]:
    """Synchronize a metric-state pytree across a named mesh axis, inside jit.

    ``"sum"/"mean"/"max"/"min"`` states use the matching XLA all-reduce;
    ``"cat"`` (and list) states use a tiled ``all_gather``. Use inside
    ``shard_map``/``pmap`` bodies where ``axis_name`` is bound.

    With telemetry enabled, one ``sync`` event per TRACE (shapes are static,
    so once per compilation — not per executed step) records the per-state
    and total gather bytes over the mesh axis: gathered states count
    ``world_size`` shards, all-reduced states one payload.
    """
    # the active flag suppresses recording when this runs as the fallback
    # leg of sync_pytree_in_mesh, which owns the aggregate sync event
    record = _TELEMETRY.enabled and not getattr(_MESH_SYNC_LOCAL, "active", False)
    per_state_bytes: Dict[str, int] = {}
    if record:
        world = _axis_size(axis_name)
        for name, value in state.items():
            red = reductions.get(name)
            if isinstance(value, list):
                nb = sum(_nbytes(v) for v in value)
            else:
                nb = _nbytes(value)
            gathered = red == "cat" or red is None or callable(red) or isinstance(value, list)
            per_state_bytes[name] = nb * world if gathered else nb
        _MESH_SYNC_LOCAL.active = True
    try:
        # one span for the whole mesh sync trace; the internal
        # all_gather_replicated spans nest under it (their *sync events*
        # stay suppressed so bytes are not double-counted — spans are pure
        # timing rows and nest freely)
        with _span("sync_in_mesh", axis=axis_name, in_jit=True):
            out: Dict[str, Union[Array, list]] = {}
            for name, value in state.items():
                red = reductions.get(name)
                if isinstance(value, list):
                    cat = jnp.concatenate([jnp.atleast_1d(v) for v in value], axis=0) if value else jnp.zeros((0,))
                    out[name] = [all_gather_replicated(cat, axis_name, tiled=True)]
                    continue
                if red is None:
                    # "gathered, not reduced" parity: stack per-rank values along a new dim 0
                    out[name] = all_gather_replicated(value, axis_name, tiled=False)
                elif red == "sum":
                    out[name] = jax.lax.psum(value, axis_name)
                elif red == "mean":
                    out[name] = jax.lax.pmean(value, axis_name)
                elif red == "max":
                    out[name] = jax.lax.pmax(value, axis_name)
                elif red == "min":
                    out[name] = jax.lax.pmin(value, axis_name)
                elif red == "cat":
                    out[name] = all_gather_replicated(value, axis_name, tiled=True)
                elif callable(red):
                    out[name] = red(all_gather_replicated(value, axis_name, tiled=False))
                else:
                    raise ValueError(f"Unknown reduction {red!r} for state {name!r}")
    finally:
        if record:
            _MESH_SYNC_LOCAL.active = False
    if record:
        _TELEMETRY.record_sync(
            "sync_in_mesh",
            gather_bytes=sum(per_state_bytes.values()),
            world_size=world,
            axis=axis_name,
            in_jit=True,
            state_bytes=per_state_bytes,
        )
    return out


def _iter_state_leaves(tree: Dict[str, Any], path: tuple = ()):
    """Depth-first ``(path, value)`` pairs of a (possibly nested) state dict."""
    for key, value in tree.items():
        if isinstance(value, dict):
            yield from _iter_state_leaves(value, path + (key,))
        else:
            yield path + (key,), value


def _path_get(tree: Any, path: tuple) -> Any:
    for key in path:
        if not isinstance(tree, dict) or key not in tree:
            return None
        tree = tree[key]
    return tree


def _path_set(tree: Dict[str, Any], path: tuple, value: Any) -> None:
    for key in path[:-1]:
        tree = tree.setdefault(key, {})
    tree[path[-1]] = value


#: reduction kinds that flatten into one fused all-reduce per (kind, dtype)
_FUSED_REDUCERS = {
    "sum": jax.lax.psum,
    "mean": jax.lax.pmean,
    "max": jax.lax.pmax,
    "min": jax.lax.pmin,
}


#: layout-manifest plausibility counters for sharded-claimed sync leaves
#: (populated only under METRICS_TPU_VERIFY_MANIFEST)
_LAYOUT_VERIFY_COUNTERS = {"claims_checked": 0, "implausible_claims": 0}


def layout_verify_counters() -> Dict[str, int]:
    """Snapshot of the sync path's layout-manifest cross-check counters:
    ``claims_checked`` (sharded-claimed leaves inspected under
    ``METRICS_TPU_VERIFY_MANIFEST``) and ``implausible_claims`` (claims the
    committed layout manifest says belong to replicated-only leaves — the
    silently-skipped-reduction bug class; behavior is unchanged, the claim
    is honored with a warning)."""
    return dict(_LAYOUT_VERIFY_COUNTERS)


def reset_layout_verify_counters() -> None:
    for key in _LAYOUT_VERIFY_COUNTERS:
        _LAYOUT_VERIFY_COUNTERS[key] = 0


def _verify_sharded_claims(sharded: List[tuple]) -> None:
    """Under ``METRICS_TPU_VERIFY_MANIFEST``, check every sharded-claimed
    (passthrough) leaf against the layout manifest's shard-axis index and
    warn on claims the manifest refutes. Pure host-side string work at
    trace time — never changes sync behavior (the spec stays authoritative;
    the warning names the leaf so the claim can be audited)."""
    try:
        from metrics_tpu.analysis.layout import leaf_may_shard
        from metrics_tpu.analysis.manifest import ENV_VERIFY_MANIFEST
    except Exception:  # pragma: no cover - analysis package always ships
        return
    if os.environ.get(ENV_VERIFY_MANIFEST, "").strip().lower() in ("", "0", "false", "no", "off"):
        return
    for path in sharded:
        _LAYOUT_VERIFY_COUNTERS["claims_checked"] += 1
        if leaf_may_shard("/".join(path)) is False:
            _LAYOUT_VERIFY_COUNTERS["implausible_claims"] += 1
            rank_zero_warn(
                f"partition spec claims state leaf {'/'.join(path)!r} sharded, but the "
                "layout manifest knows it only as replicated — the sync is passing it "
                "through WITHOUT its cross-rank reduction. Audit the spec (or "
                "regenerate the manifest with `python scripts/tracelint.py --manifest`).",
                UserWarning,
            )


def _spec_shards_axis(spec: Any, axis_name: str) -> bool:
    """True when a ``PartitionSpec`` (or spec-like tuple) places
    ``axis_name`` on some array dimension — the leaf's rows are then owned
    DISJOINTLY across the mesh axis and a cross-axis reduction would mix
    unrelated shards."""
    if spec is None:
        return False
    for entry in tuple(spec):
        if entry == axis_name:
            return True
        if isinstance(entry, (tuple, list)) and axis_name in entry:
            return True
    return False


def sync_pytree_in_mesh(
    state: Dict[str, Any],
    reductions: Dict[str, Any],
    axis_name: str,
    partition_specs: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Fused in-mesh sync: a WHOLE (possibly nested) state pytree — e.g.
    every metric of a ``MetricCollection`` — in one collective round.

    Where :func:`sync_in_mesh` launches one collective per state,
    this groups the array leaves by ``(reduction, dtype)``, ravels and
    concatenates each group into a single 1-D buffer, runs ONE
    ``psum``/``pmean``/``pmax``/``pmin`` per group, and splits the results
    back — so a collection of N metrics with M sum-reduced float32 states
    costs one all-reduce instead of M, riding a single ICI round trip.
    Leaves whose reduction is ``"cat"``/``None``/callable (and list states)
    fall back to the per-state :func:`sync_in_mesh` machinery.

    ``partition_specs`` — optional pytree of ``jax.sharding.PartitionSpec``
    nested like ``reductions``. A leaf whose spec places ``axis_name`` on an
    array dimension (a ``SlicedMetric``'s ``[S]`` slice axis sharded over
    the mesh — see ``metrics_tpu/sliced/sharding.py``) is owned disjointly
    by each mesh position: there is nothing to reduce across the axis, so
    the leaf passes through untouched — ZERO cross-host traffic for its
    sharded dimension, and the reduction applies only to the replicated
    (non-slice) leaves. Missing/None specs keep the ordinary behavior.

    ``state``/``reductions`` are matching flat or nested string-keyed dicts
    (``MetricCollection.state_reductions()`` produces the nested form).
    With telemetry enabled, ONE ``sync`` event per trace records the total
    gather bytes, the number of collective rounds actually launched, and
    how many slice-sharded leaves were passed through traffic-free.
    """
    leaves = list(_iter_state_leaves(state))
    groups: Dict[tuple, List[tuple]] = {}
    merge_groups: Dict[Any, List[tuple]] = {}
    fallback: List[tuple] = []
    sharded: List[tuple] = []
    for path, value in leaves:
        red = _path_get(reductions, path)
        if partition_specs is not None and _spec_shards_axis(
            _path_get(partition_specs, path), axis_name
        ):
            sharded.append(path)
        elif isinstance(value, jnp.ndarray) and not isinstance(value, list) and red in _FUSED_REDUCERS:
            groups.setdefault((red, jnp.asarray(value).dtype), []).append(path)
        elif (
            isinstance(value, jnp.ndarray)
            and not isinstance(value, list)
            and getattr(red, "merge_like", False)
        ):
            # sketch leaves: gathered together in ONE collective round per
            # dtype, then merged locally (deterministically, so every rank
            # lands on the same merged sketch)
            merge_groups.setdefault(jnp.asarray(value).dtype, []).append(path)
        else:
            fallback.append(path)

    if sharded:
        _verify_sharded_claims(sharded)

    record = _TELEMETRY.enabled
    if record:
        world = _axis_size(axis_name)
        gather_bytes = 0
        _MESH_SYNC_LOCAL.active = True
    out: Dict[str, Any] = {}
    try:
        with _span("sync_pytree_in_mesh", axis=axis_name, in_jit=True):
            for (red, dtype), paths in groups.items():
                parts = [jnp.asarray(_path_get(state, p)) for p in paths]
                work = [p.astype(jnp.int32) if p.dtype == jnp.bool_ else p for p in parts]
                buf = jnp.concatenate([p.ravel() for p in work]) if len(work) > 1 else work[0].ravel()
                synced = _FUSED_REDUCERS[red](buf, axis_name)
                offset = 0
                for path, part in zip(paths, parts):
                    piece = jax.lax.slice_in_dim(synced, offset, offset + part.size).reshape(part.shape)
                    if part.dtype == jnp.bool_:
                        piece = piece.astype(jnp.bool_)
                    _path_set(out, path, piece)
                    offset += part.size
                if record:
                    gather_bytes += _nbytes(buf)  # all-reduced: one payload
            for dtype, paths in merge_groups.items():
                # one fused all-gather moves every sketch leaf of this dtype
                # in a single round; each leaf's own merge reducer then folds
                # the [world, ...] stack back to one sketch
                parts = [jnp.asarray(_path_get(state, p)) for p in paths]
                buf = (
                    jnp.concatenate([p.ravel() for p in parts])
                    if len(parts) > 1
                    else parts[0].ravel()
                )
                gathered = all_gather_replicated(buf, axis_name, tiled=False)
                offset = 0
                world_n = gathered.shape[0]
                for path, part in zip(paths, parts):
                    stack = jax.lax.slice_in_dim(gathered, offset, offset + part.size, axis=1)
                    stack = stack.reshape((world_n,) + part.shape)
                    red = _path_get(reductions, path)
                    _path_set(out, path, red(stack))
                    offset += part.size
                if record:
                    gather_bytes += _nbytes(buf) * world
                    _TELEMETRY.record_sketch_merge(max(world - 1, 1) * len(paths))
            for path in sharded:
                # slice-sharded leaves: each mesh position owns disjoint
                # rows — identity, no collective, no bytes moved
                _path_set(out, path, _path_get(state, path))
            for path in fallback:
                value = _path_get(state, path)
                red = _path_get(reductions, path)
                synced = sync_in_mesh({"v": value}, {"v": red}, axis_name)
                _path_set(out, path, synced["v"])
                if record:
                    nb = sum(_nbytes(v) for v in value) if isinstance(value, list) else _nbytes(value)
                    gathered = red == "cat" or red is None or callable(red) or isinstance(value, list)
                    gather_bytes += nb * world if gathered else nb
    finally:
        if record:
            _MESH_SYNC_LOCAL.active = False
    if record:
        _TELEMETRY.record_sync(
            "sync_pytree_in_mesh",
            gather_bytes=gather_bytes,
            world_size=world,
            axis=axis_name,
            in_jit=True,
            collective_rounds=len(groups) + len(merge_groups) + len(fallback),
            n_states=len(leaves),
            sliced_passthrough=len(sharded),
            sketch_merged=sum(len(p) for p in merge_groups.values()),
        )
    return out


# ---------------------------------------------------------------------------
# Scalar reduction helpers (parity with reference reduce/class_reduce)
# ---------------------------------------------------------------------------

def reduce(x: Array, reduction: str) -> Array:
    """Reduce a tensor: 'elementwise_mean' | 'sum' | 'none'.

    Parity with /root/reference/torchmetrics/utilities/distributed.py:21-40.
    """
    if reduction == "elementwise_mean":
        return jnp.mean(x)
    if reduction == "sum":
        return jnp.sum(x)
    if reduction in ("none", None):
        return x
    raise ValueError("Reduction parameter unknown.")


def class_reduce(num: Array, denom: Array, weights: Array, class_reduction: str = "none") -> Array:
    """Per-class fraction reduction: 'micro' | 'macro' | 'weighted' | 'none'.

    Parity with /root/reference/torchmetrics/utilities/distributed.py:43-93.
    """
    valid_reduction = ("micro", "macro", "weighted", "none", None)
    fraction = jnp.sum(num) / jnp.sum(denom) if class_reduction == "micro" else num / denom
    fraction = jnp.where(jnp.isnan(fraction), 0.0, fraction) if class_reduction != "micro" else fraction

    if class_reduction == "micro":
        return fraction
    if class_reduction == "macro":
        return jnp.mean(fraction)
    if class_reduction == "weighted":
        return jnp.sum(fraction * (weights / jnp.sum(weights)))
    if class_reduction in ("none", None):
        return fraction
    raise ValueError(f"Reduction parameter {class_reduction} unknown. Choose between one of these: {valid_reduction}")
