"""Hinge loss (binary, Crammer-Singer multiclass, one-vs-all).

Behavior parity with /root/reference/torchmetrics/functional/classification/
hinge.py:24-220, with boolean-mask assignments re-expressed as ``where``
selects (jit-safe).
"""
from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _input_squeeze
from metrics_tpu.utils.data import to_onehot
from metrics_tpu.utils.enums import DataType, EnumStr

Array = jax.Array


class MulticlassMode(EnumStr):
    """Possible multiclass modes of hinge loss."""

    CRAMMER_SINGER = "crammer-singer"
    ONE_VS_ALL = "one-vs-all"


def _check_shape_and_type_consistency_hinge(preds: Array, target: Array) -> DataType:
    if target.ndim > 1:
        raise ValueError(
            f"The `target` should be one dimensional, got `target` with shape={target.shape}.",
        )

    if preds.ndim == 1:
        if preds.shape != target.shape:
            raise ValueError(
                "The `preds` and `target` should have the same shape,",
                f" got `preds` with shape={preds.shape} and `target` with shape={target.shape}.",
            )
        mode = DataType.BINARY
    elif preds.ndim == 2:
        if preds.shape[0] != target.shape[0]:
            raise ValueError(
                "The `preds` and `target` should have the same shape in the first dimension,",
                f" got `preds` with shape={preds.shape} and `target` with shape={target.shape}.",
            )
        mode = DataType.MULTICLASS
    else:
        raise ValueError(f"The `preds` should be one or two dimensional, got `preds` with shape={preds.shape}.")
    return mode


def _hinge_update(
    preds: Array,
    target: Array,
    squared: bool = False,
    multiclass_mode: Optional[Union[str, MulticlassMode]] = None,
) -> Tuple[Array, Array]:
    preds, target = _input_squeeze(preds, target)

    mode = _check_shape_and_type_consistency_hinge(preds, target)

    if mode == DataType.MULTICLASS:
        target = to_onehot(target, max(2, preds.shape[1])).astype(bool)

    if mode == DataType.MULTICLASS and (multiclass_mode is None or multiclass_mode == MulticlassMode.CRAMMER_SINGER):
        margin = jnp.sum(jnp.where(target, preds, 0.0), axis=1)
        margin = margin - jnp.max(jnp.where(target, -jnp.inf, preds), axis=1)
    elif mode == DataType.BINARY or multiclass_mode == MulticlassMode.ONE_VS_ALL:
        target = target.astype(bool)
        margin = jnp.where(target, preds, -preds)
    else:
        raise ValueError(
            "The `multiclass_mode` should be either None / 'crammer-singer' / MulticlassMode.CRAMMER_SINGER"
            "(default) or 'one-vs-all' / MulticlassMode.ONE_VS_ALL,"
            f" got {multiclass_mode}."
        )

    measures = jnp.clip(1 - margin, min=0)
    if squared:
        measures = jnp.square(measures)

    total = jnp.asarray(target.shape[0])
    return jnp.sum(measures, axis=0), total


def _hinge_compute(measure: Array, total: Array) -> Array:
    return measure / total


def hinge_loss(
    preds: Array,
    target: Array,
    squared: bool = False,
    multiclass_mode: Optional[Union[str, MulticlassMode]] = None,
) -> Array:
    """Computes the mean hinge loss (used in SVMs).

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([0, 1, 1])
        >>> preds = jnp.array([-2.2, 2.4, 0.1])
        >>> hinge_loss(preds, target)
        Array(0.29999998, dtype=float32)
    """
    measure, total = _hinge_update(preds, target, squared=squared, multiclass_mode=multiclass_mode)
    return _hinge_compute(measure, total)
