"""Fused single-dispatch MetricCollection updates (ISSUE 4 tentpole).

Parity suite: ``compile_update`` results must bit-match the eager loop
across classification / regression / retrieval metrics, compute groups,
``__jit_unsafe__`` fallbacks, and reset→update→compute cycles; the compile
cache must collapse bucketed shapes into one compilation; and the fused
path must issue exactly ONE ``fused_update`` telemetry event (one
dispatch) per batch.
"""
import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from metrics_tpu.utils.compat import shard_map

from metrics_tpu import MetricCollection
from metrics_tpu.classification import Accuracy, ConfusionMatrix, Precision, Recall
from metrics_tpu.core.fused import FUSED_ENTRY
from metrics_tpu.core.metric import Metric, _coerce_foreign
from metrics_tpu.observability import get_recorder
from metrics_tpu.parallel.distributed import sync_in_mesh, sync_pytree_in_mesh
from metrics_tpu.regression import MeanAbsoluteError, MeanSquaredError
from metrics_tpu.retrieval import RetrievalMAP


@pytest.fixture
def recorder():
    rec = get_recorder()
    rec.reset()
    rec.enable(recompile_threshold=rec.DEFAULT_RECOMPILE_THRESHOLD)
    try:
        yield rec
    finally:
        rec.disable()
        rec.recompile_threshold = rec.DEFAULT_RECOMPILE_THRESHOLD
        rec.reset()


def _cls_batch(rng, n, c=3):
    preds = rng.rand(n, c).astype(np.float32)
    preds /= preds.sum(-1, keepdims=True)
    return jnp.asarray(preds), jnp.asarray(rng.randint(0, c, n))


def _cls_collection():
    return MetricCollection(
        [
            Accuracy(),
            Precision(num_classes=3, average="macro"),
            Recall(num_classes=3, average="macro"),
            ConfusionMatrix(num_classes=3),
        ]
    )


def _assert_parity(eager, fused):
    res_e, res_f = eager.compute(), fused.compute()
    assert res_e.keys() == res_f.keys()
    for key in res_e:
        assert bool(jnp.array_equal(res_e[key], res_f[key])), (
            f"{key}: eager {res_e[key]} != fused {res_f[key]}"
        )


class _MeanStateMetric(Metric):
    """Running average with a mean-reduced state — exercises the in-kernel
    `_n_updates` bump (and blocks bucketing: no exact pad correction)."""

    def __init__(self):
        super().__init__()
        self.add_state("avg", default=jnp.asarray(0.0), dist_reduce_fx="mean")

    def _update(self, preds, target):
        self.avg = (self.avg + jnp.mean(preds)) / 2

    def _compute(self):
        return self.avg


class _JitUnsafeSum(Metric):
    """Sum metric flagged untraceable — must use the eager fallback leg."""

    __jit_unsafe__ = True

    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")

    def _update(self, preds, target):
        self.total = self.total + jnp.sum(preds)

    def _compute(self):
        return self.total


def test_fused_parity_classification_with_compute_group():
    rng = np.random.RandomState(0)
    eager, fused = _cls_collection(), _cls_collection()
    fused.compile_update()
    for n in (64, 64, 64):
        batch = _cls_batch(rng, n)
        eager.update(*batch)
        fused.update(*batch)
    # Precision/Recall share a compute group on both paths
    assert eager.compute_groups == fused.compute_groups
    assert any(len(cg) > 1 for cg in fused.compute_groups.values())
    _assert_parity(eager, fused)


def test_fused_parity_regression():
    rng = np.random.RandomState(1)
    mk = lambda: MetricCollection([MeanSquaredError(), MeanAbsoluteError()])
    eager, fused = mk(), mk()
    fused.compile_update()
    for _ in range(3):
        preds = jnp.asarray(rng.rand(50).astype(np.float32))
        target = jnp.asarray(rng.rand(50).astype(np.float32))
        eager.update(preds, target)
        fused.update(preds, target)
    _assert_parity(eager, fused)


def test_fused_parity_retrieval_jit_unsafe_fallback(recorder):
    """`exact=True` retrieval metrics are `__jit_unsafe__` (instance-level
    flip: unbounded cat-state, data-dependent grouping): they run through
    the eager fallback leg of the SAME fused call. (The table-state
    DEFAULT fuses — pinned in tests/retrieval/test_retrieval_table.py.)"""
    import warnings

    rng = np.random.RandomState(2)

    def mk():
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            return MetricCollection([Accuracy(), RetrievalMAP(exact=True)])
    eager, fused = mk(), mk()
    fused.compile_update()
    idx = jnp.asarray(np.repeat(np.arange(8), 8))
    for _ in range(2):
        preds = jnp.asarray(rng.rand(64).astype(np.float32))
        target = jnp.asarray((rng.rand(64) < 0.3).astype(np.int32))
        eager.update(preds, target, indexes=idx)
        fused.update(preds, target, indexes=idx)
    _assert_parity(eager, fused)
    totals = recorder.fused_update_totals()
    assert totals["fused_updates"] == 2
    assert totals["fallback_metric_updates"] == 2  # RetrievalMAP, both batches


def test_fused_explicit_jit_unsafe_flag_falls_back():
    rng = np.random.RandomState(3)
    mk = lambda: MetricCollection([Accuracy(), _JitUnsafeSum()])
    eager, fused = mk(), mk()
    handle = fused.compile_update()
    batch = _cls_batch(rng, 32)
    eager.update(*batch)
    fused.update(*batch)
    _assert_parity(eager, fused)
    assert handle.n_compiles == 1  # only Accuracy fused


def test_fused_reset_update_compute_cycle():
    rng = np.random.RandomState(4)
    eager, fused = _cls_collection(), _cls_collection()
    handle = fused.compile_update()
    for _ in range(2):
        batch = _cls_batch(rng, 64)
        eager.update(*batch)
        fused.update(*batch)
    _assert_parity(eager, fused)
    eager.reset()
    fused.reset()
    batch = _cls_batch(rng, 64)
    eager.update(*batch)
    fused.update(*batch)
    _assert_parity(eager, fused)
    # the post-reset cycle reuses the settled-structure cache entry
    assert handle.cache_size == handle.n_compiles <= 2


def test_fused_mean_state_counter_bumped_in_kernel():
    eager = MetricCollection([_MeanStateMetric()])
    fused = MetricCollection([_MeanStateMetric()])
    fused.compile_update()
    for i in range(3):
        x = jnp.asarray([float(i), float(i + 1)])
        eager.update(x, x)
        fused.update(x, x)
    _assert_parity(eager, fused)
    counter_e = getattr(eager["_MeanStateMetric"], "_n_updates")
    counter_f = getattr(fused["_MeanStateMetric"], "_n_updates")
    assert int(counter_e) == int(counter_f) == 3
    # eager fast path keeps a host int; the fused kernel owns a device bump
    assert isinstance(counter_e, int)
    assert isinstance(counter_f, jnp.ndarray)


def test_bucketed_shapes_share_one_compilation(recorder):
    """Two+ bucketed batch shapes must hit ONE compile-cache entry, with
    bit parity against the eager loop on the unpadded batches."""
    rng = np.random.RandomState(5)
    groups = [["Accuracy"], ["Precision", "Recall"], ["ConfusionMatrix"]]
    mk = lambda: MetricCollection(
        [
            Accuracy(),
            Precision(num_classes=3, average="macro"),
            Recall(num_classes=3, average="macro"),
            ConfusionMatrix(num_classes=3),
        ],
        compute_groups=groups,  # pinned structure: no discovery recompile
    )
    eager, fused = mk(), mk()
    handle = fused.compile_update(buckets=(128,))
    for n in (100, 120, 128):
        batch = _cls_batch(rng, n)
        eager.update(*batch)
        fused.update(*batch)
    assert handle.cache_size == 1
    assert handle.n_compiles == 1
    assert recorder.signature_counts()[FUSED_ENTRY] == 1
    assert recorder.compile_counts() == {f"{FUSED_ENTRY}[0]": 1}
    _assert_parity(eager, fused)


def test_fused_emits_exactly_one_event_per_batch(recorder):
    """The dispatch-count guard: one typed `fused_update` event per batch,
    and NO per-metric update events for fused metrics."""
    rng = np.random.RandomState(6)
    fused = _cls_collection()
    fused.compile_update()
    n_batches = 4
    for _ in range(n_batches):
        fused.update(*_cls_batch(rng, 64))
    events = [e for e in recorder.events() if e["type"] == "fused_update"]
    assert len(events) == n_batches
    assert all(e["n_fallback"] == 0 for e in events)
    # no eager per-metric update events leaked: the fused path is one dispatch
    assert not [e for e in recorder.events() if e["type"] == "update"]
    assert recorder.fused_update_totals()["fused_updates"] == n_batches


def test_fused_bucketing_declined_for_mean_states():
    """A mean-reduced state has no exact pad correction: bucketing must be
    declined (with a warning), falling back to per-shape entries."""
    import warnings

    fused = MetricCollection([_MeanStateMetric()])
    handle = fused.compile_update(buckets=(64,))
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        fused.update(jnp.ones((10,)), jnp.ones((10,)))
        fused.update(jnp.ones((20,)), jnp.ones((20,)))
    assert any("bucketing is disabled" in str(w.message) for w in caught)
    assert handle.n_compiles == 2  # per exact shape, not per bucket


def test_fused_handle_dropped_on_clone_and_add():
    fused = _cls_collection()
    fused.compile_update()
    assert fused.fused_update is not None
    clone = fused.clone(prefix="val_")
    assert clone.fused_update is None  # compiled executables are not copyable
    clone.update(*_cls_batch(np.random.RandomState(7), 16))  # eager path works
    fused.add_metrics(MeanSquaredError())
    assert fused.fused_update is None  # membership change invalidates


def test_fused_donation_defaults_off_on_cpu():
    fused = _cls_collection()
    handle = fused.compile_update()
    assert handle._donate is False  # suite runs on forced-CPU devices
    handle2 = fused.compile_update(donate=True)
    assert handle2._donate is True


def test_coerce_foreign_native_fast_path_keeps_identity():
    x = jnp.asarray([1.0, 2.0])
    args = (x, x)
    assert _coerce_foreign(args) is args
    assert _coerce_foreign(x) is x
    npx = np.ones(3)
    assert _coerce_foreign((npx,)) == (npx,)
    # mixed containers still recurse
    out = _coerce_foreign({"a": x, "b": [x]})
    assert out["a"] is x and out["b"][0] is x


def test_sync_pytree_in_mesh_one_round_matches_per_state():
    """The fused whole-pytree sync must agree with the per-state
    `sync_in_mesh` path for every reduction kind."""
    n_dev = 8
    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("rank",))
    rng = np.random.RandomState(8)
    per_rank = {
        "m1": {
            "total": jnp.asarray(rng.rand(n_dev).astype(np.float32)),
            "hits": jnp.asarray(rng.randint(0, 5, (n_dev, 4)).astype(np.int32)),
        },
        "m2": {
            "best": jnp.asarray(rng.rand(n_dev, 3).astype(np.float32)),
            "avg": jnp.asarray(rng.rand(n_dev).astype(np.float32)),
        },
    }
    reductions = {
        "m1": {"total": "sum", "hits": "sum"},
        "m2": {"best": "max", "avg": "mean"},
    }

    def body(total, hits, best, avg):
        state = {
            "m1": {"total": total[0], "hits": hits[0]},
            "m2": {"best": best[0], "avg": avg[0]},
        }
        out = sync_pytree_in_mesh(state, reductions, "rank")
        return out["m1"]["total"], out["m1"]["hits"], out["m2"]["best"], out["m2"]["avg"]

    args = (
        per_rank["m1"]["total"][:, None],
        per_rank["m1"]["hits"][:, None],
        per_rank["m2"]["best"][:, None],
        per_rank["m2"]["avg"][:, None],
    )
    total, hits, best, avg = jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=(P("rank"), P("rank"), P("rank"), P("rank")),
            out_specs=(P(), P(), P(), P()),
        )
    )(*args)
    assert np.allclose(total, per_rank["m1"]["total"].sum())
    assert np.array_equal(np.asarray(hits)[0], np.asarray(per_rank["m1"]["hits"].sum(0)))
    assert np.allclose(np.asarray(best)[0], per_rank["m2"]["best"].max(0))
    assert np.allclose(avg, per_rank["m2"]["avg"].mean(0))


def test_sync_pytree_in_mesh_records_one_sync_event(recorder):
    n_dev = 8
    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("rank",))
    state_shapes = {"a": {"x": jnp.ones((n_dev, 2)), "y": jnp.ones((n_dev,))}}
    reductions = {"a": {"x": "sum", "y": "max"}}

    def body(x, y):
        out = sync_pytree_in_mesh({"a": {"x": x[0], "y": y[0]}}, reductions, "rank")
        return out["a"]["x"], out["a"]["y"]

    jax.jit(
        shard_map(
            body, mesh=mesh, in_specs=(P("rank"), P("rank")), out_specs=(P(), P())
        )
    )(state_shapes["a"]["x"][:, None], state_shapes["a"]["y"][:, None])
    syncs = [e for e in recorder.events() if e["type"] == "sync"]
    assert len(syncs) == 1
    assert syncs[0]["source"] == "sync_pytree_in_mesh"
    # sum(x) + max(y): two (reduction, dtype) groups, two collective rounds
    assert syncs[0]["collective_rounds"] == 2
    assert syncs[0]["n_states"] == 2


# ---------------------------------------------------------------------------
# manifest-seeded fusibility (ISSUE 6): probe skip, parity, safety net
# ---------------------------------------------------------------------------

class TestManifestSeeding:
    def _batches(self, n=3):
        rng = np.random.RandomState(11)
        return [_cls_batch(rng, 64) for _ in range(n)]

    def test_parity_with_and_without_manifest(self):
        """Fused results must be identical whether fusibility came from the
        static manifest or the runtime eval_shape probe — and the manifest
        handle must actually skip probes for fusible-verdict members."""
        batches = self._batches()
        seeded, probed = _cls_collection(), _cls_collection()
        seeded.update(*batches[0])
        probed.update(*batches[0])
        h_seeded = seeded.compile_update(use_manifest=True)
        h_probed = probed.compile_update(use_manifest=False)
        for b in batches:
            seeded.update(*b)
            probed.update(*b)
        assert h_seeded.manifest_probe_skips >= 1  # ConfusionMatrix is fusible-verdict
        assert h_probed.manifest_probe_skips == 0
        _assert_parity(probed, seeded)

    def test_manifest_vs_eager_parity(self):
        batches = self._batches()
        eager, fused = _cls_collection(), _cls_collection()
        eager.update(*batches[0])
        fused.update(*batches[0])
        fused.compile_update(use_manifest=True)
        for b in batches:
            eager.update(*b)
            fused.update(*b)
        _assert_parity(eager, fused)

    def test_stale_manifest_falls_back_instead_of_crashing(self, tmp_path, monkeypatch):
        """A manifest wrongly claiming a host-sync metric fusible must not
        crash the fused path: the build failure is caught, the seeded
        members re-probe, and the refuted metric runs eagerly — with a
        warning naming the stale manifest."""
        import metrics_tpu.analysis.manifest as manifest_mod
        from metrics_tpu.analysis.manifest import class_key

        class HostSyncMetric(Metric):
            def __init__(self):
                super().__init__()
                self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")

            def _update(self, preds, target):
                self.total = self.total + float(np.asarray(preds).sum())

            def _compute(self):
                return self.total

        # forge a manifest entry for a REAL package class that fails the
        # probe at runtime: monkeypatch its key onto the local class
        fake_key = "classification/fixture.py::HostSyncMetric"
        # plain assignment: the class is test-local, nothing to restore
        HostSyncMetric.__module__ = "metrics_tpu.classification.fixture"
        HostSyncMetric.__qualname__ = "HostSyncMetric"
        assert class_key(HostSyncMetric) == fake_key

        stale = {
            "version": 1,
            "tool": "tracelint",
            "metrics": {
                fake_key: {
                    "verdict": "fusible",
                    "reason": None,
                    "detail": None,
                    "declared_jit_unsafe": None,
                    "states": {},
                }
            },
        }
        path = tmp_path / "stale_manifest.json"
        path.write_text(json.dumps(stale))
        monkeypatch.setenv("METRICS_TPU_MANIFEST", str(path))
        manifest_mod.invalidate_runtime_cache()
        try:
            rng = np.random.RandomState(5)
            batches = [_cls_batch(rng, 32) for _ in range(2)]
            col = MetricCollection({"host": HostSyncMetric(), "cm": ConfusionMatrix(num_classes=3)})
            ref = MetricCollection({"host": HostSyncMetric(), "cm": ConfusionMatrix(num_classes=3)})
            col.update(*batches[0])
            ref.update(*batches[0])
            handle = col.compile_update(use_manifest=True)
            with pytest.warns(UserWarning, match="stale"):
                for b in batches:
                    col.update(*b)
            for b in batches:
                ref.update(*b)
            _assert_parity(ref, col)
            # the handle stopped trusting the manifest after the failure
            assert handle._use_manifest is False
        finally:
            manifest_mod.invalidate_runtime_cache()

    def test_verify_mode_probes_anyway(self, monkeypatch):
        """METRICS_TPU_VERIFY_MANIFEST=1: the probe runs even for
        fusible-verdict classes (cross-check mode), so no skips happen."""
        monkeypatch.setenv("METRICS_TPU_VERIFY_MANIFEST", "1")
        batches = self._batches(2)
        col = _cls_collection()
        col.update(*batches[0])
        handle = col.compile_update(use_manifest=True)
        for b in batches:
            col.update(*b)
        assert handle.manifest_probe_skips == 0
