"""RetrievalRPrecision.

Behavior parity with /root/reference/torchmetrics/retrieval/r_precision.py:20-96.
"""
import jax

from metrics_tpu.functional.retrieval.r_precision import retrieval_r_precision
from metrics_tpu.functional.retrieval.padded import r_precision_row
from metrics_tpu.retrieval.base import RetrievalMetric

Array = jax.Array


class RetrievalRPrecision(RetrievalMetric):
    """Mean R-precision over queries.

    Default state is the fixed-capacity per-query table (fusible /
    async / mesh-synced; ``max_queries`` / ``max_docs`` size it);
    ``exact=True`` restores the unbounded cat-state reference path.
    """

    _padded_metric = staticmethod(r_precision_row)

    def _metric(self, preds: Array, target: Array) -> Array:
        return retrieval_r_precision(preds, target)
