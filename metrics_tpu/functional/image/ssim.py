"""Structural Similarity Index Measure (and multi-scale variant).

Behavior parity with /root/reference/torchmetrics/functional/image/ssim.py:
25-366, including the 5-in-1 batched depthwise convolution trick
(ssim.py:112-114) which carries straight over to
``lax.conv_general_dilated`` — one conv computes the two means and three
second moments.
"""
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.image.helper import _avg_pool2d, _depthwise_conv2d, _gaussian_kernel
from metrics_tpu.parallel.distributed import reduce
from metrics_tpu.utils.checks import _check_same_shape

Array = jax.Array


def _ssim_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    if preds.dtype != target.dtype:
        raise TypeError(
            "Expected `preds` and `target` to have the same data type."
            f" Got preds: {preds.dtype} and target: {target.dtype}."
        )
    _check_same_shape(preds, target)
    if preds.ndim != 4:
        raise ValueError(
            "Expected `preds` and `target` to have BxCxHxW shape."
            f" Got preds: {preds.shape} and target: {target.shape}."
        )
    return preds, target


def _ssim_check_kernel(kernel_size: Sequence[int], sigma: Sequence[float]) -> None:
    if len(kernel_size) != 2 or len(sigma) != 2:
        raise ValueError(
            "Expected `kernel_size` and `sigma` to have the length of two."
            f" Got kernel_size: {len(kernel_size)} and sigma: {len(sigma)}."
        )
    if any(x % 2 == 0 or x <= 0 for x in kernel_size):
        raise ValueError(f"Expected `kernel_size` to have odd positive number. Got {kernel_size}.")
    if any(y <= 0 for y in sigma):
        raise ValueError(f"Expected `sigma` to have positive number. Got {sigma}.")


def _ssim_compute(
    preds: Array,
    target: Array,
    kernel_size: Sequence[int] = (11, 11),
    sigma: Sequence[float] = (1.5, 1.5),
    reduction: str = "elementwise_mean",
    data_range: Optional[float] = None,
    k1: float = 0.01,
    k2: float = 0.03,
    return_contrast_sensitivity: bool = False,
) -> Union[Array, Tuple[Array, Array]]:
    _ssim_check_kernel(kernel_size, sigma)

    if data_range is None:
        data_range = jnp.maximum(jnp.max(preds) - jnp.min(preds), jnp.max(target) - jnp.min(target))

    c1 = (k1 * data_range) ** 2
    c2 = (k2 * data_range) ** 2

    channel = preds.shape[1]
    dtype = preds.dtype
    kernel = _gaussian_kernel(channel, kernel_size, sigma, dtype)
    pad_h = (kernel_size[0] - 1) // 2
    pad_w = (kernel_size[1] - 1) // 2

    pad_cfg = ((0, 0), (0, 0), (pad_h, pad_h), (pad_w, pad_w))
    preds = jnp.pad(preds, pad_cfg, mode="reflect")
    target = jnp.pad(target, pad_cfg, mode="reflect")

    # one grouped conv over 5 stacked planes: mu_p, mu_t, E[p^2], E[t^2], E[pt]
    input_list = jnp.concatenate([preds, target, preds * preds, target * target, preds * target])
    outputs = _depthwise_conv2d(input_list, kernel)
    n = preds.shape[0]
    output_list = [outputs[i * n:(i + 1) * n] for i in range(5)]

    mu_pred_sq = jnp.square(output_list[0])
    mu_target_sq = jnp.square(output_list[1])
    mu_pred_target = output_list[0] * output_list[1]

    sigma_pred_sq = output_list[2] - mu_pred_sq
    sigma_target_sq = output_list[3] - mu_target_sq
    sigma_pred_target = output_list[4] - mu_pred_target

    upper = 2 * sigma_pred_target + c2
    lower = sigma_pred_sq + sigma_target_sq + c2

    ssim_idx = ((2 * mu_pred_target + c1) * upper) / ((mu_pred_sq + mu_target_sq + c1) * lower)
    ssim_idx = ssim_idx[..., pad_h:-pad_h, pad_w:-pad_w] if pad_h and pad_w else ssim_idx

    if return_contrast_sensitivity:
        contrast_sensitivity = upper / lower
        contrast_sensitivity = (
            contrast_sensitivity[..., pad_h:-pad_h, pad_w:-pad_w] if pad_h and pad_w else contrast_sensitivity
        )
        return reduce(ssim_idx, reduction), reduce(contrast_sensitivity, reduction)

    return reduce(ssim_idx, reduction)


def structural_similarity_index_measure(
    preds: Array,
    target: Array,
    kernel_size: Sequence[int] = (11, 11),
    sigma: Sequence[float] = (1.5, 1.5),
    reduction: str = "elementwise_mean",
    data_range: Optional[float] = None,
    k1: float = 0.01,
    k2: float = 0.03,
) -> Array:
    """Computes the structural similarity index measure.

    Example:
        >>> import jax
        >>> preds = jax.random.uniform(jax.random.PRNGKey(0), (8, 3, 16, 16))
        >>> target = preds * 0.75
        >>> bool(structural_similarity_index_measure(preds, target) > 0.9)
        True
    """
    preds, target = _ssim_update(preds, target)
    return _ssim_compute(preds, target, kernel_size, sigma, reduction, data_range, k1, k2)


def _get_normalized_sim_and_cs(
    preds: Array,
    target: Array,
    kernel_size: Sequence[int],
    sigma: Sequence[float],
    reduction: str,
    data_range: Optional[float],
    k1: float,
    k2: float,
    normalize: Optional[str] = None,
) -> Tuple[Array, Array]:
    sim, contrast_sensitivity = _ssim_compute(
        preds, target, kernel_size, sigma, reduction, data_range, k1, k2, return_contrast_sensitivity=True
    )
    if normalize == "relu":
        sim = jax.nn.relu(sim)
        contrast_sensitivity = jax.nn.relu(contrast_sensitivity)
    return sim, contrast_sensitivity


def _multiscale_ssim_compute(
    preds: Array,
    target: Array,
    kernel_size: Sequence[int] = (11, 11),
    sigma: Sequence[float] = (1.5, 1.5),
    reduction: str = "elementwise_mean",
    data_range: Optional[float] = None,
    k1: float = 0.01,
    k2: float = 0.03,
    betas: Tuple[float, ...] = (0.0448, 0.2856, 0.3001, 0.2363, 0.1333),
    normalize: Optional[str] = None,
) -> Array:
    if preds.shape[-1] < 2 ** len(betas) or preds.shape[-2] < 2 ** len(betas):
        raise ValueError(
            f"For a given number of `betas` parameters {len(betas)}, the image height and width dimensions must be"
            f" larger than or equal to {2 ** len(betas)}."
        )
    _betas_div = max(1, (len(betas) - 1)) ** 2
    if preds.shape[-2] // _betas_div <= kernel_size[0] - 1:
        raise ValueError(
            f"For a given number of `betas` parameters {len(betas)} and kernel size {kernel_size[0]},"
            f" the image height must be larger than {(kernel_size[0] - 1) * _betas_div}."
        )
    if preds.shape[-1] // _betas_div <= kernel_size[1] - 1:
        raise ValueError(
            f"For a given number of `betas` parameters {len(betas)} and kernel size {kernel_size[1]},"
            f" the image width must be larger than {(kernel_size[1] - 1) * _betas_div}."
        )

    sim_list = []
    cs_list = []
    for _ in range(len(betas)):
        sim, contrast_sensitivity = _get_normalized_sim_and_cs(
            preds, target, kernel_size, sigma, reduction, data_range, k1, k2, normalize
        )
        sim_list.append(sim)
        cs_list.append(contrast_sensitivity)
        preds = _avg_pool2d(preds)
        target = _avg_pool2d(target)

    sim_stack = jnp.stack(sim_list)
    cs_stack = jnp.stack(cs_list)

    if normalize == "simple":
        sim_stack = (sim_stack + 1) / 2
        cs_stack = (cs_stack + 1) / 2

    betas_arr = jnp.asarray(betas)
    sim_stack = sim_stack**betas_arr
    cs_stack = cs_stack**betas_arr
    return jnp.prod(cs_stack[:-1]) * sim_stack[-1]


def multiscale_structural_similarity_index_measure(
    preds: Array,
    target: Array,
    kernel_size: Sequence[int] = (11, 11),
    sigma: Sequence[float] = (1.5, 1.5),
    reduction: str = "elementwise_mean",
    data_range: Optional[float] = None,
    k1: float = 0.01,
    k2: float = 0.03,
    betas: Tuple[float, ...] = (0.0448, 0.2856, 0.3001, 0.2363, 0.1333),
    normalize: Optional[str] = None,
) -> Array:
    """Computes the multi-scale structural similarity index measure.

    Example:
        >>> import jax
        >>> preds = jax.random.uniform(jax.random.PRNGKey(0), (8, 3, 192, 192))
        >>> target = preds * 0.75
        >>> bool(multiscale_structural_similarity_index_measure(preds, target) > 0.9)
        True
    """
    if not isinstance(betas, tuple) or not all(isinstance(beta, float) for beta in betas):
        raise ValueError("Argument `betas` is expected to be of a type tuple of floats.")
    if normalize and normalize not in ("relu", "simple"):
        raise ValueError("Argument `normalize` to be expected either `None`, `relu` or `simple`")

    preds, target = _ssim_update(preds, target)
    return _multiscale_ssim_compute(
        preds, target, kernel_size, sigma, reduction, data_range, k1, k2, betas, normalize
    )
