"""Calibration error (ECE / MCE / RMSCE).

Behavior parity with /root/reference/torchmetrics/functional/classification/
calibration_error.py:24-213. The reference's ``torch.bucketize`` +
``scatter_add_`` binning becomes ``searchsorted`` + ``.at[].add`` — fully
vectorized and jit-safe (no pre-1.6 loop fallback needed).
"""
from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import (
    _input_format_classification,
    _is_concrete,
    _score_mode_static,
)
from metrics_tpu.utils.enums import DataType

Array = jax.Array


def _binning_bucketize(
    confidences: Array, accuracies: Array, bin_boundaries: Array
) -> Tuple[Array, Array, Array]:
    n_bins = bin_boundaries.shape[0] - 1
    indices = jnp.clip(jnp.searchsorted(bin_boundaries, confidences, side="left") - 1, 0, n_bins - 1)

    zeros = jnp.zeros(n_bins, dtype=confidences.dtype)
    count_bin = zeros.at[indices].add(jnp.ones_like(confidences))
    conf_bin = zeros.at[indices].add(confidences)
    acc_bin = zeros.at[indices].add(accuracies)

    safe_count = jnp.where(count_bin == 0, 1.0, count_bin)
    conf_bin = jnp.where(count_bin == 0, 0.0, conf_bin / safe_count)
    acc_bin = jnp.where(count_bin == 0, 0.0, acc_bin / safe_count)
    prop_bin = count_bin / jnp.sum(count_bin)
    return acc_bin, conf_bin, prop_bin


def _ce_compute(
    confidences: Array,
    accuracies: Array,
    bin_boundaries: Array,
    norm: str = "l1",
    debias: bool = False,
) -> Array:
    if norm not in {"l1", "l2", "max"}:
        raise ValueError(f"Norm {norm} is not supported. Please select from l1, l2, or max. ")

    acc_bin, conf_bin, prop_bin = _binning_bucketize(confidences, accuracies, bin_boundaries)

    if norm == "l1":
        ce = jnp.sum(jnp.abs(acc_bin - conf_bin) * prop_bin)
    elif norm == "max":
        ce = jnp.max(jnp.abs(acc_bin - conf_bin))
    else:  # l2
        ce = jnp.sum(jnp.square(acc_bin - conf_bin) * prop_bin)
        if debias:
            debias_bins = (acc_bin * (acc_bin - 1) * prop_bin) / (prop_bin * accuracies.shape[0] - 1)
            ce = ce + jnp.sum(jnp.where(jnp.isnan(debias_bins) | jnp.isinf(debias_bins), 0.0, debias_bins))
        ce = jnp.where(ce > 0, jnp.sqrt(jnp.where(ce > 0, ce, 1.0)), 0.0)
    return ce


def _ce_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    # concrete inputs take the fully-validating formatter; under tracing the
    # mode comes from the shape-only deduction (value validation is host
    # work by contract — keeps the binned streaming update jit-safe)
    if _is_concrete(preds, target):
        _, _, mode = _input_format_classification(preds, target)
    else:
        mode = _score_mode_static(preds, target)

    if mode == DataType.BINARY:
        confidences, accuracies = preds, target
    elif mode == DataType.MULTICLASS:
        confidences = jnp.max(preds, axis=1)
        predictions = jnp.argmax(preds, axis=1)
        accuracies = predictions == target
    elif mode == DataType.MULTIDIM_MULTICLASS:
        flat = jnp.swapaxes(preds, 1, -1).reshape(-1, preds.shape[1])
        confidences = jnp.max(flat, axis=1)
        predictions = jnp.argmax(flat, axis=1)
        accuracies = predictions == target.flatten()
    else:
        raise ValueError(
            f"Calibration error is not well-defined for data with size {preds.shape} and targets {target.shape}."
        )
    return confidences.astype(jnp.float32), accuracies.astype(jnp.float32)


def calibration_error(preds: Array, target: Array, n_bins: int = 15, norm: str = "l1") -> Array:
    """Computes the top-label calibration error (norm: 'l1'=ECE, 'l2'=RMSCE, 'max'=MCE).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.array([0.9, 0.8, 0.3, 0.2])
        >>> target = jnp.array([1, 1, 0, 0])
        >>> bool(calibration_error(preds, target, n_bins=2) < 0.3)
        True
    """
    if norm not in ("l1", "l2", "max"):
        raise ValueError(f"Norm {norm} is not supported. Please select from l1, l2, or max. ")
    if not isinstance(n_bins, int) or n_bins <= 0:
        raise ValueError(f"Expected argument `n_bins` to be a int larger than 0 but got {n_bins}")

    confidences, accuracies = _ce_update(preds, target)
    bin_boundaries = jnp.linspace(0, 1, n_bins + 1, dtype=jnp.float32)
    return _ce_compute(confidences, accuracies, bin_boundaries, norm=norm)
