"""RetrievalFallOut — inverts the empty-query handling (queries with no
*negative* targets).

Behavior parity with /root/reference/torchmetrics/retrieval/fall_out.py:24-130.
"""
from typing import Any, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.retrieval.fall_out import retrieval_fall_out
from metrics_tpu.functional.retrieval.padded import fall_out_row
from metrics_tpu.retrieval.base import RetrievalMetric
from metrics_tpu.utils.checks import _check_retrieval_k

Array = jax.Array


class RetrievalFallOut(RetrievalMetric):
    """Mean fall-out@k over queries. Lower is better.

    Default state is the fixed-capacity per-query table (fusible /
    async / mesh-synced; ``max_queries`` / ``max_docs`` size it, the
    empty-query inversion reads the exact negative-document counter);
    ``exact=True`` restores the unbounded cat-state reference path.
    """

    _padded_metric = staticmethod(fall_out_row)

    @property
    def _padded_k(self):
        return self.k

    higher_is_better = False

    def __init__(
        self,
        empty_target_action: str = "pos",
        ignore_index: Optional[int] = None,
        k: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(empty_target_action=empty_target_action, ignore_index=ignore_index, **kwargs)
        _check_retrieval_k(k)
        self.k = k

    def _empty_rows(self, padded_target, mask):
        # queries with no NEGATIVE target are "empty" for fall-out
        return ((1.0 - padded_target) * mask).sum(-1) == 0

    def _table_empty_rows(self, pos_mass, neg_count):
        # the table's exact negative-document counter — never degraded by
        # document truncation past capacity
        return neg_count <= 0

    def _group_empty(self, mini_target: Array) -> bool:
        # a query is degenerate when it has no NEGATIVE target
        return not bool(jnp.sum(1 - mini_target))

    def _empty_error_message(self) -> str:
        return "`compute` method was provided with a query with no negative target."

    def _metric(self, preds: Array, target: Array) -> Array:
        return retrieval_fall_out(preds, target, k=self.k)
