"""Precision/Recall/FBeta/Specificity vs sklearn oracles."""
from functools import partial

import numpy as np
import pytest
from sklearn.metrics import fbeta_score as sk_fbeta
from sklearn.metrics import multilabel_confusion_matrix
from sklearn.metrics import precision_score as sk_precision
from sklearn.metrics import recall_score as sk_recall

from metrics_tpu.classification import F1Score, FBetaScore, Precision, Recall, Specificity
from metrics_tpu.functional import f1_score, fbeta_score, precision, recall, specificity
from tests.classification.inputs import _input_binary_prob, _input_multiclass, _input_multiclass_prob
from tests.helpers.testers import NUM_CLASSES, THRESHOLD, MetricTester


def _to_labels(preds, target):
    preds, target = np.asarray(preds), np.asarray(target)
    if preds.ndim == target.ndim + 1:
        preds = np.argmax(preds, axis=1)
    elif np.issubdtype(preds.dtype, np.floating):
        preds = (preds >= THRESHOLD).astype(int)
    return preds, target


def _sk_prec(preds, target, average="micro"):
    preds, target = _to_labels(preds, target)
    labels = np.arange(NUM_CLASSES) if average != "binary" else None
    avg = None if average == "none" else average
    res = sk_precision(target, preds, average=avg, labels=labels, zero_division=0)
    return res


def _sk_rec(preds, target, average="micro"):
    preds, target = _to_labels(preds, target)
    labels = np.arange(NUM_CLASSES) if average != "binary" else None
    avg = None if average == "none" else average
    return sk_recall(target, preds, average=avg, labels=labels, zero_division=0)


def _sk_fbeta_fn(preds, target, average="micro", beta=1.0):
    preds, target = _to_labels(preds, target)
    labels = np.arange(NUM_CLASSES) if average != "binary" else None
    avg = None if average == "none" else average
    return sk_fbeta(target, preds, beta=beta, average=avg, labels=labels, zero_division=0)


def _sk_specificity(preds, target, average="macro"):
    preds, target = _to_labels(preds, target)
    mcm = multilabel_confusion_matrix(target, preds, labels=np.arange(NUM_CLASSES))
    tn, fp = mcm[:, 0, 0].astype(float), mcm[:, 0, 1].astype(float)
    spec_per_class = np.divide(tn, tn + fp, out=np.zeros_like(tn), where=(tn + fp) > 0)
    if average == "macro":
        return spec_per_class.mean()
    if average == "micro":
        return tn.sum() / (tn.sum() + fp.sum())
    return spec_per_class


@pytest.mark.parametrize("average", ["micro", "macro", "weighted"])
@pytest.mark.parametrize(
    "preds, target",
    [
        (_input_multiclass.preds, _input_multiclass.target),
        (_input_multiclass_prob.preds, _input_multiclass_prob.target),
    ],
)
class TestPrecisionRecall(MetricTester):
    atol = 1e-6

    def test_precision(self, preds, target, average):
        self.run_class_metric_test(
            preds=preds,
            target=target,
            metric_class=Precision,
            sk_metric=partial(_sk_prec, average=average),
            metric_args={"average": average, "num_classes": NUM_CLASSES},
        )

    def test_recall(self, preds, target, average):
        self.run_class_metric_test(
            preds=preds,
            target=target,
            metric_class=Recall,
            sk_metric=partial(_sk_rec, average=average),
            metric_args={"average": average, "num_classes": NUM_CLASSES},
        )

    def test_fbeta(self, preds, target, average):
        self.run_class_metric_test(
            preds=preds,
            target=target,
            metric_class=FBetaScore,
            sk_metric=partial(_sk_fbeta_fn, average=average, beta=0.5),
            metric_args={"average": average, "num_classes": NUM_CLASSES, "beta": 0.5},
        )

    def test_f1(self, preds, target, average):
        self.run_class_metric_test(
            preds=preds,
            target=target,
            metric_class=F1Score,
            sk_metric=partial(_sk_fbeta_fn, average=average, beta=1.0),
            metric_args={"average": average, "num_classes": NUM_CLASSES},
        )

    def test_precision_fn(self, preds, target, average):
        self.run_functional_metric_test(
            preds,
            target,
            metric_functional=precision,
            sk_metric=partial(_sk_prec, average=average),
            metric_args={"average": average, "num_classes": NUM_CLASSES},
        )

    def test_recall_fn(self, preds, target, average):
        self.run_functional_metric_test(
            preds,
            target,
            metric_functional=recall,
            sk_metric=partial(_sk_rec, average=average),
            metric_args={"average": average, "num_classes": NUM_CLASSES},
        )


@pytest.mark.parametrize("average", ["micro", "macro"])
def test_specificity(average):
    preds, target = _input_multiclass.preds, _input_multiclass.target
    tester = MetricTester()
    tester.run_class_metric_test(
        preds=preds,
        target=target,
        metric_class=Specificity,
        sk_metric=partial(_sk_specificity, average=average),
        metric_args={"average": average, "num_classes": NUM_CLASSES},
        atol=1e-6,
    )
    tester.run_functional_metric_test(
        preds,
        target,
        metric_functional=specificity,
        sk_metric=partial(_sk_specificity, average=average),
        metric_args={"average": average, "num_classes": NUM_CLASSES},
        atol=1e-6,
    )


def test_none_average_per_class():
    preds, target = _input_multiclass.preds, _input_multiclass.target
    MetricTester().run_class_metric_test(
        preds=preds,
        target=target,
        metric_class=Precision,
        sk_metric=partial(_sk_prec, average="none"),
        metric_args={"average": "none", "num_classes": NUM_CLASSES},
        atol=1e-6,
    )
