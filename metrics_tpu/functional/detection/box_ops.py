"""Vectorized bounding-box operations (TPU-native torchvision.ops equivalents).

The reference delegates box math to torchvision's C++/CUDA kernels
(``box_convert``/``box_area``/``box_iou``, used at
/root/reference/torchmetrics/detection/map.py:23-27,318,367,398,433).  Here
they are pure jnp, batched over arbitrary leading dims so a whole
``[units, max_det, 4]`` buffer converts/intersects in one XLA op (SURVEY §2.9).
"""
from typing import Union

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array
ArrayLike = Union[Array, np.ndarray]

_ALLOWED_FMTS = ("xyxy", "xywh", "cxcywh")


def box_convert(boxes: ArrayLike, in_fmt: str, out_fmt: str) -> Array:
    """Convert ``[..., 4]`` boxes between xyxy / xywh / cxcywh formats.

    Semantics parity with torchvision.ops.box_convert (the reference's input
    normalization at map.py:318,325).
    """
    if in_fmt not in _ALLOWED_FMTS or out_fmt not in _ALLOWED_FMTS:
        raise ValueError(f"Unsupported Bounding Box Conversions for given in_fmt {in_fmt} and out_fmt {out_fmt}")
    boxes = jnp.asarray(boxes)
    if in_fmt == out_fmt:
        return boxes

    a, b, c, d = boxes[..., 0], boxes[..., 1], boxes[..., 2], boxes[..., 3]
    if in_fmt == "xywh":  # -> xyxy
        x1, y1, x2, y2 = a, b, a + c, b + d
    elif in_fmt == "cxcywh":  # -> xyxy
        x1, y1, x2, y2 = a - c / 2, b - d / 2, a + c / 2, b + d / 2
    else:
        x1, y1, x2, y2 = a, b, c, d

    if out_fmt == "xyxy":
        out = (x1, y1, x2, y2)
    elif out_fmt == "xywh":
        out = (x1, y1, x2 - x1, y2 - y1)
    else:
        out = ((x1 + x2) / 2, (y1 + y2) / 2, x2 - x1, y2 - y1)
    return jnp.stack(out, axis=-1)


def box_area(boxes: ArrayLike) -> Array:
    """Area of ``[..., 4]`` xyxy boxes (torchvision.ops.box_area parity)."""
    boxes = jnp.asarray(boxes)
    return (boxes[..., 2] - boxes[..., 0]) * (boxes[..., 3] - boxes[..., 1])


def box_iou(boxes1: ArrayLike, boxes2: ArrayLike) -> Array:
    """Pairwise IoU of xyxy boxes: ``[..., N, 4] x [..., M, 4] -> [..., N, M]``.

    Batched (vmap-free broadcasting) replacement for torchvision.ops.box_iou
    (map.py:367) — one fused XLA kernel over the full ``[U, D, G]`` buffer
    instead of a Python loop of per-(image,class) C++ calls.
    """
    boxes1 = jnp.asarray(boxes1)
    boxes2 = jnp.asarray(boxes2)
    area1 = box_area(boxes1)  # [..., N]
    area2 = box_area(boxes2)  # [..., M]

    lt = jnp.maximum(boxes1[..., :, None, :2], boxes2[..., None, :, :2])  # [..., N, M, 2]
    rb = jnp.minimum(boxes1[..., :, None, 2:], boxes2[..., None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]  # [..., N, M]
    union = area1[..., :, None] + area2[..., None, :] - inter
    return jnp.where(union > 0, inter / jnp.where(union > 0, union, 1.0), 0.0)
