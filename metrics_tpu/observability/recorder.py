"""Process-local metric telemetry: typed events, recompile detection,
sync/comm accounting, and state-memory high-water marks.

The ROADMAP north-star (a production system serving heavy traffic) needs to
know *where metric time goes*. Three failure modes are invisible without
instrumentation until a pod job is slow:

* **Silent XLA recompiles** — an unpadded batch pipeline feeds a new
  ``(shape, dtype)`` signature every step and each one retriggers
  compilation (the SNIPPETS pjit reference's call-site-mesh trap). The
  recorder tracks distinct argument signatures per entry point and warns
  once when a configurable threshold is crossed.
* **Host<->device syncs** — every cross-process ``gather_all_arrays`` and
  in-mesh ``sync_in_mesh`` records gather bytes, world size, and the pad
  waste of the pad-to-max uneven-shape contract.
* **Unbounded cat-state growth** — AUROC/ROC/PRC-style list states grow
  per update; ``Metric.state_footprint()`` plus the opt-in
  ``footprint_warn_bytes`` high-water-mark warning make the growth visible
  before it OOMs a host.

Zero-overhead contract: when the recorder is disabled (the default), the
only cost on the metric hot path is ONE attribute/bool check
(``_TELEMETRY.enabled``) — no event objects are allocated, no timestamps
taken, no locks touched. Verified by ``bench.py telemetry``.

All warning/export paths are rank-zero-gated through
``metrics_tpu.utils.prints`` so multi-host jobs emit one copy.
"""
from __future__ import annotations

import contextvars
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from metrics_tpu.utils.prints import rank_zero_warn

#: ambient span stack (innermost last) — lives here rather than in
#: ``trace.py`` so the recorder can annotate every event with the active
#: span without importing the trace module (which imports this one).
#: Context-local (contextvars), so threads AND async tasks nest correctly.
_SPAN_STACK: "contextvars.ContextVar[Tuple[int, ...]]" = contextvars.ContextVar(
    "metrics_tpu_span_stack", default=()
)


def current_span_id() -> Optional[int]:
    """Id of the innermost active :func:`metrics_tpu.observability.span`,
    or ``None`` outside any span."""
    stack = _SPAN_STACK.get()
    return stack[-1] if stack else None

#: environment variable holding a JSONL path; when set, the default recorder
#: auto-enables at import and entry points append their events to that path
#: (see ``maybe_export_env``) — how ``bench.py``/``__graft_entry__.py``
#: thread one artifact through their subprocesses
TELEMETRY_ENV_VAR = "METRICS_TPU_TELEMETRY"

#: core lifecycle event types; auxiliary events ("recompile_warning",
#: "footprint", "tracker_increment", "span", "compile", "fused_update",
#: and the async-pipeline "enqueue"/"dequeue"/"flush") ride the same stream
EVENT_TYPES = ("update", "compute", "forward", "sync")

#: footprint-HWM label for bytes pinned by the async update pipeline
#: (queued batch payloads + donated in-flight state buffers) — the memory
#: ``state_footprint()`` alone undercounts while an update is in flight
ASYNC_IN_FLIGHT_LABEL = "async_in_flight"

#: footprint keys under this prefix (SlicedMetric's [S]-leading states) are
#: attributed to a separate `<Metric>[sliced]` HWM label, so slice-axis
#: growth never masquerades as base-state growth in the high-water marks
SLICED_FOOTPRINT_PREFIX = "sliced/"

#: HWM-label suffix for the sliced split of a metric's footprint
SLICED_LABEL_SUFFIX = "[sliced]"


#: footprint keys under this prefix (fixed-capacity sketch leaves,
#: metrics_tpu/sketches/) are a BOUNDED budget, not an accumulation — the
#: HWM label split keeps them from masquerading as cat-state growth
SKETCH_FOOTPRINT_PREFIX = "sketch/"

#: HWM-label suffix for the sketch split of a metric's footprint
SKETCH_LABEL_SUFFIX = "[sketch]"

#: footprint keys under this prefix (WindowedMetric's [R]-leading ring /
#: decayed states, metrics_tpu/windowed/) are the R-fold window budget —
#: split to their own HWM label so window cost never masquerades as
#: base-state growth
WINDOWED_FOOTPRINT_PREFIX = "windowed/"

#: HWM-label suffix for the windowed split of a metric's footprint
WINDOWED_LABEL_SUFFIX = "[windowed]"


# ---------------------------------------------------------------------------
# standard time-series names (fed when a TimeSeriesRegistry is attached via
# ``attach_timeseries`` — see observability/timeseries.py). Defined HERE, not
# in timeseries.py, so the jax-free recorder module owns the vocabulary the
# health rules (observability/health.py) reference, the same way it owns the
# footprint prefixes.
# ---------------------------------------------------------------------------

#: per-call wall time distributions (ms) — one series per lifecycle phase
SERIES_UPDATE_MS = "update_ms"
SERIES_COMPUTE_MS = "compute_ms"
SERIES_FORWARD_MS = "forward_ms"
#: host wall time of one fused collection dispatch (ms)
SERIES_FUSED_DISPATCH_MS = "fused_dispatch_ms"
#: batch rows ingested through fused dispatches (counter — rolling rows/sec)
SERIES_INGEST_ROWS = "ingest_rows"
#: async pipeline: apply (dequeue->install) wall time per batch (ms)
SERIES_ASYNC_APPLY_MS = "async_apply_ms"
#: async pipeline: enqueue->apply age per batch (ms) — the live staleness
#: signal the bounded-staleness contract is about
SERIES_ASYNC_AGE_MS = "async_age_ms"
#: async pipeline: outstanding batches observed at enqueue/dequeue
SERIES_ASYNC_QUEUE_DEPTH = "async_queue_depth"
#: async pipeline: compute-snapshot staleness in unapplied batches
SERIES_ASYNC_STALENESS = "async_staleness_steps"
#: async pipeline: accepted / dropped batch counters
SERIES_ASYNC_ENQUEUED = "async_enqueued"
SERIES_ASYNC_DROPPED = "async_dropped"
#: new (shape, dtype) signatures at jitted entry points — each one is an
#: XLA (re)compilation trigger; a storm of them is the classic ragged-batch
#: failure mode the recompile alarm watches
SERIES_RECOMPILES = "recompiles"
#: sketch capacity-fill ratios reported from cold computes
SERIES_SKETCH_FILL = "sketch_fill_ratio"
#: sliced scatter: rows ingested (counter) and the per-batch share of rows
#: landing in the single hottest slice (hot-slice skew signal)
SERIES_SLICED_ROWS = "sliced_rows"
SERIES_HOT_SLICE_SHARE = "hot_slice_share"
#: exporter ticks that raised (PeriodicExporter hardening)
SERIES_EXPORT_ERRORS = "export_errors"
#: sampled model-score observations (fed by serving loops via
#: ``record_scores``) — the live distribution the drift alarm compares
#: against its frozen reference window
SERIES_SCORES = "scores"
#: fleet collector: worst per-publisher snapshot lag observed at a poll
#: (seconds behind the collector clock) — the ``publisher_stale`` signal
SERIES_PUBLISHER_LAG = "publisher_lag_s"
#: fleet collector: unfolded snapshots (queued files + in-window pending
#: deltas) observed at a poll — the ``snapshot_backlog`` signal
SERIES_COLLECTOR_BACKLOG = "collector_backlog"
#: fleet collector: fold errors (undecodable/foreign/mismatched/failed
#: snapshots) per poll — the ``fold_error`` signal
SERIES_FOLD_ERRORS = "collector_fold_errors"
#: read plane: reads served (counter — compute()/window_state()/
#: fold_values() calls, cache hits included)
SERIES_READS = "reads"
#: read plane: per-read wall time distribution (ms) — the ``read_latency``
#: alarm signal
SERIES_READ_MS = "read_ms"
#: read plane: fan-in (contributing publishers/states folded) per fleet read
SERIES_READ_FANIN = "read_fanin"
#: read plane: observed ingest-to-visible staleness per read (seconds) —
#: the ``freshness_slo`` alarm signal, fed from FreshnessStamp-carrying
#: reads (see observability/freshness.py)
SERIES_FRESHNESS_AGE_S = "freshness_age_s"
#: memory plane (observability/memory.py): live committed state bytes the
#: MemoryLedger attributes to metric state pytrees (dedup by buffer identity)
SERIES_MEM_LEDGER_BYTES = "mem_ledger_bytes"
#: memory plane: bytes held by registered cache planes (reader caches,
#: fused compile cache, retrieval layout LRU, sketch scratch, sliced value
#: cache) at an observation
SERIES_MEM_CACHE_BYTES = "mem_cache_plane_bytes"
#: memory plane: backend-reported bytes_in_use (host-RSS fallback on
#: backends that report no memory stats — see the observation's ``source``)
SERIES_MEM_DEVICE_BYTES = "mem_device_bytes_in_use"
#: memory plane: device_in_use − ledger − cache planes — the leak signal
#: the ``memory_leak`` alarm watches for monotone growth
SERIES_MEM_UNACCOUNTED = "mem_unaccounted_bytes"
#: memory plane: sliced state bytes per tenant (slice) — the
#: ``memory_budget`` alarm signal, ROADMAP item 3's headline denominator
SERIES_MEM_BYTES_PER_TENANT = "mem_bytes_per_tenant"

#: the standard counter-kind series; every other standard series is a
#: distribution (sketch-backed)
COUNTER_SERIES = (
    SERIES_INGEST_ROWS,
    SERIES_ASYNC_ENQUEUED,
    SERIES_ASYNC_DROPPED,
    SERIES_RECOMPILES,
    SERIES_SLICED_ROWS,
    SERIES_EXPORT_ERRORS,
    SERIES_FOLD_ERRORS,
    SERIES_READS,
)


def _new_sliced_totals() -> Dict[str, int]:
    return {"scatter_events": 0, "rows": 0, "max_slices": 0}


def _new_memory_totals() -> Dict[str, Any]:
    """Zeroed memory-plane counters: boundary/observation/cache-plane event
    counts and layout-cache eviction tallies (extensive — summed across
    hosts) plus last-seen and high-water gauges for the ledger, the cache
    planes, the backend in-use bytes, the unaccounted residue, and the
    bytes/tenant headline (maxed across hosts). All host ints/floats —
    TL-STATE-clean, never traced, never device-resident."""
    return {
        "events": 0,
        "update_boundaries": 0,
        "compute_boundaries": 0,
        "reset_boundaries": 0,
        "observations": 0,
        "cache_plane_events": 0,
        "plane_evictions": 0,
        "plane_evicted_bytes": 0,
        "ledger_bytes": 0,
        "max_ledger_bytes": 0,
        "cache_plane_bytes": 0,
        "max_cache_plane_bytes": 0,
        "device_bytes_in_use": 0,
        "max_device_bytes_in_use": 0,
        "unaccounted_bytes": 0,
        "max_unaccounted_bytes": 0,
        "boundary_live_bytes": 0,
        "max_boundary_live_bytes": 0,
        "bytes_per_tenant": 0.0,
        "max_bytes_per_tenant": 0.0,
    }


def _new_read_totals() -> Dict[str, float]:
    """Zeroed read-plane counters: reads served and what they folded
    (extensive — summed across hosts) plus high-water gauges for the
    worst read latency and the widest fleet fan-in (maxed across hosts)."""
    return {
        "reads": 0,
        "cache_hits": 0,
        "leaves_folded": 0,
        "ring_buckets_folded": 0,
        "table_rows_unpacked": 0,
        "fanin": 0,
        "read_s_total": 0.0,
        "max_read_ms": 0.0,
        "max_fanin": 0,
    }


def _new_freshness_totals() -> Dict[str, Any]:
    """Zeroed freshness aggregates, merged via MIN/MAX identity like the
    gauge families: ``min_event_t``/``max_event_t`` (wall clock of the
    oldest/newest contribution visible to any read; ``None`` until a
    stamped read happens — the identity element) plus high-water gauges
    for the observed staleness components."""
    return {
        "stamps": 0,
        "min_event_t": None,
        "max_event_t": None,
        "max_staleness_s": 0.0,
        "max_async_age_s": 0.0,
        "max_ring_span_s": 0.0,
        "max_watermark_lag_s": 0.0,
    }


def _new_sketch_totals() -> Dict[str, float]:
    """Zeroed sketch counters: cross-rank/pairwise sketch merges performed
    (extensive — summed across hosts) plus last-seen and high-water
    capacity-fill ratio gauges (maxed across hosts)."""
    return {"merges": 0, "fill_ratio": 0.0, "max_fill_ratio": 0.0}


def _new_fleet_totals() -> Dict[str, float]:
    """Zeroed fleet-collector counters: snapshot ingest outcomes and fold
    errors (extensive — summed across hosts) plus last-seen and high-water
    gauges for the backlog and the worst publisher lag."""
    return {
        "absorbed": 0,
        "duplicates": 0,
        "late_dropped": 0,
        "fold_errors": 0,
        "backlog": 0,
        "max_backlog": 0,
        "publisher_lag_s": 0.0,
        "max_publisher_lag_s": 0.0,
        "publishers": 0,
    }


def _new_async_totals() -> Dict[str, int]:
    """Zeroed async-pipeline counters: extensive batch counts (enqueued/
    applied/dropped/flushes — summed across hosts) plus last-seen and
    high-water gauges for queue depth, compute staleness, and in-flight
    bytes."""
    return {
        "enqueued": 0,
        "applied": 0,
        "dropped": 0,
        "flushes": 0,
        "queue_depth": 0,
        "max_queue_depth": 0,
        "staleness_steps": 0,
        "max_staleness_steps": 0,
        "in_flight_bytes": 0,
        "max_in_flight_bytes": 0,
    }


def _signature_of(args: Any, kwargs: Any) -> Tuple:
    """The ``(shape, dtype)`` signature of every array leaf in a call's
    arguments — exactly the key XLA's jit cache discriminates on, so a
    growing set of signatures at one entry point means recompiles."""
    parts: List[Tuple] = []

    def walk(obj: Any) -> None:
        shape = getattr(obj, "shape", None)
        dtype = getattr(obj, "dtype", None)
        if shape is not None and dtype is not None:
            parts.append((tuple(shape), str(dtype)))
        elif isinstance(obj, (list, tuple)):
            for o in obj:
                walk(o)
        elif isinstance(obj, dict):
            try:
                items = sorted(obj.items())
            except TypeError:
                items = list(obj.items())
            for _, o in items:
                walk(o)

    walk(args)
    if kwargs:
        walk(kwargs)
    return tuple(parts)


def _nbytes(value: Any) -> int:
    """Best-effort nbytes of an array (works on tracers: static shape*itemsize).

    Deleted arrays count 0: a donated buffer mid-dispatch pins no memory of
    its own (XLA aliases it into the kernel's output), so counting its
    metadata nbytes would double-book the bytes the async pipeline already
    reports as donated in-flight state."""
    is_deleted = getattr(value, "is_deleted", None)
    if callable(is_deleted):
        try:
            if is_deleted():
                return 0
        except Exception:  # noqa: BLE001 — foreign array types may refuse
            pass
    nb = getattr(value, "nbytes", None)
    if isinstance(nb, int):
        return nb
    size = getattr(value, "size", None)
    dtype = getattr(value, "dtype", None)
    if size is not None and dtype is not None:
        try:
            return int(size) * int(dtype.itemsize)
        except (TypeError, AttributeError):
            return 0
    return 0


class MetricRecorder:
    """Collects typed telemetry events from the metric runtime.

    Not a per-metric object: ONE recorder observes every metric in the
    process (the registry in ``metrics_tpu.observability`` hands out named
    instances; the ``"default"`` one is wired into the runtime hot paths).

    The public surface intended for users is ``enable()``/``disable()``/
    ``reset()``, the read accessors (``events``/``call_counts``/
    ``signature_counts``/``sync_totals``), and the exporters
    (``export_jsonl``/``render_prometheus``/``summary``). The ``record_*``
    methods are the runtime's hook points; callers must check ``.enabled``
    first — that check IS the zero-overhead gate.
    """

    DEFAULT_RECOMPILE_THRESHOLD = 8
    MAX_EVENTS = 200_000
    #: minimum seconds between emitted ``memory`` event rows per boundary
    #: kind — the boundary counters stay exact, only the stream is paced
    MEMORY_EVENT_INTERVAL_S = 0.25

    def __init__(
        self,
        name: str = "default",
        recompile_threshold: int = DEFAULT_RECOMPILE_THRESHOLD,
        footprint_warn_bytes: Optional[int] = None,
        profile_compiles: bool = False,
    ) -> None:
        self.name = name
        self.enabled = False
        self.recompile_threshold = recompile_threshold
        self.footprint_warn_bytes = footprint_warn_bytes
        #: opt-in compiled-cost attribution: when True, every NEW call
        #: signature at a metric entry point (i.e. every recompile the
        #: signature tracker detects) is billed by lowering+compiling the
        #: metric's pure ``update_state`` and recording a ``compile`` event
        #: with the XLA cost analysis (see observability/profiling.py)
        self.profile_compiles = profile_compiles
        self._lock = threading.Lock()
        self._t0 = time.time()
        self._events: List[Dict[str, Any]] = []
        self._dropped = 0
        self._counts: Dict[Tuple[str, str], int] = {}
        self._times: Dict[Tuple[str, str], float] = {}
        self._signatures: Dict[str, set] = {}
        self._recompile_warned: set = set()
        self._footprint_warned: set = set()
        self._footprint_hwm: Dict[str, int] = {}
        self._sync_bytes = 0
        self._pad_waste_bytes = 0
        self._sync_events = 0
        self._compile_counts: Dict[str, int] = {}
        self._compile_times: Dict[str, float] = {}
        self._fused_updates = 0
        self._fused_metric_updates = 0
        self._fused_fallback_updates = 0
        self._async = _new_async_totals()
        self._sliced = _new_sliced_totals()
        self._sliced_slice_counts: Dict[str, int] = {}
        self._sketch = _new_sketch_totals()
        self._reads = _new_read_totals()
        self._freshness = _new_freshness_totals()
        self._memory = _new_memory_totals()
        #: per-boundary-kind wall clock of the last emitted ``memory`` event
        #: — boundary COUNTERS are exact, boundary EVENT rows are throttled
        #: to MEMORY_EVENT_INTERVAL_S so an eager update loop cannot flood
        #: the ring buffer with byte snapshots
        self._memory_last_event: Dict[str, float] = {}
        #: "source|stat" -> last observed drift score (gauges; fed by the
        #: health layer's DriftRule evaluations — see record_drift_score)
        self._drift: Dict[str, float] = {}
        self._fleet = _new_fleet_totals()
        #: "op|backend" -> dispatches through the ops kernel registry
        #: (ops/dispatch.py) — which backends actually ran kernels vs
        #: fallbacks; see record_ops_dispatch
        self._ops_dispatch: Dict[str, int] = {}
        self._export_errors = 0
        #: monotonic provenance sequence for exported counter payloads —
        #: see ``next_snapshot_seq`` / ``aggregate.counter_payload``
        self._snapshot_seq = 0
        #: tid -> thread name, registered as events from new threads arrive —
        #: export_perfetto emits these as thread_name metadata so the async
        #: worker's spans land on their own labeled track
        self._thread_names: Dict[int, str] = {}
        #: attached TimeSeriesRegistry (None = the windowed layer is off and
        #: costs one attribute check per hook) — see attach_timeseries()
        self.timeseries: Optional[Any] = None
        # per-thread compute-group attribution: a shared field would let
        # concurrent MetricCollection.update calls cross-attribute events
        self._group_local = threading.local()

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def enable(
        self,
        recompile_threshold: Optional[int] = None,
        footprint_warn_bytes: Optional[int] = None,
        profile_compiles: Optional[bool] = None,
    ) -> "MetricRecorder":
        if recompile_threshold is not None:
            self.recompile_threshold = recompile_threshold
        if footprint_warn_bytes is not None:
            self.footprint_warn_bytes = footprint_warn_bytes
        if profile_compiles is not None:
            self.profile_compiles = profile_compiles
        self.enabled = True
        return self

    def disable(self) -> "MetricRecorder":
        self.enabled = False
        return self

    def attach_timeseries(self, registry: Optional[Any] = None, **kwargs: Any) -> Any:
        """Attach a :class:`~metrics_tpu.observability.timeseries.
        TimeSeriesRegistry` (created from ``**kwargs`` when not given) and
        start feeding the standard windowed series (``SERIES_*``) from the
        recorder's hooks. Returns the registry. Idempotent-friendly: a
        second call replaces the registry."""
        if registry is None:
            from metrics_tpu.observability.timeseries import TimeSeriesRegistry

            registry = TimeSeriesRegistry(**kwargs)
        self.timeseries = registry
        return registry

    def detach_timeseries(self) -> "MetricRecorder":
        """Stop feeding windowed series (the registry is dropped)."""
        self.timeseries = None
        return self

    def tick(self) -> int:
        """Deferred telemetry housekeeping: fold the attached time-series'
        pending observations into their bucket sketches now, instead of
        letting the bounded inline flush fire inside a latency-sensitive
        read. Serving loops call this between probe reads; it is a no-op
        (returning 0) with no registry attached."""
        ts = self.timeseries
        if ts is None:
            return 0
        try:
            return int(ts.housekeep())
        except Exception:  # noqa: BLE001 — telemetry must never take down the hot path
            return 0

    def _observe(self, name: str, value: float) -> None:
        """Feed one observation into the attached registry (no-op when
        detached). Called OUTSIDE the recorder lock — the registry has its
        own leaf lock and never calls back into the recorder."""
        ts = self.timeseries
        if ts is not None:
            try:
                ts.observe(name, value, kind="counter" if name in COUNTER_SERIES else "distribution")
            except Exception:  # noqa: BLE001 — telemetry must never take down the hot path
                pass

    def reset(self) -> "MetricRecorder":
        with self._lock:
            self._t0 = time.time()
            self._events = []
            self._dropped = 0
            self._counts = {}
            self._times = {}
            self._signatures = {}
            self._recompile_warned = set()
            self._footprint_warned = set()
            self._footprint_hwm = {}
            self._sync_bytes = 0
            self._pad_waste_bytes = 0
            self._sync_events = 0
            self._compile_counts = {}
            self._compile_times = {}
            self._fused_updates = 0
            self._fused_metric_updates = 0
            self._fused_fallback_updates = 0
            self._async = _new_async_totals()
            self._sliced = _new_sliced_totals()
            self._sliced_slice_counts = {}
            self._sketch = _new_sketch_totals()
            self._reads = _new_read_totals()
            self._freshness = _new_freshness_totals()
            self._memory = _new_memory_totals()
            self._memory_last_event = {}
            self._drift = {}
            self._fleet = _new_fleet_totals()
            self._ops_dispatch = {}
            self._export_errors = 0
            # the snapshot sequence survives reset ON PURPOSE: provenance
            # must stay monotonic for the publisher's whole lifetime, or a
            # collector's dedup would see post-reset payloads as replays
            self._thread_names = {}
            self._group_local = threading.local()
        # the windowed layer stays ATTACHED across reset (long jobs reset the
        # event buffer periodically; the ring is fixed-capacity and must keep
        # observing) but its data clears with everything else
        ts = self.timeseries
        if ts is not None:
            ts.reset()
        return self

    # ------------------------------------------------------------------
    # read accessors
    # ------------------------------------------------------------------
    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def call_counts(self) -> Dict[Tuple[str, str], int]:
        with self._lock:
            return dict(self._counts)

    def call_times(self) -> Dict[Tuple[str, str], float]:
        with self._lock:
            return dict(self._times)

    def signature_counts(self) -> Dict[str, int]:
        with self._lock:
            return {k: len(v) for k, v in self._signatures.items()}

    def sync_totals(self) -> Dict[str, int]:
        with self._lock:
            return {
                "sync_events": self._sync_events,
                "gather_bytes": self._sync_bytes,
                "pad_waste_bytes": self._pad_waste_bytes,
            }

    def footprint_high_water_marks(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._footprint_hwm)

    def compile_counts(self) -> Dict[str, int]:
        """Recorded XLA (re)compilations per entry point (``compile`` events)."""
        with self._lock:
            return dict(self._compile_counts)

    def compile_times(self) -> Dict[str, float]:
        """Cumulative trace+lower+compile wall seconds per entry point."""
        with self._lock:
            return dict(self._compile_times)

    def fused_update_totals(self) -> Dict[str, int]:
        """Aggregate fused-collection-update counters: batches dispatched
        through the fused path, metric updates served inside fused kernels,
        and metric updates that fell back to the eager loop."""
        with self._lock:
            return {
                "fused_updates": self._fused_updates,
                "fused_metric_updates": self._fused_metric_updates,
                "fallback_metric_updates": self._fused_fallback_updates,
            }

    def async_totals(self) -> Dict[str, int]:
        """Async-pipeline counters: batches enqueued/applied/dropped and
        flush count (extensive), plus last-seen and high-water gauges for
        queue depth, compute-snapshot staleness, and in-flight bytes."""
        with self._lock:
            return dict(self._async)

    def sliced_totals(self) -> Dict[str, int]:
        """Sliced-scatter counters: segment-scatter updates recorded (once
        per eager update, once per TRACE under the fused kernel), total rows
        scattered, and the largest slice count seen."""
        with self._lock:
            return dict(self._sliced)

    def sketch_totals(self) -> Dict[str, float]:
        """Sketch-state counters: cross-rank/pairwise sketch merges
        performed, plus the last-seen and high-water capacity-fill ratios
        reported from the compute path."""
        with self._lock:
            return dict(self._sketch)

    def footprint_slice_counts(self) -> Dict[str, int]:
        """``num_slices`` per ``<Metric>[sliced]`` HWM label — what the
        summary exporter divides by for the per-slice average."""
        with self._lock:
            return dict(self._sliced_slice_counts)

    def drift_scores(self) -> Dict[str, float]:
        """Last observed drift score per ``"source|stat"`` key (the
        ``metrics_tpu_drift_score{metric,stat}`` Prometheus family's raw
        data; gauges — merged max-wise across hosts)."""
        with self._lock:
            return dict(self._drift)

    def fleet_totals(self) -> Dict[str, float]:
        """Fleet-collector counters: snapshot ingest outcomes (absorbed/
        duplicates/late_dropped — extensive), fold errors, plus last-seen
        and high-water gauges for the unfolded backlog and the worst
        publisher lag. Fed by ``FleetCollector`` polls via
        ``record_fleet_poll``."""
        with self._lock:
            return dict(self._fleet)

    def read_totals(self) -> Dict[str, float]:
        """Read-plane counters: reads served (cache hits included) and what
        they folded — state leaves, ring buckets, retrieval-table rows —
        plus high-water gauges for the worst read latency and the widest
        fleet fan-in. Fed by ``record_read`` from every ``compute()``/
        ``window_state()``/``fold_values()`` entry point."""
        with self._lock:
            return dict(self._reads)

    def freshness_totals(self) -> Dict[str, Any]:
        """Freshness aggregates from stamped reads: wall clock of the
        oldest/newest contribution any read saw (``None`` identity until a
        stamped read happens) plus high-water staleness-component gauges.
        Merged across hosts via min/max identity like the gauge families."""
        with self._lock:
            return dict(self._freshness)

    def memory_totals(self) -> Dict[str, Any]:
        """Memory-plane counters: update/compute/reset boundary tallies,
        observatory polls, cache-plane events and eviction totals
        (extensive), plus last-seen and high-water gauges for the ledger
        bytes, the cache-plane inventory, the backend in-use bytes, the
        unaccounted residue, and bytes/tenant. Fed by
        ``record_memory_boundary`` / ``record_memory_observation`` /
        ``record_cache_plane`` — see observability/memory.py."""
        with self._lock:
            return dict(self._memory)

    def ops_dispatch_totals(self) -> Dict[str, int]:
        """Kernel-registry dispatches per ``"op|backend"`` key (backend in
        ``pallas | jnp | interpret``) — the raw data behind the Prometheus
        family ``metrics_tpu_ops_dispatch_total{op,backend}``. Extensive:
        summed across hosts by ``aggregate_across_hosts``."""
        with self._lock:
            return dict(self._ops_dispatch)

    def next_snapshot_seq(self) -> int:
        """The next monotonic provenance sequence number for an exported
        counter payload / fleet snapshot from this process. Monotonic for
        the recorder's lifetime (``reset()`` does NOT rewind it — a
        collector's duplicate detection keys on it)."""
        with self._lock:
            seq = self._snapshot_seq
            self._snapshot_seq += 1
            return seq

    def export_errors(self) -> int:
        """Exporter ticks that raised (see ``PeriodicExporter``) — a
        nonzero count means telemetry artifacts may be stale."""
        with self._lock:
            return self._export_errors

    def thread_names(self) -> Dict[int, str]:
        """tid -> thread name for every thread that recorded a span or an
        async-pipeline event (Perfetto track labeling)."""
        with self._lock:
            return dict(self._thread_names)

    def dropped_events(self) -> int:
        """Events discarded after the MAX_EVENTS buffer cap (aggregate
        counters still include them; the JSONL stream does not)."""
        with self._lock:
            return self._dropped

    # ------------------------------------------------------------------
    # hook points (callers check ``.enabled`` first)
    # ------------------------------------------------------------------
    def _append(self, event: Dict[str, Any]) -> None:
        # caller holds the lock
        stack = _SPAN_STACK.get()
        if stack and "span_id" not in event:
            # attribute every event to the innermost active trace span so
            # flat rows ("an update inside a collection forward inside a
            # sync") regain their nesting in post-hoc analysis
            event["span_id"] = stack[-1]
        if len(self._events) >= self.MAX_EVENTS:
            self._dropped += 1
            if self._dropped == 1:
                # surface the cap the moment it first bites — a silently
                # truncated JSONL artifact would misread as complete coverage
                rank_zero_warn(
                    f"Telemetry: the event buffer reached its {self.MAX_EVENTS}-event"
                    " cap; further events are dropped (aggregate counters keep"
                    " counting). Export and reset() periodically for long runs."
                    " The dropped count is reported by dropped_events(), summary(),"
                    " and the Prometheus page.",
                    UserWarning,
                )
            return
        self._events.append(event)

    def record_call(
        self,
        phase: str,
        metric: Any,
        duration_s: float,
        args: Tuple = (),
        kwargs: Optional[Dict[str, Any]] = None,
    ) -> bool:
        """Record one update/compute/forward lifecycle call with its wall
        time and argument signature (and feed recompile detection).

        Returns True when the call carried a signature NOT seen before at
        this entry point — i.e. a call that (re)triggers XLA compilation of
        the metric's jitted kernels; the caller may then attribute the
        compile cost (see ``profile_compiles``)."""
        label = type(metric).__name__
        sig = _signature_of(args, kwargs) if (args or kwargs) else ()
        with self._lock:
            key = (label, phase)
            self._counts[key] = self._counts.get(key, 0) + 1
            self._times[key] = self._times.get(key, 0.0) + duration_s
            event: Dict[str, Any] = {
                "type": phase,
                "metric": label,
                "t": round(time.time() - self._t0, 6),
                "dur_ms": round(duration_s * 1e3, 4),
                "n_calls": self._counts[key],
            }
            if sig:
                # events store at most 8 leaves (detection-style structured
                # inputs carry thousands); recompile detection below keys on
                # the FULL tuple regardless
                event["signature"] = [[list(shape), dtype] for shape, dtype in sig[:8]]
                if len(sig) > 8:
                    event["signature_leaves"] = len(sig)
            group = getattr(self._group_local, "group", None)
            if group is not None:
                event["compute_group"] = list(group)
            self._append(event)
        if phase in ("update", "compute", "forward"):
            # windowed per-phase latency distributions (SERIES_UPDATE_MS ...)
            self._observe(f"{phase}_ms", duration_s * 1e3)
        if sig and phase in ("update", "forward"):
            return self.track_signature(f"{label}.{phase}", signature=sig)
        return False

    def track_signature(self, entry: str, *args: Any, signature: Optional[Tuple] = None, **kwargs: Any) -> bool:
        """Note one call signature for a jitted entry point; warn (once per
        entry, rank-zero) when the distinct-signature count crosses
        ``recompile_threshold`` — the classic "unpadded batch -> recompile
        every step" bug. Functional/jit users can call this directly with
        their traced arguments.

        Returns True when the signature is NEW for this entry point (a
        compilation trigger), False for a cache hit."""
        sig = signature if signature is not None else _signature_of(args, kwargs)
        with self._lock:
            seen = self._signatures.setdefault(entry, set())
            before = len(seen)
            seen.add(sig)
            is_new = len(seen) > before
            crossed = (
                is_new
                and len(seen) > self.recompile_threshold
                and entry not in self._recompile_warned
            )
            if crossed:
                self._recompile_warned.add(entry)
                n = len(seen)
                self._append(
                    {
                        "type": "recompile_warning",
                        "entry": entry,
                        "distinct_signatures": n,
                        "threshold": self.recompile_threshold,
                        "t": round(time.time() - self._t0, 6),
                    }
                )
        if crossed:
            rank_zero_warn(
                f"Telemetry: entry point `{entry}` has now seen {n} distinct"
                f" (shape, dtype) argument signatures (threshold"
                f" {self.recompile_threshold}). Every new signature retriggers XLA"
                " compilation for jitted metric code — pad or bucket your batches"
                " to a fixed shape, or raise the threshold via"
                " `get_recorder().enable(recompile_threshold=...)` if the shapes"
                " are genuinely static-bounded.",
                UserWarning,
            )
        if is_new:
            # every new signature is an XLA compilation trigger — the
            # windowed rate of this counter is the recompile-storm signal
            self._observe(SERIES_RECOMPILES, 1)
        return is_new

    def record_compile(
        self,
        entry: str,
        trace_s: float = 0.0,
        lower_s: float = 0.0,
        compile_s: float = 0.0,
        cost: Optional[Dict[str, float]] = None,
        memory: Optional[Dict[str, int]] = None,
        **extra: Any,
    ) -> None:
        """Record one attributed XLA compilation: the trace/lower/compile
        wall-time breakdown plus the compiler's cost analysis (flops, bytes
        accessed) and, where the backend provides it, the compiled memory
        stats. Emitted by :func:`metrics_tpu.observability.compiled_cost`
        and by the recompile hook in ``core/metric.py`` (when
        ``profile_compiles`` is on) — turning the recompile warning's count
        into a bill."""
        total_s = float(trace_s) + float(lower_s) + float(compile_s)
        with self._lock:
            self._compile_counts[entry] = self._compile_counts.get(entry, 0) + 1
            self._compile_times[entry] = self._compile_times.get(entry, 0.0) + total_s
            event: Dict[str, Any] = {
                "type": "compile",
                "entry": entry,
                "t": round(time.time() - self._t0, 6),
                "trace_ms": round(float(trace_s) * 1e3, 4),
                "lower_ms": round(float(lower_s) * 1e3, 4),
                "compile_ms": round(float(compile_s) * 1e3, 4),
                "n_compiles": self._compile_counts[entry],
            }
            if cost:
                event["cost_analysis"] = cost
            if memory:
                event["memory_analysis"] = memory
            event.update(extra)
            self._append(event)

    def record_sync(
        self,
        source: str,
        gather_bytes: int,
        world_size: int,
        pad_waste_bytes: int = 0,
        **extra: Any,
    ) -> None:
        """Record one cross-device/cross-process state synchronization.

        ``gather_bytes`` is the bytes of synced state received per
        participant (concat/gather states count ``world_size`` shards;
        all-reduced states count one payload). ``pad_waste_bytes`` is the
        portion of those bytes that is pad-to-max padding, not data.
        """
        with self._lock:
            self._sync_events += 1
            self._sync_bytes += int(gather_bytes)
            self._pad_waste_bytes += int(pad_waste_bytes)
            event = {
                "type": "sync",
                "source": source,
                "gather_bytes": int(gather_bytes),
                "world_size": int(world_size),
                "pad_waste_bytes": int(pad_waste_bytes),
                "t": round(time.time() - self._t0, 6),
            }
            event.update(extra)
            self._append(event)

    def record_footprint(self, metric: Any, footprint: Dict[str, int], **extra: Any) -> None:
        """Record a state-memory snapshot and maintain the per-metric high
        water mark; warn once (rank-zero) when ``footprint_warn_bytes`` is
        configured and crossed — the unbounded-cat-state guard.

        Keys under ``sliced/`` (a ``SlicedMetric``'s [S]-leading states)
        are split out to a separate ``<Metric>[sliced]`` HWM label with the
        metric's ``num_slices`` remembered alongside, so the summary
        exporter can show a per-slice average and slice-axis growth never
        silently mixes with base-state growth under one mark."""
        label = type(metric).__name__
        total = int(sum(footprint.values()))
        windowed_bytes = int(
            sum(v for k, v in footprint.items() if k.startswith(WINDOWED_FOOTPRINT_PREFIX))
        )
        sliced_bytes = int(
            sum(v for k, v in footprint.items() if k.startswith(SLICED_FOOTPRINT_PREFIX))
        )
        sketch_bytes = int(
            sum(v for k, v in footprint.items() if k.startswith(SKETCH_FOOTPRINT_PREFIX))
        )
        base_bytes = total - sliced_bytes - sketch_bytes - windowed_bytes
        n_slices = getattr(metric, "num_slices", None) if sliced_bytes else None
        with self._lock:
            if windowed_bytes:
                # windowed ring/decay leaves are the R-fold window budget —
                # bounded by construction, tracked under their own mark
                windowed_label = label + WINDOWED_LABEL_SUFFIX
                if windowed_bytes > self._footprint_hwm.get(windowed_label, -1):
                    self._footprint_hwm[windowed_label] = windowed_bytes
            if sliced_bytes:
                sliced_label = label + SLICED_LABEL_SUFFIX
                if sliced_bytes > self._footprint_hwm.get(sliced_label, -1):
                    self._footprint_hwm[sliced_label] = sliced_bytes
                if isinstance(n_slices, int) and n_slices > 0:
                    self._sliced_slice_counts[sliced_label] = n_slices
            if sketch_bytes:
                # sketch leaves are a FIXED budget: the split keeps the
                # bounded bytes from tripping the cat-state growth warning's
                # mental model, and the HWM simply pins the budget
                sketch_label = label + SKETCH_LABEL_SUFFIX
                if sketch_bytes > self._footprint_hwm.get(sketch_label, -1):
                    self._footprint_hwm[sketch_label] = sketch_bytes
            if (
                base_bytes or not (sliced_bytes or sketch_bytes or windowed_bytes)
            ) and base_bytes > self._footprint_hwm.get(label, -1):
                self._footprint_hwm[label] = base_bytes
            event = {
                "type": "footprint",
                "metric": label,
                "total_bytes": total,
                "t": round(time.time() - self._t0, 6),
            }
            if sliced_bytes:
                event["sliced_bytes"] = sliced_bytes
                if isinstance(n_slices, int):
                    event["n_slices"] = n_slices
            if sketch_bytes:
                event["sketch_bytes"] = sketch_bytes
            if windowed_bytes:
                event["windowed_bytes"] = windowed_bytes
            event.update(extra)
            self._append(event)
            warn = (
                self.footprint_warn_bytes is not None
                and total > self.footprint_warn_bytes
                and label not in self._footprint_warned
            )
            if warn:
                self._footprint_warned.add(label)
        if warn:
            rank_zero_warn(
                f"Telemetry: metric `{label}` state footprint is {total} bytes,"
                f" above the configured high-water mark of"
                f" {self.footprint_warn_bytes} bytes. Unbounded list ('cat')"
                " states (AUROC/ROC/PRC-style curve accumulators) grow with"
                " every update — consider the fixed-capacity exact-curve mode"
                " or more frequent compute()+reset() cycles.",
                UserWarning,
            )

    def record_fused_update(
        self,
        n_metrics: int,
        n_fused: int,
        n_fallback: int,
        duration_s: float,
        batch_rows: Optional[int] = None,
        **extra: Any,
    ) -> None:
        """Record ONE fused collection update (one XLA dispatch serving
        ``n_fused`` metric updates, plus ``n_fallback`` eager fallbacks in
        the same batch). Exactly one ``fused_update`` event per batch is
        the fused path's dispatch-count contract — the guard test in
        tests/bases/test_fused.py pins it. ``batch_rows`` (the batch's
        leading dimension) feeds the windowed ingest-rate series."""
        with self._lock:
            self._fused_updates += 1
            self._fused_metric_updates += int(n_fused)
            self._fused_fallback_updates += int(n_fallback)
            event: Dict[str, Any] = {
                "type": "fused_update",
                "t": round(time.time() - self._t0, 6),
                "n_metrics": int(n_metrics),
                "n_fused": int(n_fused),
                "n_fallback": int(n_fallback),
                "dur_ms": round(duration_s * 1e3, 4),
            }
            if batch_rows is not None:
                event["batch_rows"] = int(batch_rows)
            event.update(extra)
            self._append(event)
        self._observe(SERIES_FUSED_DISPATCH_MS, duration_s * 1e3)
        if batch_rows is not None:
            self._observe(SERIES_INGEST_ROWS, int(batch_rows))

    def record_sketch_merge(self, n_merges: int = 1, **extra: Any) -> None:
        """Record ``n_merges`` pairwise sketch merges (cross-rank sync folds,
        ``merge_states`` calls). Counter-only — merges run inside sync/merge
        cold paths and inside traced collectives (where this hook fires once
        per TRACE, the in-jit accounting convention), so no event row is
        appended on their behalf."""
        with self._lock:
            self._sketch["merges"] += int(n_merges)

    def record_sketch_fill(self, metric: Any, ratios: Dict[str, float], **extra: Any) -> None:
        """Record capacity-fill ratios for a metric's sketch leaves (hooked
        from the cold ``compute`` path — reading occupancy syncs the leaf,
        which the update hot path must never do). Keeps last-seen and
        high-water gauges plus one ``sketch_fill`` event."""
        if not ratios:
            return
        worst = max(ratios.values())
        with self._lock:
            self._sketch["fill_ratio"] = worst
            self._sketch["max_fill_ratio"] = max(self._sketch["max_fill_ratio"], worst)
            event: Dict[str, Any] = {
                "type": "sketch_fill",
                "metric": type(metric).__name__,
                "ratios": {k: round(float(v), 6) for k, v in ratios.items()},
                "t": round(time.time() - self._t0, 6),
            }
            event.update(extra)
            self._append(event)
        self._observe(SERIES_SKETCH_FILL, worst)

    def record_scores(self, values: Any, series: str = SERIES_SCORES, max_samples: int = 32) -> None:
        """Feed a bounded sample of model scores into the windowed
        ``scores`` distribution series (no-op when no registry is
        attached). The drift alarm (``DriftRule`` in observability/
        health.py) freezes a reference window of this series and compares
        the live window against it. Host-only: ``values`` is read back
        once (callers on a hot path should pass host arrays); at most
        ``max_samples`` evenly-strided values are recorded per call so
        per-batch cost stays O(max_samples) whatever the batch size.
        Gated on ``enabled`` like every other feed: a disabled recorder
        pays one bool check and records nothing."""
        ts = self.timeseries
        if not self.enabled or ts is None:
            return
        try:
            import numpy as np

            arr = np.asarray(values, dtype=np.float64).reshape(-1)
            if arr.size == 0:
                return
            # ceil stride: floor would over-generate and the truncation
            # would then ALWAYS drop the batch tail — a biased sample when
            # batches are ordered (sorted scores, grouped tenants)
            stride = -(-arr.size // int(max_samples))
            for v in arr[::stride]:
                ts.observe(series, float(v), kind="distribution")
        except Exception:  # noqa: BLE001 — telemetry must never take down the hot path
            pass

    def record_drift_score(self, source: str, stat: str, value: float, **extra: Any) -> None:
        """Record one reference-vs-live drift score (``DriftRule``
        evaluations): a last-seen gauge per (source, stat) — rendered as
        the ``metrics_tpu_drift_score{metric,stat}`` Prometheus family and
        carried through the cross-host aggregate payload (merged max-wise,
        like every gauge family) — plus one ``drift`` event row so score
        trajectories survive in the JSONL stream."""
        key = f"{source}|{stat}"
        with self._lock:
            self._drift[key] = float(value)
            event: Dict[str, Any] = {
                "type": "drift",
                "source": source,
                "stat": stat,
                "value": round(float(value), 6),
                "t": round(time.time() - self._t0, 6),
            }
            event.update(extra)
            self._append(event)

    def record_sliced_scatter(
        self,
        metric: Any,
        n_rows: int,
        n_slices: int,
        n_leaves: int,
        in_jit: bool = False,
        hot_rows: Optional[int] = None,
        **extra: Any,
    ) -> None:
        """Record one slice-axis segment-scatter (``SlicedMetric._update``).

        On the eager path this is once per update; under the fused kernel
        the hook runs at TRACE time — once per compilation, not per executed
        batch (shapes are static), the same convention the in-jit sync-byte
        accounting uses. The counters are therefore dispatch-shaped on the
        eager path and compile-shaped on the fused one; ``bench.py sliced``
        reads the fused handle's ``n_compiles`` for the hard compile gate.

        ``hot_rows`` (eager path only — needs concrete slice ids) is the
        row count of the batch's single most-hit slice; its share of the
        batch feeds the windowed hot-slice-skew series the health layer
        alarms on.
        """
        with self._lock:
            self._sliced["scatter_events"] += 1
            self._sliced["rows"] += int(n_rows)
            self._sliced["max_slices"] = max(self._sliced["max_slices"], int(n_slices))
            event: Dict[str, Any] = {
                "type": "sliced_scatter",
                "metric": type(metric).__name__,
                "n_rows": int(n_rows),
                "n_slices": int(n_slices),
                "n_leaves": int(n_leaves),
                "in_jit": bool(in_jit),
                "t": round(time.time() - self._t0, 6),
            }
            if hot_rows is not None:
                event["hot_rows"] = int(hot_rows)
            event.update(extra)
            self._append(event)
        if not in_jit:
            # trace-time hooks are compile-shaped, not traffic-shaped — only
            # eager scatters feed the windowed ingest/skew series
            self._observe(SERIES_SLICED_ROWS, int(n_rows))
            if hot_rows is not None and n_rows:
                self._observe(SERIES_HOT_SLICE_SHARE, int(hot_rows) / int(n_rows))

    def record_ops_dispatch(self, op: str, backend: str) -> None:
        """Count one kernel-registry dispatch (``ops/dispatch.py``).

        Counter-only — no event append: a dispatched op can run inside
        every eager metric update (``_bincount`` under every
        confusion-matrix metric), and the per-call interest is which
        BACKEND served it, not each occurrence. Under jit the dispatch
        decision happens at trace time, so jitted traffic counts once per
        compilation — the same convention as the in-jit sliced-scatter
        accounting.
        """
        key = f"{op}|{backend}"
        with self._lock:
            self._ops_dispatch[key] = self._ops_dispatch.get(key, 0) + 1

    def record_async_event(
        self,
        kind: str,
        batch_index: Optional[int] = None,
        queue_depth: Optional[int] = None,
        staleness_steps: Optional[int] = None,
        in_flight_bytes: Optional[int] = None,
        dur_ms: Optional[float] = None,
        **extra: Any,
    ) -> None:
        """Record one async-pipeline transition (core/pipeline.py hooks).

        ``kind`` is one of the typed events — ``"enqueue"`` (exactly one per
        ACCEPTED batch: the per-batch observability contract the guard test
        in tests/bases/test_pipeline.py pins), ``"dequeue"`` (one per applied
        batch), ``"flush"`` (one per drain) — or a counter/gauge-only update:
        ``"drop"`` (a batch the drop policy discarded) and ``"snapshot"``
        (a bounded-staleness compute), which bump totals without adding an
        event. In-flight bytes also feed the footprint high-water mark under
        the ``async_in_flight`` label, so the memory pinned by queued
        batches and donated in-flight state shows up next to the per-metric
        state HWMs instead of being invisible exactly when pressure peaks.

        Every async event is stamped with the recording thread's id (and
        the tid -> name map updated), so the Perfetto export can land the
        worker's rows on their own labeled track.
        """
        tid = threading.get_ident()
        with self._lock:
            self._thread_names.setdefault(tid, threading.current_thread().name)
            totals = self._async
            if kind == "enqueue":
                totals["enqueued"] += 1
            elif kind == "dequeue":
                totals["applied"] += 1
            elif kind == "flush":
                totals["flushes"] += 1
            elif kind == "drop":
                totals["dropped"] += 1
            if queue_depth is not None:
                totals["queue_depth"] = int(queue_depth)
                totals["max_queue_depth"] = max(totals["max_queue_depth"], int(queue_depth))
            if staleness_steps is not None:
                totals["staleness_steps"] = int(staleness_steps)
                totals["max_staleness_steps"] = max(
                    totals["max_staleness_steps"], int(staleness_steps)
                )
            if in_flight_bytes is not None:
                totals["in_flight_bytes"] = int(in_flight_bytes)
                totals["max_in_flight_bytes"] = max(
                    totals["max_in_flight_bytes"], int(in_flight_bytes)
                )
                if int(in_flight_bytes) > self._footprint_hwm.get(ASYNC_IN_FLIGHT_LABEL, -1):
                    self._footprint_hwm[ASYNC_IN_FLIGHT_LABEL] = int(in_flight_bytes)
            if kind not in ("drop", "snapshot"):  # counter/gauge-only kinds skip the stream
                event: Dict[str, Any] = {
                    "type": kind,
                    "t": round(time.time() - self._t0, 6),
                    "tid": tid,
                }
                if batch_index is not None:
                    event["batch_index"] = int(batch_index)
                if queue_depth is not None:
                    event["queue_depth"] = int(queue_depth)
                if staleness_steps is not None:
                    event["staleness_steps"] = int(staleness_steps)
                if in_flight_bytes is not None:
                    event["in_flight_bytes"] = int(in_flight_bytes)
                if dur_ms is not None:
                    event["dur_ms"] = dur_ms
                event.update(extra)
                self._append(event)
        # windowed feeds (outside the lock; no-ops when detached)
        if kind == "enqueue":
            self._observe(SERIES_ASYNC_ENQUEUED, 1)
        elif kind == "drop":
            self._observe(SERIES_ASYNC_DROPPED, 1)
        elif kind == "dequeue":
            if dur_ms is not None:
                self._observe(SERIES_ASYNC_APPLY_MS, float(dur_ms))
            age_ms = extra.get("age_ms")
            if age_ms is not None:
                self._observe(SERIES_ASYNC_AGE_MS, float(age_ms))
        elif kind == "snapshot" and staleness_steps is not None:
            self._observe(SERIES_ASYNC_STALENESS, int(staleness_steps))
        if queue_depth is not None:
            self._observe(SERIES_ASYNC_QUEUE_DEPTH, int(queue_depth))

    def record_read(
        self,
        kind: str,
        metric: Any = None,
        duration_s: float = 0.0,
        cache_hit: bool = False,
        leaves: int = 0,
        ring_buckets: int = 0,
        table_rows: int = 0,
        fanin: int = 0,
        freshness: Optional[Any] = None,
        **extra: Any,
    ) -> None:
        """Record one read-path serve (the typed ``read`` event family).

        ``kind`` names the entry point — ``"compute"`` (Metric.compute,
        cache hit or cold), ``"window"`` (WindowedMetric.window_state /
        compute(window=)), ``"sliced"`` (SlicedMetric.compute with
        slice_ids/top_k), ``"fleet"`` (FleetCollector.fold_values), or
        ``"probe"`` (a serving loop's dashboard-age probe). The fold-size
        arguments say what the read paid for: state ``leaves`` folded,
        ``ring_buckets`` folded oldest-first, retrieval-table rows
        unpacked, and the fleet ``fanin`` (contributing publishers).

        ``freshness`` is an optional :class:`~metrics_tpu.observability.
        freshness.FreshnessStamp` (duck-typed — only its attributes are
        read, keeping this module import-free): when present, the stamp's
        min/max contributing event-times and staleness components fold
        into the freshness aggregates and the observed ingest-to-visible
        staleness feeds the windowed ``freshness_age_s`` series the
        ``freshness_slo`` alarm watches.
        """
        label = metric if isinstance(metric, str) else (
            type(metric).__name__ if metric is not None else kind
        )
        dur_ms = round(float(duration_s) * 1e3, 4)
        staleness_s: Optional[float] = None
        with self._lock:
            r = self._reads
            r["reads"] += 1
            if cache_hit:
                r["cache_hits"] += 1
            r["leaves_folded"] += int(leaves)
            r["ring_buckets_folded"] += int(ring_buckets)
            r["table_rows_unpacked"] += int(table_rows)
            r["fanin"] += int(fanin)
            r["read_s_total"] += float(duration_s)
            r["max_read_ms"] = max(r["max_read_ms"], dur_ms)
            r["max_fanin"] = max(r["max_fanin"], int(fanin))
            event: Dict[str, Any] = {
                "type": "read",
                "kind": kind,
                "metric": label,
                "t": round(time.time() - self._t0, 6),
                "dur_ms": dur_ms,
                "cache_hit": bool(cache_hit),
            }
            if leaves:
                event["leaves"] = int(leaves)
            if ring_buckets:
                event["ring_buckets"] = int(ring_buckets)
            if table_rows:
                event["table_rows"] = int(table_rows)
            if fanin:
                event["fanin"] = int(fanin)
            if freshness is not None:
                fr = self._freshness
                fr["stamps"] += 1
                lo = getattr(freshness, "min_event_t", None)
                hi = getattr(freshness, "max_event_t", None)
                if lo is not None:
                    fr["min_event_t"] = lo if fr["min_event_t"] is None else min(fr["min_event_t"], lo)
                if hi is not None:
                    fr["max_event_t"] = hi if fr["max_event_t"] is None else max(fr["max_event_t"], hi)
                    staleness_s = max(0.0, time.time() - float(hi))
                    event["staleness_s"] = round(staleness_s, 6)
                    fr["max_staleness_s"] = max(fr["max_staleness_s"], staleness_s)
                for attr, key in (
                    ("async_age_s", "max_async_age_s"),
                    ("ring_span_s", "max_ring_span_s"),
                    ("watermark_lag_s", "max_watermark_lag_s"),
                ):
                    v = float(getattr(freshness, attr, 0.0) or 0.0)
                    if v:
                        event[attr] = round(v, 6)
                        fr[key] = max(fr[key], v)
            event.update(extra)
            self._append(event)
        # windowed feeds (outside the lock; no-ops when detached)
        self._observe(SERIES_READS, 1)
        self._observe(SERIES_READ_MS, dur_ms)
        if fanin:
            self._observe(SERIES_READ_FANIN, int(fanin))
        if staleness_s is not None:
            self._observe(SERIES_FRESHNESS_AGE_S, staleness_s)

    def record_memory_boundary(
        self,
        kind: str,
        metric: Any,
        live_bytes: Any = None,
        **extra: Any,
    ) -> None:
        """Record one metric-lifecycle memory boundary (``kind`` in
        ``update | compute | reset``). The per-kind counter always bumps;
        a typed ``memory`` event row (stamped with the metric's live
        committed state bytes) is emitted at most once per
        ``MEMORY_EVENT_INTERVAL_S`` per kind, so eager update loops pay a
        counter bump, not an event allocation plus a state walk.

        ``live_bytes`` may be an int or a zero-arg callable (e.g. the
        metric's bound ``total_state_bytes``) — the callable is only
        invoked when an event row is actually emitted."""
        now = time.time()
        with self._lock:
            m = self._memory
            key = kind + "_boundaries"
            m[key] = m.get(key, 0) + 1
            emit = now - self._memory_last_event.get(kind, 0.0) >= self.MEMORY_EVENT_INTERVAL_S
            if emit:
                self._memory_last_event[kind] = now
        if not emit:
            return
        lb = int(live_bytes() if callable(live_bytes) else (live_bytes or 0))
        with self._lock:
            m = self._memory
            m["events"] += 1
            m["boundary_live_bytes"] = lb
            m["max_boundary_live_bytes"] = max(m["max_boundary_live_bytes"], lb)
            event: Dict[str, Any] = {
                "type": "memory",
                "kind": kind,
                "metric": type(metric).__name__ if metric is not None else kind,
                "live_bytes": lb,
                "t": round(time.time() - self._t0, 6),
            }
            event.update(extra)
            self._append(event)

    def record_memory_observation(
        self,
        ledger_bytes: int,
        cache_plane_bytes: int,
        device_bytes_in_use: Optional[int] = None,
        device_peak_bytes: Optional[int] = None,
        unaccounted_bytes: Optional[int] = None,
        bytes_per_tenant: Optional[float] = None,
        per_device: Optional[Dict[str, int]] = None,
        planes: Optional[Dict[str, int]] = None,
        source: Optional[str] = None,
        **extra: Any,
    ) -> None:
        """Record one full memory-observatory poll (``MemoryObservatory.
        observe``): the ledger total, the cache-plane inventory total, the
        backend's in-use/peak bytes where it reports them (``source`` says
        what backed the in-use number — ``"backend"``, ``"host_rss"``, or
        ``None`` when nothing could), and the derived unaccounted residue.
        Updates last-seen + high-water gauges, appends one ``memory`` event
        (kind ``observe``), and feeds the ``mem_*`` windowed series the
        ``memory_leak`` / ``memory_budget`` alarms watch."""
        with self._lock:
            m = self._memory
            m["observations"] += 1
            m["events"] += 1
            m["ledger_bytes"] = int(ledger_bytes)
            m["max_ledger_bytes"] = max(m["max_ledger_bytes"], int(ledger_bytes))
            m["cache_plane_bytes"] = int(cache_plane_bytes)
            m["max_cache_plane_bytes"] = max(m["max_cache_plane_bytes"], int(cache_plane_bytes))
            if device_bytes_in_use is not None:
                m["device_bytes_in_use"] = int(device_bytes_in_use)
                m["max_device_bytes_in_use"] = max(
                    m["max_device_bytes_in_use"], int(device_bytes_in_use)
                )
            if unaccounted_bytes is not None:
                m["unaccounted_bytes"] = int(unaccounted_bytes)
                m["max_unaccounted_bytes"] = max(
                    m["max_unaccounted_bytes"], int(unaccounted_bytes)
                )
            if bytes_per_tenant is not None:
                m["bytes_per_tenant"] = float(bytes_per_tenant)
                m["max_bytes_per_tenant"] = max(
                    m["max_bytes_per_tenant"], float(bytes_per_tenant)
                )
            event: Dict[str, Any] = {
                "type": "memory",
                "kind": "observe",
                "t": round(time.time() - self._t0, 6),
                "ledger_bytes": int(ledger_bytes),
                "cache_plane_bytes": int(cache_plane_bytes),
            }
            if device_bytes_in_use is not None:
                event["device_bytes_in_use"] = int(device_bytes_in_use)
            if device_peak_bytes is not None:
                event["device_peak_bytes"] = int(device_peak_bytes)
            if unaccounted_bytes is not None:
                event["unaccounted_bytes"] = int(unaccounted_bytes)
            if bytes_per_tenant is not None:
                event["bytes_per_tenant"] = round(float(bytes_per_tenant), 4)
            if per_device:
                event["per_device"] = {str(k): int(v) for k, v in per_device.items()}
            if planes:
                event["planes"] = {str(k): int(v) for k, v in planes.items()}
            if source is not None:
                event["source"] = source
            event.update(extra)
            self._append(event)
        # windowed feeds (outside the lock; no-ops when detached)
        self._observe(SERIES_MEM_LEDGER_BYTES, int(ledger_bytes))
        self._observe(SERIES_MEM_CACHE_BYTES, int(cache_plane_bytes))
        if device_bytes_in_use is not None:
            self._observe(SERIES_MEM_DEVICE_BYTES, int(device_bytes_in_use))
        if unaccounted_bytes is not None:
            self._observe(SERIES_MEM_UNACCOUNTED, int(unaccounted_bytes))
        if bytes_per_tenant is not None:
            self._observe(SERIES_MEM_BYTES_PER_TENANT, float(bytes_per_tenant))

    def record_cache_plane(
        self,
        plane: str,
        entries: int,
        nbytes: int,
        evictions: int = 0,
        evicted_bytes: int = 0,
        **extra: Any,
    ) -> None:
        """Record one cache-plane lifecycle event: a growth warning
        (ReaderCache crossing its entry threshold) or an eviction (the
        retrieval layout LRU dropping an entry). Carries the plane's entry
        count and byte size as typed fields — what the fleet alarms on
        instead of losing a ``warnings.warn`` to stderr — and sums
        eviction count/bytes into the extensive memory totals."""
        with self._lock:
            m = self._memory
            m["cache_plane_events"] += 1
            m["plane_evictions"] += int(evictions)
            m["plane_evicted_bytes"] += int(evicted_bytes)
            event: Dict[str, Any] = {
                "type": "cache_plane",
                "plane": plane,
                "entries": int(entries),
                "nbytes": int(nbytes),
                "t": round(time.time() - self._t0, 6),
            }
            if evictions:
                event["evictions"] = int(evictions)
            if evicted_bytes:
                event["evicted_bytes"] = int(evicted_bytes)
            event.update(extra)
            self._append(event)

    def record_event(self, etype: str, **fields: Any) -> None:
        """Record a free-form auxiliary event (e.g. ``tracker_increment``)."""
        with self._lock:
            tid = fields.get("tid")
            if isinstance(tid, int) and tid == threading.get_ident():
                # span-exit events carry their own thread's id — register
                # the name so Perfetto tracks are labeled
                self._thread_names.setdefault(tid, threading.current_thread().name)
            event: Dict[str, Any] = {"type": etype, "t": round(time.time() - self._t0, 6)}
            event.update(fields)
            self._append(event)

    def record_fleet_poll(
        self,
        absorbed: int = 0,
        duplicates: int = 0,
        late_dropped: int = 0,
        fold_errors: int = 0,
        backlog: int = 0,
        max_lag_s: float = 0.0,
        publishers: int = 0,
        **extra: Any,
    ) -> None:
        """Record one fleet-collector poll (``FleetCollector._feed_recorder``).

        The count arguments are DELTAS since the previous poll (summed
        into the extensive totals); ``backlog``/``max_lag_s`` are gauges
        (last seen + high-water). Feeds the windowed ``publisher_lag_s``
        / ``collector_backlog`` / ``collector_fold_errors`` series the
        three fleet alarm classes watch. An event row is appended only
        when a poll actually moved a counter — idle polls update gauges
        and series without flooding the stream."""
        with self._lock:
            f = self._fleet
            f["absorbed"] += int(absorbed)
            f["duplicates"] += int(duplicates)
            f["late_dropped"] += int(late_dropped)
            f["fold_errors"] += int(fold_errors)
            f["backlog"] = int(backlog)
            f["max_backlog"] = max(f["max_backlog"], int(backlog))
            f["publisher_lag_s"] = float(max_lag_s)
            f["max_publisher_lag_s"] = max(f["max_publisher_lag_s"], float(max_lag_s))
            f["publishers"] = max(f["publishers"], int(publishers))
            if absorbed or duplicates or late_dropped or fold_errors:
                event: Dict[str, Any] = {
                    "type": "fleet_poll",
                    "t": round(time.time() - self._t0, 6),
                    "absorbed": int(absorbed),
                    "duplicates": int(duplicates),
                    "late_dropped": int(late_dropped),
                    "fold_errors": int(fold_errors),
                    "backlog": int(backlog),
                    "max_lag_s": round(float(max_lag_s), 4),
                }
                event.update(extra)
                self._append(event)
        # windowed feeds (outside the lock; no-ops when detached)
        self._observe(SERIES_COLLECTOR_BACKLOG, int(backlog))
        self._observe(SERIES_PUBLISHER_LAG, float(max_lag_s))
        if fold_errors:
            self._observe(SERIES_FOLD_ERRORS, int(fold_errors))

    def record_export_error(self, error: Optional[BaseException] = None) -> None:
        """Count one failed exporter tick (``PeriodicExporter`` hardening):
        the thread keeps ticking, but the failure must be visible — in the
        summary, the Prometheus page, the health snapshot, and the windowed
        export-error series."""
        with self._lock:
            self._export_errors += 1
            event: Dict[str, Any] = {
                "type": "export_error",
                "t": round(time.time() - self._t0, 6),
                "n_errors": self._export_errors,
            }
            if error is not None:
                event["error"] = repr(error)
            self._append(event)
        self._observe(SERIES_EXPORT_ERRORS, 1)

    # ------------------------------------------------------------------
    # compute-group attribution (MetricCollection)
    # ------------------------------------------------------------------
    def group_attribution(self, members: List[str]) -> "_GroupContext":
        """Context manager: lifecycle events recorded inside are annotated
        with the compute-group members sharing the leader's update, so group
        updates are attributed once instead of double-counted per member."""
        return _GroupContext(self, tuple(members))

    # ------------------------------------------------------------------
    # exporters (delegating to metrics_tpu.observability.exporters)
    # ------------------------------------------------------------------
    def export_jsonl(self, path: str, append: bool = False) -> Optional[str]:
        from metrics_tpu.observability.exporters import export_jsonl

        return export_jsonl(path, recorder=self, append=append)

    def render_prometheus(self) -> str:
        from metrics_tpu.observability.exporters import render_prometheus

        return render_prometheus(recorder=self)

    def summary(self) -> str:
        from metrics_tpu.observability.exporters import summary

        return summary(recorder=self)


class _GroupContext:
    def __init__(self, recorder: MetricRecorder, members: Tuple[str, ...]) -> None:
        self._recorder = recorder
        self._members = members
        self._prev: Optional[Tuple[str, ...]] = None

    def __enter__(self) -> "_GroupContext":
        local = self._recorder._group_local
        self._prev = getattr(local, "group", None)
        local.group = self._members
        return self

    def __exit__(self, *exc: Any) -> None:
        self._recorder._group_local.group = self._prev


#: THE process-local default recorder — the instance the runtime hot paths
#: (core/metric.py, collections.py, parallel/distributed.py,
#: wrappers/tracker.py) check. Import the OBJECT, never copy its ``enabled``
#: flag.
_DEFAULT_RECORDER = MetricRecorder("default")
