"""Input canonicalization for classification (and retrieval) metrics.

Behavior parity with /root/reference/torchmetrics/utilities/checks.py
(608 LoC): the shape/dtype "case" deduction table (:65-119), num_classes and
top_k consistency rules (:122-200), and ``_input_format_classification``
(:310-449) converting every input style to canonical int binary ``(N, C)`` /
``(N, C, X)`` tensors.

TPU-first notes: all *shape* logic runs in Python at trace time (static
under jit); *value*-dependent validations (label ranges, implied class
counts) run only on concrete arrays and are skipped under tracing, so the
formatting path is fully jit-compatible whenever ``num_classes`` is given.
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.utils.data import select_topk, to_onehot
from metrics_tpu.utils.enums import DataType

Array = jax.Array


def _is_concrete(*arrays: Array) -> bool:
    return not any(isinstance(a, jax.core.Tracer) for a in arrays)


def _is_floating(x: Array) -> bool:
    return jnp.issubdtype(x.dtype, jnp.floating)


def _check_for_empty_tensors(preds: Array, target: Array) -> bool:
    return preds.size == 0 and target.size == 0


def _check_same_shape(preds: Array, target: Array) -> None:
    """Raise if predictions and targets have different shapes. Reference checks.py:29-32."""
    if preds.shape != target.shape:
        raise RuntimeError(
            f"Predictions and targets are expected to have the same shape, but got {preds.shape} and {target.shape}."
        )


def _basic_input_validation(
    preds: Array, target: Array, threshold: float, multiclass: Optional[bool], ignore_index: Optional[int]
) -> None:
    """Case-independent validation. Reference checks.py:35-63."""
    if _check_for_empty_tensors(preds, target):
        return
    if _is_floating(target):
        raise ValueError("The `target` has to be an integer tensor.")

    preds_float = _is_floating(preds)
    if not preds.shape or not target.shape:
        raise ValueError("The `preds` and `target` should be non-scalar tensors.")
    if preds.shape[0] != target.shape[0]:
        raise ValueError("The `preds` and `target` should have the same first dimension.")

    if _is_concrete(preds, target):
        tmin = int(jnp.min(target))
        if ignore_index is None and tmin < 0:
            raise ValueError("The `target` has to be a non-negative tensor.")
        if ignore_index is not None and ignore_index >= 0 and tmin < 0:
            raise ValueError("The `target` has to be a non-negative tensor.")
        if not preds_float and int(jnp.min(preds)) < 0:
            raise ValueError("If `preds` are integers, they have to be non-negative.")
        if multiclass is False and int(jnp.max(target)) > 1:
            raise ValueError("If you set `multiclass=False`, then `target` should not exceed 1.")
        if multiclass is False and not preds_float and int(jnp.max(preds)) > 1:
            raise ValueError("If you set `multiclass=False` and `preds` are integers, then `preds` should not exceed 1.")


def _check_shape_and_type_consistency(preds: Array, target: Array) -> Tuple[DataType, int]:
    """Deduce the input case from shapes/dtypes. Reference checks.py:66-119."""
    preds_float = _is_floating(preds)

    if preds.ndim == target.ndim:
        if preds.shape != target.shape:
            raise ValueError(
                "The `preds` and `target` should have the same shape,"
                f" got `preds` with shape={preds.shape} and `target` with shape={target.shape}."
            )
        if preds_float and target.size > 0 and _is_concrete(target) and int(jnp.max(target)) > 1:
            raise ValueError(
                "If `preds` and `target` are of shape (N, ...) and `preds` are floats, `target` should be binary."
            )
        if preds.ndim == 1 and preds_float:
            case = DataType.BINARY
        elif preds.ndim == 1 and not preds_float:
            case = DataType.MULTICLASS
        elif preds.ndim > 1 and preds_float:
            case = DataType.MULTILABEL
        else:
            case = DataType.MULTIDIM_MULTICLASS
        implied_classes = int(np.prod(preds.shape[1:])) if preds.size > 0 else 0

    elif preds.ndim == target.ndim + 1:
        if not preds_float:
            raise ValueError("If `preds` have one dimension more than `target`, `preds` should be a float tensor.")
        if preds.shape[2:] != target.shape[1:]:
            raise ValueError(
                "If `preds` have one dimension more than `target`, the shape of `preds` should be"
                " (N, C, ...), and the shape of `target` should be (N, ...)."
            )
        implied_classes = preds.shape[1] if preds.size > 0 else 0
        case = DataType.MULTICLASS if preds.ndim == 2 else DataType.MULTIDIM_MULTICLASS
    else:
        raise ValueError(
            "Either `preds` and `target` both should have the (same) shape (N, ...), or `target` should be (N, ...)"
            " and `preds` should be (N, C, ...)."
        )

    return case, implied_classes


def _check_num_classes_binary(num_classes: int, multiclass: Optional[bool]) -> None:
    """Reference checks.py:122-139."""
    if num_classes > 2:
        raise ValueError("Your data is binary, but `num_classes` is larger than 2.")
    if num_classes == 2 and not multiclass:
        raise ValueError(
            "Your data is binary and `num_classes=2`, but `multiclass` is not True."
            " Set it to True if you want to transform binary data to multi-class format."
        )
    if num_classes == 1 and multiclass:
        raise ValueError(
            "You have binary data and have set `multiclass=True`, but `num_classes` is 1."
            " Either set `multiclass=None`(default) or set `num_classes=2`"
            " to transform binary data to multi-class format."
        )


def _check_num_classes_mc(
    preds: Array, target: Array, num_classes: int, multiclass: Optional[bool], implied_classes: int
) -> None:
    """Reference checks.py:142-173."""
    if num_classes == 1 and multiclass is not False:
        raise ValueError(
            "You have set `num_classes=1`, but predictions are integers."
            " If you want to convert (multi-dimensional) multi-class data with 2 classes"
            " to binary/multi-label, set `multiclass=False`."
        )
    if num_classes > 1:
        if multiclass is False and implied_classes != num_classes:
            raise ValueError(
                "You have set `multiclass=False`, but the implied number of classes "
                " (from shape of inputs) does not match `num_classes`."
            )
        if target.size > 0 and _is_concrete(target) and num_classes <= int(jnp.max(target)):
            raise ValueError("The highest label in `target` should be smaller than `num_classes`.")
        if preds.shape != target.shape and num_classes != implied_classes:
            raise ValueError("The size of C dimension of `preds` does not match `num_classes`.")


def _check_num_classes_ml(num_classes: int, multiclass: Optional[bool], implied_classes: int) -> None:
    """Reference checks.py:176-187."""
    if multiclass and num_classes != 2:
        raise ValueError(
            "Your have set `multiclass=True`, but `num_classes` is not equal to 2."
            " If you are trying to transform multi-label data to 2 class multi-dimensional"
            " multi-class, you should set `num_classes` to either 2 or None."
        )
    if not multiclass and num_classes != implied_classes:
        raise ValueError("The implied number of classes (from shape of inputs) does not match num_classes.")


def _check_top_k(top_k: int, case: str, implied_classes: int, multiclass: Optional[bool], preds_float: bool) -> None:
    """Reference checks.py:190-200."""
    if case == DataType.BINARY:
        raise ValueError("You can not use `top_k` parameter with binary data.")
    if not isinstance(top_k, int) or top_k <= 0:
        raise ValueError("The `top_k` has to be an integer larger than 0.")
    if not preds_float:
        raise ValueError("You have set `top_k`, but you do not have probability predictions.")
    if multiclass is False:
        raise ValueError("If you set `multiclass=False`, you can not set `top_k`.")
    if case == DataType.MULTILABEL and multiclass:
        raise ValueError(
            "If you want to transform multi-label data to 2 class multi-dimensional"
            "multi-class data using `multiclass=True`, you can not use `top_k`."
        )
    if top_k >= implied_classes:
        raise ValueError("The `top_k` has to be strictly smaller than the `C` dimension of `preds`.")


def _check_classification_inputs(
    preds: Array,
    target: Array,
    threshold: float,
    num_classes: Optional[int],
    multiclass: Optional[bool],
    top_k: Optional[int],
    ignore_index: Optional[int] = None,
) -> DataType:
    """Full input validation; returns the deduced case. Reference checks.py:203-299."""
    _basic_input_validation(preds, target, threshold, multiclass, ignore_index)
    case, implied_classes = _check_shape_and_type_consistency(preds, target)

    if preds.shape != target.shape:
        if multiclass is False and implied_classes != 2:
            raise ValueError(
                "You have set `multiclass=False`, but have more than 2 classes in your data,"
                " based on the C dimension of `preds`."
            )
        if target.size > 0 and _is_concrete(target) and int(jnp.max(target)) >= implied_classes:
            raise ValueError(
                "The highest label in `target` should be smaller than the size of the `C` dimension of `preds`."
            )

    if num_classes:
        if case == DataType.BINARY:
            _check_num_classes_binary(num_classes, multiclass)
        elif case in (DataType.MULTICLASS, DataType.MULTIDIM_MULTICLASS):
            _check_num_classes_mc(preds, target, num_classes, multiclass, implied_classes)
        elif case == DataType.MULTILABEL:
            _check_num_classes_ml(num_classes, multiclass, implied_classes)

    if top_k is not None:
        _check_top_k(top_k, case, implied_classes, multiclass, _is_floating(preds))

    return case


def _input_squeeze(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Remove size-1 dims (keeping the batch dim). Reference checks.py:302-310."""
    if preds.shape and preds.shape[0] == 1:
        preds = jnp.expand_dims(jnp.squeeze(preds), 0)
        target = jnp.expand_dims(jnp.squeeze(target), 0)
    else:
        preds, target = jnp.squeeze(preds), jnp.squeeze(target)
    return preds, target


def _score_mode_static(preds: Array, target: Array) -> DataType:
    """Shape-only mode deduction for float-SCORE inputs (the curve /
    calibration family): the ``DataType`` the full
    :func:`_input_format_classification` would return, derived from static
    ranks alone — no value reads, so it is usable on tracers. Callers keep
    the full validating path for concrete inputs (``if _is_concrete(...)``)
    and fall back to this under jit, where value validation is host work by
    contract (the same split the capacity-mode buffers use)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    preds, target = _input_squeeze(preds, target)
    if preds.ndim == 1 and target.ndim == 1:
        return DataType.BINARY
    if preds.ndim == 2 and target.ndim == 1:
        return DataType.MULTICLASS
    if preds.ndim == target.ndim and preds.ndim >= 2:
        return DataType.MULTILABEL
    if preds.ndim >= 3 and target.ndim == preds.ndim - 1:
        return DataType.MULTIDIM_MULTICLASS
    raise ValueError(
        f"Could not deduce the classification mode from score shapes {preds.shape} /"
        f" {target.shape}"
    )


def _input_format_classification(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    num_classes: Optional[int] = None,
    multiclass: Optional[bool] = None,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, DataType]:
    """Convert every supported input style to canonical int binary tensors.

    Returns ``(preds, target, case)`` with preds/target of shape ``(N, C)``
    or ``(N, C, X)``. Full behavior parity with reference checks.py:310-449
    (see that docstring for the per-case transformation table).
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    preds, target = _input_squeeze(preds, target)

    if preds.dtype in (jnp.float16, jnp.bfloat16):
        preds = preds.astype(jnp.float32)

    case = _check_classification_inputs(
        preds,
        target,
        threshold=threshold,
        num_classes=num_classes,
        multiclass=multiclass,
        top_k=top_k,
        ignore_index=ignore_index,
    )

    if case in (DataType.BINARY, DataType.MULTILABEL) and not top_k:
        preds = (preds >= threshold).astype(jnp.int32)
        num_classes = num_classes if not multiclass else 2

    if case == DataType.MULTILABEL and top_k:
        preds = select_topk(preds, top_k)

    if case in (DataType.MULTICLASS, DataType.MULTIDIM_MULTICLASS) or multiclass:
        if _is_floating(preds):
            num_classes = preds.shape[1]
            preds = select_topk(preds, top_k or 1)
        else:
            if num_classes is None:
                if not _is_concrete(preds, target):
                    raise ValueError(
                        "`num_classes` must be given explicitly when formatting label inputs under jit"
                    )
                num_classes = int(max(int(jnp.max(preds)), int(jnp.max(target)))) + 1
            preds = to_onehot(preds, max(2, num_classes))

        target = to_onehot(target, max(2, num_classes))

        if multiclass is False:
            preds, target = preds[:, 1, ...], target[:, 1, ...]

    if not _check_for_empty_tensors(preds, target):
        if (case in (DataType.MULTICLASS, DataType.MULTIDIM_MULTICLASS) and multiclass is not False) or multiclass:
            target = target.reshape(target.shape[0], target.shape[1], -1)
            preds = preds.reshape(preds.shape[0], preds.shape[1], -1)
        else:
            target = target.reshape(target.shape[0], -1)
            preds = preds.reshape(preds.shape[0], -1)

    # some transformations above create a trailing size-1 dim for MC/binary case
    if preds.ndim > 2 and preds.shape[-1] == 1:
        preds, target = jnp.squeeze(preds, -1), jnp.squeeze(target, -1)

    return preds.astype(jnp.int32), target.astype(jnp.int32), case


def _input_format_classification_one_hot(
    num_classes: int,
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    multilabel: bool = False,
) -> Tuple[Array, Array]:
    """One-hot (num_classes, -1) formatting. Reference checks.py:452-500."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if preds.ndim not in (target.ndim, target.ndim + 1):
        raise ValueError("preds and target must have same number of dimensions, or one additional dimension for preds")

    if preds.ndim == target.ndim + 1:
        preds = jnp.argmax(preds, axis=1)

    if preds.ndim == target.ndim and jnp.issubdtype(preds.dtype, jnp.integer) and num_classes > 1 and not multilabel:
        preds = to_onehot(preds, num_classes=num_classes)
        target = to_onehot(target, num_classes=num_classes)
    elif preds.ndim == target.ndim and _is_floating(preds):
        preds = (preds >= threshold).astype(jnp.int32)

    if preds.ndim > 1:
        preds = jnp.swapaxes(preds, 1, 0)
        target = jnp.swapaxes(target, 1, 0)

    return preds.reshape(num_classes, -1), target.reshape(num_classes, -1)


# ---------------------------------------------------------------------------
# retrieval input checks
# ---------------------------------------------------------------------------

def _check_retrieval_target_and_prediction_types(
    preds: Array, target: Array, allow_non_binary_target: bool = False
) -> Tuple[Array, Array]:
    """Reference checks.py:578-608."""
    if not (jnp.issubdtype(target.dtype, jnp.integer) or target.dtype == jnp.bool_ or _is_floating(target)):
        raise ValueError("`target` must be a tensor of booleans, integers or floats")
    if not _is_floating(preds):
        raise ValueError("`preds` must be a tensor of floats")
    if not allow_non_binary_target and _is_concrete(target):
        if int(jnp.max(target)) > 1 or int(jnp.min(target)) < 0:
            raise ValueError("`target` must contain `binary` values")
    target = target.astype(jnp.float32) if _is_floating(target) else target.astype(jnp.int32)
    preds = preds.astype(jnp.float32)
    return preds.reshape(-1), target.reshape(-1)


def _check_retrieval_functional_inputs(
    preds: Array, target: Array, allow_non_binary_target: bool = False
) -> Tuple[Array, Array]:
    """Reference checks.py:503-528."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if preds.shape != target.shape:
        raise ValueError("`preds` and `target` must be of the same shape")
    if not preds.size or not preds.shape:
        raise ValueError("`preds` and `target` must be non-empty and non-scalar tensors")
    return _check_retrieval_target_and_prediction_types(preds, target, allow_non_binary_target=allow_non_binary_target)


def _check_retrieval_inputs(
    indexes: Array,
    preds: Array,
    target: Array,
    allow_non_binary_target: bool = False,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, Array]:
    """Reference checks.py:530-575."""
    indexes = jnp.asarray(indexes)
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if indexes.shape != preds.shape or preds.shape != target.shape:
        raise ValueError("`indexes`, `preds` and `target` must be of the same shape")
    if not jnp.issubdtype(indexes.dtype, jnp.integer):
        raise ValueError("`indexes` must be a tensor of long integers")

    if ignore_index is not None:
        valid_positions = target != ignore_index
        indexes, preds, target = indexes[valid_positions], preds[valid_positions], target[valid_positions]

    if not indexes.size or not indexes.shape:
        raise ValueError("`indexes`, `preds` and `target` must be non-empty and non-scalar tensors")

    preds, target = _check_retrieval_target_and_prediction_types(
        preds, target, allow_non_binary_target=allow_non_binary_target
    )
    return indexes.astype(jnp.int32).reshape(-1), preds, target


def _check_retrieval_inputs_static(
    indexes: Array,
    preds: Array,
    target: Array,
    allow_non_binary_target: bool = False,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, Array, Array]:
    """Fixed-shape variant of :func:`_check_retrieval_inputs` for the
    table-state update path: instead of FILTERING ``ignore_index`` rows
    (a data-dependent shape that cannot trace), it returns a ``valid``
    mask alongside the flattened arrays, and value-level checks (binary
    target) only fire when the data is concrete — under a fused/jitted
    trace the shapes and dtypes are still validated host-side."""
    indexes = jnp.asarray(indexes)
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if indexes.shape != preds.shape or preds.shape != target.shape:
        raise ValueError("`indexes`, `preds` and `target` must be of the same shape")
    if not jnp.issubdtype(indexes.dtype, jnp.integer):
        raise ValueError("`indexes` must be a tensor of long integers")
    if not indexes.size or not indexes.shape:
        raise ValueError("`indexes`, `preds` and `target` must be non-empty and non-scalar tensors")
    if not (jnp.issubdtype(target.dtype, jnp.integer) or target.dtype == jnp.bool_ or _is_floating(target)):
        raise ValueError("`target` must be a tensor of booleans, integers or floats")
    if not _is_floating(preds):
        raise ValueError("`preds` must be a tensor of floats")
    target = target.reshape(-1)
    if not allow_non_binary_target and _is_concrete(target):
        checkable = target
        if ignore_index is not None:
            checkable = jnp.where(target == ignore_index, 0, target)
        if int(jnp.max(checkable)) > 1 or int(jnp.min(checkable)) < 0:
            raise ValueError("`target` must contain `binary` values")
    valid = (
        jnp.ones(target.shape, bool) if ignore_index is None else target != ignore_index
    )
    # a batch that ignore_index erases completely is the reference's
    # empty-tensor error; value-dependent, so eager-path only
    if ignore_index is not None and _is_concrete(valid) and not bool(jnp.any(valid)):
        raise ValueError("`indexes`, `preds` and `target` must be non-empty and non-scalar tensors")
    target = target.astype(jnp.float32) if _is_floating(target) else target.astype(jnp.int32)
    return indexes.astype(jnp.int32).reshape(-1), preds.astype(jnp.float32).reshape(-1), target, valid


def _check_retrieval_k(k):
    """Shared @k validation for retrieval metrics."""
    if (k is not None) and not (isinstance(k, int) and k > 0):
        raise ValueError("`k` has to be a positive integer or None")
