"""Accuracy vs sklearn oracle. Parity in spirit with
/root/reference/tests/classification/test_accuracy.py."""
import numpy as np
import pytest
from sklearn.metrics import accuracy_score as sk_accuracy

from metrics_tpu.classification import Accuracy
from metrics_tpu.functional import accuracy
from tests.classification.inputs import (
    _input_binary,
    _input_binary_prob,
    _input_multiclass,
    _input_multiclass_prob,
    _input_multidim_multiclass,
    _input_multilabel,
    _input_multilabel_prob,
)
from tests.helpers.testers import NUM_CLASSES, THRESHOLD, MetricTester


def _sk_accuracy(preds, target, subset_accuracy=False):
    preds, target = np.asarray(preds), np.asarray(target)
    sk_preds, sk_target, mode = _input_format(preds, target)
    if mode == "multilabel" and not subset_accuracy:
        sk_preds, sk_target = sk_preds.reshape(-1), sk_target.reshape(-1)
    elif mode == "mdmc" and not subset_accuracy:
        sk_preds, sk_target = sk_preds.reshape(-1), sk_target.reshape(-1)
    elif mode == "mdmc" and subset_accuracy:
        return np.mean([np.array_equal(p, t) for p, t in zip(sk_preds, sk_target)])
    return sk_accuracy(y_true=sk_target, y_pred=sk_preds)


def _input_format(preds, target):
    """Mimic the canonical formatting for the oracle."""
    if preds.ndim == target.ndim and np.issubdtype(preds.dtype, np.floating):
        if preds.ndim == 1:  # binary prob
            return (preds >= THRESHOLD).astype(int), target, "binary"
        return (preds >= THRESHOLD).astype(int), target, "multilabel"  # multilabel prob
    if preds.ndim == target.ndim + 1:  # multiclass prob
        return np.argmax(preds, axis=1), target, "multiclass"
    if preds.ndim == target.ndim and preds.ndim >= 2:
        return preds, target, "mdmc"
    return preds, target, "multiclass"


@pytest.mark.parametrize(
    "preds, target, subset_accuracy",
    [
        (_input_binary_prob.preds, _input_binary_prob.target, False),
        (_input_binary.preds, _input_binary.target, False),
        (_input_multilabel_prob.preds, _input_multilabel_prob.target, False),
        (_input_multilabel.preds, _input_multilabel.target, False),
        (_input_multiclass_prob.preds, _input_multiclass_prob.target, False),
        (_input_multiclass.preds, _input_multiclass.target, False),
        (_input_multidim_multiclass.preds, _input_multidim_multiclass.target, False),
        (_input_multilabel_prob.preds, _input_multilabel_prob.target, True),
        (_input_multidim_multiclass.preds, _input_multidim_multiclass.target, True),
    ],
)
class TestAccuracy(MetricTester):
    def test_accuracy_class(self, preds, target, subset_accuracy):
        self.run_class_metric_test(
            preds=preds,
            target=target,
            metric_class=Accuracy,
            sk_metric=lambda p, t: _sk_accuracy(p, t, subset_accuracy),
            metric_args={"threshold": THRESHOLD, "subset_accuracy": subset_accuracy},
            atol=1e-6,
        )

    def test_accuracy_fn(self, preds, target, subset_accuracy):
        self.run_functional_metric_test(
            preds,
            target,
            metric_functional=accuracy,
            sk_metric=lambda p, t: _sk_accuracy(p, t, subset_accuracy),
            metric_args={"threshold": THRESHOLD, "subset_accuracy": subset_accuracy},
            atol=1e-6,
        )


def test_accuracy_topk():
    """top_k accuracy on multiclass probabilities, reference docstring value."""
    import jax.numpy as jnp

    target = jnp.array([0, 1, 2])
    preds = jnp.array([[0.1, 0.9, 0], [0.3, 0.1, 0.6], [0.2, 0.5, 0.3]])
    acc = Accuracy(top_k=2)
    np.testing.assert_allclose(acc(preds, target), 2 / 3, atol=1e-6)


def test_accuracy_invalid_average():
    with pytest.raises(ValueError):
        Accuracy(average="invalid")


def test_accuracy_mode_switch_raises():
    import jax.numpy as jnp

    acc = Accuracy()
    acc.update(jnp.array([0, 1, 1]), jnp.array([0, 1, 0]))
    with pytest.raises(ValueError):
        acc.update(jnp.array([[0.1, 0.9], [0.8, 0.2]]).ravel()[:2].reshape(2), jnp.array([0, 1]))
        acc.update(jnp.array([[0.1, 0.9, 0.0], [0.3, 0.1, 0.6]]), jnp.array([0, 1]))
