"""Flax InceptionV3 feature extractor for FID/KID/IS.

TPU-native replacement for the reference's torch-fidelity
``FeatureExtractorInceptionV3`` (/root/reference/torchmetrics/image/fid.py:
26-57): the same TF-slim "inception-v3-compat" topology expressed in Flax
linen, exposing the four FID feature depths (64, 192, 768, 2048) and the
1008-way logits.

Weights are NOT bundled (this environment has no network access): pass an
``.npz`` checkpoint produced by ``convert_torch_fidelity_weights`` (host-side
helper that maps a locally-downloaded torch-fidelity state_dict onto this
module's parameter tree). Constructing an extractor without weights raises.
"""
from typing import Any, Callable, Optional, Sequence, Union

import jax
import jax.numpy as jnp

try:
    import flax.linen as nn

    _FLAX_AVAILABLE = True
except ImportError:  # pragma: no cover
    _FLAX_AVAILABLE = False

Array = jax.Array

FID_FEATURE_DEPTHS = (64, 192, 768, 2048)


if _FLAX_AVAILABLE:

    class BasicConv2d(nn.Module):
        """Conv + BN(eps=1e-3, no scale-γ=False) + ReLU, matching TF-slim inception."""

        out_channels: int
        kernel_size: Sequence[int]
        strides: Sequence[int] = (1, 1)
        padding: Union[str, Sequence] = "VALID"

        @nn.compact
        def __call__(self, x: Array) -> Array:
            x = nn.Conv(
                self.out_channels, self.kernel_size, strides=self.strides, padding=self.padding, use_bias=False
            )(x)
            x = nn.BatchNorm(use_running_average=True, epsilon=1e-3)(x)
            return nn.relu(x)

    def _max_pool(x: Array, window: int = 3, stride: int = 2) -> Array:
        return nn.max_pool(x, (window, window), strides=(stride, stride))

    def _avg_pool3(x: Array) -> Array:
        return nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME", count_include_pad=False)

    class InceptionA(nn.Module):
        pool_features: int

        @nn.compact
        def __call__(self, x: Array) -> Array:
            b1 = BasicConv2d(64, (1, 1))(x)
            b2 = BasicConv2d(48, (1, 1))(x)
            b2 = BasicConv2d(64, (5, 5), padding="SAME")(b2)
            b3 = BasicConv2d(64, (1, 1))(x)
            b3 = BasicConv2d(96, (3, 3), padding="SAME")(b3)
            b3 = BasicConv2d(96, (3, 3), padding="SAME")(b3)
            b4 = _avg_pool3(x)
            b4 = BasicConv2d(self.pool_features, (1, 1))(b4)
            return jnp.concatenate([b1, b2, b3, b4], axis=-1)

    class InceptionB(nn.Module):
        @nn.compact
        def __call__(self, x: Array) -> Array:
            b1 = BasicConv2d(384, (3, 3), strides=(2, 2))(x)
            b2 = BasicConv2d(64, (1, 1))(x)
            b2 = BasicConv2d(96, (3, 3), padding="SAME")(b2)
            b2 = BasicConv2d(96, (3, 3), strides=(2, 2))(b2)
            b3 = _max_pool(x)
            return jnp.concatenate([b1, b2, b3], axis=-1)

    class InceptionC(nn.Module):
        channels_7x7: int

        @nn.compact
        def __call__(self, x: Array) -> Array:
            c7 = self.channels_7x7
            b1 = BasicConv2d(192, (1, 1))(x)
            b2 = BasicConv2d(c7, (1, 1))(x)
            b2 = BasicConv2d(c7, (1, 7), padding="SAME")(b2)
            b2 = BasicConv2d(192, (7, 1), padding="SAME")(b2)
            b3 = BasicConv2d(c7, (1, 1))(x)
            b3 = BasicConv2d(c7, (7, 1), padding="SAME")(b3)
            b3 = BasicConv2d(c7, (1, 7), padding="SAME")(b3)
            b3 = BasicConv2d(c7, (7, 1), padding="SAME")(b3)
            b3 = BasicConv2d(192, (1, 7), padding="SAME")(b3)
            b4 = _avg_pool3(x)
            b4 = BasicConv2d(192, (1, 1))(b4)
            return jnp.concatenate([b1, b2, b3, b4], axis=-1)

    class InceptionD(nn.Module):
        @nn.compact
        def __call__(self, x: Array) -> Array:
            b1 = BasicConv2d(192, (1, 1))(x)
            b1 = BasicConv2d(320, (3, 3), strides=(2, 2))(b1)
            b2 = BasicConv2d(192, (1, 1))(x)
            b2 = BasicConv2d(192, (1, 7), padding="SAME")(b2)
            b2 = BasicConv2d(192, (7, 1), padding="SAME")(b2)
            b2 = BasicConv2d(192, (3, 3), strides=(2, 2))(b2)
            b3 = _max_pool(x)
            return jnp.concatenate([b1, b2, b3], axis=-1)

    class InceptionE(nn.Module):
        """Final inception blocks; ``pool`` selects avg (E1) or max (E2, the
        FID-compat quirk in the last block)."""

        pool: str = "avg"

        @nn.compact
        def __call__(self, x: Array) -> Array:
            b1 = BasicConv2d(320, (1, 1))(x)
            b2 = BasicConv2d(384, (1, 1))(x)
            b2 = jnp.concatenate(
                [BasicConv2d(384, (1, 3), padding="SAME")(b2), BasicConv2d(384, (3, 1), padding="SAME")(b2)],
                axis=-1,
            )
            b3 = BasicConv2d(448, (1, 1))(x)
            b3 = BasicConv2d(384, (3, 3), padding="SAME")(b3)
            b3 = jnp.concatenate(
                [BasicConv2d(384, (1, 3), padding="SAME")(b3), BasicConv2d(384, (3, 1), padding="SAME")(b3)],
                axis=-1,
            )
            if self.pool == "avg":
                b4 = _avg_pool3(x)
            else:
                b4 = nn.max_pool(x, (3, 3), strides=(1, 1), padding="SAME")
            b4 = BasicConv2d(192, (1, 1))(b4)
            return jnp.concatenate([b1, b2, b3, b4], axis=-1)

    class InceptionV3FID(nn.Module):
        """FID-compat InceptionV3 returning the requested feature depth.

        Input: uint8/float images ``[N, 3, H, W]`` (NCHW like the reference);
        internally resized to 299x299 and normalized to [-1, 1].
        """

        num_classes: int = 1008

        @nn.compact
        def __call__(self, x: Array, feature: Union[int, str] = 2048) -> Array:
            # NCHW -> NHWC, resize, scale to [-1, 1]
            x = jnp.transpose(x.astype(jnp.float32), (0, 2, 3, 1))
            x = jax.image.resize(x, (x.shape[0], 299, 299, x.shape[3]), method="bilinear")
            x = x / 127.5 - 1.0 if x.max() > 1.5 else x * 2.0 - 1.0

            x = BasicConv2d(32, (3, 3), strides=(2, 2))(x)
            x = BasicConv2d(32, (3, 3))(x)
            x = BasicConv2d(64, (3, 3), padding="SAME")(x)
            x = _max_pool(x)
            if feature == 64:
                return jnp.mean(x, axis=(1, 2))

            x = BasicConv2d(80, (1, 1))(x)
            x = BasicConv2d(192, (3, 3))(x)
            x = _max_pool(x)
            if feature == 192:
                return jnp.mean(x, axis=(1, 2))

            x = InceptionA(pool_features=32)(x)
            x = InceptionA(pool_features=64)(x)
            x = InceptionA(pool_features=64)(x)
            x = InceptionB()(x)
            x = InceptionC(channels_7x7=128)(x)
            x = InceptionC(channels_7x7=160)(x)
            x = InceptionC(channels_7x7=160)(x)
            x = InceptionC(channels_7x7=192)(x)
            if feature == 768:
                return jnp.mean(x, axis=(1, 2))

            x = InceptionD()(x)
            x = InceptionE(pool="avg")(x)
            x = InceptionE(pool="max")(x)
            x = jnp.mean(x, axis=(1, 2))  # [N, 2048]
            if feature == 2048:
                return x

            logits = nn.Dense(self.num_classes)(x)
            if feature == "logits_unbiased":
                # torch-fidelity's unbiased logits drop the bias term
                kernel = self.variables["params"]["Dense_0"]["kernel"]
                return x @ kernel
            return logits


def convert_torch_fidelity_weights(state_dict: Any) -> dict:  # pragma: no cover
    """Map a torch-fidelity FeatureExtractorInceptionV3 state_dict onto the
    Flax parameter tree (host-side, torch required). Save the result with
    ``numpy.savez`` and pass its path as ``feature_extractor_weights_path``."""
    raise NotImplementedError(
        "Weight conversion requires the torch-fidelity checkpoint, which this"
        " environment cannot download. Run this helper where the checkpoint"
        " is available."
    )


def build_fid_inception(
    feature: Union[int, str] = 2048, weights_path: Optional[str] = None
) -> Callable[[Array], Array]:
    """Build an ``imgs -> [N, d]`` extractor from the bundled InceptionV3.

    Raises a clear error when no weights are provided — FID/KID/IS values
    from a randomly-initialized network are meaningless. Pass a callable
    ``feature`` to the metrics to use your own extractor instead.
    """
    if not _FLAX_AVAILABLE:
        raise ModuleNotFoundError("The bundled InceptionV3 requires `flax` to be installed.")
    if weights_path is None:
        raise ValueError(
            "The bundled InceptionV3 needs pretrained weights for meaningful FID/KID/IS values"
            " and none are bundled (no network access). Provide"
            " `feature_extractor_weights_path` (an .npz produced by"
            " `metrics_tpu.models.inception.convert_torch_fidelity_weights`),"
            " or pass a callable `feature` extractor."
        )
    import numpy as np

    model = InceptionV3FID()
    loaded = dict(np.load(weights_path, allow_pickle=True))
    variables = jax.tree_util.tree_map(jnp.asarray, loaded["variables"].item())

    def extract(imgs: Array) -> Array:
        return model.apply(variables, imgs, feature=feature)

    return jax.jit(extract, static_argnames=())
