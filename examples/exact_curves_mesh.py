"""Exact curve metrics at dataset scale, entirely inside one jitted step.

The reference's AUROC/AveragePrecision buffer every sample in unbounded
host-side lists, so the curve family never touches the accelerator's
compiled path. Capacity mode (a TPU-native extension, docs/tpu_concepts.md)
gives each device a fixed [capacity] (binary) or [capacity, C] (multiclass)
buffer: update, mesh sync, and compute all trace under jit, and the values
are EXACT (tie-aware sorted curves, not binned approximations).

This example evaluates a multiclass classifier's macro AUROC + macro
AveragePrecision over a sharded eval set: every device accumulates its
shard through a lax.scan of jitted updates, one collective gathers the
buffer triples, and every device computes the identical global values.

Run on any host (8 virtual CPU devices are provisioned if needed):
    python examples/exact_curves_mesh.py
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))  # repo root

import os

# 8-virtual-CPU-device bootstrap (same recipe as tests/helpers/force_cpu.py:
# append the device-count flag to any existing XLA_FLAGS and re-force the
# cpu platform via jax.config, which wins over sitecustomize-pinned hardware
# plugins as long as it runs before the first backend query). Multi-chip TPU
# users: delete this block to run on the real mesh.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from metrics_tpu import AUROC, AveragePrecision
from metrics_tpu.parallel.distributed import sync_in_mesh
from metrics_tpu.utils.compat import shard_map


def main() -> None:
    n_dev = len(jax.devices())
    mesh = Mesh(np.array(jax.devices()), ("dp",))

    num_classes = 5
    steps, per_step = 4, 16                      # per-device eval micro-batches
    per_dev = steps * per_step
    total = n_dev * per_dev

    rng = np.random.default_rng(0)
    logits = rng.normal(size=(total, num_classes)).astype(np.float32)
    target_np = rng.integers(0, num_classes, total).astype(np.int32)
    # make the scores informative so the curves are non-trivial
    logits[np.arange(total), target_np] += 1.0
    preds_np = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)

    auroc = AUROC(num_classes=num_classes, capacity=per_dev)
    ap = AveragePrecision(num_classes=num_classes, capacity=per_dev, average="macro")

    @jax.jit
    def evaluate(preds, target):
        """Whole eval epoch: scan of updates + one sync + compute, per device."""

        def device_eval(p, t):  # p: [per_dev, C] shard, t: [per_dev]
            def step(state, batch):
                sp, st = batch
                return (
                    auroc.update_state(state[0], sp, st),
                    ap.update_state(state[1], sp, st),
                ), 0.0

            p_steps = p.reshape(steps, per_step, num_classes)
            t_steps = t.reshape(steps, per_step)
            # fold step 0 eagerly so the scan carry is device-varying from
            # the start (a fresh init_state is replicated, and shard_map's
            # varying-axis check rejects a replicated->varying carry)
            init = (
                auroc.update_state(auroc.init_state(), p_steps[0], t_steps[0]),
                ap.update_state(ap.init_state(), p_steps[0], t_steps[0]),
            )
            (s_auroc, s_ap), _ = jax.lax.scan(step, init, (p_steps[1:], t_steps[1:]))

            # the library's one-call mesh sync: each state's declared reducer
            # picks its collective (cat buffers all_gather, the overflow
            # tally psums)
            return (
                auroc.compute_state(sync_in_mesh(s_auroc, auroc.state_reductions(), "dp"))[None],
                ap.compute_state(sync_in_mesh(s_ap, ap.state_reductions(), "dp"))[None],
            )

        return shard_map(
            device_eval, mesh=mesh, in_specs=(P("dp"), P("dp")), out_specs=(P("dp"), P("dp"))
        )(preds, target)

    sharding = NamedSharding(mesh, P("dp"))
    preds = jax.device_put(jnp.asarray(preds_np), sharding)
    target = jax.device_put(jnp.asarray(target_np), sharding)

    auroc_vals, ap_vals = evaluate(preds, target)
    print(f"devices: {n_dev}")
    print(f"macro AUROC (identical on every device): {np.asarray(auroc_vals)}")
    print(f"macro AP    (identical on every device): {np.asarray(ap_vals)}")

    # the same values, computed eagerly on one device over the full data
    eager_auroc = AUROC(num_classes=num_classes, capacity=total)
    eager_auroc.update(jnp.asarray(preds_np), jnp.asarray(target_np))
    eager_ap = AveragePrecision(num_classes=num_classes, capacity=total, average="macro")
    eager_ap.update(jnp.asarray(preds_np), jnp.asarray(target_np))
    print(f"eager single-device AUROC: {float(eager_auroc.compute()):.6f}")
    print(f"eager single-device AP:    {float(eager_ap.compute()):.6f}")

    assert np.allclose(np.asarray(auroc_vals), float(eager_auroc.compute()), atol=1e-6)
    assert np.allclose(np.asarray(ap_vals), float(eager_ap.compute()), atol=1e-6)
    print("mesh == eager: exact curve values agree")


if __name__ == "__main__":
    main()
