"""Fixed-capacity per-query table state (retrieval/table.py) vs the
``exact=True`` cat-state path.

The contract under test (docs/retrieval_states.md):

* **In-window parity** — distinct queries <= max_queries and per-query
  docs <= max_docs: per-query values are bit-identical to the exact
  path; the final mean over queries is bit-identical whenever the value
  sum is exactly representable (dyadic values — hit-rate, precision@2^k)
  and within float tolerance otherwise (the fixed [max_queries] row
  count can re-associate the final reduction tree).
* **Policy exactness** — all four ``empty_target_action`` modes and
  ``ignore_index`` behave identically to exact mode (the table's
  POS/NEG counters never truncate).
* **Reservoir determinism** — the sampled query set past capacity is a
  pure function of the query-id set: independent of arrival order,
  batch chunking, and rank placement.
* **Composition** — fused single-dispatch, ragged-shape bucketing (one
  compile), async ingest, and the 8-device mesh merge round all produce
  the same states as eager updates.
"""
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from metrics_tpu import MetricCollection
from metrics_tpu.retrieval import (
    RetrievalFallOut,
    RetrievalHitRate,
    RetrievalMAP,
    RetrievalMRR,
    RetrievalNormalizedDCG,
    RetrievalPrecision,
    RetrievalRPrecision,
    RetrievalRecall,
)
from metrics_tpu.retrieval.table import (
    _unpack,
    retrieval_table_fill,
    retrieval_table_init,
    retrieval_table_insert,
    retrieval_table_layout,
    retrieval_table_merge,
)

ALL_CLASSES = [
    (RetrievalMAP, {}),
    (RetrievalMRR, {}),
    (RetrievalPrecision, {"k": 2}),
    (RetrievalRecall, {"k": 3}),
    (RetrievalHitRate, {"k": 2}),
    (RetrievalFallOut, {"k": 2}),
    (RetrievalRPrecision, {}),
    (RetrievalNormalizedDCG, {}),
    (RetrievalNormalizedDCG, {"k": 3}),
]


def _stream(seed=0, n_q=19, lo=1, hi=9, all_pos_every=7, all_neg_every=5):
    rng = np.random.RandomState(seed)
    idx_l, p_l, t_l = [], [], []
    for q in range(n_q):
        n = int(rng.randint(lo, hi))
        idx_l.append(np.full(n, q * 13 + 5))  # sparse non-contiguous ids
        p_l.append((rng.randint(0, 64, n) / 64.0).astype(np.float32))
        if q % all_neg_every == 0:
            t = np.zeros(n)
        elif q % all_pos_every == 0:
            t = np.ones(n)
        else:
            t = rng.randint(0, 2, n)
        t_l.append(t.astype(np.int32))
    return (
        np.concatenate(idx_l),
        np.concatenate(p_l),
        np.concatenate(t_l),
    )


def _pair(cls, action="neg", ignore_index=None, **kw):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        exact = cls(empty_target_action=action, ignore_index=ignore_index, exact=True, **kw)
    table = cls(
        empty_target_action=action,
        ignore_index=ignore_index,
        max_queries=64,
        max_docs=16,
        **kw,
    )
    return exact, table


# ---------------------------------------------------------------------------
# in-window parity
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("cls, kw", ALL_CLASSES, ids=lambda c: getattr(c, "__name__", str(c)))
@pytest.mark.parametrize("action", ["neg", "pos", "skip"])
def test_table_matches_exact_all_actions(cls, kw, action):
    idx, preds, target = _stream(1)
    exact, table = _pair(cls, action=action, **kw)
    cuts = [0, 17, 18, 60, len(idx)]
    for lo, hi in zip(cuts, cuts[1:]):
        if hi > lo:
            for m in (exact, table):
                m.update(
                    jnp.asarray(preds[lo:hi]), jnp.asarray(target[lo:hi]), indexes=jnp.asarray(idx[lo:hi])
                )
    np.testing.assert_allclose(
        np.asarray(exact.compute()), np.asarray(table.compute()), atol=1e-6
    )


@pytest.mark.parametrize(
    "cls, kw",
    [(RetrievalHitRate, {"k": 2}), (RetrievalPrecision, {"k": 2}), (RetrievalPrecision, {"k": 4})],
)
def test_table_bit_identical_on_dyadic_values(cls, kw):
    """Hit-rate / precision@2^k per-query values are dyadic rationals, so
    their sum is exact in f32 whatever the reduction tree — the table and
    exact paths must agree BIT-for-bit, not just within tolerance."""
    idx, preds, target = _stream(2)
    exact, table = _pair(cls, **kw)
    for m in (exact, table):
        m.update(jnp.asarray(preds), jnp.asarray(target), indexes=jnp.asarray(idx))
    assert float(exact.compute()) == float(table.compute())


def test_table_ignore_index_matches_exact():
    rng = np.random.RandomState(3)
    idx, preds, target = _stream(3)
    target = target.copy()
    target[rng.rand(len(target)) < 0.25] = -100  # ignored docs
    exact, table = _pair(RetrievalMAP, ignore_index=-100)
    for m in (exact, table):
        m.update(jnp.asarray(preds), jnp.asarray(target), indexes=jnp.asarray(idx))
    np.testing.assert_allclose(
        np.asarray(exact.compute()), np.asarray(table.compute()), atol=1e-6
    )


def test_table_error_action_parity():
    exact, table = _pair(RetrievalMAP, action="error")
    z = jnp.zeros(4, jnp.int32)
    for m in (exact, table):
        m.update(jnp.asarray([0.1, 0.2, 0.3, 0.4]), z, indexes=jnp.asarray([0, 0, 1, 1]))
        with pytest.raises(ValueError, match="no positive"):
            m.compute()


def test_table_fall_out_inverted_empty_counter():
    """FallOut's empty flag reads the NEG counter — all-positive queries
    trip the inverted error exactly as the cat path does."""
    exact, table = _pair(RetrievalFallOut, action="error")
    ones = jnp.ones(4, jnp.int32)
    for m in (exact, table):
        m.update(jnp.asarray([0.1, 0.2, 0.3, 0.4]), ones, indexes=jnp.asarray([0, 0, 1, 1]))
        with pytest.raises(ValueError, match="no negative"):
            m.compute()


def test_table_graded_ndcg_matches_exact():
    rng = np.random.RandomState(4)
    n_per = [3, 8, 5, 12, 1, 7]
    idx = np.concatenate([np.full(n, q * 3) for q, n in enumerate(n_per)])
    preds = (rng.randint(0, 64, sum(n_per)) / 64.0).astype(np.float32)
    target = rng.randint(0, 6, sum(n_per)).astype(np.int32)
    exact, table = _pair(RetrievalNormalizedDCG, k=4)
    for m in (exact, table):
        m.update(jnp.asarray(preds), jnp.asarray(target), indexes=jnp.asarray(idx))
    np.testing.assert_allclose(
        np.asarray(exact.compute()), np.asarray(table.compute()), atol=1e-6
    )


def test_exact_mode_is_jit_unsafe_table_is_not():
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        exact = RetrievalMAP(exact=True)
    table = RetrievalMAP()
    assert exact.__jit_unsafe__ is True  # instance-level flip
    assert getattr(table, "__jit_unsafe__") is False
    assert isinstance(exact.indexes, list)
    assert isinstance(table.qtable, jnp.ndarray)


def test_empty_compute_raises_descriptive():
    m = RetrievalMAP(max_queries=8, max_docs=8)
    m._update_called = True  # silence the warn; the raise is the contract
    with pytest.raises(ValueError, match="no accumulated samples"):
        m.compute()


# ---------------------------------------------------------------------------
# ragged chunking / capacity semantics
# ---------------------------------------------------------------------------


def test_chunking_invariance():
    """One big update == many ragged updates == doc-level dribble."""
    idx, preds, target = _stream(5, n_q=11)
    ms = [RetrievalMAP(max_queries=32, max_docs=16) for _ in range(3)]
    ms[0].update(jnp.asarray(preds), jnp.asarray(target), indexes=jnp.asarray(idx))
    for lo in range(0, len(idx), 7):
        ms[1].update(
            jnp.asarray(preds[lo : lo + 7]), jnp.asarray(target[lo : lo + 7]), indexes=jnp.asarray(idx[lo : lo + 7])
        )
    for lo in range(0, len(idx), 1):
        ms[2].update(
            jnp.asarray(preds[lo : lo + 1]), jnp.asarray(target[lo : lo + 1]), indexes=jnp.asarray(idx[lo : lo + 1])
        )
    vals = [float(m.compute()) for m in ms]
    assert vals[0] == vals[1] == vals[2]


def test_doc_overflow_keeps_counters_exact_and_truncates_topk():
    """A query streaming far past max_docs: NSEEN/POS/NEG stay exact, the
    stored docs are the top-scored survivors, and the empty policy still
    reads the exact counters."""
    rng = np.random.RandomState(6)
    n = 300
    preds = rng.rand(n).astype(np.float32)
    target = (rng.rand(n) < 0.3).astype(np.int32)
    m = RetrievalPrecision(k=4, max_queries=4, max_docs=16)
    for lo in range(0, n, 37):
        m.update(
            jnp.asarray(preds[lo : lo + 37]), jnp.asarray(target[lo : lo + 37]), indexes=jnp.zeros(min(37, n - lo), jnp.int32)
        )
    key, qid, nseen, pos, neg, fill, pt, tt = _unpack(m.qtable)
    occ = np.asarray(key) > 0
    assert occ.sum() == 1
    r = int(np.nonzero(occ)[0][0])
    assert int(np.asarray(nseen)[r]) == n
    assert int(np.asarray(pos)[r]) == int(target.sum())
    assert int(np.asarray(neg)[r]) == int((target == 0).sum())
    f = int(np.asarray(fill)[r])
    assert f <= 16
    # stored docs are the global top-f by score: precision@4 over them
    # equals precision@4 over the full stream (truncation keeps the top)
    order = np.argsort(-preds, kind="stable")
    expect = float(target[order[:4]].sum() / 4.0)
    assert float(m.compute()) == pytest.approx(expect)


def test_query_reservoir_is_order_and_chunking_invariant():
    """Past max_queries the retained query SET is a pure function of the
    id set (deterministic hash keys): permuted arrival and different batch
    sizes land the same rows, and compute() is identical."""
    rng = np.random.RandomState(7)
    qids = np.repeat(np.arange(40) * 7 + 3, 4)
    preds = rng.rand(160).astype(np.float32)
    target = (rng.rand(160) < 0.5).astype(np.int32)

    def run(order_seed, batch):
        m = RetrievalMAP(max_queries=16, max_docs=8)
        o = np.random.RandomState(order_seed).permutation(160)
        qi, pp, tt = qids[o], preds[o], target[o]
        for lo in range(0, 160, batch):
            m.update(jnp.asarray(pp[lo : lo + batch]), jnp.asarray(tt[lo : lo + batch]), indexes=jnp.asarray(qi[lo : lo + batch]))
        key, qid, *_ = _unpack(m.qtable)
        kept = sorted(int(q) for q, k in zip(np.asarray(qid), np.asarray(key)) if k > 0)
        return kept, float(m.compute())

    k1, v1 = run(0, 160)
    k2, v2 = run(1, 13)
    k3, v3 = run(2, 41)
    assert k1 == k2 == k3 and len(k1) == 16
    assert v1 == v2 == v3


def test_admitted_query_docs_are_complete():
    """A query surviving the reservoir was admitted at FIRST sight (the
    table minimum only rises), so its stored docs are the full stream —
    pinned by comparing against an uncapped table over the kept subset."""
    rng = np.random.RandomState(8)
    qids = np.repeat(np.arange(30), 5)
    preds = rng.rand(150).astype(np.float32)
    target = (rng.rand(150) < 0.5).astype(np.int32)
    small = RetrievalMAP(max_queries=8, max_docs=8)
    for lo in range(0, 150, 11):
        small.update(jnp.asarray(preds[lo : lo + 11]), jnp.asarray(target[lo : lo + 11]), indexes=jnp.asarray(qids[lo : lo + 11]))
    key, qid, nseen, *_ = _unpack(small.qtable)
    kept = {int(q) for q, k in zip(np.asarray(qid), np.asarray(key)) if k > 0}
    assert len(kept) == 8
    for q in kept:
        want = int((qids == q).sum())
        got = int(np.asarray(nseen)[np.asarray(qid) == q][0])
        assert got == want
    # compute == exact mean restricted to the sampled queries
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        ref = RetrievalMAP(exact=True)
    mask = np.isin(qids, sorted(kept))
    ref.update(jnp.asarray(preds[mask]), jnp.asarray(target[mask]), indexes=jnp.asarray(qids[mask]))
    np.testing.assert_allclose(float(small.compute()), float(ref.compute()), atol=1e-6)


# ---------------------------------------------------------------------------
# merge / distributed
# ---------------------------------------------------------------------------


def test_merge_states_equals_single_stream():
    idx, preds, target = _stream(9)
    half = len(idx) // 2
    m1 = RetrievalMAP(max_queries=64, max_docs=16)
    m2 = RetrievalMAP(max_queries=64, max_docs=16)
    m1.update(jnp.asarray(preds[:half]), jnp.asarray(target[:half]), indexes=jnp.asarray(idx[:half]))
    m2.update(jnp.asarray(preds[half:]), jnp.asarray(target[half:]), indexes=jnp.asarray(idx[half:]))
    merged = m1.merge_states(
        {k: getattr(m1, k) for k in m1._defaults}, {k: getattr(m2, k) for k in m2._defaults}
    )
    full = RetrievalMAP(max_queries=64, max_docs=16)
    full.update(jnp.asarray(preds), jnp.asarray(target), indexes=jnp.asarray(idx))
    got = float(full.compute_state(merged))
    assert got == float(full.compute())


def test_merge_commutes_in_window():
    idx, preds, target = _stream(10)
    half = len(idx) // 2
    t1 = retrieval_table_insert(
        retrieval_table_init(64, 16), idx[:half], preds[:half], target[:half]
    )
    t2 = retrieval_table_insert(
        retrieval_table_init(64, 16), idx[half:], preds[half:], target[half:]
    )
    ab = retrieval_table_merge(t1, t2)
    ba = retrieval_table_merge(t2, t1)
    # row multiset equality (row order differs; canonicalize by qid)
    la = retrieval_table_layout(ab)
    lb = retrieval_table_layout(ba)
    for xa, xb in zip(la, lb):
        assert jnp.array_equal(jnp.asarray(xa), jnp.asarray(xb))


def test_mesh_merge_round_equals_host_fold():
    from jax.sharding import Mesh, PartitionSpec as P

    from metrics_tpu.parallel.distributed import sync_pytree_in_mesh
    from metrics_tpu.utils.compat import shard_map

    n_dev = 8
    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("rank",))
    rng = np.random.RandomState(11)
    per_rank, streams = [], []
    for r in range(n_dev):
        m = RetrievalMAP(max_queries=128, max_docs=16)
        counts = rng.randint(1, 6, 5)
        idx = np.repeat(np.arange(r * 5, r * 5 + 5), counts)
        p = rng.rand(len(idx)).astype(np.float32)
        t = (rng.rand(len(idx)) < 0.5).astype(np.int32)
        m.update(jnp.asarray(p), jnp.asarray(t), indexes=jnp.asarray(idx))
        per_rank.append(jnp.asarray(m.qtable))
        streams.append((idx, p, t))
    template = RetrievalMAP(max_queries=128, max_docs=16)
    reductions = template.state_reductions()
    stacked = jnp.stack(per_rank)

    def body(tab):
        return sync_pytree_in_mesh({"qtable": tab[0]}, reductions, "rank")["qtable"]

    synced = jax.jit(
        shard_map(body, mesh=mesh, in_specs=(P("rank"),), out_specs=P())
    )(stacked)
    assert jnp.array_equal(synced, reductions["qtable"](stacked))
    # in-window: fold == one metric over the union stream
    union = RetrievalMAP(max_queries=128, max_docs=16)
    for idx, p, t in streams:
        union.update(jnp.asarray(p), jnp.asarray(t), indexes=jnp.asarray(idx))
    assert float(union.compute_state({"qtable": synced})) == float(union.compute())


# ---------------------------------------------------------------------------
# fused / bucketed / async composition
# ---------------------------------------------------------------------------


def _ragged_batches(seed=12):
    rng = np.random.RandomState(seed)
    out = []
    for base, n_q in ((0, 10), (10, 13), (23, 7)):
        counts = rng.randint(2, 8, n_q)
        idx = np.repeat(np.arange(base, base + n_q), counts)
        n = len(idx)
        out.append(
            (
                jnp.asarray(rng.rand(n).astype(np.float32)),
                jnp.asarray((rng.rand(n) < 0.4).astype(np.int32)),
                jnp.asarray(idx),
            )
        )
    return out


def test_fused_bucketed_single_compile_bit_parity():
    kw = dict(max_queries=256, max_docs=32)
    fused = MetricCollection([RetrievalNormalizedDCG(**kw), RetrievalMAP(**kw)])
    eager = MetricCollection([RetrievalNormalizedDCG(**kw), RetrievalMAP(**kw)])
    handle = fused.compile_update(buckets=[64, 128, 256])
    for p, t, i in _ragged_batches():
        fused.update(p, t, indexes=i)
        eager.update(p, t, indexes=i)
    rf = {k: float(v) for k, v in fused.compute().items()}
    re_ = {k: float(v) for k, v in eager.compute().items()}
    assert rf == re_
    assert len(handle._cache) == 1  # ONE compile across 3 ragged shapes
    assert not handle._eager_names  # nobody fell back eagerly
    # state-level bit parity, not just the computed scalars
    for name in ("RetrievalNormalizedDCG", "RetrievalMAP"):
        assert jnp.array_equal(fused[name].qtable, eager[name].qtable)


def test_async_ingest_bit_parity():
    kw = dict(max_queries=256, max_docs=32)
    a = MetricCollection([RetrievalMAP(**kw)])
    b = MetricCollection([RetrievalMAP(**kw)])
    a.compile_update_async(buckets=[64, 128, 256])
    for p, t, i in _ragged_batches(13):
        a.update_async(p, t, indexes=i)
        b.update(p, t, indexes=i)
    assert float(a.compute()["RetrievalMAP"]) == float(b.compute()["RetrievalMAP"])


def test_manifest_seeds_fused_build_without_probe():
    from metrics_tpu.core.metric import Metric

    entry = RetrievalMAP.static_fusibility()
    assert entry is not None and entry["verdict"] == "fusible"
    assert entry["states"]["qtable"]["dist_reduce_fx"] == "merge"


# ---------------------------------------------------------------------------
# observability surface
# ---------------------------------------------------------------------------


def test_footprint_under_sketch_prefix_and_fill_ratio():
    m = RetrievalMAP(max_queries=32, max_docs=8)
    fp = m.state_footprint()
    assert list(fp) == ["sketch/qtable"]
    assert fp["sketch/qtable"] == 32 * (7 + 16) * 4
    idx, preds, target = _stream(14, n_q=5)
    m.update(jnp.asarray(preds), jnp.asarray(target), indexes=jnp.asarray(idx))
    ratios = m.sketch_fill_ratios()
    assert ratios["qtable"] == pytest.approx(5 / 32)
    assert int(retrieval_table_fill(m.qtable)) == 5


def test_layout_cache_bounded_across_epochs():
    """The module-level epoch-keyed layout cache must stay LRU-bounded no
    matter how many write/read epochs a long-lived metric cycles through —
    a serving loop polling between ingest batches must not grow it."""
    from metrics_tpu.retrieval import base as rbase

    m = RetrievalMAP(max_queries=32, max_docs=8)
    idx, preds, target = _stream(3, n_q=4)
    for _ in range(3 * rbase._LAYOUT_CACHE_MAX):
        m.update(jnp.asarray(preds), jnp.asarray(target), indexes=jnp.asarray(idx))
        m.compute()
    assert len(rbase._LAYOUT_CACHE) <= rbase._LAYOUT_CACHE_MAX
