"""Mean absolute percentage error.

Behavior parity with /root/reference/torchmetrics/functional/regression/mape.py
(epsilon = 1.17e-06, taken from sklearn's implementation).
"""
from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _check_same_shape

Array = jax.Array


def _mean_absolute_percentage_error_update(
    preds: Array,
    target: Array,
    epsilon: float = 1.17e-06,
) -> Tuple[Array, int]:
    _check_same_shape(preds, target)
    abs_per_error = jnp.abs(preds - target) / jnp.clip(jnp.abs(target), min=epsilon)
    return jnp.sum(abs_per_error), target.size


def _mean_absolute_percentage_error_compute(sum_abs_per_error: Array, num_obs: Array) -> Array:
    return sum_abs_per_error / num_obs


def mean_absolute_percentage_error(preds: Array, target: Array) -> Array:
    """Computes mean absolute percentage error.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([1., 10., 1e6])
        >>> preds = jnp.array([0.9, 15., 1.2e6])
        >>> mean_absolute_percentage_error(preds, target)
        Array(0.26666668, dtype=float32)
    """
    sum_abs_per_error, num_obs = _mean_absolute_percentage_error_update(preds, target)
    return _mean_absolute_percentage_error_compute(sum_abs_per_error, num_obs)
