"""Modular FBetaScore / F1Score.

Behavior parity with /root/reference/torchmetrics/classification/
f_beta.py:23-303.
"""
from typing import Any, Optional

import jax

from metrics_tpu.classification.stat_scores import StatScores
from metrics_tpu.functional.classification.f_beta import _fbeta_compute
from metrics_tpu.utils.enums import AverageMethod

Array = jax.Array


class FBetaScore(StatScores):
    """Computes F-beta.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([0, 1, 2, 0, 1, 2])
        >>> preds = jnp.array([0, 2, 1, 0, 0, 1])
        >>> f_beta = FBetaScore(num_classes=3, beta=0.5)
        >>> f_beta(preds, target)
        Array(0.33333334, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True

    def __init__(
        self,
        num_classes: Optional[int] = None,
        beta: float = 1.0,
        threshold: float = 0.5,
        average: str = "micro",
        mdmc_average: Optional[str] = None,
        ignore_index: Optional[int] = None,
        top_k: Optional[int] = None,
        multiclass: Optional[bool] = None,
        **kwargs: Any,
    ) -> None:
        self.beta = beta
        allowed_average = list(AverageMethod)
        if average not in allowed_average:
            raise ValueError(f"The `average` has to be one of {allowed_average}, got {average}.")

        super().__init__(
            reduce="macro" if average in ["weighted", "none", None] else average,
            mdmc_reduce=mdmc_average,
            threshold=threshold,
            top_k=top_k,
            num_classes=num_classes,
            multiclass=multiclass,
            ignore_index=ignore_index,
            **kwargs,
        )
        self.average = average
        self.ignore_index = ignore_index

    def _compute(self) -> Array:
        tp, fp, tn, fn = self._get_final_stats()
        return _fbeta_compute(tp, fp, tn, fn, self.beta, self.ignore_index, self.average, self.mdmc_reduce)


class F1Score(FBetaScore):
    """F-beta with beta=1.0.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([0, 1, 2, 0, 1, 2])
        >>> preds = jnp.array([0, 2, 1, 0, 0, 1])
        >>> f1 = F1Score(num_classes=3)
        >>> f1(preds, target)
        Array(0.33333334, dtype=float32)
    """

    def __init__(
        self,
        num_classes: Optional[int] = None,
        threshold: float = 0.5,
        average: str = "micro",
        mdmc_average: Optional[str] = None,
        ignore_index: Optional[int] = None,
        top_k: Optional[int] = None,
        multiclass: Optional[bool] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            num_classes=num_classes,
            beta=1.0,
            threshold=threshold,
            average=average,
            mdmc_average=mdmc_average,
            ignore_index=ignore_index,
            top_k=top_k,
            multiclass=multiclass,
            **kwargs,
        )
