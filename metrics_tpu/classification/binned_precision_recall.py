"""Binned (fixed-threshold) precision-recall family — the TPU-native curve
formulation.

Behavior parity with /root/reference/torchmetrics/classification/
binned_precision_recall.py:45-322: static-shape ``[num_classes,
num_thresholds]`` TP/FP/FN accumulators with sum reduction. This is the
critical TPU template (SURVEY.md §2.4): the whole metric is jit-compatible,
its state syncs with a single psum, and memory is constant in dataset size.

TPU-first departure: the reference updates with a Python loop over
thresholds (binned_precision_recall.py:165-171, "to conserve memory");
here the update is a single vectorized pass — each prediction is bucketized
with ``searchsorted`` into its threshold bin (O(N·C·log T)), per-bin counts
are accumulated with a scatter-add (O(N·C + C·T) memory), and the
``pred >= threshold_t`` counts are recovered with a reversed cumulative sum.
Identical numerics for sorted thresholds (enforced at construction).
"""
from typing import Any, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.average_precision import (
    _average_precision_compute_with_precision_recall,
)
from metrics_tpu.utils.data import METRIC_EPS, to_onehot

Array = jax.Array


def _recall_at_precision(
    precision: Array,
    recall: Array,
    thresholds: Array,
    min_precision: float,
) -> Tuple[Array, Array]:
    """Highest recall with precision >= min_precision (ties -> max precision,
    then max threshold). Vectorized form of reference
    binned_precision_recall.py:25-42 (which zips to len(thresholds))."""
    n = thresholds.shape[0]
    precision, recall = precision[:n], recall[:n]
    valid = precision >= min_precision
    r = jnp.where(valid, recall, -jnp.inf)
    max_recall = jnp.max(r)
    cand = valid & (recall == max_recall)
    p = jnp.where(cand, precision, -jnp.inf)
    cand = cand & (precision == jnp.max(p))
    best_threshold = jnp.max(jnp.where(cand, thresholds, -jnp.inf))
    max_recall = jnp.where(jnp.isfinite(max_recall), max_recall, 0.0)
    best_threshold = jnp.where(max_recall == 0.0, jnp.asarray(1e6, thresholds.dtype), best_threshold)
    return max_recall, best_threshold


class BinnedPrecisionRecallCurve(Metric):
    """Precision-recall pairs at fixed thresholds, in constant memory.

    Example:
        >>> import jax.numpy as jnp
        >>> pred = jnp.array([0.0, 0.1, 0.8, 0.4])
        >>> target = jnp.array([0, 1, 1, 0])
        >>> pr_curve = BinnedPrecisionRecallCurve(num_classes=1, thresholds=5)
        >>> precision, recall, thresholds = pr_curve(pred, target)
        >>> precision
        Array([0.5000001 , 0.50000024, 1.        , 1.        , 1.        ,
               1.        ], dtype=float32)
        >>> recall
        Array([0.9999995 , 0.49999976, 0.49999976, 0.49999976, 0.        ,
               0.        ], dtype=float32)
    """

    is_differentiable = False

    def __init__(
        self,
        num_classes: int,
        thresholds: Union[int, Array, List[float], None] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        if isinstance(thresholds, int):
            self.num_thresholds = thresholds
            self.thresholds = jnp.linspace(0, 1.0, thresholds)
        elif thresholds is not None:
            if not isinstance(thresholds, (list, jnp.ndarray)):
                raise ValueError("Expected argument `thresholds` to either be an integer, list of floats or a tensor")
            thresholds = jnp.asarray(thresholds, dtype=jnp.float32)
            if bool(jnp.any(thresholds[1:] < thresholds[:-1])):
                raise ValueError("Expected argument `thresholds` to be sorted in increasing order")
            self.num_thresholds = thresholds.size
            self.thresholds = thresholds
        else:
            raise ValueError("Expected argument `thresholds` to either be an integer, list of floats or a tensor")

        for name in ("TPs", "FPs", "FNs"):
            self.add_state(
                name=name,
                default=jnp.zeros((num_classes, self.num_thresholds), dtype=jnp.float32),
                dist_reduce_fx="sum",
            )

    def _update(self, preds: Array, target: Array) -> None:
        if preds.ndim == target.ndim == 1:
            preds = preds.reshape(-1, 1)
            target = target.reshape(-1, 1)
        if preds.ndim == target.ndim + 1:
            target = to_onehot(target, num_classes=self.num_classes)

        target = (target == 1).astype(jnp.float32)  # [N, C]
        preds = preds.astype(jnp.float32)

        # bin index of the largest threshold <= pred; -1 means below all
        # thresholds (masked out of the scatter)
        bins = jnp.searchsorted(self.thresholds, preds, side="right") - 1  # [N, C], in [-1, T-1]
        valid = (bins >= 0).astype(jnp.float32)
        bins_c = jnp.maximum(bins, 0)
        cols = jnp.broadcast_to(jnp.arange(preds.shape[1]), preds.shape)

        zeros = jnp.zeros((preds.shape[1], self.num_thresholds), dtype=jnp.float32)
        pos_per_bin = zeros.at[cols, bins_c].add(target * valid)
        all_per_bin = zeros.at[cols, bins_c].add(valid)

        # pred >= thresholds[t]  <=>  bin >= t : reversed cumulative sum
        tp = jnp.cumsum(pos_per_bin[:, ::-1], axis=1)[:, ::-1]
        pred_pos = jnp.cumsum(all_per_bin[:, ::-1], axis=1)[:, ::-1]
        total_pos = jnp.sum(target, axis=0)[:, None]

        self.TPs = self.TPs + tp
        self.FPs = self.FPs + (pred_pos - tp)
        self.FNs = self.FNs + (total_pos - tp)

    def _compute(self) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
        precisions = (self.TPs + METRIC_EPS) / (self.TPs + self.FPs + METRIC_EPS)
        recalls = self.TPs / (self.TPs + self.FNs + METRIC_EPS)

        # guarantee the curve ends at precision=1, recall=0
        t_ones = jnp.ones((self.num_classes, 1), dtype=precisions.dtype)
        precisions = jnp.concatenate([precisions, t_ones], axis=1)
        t_zeros = jnp.zeros((self.num_classes, 1), dtype=recalls.dtype)
        recalls = jnp.concatenate([recalls, t_zeros], axis=1)
        if self.num_classes == 1:
            return precisions[0, :], recalls[0, :], self.thresholds
        return list(precisions), list(recalls), [self.thresholds for _ in range(self.num_classes)]


class BinnedAveragePrecision(BinnedPrecisionRecallCurve):
    """Average precision at fixed thresholds, in constant memory.

    Example:
        >>> import jax.numpy as jnp
        >>> pred = jnp.array([0.0, 1.0, 2.0, 3.0]) / 3
        >>> target = jnp.array([0, 1, 1, 1])
        >>> average_precision = BinnedAveragePrecision(num_classes=1, thresholds=10)
        >>> bool(average_precision(pred, target) > 0.99)
        True
    """

    def _compute(self) -> Union[List[Array], Array]:
        precisions, recalls, _ = super()._compute()
        return _average_precision_compute_with_precision_recall(
            precisions, recalls, self.num_classes, average=None
        )


class BinnedRecallAtFixedPrecision(BinnedPrecisionRecallCurve):
    """Highest recall at a minimum precision, at fixed thresholds."""

    def __init__(
        self,
        num_classes: int,
        min_precision: float,
        thresholds: Union[int, Array, List[float], None] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(num_classes=num_classes, thresholds=thresholds, **kwargs)
        self.min_precision = min_precision

    def _compute(self) -> Tuple[Array, Array]:
        precisions, recalls, thresholds = super()._compute()

        if self.num_classes == 1:
            return _recall_at_precision(precisions, recalls, thresholds, self.min_precision)

        recalls_at_p = []
        thresholds_at_p = []
        for i in range(self.num_classes):
            r, t = _recall_at_precision(precisions[i], recalls[i], thresholds[i], self.min_precision)
            recalls_at_p.append(r)
            thresholds_at_p.append(t)
        return jnp.stack(recalls_at_p), jnp.stack(thresholds_at_p)
