"""Device memory observatory: live HBM ledger + cache-plane inventory.

``state_footprint()`` (core/metric.py) *predicts* bytes from shapes and
dtypes; nothing asked the device what is actually resident. Meanwhile the
runtime grew four invisible device-memory consumers — ReaderCache AOT
executables, the fused-update compile cache, the retrieval layout LRU,
and sketch scratch — plus the sliced per-slice value cache on the host.
This module makes "where did my HBM go" answerable from telemetry:

* :class:`MemoryLedger` walks live metric state pytrees and reports
  *committed* bytes — dedup by buffer identity, so donated/aliased
  fused-update buffers (deleted arrays count 0 via the ``_nbytes``
  contract) and shared compute-group state are never double-counted —
  with a per-device breakdown for slice-sharded state.
* A **cache-plane registry**: every byte-holding cache registers a
  ``nbytes()`` callback under a stable plane name
  (``reader_cache | fused_compile | retrieval_layout | sketch_scratch |
  sliced_value_cache | windowed_fold_memo``) into one global inventory.
* :class:`MemoryObservatory` polls backend ``memory_stats()``
  (bytes_in_use / peak_bytes_in_use where the backend provides them;
  graceful host-RSS fallback on CPU, ``None`` when nothing reports) and
  derives the **unaccounted-bytes** residue
  ``in_use − ledger − cache planes`` — the leak signal the
  ``memory_leak`` alarm (observability/health.py) watches for monotone
  growth, while ``memory_budget`` watches the ledger's bytes/tenant.

Everything here is read-path / poll-rate code: the metric hot paths only
touch the recorder's one-bool-gated ``record_memory_boundary`` hook. The
module never imports jax at import time (backend access is lazy), so the
recorder's jax-free property is preserved for everything but the poller.
"""
from __future__ import annotations

import os
import threading
import weakref
from typing import Any, Callable, Dict, Iterable, List, Optional

from metrics_tpu.observability.recorder import _DEFAULT_RECORDER, _nbytes

__all__ = [
    "MemoryLedger",
    "MemoryObservatory",
    "backend_memory_stats",
    "cache_plane_inventory",
    "cache_plane_total",
    "executable_nbytes",
    "host_rss_bytes",
    "live_metrics",
    "register_cache_plane",
    "unregister_cache_plane",
]


# ---------------------------------------------------------------------------
# live-metric registry (fed by Metric.__init__ via _track_metric)
# ---------------------------------------------------------------------------

_LIVE_METRICS: "weakref.WeakSet[Any]" = weakref.WeakSet()
_LIVE_LOCK = threading.Lock()


def _track_metric(metric: Any) -> None:
    """Register a live metric for default-ledger walks. Called from
    ``Metric.__init__`` — one WeakSet add, and never allowed to fail a
    metric's construction."""
    try:
        with _LIVE_LOCK:
            _LIVE_METRICS.add(metric)
    except Exception:  # noqa: BLE001 — unhashable/weakref-less foreign subclass
        pass


def live_metrics() -> List[Any]:
    """Every live (not yet garbage-collected) metric instance in the
    process — the default population a :class:`MemoryLedger` walks."""
    with _LIVE_LOCK:
        return list(_LIVE_METRICS)


# ---------------------------------------------------------------------------
# cache-plane registry
# ---------------------------------------------------------------------------

_PLANES: Dict[str, Callable[[], int]] = {}
_PLANES_LOCK = threading.Lock()


def register_cache_plane(name: str, nbytes_fn: Callable[[], int]) -> str:
    """Register (or replace) a byte-holding cache's ``nbytes()`` callback
    under ``name``. Owning modules register ONE plane per cache kind at
    import (the callback fans out over a WeakSet of live instances), so
    the inventory is a short, stable table, not per-instance churn."""
    with _PLANES_LOCK:
        _PLANES[name] = nbytes_fn
    return name


def unregister_cache_plane(name: str) -> bool:
    with _PLANES_LOCK:
        return _PLANES.pop(name, None) is not None


def cache_plane_inventory() -> Dict[str, int]:
    """Current bytes per registered plane. A callback that raises reports
    0 — the inventory must never take down a poll."""
    with _PLANES_LOCK:
        planes = dict(_PLANES)
    out: Dict[str, int] = {}
    for name, fn in planes.items():
        try:
            out[name] = int(fn())
        except Exception:  # noqa: BLE001
            out[name] = 0
    return out


def cache_plane_total() -> int:
    return sum(cache_plane_inventory().values())


def executable_nbytes(compiled: Any) -> int:
    """Best-effort footprint of one AOT-compiled executable via its
    ``memory_analysis()`` (generated code + temp/argument/output
    allocations). Backends without the analysis (CPU commonly) report 0 —
    the plane then carries entry counts with honest zero bytes."""
    ma = getattr(compiled, "memory_analysis", None)
    if not callable(ma):
        return 0
    try:
        analysis = ma()
    except Exception:  # noqa: BLE001
        return 0
    if analysis is None:
        return 0
    if isinstance(analysis, dict):
        return int(sum(v for v in analysis.values() if isinstance(v, (int, float)) and v > 0))
    total = 0
    for attr in (
        "generated_code_size_in_bytes",
        "argument_size_in_bytes",
        "output_size_in_bytes",
        "temp_size_in_bytes",
        "alias_size_in_bytes",
    ):
        v = getattr(analysis, attr, None)
        if isinstance(v, (int, float)) and v > 0:
            total += int(v)
    return total


# ---------------------------------------------------------------------------
# the ledger
# ---------------------------------------------------------------------------


def _leaf_devices(value: Any) -> List[Any]:
    """Devices a leaf is resident on (duck-typed; ``["host"]`` for numpy
    and Python scalars). Sharded arrays report every addressable device."""
    devs = getattr(value, "devices", None)
    if callable(devs):
        try:
            ds = devs()
            if ds:
                return sorted(ds, key=str)
        except Exception:  # noqa: BLE001
            pass
    dev = getattr(value, "device", None)
    if dev is not None and not callable(dev):
        return [dev]
    return ["host"]


def _per_device_bytes(value: Any, nbytes: int) -> Dict[str, int]:
    """Per-device byte attribution of one leaf: exact via addressable
    shards when the array exposes them (slice-sharded [S] state), else
    split evenly across its devices."""
    shards = getattr(value, "addressable_shards", None)
    if shards:
        try:
            out: Dict[str, int] = {}
            for shard in shards:
                data = getattr(shard, "data", None)
                nb = _nbytes(data) if data is not None else 0
                key = str(getattr(shard, "device", "host"))
                out[key] = out.get(key, 0) + nb
            if out:
                return out
        except Exception:  # noqa: BLE001
            pass
    devices = _leaf_devices(value)
    if not devices:
        return {"host": nbytes}
    share, rem = divmod(nbytes, len(devices))
    out = {}
    for i, d in enumerate(devices):
        out[str(d)] = share + (1 if i < rem else 0)
    return out


def _iter_state_leaves(metric: Any):
    """Yield every array-state leaf of a metric (list/'cat' states flatten;
    children recurse — the buffer-identity dedup makes re-visits free)."""
    defaults = getattr(metric, "_defaults", None)
    if isinstance(defaults, dict):
        for name in defaults:
            val = getattr(metric, name, None)
            if isinstance(val, list):
                for item in val:
                    yield item
            elif val is not None and not isinstance(val, (int, float)):
                yield val
    children = getattr(metric, "_children", None)
    if isinstance(children, dict):
        kids = children.values()
    elif isinstance(children, (list, tuple)):
        kids = children
    else:
        kids = ()
    for child in kids:
        yield from _iter_state_leaves(child)


class MemoryLedger:
    """Walks metric state pytrees and reports *live committed* bytes.

    Dedup is by buffer identity (``id`` of the array object): compute-group
    members literally share the leader's arrays, and fused group
    propagation installs the same objects into every member, so a naive
    per-metric sum double-books them. Donated buffers mid-dispatch are
    deleted arrays and count 0 (the ``_nbytes`` contract), matching the
    async pipeline's separate in-flight accounting.

    ``metrics=None`` (the default) walks every live metric in the process
    — the population ``Metric.__init__`` registers. Passing an explicit
    iterable scopes the ledger (e.g. one serving loop's collection)."""

    def __init__(self, metrics: Optional[Iterable[Any]] = None) -> None:
        self._metrics = None if metrics is None else list(metrics)

    def metrics(self) -> List[Any]:
        return live_metrics() if self._metrics is None else list(self._metrics)

    def measure(self) -> Dict[str, Any]:
        """One ledger walk. Host-only reads (shape × itemsize metadata; no
        device sync). Returns totals, the per-device breakdown, per-metric
        attribution (first-owner wins for shared buffers), and the sliced
        bytes/tenant headline."""
        seen: set = set()
        total = 0
        n_buffers = 0
        n_shared = 0
        n_donated = 0
        per_device: Dict[str, int] = {}
        per_metric: Dict[str, int] = {}
        sliced_bytes = 0
        num_tenants = 0
        counted_metrics: set = set()
        for metric in self.metrics():
            if id(metric) in counted_metrics:
                continue
            counted_metrics.add(id(metric))
            label = type(metric).__name__
            metric_bytes = 0
            try:
                n_slices = getattr(metric, "num_slices", None)
                for leaf in _iter_state_leaves(metric):
                    key = id(leaf)
                    if key in seen:
                        n_shared += 1
                        continue
                    seen.add(key)
                    nb = _nbytes(leaf)
                    if nb == 0 and callable(getattr(leaf, "is_deleted", None)):
                        try:
                            if leaf.is_deleted():
                                n_donated += 1
                                continue
                        except Exception:  # noqa: BLE001
                            pass
                    if nb <= 0:
                        continue
                    n_buffers += 1
                    total += nb
                    metric_bytes += nb
                    for dev, db in _per_device_bytes(leaf, nb).items():
                        per_device[dev] = per_device.get(dev, 0) + db
                if isinstance(n_slices, int) and n_slices > 0:
                    sliced_bytes += metric_bytes
                    num_tenants += n_slices
            except Exception:  # noqa: BLE001 — a mid-mutation metric must not kill the poll
                continue
            if metric_bytes:
                per_metric[label] = per_metric.get(label, 0) + metric_bytes
        return {
            "total_bytes": total,
            "per_device": per_device,
            "per_metric": per_metric,
            "sliced_bytes": sliced_bytes,
            "num_tenants": num_tenants,
            "bytes_per_tenant": (sliced_bytes / num_tenants) if num_tenants else 0.0,
            "n_metrics": len(counted_metrics),
            "n_buffers": n_buffers,
            "n_shared": n_shared,
            "n_donated": n_donated,
        }

    def total_bytes(self) -> int:
        return int(self.measure()["total_bytes"])


# ---------------------------------------------------------------------------
# backend poller + observatory
# ---------------------------------------------------------------------------


def backend_memory_stats() -> Dict[str, Dict[str, int]]:
    """Per-device backend memory stats (``device.memory_stats()``):
    ``bytes_in_use`` / ``peak_bytes_in_use`` / ``bytes_limit`` where the
    backend provides them. TPU/GPU report; XLA:CPU typically returns
    nothing — then the result is ``{}`` and callers fall back gracefully."""
    try:
        import jax

        devices = jax.local_devices()
    except Exception:  # noqa: BLE001 — no backend is a valid observatory state
        return {}
    out: Dict[str, Dict[str, int]] = {}
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001
            stats = None
        if not stats:
            continue
        entry: Dict[str, int] = {}
        for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            v = stats.get(key)
            if isinstance(v, (int, float)):
                entry[key] = int(v)
        if entry:
            out[str(d)] = entry
    return out


def host_rss_bytes() -> Optional[int]:
    """Current resident set size of this process (``/proc/self/statm``;
    ``None`` off Linux) — the in-use fallback when the backend reports no
    memory stats, so the unaccounted-bytes leak signal still exists on a
    CPU box. The absolute value includes the Python heap; the leak alarm
    only cares about monotone *growth*, which survives the offset."""
    try:
        with open("/proc/self/statm") as fh:
            pages = int(fh.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except Exception:  # noqa: BLE001
        return None


class MemoryObservatory:
    """One poll surface over the ledger, the cache planes, and the
    backend: ``observe()`` measures everything, derives the unaccounted
    residue, feeds the recorder's ``mem_*`` series + one typed ``memory``
    event (when telemetry is enabled), and returns the full report dict.

    Serving loops call ``observe()`` at probe rate (alongside
    ``rec.tick()``); benches call it between ingest phases. It is never
    on a metric hot path."""

    def __init__(
        self,
        recorder: Optional[Any] = None,
        ledger: Optional[MemoryLedger] = None,
        use_host_rss: bool = True,
    ) -> None:
        self.recorder = _DEFAULT_RECORDER if recorder is None else recorder
        self.ledger = MemoryLedger() if ledger is None else ledger
        #: whether to fall back to /proc RSS when the backend reports no
        #: memory stats (CPU) — off for strict device-only accounting
        self.use_host_rss = bool(use_host_rss)

    def observe(self, **extra: Any) -> Dict[str, Any]:
        report = self.ledger.measure()
        planes = cache_plane_inventory()
        plane_total = sum(planes.values())
        backend = backend_memory_stats()
        in_use: Optional[int] = None
        peak: Optional[int] = None
        source: Optional[str] = None
        if backend:
            in_use = sum(e.get("bytes_in_use", 0) for e in backend.values())
            peaks = [e["peak_bytes_in_use"] for e in backend.values() if "peak_bytes_in_use" in e]
            peak = sum(peaks) if peaks else None
            source = "backend"
        elif self.use_host_rss:
            rss = host_rss_bytes()
            if rss is not None:
                in_use = rss
                source = "host_rss"
        unaccounted: Optional[int] = None
        if in_use is not None:
            unaccounted = int(in_use) - int(report["total_bytes"]) - int(plane_total)
        out: Dict[str, Any] = dict(report)
        out.update(
            {
                "cache_planes": planes,
                "cache_plane_bytes": plane_total,
                "backend": backend,
                "device_bytes_in_use": in_use,
                "device_peak_bytes": peak,
                "unaccounted_bytes": unaccounted,
                "source": source,
            }
        )
        rec = self.recorder
        if rec is not None and rec.enabled:
            rec.record_memory_observation(
                ledger_bytes=int(report["total_bytes"]),
                cache_plane_bytes=int(plane_total),
                device_bytes_in_use=in_use,
                device_peak_bytes=peak,
                unaccounted_bytes=unaccounted,
                bytes_per_tenant=report["bytes_per_tenant"] or None,
                per_device=report["per_device"] or None,
                planes=planes or None,
                source=source,
                **extra,
            )
        return out
