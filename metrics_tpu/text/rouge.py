"""Modular ROUGEScore.

Behavior parity with /root/reference/torchmetrics/text/rouge.py:31-193:
per-sentence scores appended to list states (one per ``rouge_key`` ×
fmeasure/precision/recall), all-gathered across ranks, mean on compute.
"""
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.text.rouge import (
    ALLOWED_ACCUMULATE_VALUES,
    ALLOWED_ROUGE_KEYS,
    _rouge_score_compute,
    _rouge_score_update,
)
from metrics_tpu.utils.imports import _NLTK_AVAILABLE

Array = jax.Array


class ROUGEScore(Metric):
    """Calculate ROUGE score for automatic summarization.

    Args:
        use_stemmer: Use the Porter stemmer to strip word suffixes.
        normalizer: Custom normalization function ``str -> str``.
        tokenizer: Custom tokenization function ``str -> Sequence[str]``.
        accumulate: Multi-reference accumulation: ``"best"`` takes the
            reference with the highest first-key fmeasure, ``"avg"`` averages
            over all references.
        rouge_keys: Which rouge scores to compute (``rouge1..rouge9``,
            ``rougeL``, ``rougeLsum``).

    Example:
        >>> preds = "My name is John"
        >>> target = "Is your name John"
        >>> rouge = ROUGEScore(rouge_keys=("rouge1", "rouge2", "rougeL"))
        >>> from pprint import pprint
        >>> pprint(rouge(preds, target))
        {'rouge1_fmeasure': Array(0.75, dtype=float32),
         'rouge1_precision': Array(0.75, dtype=float32),
         'rouge1_recall': Array(0.75, dtype=float32),
         'rouge2_fmeasure': Array(0., dtype=float32),
         'rouge2_precision': Array(0., dtype=float32),
         'rouge2_recall': Array(0., dtype=float32),
         'rougeL_fmeasure': Array(0.5, dtype=float32),
         'rougeL_precision': Array(0.5, dtype=float32),
         'rougeL_recall': Array(0.5, dtype=float32)}
    """

    higher_is_better = True
    is_differentiable = False
    __jit_unsafe__ = True  # update consumes Python strings

    def __init__(
        self,
        use_stemmer: bool = False,
        normalizer: Optional[Callable[[str], str]] = None,
        tokenizer: Optional[Callable[[str], Sequence[str]]] = None,
        accumulate: str = "best",
        rouge_keys: Union[str, Tuple[str, ...]] = ("rouge1", "rouge2", "rougeL", "rougeLsum"),
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if use_stemmer or "rougeLsum" in rouge_keys:
            if not _NLTK_AVAILABLE:
                raise ModuleNotFoundError(
                    "Stemmer and/or `rougeLsum` requires that `nltk` is installed. Use `pip install nltk`."
                )
            import nltk

        if not isinstance(rouge_keys, tuple):
            rouge_keys = (rouge_keys,)
        for key in rouge_keys:
            if key not in ALLOWED_ROUGE_KEYS:
                raise ValueError(
                    f"Got unknown rouge key {key}. Expected to be one of {list(ALLOWED_ROUGE_KEYS.keys())}"
                )
        if accumulate not in ALLOWED_ACCUMULATE_VALUES:
            raise ValueError(
                f"Got unknown accumulate value {accumulate}. Expected to be one of {ALLOWED_ACCUMULATE_VALUES}"
            )

        self.rouge_keys = rouge_keys
        self.rouge_keys_values = [ALLOWED_ROUGE_KEYS[key] for key in rouge_keys]
        self.stemmer = nltk.stem.porter.PorterStemmer() if use_stemmer else None
        self.normalizer = normalizer
        self.tokenizer = tokenizer
        self.accumulate = accumulate

        for rouge_key in self.rouge_keys:
            for score in ("fmeasure", "precision", "recall"):
                self.add_state(f"{rouge_key}_{score}", [], dist_reduce_fx=None)

    def _update(
        self, preds: Union[str, Sequence[str]], target: Union[str, Sequence[str], Sequence[Sequence[str]]]
    ) -> None:
        if isinstance(target, list) and all(isinstance(tgt, str) for tgt in target):
            target = [target] if isinstance(preds, str) else [[tgt] for tgt in target]
        if isinstance(preds, str):
            preds = [preds]
        if isinstance(target, str):
            target = [[target]]

        output: Dict[Union[int, str], List[Dict[str, float]]] = _rouge_score_update(
            preds,
            target,
            self.rouge_keys_values,
            stemmer=self.stemmer,
            normalizer=self.normalizer,
            tokenizer=self.tokenizer,
            accumulate=self.accumulate,
        )
        for rouge_key, metrics in output.items():
            for metric in metrics:
                for tp, value in metric.items():
                    getattr(self, f"rouge{rouge_key}_{tp}").append(jnp.asarray(value, jnp.float32))

    def _compute(self) -> Dict[str, Array]:
        update_output = {}
        for rouge_key in self.rouge_keys_values:
            for tp in ("fmeasure", "precision", "recall"):
                update_output[f"rouge{rouge_key}_{tp}"] = getattr(self, f"rouge{rouge_key}_{tp}")
        return _rouge_score_compute(update_output)

    # NOTE: the reference overrides __hash__ here (rouge.py:183-193) to work
    # around a torch nn.Module hashing bug with list states; the base
    # Metric.__hash__ in this framework already hashes list states by id.
