"""Modular CosineSimilarity (cat-state).

Behavior parity with /root/reference/torchmetrics/regression/cosine_similarity.py:24-89.
"""
from typing import Any, Optional

import jax

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.regression.cosine_similarity import (
    _cosine_similarity_compute,
    _cosine_similarity_update,
)
from metrics_tpu.utils.data import dim_zero_cat

Array = jax.Array


class CosineSimilarity(Metric):
    """Computes cosine similarity between predictions and targets.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([[0., 1.], [1., 1.]])
        >>> preds = jnp.array([[0., 1.], [0., 1.]])
        >>> cosine_similarity = CosineSimilarity(reduction='mean')
        >>> cosine_similarity(preds, target)
        Array(0.8535534, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = True
    #: list-append update traces; the cat states exclude it from fusion anyway
    __jit_unsafe__ = False

    def __init__(self, reduction: Optional[str] = "sum", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        allowed_reduction = ("sum", "mean", "none", None)
        if reduction not in allowed_reduction:
            raise ValueError(f"Expected argument `reduction` to be one of {allowed_reduction} but got {reduction}")
        self.reduction = reduction
        self.add_state("preds", default=[], dist_reduce_fx="cat")
        self.add_state("target", default=[], dist_reduce_fx="cat")

    def _update(self, preds: Array, target: Array) -> None:
        preds, target = _cosine_similarity_update(preds, target)
        self.preds.append(preds)
        self.target.append(target)

    def _compute(self) -> Array:
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _cosine_similarity_compute(preds, target, self.reduction)
