"""Modular HammingDistance.

Behavior parity with /root/reference/torchmetrics/classification/
hamming.py:23-100.
"""
from typing import Any

import jax
import jax.numpy as jnp

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.hamming import _hamming_distance_compute, _hamming_distance_update

Array = jax.Array


class HammingDistance(Metric):
    """Computes the average Hamming distance (Hamming loss).

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([[0, 1], [1, 1]])
        >>> preds = jnp.array([[0, 1], [0, 1]])
        >>> hamming_distance = HammingDistance()
        >>> hamming_distance(preds, target)
        Array(0.25, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = False

    def __init__(self, threshold: float = 0.5, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("correct", default=jnp.asarray(0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")
        self.threshold = threshold

    def _update(self, preds: Array, target: Array) -> None:
        correct, total = _hamming_distance_update(preds, target, self.threshold)
        self.correct = self.correct + correct
        self.total = self.total + total

    def _compute(self) -> Array:
        return _hamming_distance_compute(self.correct, self.total)
