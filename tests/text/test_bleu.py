"""BLEUScore parity vs nltk corpus_bleu (the reference's own oracle,
/root/reference/tests/text/test_bleu.py:18-28)."""
from functools import partial

import pytest

nltk_bleu = pytest.importorskip("nltk.translate.bleu_score")

from metrics_tpu.functional.text.bleu import bleu_score
from metrics_tpu.text.bleu import BLEUScore
from tests.text.helpers import TextTester
from tests.text.inputs import _inputs_multiple_references, _inputs_single_sentence_multiple_references

smooth_func = nltk_bleu.SmoothingFunction().method2


def _nltk_bleu(preds, targets, weights, smoothing_function):
    preds_ = [pred.split() for pred in preds]
    targets_ = [[line.split() for line in target] for target in targets]
    return nltk_bleu.corpus_bleu(
        list_of_references=targets_, hypotheses=preds_, weights=weights, smoothing_function=smoothing_function
    )


@pytest.mark.parametrize(
    ["weights", "n_gram", "smooth_fn", "smooth"],
    [
        ([1], 1, None, False),
        ([0.5, 0.5], 2, smooth_func, True),
        ([0.333333, 0.333333, 0.333333], 3, None, False),
        ([0.25, 0.25, 0.25, 0.25], 4, smooth_func, True),
    ],
)
class TestBLEUScore(TextTester):
    def test_bleu_score_class(self, weights, n_gram, smooth_fn, smooth):
        self.run_class_metric_test(
            preds=_inputs_multiple_references.preds,
            targets=_inputs_multiple_references.targets,
            metric_class=BLEUScore,
            sk_metric=partial(_nltk_bleu, weights=weights, smoothing_function=smooth_fn),
            metric_args={"n_gram": n_gram, "smooth": smooth},
        )

    def test_bleu_score_functional(self, weights, n_gram, smooth_fn, smooth):
        self.run_functional_metric_test(
            preds=_inputs_multiple_references.preds,
            targets=_inputs_multiple_references.targets,
            metric_functional=bleu_score,
            sk_metric=partial(_nltk_bleu, weights=weights, smoothing_function=smooth_fn),
            metric_args={"n_gram": n_gram, "smooth": smooth},
        )


def test_bleu_empty():
    """No n-gram overlap at all -> 0 (reference test_bleu.py:85-89)."""
    assert float(bleu_score([""], [[""]])) == 0.0


def test_no_4_gram():
    """Shorter-than-n predictions -> 0."""
    assert float(bleu_score(["My full program"], [["My full program tests"]])) == 0.0


def test_bleu_single_sentence():
    preds = _inputs_single_sentence_multiple_references.preds[0]
    targets = _inputs_single_sentence_multiple_references.targets[0]
    expected = _nltk_bleu(preds, targets, weights=[0.25] * 4, smoothing_function=None)
    assert float(bleu_score(preds, targets)) == pytest.approx(expected, abs=1e-4)
