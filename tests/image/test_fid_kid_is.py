"""FID / KID / IS numeric tests + torch->Flax Inception weight-conversion parity.

The reference ships a downloaded torch-fidelity InceptionV3
(/root/reference/torchmetrics/image/fid.py:26-57) and tests FID/KID/IS against
torch_fidelity itself (/root/reference/tests/image/test_fid.py). This
environment has no network, so:

- conversion correctness is proven with a torch *mirror* of the FID inception
  topology (exact torch-fidelity state_dict key names), randomly initialized,
  converted via ``convert_torch_fidelity_weights`` and checked for feature
  parity at every depth;
- FID numerics are checked against scipy.linalg.sqrtm (the reference's own
  backend, fid.py:66-74) on synthetic features;
- KID / IS numerics are checked against straight numpy re-derivations of the
  reference formulas (kid.py:29-66, inception.py:120-140).
"""
import numpy as np
import pytest
import scipy.linalg
import torch
import torch.nn.functional as F
from torch import nn as tnn

import jax.numpy as jnp

from metrics_tpu.image.fid import FrechetInceptionDistance
from metrics_tpu.image.inception import InceptionScore
from metrics_tpu.image.kid import KernelInceptionDistance
from metrics_tpu.models.inception import InceptionV3FID, convert_torch_fidelity_weights

# ---------------------------------------------------------------------------
# Torch mirror of the FID-compat InceptionV3 (torch-fidelity module naming)
# ---------------------------------------------------------------------------


class TBasicConv2d(tnn.Module):
    def __init__(self, cin, cout, **kw):
        super().__init__()
        self.conv = tnn.Conv2d(cin, cout, bias=False, **kw)
        self.bn = tnn.BatchNorm2d(cout, eps=0.001)

    def forward(self, x):
        return F.relu(self.bn(self.conv(x)))


def _avg3(x):
    return F.avg_pool2d(x, kernel_size=3, stride=1, padding=1, count_include_pad=False)


class TInceptionA(tnn.Module):
    def __init__(self, cin, pool_features):
        super().__init__()
        self.branch1x1 = TBasicConv2d(cin, 64, kernel_size=1)
        self.branch5x5_1 = TBasicConv2d(cin, 48, kernel_size=1)
        self.branch5x5_2 = TBasicConv2d(48, 64, kernel_size=5, padding=2)
        self.branch3x3dbl_1 = TBasicConv2d(cin, 64, kernel_size=1)
        self.branch3x3dbl_2 = TBasicConv2d(64, 96, kernel_size=3, padding=1)
        self.branch3x3dbl_3 = TBasicConv2d(96, 96, kernel_size=3, padding=1)
        self.branch_pool = TBasicConv2d(cin, pool_features, kernel_size=1)

    def forward(self, x):
        return torch.cat(
            [
                self.branch1x1(x),
                self.branch5x5_2(self.branch5x5_1(x)),
                self.branch3x3dbl_3(self.branch3x3dbl_2(self.branch3x3dbl_1(x))),
                self.branch_pool(_avg3(x)),
            ],
            dim=1,
        )


class TInceptionB(tnn.Module):
    def __init__(self, cin):
        super().__init__()
        self.branch3x3 = TBasicConv2d(cin, 384, kernel_size=3, stride=2)
        self.branch3x3dbl_1 = TBasicConv2d(cin, 64, kernel_size=1)
        self.branch3x3dbl_2 = TBasicConv2d(64, 96, kernel_size=3, padding=1)
        self.branch3x3dbl_3 = TBasicConv2d(96, 96, kernel_size=3, stride=2)

    def forward(self, x):
        return torch.cat(
            [
                self.branch3x3(x),
                self.branch3x3dbl_3(self.branch3x3dbl_2(self.branch3x3dbl_1(x))),
                F.max_pool2d(x, kernel_size=3, stride=2),
            ],
            dim=1,
        )


class TInceptionC(tnn.Module):
    def __init__(self, cin, c7):
        super().__init__()
        self.branch1x1 = TBasicConv2d(cin, 192, kernel_size=1)
        self.branch7x7_1 = TBasicConv2d(cin, c7, kernel_size=1)
        self.branch7x7_2 = TBasicConv2d(c7, c7, kernel_size=(1, 7), padding=(0, 3))
        self.branch7x7_3 = TBasicConv2d(c7, 192, kernel_size=(7, 1), padding=(3, 0))
        self.branch7x7dbl_1 = TBasicConv2d(cin, c7, kernel_size=1)
        self.branch7x7dbl_2 = TBasicConv2d(c7, c7, kernel_size=(7, 1), padding=(3, 0))
        self.branch7x7dbl_3 = TBasicConv2d(c7, c7, kernel_size=(1, 7), padding=(0, 3))
        self.branch7x7dbl_4 = TBasicConv2d(c7, c7, kernel_size=(7, 1), padding=(3, 0))
        self.branch7x7dbl_5 = TBasicConv2d(c7, 192, kernel_size=(1, 7), padding=(0, 3))
        self.branch_pool = TBasicConv2d(cin, 192, kernel_size=1)

    def forward(self, x):
        b2 = self.branch7x7_3(self.branch7x7_2(self.branch7x7_1(x)))
        b3 = self.branch7x7dbl_5(
            self.branch7x7dbl_4(self.branch7x7dbl_3(self.branch7x7dbl_2(self.branch7x7dbl_1(x))))
        )
        return torch.cat([self.branch1x1(x), b2, b3, self.branch_pool(_avg3(x))], dim=1)


class TInceptionD(tnn.Module):
    def __init__(self, cin):
        super().__init__()
        self.branch3x3_1 = TBasicConv2d(cin, 192, kernel_size=1)
        self.branch3x3_2 = TBasicConv2d(192, 320, kernel_size=3, stride=2)
        self.branch7x7x3_1 = TBasicConv2d(cin, 192, kernel_size=1)
        self.branch7x7x3_2 = TBasicConv2d(192, 192, kernel_size=(1, 7), padding=(0, 3))
        self.branch7x7x3_3 = TBasicConv2d(192, 192, kernel_size=(7, 1), padding=(3, 0))
        self.branch7x7x3_4 = TBasicConv2d(192, 192, kernel_size=3, stride=2)

    def forward(self, x):
        b1 = self.branch3x3_2(self.branch3x3_1(x))
        b2 = self.branch7x7x3_4(self.branch7x7x3_3(self.branch7x7x3_2(self.branch7x7x3_1(x))))
        return torch.cat([b1, b2, F.max_pool2d(x, kernel_size=3, stride=2)], dim=1)


class TInceptionE(tnn.Module):
    def __init__(self, cin, pool="avg"):
        super().__init__()
        self.pool = pool
        self.branch1x1 = TBasicConv2d(cin, 320, kernel_size=1)
        self.branch3x3_1 = TBasicConv2d(cin, 384, kernel_size=1)
        self.branch3x3_2a = TBasicConv2d(384, 384, kernel_size=(1, 3), padding=(0, 1))
        self.branch3x3_2b = TBasicConv2d(384, 384, kernel_size=(3, 1), padding=(1, 0))
        self.branch3x3dbl_1 = TBasicConv2d(cin, 448, kernel_size=1)
        self.branch3x3dbl_2 = TBasicConv2d(448, 384, kernel_size=3, padding=1)
        self.branch3x3dbl_3a = TBasicConv2d(384, 384, kernel_size=(1, 3), padding=(0, 1))
        self.branch3x3dbl_3b = TBasicConv2d(384, 384, kernel_size=(3, 1), padding=(1, 0))
        self.branch_pool = TBasicConv2d(cin, 192, kernel_size=1)

    def forward(self, x):
        b2 = self.branch3x3_1(x)
        b2 = torch.cat([self.branch3x3_2a(b2), self.branch3x3_2b(b2)], dim=1)
        b3 = self.branch3x3dbl_2(self.branch3x3dbl_1(x))
        b3 = torch.cat([self.branch3x3dbl_3a(b3), self.branch3x3dbl_3b(b3)], dim=1)
        if self.pool == "avg":
            bp = _avg3(x)
        else:
            bp = F.max_pool2d(x, kernel_size=3, stride=1, padding=1)
        return torch.cat([self.branch1x1(x), b2, b3, self.branch_pool(bp)], dim=1)


class TorchFIDInception(tnn.Module):
    """Torch mirror with torch-fidelity's exact module names / state_dict keys."""

    def __init__(self):
        super().__init__()
        self.Conv2d_1a_3x3 = TBasicConv2d(3, 32, kernel_size=3, stride=2)
        self.Conv2d_2a_3x3 = TBasicConv2d(32, 32, kernel_size=3)
        self.Conv2d_2b_3x3 = TBasicConv2d(32, 64, kernel_size=3, padding=1)
        self.Conv2d_3b_1x1 = TBasicConv2d(64, 80, kernel_size=1)
        self.Conv2d_4a_3x3 = TBasicConv2d(80, 192, kernel_size=3)
        self.Mixed_5b = TInceptionA(192, pool_features=32)
        self.Mixed_5c = TInceptionA(256, pool_features=64)
        self.Mixed_5d = TInceptionA(288, pool_features=64)
        self.Mixed_6a = TInceptionB(288)
        self.Mixed_6b = TInceptionC(768, c7=128)
        self.Mixed_6c = TInceptionC(768, c7=160)
        self.Mixed_6d = TInceptionC(768, c7=160)
        self.Mixed_6e = TInceptionC(768, c7=192)
        self.Mixed_7a = TInceptionD(768)
        self.Mixed_7b = TInceptionE(1280, pool="avg")
        self.Mixed_7c = TInceptionE(2048, pool="max")
        self.fc = tnn.Linear(2048, 1008)

    def forward(self, x, feature=2048):
        x = x * 2.0 - 1.0  # float [0,1] contract, same as the Flax path
        x = self.Conv2d_1a_3x3(x)
        x = self.Conv2d_2a_3x3(x)
        x = self.Conv2d_2b_3x3(x)
        x = F.max_pool2d(x, kernel_size=3, stride=2)
        if feature == 64:
            return x.mean(dim=(2, 3))
        x = self.Conv2d_3b_1x1(x)
        x = self.Conv2d_4a_3x3(x)
        x = F.max_pool2d(x, kernel_size=3, stride=2)
        if feature == 192:
            return x.mean(dim=(2, 3))
        x = self.Mixed_5b(x)
        x = self.Mixed_5c(x)
        x = self.Mixed_5d(x)
        x = self.Mixed_6a(x)
        x = self.Mixed_6b(x)
        x = self.Mixed_6c(x)
        x = self.Mixed_6d(x)
        x = self.Mixed_6e(x)
        if feature == 768:
            return x.mean(dim=(2, 3))
        x = self.Mixed_7a(x)
        x = self.Mixed_7b(x)
        x = self.Mixed_7c(x)
        x = x.mean(dim=(2, 3))
        if feature == 2048:
            return x
        if feature == "logits_unbiased":
            return x @ self.fc.weight.T
        return self.fc(x)


@pytest.fixture(scope="module")
def converted_pair():
    torch.manual_seed(0)
    net = TorchFIDInception().eval()
    with torch.no_grad():
        for mod in net.modules():
            if isinstance(mod, tnn.BatchNorm2d):
                mod.running_mean.normal_(0.0, 0.5)
                mod.running_var.uniform_(0.5, 1.5)
                mod.weight.uniform_(0.5, 1.5)
                mod.bias.normal_(0.0, 0.1)
    variables = convert_torch_fidelity_weights(net.state_dict())
    return net, variables


@pytest.mark.parametrize("feature", [64, 192, 768, 2048, "logits_unbiased", "logits"])
def test_weight_conversion_feature_parity(converted_pair, feature):
    """Converted Flax inception matches the torch mirror at every depth."""
    net, variables = converted_pair
    rng = np.random.RandomState(7)
    imgs = rng.rand(2, 3, 299, 299).astype(np.float32)

    with torch.no_grad():
        expected = net(torch.from_numpy(imgs), feature=feature).numpy()

    model = InceptionV3FID()
    flax_feature = 9999 if feature == "logits" else feature  # any non-depth value -> logits
    got = np.asarray(model.apply(variables, jnp.asarray(imgs), feature=flax_feature))

    assert got.shape == expected.shape
    np.testing.assert_allclose(got, expected, rtol=1e-3, atol=5e-3)


def test_weight_roundtrip_through_npz(converted_pair, tmp_path):
    """npz save -> build_fid_inception load path produces identical features."""
    from metrics_tpu.models.inception import build_fid_inception

    net, variables = converted_pair
    path = tmp_path / "inception.npz"
    np.savez(path, variables=np.asarray(variables, dtype=object))

    extractor = build_fid_inception(64, str(path))
    rng = np.random.RandomState(3)
    imgs = jnp.asarray(rng.rand(2, 3, 299, 299).astype(np.float32))
    got = np.asarray(extractor(imgs))
    direct = np.asarray(InceptionV3FID().apply(variables, imgs, feature=64))
    np.testing.assert_allclose(got, direct, rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# Numeric tests with a deterministic identity extractor
# ---------------------------------------------------------------------------


def _identity_extractor(x):
    return x


def _scipy_fid(real: np.ndarray, fake: np.ndarray) -> float:
    """Reference FID formula with scipy.linalg.sqrtm (fid.py:66-74, 97-124)."""
    mu1, mu2 = real.mean(0), fake.mean(0)
    cov1 = np.cov(real, rowvar=False)
    cov2 = np.cov(fake, rowvar=False)
    covmean = scipy.linalg.sqrtm(cov1 @ cov2)
    if np.iscomplexobj(covmean):
        covmean = covmean.real
    diff = mu1 - mu2
    return float(diff @ diff + np.trace(cov1) + np.trace(cov2) - 2 * np.trace(covmean))


@pytest.mark.parametrize("mode_kwargs,rtol", [(dict(exact=True), 1e-4), (dict(feature_dim=16), 1e-3)])
def test_fid_matches_scipy_oracle(mode_kwargs, rtol):
    """exact mode reproduces the f64 scipy formula tightly; the streaming
    default (f32 moments + Newton–Schulz trace-sqrtm) tracks it to its
    documented device tolerance."""
    rng = np.random.RandomState(0)
    real = (rng.randn(200, 16) + 0.5).astype(np.float64)
    fake = (rng.randn(180, 16) * 1.3 - 0.2).astype(np.float64)

    metric = FrechetInceptionDistance(feature=_identity_extractor, **mode_kwargs)
    metric.update(jnp.asarray(real), real=True)
    metric.update(jnp.asarray(fake), real=False)
    got = float(metric.compute())

    expected = _scipy_fid(real, fake)
    np.testing.assert_allclose(got, expected, rtol=rtol)


def test_fid_same_distribution_near_zero():
    rng = np.random.RandomState(1)
    feats = rng.randn(300, 8).astype(np.float64)
    metric = FrechetInceptionDistance(feature=_identity_extractor, exact=True)
    metric.update(jnp.asarray(feats), real=True)
    metric.update(jnp.asarray(feats), real=False)
    assert abs(float(metric.compute())) < 1e-6

    streaming = FrechetInceptionDistance(feature=_identity_extractor, feature_dim=8)
    streaming.update(jnp.asarray(feats), real=True)
    streaming.update(jnp.asarray(feats), real=False)
    # identical moments -> the only residue is the Newton–Schulz tolerance
    assert abs(float(streaming.compute())) < 1e-2


def test_fid_batched_updates_equal_single():
    rng = np.random.RandomState(2)
    real = rng.randn(120, 8)
    fake = rng.randn(120, 8) + 1.0
    m1 = FrechetInceptionDistance(feature=_identity_extractor, feature_dim=8)
    for chunk in np.array_split(real, 4):
        m1.update(jnp.asarray(chunk), real=True)
    for chunk in np.array_split(fake, 3):
        m1.update(jnp.asarray(chunk), real=False)
    m2 = FrechetInceptionDistance(feature=_identity_extractor, feature_dim=8)
    m2.update(jnp.asarray(real), real=True)
    m2.update(jnp.asarray(fake), real=False)
    np.testing.assert_allclose(float(m1.compute()), float(m2.compute()), rtol=1e-6)


def _numpy_poly_mmd(f_real, f_fake, degree=3, gamma=None, coef=1.0):
    """Reference kid.py:29-66 re-derived in numpy."""
    if gamma is None:
        gamma = 1.0 / f_real.shape[1]
    k11 = (f_real @ f_real.T * gamma + coef) ** degree
    k22 = (f_fake @ f_fake.T * gamma + coef) ** degree
    k12 = (f_real @ f_fake.T * gamma + coef) ** degree
    m = k11.shape[0]
    kt11 = k11.sum() - np.trace(k11)
    kt22 = k22.sum() - np.trace(k22)
    return (kt11 + kt22) / (m * (m - 1)) - 2 * k12.sum() / (m * m)


def test_kid_matches_numpy_oracle():
    rng = np.random.RandomState(0)
    real = rng.randn(100, 8).astype(np.float64)
    fake = (rng.randn(90, 8) + 0.3).astype(np.float64)
    subsets, subset_size, seed = 5, 50, 42

    metric = KernelInceptionDistance(
        feature=_identity_extractor, subsets=subsets, subset_size=subset_size, seed=seed
    )
    metric.update(jnp.asarray(real), real=True)
    metric.update(jnp.asarray(fake), real=False)
    got_mean, got_std = (float(v) for v in metric.compute())

    oracle_rng = np.random.RandomState(seed)
    scores = []
    for _ in range(subsets):
        pr = oracle_rng.permutation(real.shape[0])[:subset_size]
        pf = oracle_rng.permutation(fake.shape[0])[:subset_size]
        scores.append(_numpy_poly_mmd(real[pr], fake[pf]))
    np.testing.assert_allclose(got_mean, np.mean(scores), rtol=1e-5)
    np.testing.assert_allclose(got_std, np.std(scores, ddof=1), rtol=1e-4)


def test_kid_raises_on_small_subset():
    metric = KernelInceptionDistance(feature=_identity_extractor, subset_size=50)
    metric.update(jnp.asarray(np.random.randn(10, 4)), real=True)
    metric.update(jnp.asarray(np.random.randn(10, 4)), real=False)
    with pytest.raises(ValueError, match="subset_size"):
        metric.compute()


def test_inception_score_matches_numpy_oracle():
    rng = np.random.RandomState(0)
    logits = rng.randn(100, 10).astype(np.float64) * 2.0
    splits, seed = 4, 11

    # exact=True: the oracle replicates the reference's seeded shuffle; the
    # streaming default assigns splits round-robin (own parity tests)
    metric = InceptionScore(feature=_identity_extractor, splits=splits, seed=seed, exact=True)
    metric.update(jnp.asarray(logits))
    got_mean, got_std = (float(v) for v in metric.compute())

    idx = np.random.RandomState(seed).permutation(logits.shape[0])
    shuffled = logits[idx]
    expm = np.exp(shuffled - shuffled.max(axis=1, keepdims=True))
    prob = expm / expm.sum(axis=1, keepdims=True)
    log_prob = np.log(prob)
    kls = []
    for p, lp in zip(np.array_split(prob, splits), np.array_split(log_prob, splits)):
        marginal = p.mean(axis=0, keepdims=True)
        kls.append(np.exp((p * (lp - np.log(marginal))).sum(axis=1).mean()))
    np.testing.assert_allclose(got_mean, np.mean(kls), rtol=1e-5)
    np.testing.assert_allclose(got_std, np.std(kls, ddof=1), rtol=1e-4)


def test_feature_argument_validation():
    with pytest.raises(ValueError, match="feature"):
        FrechetInceptionDistance(feature=100)
    with pytest.raises(TypeError):
        KernelInceptionDistance(feature=[1, 2])
    with pytest.raises(ValueError, match="weights"):
        FrechetInceptionDistance(feature=2048)  # bundled net without weights


def test_extractor_finalize_validates_last_batch(converted_pair, tmp_path):
    """The async range check is one batch delayed; finalize() (called from
    FID/KID/IS compute) must flush it so a mis-ranged FINAL batch still
    raises instead of silently mis-scaling features."""
    from metrics_tpu.models.inception import build_fid_inception

    net, variables = converted_pair
    path = tmp_path / "inception.npz"
    np.savez(path, variables=np.asarray(variables, dtype=object))
    extractor = build_fid_inception(64, str(path))

    bad = jnp.asarray(np.random.RandomState(0).rand(2, 3, 299, 299).astype(np.float32) * 255.0)
    extractor(bad)  # async check enqueued, not yet validated
    with pytest.raises(ValueError, match="must be in"):
        extractor.finalize()
    # flushed: a second finalize is a no-op
    extractor.finalize()


# ---------------------------------------------------------------------------
# streaming-state parity / composition (docs/image_detection_states.md)
# ---------------------------------------------------------------------------


def test_fid_streaming_state_is_exact_sufficient_statistics():
    """The covariance-identity contract: on dyadic features with a
    power-of-two count every moment leaf is BITWISE equal to the float64
    cat-state moments (sums of multiples of 1/4 stay exactly representable
    in float32), and the derived mean/cov match numpy's float64 estimators
    to float32 ulp of the moment scale — the streaming state loses nothing,
    the only approximation in the FID pipeline is compute()'s trace-sqrtm."""
    from metrics_tpu.sketches.moments import mean_cov_from_moments

    rng = np.random.RandomState(21)
    n, d = 64, 8  # n = 2^6: mean division is exact
    feats = rng.randint(0, 16, (n, d)).astype(np.float64) / 2.0  # dyadic

    m = FrechetInceptionDistance(feature=_identity_extractor, feature_dim=d)
    for chunk in np.array_split(feats, 5):
        m.update(jnp.asarray(chunk.astype(np.float32)), real=True)

    np.testing.assert_array_equal(np.asarray(m.real_feat_sum), feats.sum(0).astype(np.float32))
    np.testing.assert_array_equal(np.asarray(m.real_outer_sum), (feats.T @ feats).astype(np.float32))
    assert float(m.real_count) == n

    mean, cov = mean_cov_from_moments(m.real_feat_sum, m.real_outer_sum, m.real_count)
    np.testing.assert_array_equal(np.asarray(mean), feats.mean(0).astype(np.float32))
    # the identity's subtraction cancels two exact O(n·μ²) terms: its error
    # is a few ulp AT THAT SCALE, asserted explicitly
    scale = np.float32(np.abs(feats.T @ feats).max() / (n - 1))
    np.testing.assert_allclose(
        np.asarray(cov), np.cov(feats, rowvar=False), atol=8 * np.spacing(scale)
    )


def test_fid_is_width_mismatch_raises():
    m = FrechetInceptionDistance(feature=_identity_extractor, feature_dim=8)
    with pytest.raises(ValueError, match="feature_dim"):
        m.update(jnp.zeros((4, 16)), real=True)
    s = InceptionScore(feature=_identity_extractor, num_classes=8)
    with pytest.raises(ValueError, match="num_classes"):
        s.update(jnp.zeros((4, 16)))


def test_is_streaming_matches_round_robin_oracle():
    """The streaming default equals a float64 re-derivation that assigns
    samples to splits round-robin by arrival index, and the state is
    chunking-invariant (split_count exactly; the float sums to 1e-6, the
    per-batch partial-sum re-association)."""
    rng = np.random.RandomState(22)
    logits = rng.randn(60, 6).astype(np.float64)
    splits = 3

    def run(batch):
        m = InceptionScore(feature=_identity_extractor, num_classes=6, splits=splits)
        for lo in range(0, 60, batch):
            m.update(jnp.asarray(logits[lo : lo + batch].astype(np.float32)))
        return m

    m1, m2 = run(60), run(7)
    np.testing.assert_array_equal(np.asarray(m1.split_count), np.asarray(m2.split_count))
    np.testing.assert_allclose(np.asarray(m1.prob_sum), np.asarray(m2.prob_sum), atol=1e-6)
    np.testing.assert_allclose(np.asarray(m1.plogp_sum), np.asarray(m2.plogp_sum), atol=1e-6)
    got_mean, got_std = (float(v) for v in m1.compute())

    expm = np.exp(logits - logits.max(axis=1, keepdims=True))
    prob = expm / expm.sum(axis=1, keepdims=True)
    kls = []
    for k in range(splits):
        p = prob[k::splits]  # round-robin by arrival index
        marginal = p.mean(axis=0, keepdims=True)
        kls.append(np.exp((p * (np.log(p) - np.log(marginal))).sum(axis=1).mean()))
    np.testing.assert_allclose(got_mean, np.mean(kls), rtol=1e-5)
    np.testing.assert_allclose(got_std, np.std(kls, ddof=1), rtol=1e-4)


def test_kid_reservoir_draws_match_exact_in_window():
    """Satellite pin: inside the lossless window the KID reservoir holds
    the exact features in arrival order, so the host-RNG subset draws — and
    therefore compute() — are bit-identical to the ``exact=True`` cat-state
    path. The FID/IS streaming refactor must not move this."""
    rng = np.random.RandomState(23)
    kw = dict(feature=_identity_extractor, subsets=4, subset_size=10, seed=123)
    a = KernelInceptionDistance(**kw)
    with pytest.warns(UserWarning, match="memory"):
        b = KernelInceptionDistance(exact=True, **kw)
    for _ in range(3):
        real = rng.randn(15, 6).astype(np.float32)
        fake = rng.randn(12, 6).astype(np.float32)
        for m in (a, b):
            m.update(jnp.asarray(real), real=True)
            m.update(jnp.asarray(fake), real=False)
    am, astd = a.compute()
    bm, bstd = b.compute()
    assert float(am) == float(bm)
    assert float(astd) == float(bstd)


def _int_feature_batches(rng, sizes, d):
    """Integer-valued float32 features: every sum in the moment leaves is
    exactly representable, so fused-vs-eager parity is bitwise."""
    return [jnp.asarray(rng.randint(0, 8, (n, d)).astype(np.float32)) for n in sizes]


def test_fid_is_fused_bucketed_single_compile_bit_parity():
    from metrics_tpu import MetricCollection

    d = 8
    mk = lambda: MetricCollection(
        [
            FrechetInceptionDistance(feature=_identity_extractor, feature_dim=d),
            InceptionScore(feature=_identity_extractor, num_classes=d, splits=3),
        ]
    )
    fused, eager = mk(), mk()
    handle = fused.compile_update(buckets=[8])
    rng = np.random.RandomState(24)
    for x in _int_feature_batches(rng, (3, 5, 7), d):
        fused.update(x, real=True)
        eager.update(x, real=True)
    for x in _int_feature_batches(rng, (4, 6, 2), d):
        fused.update(x, real=False)
        eager.update(x, real=False)
    # ONE compile per static `real` flag across 3 ragged shapes each
    assert len(handle._cache) == 2
    assert not handle._eager_names  # nobody fell back eagerly
    rf = {k: np.asarray(v) for k, v in fused.compute().items()}
    re_ = {k: np.asarray(v) for k, v in eager.compute().items()}
    for k in re_:
        np.testing.assert_array_equal(rf[k], re_[k])
    for s in ("real_feat_sum", "real_outer_sum", "real_count", "fake_feat_sum", "fake_outer_sum", "fake_count"):
        assert jnp.array_equal(
            getattr(fused["FrechetInceptionDistance"], s), getattr(eager["FrechetInceptionDistance"], s)
        ), s
    for s in ("prob_sum", "plogp_sum", "split_count"):
        assert jnp.array_equal(getattr(fused["InceptionScore"], s), getattr(eager["InceptionScore"], s)), s


def test_fid_is_async_ingest_bit_parity():
    from metrics_tpu import MetricCollection

    d = 8
    mk = lambda: MetricCollection(
        [
            FrechetInceptionDistance(feature=_identity_extractor, feature_dim=d),
            InceptionScore(feature=_identity_extractor, num_classes=d, splits=3),
        ]
    )
    a, b = mk(), mk()
    a.compile_update_async(buckets=[8])
    rng = np.random.RandomState(25)
    for x in _int_feature_batches(rng, (3, 5, 7), d):
        a.update_async(x, real=True)
        b.update(x, real=True)
    for x in _int_feature_batches(rng, (4, 6, 2), d):
        a.update_async(x, real=False)
        b.update(x, real=False)
    ra = {k: np.asarray(v) for k, v in a.compute().items()}
    rb = {k: np.asarray(v) for k, v in b.compute().items()}
    for k in rb:
        np.testing.assert_array_equal(ra[k], rb[k])


def test_fid_mesh_merge_round_equals_host_fold():
    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    from metrics_tpu.parallel.distributed import sync_pytree_in_mesh
    from metrics_tpu.utils.compat import shard_map

    d = 6
    rng = np.random.RandomState(26)
    states, streams = [], []
    for r in range(8):
        m = FrechetInceptionDistance(feature=_identity_extractor, feature_dim=d)
        real = rng.randint(0, 8, (5, d)).astype(np.float32)
        fake = rng.randint(0, 8, (4, d)).astype(np.float32)
        m.update(jnp.asarray(real), real=True)
        m.update(jnp.asarray(fake), real=False)
        states.append({k: jnp.asarray(getattr(m, k)) for k in m._defaults})
        streams.append((real, fake))
    template = FrechetInceptionDistance(feature=_identity_extractor, feature_dim=d)
    reductions = template.state_reductions()
    stacked = {k: jnp.stack([s[k] for s in states]) for k in states[0]}
    mesh = Mesh(np.array(jax.devices()[:8]), ("rank",))

    def body(st):
        return sync_pytree_in_mesh({k: v[0] for k, v in st.items()}, reductions, "rank")

    synced = jax.jit(shard_map(body, mesh=mesh, in_specs=(P("rank"),), out_specs=P()))(stacked)
    # integer features: the cross-rank sums are exact, so the mesh round
    # reproduces the single-stream union metric BITWISE, leaf for leaf
    union = FrechetInceptionDistance(feature=_identity_extractor, feature_dim=d)
    for real, fake in streams:
        union.update(jnp.asarray(real), real=True)
        union.update(jnp.asarray(fake), real=False)
    for k in synced:
        assert jnp.array_equal(synced[k], getattr(union, k)), k
    assert float(union.compute_state(synced)) == float(union.compute())


def test_is_mesh_merge_round_equals_host_fold():
    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    from metrics_tpu.parallel.distributed import sync_pytree_in_mesh
    from metrics_tpu.utils.compat import shard_map

    d = 6
    rng = np.random.RandomState(27)
    states = []
    for r in range(8):
        m = InceptionScore(feature=_identity_extractor, num_classes=d, splits=3)
        m.update(jnp.asarray(rng.randint(0, 6, (5, d)).astype(np.float32)))
        states.append({k: jnp.asarray(getattr(m, k)) for k in m._defaults})
    template = InceptionScore(feature=_identity_extractor, num_classes=d, splits=3)
    reductions = template.state_reductions()
    stacked = {k: jnp.stack([s[k] for s in states]) for k in states[0]}
    mesh = Mesh(np.array(jax.devices()[:8]), ("rank",))

    def body(st):
        return sync_pytree_in_mesh({k: v[0] for k, v in st.items()}, reductions, "rank")

    synced = jax.jit(shard_map(body, mesh=mesh, in_specs=(P("rank"),), out_specs=P()))(stacked)
    for k in synced:
        assert jnp.array_equal(synced[k], reductions[k](stacked[k])), k
    mean, std = template.compute_state(synced)
    assert np.isfinite(float(mean)) and np.isfinite(float(std))
