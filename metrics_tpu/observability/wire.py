"""Versioned wire format for fleet snapshots: metric-state pytrees and
telemetry payloads as self-describing, dtype-stable byte blobs.

ROADMAP item 3's transport layer. Every metric state in this repo is a
CRDT-style mergeable value (sum/max/min reducers, the sketch
init/insert/merge contract, ``merge_payloads`` identity semantics), which
means N serving processes can each serialize their state, ship the bytes
anywhere, and a collector can fold them back into the single-job answer.
This module is the serialization half of that story; the fold half lives
in :mod:`metrics_tpu.observability.collector`.

Design constraints, in order:

* **Dtype-stable**: array leaves round-trip bit-for-bit. Each leaf carries
  its numpy dtype string (normalized little-endian) plus the raw buffer
  base64-encoded — JSON numbers would silently promote int64 counters to
  doubles and round float32 state, so raw bytes are the only encoding that
  keeps the collector fold *bit-identical* to the single-job accumulation.
* **Schema-versioned**: every snapshot leads with a magic string and a
  schema version; a collector refuses (counts, never crashes on) bytes
  from a future schema instead of misreading them.
* **Manifest-keyed**: the header carries a fingerprint of the committed
  fusibility manifest (the repo's machine description of every metric's
  state layout and reducers) plus a structural key of the published
  states (class path + per-leaf name/dtype/shape signatures). Publisher/
  collector version AND layout skew is detected *before* a fold can
  silently mis-merge.
* **Provenance-stamped**: host id, process index, publisher id, a
  monotonic per-publisher sequence number, and the wall clock — the
  fields the collector's dedup (exactly-once per ``(publisher, seq)``),
  late-window watermark, and per-publisher liveness tracking key on.
* **Transport-agnostic**: a snapshot is ``bytes``. The in-tree transport
  is a directory queue of atomic files (:class:`~metrics_tpu.
  observability.collector.SnapshotSink`), but nothing here assumes it.

Two snapshot **modes** cover the two publishing disciplines:

* ``"state"`` (default) — the publisher ships its *cumulative* state
  every tick; per publisher the collector keeps the newest sequence
  number and the cross-publisher fold merges one state per publisher
  (exactly :func:`~metrics_tpu.observability.aggregate_across_hosts`'s
  semantics, with files instead of a collective).
* ``"delta"`` — the publisher resets after each publish, so every
  snapshot is a disjoint increment and the collector folds *all* of them
  (in sequence order per publisher) — the shape a publisher uses when its
  own memory must stay bounded across an unbounded run.

See docs/fleet_collector.md for the byte-level schema reference.
"""
from __future__ import annotations

import base64
import hashlib
import json
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "WIRE_MAGIC",
    "WIRE_SCHEMA_VERSION",
    "Snapshot",
    "WireError",
    "decode_snapshot",
    "encode_snapshot",
    "manifest_fingerprint",
    "members_of",
    "snapshot_states",
    "states_key",
]

#: leading magic every snapshot blob starts with (inside the JSON header)
WIRE_MAGIC = "metrics-tpu-snapshot"

#: current wire schema. Decoders accept any version <= this and refuse
#: newer ones — an old collector must never misread a future layout.
#: v2 adds the OPTIONAL ``span`` header field (the publisher's active
#: trace-span context, for cross-process trace stitching); v1 snapshots
#: decode unchanged with ``span=None``.
WIRE_SCHEMA_VERSION = 2

#: accepted snapshot modes (see module docstring)
MODES = ("state", "delta")


class WireError(ValueError):
    """Raised on undecodable/foreign/future-schema snapshot bytes. The
    collector catches it per snapshot and counts a ``fold_error`` instead
    of dying — one corrupt file must not take down the fleet view."""


# ---------------------------------------------------------------------------
# leaf codec (dtype-stable)
# ---------------------------------------------------------------------------

def _encode_leaf(value: Any) -> Any:
    """One state leaf -> JSON-safe form. Arrays keep dtype + raw bytes
    (bit-exact); Python scalars (the eager auto-count fast path leaves an
    int behind) pass through as JSON numbers; list states (cat
    accumulators) encode element-wise."""
    if isinstance(value, (int, float)):
        return value
    if isinstance(value, list):
        return {"__list__": [_encode_leaf(v) for v in value]}
    import numpy as np

    arr = np.asarray(value)
    # normalize to little-endian so the wire bytes mean the same thing on
    # every host ('|' = byte-order-free dtypes like uint8 stay as-is)
    if arr.dtype.byteorder == ">":
        arr = arr.astype(arr.dtype.newbyteorder("<"))
    return {
        "__arr__": {
            "dtype": arr.dtype.str,
            "shape": list(arr.shape),
            "data": base64.b64encode(np.ascontiguousarray(arr).tobytes()).decode("ascii"),
        }
    }


def _decode_leaf(value: Any) -> Any:
    if isinstance(value, dict) and "__list__" in value:
        return [_decode_leaf(v) for v in value["__list__"]]
    if isinstance(value, dict) and "__arr__" in value:
        import numpy as np

        spec = value["__arr__"]
        try:
            raw = base64.b64decode(spec["data"].encode("ascii"), validate=True)
            arr = np.frombuffer(raw, dtype=np.dtype(spec["dtype"]))
            return arr.reshape([int(d) for d in spec["shape"]]).copy()
        except (KeyError, ValueError, TypeError) as err:
            raise WireError(f"corrupt array leaf: {err!r}") from err
    return value


# ---------------------------------------------------------------------------
# states helpers
# ---------------------------------------------------------------------------

def members_of(obj: Any) -> Dict[str, Any]:
    """The canonical ``{metric name: metric}`` member map of a template —
    a :class:`~metrics_tpu.collections.MetricCollection` keys members by
    their collection names, a bare metric keys its one entry by its class
    name. THE single source of the member enumeration: the snapshot shape
    (:func:`snapshot_states`), the layout key (:func:`states_key`), and
    the collector's fold all derive from this one helper, so they cannot
    drift apart."""
    if hasattr(obj, "items") and hasattr(obj, "compile_update"):  # MetricCollection
        return dict(obj.items(keep_base=True))
    return {type(obj).__name__: obj}


def snapshot_states(obj: Any) -> Dict[str, Dict[str, Any]]:
    """Snapshot a metric's (or collection's) current states in the wire's
    canonical ``{metric name: {state name: leaf}}`` shape (member keying
    per :func:`members_of`). Leaves are the live state values (arrays /
    eager-int counters / cat lists) — callers publishing ``"delta"``-mode
    snapshots reset the metric right after snapshotting."""
    return {name: _metric_states(m) for name, m in members_of(obj).items()}


def _metric_states(metric: Any) -> Dict[str, Any]:
    return {name: getattr(metric, name) for name in metric._defaults}


def _leaf_key(value: Any) -> str:
    """One leaf's structural signature for :func:`states_key`.

    Cat-list states key as ``"list"`` (their shape is data, not layout)
    and SCALAR leaves as bare ``"int"``/``"float"`` — the eager counter
    fast path leaves a Python int where another publisher holds an int32
    array, and that flip-flop must not read as layout skew. Arrays with
    real axes key dtype + shape: config-determined layouts (bin counts,
    class axes, sketch capacities) are exactly the skew that would
    otherwise poison a fold with a broadcast error."""
    if isinstance(value, list):
        return "list"
    if isinstance(value, bool) or isinstance(value, int):
        return "int"
    if isinstance(value, float):
        return "float"
    import numpy as np

    arr = np.asarray(value)
    if arr.ndim == 0:
        return "int" if arr.dtype.kind in "biu" else "float"
    return f"{arr.dtype.str}{list(arr.shape)}"


def states_key(obj: Any) -> Dict[str, Any]:
    """Structural key of a template's states: class path plus each leaf's
    name and structural signature (dtype + shape for non-scalar arrays —
    see :func:`_leaf_key`). Rides the snapshot header so a collector can
    refuse (count a ``fold_error`` for) a publisher whose metric layout
    disagrees with the collector template *before* any leaf is folded —
    including same-class config skew that changes a state's shape (bin
    counts, class axes, sketch capacities). Same-shape config skew (e.g.
    two scalar-state metrics constructed differently) is structurally
    invisible; the manifest fingerprint plus deployment discipline own
    that case."""
    def one(metric: Any) -> Dict[str, Any]:
        return {
            "class": f"{type(metric).__module__}.{type(metric).__name__}",
            "states": {
                name: _leaf_key(getattr(metric, name)) for name in sorted(metric._defaults)
            },
        }

    return {name: one(m) for name, m in members_of(obj).items()}


_MANIFEST_FP_CACHE: Optional[str] = None


def manifest_fingerprint() -> str:
    """Short sha256 fingerprint of the committed analyzer manifests — the
    fusibility manifest (every metric's state layout and reducers) plus,
    when present, the layout manifest (per-leaf shard axis and reshard
    recipe) — so two builds with the same fingerprint serialize the same
    state schemas AND agree on how each leaf reshards. ``""`` when no
    fusibility manifest is present (installed package without the
    scripts/ tree); collectors treat empty as "unknown, fold anyway" and
    a *mismatching* non-empty pair as skew. Cached for the process
    lifetime: the collector consults it per ingested snapshot, and
    re-hashing the manifest files at thousands of snapshots/s would
    dominate the fold."""
    global _MANIFEST_FP_CACHE
    if _MANIFEST_FP_CACHE is not None:
        return _MANIFEST_FP_CACHE
    try:
        from metrics_tpu.analysis.manifest import default_manifest_path

        data = default_manifest_path().read_bytes()
        try:
            from metrics_tpu.analysis.layout import default_layout_manifest_path

            layout = default_layout_manifest_path().read_bytes()
        except Exception:  # noqa: BLE001 — pre-layout checkouts stay readable
            layout = b""
        _MANIFEST_FP_CACHE = hashlib.sha256(data + b"\x00" + layout).hexdigest()[:16]
    except Exception:  # noqa: BLE001 — absent manifest is a legal deployment
        _MANIFEST_FP_CACHE = ""
    return _MANIFEST_FP_CACHE


# ---------------------------------------------------------------------------
# snapshot codec
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Snapshot:
    """One decoded fleet snapshot: provenance header + payloads.

    ``telemetry`` is a LIST of per-process counter payloads (the
    :func:`~metrics_tpu.observability.counter_payload` shape) — a leaf
    publisher ships a one-element list, a mid-tier collector re-publishes
    the concatenation for its whole subtree, and the top-level fold is
    :func:`~metrics_tpu.observability.merge_payloads` over every payload
    in the tree — identical semantics to ``aggregate_across_hosts``."""

    publisher: str
    seq: int
    t: float
    host: str = ""
    process: int = 0
    mode: str = "state"
    tier: str = "leaf"
    schema: int = WIRE_SCHEMA_VERSION
    manifest_hash: str = ""
    states: Optional[Dict[str, Dict[str, Any]]] = None
    states_key: Optional[Dict[str, Any]] = None
    telemetry: List[Dict[str, Any]] = field(default_factory=list)
    #: publisher's active trace-span context at publish time (schema v2+):
    #: ``{"span_id": int, "parent_id": int|None, "trace": [span events]}``.
    #: None on v1 snapshots and span-less publishers — folds are unaffected.
    span: Optional[Dict[str, Any]] = None

    @property
    def key(self) -> Tuple[str, int]:
        """The dedup identity: ``(publisher, seq)``."""
        return (self.publisher, self.seq)


def encode_snapshot(
    *,
    publisher: str,
    seq: int,
    t: Optional[float] = None,
    host: str = "",
    process: int = 0,
    mode: str = "state",
    tier: str = "leaf",
    states: Optional[Dict[str, Dict[str, Any]]] = None,
    states_template: Optional[Any] = None,
    telemetry: Optional[Any] = None,
    manifest_hash: Optional[str] = None,
    span: Optional[Dict[str, Any]] = None,
) -> bytes:
    """Serialize one snapshot to wire bytes (UTF-8 JSON, array leaves as
    base64 raw buffers).

    ``states`` is the canonical ``{metric: {state: leaf}}`` dict (use
    :func:`snapshot_states`); ``states_template`` (the metric/collection
    the states came from) additionally embeds the structural
    :func:`states_key` so the collector can verify layout agreement.
    ``telemetry`` is one counter payload or a list of them. ``t`` defaults
    to the wall clock; ``manifest_hash`` to the live
    :func:`manifest_fingerprint`. ``span`` (schema v2) optionally carries
    the publisher's active trace-span context so the collector can stitch
    cross-process traces (see :func:`~metrics_tpu.observability.trace.
    current_span_context`)."""
    if mode not in MODES:
        raise ValueError(f"mode must be one of {MODES}, got {mode!r}")
    if not publisher:
        raise ValueError("publisher id must be non-empty")
    if seq < 0:
        raise ValueError(f"seq must be non-negative, got {seq}")
    if telemetry is None:
        payloads: List[Dict[str, Any]] = []
    elif isinstance(telemetry, dict):
        payloads = [telemetry]
    else:
        payloads = list(telemetry)
    doc: Dict[str, Any] = {
        "magic": WIRE_MAGIC,
        "schema": WIRE_SCHEMA_VERSION,
        "publisher": publisher,
        "seq": int(seq),
        "t": float(time.time() if t is None else t),
        "host": host,
        "process": int(process),
        "mode": mode,
        "tier": tier,
        "manifest_hash": manifest_fingerprint() if manifest_hash is None else manifest_hash,
    }
    if states is not None:
        doc["states"] = {
            metric: {name: _encode_leaf(leaf) for name, leaf in tree.items()}
            for metric, tree in states.items()
        }
        if states_template is not None:
            doc["states_key"] = states_key(states_template)
    if payloads:
        doc["telemetry"] = payloads
    if span is not None:
        doc["span"] = span
    return json.dumps(doc, sort_keys=True).encode("utf-8")


def decode_snapshot(data: bytes) -> Snapshot:
    """Parse wire bytes back into a :class:`Snapshot`. Raises
    :class:`WireError` on anything that is not a complete snapshot this
    build can read (truncated JSON, foreign magic, a FUTURE schema
    version, corrupt array leaves) — the collector's per-snapshot
    ``fold_error`` boundary."""
    try:
        doc = json.loads(data.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as err:
        raise WireError(f"undecodable snapshot bytes: {err!r}") from err
    if not isinstance(doc, dict) or doc.get("magic") != WIRE_MAGIC:
        raise WireError("not a metrics-tpu snapshot (bad magic)")
    schema = doc.get("schema")
    if not isinstance(schema, int) or schema < 1:
        raise WireError(f"bad schema version {schema!r}")
    if schema > WIRE_SCHEMA_VERSION:
        raise WireError(
            f"snapshot schema v{schema} is newer than this build's"
            f" v{WIRE_SCHEMA_VERSION}; upgrade the collector"
        )
    try:
        publisher = doc["publisher"]
        seq = int(doc["seq"])
        t = float(doc["t"])
    except (KeyError, TypeError, ValueError) as err:
        raise WireError(f"snapshot header incomplete: {err!r}") from err
    states = doc.get("states")
    if states is not None:
        states = {
            metric: {name: _decode_leaf(leaf) for name, leaf in tree.items()}
            for metric, tree in states.items()
        }
    telemetry = doc.get("telemetry", [])
    if not isinstance(telemetry, list):
        raise WireError("telemetry payload must be a list of counter payloads")
    mode = doc.get("mode", "state")
    if mode not in MODES:
        raise WireError(f"unknown snapshot mode {mode!r}")
    return Snapshot(
        publisher=publisher,
        seq=seq,
        t=t,
        host=doc.get("host", ""),
        process=int(doc.get("process", 0)),
        mode=mode,
        tier=doc.get("tier", "leaf"),
        schema=schema,
        manifest_hash=doc.get("manifest_hash", ""),
        states=states,
        states_key=doc.get("states_key"),
        telemetry=telemetry,
        span=doc.get("span") if isinstance(doc.get("span"), dict) else None,
    )
