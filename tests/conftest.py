"""Test session configuration: force CPU with 8 virtual devices so mesh /
collective tests run without TPU hardware (SURVEY.md §4 implication).

A pytest plugin (jaxtyping) imports jax before this conftest runs, so the
platform must be set via ``jax.config.update`` (still possible until the
backend is first queried), and the XLA flag via the environment (read at
backend initialization).
"""
import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

assert jax.device_count() >= 8, f"expected >=8 virtual devices, got {jax.device_count()}"
