"""Modular ShortTimeObjectiveIntelligibility.

Behavior parity with /root/reference/torchmetrics/audio/stoi.py:25-126
(sum/count states averaging per-utterance STOI); the DSP itself is the
JAX implementation in functional/audio/stoi.py (the reference wraps pystoi).
"""
from typing import Any

import jax
import jax.numpy as jnp

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.audio.stoi import short_time_objective_intelligibility

Array = jax.Array


class ShortTimeObjectiveIntelligibility(Metric):
    """Average STOI over accumulated utterances.

    Args:
        fs: sampling frequency of the input waveforms.
        extended: use extended STOI (eSTOI).
    """

    is_differentiable = False
    higher_is_better = True
    __jit_unsafe__ = True  # silent-frame removal is data-dependent host work

    def __init__(self, fs: int, extended: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not (isinstance(fs, int) and fs > 0):
            raise ValueError(f"Expected argument `fs` to be a positive int, but got {fs}")
        self.fs = fs
        self.extended = extended

        self.add_state("sum_stoi", default=jnp.asarray(0.0, jnp.float32), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0, jnp.int32), dist_reduce_fx="sum")

    def _update(self, preds: Array, target: Array) -> None:
        stoi_batch = short_time_objective_intelligibility(preds, target, self.fs, self.extended).reshape(-1)
        self.sum_stoi = self.sum_stoi + jnp.sum(stoi_batch)
        self.total = self.total + stoi_batch.shape[0]

    def _compute(self) -> Array:
        return self.sum_stoi / self.total
