"""Modular ExtendedEditDistance.

Behavior parity with /root/reference/torchmetrics/text/eed.py:24-131 (list
state of sentence scores, gathered across ranks and averaged).
"""
from typing import Any, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.text.eed import _eed_compute, _eed_update

Array = jax.Array


class ExtendedEditDistance(Metric):
    """Corpus Extended Edit Distance (average of sentence-level scores).

    Example:
        >>> preds = ["this is the prediction", "here is an other sample"]
        >>> target = ["this is the reference", "here is another one"]
        >>> metric = ExtendedEditDistance()
        >>> float(metric(preds, target))  # doctest: +ELLIPSIS
        0.3077...
    """

    is_differentiable = False
    higher_is_better = False
    __jit_unsafe__ = True  # update consumes Python strings

    def __init__(
        self,
        language: str = "en",
        return_sentence_level_score: bool = False,
        alpha: float = 2.0,
        rho: float = 0.3,
        deletion: float = 0.2,
        insertion: float = 1.0,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if language not in ("en", "ja"):
            raise ValueError(f"Expected argument `language` to either be `en` or `ja` but got {language}")
        self.language = language
        self.return_sentence_level_score = return_sentence_level_score
        for param_name, param in zip(["alpha", "rho", "deletion", "insertion"], [alpha, rho, deletion, insertion]):
            if not isinstance(param, float) or param < 0:
                raise ValueError(f"Parameter `{param_name}` is expected to be a non-negative float.")
        self.alpha = alpha
        self.rho = rho
        self.deletion = deletion
        self.insertion = insertion

        self.add_state("sentence_eed", [], dist_reduce_fx="cat")

    def _update(
        self, preds: Union[str, Sequence[str]], target: Sequence[Union[str, Sequence[str]]]
    ) -> None:
        scores = _eed_update(
            preds, target, self.language, self.alpha, self.rho, self.deletion, self.insertion
        )
        self.sentence_eed.extend(jnp.asarray(s, jnp.float32)[None] for s in scores)

    def _compute(self) -> Union[Array, Tuple[Array, Array]]:
        if not self.sentence_eed:
            average = jnp.asarray(0.0, jnp.float32)
            scores = jnp.zeros((0,), jnp.float32)
        else:
            scores = jnp.concatenate(self.sentence_eed)
            average = jnp.mean(scores)
        if self.return_sentence_level_score:
            return average, scores
        return average
