"""Structured trace spans: nested, context-local timing regions emitted
through the :class:`MetricRecorder` event stream, plus a Chrome/Perfetto
trace-event exporter.

PR 1's recorder answers *what ran and for how long*, but its rows are flat:
an ``update`` inside a ``MetricCollection.forward`` inside a distributed
sync is three unrelated events. Spans restore the nesting — every span has
an id and a parent id maintained on a ``contextvars`` stack (so concurrent
threads and async tasks each see their own ancestry), and every OTHER event
recorded while a span is active carries that span's id, re-attaching the
flat rows to the tree.

The runtime opens spans for you: ``Metric.update/compute/forward/sync``,
``MetricCollection.update/forward/compute``, and the transport hooks
(``gather_all_arrays`` / ``sync_in_mesh`` / ``all_gather_replicated``) are
spans whenever the default recorder is enabled. User code adds its own::

    from metrics_tpu.observability import get_recorder, span
    get_recorder().enable()
    with span("eval_epoch", epoch=3):
        ...  # metric traffic nests under this span

Zero-overhead contract: entering a span while the recorder is disabled
costs one attribute check; no ids are drawn, no clocks read, nothing
recorded.

``export_perfetto(path)`` renders the span log as trace-event JSON that
``chrome://tracing`` / https://ui.perfetto.dev load directly.
"""
from __future__ import annotations

import itertools
import json
import threading
import time
from typing import Any, Dict, List, Optional

from metrics_tpu.observability.recorder import _DEFAULT_RECORDER, _SPAN_STACK, current_span_id
from metrics_tpu.utils.prints import _process_index

__all__ = ["span", "current_span_id", "current_span_context", "export_perfetto"]

#: process-wide monotonically increasing span ids; ``itertools.count`` is
#: atomic under the GIL, so concurrent threads never share an id
_SPAN_IDS = itertools.count(1)


class span:
    """Context manager marking one nested timing region.

    ``with span("name", **attributes):`` records a ``span`` event on exit
    carrying ``span_id`` / ``parent_id`` / ``name`` / ``dur_ms`` / ``tid``
    plus the given JSON-safe attributes. Nestable: the parent link follows
    the ``contextvars`` ancestry, so spans opened in different threads (or
    asyncio tasks) cannot interleave each other's stacks. Each instance
    marks ONE region — use a fresh ``span(...)`` per ``with`` block (an
    instance holds per-entry state, so re-entering the same object while
    it is active would corrupt the ancestry stack; nesting distinct
    instances, including same-named ones, is the supported shape).
    """

    __slots__ = ("name", "attributes", "_recorder", "_token", "_t0", "span_id", "parent_id")

    def __init__(self, name: str, recorder: Optional[Any] = None, **attributes: Any) -> None:
        self.name = name
        self.attributes = attributes
        self._recorder = recorder
        self._token = None
        self.span_id: Optional[int] = None
        self.parent_id: Optional[int] = None

    def __enter__(self) -> "span":
        rec = self._recorder if self._recorder is not None else _DEFAULT_RECORDER
        if not rec.enabled:  # disabled spans cost this ONE check
            return self
        stack = _SPAN_STACK.get()
        self.span_id = next(_SPAN_IDS)
        self.parent_id = stack[-1] if stack else None
        self._token = _SPAN_STACK.set(stack + (self.span_id,))
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc: Any) -> None:
        if self._token is None:
            return
        dur_s = time.perf_counter() - self._t0
        _SPAN_STACK.reset(self._token)
        self._token = None
        rec = self._recorder if self._recorder is not None else _DEFAULT_RECORDER
        event: Dict[str, Any] = {
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "dur_ms": round(dur_s * 1e3, 4),
            "tid": threading.get_ident(),
        }
        if self.attributes:
            event["attributes"] = self.attributes
        if exc and exc[0] is not None:
            event["error"] = getattr(exc[0], "__name__", str(exc[0]))
        rec.record_event("span", **event)


def _resolve(recorder: Optional[Any]) -> Any:
    return recorder if recorder is not None else _DEFAULT_RECORDER


def current_span_context(recorder: Optional[Any] = None) -> Optional[Dict[str, Any]]:
    """The calling context's active span as a JSON-safe dict, or ``None``
    when the recorder is disabled or no span is open.

    This is the cross-process half of span nesting: a publisher embeds it
    in the snapshot wire header (schema v2 ``span`` field) and the fleet
    collector attaches it to the fold span it opens for that snapshot, so
    :func:`export_perfetto`'s fleet mode can draw a flow arrow from the
    publish site in one process to the fold in another. Shape::

        {"span_id": int, "parent_id": int | None, "t": wall-clock seconds}
    """
    rec = _resolve(recorder)
    if not rec.enabled:
        return None
    stack = _SPAN_STACK.get()
    if not stack:
        return None
    return {
        "span_id": stack[-1],
        "parent_id": stack[-2] if len(stack) > 1 else None,
        "t": time.time(),
    }


def export_perfetto(
    path: str, recorder: Optional[Any] = None, collector: Optional[Any] = None
) -> Optional[str]:
    """Write the recorded span log as Chrome/Perfetto trace-event JSON.

    Every ``span`` event becomes one complete ("X") trace event with
    microsecond ``ts``/``dur``; nesting renders from ts/dur containment per
    (pid, tid) track, exactly how the contextvars stack nested them.
    Duration-carrying lifecycle events (``update``/``compute``/``forward``),
    ``sync``/``compile`` rows, and the async-pipeline transitions
    (``enqueue``/``dequeue``/``flush`` — which carry the recording thread's
    id) are included too, so the Perfetto view shows the same stream the
    JSONL export does. The recorder's tid -> thread-name map is emitted as
    ``thread_name``/``process_name`` metadata, so the async worker's rows
    land on their own LABELED track (``metrics-tpu-async-update``) instead
    of interleaving with the main thread. Rank-zero gated: returns the
    path written, or ``None`` on non-zero ranks.

    **Fleet mode** — pass ``collector`` (a :class:`~metrics_tpu.
    observability.collector.FleetCollector`): the per-publisher
    publish-span contexts stored from wire-v2 snapshot headers render as
    one labeled Perfetto *process track per publisher* (publish instants),
    and each ``fleet_fold`` span's ``links`` become flow arrows from the
    publish site in the publisher's process to the fold in the
    collector's — one merged ingest-to-visible timeline across the fleet.
    """
    if _process_index() != 0:
        return None
    rec = _resolve(recorder)
    if collector is not None and recorder is None and getattr(collector, "_recorder", None) is not None:
        rec = collector._recorder
    pid = _process_index()
    all_events = rec.events()
    # spans carry the real thread id; other rows only carry the enclosing
    # span's id — resolve them onto the same track so ts/dur containment
    # (Perfetto's nesting rule is per (pid, tid)) actually nests them
    span_tid = {
        ev["span_id"]: ev.get("tid", 0) for ev in all_events if ev.get("type") == "span"
    }
    trace_events: List[Dict[str, Any]] = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": pid,
            "tid": 0,
            "args": {"name": f"metrics_tpu rank {pid} ({rec.name})"},
        }
    ]
    for tid, tname in sorted(rec.thread_names().items()):
        trace_events.append(
            {"name": "thread_name", "ph": "M", "pid": pid, "tid": int(tid), "args": {"name": tname}}
        )
    for ev in all_events:
        etype = ev.get("type")
        dur_ms = ev.get("dur_ms")
        if etype == "span":
            name = ev.get("name", "span")
        elif etype in ("update", "compute", "forward"):
            name = f"{ev.get('metric', '?')}.{etype}"
        elif etype in ("sync", "metric_sync", "compile"):
            name = f"{etype}:{ev.get('source') or ev.get('metric') or ev.get('entry') or '?'}"
            if dur_ms is None:
                dur_ms = ev.get("compile_ms", 0.0)
        elif etype in ("enqueue", "dequeue", "flush"):
            # async-pipeline transitions: stamped with the recording
            # thread's id, so dequeues render on the worker's labeled track
            name = f"async.{etype}"
            if ev.get("batch_index") is not None:
                name = f"{name}[{ev['batch_index']}]"
        else:
            continue
        dur_ms = float(dur_ms or 0.0)
        # events carry their END time relative to recorder start ("t");
        # the trace event starts dur earlier
        end_us = float(ev.get("t", 0.0)) * 1e6
        args = {
            k: v
            for k, v in ev.items()
            if k not in ("type", "t", "dur_ms", "tid", "name") and _json_safe(v)
        }
        trace_events.append(
            {
                "name": name,
                "cat": etype,
                "ph": "X",
                "ts": round(max(end_us - dur_ms * 1e3, 0.0), 3),
                "dur": round(dur_ms * 1e3, 3),
                "pid": pid,
                "tid": int(ev.get("tid") or span_tid.get(ev.get("span_id"), 0)),
                "args": args,
            }
        )
    if collector is not None:
        trace_events.extend(_fleet_trace_events(collector, rec, pid, all_events))
    doc = {
        "traceEvents": trace_events,
        "displayTimeUnit": "ms",
        "otherData": {"recorder": rec.name},
    }
    from metrics_tpu.observability.exporters import _atomic_write

    _atomic_write(path, json.dumps(doc))
    return path


def _fleet_trace_events(
    collector: Any, rec: Any, collector_pid: int, all_events: List[Dict[str, Any]]
) -> List[Dict[str, Any]]:
    """Per-publisher tracks + publish->fold flow arrows (fleet mode).

    Publisher span contexts carry WALL-clock publish times; the collector
    recorder's rows are relative to its start anchor (``rec._t0``), so
    publisher instants are re-anchored onto the same timeline. Flows pair
    by ``(publisher, seq)``: the ``s`` end sits on the publish instant in
    the publisher's process, the ``f`` end on the collector's matching
    ``fleet_fold`` span."""
    t0_wall = float(getattr(rec, "_t0", 0.0))
    out: List[Dict[str, Any]] = []
    spans_by_pub = collector.publisher_spans()
    # stable small pids per publisher, offset clear of real process indices
    pub_pid = {name: 1000 + i for i, name in enumerate(sorted(spans_by_pub))}
    flow_ids = itertools.count(1_000_000)
    # (publisher, seq) -> flow id, created at the publish instant
    flow_of: Dict[Any, int] = {}
    for name, ctxs in sorted(spans_by_pub.items()):
        ppid = pub_pid[name]
        out.append(
            {"name": "process_name", "ph": "M", "pid": ppid, "tid": 0,
             "args": {"name": f"publisher {name}"}}
        )
        for ctx in ctxs:
            ts = round(max((float(ctx.get("t", t0_wall)) - t0_wall) * 1e6, 0.0), 3)
            seq = ctx.get("seq")
            fid = next(flow_ids)
            flow_of[(name, seq)] = fid
            out.append(
                {"name": f"publish[{seq}]", "cat": "fleet", "ph": "i", "s": "p",
                 "ts": ts, "pid": ppid, "tid": 0,
                 "args": {k: v for k, v in ctx.items() if _json_safe(v)}}
            )
            out.append(
                {"name": "publish->fold", "cat": "fleet", "ph": "s", "id": fid,
                 "ts": ts, "pid": ppid, "tid": 0}
            )
    # bind each fold span's links to the publish flows
    for ev in all_events:
        if ev.get("type") != "span" or ev.get("name") != "fleet_fold":
            continue
        links = (ev.get("attributes") or {}).get("links") or []
        dur_ms = float(ev.get("dur_ms") or 0.0)
        end_us = float(ev.get("t", 0.0)) * 1e6
        ts = round(max(end_us - dur_ms * 1e3, 0.0), 3)
        tid = int(ev.get("tid") or 0)
        for link in links:
            fid = flow_of.get((link.get("publisher"), link.get("seq")))
            if fid is None:
                continue
            out.append(
                {"name": "publish->fold", "cat": "fleet", "ph": "f", "bp": "e",
                 "id": fid, "ts": ts, "pid": collector_pid, "tid": tid}
            )
    return out


def _json_safe(value: Any) -> bool:
    try:
        json.dumps(value)
        return True
    except (TypeError, ValueError):
        return False
