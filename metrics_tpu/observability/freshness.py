"""Ingest-to-visible freshness stamps for the read path.

Production asks one question of every dashboard number: *how old is it?*
The write path already carries every ingredient of the answer —

* the async update pipeline stamps each accepted batch with its accept
  wall time (``core/pipeline.py`` queue items),
* the windowed ring encodes a bucket clock (``windowed/metric.py``),
* fleet snapshots carry provenance ``t``/``seq`` in the wire header
  (``observability/wire.py``) and the collector keeps a watermark,

but nothing composed them into a per-read answer. A
:class:`FreshnessStamp` is that composition: a tiny immutable record of
the wall-clock span of everything that contributed to a read
(``min_event_t``/``max_event_t``), plus the three staleness components a
read can still be missing — data accepted into the async queue but not
yet applied (``async_age_s``), the age span of the ring buckets a
windowed fold covered (``ring_span_s``), and how far the fleet watermark
trails the collector's clock (``watermark_lag_s``).

Stamps form a commutative monoid under :meth:`FreshnessStamp.merge`
(min over ``min_event_t``, max over everything else, with the empty
:data:`IDENTITY` stamp as the identity element) — exactly the shape the
fleet aggregation layer (``observability/aggregate.py``) needs to fold
them across heterogeneous payloads with the PR 13 ``.get``-with-default
convention: a payload that predates the freshness family merges as
identity instead of poisoning the fold.

The module is deliberately jax-free and import-light (stdlib only), like
``recorder.py``: stamps are built on read paths that must stay cheap, and
the recorder duck-types them (``record_read(freshness=stamp)``) so no
import cycle forms.
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Dict, Iterable, Optional

__all__ = ["FreshnessStamp", "IDENTITY", "merge_stamps", "stamp_from_payload"]


@dataclass(frozen=True)
class FreshnessStamp:
    """The freshness of one read: when the data it reflects was ingested,
    and what visible-latency components still apply.

    ``min_event_t`` / ``max_event_t`` are wall-clock (``time.time``)
    timestamps of the oldest / newest contribution reflected in the read's
    value; ``None`` means "no contribution observed" (the merge identity).
    ``async_age_s`` is the age of the oldest batch accepted into an async
    update queue but not yet applied — data the read could NOT see yet.
    ``ring_span_s`` is the wall-clock span of the ring buckets a windowed
    fold covered (how far back the window reaches). ``watermark_lag_s``
    is how far the fleet watermark trails the collector clock at a fleet
    read — the late-snapshot horizon.
    """

    min_event_t: Optional[float] = None
    max_event_t: Optional[float] = None
    async_age_s: float = 0.0
    ring_span_s: float = 0.0
    watermark_lag_s: float = 0.0

    def merge(self, other: "FreshnessStamp") -> "FreshnessStamp":
        """Commutative monoid fold: min of the min-times, max of the
        max-times and of every staleness component. Merging with
        :data:`IDENTITY` returns a stamp equal to ``self``."""
        lo_a, lo_b = self.min_event_t, other.min_event_t
        hi_a, hi_b = self.max_event_t, other.max_event_t
        return FreshnessStamp(
            min_event_t=lo_a if lo_b is None else (lo_b if lo_a is None else min(lo_a, lo_b)),
            max_event_t=hi_a if hi_b is None else (hi_b if hi_a is None else max(hi_a, hi_b)),
            async_age_s=max(self.async_age_s, other.async_age_s),
            ring_span_s=max(self.ring_span_s, other.ring_span_s),
            watermark_lag_s=max(self.watermark_lag_s, other.watermark_lag_s),
        )

    # ------------------------------------------------------------------
    # derived staleness
    # ------------------------------------------------------------------
    def visible_age_s(self, now: Optional[float] = None) -> float:
        """Age of the NEWEST data the read reflects — "how old is the
        number on this dashboard". 0.0 for an empty stamp (nothing
        ingested yet means nothing is stale yet)."""
        if self.max_event_t is None:
            return 0.0
        return max(0.0, (time.time() if now is None else now) - self.max_event_t)

    def staleness_s(self, now: Optional[float] = None) -> float:
        """The end-to-end ingest-to-visible staleness bound: the dashboard
        age plus whatever is accepted-but-not-yet-visible (async in-flight
        age) and the fleet late-snapshot horizon. This is the quantity the
        ``freshness_slo`` alarm bounds at p95."""
        return self.visible_age_s(now) + max(self.async_age_s, self.watermark_lag_s)

    @property
    def is_identity(self) -> bool:
        return (
            self.min_event_t is None
            and self.max_event_t is None
            and not (self.async_age_s or self.ring_span_s or self.watermark_lag_s)
        )

    # ------------------------------------------------------------------
    # payload round-trip (fleet aggregation / wire)
    # ------------------------------------------------------------------
    def to_payload(self) -> Dict[str, Any]:
        """JSON-safe dict with the same keys the aggregate layer's
        freshness family uses; ``None`` min/max survive as nulls."""
        return {
            "min_event_t": self.min_event_t,
            "max_event_t": self.max_event_t,
            "async_age_s": self.async_age_s,
            "ring_span_s": self.ring_span_s,
            "watermark_lag_s": self.watermark_lag_s,
        }

    @staticmethod
    def from_payload(payload: Optional[Dict[str, Any]]) -> "FreshnessStamp":
        """Inverse of :meth:`to_payload`; a missing/empty payload is the
        identity stamp (the heterogeneous-fleet convention)."""
        if not payload:
            return IDENTITY
        lo = payload.get("min_event_t")
        hi = payload.get("max_event_t")
        return FreshnessStamp(
            min_event_t=float(lo) if lo is not None else None,
            max_event_t=float(hi) if hi is not None else None,
            async_age_s=float(payload.get("async_age_s") or 0.0),
            ring_span_s=float(payload.get("ring_span_s") or 0.0),
            watermark_lag_s=float(payload.get("watermark_lag_s") or 0.0),
        )


#: the merge identity — what a contribution-free read (or a payload from a
#: publisher predating the freshness family) folds as
IDENTITY = FreshnessStamp()


def merge_stamps(stamps: Iterable[Optional[FreshnessStamp]]) -> FreshnessStamp:
    """Fold any number of stamps (``None`` entries fold as identity)."""
    out = IDENTITY
    for s in stamps:
        if s is not None:
            out = out.merge(s)
    return out


# alias used by `stamp_from_payload` re-export convention
stamp_from_payload = FreshnessStamp.from_payload
