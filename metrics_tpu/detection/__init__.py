"""Detection metrics (reference: /root/reference/torchmetrics/detection/)."""
from metrics_tpu.detection.mean_ap import MeanAveragePrecision  # noqa: F401

__all__ = ["MeanAveragePrecision"]
