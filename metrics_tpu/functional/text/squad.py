"""SQuAD v1 metric (exact match + token F1).

Behavior parity with /root/reference/torchmetrics/functional/text/squad.py:41-199
(itself the official SQuAD v1.1 evaluation recipe: lowercase, strip
punctuation and articles, whitespace-tokenize; per question take the max
score over all gold answers; report percentages).

Host-side string processing feeding scalar device states (SURVEY §2.7).
"""
import re
import string
from collections import Counter
from typing import Any, Callable, Dict, List, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.utils.prints import rank_zero_warn

Array = jax.Array

SINGLE_PRED_TYPE = Dict[str, str]
PREDS_TYPE = Union[SINGLE_PRED_TYPE, List[SINGLE_PRED_TYPE]]
SINGLE_TARGET_TYPE = Dict[str, Any]
TARGETS_TYPE = Union[SINGLE_TARGET_TYPE, List[SINGLE_TARGET_TYPE]]

_SQUAD_FORMAT = {
    "answers": {"answer_start": [1], "text": ["This is a test text"]},
    "context": "This is a test context.",
    "id": "1",
    "question": "Is this a test?",
    "title": "train test",
}

_ARTICLES = re.compile(r"\b(a|an|the)\b")
_PUNCT = set(string.punctuation)


def _normalize_text(s: str) -> str:
    """Lowercase, drop punctuation, drop articles, squeeze whitespace."""
    s = "".join(ch for ch in s.lower() if ch not in _PUNCT)
    s = _ARTICLES.sub(" ", s)
    return " ".join(s.split())


def _get_tokens(s: str) -> List[str]:
    return _normalize_text(s).split() if s else []


def _exact_match_score(prediction: str, ground_truth: str) -> float:
    return float(_normalize_text(prediction) == _normalize_text(ground_truth))


def _f1_score(prediction: str, ground_truth: str) -> float:
    pred_tokens = _get_tokens(prediction)
    target_tokens = _get_tokens(ground_truth)
    if not pred_tokens or not target_tokens:
        # no-answer convention: 1 iff both are empty
        return float(pred_tokens == target_tokens)
    overlap = sum((Counter(pred_tokens) & Counter(target_tokens)).values())
    if overlap == 0:
        return 0.0
    precision = overlap / len(pred_tokens)
    recall = overlap / len(target_tokens)
    return 2 * precision * recall / (precision + recall)


def _max_over_ground_truths(
    metric_fn: Callable[[str, str], float], prediction: str, ground_truths: List[str]
) -> float:
    return max(metric_fn(prediction, truth) for truth in ground_truths)


def _squad_input_check(preds: PREDS_TYPE, targets: TARGETS_TYPE) -> Tuple[Dict[str, str], List[dict]]:
    """Validate inputs and convert to (id -> answer, nested article format)."""
    if isinstance(preds, dict):
        preds = [preds]
    if isinstance(targets, dict):
        targets = [targets]

    for pred in preds:
        if "prediction_text" not in pred or "id" not in pred:
            raise KeyError(
                "Expected keys in a single prediction are 'prediction_text' and 'id'."
                " Please make sure that 'prediction_text' maps to the answer string and"
                " 'id' maps to the key string."
            )
    for target in targets:
        if "answers" not in target or "id" not in target:
            raise KeyError(
                "Expected keys in a single target are 'answers' and 'id'."
                " Please make sure that 'answers' maps to a `SQuAD` format dictionary and"
                f" 'id' maps to the key string.\nSQuAD Format: {_SQUAD_FORMAT}"
            )
        if "text" not in target["answers"]:
            raise KeyError(
                "Expected keys in a 'answers' are 'text'."
                f" Please make sure that 'answer' maps to a `SQuAD` format dictionary.\n"
                f"SQuAD Format: {_SQUAD_FORMAT}"
            )

    preds_dict = {pred["id"]: pred["prediction_text"] for pred in preds}
    qas = [
        {"id": tgt["id"], "answers": [{"text": txt} for txt in tgt["answers"]["text"]]}
        for tgt in targets
    ]
    return preds_dict, [{"paragraphs": [{"qas": qas}]}]


def _squad_update(preds: Dict[str, str], target: List[dict]) -> Tuple[Array, Array, Array]:
    """Sum of per-question F1 / exact-match (max over gold answers) + count."""
    f1 = 0.0
    exact_match = 0.0
    total = 0
    for article in target:
        for paragraph in article["paragraphs"]:
            for qa in paragraph["qas"]:
                total += 1
                if qa["id"] not in preds:
                    rank_zero_warn(f"Unanswered question {qa['id']} will receive score 0.")
                    continue
                ground_truths = [answer["text"] for answer in qa["answers"]]
                pred = preds[qa["id"]]
                exact_match += _max_over_ground_truths(_exact_match_score, pred, ground_truths)
                f1 += _max_over_ground_truths(_f1_score, pred, ground_truths)
    return jnp.asarray(f1, jnp.float32), jnp.asarray(exact_match, jnp.float32), jnp.asarray(total, jnp.int32)


def _squad_compute(f1: Array, exact_match: Array, total: Array) -> Dict[str, Array]:
    return {"exact_match": 100.0 * exact_match / total, "f1": 100.0 * f1 / total}


def squad(preds: PREDS_TYPE, target: TARGETS_TYPE) -> Dict[str, Array]:
    """SQuAD v1 exact-match + F1 (percent).

    Example:
        >>> preds = [{"prediction_text": "1976", "id": "56e10a3be3433e1400422b22"}]
        >>> target = [{"answers": {"answer_start": [97], "text": ["1976"]}, "id": "56e10a3be3433e1400422b22"}]
        >>> {k: float(v) for k, v in squad(preds, target).items()}
        {'exact_match': 100.0, 'f1': 100.0}
    """
    preds_dict, target_dict = _squad_input_check(preds, target)
    f1, exact_match, total = _squad_update(preds_dict, target_dict)
    return _squad_compute(f1, exact_match, total)
