"""Pairwise euclidean distance.

Behavior parity with /root/reference/torchmetrics/functional/pairwise/euclidean.py:20-85.
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.pairwise.helpers import _check_input, _reduce_distance_matrix, _zero_diagonal

Array = jax.Array


def _pairwise_euclidean_distance_update(
    x: Array, y: Optional[Array] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    x_norm = jnp.sum(x * x, axis=1, keepdims=True)
    y_norm = jnp.sum(y * y, axis=1)[None, :]
    distance = x_norm + y_norm - 2 * jnp.matmul(x, y.T, precision=jax.lax.Precision.HIGHEST)
    distance = _zero_diagonal(distance, zero_diagonal)
    return jnp.sqrt(jnp.maximum(distance, 0.0))


def pairwise_euclidean_distance(
    x: Array, y: Optional[Array] = None, reduction: Optional[str] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """Pairwise euclidean distance between rows of x (and y).

    Example:
        >>> import jax.numpy as jnp
        >>> x = jnp.array([[2., 3.], [3., 5.], [5., 8.]])
        >>> y = jnp.array([[1., 0.], [2., 1.]])
        >>> pairwise_euclidean_distance(x, y)
        Array([[3.1622777, 2.       ],
               [5.3851647, 4.1231055],
               [8.944272 , 7.615773 ]], dtype=float32)
    """
    distance = _pairwise_euclidean_distance_update(x, y, zero_diagonal)
    return _reduce_distance_matrix(distance, reduction)
