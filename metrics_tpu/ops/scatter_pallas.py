"""Pallas TPU kernel: fused bincount / segment-scatter via tiled one-hot matmul.

The index-mapped bincount ``(target * C + pred) -> counts`` is the core of
every confusion-matrix classification metric (``utils/data.py::_bincount``
via ``functional/classification/confusion_matrix.py``), and the same
reduction shape — scatter-add ``[B]``-aligned rows into ``[S]`` segments —
is the per-update cost of the ``SlicedMetric`` slice-axis scatter
(``sliced/metric.py``). XLA lowers both to a generic serial scatter; this
kernel re-expresses them as what the TPU is actually good at: a tiled
one-hot matrix product on the MXU.

One grid step owns a ``(TILE_S, D)`` output tile and streams ``TILE_B``
index rows through VMEM: the tile's one-hot membership matrix
``[TILE_B, TILE_S]`` is built on-chip from a broadcasted iota (never
materialized in HBM) and contracted against the value rows on the MXU,
accumulating into the resident output tile across the batch dimension of
the grid. Out-of-range ids (negative included) match no one-hot column and
are dropped — exactly ``jax.ops.segment_sum``'s documented semantics, which
the jnp fallback shares.

Accumulation is float32 on the MXU. Unit-weight COUNTS (bincount) are
exact while the batch stays below ``2**24`` — the route's bound — and
float payload scatters agree with the fallback within f32
summation-order rounding (callers accumulate across batches OUTSIDE the
kernel, ``old + delta``, so per-dispatch magnitudes are batch-bounded).
Integer payload scatters always take the exact jnp fallback: their
per-segment partial magnitudes are not statically bounded, and a partial
past ``2**24`` would round silently where XLA's scatter is exact.

Entry points: :func:`segment_sum_tiled` (the raw kernel wrapper),
:func:`segment_sum_dispatch` / :func:`bincount_dispatch` (registry-routed,
see :mod:`metrics_tpu.ops.dispatch`). ``segment_max`` / ``segment_min``
fill their formerly jnp-only registry slots with a masked-select VPU
kernel (:func:`segment_extremum_tiled`) behind the same f32 routing
floors; extremum folds never round, so their kernel-vs-fallback parity is
bit-exact on every input.
"""
import functools
from typing import Any, Union

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl

from metrics_tpu.ops.dispatch import dispatch, register_kernel

try:  # TPU-specific memory spaces; absent on CPU-only installs
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except ImportError:  # pragma: no cover
    _VMEM = None

Array = jax.Array
ArrayLike = Union[Array, np.ndarray]

#: batch rows streamed per grid step (sublane-aligned multiple of 8)
_TILE_B = 512
#: segment columns owned per grid step (one MXU lane tile)
_TILE_S = 128
#: f32 integer-exactness window: unit-weight counts / integer partial sums
#: below this are exact on the MXU accumulate path
_F32_EXACT = 1 << 24


def _segment_sum_kernel(ids_ref, vals_ref, out_ref):
    """Accumulate one (TILE_S, D) segment tile over the batch grid axis."""
    i = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        out_ref[:, :] = jnp.zeros_like(out_ref)

    ids = ids_ref[0, :]  # [TILE_B] int32
    seg = i * _TILE_S + jax.lax.broadcasted_iota(jnp.int32, (_TILE_B, _TILE_S), 1)
    onehot = (ids[:, None] == seg).astype(jnp.float32)  # [TILE_B, TILE_S], on-chip only
    # contract the batch axis: [TILE_B, TILE_S] x [TILE_B, D] -> [TILE_S, D]
    out_ref[:, :] += jax.lax.dot_general(
        onehot,
        vals_ref[:, :],
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


@functools.partial(jax.jit, static_argnames=("num_segments", "interpret"))
def segment_sum_tiled(
    vals: ArrayLike, ids: ArrayLike, num_segments: int, interpret: bool = False
) -> Array:
    """Segment-sum ``[B, D] x [B] -> [num_segments, D]`` via the tiled
    one-hot MXU kernel. ``vals`` may be ``[B]`` (returns ``[num_segments]``).

    Pads B/D/S up to tile multiples (pad rows carry id ``-1``, matching no
    segment) and slices back. Float32 compute; out-of-range ids drop.
    """
    vals = jnp.asarray(vals, jnp.float32)
    squeeze = vals.ndim == 1
    if squeeze:
        vals = vals[:, None]
    ids = jnp.asarray(ids, jnp.int32).reshape(-1)
    b, d = vals.shape
    b_pad = -(-max(b, 1) // _TILE_B) * _TILE_B
    d_pad = -(-max(d, 1) // 128) * 128
    s_pad = -(-max(num_segments, 1) // _TILE_S) * _TILE_S

    ids_p = jnp.full((1, b_pad), -1, jnp.int32).at[0, :b].set(ids)
    vals_p = jnp.zeros((b_pad, d_pad), jnp.float32).at[:b, :d].set(vals)

    ms = {"memory_space": _VMEM} if (not interpret and _VMEM is not None) else {}
    out = pl.pallas_call(
        _segment_sum_kernel,
        out_shape=jax.ShapeDtypeStruct((s_pad, d_pad), jnp.float32),
        grid=(s_pad // _TILE_S, b_pad // _TILE_B),
        in_specs=[
            pl.BlockSpec((1, _TILE_B), lambda i, j: (0, j), **ms),
            pl.BlockSpec((_TILE_B, d_pad), lambda i, j: (j, 0), **ms),
        ],
        out_specs=pl.BlockSpec((_TILE_S, d_pad), lambda i, j: (i, 0), **ms),
        interpret=interpret,
    )(ids_p, vals_p)
    out = out[:num_segments, :d]
    return out[:, 0] if squeeze else out


# ---------------------------------------------------------------------------
# registry-routed entry points
# ---------------------------------------------------------------------------


def _route_dtype_ok(dtype: jnp.dtype) -> bool:
    """float32 ONLY. Every other dtype diverges from its fallback by more
    than summation order: ``jax.ops.segment_sum`` accumulates bf16/f16
    IN bf16/f16 (a 100k-row bf16 sum saturates around 256), so the
    kernel's f32-accumulate-then-cast result differs by orders of
    magnitude, not ulps; INTEGER leaves have statically unbounded
    per-segment partials that would round past ``2**24`` where XLA's
    scatter is exact; f64 would lose precision (the box-IoU guard's
    logic). All of those take the exact fallback."""
    return jnp.dtype(dtype) == jnp.dtype(jnp.float32)


def _segment_route(vals: Any, ids: Array, num_segments: int) -> bool:
    b = ids.shape[0]
    d = 1 if len(vals.shape) == 1 else vals.shape[1]
    return (
        _route_dtype_ok(vals.dtype)
        and b >= 256  # tiny batches: pad waste dominates, scatter is fine
        and num_segments >= 64
        and b < _F32_EXACT  # unit-weight counts stay f32-exact (bincount)
        # the kernel tiles B and S but holds the FULL feature dim per block:
        # vals block (512, d_pad) + resident out tile (128, d_pad) must fit
        # VMEM with pipelining double-buffers — d_pad <= 1024 keeps the
        # working set ~5 MiB; wider leaves take the fallback instead of
        # failing Mosaic compilation at runtime
        and -(-max(d, 1) // 128) * 128 <= 1024
        # dense one-hot work is B * S_pad MACs per 128 value lanes; cap the
        # blow-up where an enormous (B, S) product would out-cost the
        # scatter it replaces
        and b * (-(-num_segments // _TILE_S) * _TILE_S) * max(d, 1) <= 1 << 36
    )


def _segment_sum_pallas(vals, ids, num_segments, interpret=False):
    out = segment_sum_tiled(vals, ids, num_segments, interpret=interpret)
    return out.astype(jnp.asarray(vals).dtype)


def _segment_sum_jnp(vals, ids, num_segments):
    return jax.ops.segment_sum(vals, ids, num_segments=num_segments)


register_kernel(
    "segment_sum",
    pallas_fn=_segment_sum_pallas,
    jnp_fn=_segment_sum_jnp,
    route=_segment_route,
)


# ---------------------------------------------------------------------------
# segment extremum kernels (the formerly jnp-only registry slots)
# ---------------------------------------------------------------------------

#: batch rows folded per extremum grid step: the [_TILE_BE, _TILE_S, D]
#: masked-select temporary is the kernel's VMEM high-water mark, so the
#: batch tile stays one sublane group
_TILE_BE = 8


def _make_segment_ext_kernel(is_max: bool):
    fill = -jnp.inf if is_max else jnp.inf
    combine = jnp.maximum if is_max else jnp.minimum

    def kernel(ids_ref, vals_ref, out_ref):
        i = pl.program_id(0)
        j = pl.program_id(1)

        @pl.when(j == 0)
        def _init():
            out_ref[:, :] = jnp.full_like(out_ref, fill)

        ids = ids_ref[0, :]  # [_TILE_BE] int32
        seg = i * _TILE_S + jax.lax.broadcasted_iota(jnp.int32, (_TILE_BE, _TILE_S), 1)
        onehot = ids[:, None] == seg  # [_TILE_BE, _TILE_S]
        # masked select then fold the batch axis: unlike the sum kernel
        # there is no matmul form for an extremum, so this is VPU work
        # over a [_TILE_BE, _TILE_S, D] temporary
        cand = jnp.where(onehot[:, :, None], vals_ref[:, :][:, None, :], fill)
        out_ref[:, :] = combine(out_ref[:, :], cand.max(axis=0) if is_max else cand.min(axis=0))

    return kernel


@functools.partial(jax.jit, static_argnames=("num_segments", "is_max", "interpret"))
def segment_extremum_tiled(
    vals: ArrayLike, ids: ArrayLike, num_segments: int, is_max: bool, interpret: bool = False
) -> Array:
    """Segment-max/min ``[B, D] x [B] -> [num_segments, D]`` with the same
    tiling scheme as :func:`segment_sum_tiled` (pad rows carry id ``-1``
    and match no segment; empty segments hold the extremum identity —
    exactly ``jax.ops.segment_max/min``'s fill). Extremum folds have no
    rounding, so parity with the fallback is bit-exact for every input,
    not just the integer window."""
    fill = -jnp.inf if is_max else jnp.inf
    vals = jnp.asarray(vals, jnp.float32)
    squeeze = vals.ndim == 1
    if squeeze:
        vals = vals[:, None]
    ids = jnp.asarray(ids, jnp.int32).reshape(-1)
    b, d = vals.shape
    b_pad = -(-max(b, 1) // _TILE_BE) * _TILE_BE
    d_pad = -(-max(d, 1) // 128) * 128
    s_pad = -(-max(num_segments, 1) // _TILE_S) * _TILE_S

    ids_p = jnp.full((1, b_pad), -1, jnp.int32).at[0, :b].set(ids)
    vals_p = jnp.full((b_pad, d_pad), fill, jnp.float32).at[:b, :d].set(vals)

    ms = {"memory_space": _VMEM} if (not interpret and _VMEM is not None) else {}
    out = pl.pallas_call(
        _make_segment_ext_kernel(is_max),
        out_shape=jax.ShapeDtypeStruct((s_pad, d_pad), jnp.float32),
        grid=(s_pad // _TILE_S, b_pad // _TILE_BE),
        in_specs=[
            pl.BlockSpec((1, _TILE_BE), lambda i, j: (0, j), **ms),
            pl.BlockSpec((_TILE_BE, d_pad), lambda i, j: (j, 0), **ms),
        ],
        out_specs=pl.BlockSpec((_TILE_S, d_pad), lambda i, j: (i, 0), **ms),
        interpret=interpret,
    )(ids_p, vals_p)
    out = out[:num_segments, :d]
    return out[:, 0] if squeeze else out


def _segment_ext_route(vals: Any, ids: Array, num_segments: int) -> bool:
    """The segment-sum route's f32-only floors verbatim, minus the 2**24
    exactness cap (an extremum never rounds) and with a tighter feature
    bound (the masked-select temporary scales with D). The kernel handles
    rank 1-2 only; the dispatch wrappers flatten ND values first, but a
    direct ``dispatch()`` caller with ND values must take the fallback."""
    b = ids.shape[0]
    d = 1 if len(vals.shape) == 1 else vals.shape[1]
    return (
        len(vals.shape) <= 2
        and _route_dtype_ok(vals.dtype)
        and b >= 256
        and num_segments >= 64
        and -(-max(d, 1) // 128) * 128 <= 256
        and b * (-(-num_segments // _TILE_S) * _TILE_S) * max(d, 1) <= 1 << 36
    )


def _segment_max_pallas(vals, ids, num_segments, interpret=False):
    out = segment_extremum_tiled(vals, ids, num_segments, is_max=True, interpret=interpret)
    return out.astype(jnp.asarray(vals).dtype)


def _segment_min_pallas(vals, ids, num_segments, interpret=False):
    out = segment_extremum_tiled(vals, ids, num_segments, is_max=False, interpret=interpret)
    return out.astype(jnp.asarray(vals).dtype)


register_kernel(
    "segment_max",
    pallas_fn=_segment_max_pallas,
    jnp_fn=lambda vals, ids, num_segments: jax.ops.segment_max(
        vals, ids, num_segments=num_segments
    ),
    route=_segment_ext_route,
)
register_kernel(
    "segment_min",
    pallas_fn=_segment_min_pallas,
    jnp_fn=lambda vals, ids, num_segments: jax.ops.segment_min(
        vals, ids, num_segments=num_segments
    ),
    route=_segment_ext_route,
)


def segment_sum_dispatch(vals: ArrayLike, ids: ArrayLike, num_segments: int) -> Array:
    """Registry-routed segment-sum over the LEADING axis: ``[B, ...]`` rows
    scatter-add into ``[num_segments, ...]``. Trailing dims are flattened
    through the kernel and restored; result dtype follows the input (the
    jnp fallback's contract). Out-of-range ids (negative included) drop on
    both paths."""
    vals = jnp.asarray(vals)
    ids = jnp.asarray(ids)
    lead = vals.shape[0] if vals.ndim else 0
    flat = vals.reshape(lead, -1) if vals.ndim > 2 else vals
    out = dispatch("segment_sum", flat, ids, num_segments)
    if vals.ndim > 2:
        out = out.reshape((num_segments,) + vals.shape[1:])
    return out


def _segment_ext_dispatch(name: str, vals: ArrayLike, ids: ArrayLike, num_segments: int) -> Array:
    # trailing dims flatten through the 2-D kernel and restore — exact for
    # an elementwise extremum (the segment_sum_dispatch contract)
    vals = jnp.asarray(vals)
    ids = jnp.asarray(ids)
    lead = vals.shape[0] if vals.ndim else 0
    flat = vals.reshape(lead, -1) if vals.ndim > 2 else vals
    out = dispatch(name, flat, ids, num_segments)
    if vals.ndim > 2:
        out = out.reshape((num_segments,) + vals.shape[1:])
    return out


def segment_max_dispatch(vals: ArrayLike, ids: ArrayLike, num_segments: int) -> Array:
    """Registry-routed segment-max over the LEADING axis (the masked-select
    Pallas kernel on TPU inside the f32 route floors, ``jax.ops.segment_max``
    elsewhere; trailing dims flatten through the kernel and restore; empty
    segments fill with the extremum identity on both paths)."""
    return _segment_ext_dispatch("segment_max", vals, ids, num_segments)


def segment_min_dispatch(vals: ArrayLike, ids: ArrayLike, num_segments: int) -> Array:
    """Registry-routed segment-min (see :func:`segment_max_dispatch`)."""
    return _segment_ext_dispatch("segment_min", vals, ids, num_segments)


# ---------------------------------------------------------------------------
# bincount: validation at the dispatch boundary + the same kernel
# ---------------------------------------------------------------------------


def _bincount_route(x: Array, minlength: int) -> bool:
    # shape-only probe for the unit-weight values (counts are bounded by the
    # route's B cap, hence f32-exact) — no device allocation on the hot path
    probe = jax.ShapeDtypeStruct((x.shape[0] if x.ndim else 1,), jnp.float32)
    return not jax.config.jax_enable_x64 and _segment_route(probe, x, minlength)


def _bincount_pallas(x, minlength, interpret=False):
    ones = jnp.ones(x.shape, jnp.float32)
    return segment_sum_tiled(ones, x, minlength, interpret=interpret).astype(jnp.int32)


def _bincount_jnp(x, minlength):
    return jnp.bincount(x, length=minlength)


register_kernel(
    "bincount",
    pallas_fn=_bincount_pallas,
    jnp_fn=_bincount_jnp,
    route=_bincount_route,
)


def bincount_dispatch(x: ArrayLike, minlength: int) -> Array:
    """Registry-routed static-length bincount with hardened inputs.

    ``jnp.bincount`` inherits XLA scatter's silent edge semantics: float
    indices raise only deep in the scatter lowering, and NEGATIVE indices
    are silently clipped into bin 0 — corrupting the count that every
    confusion-matrix metric is built on. This boundary makes the contract
    explicit:

    * ``minlength`` must be a positive Python int (it is the static output
      length under jit).
    * ``x`` must be integer-typed — floats raise ``TypeError`` here, not
      three layers down.
    * negative indices raise ``ValueError`` when the values are already on
      the host (numpy arrays, Python sequences) — a free check. Device or
      traced values are NOT pulled back for validation (a per-call
      device->host sync would serialize every eager classification
      update); instead negatives are masked to ``minlength`` and DROPPED
      on both backends — the deterministic fate of too-large ids, never a
      silent bin-0 credit.
    """
    if not isinstance(minlength, int) or isinstance(minlength, bool) or minlength <= 0:
        raise ValueError(f"`minlength` must be a positive int, got {minlength!r}")
    host_vals = np.asarray(x) if isinstance(x, (np.ndarray, list, tuple)) else None
    x = jnp.asarray(x).reshape(-1)
    if not jnp.issubdtype(x.dtype, jnp.integer):
        raise TypeError(
            f"bincount indices must be integer-typed, got dtype {x.dtype};"
            " cast labels with .astype(jnp.int32) at the call site"
        )
    if host_vals is not None and host_vals.size and host_vals.min() < 0:
        raise ValueError(
            f"bincount indices must be non-negative, got min {int(host_vals.min())};"
            " XLA scatter would otherwise clip negatives into bin 0"
        )
    if x.dtype.itemsize < 4:
        # the out-of-range sentinel below must be representable: in int8,
        # `minlength=300` wraps to 44 — a VALID bin — silently re-crediting
        # the masked negatives (and int16 overflows similarly). int64 stays:
        # downcasting could wrap a huge OOB label INTO range.
        x = x.astype(jnp.int32)
    if host_vals is None:
        # device/traced values: force negatives out of range so both
        # backends DROP them (scatter would clip them into bin 0); fuses
        # into the count — no host sync
        x = jnp.where(x < 0, minlength, x)
    return dispatch("bincount", x, minlength)


