"""Deterministic image corpus for the inference-metric oracle fixtures —
shared by the stored-score test (tests/image/test_inference_fixture.py) and
the generator (scripts/make_image_oracle.py).

Fully seeded: any environment reproduces the SAME image sets, so scores
stored by one environment (e.g. one with network access, pretrained
weights, and the torch-fidelity / official LPIPS packages) pin every other
environment unconditionally — the PESQ stored-corpus pattern
(tests/audio/pesq_corpus.py) applied to FID/KID/IS and LPIPS.
"""
from typing import Tuple

import numpy as np

N_IMAGES = 20
HW = 96


def _structured(rng: np.random.Generator, n: int) -> np.ndarray:
    """Smooth, structured uint8 images: soft blobs + gradients (the 'real'
    distribution)."""
    yy, xx = np.mgrid[0:HW, 0:HW].astype(np.float32) / HW
    imgs = []
    for _ in range(n):
        base = np.zeros((HW, HW, 3), np.float32)
        for _ in range(4):
            cx, cy, r = rng.uniform(0.2, 0.8, 3)
            col = rng.uniform(0.3, 1.0, 3)
            blob = np.exp(-(((xx - cx) ** 2 + (yy - cy) ** 2) / (0.05 + 0.1 * r)))
            base += blob[..., None] * col[None, None, :]
        base += 0.3 * np.stack([xx, yy, 1 - xx], -1)
        base /= max(base.max(), 1e-6)
        imgs.append((base * 255).astype(np.uint8))
    return np.stack(imgs).transpose(0, 3, 1, 2)  # NCHW uint8


def _textured(rng: np.random.Generator, n: int) -> np.ndarray:
    """Noise-textured variants (the 'fake' distribution): structured base
    plus strong high-frequency noise."""
    base = _structured(rng, n).astype(np.float32)
    noise = rng.integers(-60, 60, base.shape).astype(np.float32)
    return np.clip(base + noise, 0, 255).astype(np.uint8)


def fid_sets() -> Tuple[np.ndarray, np.ndarray]:
    """(real, fake) uint8 NCHW image sets for FID/KID/IS."""
    rng = np.random.default_rng(2024)
    return _structured(rng, N_IMAGES), _textured(rng, N_IMAGES)


def lpips_pairs() -> Tuple[np.ndarray, np.ndarray]:
    """(img1, img2) float NCHW pairs in [-1, 1] for LPIPS."""
    rng = np.random.default_rng(4048)
    a = _structured(rng, 8).astype(np.float32) / 127.5 - 1.0
    jitter = rng.normal(0, 0.15, a.shape).astype(np.float32)
    b = np.clip(a + jitter, -1, 1)
    return a, b


def seed0_extractors():
    """The drift-pin extractor pair — seed-0 random-weight InceptionV3
    through the SHALLOW taps (the deep taps collapse to near-constant
    features under random weights: measured std 2e-4 at depth 2048 vs 0.07
    at 192). ONE definition shared by the fixture generator
    (scripts/make_image_oracle.py) and tests/image/test_inference_fixture.py
    so the pinned configuration cannot drift between them.

    Returns ``(feat, logits)``: jitted ``imgs -> [N, 192]`` features for
    FID/KID and ``imgs -> [N, 64]`` pseudo-logits for IS.
    """
    import jax
    import jax.numpy as jnp

    from metrics_tpu.models.inception import InceptionV3FID

    model = InceptionV3FID()
    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 3, 299, 299), jnp.float32), feature="logits_unbiased"
    )
    feat = jax.jit(
        lambda imgs: model.apply(variables, imgs.astype(jnp.float32) / 255.0, feature=192)
    )
    logits = jax.jit(
        lambda imgs: model.apply(variables, imgs.astype(jnp.float32) / 255.0, feature=64)
    )
    return feat, logits


#: KID subset permutations and IS splits must be seeded for the drift pin
KID_KWARGS = dict(subset_size=10, subsets=4, seed=123)
IS_KWARGS = dict(splits=2, seed=123)


def engine_scores(feat=None, logits=None):
    """FID/KID/IS over the corpus — the ONE scoring definition shared by
    generator and test. Default extractors are the seed-0 drift-pin pair."""
    import jax.numpy as jnp

    from metrics_tpu.image import (
        FrechetInceptionDistance,
        InceptionScore,
        KernelInceptionDistance,
    )

    if feat is None or logits is None:
        feat, logits = seed0_extractors()
    real, fake = fid_sets()

    # exact=True: the fixture pins the REFERENCE engine semantics (f64
    # eigh trace-sqrtm, seeded shuffle splits) that official/real-weight
    # csvs are compared against; the streaming default has its own tests
    fid = FrechetInceptionDistance(feature=feat, exact=True)
    fid.update(jnp.asarray(real), real=True)
    fid.update(jnp.asarray(fake), real=False)

    kid = KernelInceptionDistance(feature=feat, **KID_KWARGS)
    kid.update(jnp.asarray(real), real=True)
    kid.update(jnp.asarray(fake), real=False)
    kid_mean, _ = kid.compute()

    inception = InceptionScore(feature=logits, exact=True, **IS_KWARGS)
    inception.update(jnp.asarray(fake))
    is_mean, is_std = inception.compute()

    return {
        "fid": float(fid.compute()),
        "kid_mean": float(kid_mean),
        "is_mean": float(is_mean),
        "is_std": float(is_std),
    }
