"""Reference-parity sweep for the deterministic image metrics.

Breadth parity with /root/reference/tests/image/test_{psnr,ssim,ms_ssim,
uqi}.py: PSNR / SSIM / MS-SSIM / UQI against the reference implementation
over the argument axes their grids sweep (data_range, base, dim-reduced
PSNR, kernel size/sigma, k1/k2, reduction modes, MS-SSIM betas) plus
image_gradients. FID/KID/IS and LPIPS have their own converter + gated
real-weight suites (test_fid_kid_is.py, test_real_weights.py).
"""
import numpy as np
import pytest

import jax.numpy as jnp

from metrics_tpu.image import (
    MultiScaleStructuralSimilarityIndexMeasure,
    PeakSignalNoiseRatio,
    StructuralSimilarityIndexMeasure,
    UniversalImageQualityIndex,
)
from metrics_tpu.functional.image.gradients import image_gradients
from tests.helpers.reference import load_reference_module

torch = pytest.importorskip("torch")

_rng = np.random.default_rng(37)
BATCHES = 2
A = _rng.random((BATCHES, 4, 3, 64, 64)).astype(np.float32)
B = np.clip(A + 0.08 * _rng.standard_normal(A.shape).astype(np.float32), 0, 1)


def _ref_img(attr, *args, **kwargs):
    mod = load_reference_module("torchmetrics.image")
    return getattr(mod, attr)(*args, **kwargs)


def _parity(ours, ref, rtol=1e-4, preds=B, target=A):
    for i in range(BATCHES):
        ours.update(jnp.asarray(preds[i]), jnp.asarray(target[i]))
        ref.update(torch.as_tensor(preds[i]), torch.as_tensor(target[i]))
    np.testing.assert_allclose(float(ours.compute()), float(ref.compute()), rtol=rtol)


@pytest.mark.parametrize("data_range", [None, 1.0, 2.0])
@pytest.mark.parametrize("base", [10.0, 2.0])
def test_psnr_reference_grid(data_range, base):
    args = {"data_range": data_range, "base": base}
    _parity(PeakSignalNoiseRatio(**args), _ref_img("PeakSignalNoiseRatio", **args))


def test_psnr_dim_reduced_reference_parity():
    """Per-image PSNR (dim argument) with elementwise_mean reduction."""
    args = {"data_range": 1.0, "dim": (1, 2, 3), "reduction": "elementwise_mean"}
    _parity(PeakSignalNoiseRatio(**args), _ref_img("PeakSignalNoiseRatio", **args))


@pytest.mark.parametrize("kernel_size", [(11, 11), (7, 7)])
@pytest.mark.parametrize("sigma", [(1.5, 1.5), (0.8, 0.8)])
def test_ssim_kernel_grid(kernel_size, sigma):
    args = {"kernel_size": kernel_size, "sigma": sigma, "data_range": 1.0}
    _parity(
        StructuralSimilarityIndexMeasure(**args),
        _ref_img("StructuralSimilarityIndexMeasure", **args),
    )


@pytest.mark.parametrize("k1, k2", [(0.01, 0.03), (0.02, 0.05)])
def test_ssim_k_constants(k1, k2):
    args = {"k1": k1, "k2": k2, "data_range": 1.0}
    _parity(
        StructuralSimilarityIndexMeasure(**args),
        _ref_img("StructuralSimilarityIndexMeasure", **args),
    )


def test_ms_ssim_reference_parity():
    # >160px inputs so the 5-beta/kernel-11 pyramid is valid (reference constraint)
    big_a = _rng.random((2, 3, 192, 192)).astype(np.float32)
    big_b = np.clip(big_a + 0.05 * _rng.standard_normal(big_a.shape).astype(np.float32), 0, 1)
    ours = MultiScaleStructuralSimilarityIndexMeasure(data_range=1.0)
    ref = _ref_img("MultiScaleStructuralSimilarityIndexMeasure", data_range=1.0)
    ours.update(jnp.asarray(big_b), jnp.asarray(big_a))
    ref.update(torch.as_tensor(big_b), torch.as_tensor(big_a))
    np.testing.assert_allclose(float(ours.compute()), float(ref.compute()), rtol=1e-3)


@pytest.mark.parametrize("kernel_size", [(11, 11), (5, 5)])  # odd required (reference uqi.py:86)
def test_uqi_reference_grid(kernel_size):
    args = {"kernel_size": kernel_size}
    _parity(
        UniversalImageQualityIndex(**args),
        _ref_img("UniversalImageQualityIndex", **args),
        rtol=1e-3,
    )


def test_image_gradients_reference_parity():
    ref_fn = getattr(load_reference_module("torchmetrics.functional"), "image_gradients")
    img = jnp.asarray(A[0])
    dy, dx = image_gradients(img)
    ref_dy, ref_dx = ref_fn(torch.as_tensor(A[0]))
    np.testing.assert_allclose(np.asarray(dy), ref_dy.numpy(), atol=1e-6)
    np.testing.assert_allclose(np.asarray(dx), ref_dx.numpy(), atol=1e-6)


def test_ssim_validation_matches_reference():
    # validation fires when the kernel is used (compute path), as in the
    # reference functional
    even = StructuralSimilarityIndexMeasure(kernel_size=(4, 4), data_range=1.0)
    with pytest.raises(ValueError, match="odd"):
        even(jnp.asarray(A[0]), jnp.asarray(B[0]))
    bad_sigma = StructuralSimilarityIndexMeasure(sigma=(0.0, 0.0), data_range=1.0)
    with pytest.raises(ValueError):
        bad_sigma(jnp.asarray(A[0]), jnp.asarray(B[0]))
    m = StructuralSimilarityIndexMeasure(data_range=1.0)
    with pytest.raises(RuntimeError, match="same shape"):
        m.update(jnp.zeros((2, 3, 16, 16)), jnp.zeros((2, 3, 16)))  # rank mismatch


def test_psnr_identical_images_infinite():
    m = PeakSignalNoiseRatio(data_range=1.0)
    m.update(jnp.asarray(A[0]), jnp.asarray(A[0]))
    assert np.isinf(float(m.compute()))
