"""F-beta / F1 functional kernels.

Behavior parity with /root/reference/torchmetrics/functional/classification/
f_beta.py:24-229 (the micro path masks ignored classes before summing; the
macro/none class removal is re-expressed as a jit-safe ignore mask).
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.stat_scores import (
    _check_avg_arguments,
    _reduce_stat_scores,
    _stat_scores_update,
)
from metrics_tpu.utils.data import _safe_divide
from metrics_tpu.utils.enums import AverageMethod, MDMCAverageMethod

Array = jax.Array


def _fbeta_compute(
    tp: Array,
    fp: Array,
    tn: Array,
    fn: Array,
    beta: float,
    ignore_index: Optional[int],
    average: str,
    mdmc_average: Optional[str],
) -> Array:
    """Reference f_beta.py:30-108.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.classification.stat_scores import _stat_scores_update
        >>> target = jnp.array([0, 1, 2, 0, 1, 2])
        >>> preds = jnp.array([0, 2, 1, 0, 0, 1])
        >>> tp, fp, tn, fn = _stat_scores_update(preds, target, reduce='micro', num_classes=3)
        >>> _fbeta_compute(tp, fp, tn, fn, beta=0.5, ignore_index=None, average='micro', mdmc_average=None)
        Array(0.33333334, dtype=float32)
    """
    if average == AverageMethod.MICRO and mdmc_average != MDMCAverageMethod.SAMPLEWISE:
        mask = tp >= 0
        precision = _safe_divide(jnp.sum(jnp.where(mask, tp, 0)).astype(jnp.float32), jnp.sum(jnp.where(mask, tp + fp, 0)))
        recall = _safe_divide(jnp.sum(jnp.where(mask, tp, 0)).astype(jnp.float32), jnp.sum(jnp.where(mask, tp + fn, 0)))
    else:
        precision = _safe_divide(tp.astype(jnp.float32), tp + fp)
        recall = _safe_divide(tp.astype(jnp.float32), tp + fn)

    num = (1 + beta**2) * precision * recall
    denom = beta**2 * precision + recall
    denom = jnp.where(denom == 0.0, 1.0, denom)

    # absent classes (no TPs, FPs, nor FNs) are meaningless for per-class scores
    if average == AverageMethod.NONE and mdmc_average != MDMCAverageMethod.SAMPLEWISE:
        meaningless = (tp | fn | fp) == 0
        if ignore_index is not None:
            meaningless = meaningless.at[ignore_index].set(True)
        num = jnp.where(meaningless, -1.0, num)
        denom = jnp.where(meaningless, -1.0, denom)
    elif ignore_index is not None:
        if average not in (AverageMethod.MICRO, AverageMethod.SAMPLES) and mdmc_average == MDMCAverageMethod.SAMPLEWISE:
            num = num.at[..., ignore_index].set(-1.0)
            denom = denom.at[..., ignore_index].set(-1.0)
        elif average not in (AverageMethod.MICRO, AverageMethod.SAMPLES):
            num = num.at[ignore_index, ...].set(-1.0)
            denom = denom.at[ignore_index, ...].set(-1.0)

    if average == AverageMethod.MACRO and mdmc_average != MDMCAverageMethod.SAMPLEWISE:
        cond = ((tp + fp + fn) == 0) | ((tp + fp + fn) == -3)
        num = jnp.where(cond, 0.0, num)
        denom = jnp.where(cond, -1.0, denom)

    return _reduce_stat_scores(
        numerator=num,
        denominator=denom,
        weights=None if average != AverageMethod.WEIGHTED else (tp + fn),
        average=average,
        mdmc_average=mdmc_average,
    )


def fbeta_score(
    preds: Array,
    target: Array,
    beta: float = 1.0,
    average: str = "micro",
    mdmc_average: Optional[str] = None,
    ignore_index: Optional[int] = None,
    num_classes: Optional[int] = None,
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    multiclass: Optional[bool] = None,
) -> Array:
    """One-shot F-beta. Reference f_beta.py:111-229.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([0, 1, 2, 0, 1, 2])
        >>> preds = jnp.array([0, 2, 1, 0, 0, 1])
        >>> fbeta_score(preds, target, num_classes=3, beta=0.5)
        Array(0.33333334, dtype=float32)
    """
    _check_avg_arguments(average, mdmc_average, num_classes, ignore_index)

    reduce = "macro" if average in ("weighted", "none", None) else average
    tp, fp, tn, fn = _stat_scores_update(
        preds,
        target,
        reduce=reduce,
        mdmc_reduce=mdmc_average,
        threshold=threshold,
        num_classes=num_classes,
        top_k=top_k,
        multiclass=multiclass,
        ignore_index=ignore_index,
    )
    return _fbeta_compute(tp, fp, tn, fn, beta, ignore_index, average, mdmc_average)


def f1_score(
    preds: Array,
    target: Array,
    average: str = "micro",
    mdmc_average: Optional[str] = None,
    ignore_index: Optional[int] = None,
    num_classes: Optional[int] = None,
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    multiclass: Optional[bool] = None,
) -> Array:
    """F1 = F-beta with beta=1. Reference f_beta.py:232-344.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([0, 1, 2, 0, 1, 2])
        >>> preds = jnp.array([0, 2, 1, 0, 0, 1])
        >>> f1_score(preds, target, num_classes=3)
        Array(0.33333334, dtype=float32)
    """
    return fbeta_score(preds, target, 1.0, average, mdmc_average, ignore_index, num_classes, threshold, top_k, multiclass)
