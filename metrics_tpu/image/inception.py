"""Inception Score.

Behavior parity with /root/reference/torchmetrics/image/inception.py:28-171.
``feature`` accepts any callable ``imgs -> [N, num_classes]`` logits
extractor or 'logits_unbiased'/int for the bundled Flax InceptionV3.
"""
from typing import Any, Callable, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.core.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat
from metrics_tpu.utils.prints import rank_zero_warn

Array = jax.Array


class InceptionScore(Metric):
    """Computes the Inception Score (mean and std over splits)."""

    __jit_unsafe__ = True
    is_differentiable = False
    higher_is_better = True

    def __init__(
        self,
        feature: Union[str, int, Callable] = "logits_unbiased",
        splits: int = 10,
        seed: int = None,
        feature_extractor_weights_path: str = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)

        rank_zero_warn(
            "Metric `InceptionScore` will save all extracted features in buffer."
            " For large datasets this may lead to large memory footprint.",
            UserWarning,
        )

        if isinstance(feature, (str, int)):
            valid_int_input = ("logits_unbiased", 64, 192, 768, 2048)
            if feature not in valid_int_input:
                raise ValueError(
                    f"Integer input to argument `feature` must be one of {valid_int_input}, but got {feature}."
                )
            from metrics_tpu.models.inception import build_fid_inception

            self.inception = build_fid_inception(feature, feature_extractor_weights_path)
        elif callable(feature):
            self.inception = feature
        else:
            raise TypeError("Got unknown input to argument `feature`")

        self.splits = splits
        self._rng = np.random.RandomState(seed)
        self.add_state("features", [], dist_reduce_fx=None)

    def _update(self, imgs: Array) -> None:
        features = self.inception(imgs)
        self.features.append(features)

    def _compute(self) -> Tuple[Array, Array]:
        getattr(self.inception, "finalize", lambda: None)()  # flush async range check of the last batch
        features = dim_zero_cat(self.features)
        idx = self._rng.permutation(features.shape[0])
        features = features[idx]

        prob = jax.nn.softmax(features, axis=1)
        log_prob = jax.nn.log_softmax(features, axis=1)

        prob_chunks = jnp.array_split(prob, self.splits, axis=0)
        log_prob_chunks = jnp.array_split(log_prob, self.splits, axis=0)

        kl_ = []
        for p, log_p in zip(prob_chunks, log_prob_chunks):
            m_p = jnp.mean(p, axis=0, keepdims=True)
            kl = p * (log_p - jnp.log(m_p))
            kl_.append(jnp.exp(jnp.mean(jnp.sum(kl, axis=1))))
        kl = jnp.stack(kl_)
        return jnp.mean(kl), jnp.std(kl, ddof=1)
