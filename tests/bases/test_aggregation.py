"""Aggregation metric tests.

Mirrors /root/reference/tests/bases/test_aggregation.py in spirit.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from metrics_tpu.aggregation import CatMetric, MaxMetric, MeanMetric, MinMetric, SumMetric


def compare_mean(values, weights):
    return np.average(np.asarray(values).flatten(), weights=np.broadcast_to(weights, np.shape(values)).flatten())


@pytest.mark.parametrize(
    "metric_class, compare_fn",
    [
        (MinMetric, np.min),
        (MaxMetric, np.max),
        (SumMetric, np.sum),
        (CatMetric, lambda x: np.concatenate([np.atleast_1d(v) for v in x])),
        (MeanMetric, np.mean),
    ],
)
@pytest.mark.parametrize("case", ["scalar", "tensor", "multidim"])
def test_aggregation_parity(metric_class, compare_fn, case):
    rng = np.random.RandomState(5)
    if case == "scalar":
        values = [float(v) for v in rng.rand(10)]
    elif case == "tensor":
        values = [rng.rand(5).astype(np.float32) for _ in range(10)]
    else:
        values = [rng.rand(3, 4).astype(np.float32) for _ in range(10)]

    metric = metric_class()
    for v in values:
        metric.update(v)
    result = np.asarray(metric.compute())

    flat = np.concatenate([np.atleast_1d(np.asarray(v)).ravel() for v in values])
    if metric_class is CatMetric:
        if case == "scalar":
            expected = np.asarray(values, dtype=np.float32)
        else:
            expected = np.concatenate([np.asarray(v).reshape(np.asarray(v).shape) for v in values])
        assert result.ravel() == pytest.approx(expected.ravel(), abs=1e-6)
    else:
        expected = compare_fn(flat)
        assert result == pytest.approx(expected, abs=1e-5)


def test_mean_metric_weighted():
    metric = MeanMetric()
    metric.update(jnp.asarray([1.0, 2.0, 3.0]), weight=jnp.asarray([1.0, 2.0, 3.0]))
    metric.update(4.0, weight=2.0)
    expected = (1 * 1 + 2 * 2 + 3 * 3 + 4 * 2) / (1 + 2 + 3 + 2)
    assert float(metric.compute()) == pytest.approx(expected, abs=1e-6)


@pytest.mark.parametrize("metric_class", [MinMetric, MaxMetric, SumMetric, CatMetric, MeanMetric])
def test_nan_strategies(metric_class):
    with pytest.raises(ValueError):
        metric_class(nan_strategy="invalid")

    m = metric_class(nan_strategy="error")
    with pytest.raises(RuntimeError):
        m.update(jnp.asarray([1.0, jnp.nan]))

    m = metric_class(nan_strategy="ignore")
    m.update(jnp.asarray([1.0, jnp.nan, 3.0]))
    res = np.asarray(m.compute())
    assert not np.any(np.isnan(res))

    m = metric_class(nan_strategy=2.0)
    m.update(jnp.asarray([1.0, jnp.nan, 3.0]))
    res = np.asarray(m.compute())
    assert not np.any(np.isnan(res))

    m = metric_class(nan_strategy="warn")
    with pytest.warns(UserWarning):
        m.update(jnp.asarray([1.0, jnp.nan, 3.0]))


def test_zero_value_not_skipped():
    """The reference's `any(value.flatten())` guard wrongly skips all-zero
    updates; element count is the correct emptiness check."""
    m = MaxMetric()
    m.update(0.0)
    assert float(m.compute()) == 0.0
    s = SumMetric()
    s.update(jnp.zeros(3))
    assert float(s.compute()) == 0.0


def test_aggregator_reset():
    m = SumMetric()
    m.update(5.0)
    m.reset()
    m.update(2.0)
    assert float(m.compute()) == 2.0


def test_mean_metric_joint_nan_filtering():
    """Elementwise weight with NaN in value must not desync shapes."""
    m = MeanMetric(nan_strategy="ignore")
    m.update(jnp.asarray([1.0, jnp.nan, 3.0]), weight=jnp.asarray([1.0, 5.0, 2.0]))
    assert float(m.compute()) == pytest.approx((1 * 1 + 3 * 2) / (1 + 2))


@pytest.mark.parametrize(
    "metric_class, values, expected",
    [
        (SumMetric, [1.0, np.nan, 3.0], 4.0),
        (MaxMetric, [1.0, np.nan, 3.0], 3.0),
        (MinMetric, [1.0, np.nan, 3.0], 1.0),
        (MeanMetric, [1.0, np.nan, 3.0], 2.0),
    ],
)
def test_nan_ignore_under_jit(metric_class, values, expected):
    """jit and eager must agree for nan_strategy='ignore'."""
    import jax

    m = metric_class(nan_strategy="ignore")
    state = jax.jit(m.update_state)(m.init_state(), jnp.asarray(values))
    assert float(m.compute_state(state)) == pytest.approx(expected)


def test_mean_merge_states():
    m = MeanMetric()
    s1 = m.init_state()
    s1 = m.update_state(s1, 1.0)
    s2 = m.init_state()
    s2 = m.update_state(s2, 3.0)
    merged = m.merge_states(s1, s2)
    assert float(m.compute_state(merged)) == pytest.approx(2.0)
