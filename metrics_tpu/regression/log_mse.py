"""Modular MeanSquaredLogError.

Behavior parity with /root/reference/torchmetrics/regression/log_mse.py:23-84.
"""
from typing import Any

import jax
import jax.numpy as jnp

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.regression.log_mse import (
    _mean_squared_log_error_compute,
    _mean_squared_log_error_update,
)

Array = jax.Array


class MeanSquaredLogError(Metric):
    """Computes mean squared log error.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([2.5, 5.0, 4.0, 8.0])
        >>> preds = jnp.array([3.0, 5.0, 2.5, 7.0])
        >>> mean_squared_log_error = MeanSquaredLogError()
        >>> mean_squared_log_error(preds, target)
        Array(0.03973011, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_squared_log_error", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")

    def _update(self, preds: Array, target: Array) -> None:
        sum_squared_log_error, n_obs = _mean_squared_log_error_update(preds, target)
        self.sum_squared_log_error = self.sum_squared_log_error + sum_squared_log_error
        self.total = self.total + n_obs

    def _compute(self) -> Array:
        return _mean_squared_log_error_compute(self.sum_squared_log_error, self.total)
