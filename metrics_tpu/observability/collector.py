"""Fleet observatory: a merge-tree snapshot collector over the wire format.

The fold half of ROADMAP item 3 (:mod:`metrics_tpu.observability.wire` is
the serialization half): N serving processes publish snapshots into a
transport-agnostic sink, and a collector process folds them — at
thousands of snapshots per second — into the same answer a single job
would have computed, using the reducers already in-tree
(``Metric.merge_states`` for metric-state pytrees,
:func:`~metrics_tpu.observability.merge_payloads` for telemetry).

* :class:`SnapshotSink` — the publisher side of the in-tree transport: a
  **directory queue** of atomic snapshot files (tmp + ``os.replace``; a
  reader can never observe a truncation). No RPC dependency; any
  shared/synced filesystem, object-store mount, or sidecar shipping the
  files works. The sink owns the monotonic per-publisher sequence number.
* :class:`SnapshotQueue` — the collector side: consume-on-read polling of
  the directory, oldest first, with an optional per-poll cap so one burst
  cannot head-of-line-block liveness accounting.
* :class:`FleetCollector` — decode, validate, dedup, and fold:

  - **exactly-once**: snapshots are identified by ``(publisher, seq)``;
    a duplicate (retried ship, double-mounted queue) is counted and
    dropped, never folded twice.
  - **bounded late window with a watermark**: the event-time watermark
    trails the newest snapshot wall-clock by ``late_window_s``. Late
    snapshots still above the watermark fold normally (``"delta"`` mode
    holds pending snapshots until the watermark passes them so they fold
    in sequence order — the fold is arrival-order independent);
    post-watermark stragglers are counted and dropped.
  - **per-publisher liveness/lag**: last sequence, last snapshot time,
    and the current lag per publisher; ``stale_after_s`` marks silent
    publishers, and every poll feeds the windowed ``publisher_lag_s`` /
    ``collector_backlog`` / ``collector_fold_errors`` telemetry series
    the ``publisher_stale`` / ``snapshot_backlog`` / ``fold_error``
    health alarms watch.
  - **hierarchical fan-in**: :meth:`FleetCollector.publish_fold`
    re-publishes the collector's own fold as a snapshot, so host-level
    collectors feed rack collectors feed a global one — a merge tree;
    every tier runs the same code and the same reducers.

Folding disciplines per snapshot ``mode`` (set by the publisher):

* ``"state"`` — cumulative snapshots: per publisher the newest sequence
  wins, and the global fold merges one state per publisher (sorted by
  publisher id) — exactly ``aggregate_across_hosts``'s semantics with
  files instead of a collective.
* ``"delta"`` — publishers reset after publishing; every snapshot is a
  disjoint increment, folded in sequence order per publisher below the
  watermark.

See docs/fleet_collector.md and ``examples/fleet_collector.py``.
"""
from __future__ import annotations

import os
import re
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from metrics_tpu.observability.wire import (
    Snapshot,
    WireError,
    decode_snapshot,
    encode_snapshot,
    members_of,
    snapshot_states,
    states_key,
)

__all__ = [
    "FleetCollector",
    "PublisherStatus",
    "SnapshotQueue",
    "SnapshotSink",
]

#: snapshot file suffix in a directory queue
SNAPSHOT_SUFFIX = ".snap"

_SAFE_ID = re.compile(r"[^A-Za-z0-9._-]+")


def _safe_name(publisher: str) -> str:
    """Publisher id -> filesystem-safe file stem."""
    return _SAFE_ID.sub("_", publisher) or "publisher"


class SnapshotSink:
    """Publisher-side directory queue: atomic snapshot files, one per
    ``publish()``.

    Owns the monotonic per-publisher sequence number (``seq_start`` lets
    a restarted publisher resume above its previous range — sequence
    numbers identify snapshots, so a restart that reuses them would be
    deduplicated away as duplicates). Thread-safe."""

    def __init__(
        self,
        directory: str,
        publisher: str,
        host: str = "",
        process: int = 0,
        tier: str = "leaf",
        seq_start: int = 0,
    ) -> None:
        if not publisher:
            raise ValueError("publisher id must be non-empty")
        self.directory = str(directory)
        self.publisher = publisher
        self.host = host
        self.process = int(process)
        self.tier = tier
        os.makedirs(self.directory, exist_ok=True)
        self._seq = int(seq_start)
        self._dups = 0
        self._lock = threading.Lock()
        self.last_path: Optional[str] = None
        self._last_blob: Optional[bytes] = None

    def publish(
        self,
        *,
        states: Optional[Dict[str, Dict[str, Any]]] = None,
        states_template: Optional[Any] = None,
        telemetry: Optional[Any] = None,
        mode: str = "state",
        t: Optional[float] = None,
        span: Optional[Dict[str, Any]] = None,
    ) -> str:
        """Encode and atomically land one snapshot file; returns its path.
        ``states``/``telemetry`` as in :func:`~metrics_tpu.observability.
        wire.encode_snapshot`; the sink supplies the provenance header.
        ``span`` defaults to the CALLER'S active trace-span context (wire
        v2), so a publish inside ``with span("publish_tick"):`` is
        automatically stitchable from the collector side."""
        if span is None:
            from metrics_tpu.observability.trace import current_span_context

            span = current_span_context()
        with self._lock:
            seq = self._seq
            self._seq += 1
            blob = encode_snapshot(
                publisher=self.publisher,
                seq=seq,
                t=t,
                host=self.host,
                process=self.process,
                mode=mode,
                tier=self.tier,
                states=states,
                states_template=states_template,
                telemetry=telemetry,
                span=span,
            )
            path = self._write(blob, seq)
            self.last_path = path
            self._last_blob = blob
            return path

    def republish_last(self) -> Optional[str]:
        """Write the previous snapshot AGAIN under a fresh file name (same
        publisher + sequence number inside) — fault injection for the
        collector's exactly-once dedup contract. Returns the new path, or
        ``None`` before the first publish."""
        with self._lock:
            if self._last_blob is None:
                return None
            self._dups += 1
            return self._write(self._last_blob, self._seq - 1, dup=self._dups)

    def _write(self, blob: bytes, seq: int, dup: int = 0) -> str:
        stem = f"{_safe_name(self.publisher)}-{seq:012d}{f'-dup{dup}' if dup else ''}"
        path = os.path.join(self.directory, stem + SNAPSHOT_SUFFIX)
        tmp = os.path.join(self.directory, f".{stem}.tmp.{os.getpid()}")
        with open(tmp, "wb") as fh:
            fh.write(blob)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        return path


class SnapshotQueue:
    """Collector-side directory queue: consume-on-read polling.

    ``poll()`` returns up to ``max_files`` ``(path, bytes)`` pairs oldest
    first and unlinks each file after reading it — a snapshot is consumed
    exactly once even across collector restarts. Unreadable files are
    returned with ``b""`` bytes so the collector can count the loss
    instead of silently skipping it."""

    def __init__(self, directory: str) -> None:
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)

    def backlog(self) -> int:
        """Snapshot files currently waiting in the directory."""
        try:
            return sum(1 for n in os.listdir(self.directory) if n.endswith(SNAPSHOT_SUFFIX))
        except OSError:
            return 0

    def poll(self, max_files: Optional[int] = None) -> List[Tuple[str, bytes]]:
        try:
            names = sorted(n for n in os.listdir(self.directory) if n.endswith(SNAPSHOT_SUFFIX))
        except OSError:
            return []
        if max_files is not None:
            names = names[: int(max_files)]
        out: List[Tuple[str, bytes]] = []
        for name in names:
            path = os.path.join(self.directory, name)
            try:
                with open(path, "rb") as fh:
                    blob = fh.read()
            except OSError:
                blob = b""
            try:
                os.unlink(path)
            except OSError:
                pass
            out.append((path, blob))
        return out


@dataclass(frozen=True)
class PublisherStatus:
    """One publisher's liveness/lag view at a point in time."""

    publisher: str
    host: str
    process: int
    tier: str
    last_seq: int
    last_t: float
    last_arrival: float
    lag_s: float
    stale: bool
    absorbed: int
    duplicates: int
    late_dropped: int
    pending: int
    retired: bool = False


class _Pub:
    """Per-publisher collector state (internal)."""

    __slots__ = (
        "publisher", "host", "process", "tier", "seen", "pending",
        "newest", "delta_states", "delta_frontier", "telemetry",
        "telemetry_seq", "last_seq", "last_t", "last_arrival",
        "absorbed", "duplicates", "late_dropped", "retired", "spans",
    )

    def __init__(self, publisher: str) -> None:
        self.publisher = publisher
        self.host = ""
        self.process = 0
        self.tier = "leaf"
        self.seen: Dict[int, float] = {}  # seq -> snapshot t (pruned at watermark)
        self.pending: Dict[int, Snapshot] = {}  # delta mode, awaiting watermark
        self.newest: Optional[Snapshot] = None  # state mode, max-seq snapshot
        self.delta_states: Optional[Dict[str, Dict[str, Any]]] = None
        self.delta_frontier = -1
        self.telemetry: List[Dict[str, Any]] = []
        self.telemetry_seq = -1
        self.last_seq = -1
        self.last_t = float("-inf")
        self.last_arrival = float("-inf")
        self.absorbed = 0
        self.duplicates = 0
        self.late_dropped = 0
        self.retired = False
        # publisher-side trace-span contexts from snapshot headers (wire
        # v2), newest last, bounded — export_perfetto's fleet mode reads
        # them to draw publish instants + flow links per publisher track
        self.spans: List[Dict[str, Any]] = []


class FleetCollector:
    """Folds published snapshots into one fleet view (see module docs).

    ``template`` — a metric or :class:`~metrics_tpu.collections.
    MetricCollection` structurally identical to what publishers snapshot;
    its per-leaf reducers (``merge_states``) ARE the fold. ``None`` for a
    telemetry-only collector. ``recorder`` (default: the process default)
    receives the windowed liveness/backlog/fold-error series each poll
    when enabled."""

    def __init__(
        self,
        directory: Optional[str] = None,
        template: Optional[Any] = None,
        late_window_s: float = 30.0,
        stale_after_s: float = 10.0,
        recorder: Optional[Any] = None,
        clock: Optional[Callable[[], float]] = None,
        name: str = "collector",
        max_skew_s: float = 30.0,
    ) -> None:
        if late_window_s < 0:
            raise ValueError(f"late_window_s must be >= 0, got {late_window_s}")
        if stale_after_s <= 0:
            raise ValueError(f"stale_after_s must be positive, got {stale_after_s}")
        if max_skew_s < 0:
            raise ValueError(f"max_skew_s must be >= 0, got {max_skew_s}")
        self.queue = SnapshotQueue(directory) if directory is not None else None
        self.template = template
        self._template_key = states_key(template) if template is not None else None
        self._template_members = members_of(template) if template is not None else {}
        self.late_window_s = float(late_window_s)
        self.stale_after_s = float(stale_after_s)
        #: a publisher clock running AHEAD of the collector would drag the
        #: watermark forward and late-drop every honest peer; snapshot
        #: times beyond ``arrival + max_skew_s`` are clamped (and counted)
        #: before they touch the watermark or liveness accounting
        self.max_skew_s = float(max_skew_s)
        self.name = name
        self.clock = clock if clock is not None else time.time
        self._recorder = recorder
        self._lock = threading.Lock()
        self._pubs: Dict[str, _Pub] = {}
        self._max_t = float("-inf")
        self.fold_errors = 0
        self.fold_error_details: List[str] = []  # bounded ring, newest last
        self.clock_skew_clamps = 0
        self._max_clock_skew_s = 0.0  # largest ahead-of-collector skew observed
        self._reported = {"absorbed": 0, "duplicates": 0, "late_dropped": 0, "fold_errors": 0}

    # ------------------------------------------------------------------
    # ingest
    # ------------------------------------------------------------------
    @property
    def watermark(self) -> float:
        """Event-time watermark: newest snapshot time seen minus the late
        window. Snapshots at or below it are final — a straggler behind
        the watermark is counted and dropped, never folded."""
        return self._max_t - self.late_window_s

    def poll(self, max_files: Optional[int] = None, now: Optional[float] = None) -> int:
        """Consume queued snapshot files (up to ``max_files``), ingest
        each, advance the watermark fold, and feed the telemetry series.
        Returns the number of files consumed. Safe to call on a timer from
        one thread while another queries the fold."""
        if self.queue is None:
            raise ValueError("this collector was constructed without a queue directory")
        # the backlog gauge is measured BEFORE consuming: "how much work
        # was waiting when the collector woke up" is the falling-behind
        # signal — post-consume it would always read near zero and the
        # snapshot_backlog alarm could never fire
        backlog_pre = self.backlog()
        entries = self.queue.poll(max_files=max_files)
        for path, blob in entries:
            if not blob:
                self._count_fold_error(f"unreadable snapshot file {os.path.basename(path)}")
                continue
            self.ingest(blob, now=now)
        self._advance()
        self._feed_recorder(now=now, backlog=backlog_pre)
        return len(entries)

    def ingest(self, blob: bytes, now: Optional[float] = None) -> bool:
        """Ingest one raw snapshot (the transport-agnostic entry point —
        ``poll`` calls this per file; tests and benches call it directly).
        Returns True when the snapshot was absorbed, False when it was
        deduplicated, late-dropped, or counted as a fold error."""
        try:
            snap = decode_snapshot(blob)
        except WireError as err:
            self._count_fold_error(str(err))
            return False
        return self._ingest_snapshot(snap, now=now)

    MAX_PUB_SPANS = 256

    def _ingest_snapshot(self, snap: Snapshot, now: Optional[float] = None) -> bool:
        arrival = self.clock() if now is None else float(now)
        with self._lock:
            pub = self._pubs.get(snap.publisher)
            if pub is None:
                pub = self._pubs[snap.publisher] = _Pub(snap.publisher)
            if snap.host:
                pub.host = snap.host
            pub.process = snap.process
            pub.tier = snap.tier
            # liveness first: even a duplicate/late snapshot proves the
            # publisher process is alive and shipping
            pub.last_arrival = arrival
            pub.retired = False
            # clamp a fast publisher clock BEFORE it touches the watermark
            # (one skewed peer must not late-drop every honest one)
            skew = snap.t - arrival
            if skew > 0:
                self._max_clock_skew_s = max(self._max_clock_skew_s, skew)
            t_eff = snap.t
            if skew > self.max_skew_s:
                t_eff = arrival + self.max_skew_s
                self.clock_skew_clamps += 1
            if snap.seq in pub.seen or snap.seq in pub.pending or (
                snap.mode == "delta" and snap.seq <= pub.delta_frontier
            ):
                pub.duplicates += 1
                return False
            if t_eff <= self.watermark:
                pub.late_dropped += 1
                return False
            if snap.states is not None and not self._states_compatible(snap):
                return False
            pub.seen[snap.seq] = t_eff
            pub.last_seq = max(pub.last_seq, snap.seq)
            pub.last_t = max(pub.last_t, t_eff)
            self._max_t = max(self._max_t, t_eff)
            if snap.span is not None:
                # wire-v2 trace stitching: keep the publisher's publish-time
                # span context for the fleet Perfetto timeline
                pub.spans.append({"t": t_eff, "seq": snap.seq, **snap.span})
                if len(pub.spans) > self.MAX_PUB_SPANS:
                    pub.spans = pub.spans[-self.MAX_PUB_SPANS :]
            if snap.telemetry and snap.seq > pub.telemetry_seq:
                # telemetry payloads are cumulative counters: newest wins
                # per publisher, whatever the states mode. Each payload is
                # annotated with its publisher id — several publishers on
                # one host share a process index, and the federated
                # Prometheus view needs a disambiguating label per rank
                pub.telemetry = [
                    p if p.get("publisher") else {**p, "publisher": snap.publisher}
                    for p in snap.telemetry
                ]
                pub.telemetry_seq = snap.seq
            if snap.mode == "delta" and snap.states is not None:
                # deltas hold until the watermark passes them so the fold
                # runs in sequence order whatever the arrival order
                pub.pending[snap.seq] = snap
            elif snap.states is not None:
                if pub.newest is None or snap.seq > pub.newest.seq:
                    pub.newest = snap
            pub.absorbed += 1
            return True

    def _states_compatible(self, snap: Snapshot) -> bool:
        """Validate a states-carrying snapshot against the collector
        template BEFORE any leaf is folded; a mismatch is a fold error.
        Caller holds the lock."""
        if self.template is None:
            self._count_fold_error_locked(
                f"publisher {snap.publisher!r} shipped metric states but this"
                " collector has no template to fold them with"
            )
            return False
        if snap.states_key is not None and snap.states_key != self._template_key:
            self._count_fold_error_locked(
                f"publisher {snap.publisher!r} states layout disagrees with the"
                f" collector template (seq {snap.seq})"
            )
            return False
        from metrics_tpu.observability.wire import manifest_fingerprint

        ours = manifest_fingerprint()
        if snap.manifest_hash and ours and snap.manifest_hash != ours:
            self._count_fold_error_locked(
                f"publisher {snap.publisher!r} manifest fingerprint"
                f" {snap.manifest_hash} != collector {ours} (version skew)"
            )
            return False
        return True

    # ------------------------------------------------------------------
    # watermark fold
    # ------------------------------------------------------------------
    def _advance(self) -> None:
        """Fold delta snapshots the watermark has passed (in sequence
        order) and prune resolved sequence numbers."""
        with self._lock:
            wm = self.watermark
            for pub in self._pubs.values():
                ready = sorted(s for s, snap in pub.pending.items() if snap.t <= wm)
                for seq in ready:
                    snap = pub.pending.pop(seq)
                    self._fold_delta_locked(pub, snap)
                # sequence numbers at or below the watermark can never fold
                # again (any re-arrival is late-dropped first), so the dedup
                # set stays bounded by the late window
                pub.seen = {s: t for s, t in pub.seen.items() if t > wm}

    def _fold_delta_locked(self, pub: _Pub, snap: Snapshot) -> None:
        try:
            if pub.delta_states is None:
                pub.delta_states = snap.states
            else:
                pub.delta_states = self._merge_states_trees(pub.delta_states, snap.states)
            pub.delta_frontier = max(pub.delta_frontier, snap.seq)
        except Exception as err:  # noqa: BLE001 — one bad snapshot must not kill the tree
            self._count_fold_error_locked(
                f"delta fold failed for {pub.publisher!r} seq {snap.seq}: {err!r}"
            )

    def _merge_states_trees(
        self, a: Dict[str, Dict[str, Any]], b: Dict[str, Dict[str, Any]]
    ) -> Dict[str, Dict[str, Any]]:
        out: Dict[str, Dict[str, Any]] = {}
        for name, metric in self._template_members.items():
            out[name] = metric.merge_states(a[name], b[name])
        return out

    def flush_pending(self) -> None:
        """Force-fold every pending delta snapshot regardless of the
        watermark (sequence order per publisher) — the shutdown/inspection
        path when no further snapshots are expected."""
        with self._lock:
            for pub in self._pubs.values():
                for seq in sorted(pub.pending):
                    self._fold_delta_locked(pub, pub.pending.pop(seq))

    # ------------------------------------------------------------------
    # error accounting
    # ------------------------------------------------------------------
    MAX_ERROR_DETAILS = 64

    def _count_fold_error(self, detail: str) -> None:
        with self._lock:
            self._count_fold_error_locked(detail)

    def _count_fold_error_locked(self, detail: str) -> None:
        self.fold_errors += 1
        self.fold_error_details.append(detail)
        if len(self.fold_error_details) > self.MAX_ERROR_DETAILS:
            self.fold_error_details = self.fold_error_details[-self.MAX_ERROR_DETAILS :]

    # ------------------------------------------------------------------
    # views
    # ------------------------------------------------------------------
    def totals(self) -> Dict[str, int]:
        with self._lock:
            return {
                "absorbed": sum(p.absorbed for p in self._pubs.values()),
                "duplicates": sum(p.duplicates for p in self._pubs.values()),
                "late_dropped": sum(p.late_dropped for p in self._pubs.values()),
                "fold_errors": self.fold_errors,
                "clock_skew_clamps": self.clock_skew_clamps,
                "publishers": len(self._pubs),
            }

    def publisher_spans(self) -> Dict[str, List[Dict[str, Any]]]:
        """Per-publisher publish-time span contexts (wire v2 headers),
        newest last — the raw material of :func:`~metrics_tpu.
        observability.trace.export_perfetto`'s fleet mode."""
        with self._lock:
            return {name: list(p.spans) for name, p in sorted(self._pubs.items()) if p.spans}

    def backlog(self) -> int:
        """Unfolded work: queued snapshot files plus pending (in-window)
        delta snapshots."""
        with self._lock:
            pending = sum(len(p.pending) for p in self._pubs.values())
        return pending + (self.queue.backlog() if self.queue is not None else 0)

    def retire_publisher(self, publisher: str) -> bool:
        """Deregister a cleanly-shut-down publisher from liveness tracking:
        its folded contribution STAYS in the fleet view, but its lag no
        longer feeds the ``publisher_stale`` signal — a publisher that
        *said goodbye* is not a stalled one. A later snapshot from the
        same id un-retires it. Returns False for an unknown publisher."""
        with self._lock:
            p = self._pubs.get(publisher)
            if p is None:
                return False
            p.retired = True
            return True

    def publishers(self, now: Optional[float] = None) -> List[PublisherStatus]:
        """Liveness/lag per publisher, sorted by publisher id. ``lag_s``
        is collector-clock now minus the publisher's newest snapshot
        time; a non-retired publisher silent longer than ``stale_after_s``
        is ``stale`` — the ``publisher_stale`` alarm's raw data."""
        now = self.clock() if now is None else float(now)
        with self._lock:
            out = []
            for name in sorted(self._pubs):
                p = self._pubs[name]
                lag = max(0.0, now - p.last_t) if p.last_t > float("-inf") else float("inf")
                out.append(
                    PublisherStatus(
                        publisher=p.publisher,
                        host=p.host,
                        process=p.process,
                        tier=p.tier,
                        last_seq=p.last_seq,
                        last_t=p.last_t,
                        last_arrival=p.last_arrival,
                        lag_s=lag,
                        stale=(not p.retired) and lag > self.stale_after_s,
                        absorbed=p.absorbed,
                        duplicates=p.duplicates,
                        late_dropped=p.late_dropped,
                        pending=len(p.pending),
                        retired=p.retired,
                    )
                )
            return out

    # ------------------------------------------------------------------
    # the fold
    # ------------------------------------------------------------------
    def fold_states(self) -> Optional[Dict[str, Dict[str, Any]]]:
        """The global metric-state fold: one state tree per publisher
        (newest cumulative snapshot in ``"state"`` mode, the
        watermark-folded increments in ``"delta"`` mode), merged across
        publishers in sorted publisher order through the template's
        ``merge_states`` — deterministic whatever the arrival order, and
        bit-identical to a single job that saw every event (integer-exact
        reducers; float sums associate to rounding). ``None`` when no
        publisher has shipped states yet."""
        with self._lock:
            contributions: List[Tuple[str, str, Dict[str, Dict[str, Any]]]] = []
            for name in sorted(self._pubs):
                p = self._pubs[name]
                if p.newest is not None and p.newest.states is not None:
                    contributions.append((name, "newest", p.newest.states))
                if p.delta_states is not None:
                    contributions.append((name, "delta", p.delta_states))
        folded: Optional[Dict[str, Dict[str, Any]]] = None
        for pub_name, kind, tree in contributions:
            # ONE poisonous contribution (a skewed publisher absorbed
            # before the structural key existed, or a key-less snapshot)
            # must not take the whole fleet view dark forever: validate
            # the contribution's leaf structure against the template —
            # which attributes the skew to the RIGHT publisher, where a
            # failed pairwise merge could not — then count + EVICT it and
            # keep folding everyone else. The try/except is the final net
            # for same-structure merges that still raise.
            problem = self._structural_mismatch(tree)
            if problem is None:
                try:
                    folded = tree if folded is None else self._merge_states_trees(folded, tree)
                    continue
                except Exception as err:  # noqa: BLE001
                    problem = repr(err)
            self._count_fold_error(
                f"fold contribution from {pub_name!r} evicted: {problem}"
            )
            with self._lock:
                p = self._pubs.get(pub_name)
                if p is not None:
                    if kind == "newest":
                        p.newest = None
                    else:
                        p.delta_states = None
        return folded

    def _structural_mismatch(self, tree: Dict[str, Dict[str, Any]]) -> Optional[str]:
        """Compare a contribution's leaf structure (names + dtype/shape
        signatures) against the collector template; returns a description
        of the first mismatch, or ``None`` when the fold is safe."""
        if self._template_key is None:
            return "no collector template"
        from metrics_tpu.observability.wire import _leaf_key

        if set(tree) != set(self._template_key):
            return f"metric set {sorted(tree)} != template {sorted(self._template_key)}"
        for metric, leaves in tree.items():
            want = self._template_key[metric]["states"]
            if set(leaves) != set(want):
                return f"{metric!r} states {sorted(leaves)} != template {sorted(want)}"
            for name, leaf in leaves.items():
                got = _leaf_key(leaf)
                if got != want[name]:
                    return f"{metric}.{name} layout {got} != template {want[name]}"
        return None

    def fold_values(self) -> Dict[str, Any]:
        """``compute`` over the global fold: the fleet-wide metric VALUES
        (the number a dashboard wants), via each template member's pure
        ``compute_state``. Empty when there is nothing to fold.

        A fleet-tier ``read`` event rides every call when the recorder is
        enabled: fan-in (contributing publishers), fold wall time, and a
        :class:`~metrics_tpu.observability.freshness.FreshnessStamp`
        carrying the contributing snapshot-time span plus the watermark
        lag — the dashboard's exact ingest-to-visible staleness."""
        rec = self._recorder
        if rec is None:
            from metrics_tpu.observability.recorder import _DEFAULT_RECORDER as rec  # noqa: N813
        if not rec.enabled:  # fast path: the disabled fold pays one check
            return self._fold_values_impl()
        from metrics_tpu.observability.trace import span as _span

        # the fold span LINKS to each contributing publisher's publish-time
        # span (wire v2 header) — the cross-process edge perfetto draws
        with self._lock:
            links = [
                {"publisher": name, "span_id": p.spans[-1].get("span_id"), "seq": p.spans[-1].get("seq")}
                for name, p in sorted(self._pubs.items())
                if p.spans
            ]
        t0 = time.perf_counter()
        with _span("fleet_fold", recorder=rec, collector=self.name, links=links):
            out = self._fold_values_impl()
        self._record_fleet_read(rec, time.perf_counter() - t0, leaves=len(out))
        return out

    def _fold_values_impl(self) -> Dict[str, Any]:
        folded = self.fold_states()
        if folded is None:
            return {}
        out: Dict[str, Any] = {}
        for name, metric in self._template_members.items():
            try:
                out[name] = metric.compute_state(folded[name])
            except Exception as err:  # noqa: BLE001
                self._count_fold_error(f"compute over fold failed for {name!r}: {err!r}")
        return out

    def _record_fleet_read(self, rec: Any, dur_s: float, leaves: int) -> None:
        """Emit the fleet-tier read event + freshness stamp (best effort:
        telemetry must never break the fold)."""
        try:
            from metrics_tpu.observability.freshness import FreshnessStamp

            with self._lock:
                contrib = [
                    p.last_t
                    for p in self._pubs.values()
                    if (p.newest is not None or p.delta_states is not None)
                    and p.last_t > float("-inf")
                ]
                wm = self._max_t - self.late_window_s
            lag = max(0.0, self.clock() - wm) if contrib else 0.0
            stamp = FreshnessStamp(
                min_event_t=min(contrib) if contrib else None,
                max_event_t=max(contrib) if contrib else None,
                watermark_lag_s=lag,
            )
            rec.record_read(
                "fleet", None, duration_s=dur_s, leaves=leaves,
                fanin=len(contrib), freshness=stamp, collector=self.name,
            )
        except Exception:  # noqa: BLE001
            pass

    def fold_telemetry(self) -> List[Dict[str, Any]]:
        """Every publisher's newest telemetry payload list, concatenated
        in sorted publisher order — the input
        :func:`~metrics_tpu.observability.merge_payloads` merges into the
        job-wide aggregate."""
        with self._lock:
            out: List[Dict[str, Any]] = []
            for name in sorted(self._pubs):
                out.extend(self._pubs[name].telemetry)
            return out

    def merged_telemetry(self) -> Optional[Dict[str, Any]]:
        """The fleet-wide telemetry aggregate (``merge_payloads`` over
        :meth:`fold_telemetry`), or ``None`` when no publisher shipped
        telemetry."""
        payloads = self.fold_telemetry()
        if not payloads:
            return None
        from metrics_tpu.observability.aggregate import merge_payloads

        return merge_payloads(payloads)

    # ------------------------------------------------------------------
    # hierarchy
    # ------------------------------------------------------------------
    def publish_fold(self, sink: SnapshotSink, t: Optional[float] = None) -> Optional[str]:
        """Re-publish this collector's global fold as ONE snapshot into a
        parent tier's sink — the merge-tree edge (host collector -> rack
        sink -> global collector), every tier running the same fold.
        Cumulative (``"state"`` mode) by construction. Returns the path
        written, or ``None`` when there is nothing to publish yet."""
        folded = self.fold_states()
        payloads = self.fold_telemetry()
        if folded is None and not payloads:
            return None
        return sink.publish(
            states=folded,
            states_template=self.template if folded is not None else None,
            telemetry=payloads or None,
            mode="state",
            t=t,
        )

    # ------------------------------------------------------------------
    # telemetry feed + Prometheus
    # ------------------------------------------------------------------
    def _feed_recorder(self, now: Optional[float] = None, backlog: Optional[int] = None) -> None:
        rec = self._recorder
        if rec is None:
            from metrics_tpu.observability.recorder import _DEFAULT_RECORDER as rec  # noqa: N813
        if not rec.enabled:
            return
        totals = self.totals()
        deltas = {k: totals[k] - self._reported[k] for k in self._reported}
        self._reported = {k: totals[k] for k in self._reported}
        statuses = self.publishers(now=now)
        lags = [s.lag_s for s in statuses if not s.retired and s.lag_s != float("inf")]
        try:
            rec.record_fleet_poll(
                absorbed=deltas["absorbed"],
                duplicates=deltas["duplicates"],
                late_dropped=deltas["late_dropped"],
                fold_errors=deltas["fold_errors"],
                backlog=self.backlog() if backlog is None else backlog,
                max_lag_s=max(lags) if lags else 0.0,
                publishers=totals["publishers"],
            )
        except Exception:  # noqa: BLE001 — telemetry must never break the fold
            pass

    def prometheus_lines(self, now: Optional[float] = None) -> List[str]:
        """The collector's own families: per-publisher liveness/lag/seq
        plus the snapshot outcome counters, backlog, and watermark age."""
        from metrics_tpu.observability.exporters import _labels

        now_f = self.clock() if now is None else float(now)
        statuses = self.publishers(now=now_f)
        totals = self.totals()
        lines = [
            "# HELP metrics_tpu_fleet_publisher_up Publisher liveness (1 = shipped a snapshot within stale_after_s).",
            "# TYPE metrics_tpu_fleet_publisher_up gauge",
        ]
        for s in statuses:
            lines.append(
                f"metrics_tpu_fleet_publisher_up{_labels(publisher=s.publisher, host=s.host)}"
                f" {0 if s.stale else 1}"
            )
        lines.append("# HELP metrics_tpu_fleet_publisher_lag_seconds Now minus the publisher's newest snapshot time.")
        lines.append("# TYPE metrics_tpu_fleet_publisher_lag_seconds gauge")
        for s in statuses:
            if s.lag_s != float("inf"):
                lines.append(
                    f"metrics_tpu_fleet_publisher_lag_seconds"
                    f"{_labels(publisher=s.publisher, host=s.host)} {s.lag_s:g}"
                )
        lines.append("# HELP metrics_tpu_fleet_publisher_last_seq Newest sequence number absorbed per publisher.")
        lines.append("# TYPE metrics_tpu_fleet_publisher_last_seq gauge")
        for s in statuses:
            lines.append(
                f"metrics_tpu_fleet_publisher_last_seq"
                f"{_labels(publisher=s.publisher, host=s.host)} {s.last_seq}"
            )
        lines.append("# HELP metrics_tpu_fleet_snapshots_total Snapshots by ingest outcome (absorbed|duplicate|late_dropped|fold_error; disjoint).")
        lines.append("# TYPE metrics_tpu_fleet_snapshots_total counter")
        for outcome, key in (
            ("absorbed", "absorbed"),
            ("duplicate", "duplicates"),
            ("late_dropped", "late_dropped"),
            ("fold_error", "fold_errors"),
        ):
            lines.append(
                f"metrics_tpu_fleet_snapshots_total{_labels(outcome=outcome)} {totals[key]}"
            )
        lines.append("# HELP metrics_tpu_fleet_clock_skew_seconds Largest ahead-of-collector publisher clock skew observed.")
        lines.append("# TYPE metrics_tpu_fleet_clock_skew_seconds gauge")
        lines.append(f"metrics_tpu_fleet_clock_skew_seconds {self._max_clock_skew_s:g}")
        lines.append("# HELP metrics_tpu_fleet_clock_skew_clamps_total Snapshot times clamped to now + max_skew_s before watermark accounting.")
        lines.append("# TYPE metrics_tpu_fleet_clock_skew_clamps_total counter")
        lines.append(f"metrics_tpu_fleet_clock_skew_clamps_total {totals['clock_skew_clamps']}")
        lines.append("# HELP metrics_tpu_fleet_backlog Unfolded snapshots (queued files + in-window pending deltas).")
        lines.append("# TYPE metrics_tpu_fleet_backlog gauge")
        lines.append(f"metrics_tpu_fleet_backlog {self.backlog()}")
        lines.append("# HELP metrics_tpu_fleet_publishers Distinct publishers ever seen.")
        lines.append("# TYPE metrics_tpu_fleet_publishers gauge")
        lines.append(f"metrics_tpu_fleet_publishers {totals['publishers']}")
        if self._max_t > float("-inf"):
            lines.append("# HELP metrics_tpu_fleet_watermark_age_seconds Now minus the event-time watermark.")
            lines.append("# TYPE metrics_tpu_fleet_watermark_age_seconds gauge")
            lines.append(f"metrics_tpu_fleet_watermark_age_seconds {max(0.0, now_f - self.watermark):g}")
        return lines

    def fold_value_lines(self) -> List[str]:
        """Scalar fleet-wide metric values as a Prometheus family (vector
        results are skipped — exposition samples are scalars)."""
        from metrics_tpu.observability.exporters import _labels

        values = self.fold_values()
        lines: List[str] = []
        scalars = []
        for name, value in sorted(values.items()):
            try:
                scalars.append((name, float(value)))
            except (TypeError, ValueError):
                continue
        if scalars:
            lines.append("# HELP metrics_tpu_fleet_metric_value Fleet-wide metric value computed over the global fold.")
            lines.append("# TYPE metrics_tpu_fleet_metric_value gauge")
            for name, v in scalars:
                lines.append(f"metrics_tpu_fleet_metric_value{_labels(metric=name)} {v:g}")
        return lines

    def render_prometheus(
        self,
        now: Optional[float] = None,
        include_collector_families: bool = True,
        include_fold_values: bool = False,
    ) -> str:
        """The federated Prometheus page: the merged telemetry rendered
        through :func:`~metrics_tpu.observability.render_prometheus`
        (every per-rank family carries ``process`` AND ``host`` labels;
        the totals are the global fold), plus the collector's own fleet
        families and — optionally — the fleet-wide metric values.

        The fold-derived portion is deterministic for a given absorbed
        multiset whatever the arrival order (the fold-determinism
        contract); the collector families count arrival bookkeeping, so
        ``include_collector_families=False`` gives the strictly
        deterministic page."""
        from metrics_tpu.observability.exporters import render_prometheus

        merged = self.merged_telemetry()
        parts: List[str] = []
        if merged is not None:
            parts.append(render_prometheus(aggregate=merged))
        if include_fold_values:
            lines = self.fold_value_lines()
            if lines:
                parts.append("\n".join(lines) + "\n")
        if include_collector_families:
            parts.append("\n".join(self.prometheus_lines(now=now)) + "\n")
        return "".join(parts)
