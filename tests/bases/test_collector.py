"""Fleet collector tests (ISSUE 13 tentpole + fold-determinism satellite):
the directory-queue transport, exactly-once dedup, the bounded late window
with watermark, per-publisher liveness/retirement, delta-mode sequence-
order folding, hierarchical (merge-tree) fan-in, the fold_error boundary,
the federated Prometheus view, the recorder/health wiring for the three
fleet alarm classes, and the arrival-order-independence contract: the
same snapshot multiset folded in any order (including a duplicate and a
late arrival) yields bit-identical collector state, a byte-identical
Prometheus exposition, and matches single-job ``aggregate_across_hosts``
/ sequential accumulation on the same events."""
import itertools
import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

from metrics_tpu import MeanSquaredError, MetricCollection
from metrics_tpu.aggregation import SumMetric
from metrics_tpu.classification import Accuracy
from metrics_tpu.observability import (
    FleetCollector,
    HealthMonitor,
    PeriodicExporter,
    SnapshotSink,
    counter_payload,
    default_rules,
    encode_snapshot,
    get_recorder,
    merge_payloads,
    render_prometheus,
    snapshot_states,
)
from metrics_tpu.observability.collector import SnapshotQueue
from metrics_tpu.observability.recorder import (
    SERIES_COLLECTOR_BACKLOG,
    SERIES_FOLD_ERRORS,
    SERIES_PUBLISHER_LAG,
)
from metrics_tpu.observability.timeseries import TimeSeriesRegistry

T0 = 1_000_000.0


def make_collection():
    return MetricCollection({"acc": Accuracy(num_classes=2), "mse": MeanSquaredError()})


def int_batches(seed, n_batches, bs=16):
    """Integer-exact traffic: sum/count reducers fold bit-identically."""
    rng = np.random.RandomState(seed)
    return [
        (
            jnp.asarray(rng.randint(0, 2, bs), jnp.int32),
            jnp.asarray(rng.randint(0, 2, bs), jnp.int32),
        )
        for _ in range(n_batches)
    ]


def publisher_snapshots(pub_index, n_snaps, mode="state", bs=16, telemetry=None):
    """Encoded snapshots of one publisher's evolving collection. In state
    mode each snapshot is cumulative; in delta mode the collection resets
    after each publish."""
    col = make_collection()
    blobs = []
    for seq, (preds, target) in enumerate(int_batches(100 + pub_index, n_snaps, bs)):
        col.update(preds, target)
        blobs.append(
            encode_snapshot(
                publisher=f"pub{pub_index}",
                seq=seq,
                t=T0 + seq,
                host=f"h{pub_index}",
                process=pub_index,
                mode=mode,
                states=snapshot_states(col),
                states_template=col,
                telemetry=telemetry,
            )
        )
        if mode == "delta":
            col.reset()
    return blobs


def assert_states_equal(a, b):
    assert set(a) == set(b)
    for m in a:
        assert set(a[m]) == set(b[m])
        for leaf in a[m]:
            assert np.array_equal(np.asarray(a[m][leaf]), np.asarray(b[m][leaf])), (m, leaf)


# ---------------------------------------------------------------------------
# transport
# ---------------------------------------------------------------------------

class TestTransport:
    def test_sink_writes_atomic_files_queue_consumes_once(self, tmp_path):
        sink = SnapshotSink(str(tmp_path), publisher="p0", host="h", process=0)
        sink.publish(telemetry={"process": 0})
        sink.publish(telemetry={"process": 0})
        queue = SnapshotQueue(str(tmp_path))
        assert queue.backlog() == 2
        entries = queue.poll()
        assert len(entries) == 2
        assert queue.backlog() == 0 and queue.poll() == []
        # no tmp litter
        assert all(not n.startswith(".") for n in os.listdir(tmp_path))

    def test_poll_cap_drains_oldest_first(self, tmp_path):
        sink = SnapshotSink(str(tmp_path), publisher="p0")
        for _ in range(5):
            sink.publish(telemetry={"process": 0})
        queue = SnapshotQueue(str(tmp_path))
        first = queue.poll(max_files=2)
        assert len(first) == 2 and queue.backlog() == 3
        # oldest sequence numbers come out first
        seqs = [json.loads(blob)["seq"] for _, blob in first]
        assert seqs == [0, 1]

    def test_sink_seq_monotonic_and_restart_offset(self, tmp_path):
        sink = SnapshotSink(str(tmp_path), publisher="p0")
        sink.publish(telemetry={"process": 0})
        restarted = SnapshotSink(str(tmp_path), publisher="p0", seq_start=100)
        restarted.publish(telemetry={"process": 0})
        seqs = sorted(json.loads(b)["seq"] for _, b in SnapshotQueue(str(tmp_path)).poll())
        assert seqs == [0, 100]

    def test_republish_last_is_byte_identical_dup(self, tmp_path):
        sink = SnapshotSink(str(tmp_path), publisher="p0")
        assert sink.republish_last() is None
        sink.publish(telemetry={"process": 0})
        dup = sink.republish_last()
        assert dup is not None and dup != sink.last_path
        blobs = [b for _, b in SnapshotQueue(str(tmp_path)).poll()]
        assert len(blobs) == 2 and blobs[0] == blobs[1]


# ---------------------------------------------------------------------------
# state-mode folding + single-job parity
# ---------------------------------------------------------------------------

class TestStateModeFold:
    def test_fold_matches_single_job_bit_identical(self, tmp_path):
        collector = FleetCollector(str(tmp_path), template=make_collection())
        single = make_collection()
        for p in range(3):
            col = make_collection()
            sink = SnapshotSink(str(tmp_path), publisher=f"pub{p}", host=f"h{p}", process=p)
            for preds, target in int_batches(p, 4):
                col.update(preds, target)
                single.update(preds, target)
            sink.publish(states=snapshot_states(col), states_template=col, t=T0)
        collector.poll(now=T0)
        folded = collector.fold_states()
        # collector fold == merge_states fold of the three publisher
        # states == the states a single job accumulating all events holds
        # (integer-exact sum/count reducers)
        expected = snapshot_states(single)
        assert_states_equal(folded, expected)
        vals = collector.fold_values()
        singles = single.compute()
        for k in singles:
            assert float(vals[k]) == pytest.approx(float(singles[k]))

    def test_newest_sequence_wins_per_publisher(self, tmp_path):
        collector = FleetCollector(str(tmp_path), template=make_collection())
        blobs = publisher_snapshots(0, 5)
        for blob in blobs:
            collector.ingest(blob, now=T0)
        # cumulative: folding all five == decoding only the newest
        fresh = FleetCollector(template=make_collection())
        fresh.ingest(blobs[-1], now=T0)
        assert_states_equal(collector.fold_states(), fresh.fold_states())

    def test_telemetry_fold_matches_merge_payloads(self):
        rec = get_recorder()
        rec.reset()
        rec.enable()
        try:
            m = SumMetric()
            m.update(jnp.asarray([1.0]))
            payloads = []
            collector = FleetCollector(template=None)
            for p in range(3):
                payload = counter_payload(rec)
                payload["process"] = p
                payloads.append(payload)
                collector.ingest(
                    encode_snapshot(
                        publisher=f"pub{p}", seq=0, t=T0, process=p, telemetry=payload
                    ),
                    now=T0,
                )
            merged = collector.merged_telemetry()
            # the collector annotates payloads with their publisher id (the
            # federated page's disambiguating label); strip it to compare
            # against the single-job merge of the SAME payloads
            expected = merge_payloads(payloads)
            for fam in ("call_counts", "sync_totals", "footprint_hwm", "call_times"):
                assert merged[fam] == expected[fam]
            assert merged["world_size"] == expected["world_size"]
        finally:
            rec.disable()
            rec.reset()


# ---------------------------------------------------------------------------
# dedup + late window
# ---------------------------------------------------------------------------

class TestDedupAndLateness:
    def test_duplicates_folded_exactly_once(self, tmp_path):
        sink = SnapshotSink(str(tmp_path), publisher="p0")
        col = make_collection()
        col.update(*int_batches(0, 1)[0])
        sink.publish(states=snapshot_states(col), states_template=col, t=T0)
        sink.republish_last()
        sink.republish_last()
        collector = FleetCollector(str(tmp_path), template=make_collection())
        collector.poll(now=T0)
        totals = collector.totals()
        assert totals["absorbed"] == 1 and totals["duplicates"] == 2
        assert_states_equal(collector.fold_states(), snapshot_states(col))

    def test_post_watermark_straggler_counted_and_dropped(self):
        collector = FleetCollector(template=make_collection(), late_window_s=5.0)
        fresh = publisher_snapshots(0, 1)[0]
        # a fresh snapshot advances the watermark to T0 - 5
        collector.ingest(fresh, now=T0)
        col = make_collection()
        col.update(*int_batches(1, 1)[0])
        straggler = encode_snapshot(
            publisher="pub9", seq=0, t=T0 - 30.0, states=snapshot_states(col), states_template=col
        )
        assert not collector.ingest(straggler, now=T0)
        assert collector.totals()["late_dropped"] == 1
        # the straggler contributed nothing to the fold
        ref = FleetCollector(template=make_collection())
        ref.ingest(fresh, now=T0)
        assert_states_equal(collector.fold_states(), ref.fold_states())

    def test_in_window_late_arrival_folds(self):
        collector = FleetCollector(template=make_collection(), late_window_s=60.0)
        blobs = publisher_snapshots(0, 3)
        collector.ingest(blobs[2], now=T0)  # newest first
        collector.ingest(blobs[0], now=T0)  # older, but inside the window
        assert collector.totals()["absorbed"] == 2
        assert collector.totals()["late_dropped"] == 0


# ---------------------------------------------------------------------------
# delta mode
# ---------------------------------------------------------------------------

class TestDeltaMode:
    def test_delta_fold_in_seq_order_any_arrival(self):
        blobs = publisher_snapshots(0, 4, mode="delta")
        single = make_collection()
        for preds, target in int_batches(100, 4):
            single.update(preds, target)
        results = []
        # the late window must cover the snapshots' timestamp spread (3s
        # here): arrival-order independence is only promised for snapshots
        # the watermark has not passed — a window narrower than the spread
        # legitimately drops stragglers when newer timestamps arrive first
        for order in ([0, 1, 2, 3], [3, 1, 0, 2]):
            collector = FleetCollector(template=make_collection(), late_window_s=10.0)
            for i in order:
                collector.ingest(blobs[i], now=T0)
            # watermark passes every delta once a fresh marker arrives
            collector.ingest(
                encode_snapshot(publisher="pub0", seq=99, t=T0 + 100.0), now=T0 + 100.0
            )
            collector._advance()
            results.append(collector.fold_states())
        assert_states_equal(results[0], results[1])
        assert_states_equal(results[0], snapshot_states(single))

    def test_flush_pending_folds_in_window_deltas(self):
        blobs = publisher_snapshots(0, 3, mode="delta")
        collector = FleetCollector(template=make_collection(), late_window_s=1e9)
        for blob in blobs:
            collector.ingest(blob, now=T0)
        assert collector.fold_states() is None  # all pending, watermark far behind
        collector.flush_pending()
        single = make_collection()
        for preds, target in int_batches(100, 3):
            single.update(preds, target)
        assert_states_equal(collector.fold_states(), snapshot_states(single))

    def test_delta_duplicate_of_folded_seq_dropped(self):
        blobs = publisher_snapshots(0, 2, mode="delta")
        collector = FleetCollector(template=make_collection(), late_window_s=0.0)
        for blob in blobs:
            collector.ingest(blob, now=T0)
        collector._advance()  # watermark == newest t, folds everything
        before = collector.fold_states()
        assert not collector.ingest(blobs[0], now=T0)
        collector.flush_pending()
        assert_states_equal(collector.fold_states(), before)
        # dropped as duplicate OR late — either way folded exactly once
        totals = collector.totals()
        assert totals["duplicates"] + totals["late_dropped"] >= 1


# ---------------------------------------------------------------------------
# fold determinism (ISSUE 13 satellite)
# ---------------------------------------------------------------------------

class TestFoldDeterminism:
    def test_any_arrival_order_bit_identical_state_and_exposition(self):
        """The acceptance pin: the same multiset — three publishers' worth
        of snapshots plus one DUPLICATE and one in-window LATE arrival —
        folded under every arrival permutation yields bit-identical folded
        leaves and a byte-identical fold-side Prometheus page."""
        rec = get_recorder()
        rec.reset()
        rec.enable()
        try:
            m = SumMetric()
            m.update(jnp.asarray([1.0]))
            base_payload = counter_payload(rec)
        finally:
            rec.disable()
            rec.reset()
        blobs = []
        for p in range(3):
            payload = dict(base_payload, process=p)
            blobs.extend(
                publisher_snapshots(p, 2, telemetry=payload)
            )
        # the "late arrival": pub0's seq-0 snapshot re-shipped — identical
        # (publisher, seq), so wherever it lands in the order it is the
        # duplicate; the older-t snapshots themselves are the in-window
        # late arrivals when a permutation delivers newer t first
        dup = blobs[0]
        items = blobs + [dup]
        pages = set()
        folds = []
        for order in itertools.islice(itertools.permutations(range(len(items))), 0, 24, 5):
            collector = FleetCollector(template=make_collection(), late_window_s=1e6)
            for i in order:
                collector.ingest(items[i], now=T0 + 10.0)
            assert collector.totals()["duplicates"] == 1
            folds.append(collector.fold_states())
            pages.add(
                collector.render_prometheus(
                    include_collector_families=False, include_fold_values=True
                )
            )
        for other in folds[1:]:
            assert_states_equal(folds[0], other)
        assert len(pages) == 1  # byte-identical exposition

    def test_fold_matches_aggregate_across_hosts_semantics(self):
        """Collector telemetry fold == merge_payloads of the same payload
        list — the single-job ``aggregate_across_hosts`` merge — family by
        family, rendered byte-identically through render_prometheus."""
        rec = get_recorder()
        rec.reset()
        rec.enable()
        try:
            m = SumMetric()
            m.update(jnp.asarray([2.0]))
            payloads = []
            for p in range(3):
                payload = counter_payload(rec)
                payload["process"] = p
                payload["publisher"] = f"pub{p}"  # pre-annotated: identical inputs
                payloads.append(payload)
            collector = FleetCollector(template=None)
            for p, payload in enumerate(payloads):
                collector.ingest(
                    encode_snapshot(publisher=f"pub{p}", seq=0, t=T0, process=p, telemetry=payload),
                    now=T0,
                )
            merged = collector.merged_telemetry()
            expected = merge_payloads(payloads)
            assert render_prometheus(aggregate=merged) == render_prometheus(aggregate=expected)
        finally:
            rec.disable()
            rec.reset()


# ---------------------------------------------------------------------------
# hierarchy (merge tree)
# ---------------------------------------------------------------------------

class TestHierarchy:
    def test_two_tier_fold_equals_flat_fold(self, tmp_path):
        single = make_collection()
        child_dirs = [tmp_path / "rack0", tmp_path / "rack1"]
        parent_dir = tmp_path / "global"
        children = []
        for rack, d in enumerate(child_dirs):
            child = FleetCollector(str(d), template=make_collection())
            for p in range(2):
                idx = rack * 2 + p
                col = make_collection()
                sink = SnapshotSink(str(d), publisher=f"pub{idx}", process=idx)
                for preds, target in int_batches(idx, 3):
                    col.update(preds, target)
                    single.update(preds, target)
                sink.publish(states=snapshot_states(col), states_template=col, t=T0)
            child.poll(now=T0)
            children.append(child)
        parent = FleetCollector(str(parent_dir), template=make_collection())
        for rack, child in enumerate(children):
            sink = SnapshotSink(str(parent_dir), publisher=f"rack{rack}", tier="rack")
            assert child.publish_fold(sink, t=T0) is not None
        parent.poll(now=T0)
        assert_states_equal(parent.fold_states(), snapshot_states(single))
        statuses = parent.publishers(now=T0)
        assert [s.tier for s in statuses] == ["rack", "rack"]

    def test_publish_fold_empty_collector_is_noop(self, tmp_path):
        collector = FleetCollector(str(tmp_path / "q"), template=make_collection())
        sink = SnapshotSink(str(tmp_path / "parent"), publisher="rack0")
        assert collector.publish_fold(sink) is None


# ---------------------------------------------------------------------------
# fold_error boundary
# ---------------------------------------------------------------------------

class TestFoldErrors:
    def test_corrupt_file_counted_and_survived(self, tmp_path):
        (tmp_path / "bad-000000000000.snap").write_bytes(b"garbage")
        sink = SnapshotSink(str(tmp_path), publisher="p0")
        col = make_collection()
        col.update(*int_batches(0, 1)[0])
        sink.publish(states=snapshot_states(col), states_template=col, t=T0)
        collector = FleetCollector(str(tmp_path), template=make_collection())
        collector.poll(now=T0)
        assert collector.totals()["fold_errors"] == 1
        assert collector.totals()["absorbed"] == 1
        assert collector.fold_error_details

    def test_states_without_template_is_fold_error(self):
        collector = FleetCollector(template=None)
        blob = publisher_snapshots(0, 1)[0]
        assert not collector.ingest(blob, now=T0)
        assert collector.totals()["fold_errors"] == 1

    def test_layout_skew_is_fold_error(self):
        collector = FleetCollector(
            template=MetricCollection({"acc": Accuracy(num_classes=2)})
        )
        blob = publisher_snapshots(0, 1)[0]  # acc+mse layout
        assert not collector.ingest(blob, now=T0)
        assert collector.totals()["fold_errors"] == 1
        assert "layout" in collector.fold_error_details[-1]

    def test_future_schema_is_fold_error(self):
        collector = FleetCollector(template=make_collection())
        doc = json.loads(publisher_snapshots(0, 1)[0].decode())
        doc["schema"] = 99
        assert not collector.ingest(json.dumps(doc).encode(), now=T0)
        assert collector.totals()["fold_errors"] == 1

    def test_shape_skew_refused_at_ingest(self):
        """A same-class publisher whose config changes a state's SHAPE
        (the fold-poisoning hazard) is refused by the structural key
        before any leaf folds."""
        from metrics_tpu.classification import ConfusionMatrix

        collector = FleetCollector(
            template=MetricCollection({"cm": ConfusionMatrix(num_classes=3)})
        )
        skew = MetricCollection({"cm": ConfusionMatrix(num_classes=5)})
        skew.update(jnp.asarray([1, 0]), jnp.asarray([1, 1]))
        blob = encode_snapshot(
            publisher="pub0", seq=0, t=T0, states=snapshot_states(skew), states_template=skew
        )
        assert not collector.ingest(blob, now=T0)
        assert collector.totals()["fold_errors"] == 1
        assert collector.fold_states() is None

    def test_poisonous_keyless_contribution_evicted_not_fatal(self):
        """An absorbed skewed contribution (shipped WITHOUT a states_key,
        so ingest could not refuse it) must not take the fleet view dark
        forever: the fold validates each contribution structurally,
        evicts the mismatching publisher (counted, attributed), and keeps
        folding everyone else — and the error does not re-count on every
        subsequent read."""
        from metrics_tpu.classification import ConfusionMatrix

        collector = FleetCollector(template=MetricCollection({"cm": ConfusionMatrix(num_classes=3)}))
        good = MetricCollection({"cm": ConfusionMatrix(num_classes=3)})
        good.update(jnp.asarray([1, 0]), jnp.asarray([1, 1]))
        collector.ingest(
            encode_snapshot(
                publisher="good", seq=0, t=T0, states=snapshot_states(good), states_template=good
            ),
            now=T0,
        )
        skew = MetricCollection({"cm": ConfusionMatrix(num_classes=5)})
        skew.update(jnp.asarray([1, 0]), jnp.asarray([1, 1]))
        # no states_template => no states_key on the wire => absorbed
        poisoned = encode_snapshot(
            publisher="skewed", seq=0, t=T0, states=snapshot_states(skew)
        )
        assert collector.ingest(poisoned, now=T0)
        folded = collector.fold_states()
        assert folded is not None  # the view stays up
        assert_states_equal(folded, snapshot_states(good))
        assert collector.totals()["fold_errors"] == 1
        assert "skewed" in collector.fold_error_details[-1]
        # eviction is permanent: a second read neither fails nor re-counts
        assert collector.fold_states() is not None
        assert collector.totals()["fold_errors"] == 1

    def test_error_details_ring_is_bounded(self):
        collector = FleetCollector(template=None)
        for _ in range(collector.MAX_ERROR_DETAILS + 10):
            collector.ingest(b"junk", now=T0)
        assert len(collector.fold_error_details) == collector.MAX_ERROR_DETAILS


# ---------------------------------------------------------------------------
# liveness
# ---------------------------------------------------------------------------

class TestLiveness:
    def test_lag_and_staleness_with_injected_clock(self):
        now = [T0]
        collector = FleetCollector(
            template=make_collection(), stale_after_s=5.0, clock=lambda: now[0]
        )
        collector.ingest(publisher_snapshots(0, 1)[0], now=T0)
        status = collector.publishers()[0]
        # snapshot t is T0 (publisher_snapshots stamps T0+seq)
        assert not status.stale and status.lag_s == pytest.approx(0.0)
        now[0] = T0 + 10.0
        status = collector.publishers()[0]
        assert status.stale and status.lag_s == pytest.approx(10.0)

    def test_retire_publisher_clears_staleness_until_next_snapshot(self):
        now = [T0 + 10.0]
        collector = FleetCollector(
            template=make_collection(), stale_after_s=5.0, clock=lambda: now[0]
        )
        blobs = publisher_snapshots(0, 2)
        collector.ingest(blobs[0], now=T0)
        assert collector.publishers()[0].stale
        assert collector.retire_publisher("pub0")
        assert not collector.retire_publisher("unknown")
        status = collector.publishers()[0]
        assert status.retired and not status.stale
        # a later snapshot un-retires
        collector.ingest(blobs[1], now=now[0])
        assert not collector.publishers()[0].retired


# ---------------------------------------------------------------------------
# recorder / health / Prometheus wiring
# ---------------------------------------------------------------------------

@pytest.fixture
def recorder():
    rec = get_recorder()
    rec.reset()
    rec.enable()
    try:
        yield rec
    finally:
        rec.disable()
        rec.detach_timeseries()
        rec.reset()


class TestObservabilityWiring:
    def test_poll_feeds_fleet_series_and_totals(self, tmp_path, recorder):
        recorder.attach_timeseries(bucket_seconds=1.0, n_buckets=16, sketch_capacity=32)
        sink = SnapshotSink(str(tmp_path), publisher="p0")
        sink.publish(telemetry={"process": 0}, t=T0)
        sink.republish_last()
        (tmp_path / "bad-000000000099.snap").write_bytes(b"junk")
        collector = FleetCollector(str(tmp_path), template=None, recorder=recorder)
        collector.poll(now=T0)
        totals = recorder.fleet_totals()
        assert totals["absorbed"] == 1
        assert totals["duplicates"] == 1
        assert totals["fold_errors"] == 1
        ts = recorder.timeseries
        assert ts.get(SERIES_COLLECTOR_BACKLOG).count(None) == 1
        assert ts.get(SERIES_PUBLISHER_LAG).count(None) == 1
        assert ts.get(SERIES_FOLD_ERRORS).total(None) == 1.0

    def test_fleet_totals_ride_counter_payload_and_prometheus(self, recorder):
        recorder.record_fleet_poll(
            absorbed=5, duplicates=1, late_dropped=2, fold_errors=1, backlog=7,
            max_lag_s=3.5, publishers=3,
        )
        payload = counter_payload(recorder)
        assert payload["fleet_totals"]["absorbed"] == 5
        assert payload["fleet_totals"]["max_backlog"] == 7
        merged = merge_payloads([payload, payload])
        assert merged["fleet_totals"]["absorbed"] == 10  # extensive: summed
        assert merged["fleet_totals"]["max_backlog"] == 7  # gauge: maxed
        page = render_prometheus(recorder)
        assert 'metrics_tpu_fleet_ingest_total{outcome="absorbed"} 5' in page
        assert 'metrics_tpu_fleet_backlog_snapshots{window="max"} 7' in page
        # mixed-fleet identity: an old payload without the family merges clean
        old = {"process": 1}
        merged = merge_payloads([old, payload])
        assert merged["fleet_totals"]["absorbed"] == 5

    def test_three_fleet_alarm_classes_fire_and_clear(self):
        """publisher_stale / snapshot_backlog / fold_error each trip on a
        synthetic fault window and clear once the window rolls past —
        driven end-to-end (real subprocesses) by examples/fleet_collector.py
        in the CI smoke leg."""
        reg = TimeSeriesRegistry(bucket_seconds=1.0, n_buckets=64, sketch_capacity=32)
        monitor = HealthMonitor(
            default_rules(
                window_s=5.0,
                publisher_lag_limit_s=4.0,
                backlog_limit=10,
                fold_errors_per_window=1,
            ),
            registry=reg,
        )
        fleet_alarms = {"publisher_stale", "snapshot_backlog", "fold_error"}
        # healthy phase
        for i in range(3):
            reg.observe(SERIES_PUBLISHER_LAG, 0.5, t=T0 + i)
            reg.observe(SERIES_COLLECTOR_BACKLOG, 2, t=T0 + i)
        snap = monitor.evaluate(now=T0 + 3)
        assert not {a.name for a in snap.firing} & fleet_alarms
        # fault phase: a stalled publisher, a pile-up, a corrupt snapshot
        reg.observe(SERIES_PUBLISHER_LAG, 9.0, t=T0 + 4)
        reg.observe(SERIES_COLLECTOR_BACKLOG, 40, t=T0 + 4)
        reg.observe(SERIES_FOLD_ERRORS, 1, t=T0 + 4, kind="counter")
        snap = monitor.evaluate(now=T0 + 5)
        assert fleet_alarms <= {a.name for a in snap.firing}
        assert snap.status == "critical"  # fold_error is critical
        # recovery: the window rolls past the fault
        for i in range(6, 12):
            reg.observe(SERIES_PUBLISHER_LAG, 0.5, t=T0 + i)
            reg.observe(SERIES_COLLECTOR_BACKLOG, 2, t=T0 + i)
        snap = monitor.evaluate(now=T0 + 12)
        assert not {a.name for a in snap.firing} & fleet_alarms
        assert set(monitor.fired_and_cleared()) >= fleet_alarms

    def test_collector_prometheus_page_families(self, tmp_path, recorder):
        sink = SnapshotSink(str(tmp_path), publisher="p0", host="hostA")
        col = make_collection()
        col.update(*int_batches(0, 1)[0])
        sink.publish(
            states=snapshot_states(col), states_template=col,
            telemetry=counter_payload(recorder), t=T0,
        )
        collector = FleetCollector(str(tmp_path), template=make_collection())
        collector.poll(now=T0)
        page = collector.render_prometheus(now=T0, include_fold_values=True)
        assert 'metrics_tpu_fleet_publisher_up{publisher="p0",host="hostA"} 1' in page
        assert 'metrics_tpu_fleet_snapshots_total{outcome="absorbed"} 1' in page
        assert 'metrics_tpu_fleet_metric_value{metric="acc"}' in page
        # the merged-telemetry portion carries host AND publisher labels
        assert 'publisher="p0"' in page

    def test_periodic_exporter_publishes_heartbeat_snapshots(self, tmp_path, recorder):
        col = make_collection()
        col.update(*int_batches(0, 1)[0])
        sink = SnapshotSink(str(tmp_path / "q"), publisher="svc0")
        exporter = PeriodicExporter(
            interval_s=30.0,
            snapshot_sink=sink,
            states_fn=lambda: col,
            recorder=recorder,
        )
        exporter.export_once()
        exporter.export_once()  # idle tick still heartbeats
        collector = FleetCollector(str(tmp_path / "q"), template=make_collection())
        collector.poll()
        totals = collector.totals()
        assert totals["absorbed"] == 2
        assert_states_equal(collector.fold_states(), snapshot_states(col))
        assert collector.fold_telemetry()  # counter payload rode along

    def test_periodic_exporter_dict_states_fn_carries_template_key(self, tmp_path, recorder):
        """A states_fn returning a bare dict must not bypass collector
        layout validation: the explicit states_template supplies the
        structural key on the wire."""
        from metrics_tpu.observability import decode_snapshot, states_key
        from metrics_tpu.observability.collector import SnapshotQueue

        col = make_collection()
        col.update(*int_batches(0, 1)[0])
        sink = SnapshotSink(str(tmp_path / "q"), publisher="svc0")
        exporter = PeriodicExporter(
            interval_s=30.0,
            snapshot_sink=sink,
            states_fn=lambda: snapshot_states(col),
            states_template=col,
            recorder=recorder,
        )
        exporter.export_once()
        (_, blob), = SnapshotQueue(str(tmp_path / "q")).poll()
        assert decode_snapshot(blob).states_key == states_key(col)
