"""tracelint engine: file contexts, suppression pragmas, and the run loop.

Stdlib-only (ast/pathlib/re): the scripts load this package standalone so a
lint run never pays the jax import. Rules receive a :class:`FileContext`
(parsed tree + import-alias maps) and yield :class:`Violation` records; the
engine drops violations whose source line carries a
``# tracelint: disable=RULE-ID`` pragma and hands the rest to the baseline
partitioner.
"""
from __future__ import annotations

import ast
import pathlib
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

#: the package whose invariants the rules encode; relpaths are computed
#: against this directory so path-scoped rules (TL-COLLECTIVE, TL-PRINT)
#: stay stable no matter where the checkout lives
PACKAGE_NAME = "metrics_tpu"

_PRAGMA_RE = re.compile(r"#\s*tracelint:\s*disable=([A-Za-z0-9_\-,\s]+)")
_FILE_PRAGMA_RE = re.compile(r"#\s*tracelint:\s*disable-file=([A-Za-z0-9_\-,\s]+)")


def suppressed_rules(line_text: str) -> Set[str]:
    """Rule ids disabled by a ``# tracelint: disable=...`` pragma on a line.

    Ids are comma-separated and case-insensitive; ``all`` disables every
    rule. Text after the id list (a justification) is permitted:
    ``# tracelint: disable=TL-TRACE — eager-only guard``.
    """
    match = _PRAGMA_RE.search(line_text)
    if not match:
        return set()
    return {tok.strip().upper() for tok in match.group(1).split(",") if tok.strip()}


def file_suppressed_rules(lines: Sequence[str], tree: ast.Module) -> Set[str]:
    """Rule ids disabled file-wide by ``# tracelint: disable-file=...``.

    Only the module docstring line region is honored (the header lines up to
    and including the docstring statement, or the comment block preceding the
    first statement) — a file-wide waiver is a visible, top-of-file decision,
    never something buried mid-module. ``all`` disables every rule.
    """
    if tree.body:
        first = tree.body[0]
        is_docstring = (
            isinstance(first, ast.Expr)
            and isinstance(first.value, ast.Constant)
            and isinstance(first.value.value, str)
        )
        last_line = (getattr(first, "end_lineno", first.lineno) or first.lineno) if is_docstring else max(
            first.lineno - 1, 0
        )
    else:
        last_line = len(lines)
    rules: Set[str] = set()
    for text in lines[:last_line]:
        match = _FILE_PRAGMA_RE.search(text)
        if match:
            rules.update(tok.strip().upper() for tok in match.group(1).split(",") if tok.strip())
    return rules


def _dotted_chain(node: ast.AST) -> List[str]:
    """``jax.numpy`` -> ["jax", "numpy"]; [] when not a pure Name/Attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return []


@dataclass(frozen=True)
class Violation:
    """One rule finding, addressed by package-relative path.

    ``snippet`` (the stripped source line) — not the line number — is the
    stable half of the baseline key, so unrelated edits above a
    grandfathered violation don't invalidate the baseline.
    """

    rule: str
    path: str
    line: int
    col: int
    message: str
    snippet: str

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.snippet)

    def to_dict(self) -> Dict[str, object]:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "snippet": self.snippet,
        }

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


class FileContext:
    """Parsed view of one source file handed to every rule."""

    def __init__(self, path: Optional[pathlib.Path], relpath: str, source: str) -> None:
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=relpath)
        self._alias_maps: Optional[Dict[str, Set[str]]] = None
        self._member_maps: Optional[Dict[str, Dict[str, str]]] = None
        self._file_suppressed: Optional[Set[str]] = None

    # ------------------------------------------------------------------
    # import-alias maps (lazy; shared by several rules)
    # ------------------------------------------------------------------
    def _aliases(self) -> Dict[str, Set[str]]:
        if self._alias_maps is not None:
            return self._alias_maps
        numpy: Set[str] = set()
        jnp: Set[str] = set()
        jax_names: Set[str] = set()
        lax: Set[str] = set()
        warnings_mod: Set[str] = set()
        warn_fns: Set[str] = set()
        lax_collectives: Set[str] = set()
        process_allgather: Set[str] = set()
        jnp_members: Dict[str, str] = {}
        numpy_members: Dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    bound = alias.asname or alias.name.split(".")[0]
                    if alias.name == "numpy":
                        numpy.add(bound)
                    elif alias.name == "jax.numpy" and alias.asname:
                        jnp.add(alias.asname)
                    elif alias.name == "jax":
                        jax_names.add(bound)
                    elif alias.name == "jax.lax" and alias.asname:
                        lax.add(alias.asname)
                    elif alias.name == "warnings":
                        warnings_mod.add(bound)
            elif isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    bound = alias.asname or alias.name
                    if node.module == "jax" and alias.name == "numpy":
                        jnp.add(bound)
                    elif node.module == "jax" and alias.name == "lax":
                        lax.add(bound)
                    elif node.module == "numpy":
                        # direct-member imports (`from numpy import asarray`)
                        # are host pullers at the call site; record bound ->
                        # original so rules can key on the member name
                        numpy_members[bound] = alias.name
                    elif node.module == "jax.numpy":
                        jnp_members[bound] = alias.name
                    elif node.module == "warnings" and alias.name == "warn":
                        warn_fns.add(bound)
                    elif node.module == "jax.lax":
                        lax_collectives.add(bound)
                    elif node.module and "multihost_utils" in node.module and alias.name == "process_allgather":
                        process_allgather.add(bound)
        # simple same-file rebindings (`np = jnp`, `mylax = jax.lax`): a
        # Name-to-Name or Name-to-dotted-chain assignment re-aliases the
        # module object, and every rule keyed on the original alias must
        # follow it. MODULE-LEVEL assignments only — a function-local shadow
        # (`np = jnp` inside one helper) must not re-alias `np` file-wide
        # and silently exempt every other function's `np.*` host pulls.
        # Fixed-point so chained rebindings (`a = jnp; b = a`) resolve in
        # file order regardless of statement order.
        rebinds: List[Tuple[str, object]] = []
        for node in self.tree.body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, (ast.Name, ast.Attribute))
            ):
                rebinds.append((node.targets[0].id, node.value))
        changed = True
        while changed:
            changed = False
            for bound, value in rebinds:
                chain = _dotted_chain(value)
                for names, canonical in (
                    (jnp, ["jax", "numpy"]),
                    (lax, ["jax", "lax"]),
                    (numpy, ["numpy"]),
                    (jax_names, ["jax"]),
                ):
                    if bound in names:
                        continue
                    root_match = chain and (
                        chain == canonical or (len(chain) == 1 and chain[0] in names)
                    )
                    # `x = jax.numpy` / `x = jax.lax` via a jax alias root
                    attr_match = (
                        len(chain) == 2
                        and chain[0] in jax_names
                        and ["jax", chain[1]] == canonical
                    )
                    if root_match or attr_match:
                        names.add(bound)
                        changed = True
        self._member_maps = {"jnp_members": jnp_members, "numpy_members": numpy_members}
        self._alias_maps = {
            "numpy": numpy,
            "jnp": jnp,
            "jax": jax_names,
            "lax": lax,
            "warnings": warnings_mod,
            "warn_fns": warn_fns,
            "lax_names": lax_collectives,
            "process_allgather": process_allgather,
        }
        return self._alias_maps

    @property
    def numpy_aliases(self) -> Set[str]:
        return self._aliases()["numpy"]

    @property
    def jnp_aliases(self) -> Set[str]:
        return self._aliases()["jnp"]

    @property
    def jax_aliases(self) -> Set[str]:
        return self._aliases()["jax"]

    @property
    def lax_aliases(self) -> Set[str]:
        return self._aliases()["lax"]

    @property
    def warnings_aliases(self) -> Set[str]:
        return self._aliases()["warnings"]

    @property
    def warn_fn_aliases(self) -> Set[str]:
        return self._aliases()["warn_fns"]

    @property
    def lax_from_imports(self) -> Set[str]:
        return self._aliases()["lax_names"]

    @property
    def process_allgather_aliases(self) -> Set[str]:
        return self._aliases()["process_allgather"]

    @property
    def jnp_member_imports(self) -> Dict[str, str]:
        """``from jax.numpy import concatenate [as cat]`` -> {"cat": "concatenate"}."""
        self._aliases()
        return self._member_maps["jnp_members"]

    @property
    def numpy_member_imports(self) -> Dict[str, str]:
        """``from numpy import asarray [as aa]`` -> {"aa": "asarray"}."""
        self._aliases()
        return self._member_maps["numpy_members"]

    @property
    def file_suppressed(self) -> Set[str]:
        """Rule ids waived for the whole file by a docstring-region
        ``# tracelint: disable-file=...`` pragma (``ALL`` waives every rule)."""
        if self._file_suppressed is None:
            self._file_suppressed = file_suppressed_rules(self.lines, self.tree)
        return self._file_suppressed

    # ------------------------------------------------------------------
    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def violation(self, rule_id: str, node: ast.AST, message: str) -> Violation:
        lineno = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Violation(
            rule=rule_id,
            path=self.relpath,
            line=lineno,
            col=col,
            message=message,
            snippet=self.line_text(lineno).strip(),
        )


@dataclass
class LintResult:
    """Outcome of one analyzer run (pre-baseline partitioning)."""

    violations: List[Violation] = field(default_factory=list)
    suppressed: List[Violation] = field(default_factory=list)
    n_files: int = 0
    parse_errors: List[str] = field(default_factory=list)
    #: package-relative paths of every file analyzed — lets the CLI scope
    #: baseline updates/staleness to the analyzed subset
    relpaths: List[str] = field(default_factory=list)


def default_package_root() -> pathlib.Path:
    """The ``metrics_tpu`` package directory (this file's grandparent)."""
    return pathlib.Path(__file__).resolve().parent.parent


def package_relpath(path: pathlib.Path) -> str:
    """Posix path relative to the ``metrics_tpu`` package dir when the file
    lives under one; otherwise the bare filename (test fixtures, scripts)."""
    parts = list(path.resolve().parts)
    if PACKAGE_NAME in parts:
        idx = len(parts) - 1 - parts[::-1].index(PACKAGE_NAME)
        tail = parts[idx + 1 :]
        if tail:
            return "/".join(tail)
    return path.name


def run_rules(ctx: FileContext, rules: Sequence) -> Tuple[List[Violation], List[Violation]]:
    """Run ``rules`` over one file; returns (kept, pragma-suppressed)."""
    kept: List[Violation] = []
    suppressed: List[Violation] = []
    file_disabled = ctx.file_suppressed
    for rule in rules:
        if "ALL" in file_disabled or rule.id.upper() in file_disabled:
            continue  # file-wide waiver: the rule never runs on this file
        for violation in rule.check(ctx):
            disabled = suppressed_rules(ctx.line_text(violation.line))
            if "ALL" in disabled or violation.rule.upper() in disabled:
                suppressed.append(violation)
            else:
                kept.append(violation)
    return kept, suppressed


def analyze_source(
    source: str,
    relpath: str = "<string>",
    rules: Optional[Sequence] = None,
    path: Optional[pathlib.Path] = None,
) -> Tuple[List[Violation], List[Violation]]:
    """Analyze a source string (the test-fixture entry point)."""
    from .rules import all_rules

    ctx = FileContext(path, relpath, source)
    return run_rules(ctx, rules if rules is not None else all_rules())


def iter_python_files(paths: Iterable[pathlib.Path]) -> List[pathlib.Path]:
    files: List[pathlib.Path] = []
    for p in paths:
        p = pathlib.Path(p)
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        elif p.suffix == ".py":
            files.append(p)
    return files


def analyze_paths(
    paths: Optional[Iterable[pathlib.Path]] = None,
    rules: Optional[Sequence] = None,
) -> LintResult:
    """Analyze every ``*.py`` under ``paths`` (default: the whole package)."""
    from .rules import all_rules

    if paths is None:
        paths = [default_package_root()]
    if rules is None:
        rules = all_rules()
    result = LintResult()
    for path in iter_python_files(paths):
        try:
            ctx = FileContext(path, package_relpath(path), path.read_text())
        except (SyntaxError, UnicodeDecodeError) as err:
            result.parse_errors.append(f"{path}: {err}")
            continue
        kept, suppressed = run_rules(ctx, rules)
        result.violations.extend(kept)
        result.suppressed.extend(suppressed)
        result.n_files += 1
        result.relpaths.append(ctx.relpath)
    result.violations.sort(key=lambda v: (v.path, v.line, v.rule))
    return result
