"""Modular PearsonCorrCoef — streaming per-rank moments with the
parallel-variance cross-rank merge.

Behavior parity with /root/reference/torchmetrics/regression/pearson.py:23-146:
the one reference metric with a custom cross-rank merge beyond sum/cat
(``_final_aggregation``). States use ``dist_reduce_fx=None`` (gathered and
stacked, not reduced); compute applies the merge when it sees stacked
multi-rank moments.
"""
from typing import Any, Dict

import jax
import jax.numpy as jnp

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.regression.pearson import (
    _final_aggregation,
    _pearson_corrcoef_compute,
    _pearson_corrcoef_update,
)

Array = jax.Array


class PearsonCorrCoef(Metric):
    """Computes the Pearson correlation coefficient.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([3., -0.5, 2., 7.])
        >>> preds = jnp.array([2.5, 0.0, 2., 8.])
        >>> pearson = PearsonCorrCoef()
        >>> pearson(preds, target)
        Array(0.98486954, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = None  # both -1 and 1 are optimal

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        for name in ("mean_x", "mean_y", "var_x", "var_y", "corr_xy", "n_total"):
            self.add_state(name, default=jnp.asarray(0.0), dist_reduce_fx=None)

    def _update(self, preds: Array, target: Array) -> None:
        self.mean_x, self.mean_y, self.var_x, self.var_y, self.corr_xy, self.n_total = _pearson_corrcoef_update(
            preds, target, self.mean_x, self.mean_y, self.var_x, self.var_y, self.corr_xy, self.n_total
        )

    def _compute(self) -> Array:
        if self.mean_x.ndim == 1 and self.mean_x.shape[0] > 1:
            # states were gathered (stacked) across ranks — merge the moments
            var_x, var_y, corr_xy, n_total = _final_aggregation(
                self.mean_x, self.mean_y, self.var_x, self.var_y, self.corr_xy, self.n_total
            )
        else:
            var_x, var_y, corr_xy, n_total = self.var_x, self.var_y, self.corr_xy, self.n_total
        return _pearson_corrcoef_compute(var_x, var_y, corr_xy, n_total)

    def merge_states(self, a: Dict[str, Array], b: Dict[str, Array]) -> Dict[str, Array]:
        """Stack the two ranks' moments; compute() applies _final_aggregation."""
        return {
            name: jnp.concatenate([jnp.atleast_1d(a[name]), jnp.atleast_1d(b[name])])
            for name in self._defaults
        }
