"""Serving-loop observatory: live windowed telemetry + SLO health under
simulated traffic, with fault injection that trips every alarm class.

The demo the live health layer exists for (ROADMAP item 2): a simulated
heavy-traffic serving loop drives ``compile_update_async`` ingest into a
fused collection (sketched ``AUROC`` + ``MeanSquaredError``) plus a
per-tenant ``SlicedMetric``, while a :class:`PeriodicExporter` publishes
telemetry, windowed quantiles, and health the whole time:

* the recorder's :class:`TimeSeriesRegistry` turns every hot-path signal
  (update/fused-dispatch wall time, enqueue->apply age, queue depth,
  drops, recompiles, sketch fill, hot-slice share) into ring-of-buckets
  windows backed by ``qsketch`` states;
* a :class:`HealthMonitor` with the seven standard alarm classes (queue
  saturation, staleness, drop-rate SLO burn, recompile storm, sketch-fill
  ceiling, hot-slice skew, score drift) evaluates them continuously,
  logging every fired/cleared transition to a JSONL alarm log;
* ``--inject`` drives a fault phase that demonstrably trips the alarms —
  ``bursts`` (unpaced producer vs a bounded drop-policy queue), ``stall``
  (a reader holding the state snapshot lock, i.e. a slow consumer),
  ``recompiles`` (ragged batch shapes), ``skew`` (one hot tenant),
  ``drift`` (a shifted score distribution vs the reference window frozen
  during warmup), ``stale-reader`` (the dashboard reader pauses past the
  freshness bound while ingest continues — the ``freshness_slo`` /
  ``read_latency`` signal), ``leak`` (host pages pinned outside any
  ledgered state, so the memory observatory's unaccounted-bytes residue
  grows monotonically — ``memory_leak``'s signal; released at recovery),
  ``budget`` (the per-tenant byte ceiling is shrunk below the live sliced
  state — ``memory_budget``; restored at recovery), or ``all`` —
  followed by a recovery phase in which every alarm clears.

Artifacts land in ``--out-dir``: ``metrics.prom`` (Prometheus page incl.
windowed quantiles + health families), ``telemetry.jsonl`` (event log),
``health_alarms.jsonl`` (alarm transitions), ``trace.json`` (Perfetto,
with the async worker on its own labeled track), ``health.txt`` (final
terminal summary), and ``report.json``. Exit status is 0 unless
``--assert-fired-cleared`` is set and no alarm both fired and cleared
(the CI smoke contract).

Run::

    python examples/serving_loop.py --duration 10 --inject bursts
"""
import argparse
import json
import os
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))  # repo root

if "XLA_FLAGS" not in os.environ:
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"

import numpy as np

import jax.numpy as jnp

from metrics_tpu import AUROC, MeanSquaredError, MetricCollection
from metrics_tpu.aggregation import SumMetric
from metrics_tpu.observability import (
    DriftRule,
    HealthMonitor,
    MemoryBudget,
    MemoryObservatory,
    PeriodicExporter,
    aggregate_across_hosts,
    default_rules,
    export_perfetto,
    get_recorder,
    render_health,
    render_prometheus,
    summary,
)
from metrics_tpu.sliced import SlicedMetric

INJECT_MODES = (
    "none", "bursts", "stall", "recompiles", "skew", "drift", "stale-reader",
    "leak", "budget", "all",
)

#: phase boundaries as fractions of --duration: steady warmup, fault
#: injection, recovery (the collection is reset at the recovery boundary —
#: an epoch boundary — so sketch fill drains and every alarm can clear)
WARMUP_FRAC, FAULT_END_FRAC = 0.18, 0.45


def _make_batch(
    rng: "np.random.Generator", n: int, hot_tenant: bool, tenants: int, drifting: bool = False
):
    """One simulated traffic batch: binary targets, noisy scores, and
    row-aligned tenant ids (85% to tenant 0 under skew injection). Under
    drift injection the score distribution SHIFTS — a calibration
    regression upstream of any label — which is exactly the signal the
    score-drift alarm compares against its frozen reference window."""
    target = rng.integers(0, 2, n)
    if drifting:
        # shifted marginal: scores collapse into a tight high cluster
        # regardless of label — far enough from the bimodal healthy
        # marginal that even a half-drifted live window scores well past
        # the alarm threshold
        preds = np.clip(target * 0.08 + rng.normal(0.86, 0.07, n), 0.0, 1.0)
    else:
        preds = np.clip(target * 0.7 + rng.normal(0.3, 0.25, n), 0.0, 1.0)
    if hot_tenant:
        ids = np.where(rng.random(n) < 0.85, 0, rng.integers(0, tenants, n))
    else:
        ids = rng.integers(0, tenants, n)
    return (
        jnp.asarray(preds, jnp.float32),
        jnp.asarray(target, jnp.int32),
        jnp.asarray(ids, jnp.int32),
        preds,  # host copy: the sampled-score feed must not pay a device read
    )


def run(
    duration: float = 15.0,
    inject: str = "all",
    out_dir: str = "serving_artifacts",
    qps: float = 60.0,
    batch_size: int = 64,
    queue_depth: int = 8,
    sketch_capacity: int = 8192,
    tenants: int = 64,
    bucket_seconds: float = 0.5,
    window_s: float = 4.0,
    export_interval_s: float = 1.0,
    seed: int = 0,
    verbose: bool = True,
):
    """Drive the serving loop and return the run report (also written to
    ``<out_dir>/report.json``)."""
    if inject not in INJECT_MODES:
        raise ValueError(f"inject must be one of {INJECT_MODES}, got {inject!r}")
    out = pathlib.Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    rng = np.random.default_rng(seed)

    rec = get_recorder()
    was_enabled = rec.enabled
    rec.reset()
    rec.enable()
    rec.attach_timeseries(
        bucket_seconds=bucket_seconds,
        n_buckets=max(int(3 * window_s / bucket_seconds), 16),
        sketch_capacity=128,
    )
    monitor = HealthMonitor(
        default_rules(
            queue_depth_limit=max(queue_depth // 2, 2),
            staleness_limit_steps=max(queue_depth // 2, 2),
            drop_budget=0.02,
            drop_burn_threshold=2.0,
            recompiles_per_window=8,
            fill_ceiling=0.5,
            hot_share_limit=0.5,
            window_s=window_s,
            # the reference is frozen EXPLICITLY at the warmup boundary
            # below (count-gated auto-freeze trusts traffic-rate timing,
            # and a cold-cache crawl once pushed it into the fault window —
            # baselining on the drifted scores themselves); the threshold
            # keeps headroom over small-reference binning noise while the
            # injected shift measures 2-19 PSI
            drift_threshold=0.5,
            drift_freeze_after=6 * batch_size,
            # the stale-reader fault pauses the dashboard reader for the
            # whole fault window (a few seconds); both read-path bounds
            # sit well inside it and well above healthy probe readings
            freshness_bound_s=1.5,
            read_latency_limit_ms=400.0,
            # memory plane: the healthy per-tenant ceiling is generous (the
            # budget fault trips it by SHRINKING the live rule's threshold,
            # not by growing state); the leak bound sits well below the
            # pinned-page injection total but above normal RSS jitter from
            # recovery-phase recompiles
            tenant_bytes_limit=16 * 1024,
            unaccounted_growth_bytes=16 * 1024 * 1024,
        ),
        recorder=rec,
        alarm_log_path=str(out / "health_alarms.jsonl"),
    )
    # the memory observatory feeds the mem_* series the two memory rules
    # watch: the ledger walks live metric state, cache planes self-report,
    # and the residue vs host RSS (no device backend on CPU) is the leak
    # signal the pinned-page injection grows
    observatory = MemoryObservatory(recorder=rec)
    budget_rules = [r for r in monitor.rules if isinstance(r, MemoryBudget)]
    exporter = PeriodicExporter(
        interval_s=export_interval_s,
        prometheus_path=str(out / "metrics.prom"),
        jsonl_path=str(out / "telemetry.jsonl"),
        health=monitor,
    )
    exporter.start()

    # the serving metrics: a fused async-ingested collection (sketched
    # AUROC exercises the fill alarm; MSE rides the same dispatch), a
    # per-tenant sliced MSE (hot-slice signal), and a deliberately
    # shape-fragile "canary" whose ragged updates simulate an unpadded
    # pipeline for the recompile storm
    # shape_stable_reads: the probe computes this metric every poll tick on
    # a growing stream — the lossless exact kernels would re-trace per fill
    # count (~1s/read), so reads ride the fixed-shape bucketed weighted
    # kernels from row one (rank-error envelope instead of bit-parity; the
    # right trade for a dashboard, never the default)
    auroc = AUROC(pos_label=1, sketch_capacity=sketch_capacity, shape_stable_reads=True)
    collection = MetricCollection({"auroc": auroc, "mse": MeanSquaredError()})
    handle = collection.compile_update_async(queue_depth=queue_depth, policy="drop")
    per_tenant = SlicedMetric(MeanSquaredError(), num_slices=tenants)
    canary = SumMetric()

    # pre-traffic warm-up: pay the first-batch XLA compiles (fused kernel,
    # sliced scatter, canary) BEFORE the phase clock starts — a real
    # serving job warms its caches before taking traffic, and the phase
    # boundaries (warmup/fault/recovery fractions of --duration) assume
    # full-rate steps from t=0 (the drift reference in particular must
    # freeze from enough WARMUP-phase samples, not crawl through compiles
    # into the fault window)
    preds, target, ids, _ = _make_batch(rng, batch_size, False, tenants)
    handle.update_async(preds, target)
    handle.flush()
    per_tenant.update(ids, preds, target.astype(jnp.float32))
    canary.update(jnp.ones((8,), jnp.float32))

    t_start = time.time()
    fault_lo, fault_hi = WARMUP_FRAC * duration, FAULT_END_FRAC * duration
    step = 0
    did_reset = False
    froze_ref = False
    last_probe = 0.0
    ragged_step = 0
    pinned: list = []  # leak-injection host pages (released at recovery)
    budget_saved = None  # (rule, original threshold) pairs while shrunk
    # the dashboard's view: the FreshnessStamp captured at its last
    # completed read (collection ingest walls + async accept->apply age),
    # and — under the stale-reader fault — when its stuck read began
    last_stamp = collection.freshness()
    read_start = None

    def probe(reading_stalled: bool = False):
        """The dashboard's REAL read, every few hundred ms: a plain
        bounded-staleness ``handle.compute()`` plus ``per_tenant.compute()``
        through the incremental read plane — epoch-keyed result caches,
        dirty-slice folds, memoized window folds, and shape-bucketed sketch
        kernels make a full ``compute()`` cheap enough for the poll path,
        so the old hand-rolled probe (freshness stamp + raw fill-leaf peek
        that dodged the per-fill-count retrace) is gone. The staleness
        bound is the queue depth: the probe OBSERVES a saturated queue
        (the bursts fault's signal) instead of draining it away, and the
        cold compute path records the sketch fill ratios the fill alarm
        watches as part of the read cycle.

        ``reading_stalled`` simulates the stale-reader fault: the
        dashboard reader is paused mid-read, so the probe keeps reporting
        the LAST completed read's stamp (its ingest-to-visible age grows
        against the live clock — ``freshness_slo``'s signal) and the
        stuck read's elapsed time (``read_latency``'s signal)."""
        nonlocal last_stamp, read_start
        now = time.time()
        if reading_stalled:
            rec.record_async_event("snapshot", staleness_steps=handle.pending)
            if read_start is None:
                read_start = now
            rec.record_read("probe", duration_s=now - read_start, freshness=last_stamp)
        else:
            t0 = time.perf_counter()
            try:
                # records its own "snapshot" staleness gauge via the
                # handle's _before_compute hook
                handle.compute(max_staleness=queue_depth)
                per_tenant.compute()
            except (ValueError, RuntimeError):
                # empty-state read right after an epoch-boundary reset
                # (async ingest not yet applied): nothing to serve yet
                rec.record_async_event("snapshot", staleness_steps=handle.pending)
            last_stamp = collection.freshness(now)
            read_start = None
            rec.record_read(
                "probe", duration_s=time.perf_counter() - t0, freshness=last_stamp
            )
        # deferred telemetry housekeeping: fold pending time-series
        # observations here, between probe reads, so bucket compaction
        # never lands inside a timed read; the memory poll rides the same
        # cadence so the mem_* series are fresh for rule evaluation
        rec.tick()
        observatory.observe()
        monitor.evaluate()

    try:
        while True:
            now = time.time()
            elapsed = now - t_start
            if elapsed >= duration:
                break
            in_fault = fault_lo <= elapsed < fault_hi
            skewing = in_fault and inject in ("skew", "all")
            drifting = in_fault and inject in ("drift", "all")
            reader_paused = in_fault and inject in ("stale-reader", "all")
            leaking = in_fault and inject in ("leak", "all")
            budget_fault = in_fault and inject in ("budget", "all")

            if leaking and len(pinned) < 24:
                # the leak: pin host pages OUTSIDE any ledgered state or
                # registered cache plane, so only the unaccounted residue
                # (RSS − ledger − planes) grows. 8 MB chunks are mmap'd by
                # the allocator, so clearing the list at recovery returns
                # the pages to the OS and the alarm's monotone-growth test
                # goes quiet
                pinned.append(np.full(1 << 20, float(step), np.float64))
            if budget_fault and budget_saved is None:
                # the budget fault: the ceiling drops below the live sliced
                # state (ops shrinking a tenant's quota), not the state
                # growing — restore at recovery clears it
                budget_saved = [(r, r.threshold) for r in budget_rules]
                for r in budget_rules:
                    r.threshold = 1.0

            if not froze_ref and elapsed >= 0.9 * fault_lo:
                # end of warmup: freeze the drift reference from the
                # known-healthy scores recorded so far (see default_rules
                # note above). Latch only on SUCCESS — an empty window here
                # (very slow first steps) must retry next iteration, not
                # silently fall back to the count gate this freeze exists
                # to bypass
                froze_ref = all(
                    r.freeze_reference(rec.timeseries)
                    for r in monitor.rules
                    if isinstance(r, DriftRule)
                )

            if not did_reset and elapsed >= fault_hi:
                # recovery boundary = epoch boundary: publish values once
                # (a real drained compute), reset (sketch fill falls back to
                # empty), and warm-reuse the compile cache for the fresh
                # async handle
                handle.flush()
                collection.compute()
                collection.reset()
                handle = collection.compile_update_async(
                    queue_depth=queue_depth, policy="drop"
                )
                # memory recovery: drop the pinned pages (mmap'd chunks go
                # back to the OS, so RSS — and with it the unaccounted
                # residue — stops growing and the leak window rolls clear)
                # and restore any shrunk per-tenant ceiling
                pinned.clear()
                if budget_saved is not None:
                    for r, thresh in budget_saved:
                        r.threshold = thresh
                    budget_saved = None
                did_reset = True

            preds, target, ids, host_scores = _make_batch(rng, batch_size, skewing, tenants, drifting)
            # score feed for the drift alarm (host values — no device
            # readback on the serving hot path); the full batch feeds so
            # the reference window accumulates fast enough to freeze
            # well inside warmup
            rec.record_scores(host_scores, max_samples=batch_size)
            if in_fault and inject in ("bursts", "all") and (inject != "all" or step % 2 == 0):
                # unpaced producer: enqueue as fast as the host allows for
                # one slice of the fault window — the bounded drop-policy
                # queue saturates (depth), sheds load (drops), and batches
                # age in the queue (staleness)
                burst_until = min(now + 0.2, t_start + fault_hi)
                while time.time() < burst_until:
                    handle.update_async(preds, target)
                probe(reading_stalled=reader_paused)
            elif in_fault and inject in ("stall", "all"):
                # slow consumer: a reader holds the state snapshot lock, so
                # the worker cannot install batches while the producer keeps
                # offering — the queue fills and sheds exactly like a stalled
                # downstream
                with handle.snapshot():
                    rec.record_async_event("snapshot", staleness_steps=handle.pending)
                    stall_until = min(time.time() + 0.2, t_start + fault_hi)
                    while time.time() < stall_until:
                        handle.update_async(preds, target)
            else:
                handle.update_async(preds, target)
                time.sleep(max(0.0, 1.0 / qps))
            step += 1

            per_tenant.update(ids, preds, target.astype(jnp.float32))
            if in_fault and inject in ("recompiles", "all"):
                # ragged shapes: every new length is a new (shape, dtype)
                # signature — the classic unpadded-pipeline recompile storm
                # (a few fresh lengths per step, like a real unpadded feed)
                for j in range(4):
                    ragged_step += 1
                    canary.update(jnp.ones((8 + ragged_step,), jnp.float32))
            else:
                canary.update(jnp.ones((8,), jnp.float32))

            if now - last_probe >= export_interval_s / 2:
                last_probe = now
                probe(reading_stalled=reader_paused)

        # epoch-end publish: one full (drained) compute, then the second
        # epoch boundary — reset so the tail starts with empty sketches
        # (fill must CLEAR, and a sketch refilled by recovery traffic
        # would hold the alarm up forever)
        handle.flush()
        values = collection.compute()
        collection.reset()
        handle = collection.compile_update_async(queue_depth=queue_depth, policy="drop")
        # quiet tail: light traffic while the windows roll past the last
        # fault signal, so every alarm that is going to clear has the wall
        # time to do it
        tail_end = time.time() + window_s + 2 * bucket_seconds
        while time.time() < tail_end:
            preds, target, ids, host_scores = _make_batch(rng, batch_size, False, tenants)
            rec.record_scores(host_scores)
            handle.update_async(preds, target)
            per_tenant.update(ids, preds, target.astype(jnp.float32))
            canary.update(jnp.ones((8,), jnp.float32))
            probe()
            time.sleep(0.1)
        handle.flush()
        final = monitor.evaluate()
    finally:
        try:
            handle.close()
        except Exception:  # noqa: BLE001 — teardown must reach the exporter stop
            pass
        exporter.stop()

    # final artifacts: job-wide Prometheus (the aggregate path is a no-op
    # single-process and the real merge on a multi-process mesh), Perfetto
    # trace with the worker's labeled track, terminal health summary
    aggregate = aggregate_across_hosts(rec)
    prom = render_prometheus(rec, aggregate=aggregate)
    if prom:
        prom += "\n".join(monitor.prometheus_lines(final)) + "\n"
        (out / "metrics.prom").write_text(prom)
    export_perfetto(str(out / "trace.json"), recorder=rec)
    health_text = render_health(final)
    (out / "health.txt").write_text(health_text + "\n")

    async_totals = rec.async_totals()
    report = {
        "inject": inject,
        "duration_s": duration,
        "steps": step,
        "final_status": final.status,
        "final_values": {k: float(v) for k, v in values.items()},
        "alarms_fired": monitor.fired_ever(),
        "alarms_fired_and_cleared": monitor.fired_and_cleared(),
        "transitions": monitor.transitions(),
        "async": {
            "enqueued": async_totals["enqueued"],
            "applied": async_totals["applied"],
            "dropped": async_totals["dropped"],
            "max_queue_depth": async_totals["max_queue_depth"],
            "max_staleness_steps": async_totals["max_staleness_steps"],
        },
        "reads": rec.read_totals(),
        "freshness": rec.freshness_totals(),
        "memory": rec.memory_totals(),
        "export_errors": rec.export_errors(),
    }
    (out / "report.json").write_text(json.dumps(report, indent=2) + "\n")
    if verbose:
        print(summary(rec))
        print(health_text)
        print(
            f"serving_loop: {step} steps; alarms fired={report['alarms_fired']}"
            f" fired_and_cleared={report['alarms_fired_and_cleared']};"
            f" artifacts in {out}/"
        )

    rec.disable()
    rec.detach_timeseries()
    rec.reset()
    if was_enabled:
        rec.enable()
    return report


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--duration", type=float, default=15.0, help="traffic seconds (excl. quiet tail)")
    parser.add_argument("--inject", choices=INJECT_MODES, default="all")
    parser.add_argument("--out-dir", default="serving_artifacts")
    parser.add_argument("--qps", type=float, default=60.0)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--queue-depth", type=int, default=8)
    parser.add_argument("--sketch-capacity", type=int, default=8192)
    parser.add_argument("--tenants", type=int, default=64)
    parser.add_argument("--bucket-seconds", type=float, default=0.5)
    parser.add_argument("--window-seconds", type=float, default=4.0)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--assert-fired-cleared",
        action="store_true",
        help="exit nonzero unless at least one alarm both fired and cleared (CI smoke)",
    )
    parser.add_argument(
        "--assert-alarm",
        action="append",
        default=[],
        metavar="NAME",
        help="exit nonzero unless the NAMED alarm both fired and cleared (repeatable;"
        " the drift smoke leg pins score_drift specifically — a generic"
        " any-alarm assert would pass with drift detection broken)",
    )
    args = parser.parse_args(argv)
    report = run(
        duration=args.duration,
        inject=args.inject,
        out_dir=args.out_dir,
        qps=args.qps,
        batch_size=args.batch_size,
        queue_depth=args.queue_depth,
        sketch_capacity=args.sketch_capacity,
        tenants=args.tenants,
        bucket_seconds=args.bucket_seconds,
        window_s=args.window_seconds,
        seed=args.seed,
    )
    if args.assert_fired_cleared and not report["alarms_fired_and_cleared"]:
        print("FAIL: no alarm both fired and cleared", file=sys.stderr)
        return 2
    missing = [a for a in args.assert_alarm if a not in report["alarms_fired_and_cleared"]]
    if missing:
        print(
            f"FAIL: alarm(s) {missing} did not both fire and clear"
            f" (fired_and_cleared={report['alarms_fired_and_cleared']})",
            file=sys.stderr,
        )
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
