"""Gaussian kernel helper for image metrics.

Behavior parity with /root/reference/torchmetrics/functional/image/helper.py.
"""
from typing import Sequence

import jax
import jax.numpy as jnp

Array = jax.Array


def _gaussian(kernel_size: int, sigma: float, dtype) -> Array:
    """1D gaussian kernel of shape (1, kernel_size)."""
    dist = jnp.arange((1 - kernel_size) / 2, (1 + kernel_size) / 2, 1.0, dtype=dtype)
    gauss = jnp.exp(-jnp.square(dist / sigma) / 2)
    return (gauss / jnp.sum(gauss))[None, :]


def _gaussian_kernel(channel: int, kernel_size: Sequence[int], sigma: Sequence[float], dtype) -> Array:
    """2D gaussian kernel of shape (channel, 1, kh, kw) for a grouped conv."""
    kernel_x = _gaussian(kernel_size[0], sigma[0], dtype)
    kernel_y = _gaussian(kernel_size[1], sigma[1], dtype)
    kernel = kernel_x.T @ kernel_y  # (kh, kw)
    return jnp.broadcast_to(kernel, (channel, 1, kernel_size[0], kernel_size[1]))


def _depthwise_conv2d(x: Array, kernel: Array) -> Array:
    """VALID depthwise conv: x [N,C,H,W], kernel [C,1,kh,kw]."""
    return jax.lax.conv_general_dilated(
        x,
        kernel,
        window_strides=(1, 1),
        padding="VALID",
        feature_group_count=x.shape[1],
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )


def _avg_pool2d(x: Array) -> Array:
    """2x2 average pool with stride 2 (torch F.avg_pool2d parity)."""
    summed = jax.lax.reduce_window(
        x, 0.0, jax.lax.add, window_dimensions=(1, 1, 2, 2), window_strides=(1, 1, 2, 2), padding="VALID"
    )
    return summed / 4.0
