"""In-repo C++ linear-sum-assignment solver vs scipy (the reference's own
backend for PIT's large-speaker path, SURVEY §2.9)."""
import numpy as np
import pytest
from scipy.optimize import linear_sum_assignment

import jax.numpy as jnp

from metrics_tpu.native import lsap, native_lsap_available


@pytest.mark.parametrize("maximize", [False, True])
def test_optimal_cost_parity_with_scipy(maximize):
    rng = np.random.default_rng(0)
    for trial in range(120):
        n = int(rng.integers(1, 13))
        m = rng.standard_normal((n, n))
        if trial % 3 == 0:
            m = np.round(m)  # degenerate ties: many co-optimal assignments
        cols = lsap(m[None], maximize=maximize)[0]
        assert sorted(cols) == list(range(n))  # a permutation
        want_rows, want_cols = linear_sum_assignment(m, maximize=maximize)
        np.testing.assert_allclose(
            m[np.arange(n), cols].sum(), m[want_rows, want_cols].sum(), atol=1e-9
        )


def test_batched_and_validation():
    rng = np.random.default_rng(1)
    batch = rng.standard_normal((20, 6, 6))
    out = lsap(batch, maximize=True)
    assert out.shape == (20, 6)
    with pytest.raises(ValueError, match="square"):
        lsap(np.zeros((2, 3, 4)))


def test_native_solver_compiles_here():
    """The toolchain exists in this environment, so the C++ path (not the
    scipy fallback) must actually be active."""
    assert native_lsap_available()


def test_pit_large_speakers_uses_host_assignment():
    """PIT beyond the exhaustive limit routes through the native solver and
    still finds the optimal permutation."""
    from metrics_tpu.functional.audio.pit import permutation_invariant_training

    rng = np.random.default_rng(2)
    spk = 8  # > _MAX_EXHAUSTIVE_SPK
    target = rng.standard_normal((2, spk, 64)).astype(np.float32)
    perm = rng.permutation(spk)
    preds = target[:, perm] + 0.01 * rng.standard_normal((2, spk, 64)).astype(np.float32)

    def neg_mse(p, t):
        return -jnp.mean((p - t) ** 2, axis=-1)

    best_metric, best_perm = permutation_invariant_training(
        jnp.asarray(preds), jnp.asarray(target), neg_mse, "max"
    )
    # best_perm[target_i] is the matching pred index, i.e. the INVERSE of
    # the permutation applied to build preds
    for b in range(2):
        np.testing.assert_array_equal(np.asarray(best_perm)[b], np.argsort(perm))
    assert float(jnp.min(best_metric)) > -0.01


def test_nonfinite_costs_rejected():
    m = np.zeros((4, 4))
    m[2, 3] = np.inf
    with pytest.raises(ValueError, match="invalid numeric"):
        lsap(m[None])
    m[2, 3] = np.nan
    with pytest.raises(ValueError, match="invalid numeric"):
        lsap(m[None])
