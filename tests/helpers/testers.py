"""Universal metric test harness.

Parity in spirit with the reference MetricTester
(/root/reference/tests/helpers/testers.py:329-564): numerical parity vs a
reference oracle (sklearn etc.) both per-batch and on the full accumulated
dataset, const-attr immutability, compile check (jit replaces torchscript),
pickle round-trip, hashability. The reference's 2-process Gloo pool is
replaced by (a) a virtual-rank merge check via the pure state API and (b)
real-collective tests over an 8-virtual-device CPU mesh in tests/bases.
"""
import functools
from functools import partial
import pickle
from typing import Any, Callable, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

NUM_PROCESSES = 2  # virtual ranks for merge-based ddp simulation
NUM_BATCHES = 4
BATCH_SIZE = 32
NUM_CLASSES = 5
EXTRA_DIM = 3
THRESHOLD = 0.5


def _assert_allclose(tpu_result: Any, sk_result: Any, atol: float = 1e-8) -> None:
    if isinstance(tpu_result, dict):
        assert isinstance(sk_result, dict), f"oracle returned {type(sk_result)}, metric returned dict"
        for key in tpu_result:
            np.testing.assert_allclose(
                np.asarray(tpu_result[key]), np.asarray(sk_result[key]), atol=atol, rtol=1e-5, err_msg=f"key={key}"
            )
    elif isinstance(tpu_result, (list, tuple)) and not isinstance(sk_result, np.ndarray):
        for t, s in zip(tpu_result, sk_result):
            _assert_allclose(t, s, atol=atol)
    else:
        np.testing.assert_allclose(np.asarray(tpu_result), np.asarray(sk_result), atol=atol, rtol=1e-5)


def _assert_array(tpu_result: Any) -> None:
    if isinstance(tpu_result, dict):
        for key in tpu_result:
            assert isinstance(tpu_result[key], jnp.ndarray), f"{key} is not an array"
    elif isinstance(tpu_result, (list, tuple)):
        for el in tpu_result:
            _assert_array(el)
    else:
        assert isinstance(tpu_result, jnp.ndarray), f"{tpu_result} is not an array"


def _class_test(
    preds: Any,
    target: Any,
    metric_class: type,
    sk_metric: Callable,
    metric_args: Optional[dict] = None,
    check_batch: bool = True,
    check_merge: bool = True,
    check_jit: bool = True,
    check_pickle: bool = True,
    dist_sync_on_step: bool = False,
    atol: float = 1e-8,
    fragment_kwargs: bool = False,
    **kwargs_update: Any,
) -> None:
    """Single-process lifecycle + virtual-rank merge parity test."""
    metric_args = metric_args or {}
    metric = metric_class(**metric_args)

    # const attrs are immutable
    for attr in ("is_differentiable", "higher_is_better"):
        try:
            setattr(metric, attr, True)
            raise AssertionError(f"const attr {attr} was assignable")
        except RuntimeError:
            pass

    num_batches = len(preds) if isinstance(preds, (list, tuple)) else preds.shape[0]
    for i in range(num_batches):
        batch_kwargs = {
            k: (v[i] if isinstance(v, (list, tuple)) or (hasattr(v, "shape") and len(v) == num_batches) else v)
            for k, v in kwargs_update.items()
        }
        batch_result = metric(preds[i], target[i], **batch_kwargs)

        if check_pickle and i == 0:
            clone = pickle.loads(pickle.dumps(metric))
            assert type(clone) is type(metric)

        if check_batch:
            sk_batch_result = sk_metric(preds[i], target[i], **batch_kwargs)
            _assert_allclose(batch_result, sk_batch_result, atol=atol)

    # full-dataset accumulated value vs oracle on everything
    result = metric.compute()
    _assert_array(result)
    total_kwargs = {
        k: (np.concatenate([np.asarray(vv) for vv in v]) if isinstance(v, (list, tuple)) or (hasattr(v, "shape") and len(v) == num_batches) else v)
        for k, v in kwargs_update.items()
    }
    if isinstance(preds, (list, tuple)):
        all_preds = np.concatenate([np.asarray(p) for p in preds])
        all_target = np.concatenate([np.asarray(t) for t in target])
    else:
        all_preds = np.asarray(preds).reshape(-1, *preds.shape[2:])
        all_target = np.asarray(target).reshape(-1, *target.shape[2:])
    sk_result = sk_metric(all_preds, all_target, **total_kwargs)
    _assert_allclose(result, sk_result, atol=atol)

    # hashability
    assert isinstance(hash(metric), int)

    # virtual-rank merge parity: split batches over NUM_PROCESSES "ranks",
    # accumulate independently via the pure state API, merge, compute.
    if check_merge and not kwargs_update:
        states = []
        for rank in range(NUM_PROCESSES):
            m = metric_class(**metric_args)
            state = m.init_state()
            for i in range(rank, num_batches, NUM_PROCESSES):
                state = m.update_state(state, preds[i], target[i])
            states.append(state)
        merged = functools.reduce(metric.merge_states, states)
        merged_result = metric.compute_state(merged)
        _assert_allclose(merged_result, sk_result, atol=atol)

    # dist_sync_on_step semantics (reference testers.py:392-470 ddp x
    # dist_sync_on_step grid): at every step each virtual rank contributes
    # ONE batch, the per-step forward value is computed on the merged
    # cross-rank batch state, and must equal the oracle over both ranks'
    # batches concatenated. Uses the pure state API as the sync transport —
    # the same merge path a mesh all_gather feeds.
    if dist_sync_on_step and check_merge and not kwargs_update:
        m = metric_class(**metric_args)
        for step in range(num_batches // NUM_PROCESSES):
            batch_states = []
            for rank in range(NUM_PROCESSES):
                i = step * NUM_PROCESSES + rank
                batch_states.append(m.update_state(m.init_state(), preds[i], target[i]))
            synced = functools.reduce(m.merge_states, batch_states)
            step_result = m.compute_state(synced)
            lo, hi = step * NUM_PROCESSES, step * NUM_PROCESSES + NUM_PROCESSES
            step_preds = np.concatenate([np.asarray(preds[i]) for i in range(lo, hi)])
            step_target = np.concatenate([np.asarray(target[i]) for i in range(lo, hi)])
            _assert_allclose(step_result, sk_metric(step_preds, step_target), atol=atol)

    # jit-compilability of the pure update (replaces torchscript check)
    if check_jit and not getattr(metric_class, "__jit_unsafe__", False) and not kwargs_update:
        m = metric_class(**metric_args)
        state = m.init_state()
        try:
            jit_state = jax.jit(m.update_state)(state, jnp.asarray(preds[0]), jnp.asarray(target[0]))
        except ValueError as err:
            if "under jit" in str(err):
                return  # documented contract: class-count inference needs concrete values
            raise
        eager_state = m.update_state(state, jnp.asarray(preds[0]), jnp.asarray(target[0]))
        for k in eager_state:
            ev, jv = eager_state[k], jit_state[k]
            if isinstance(ev, list):
                for e, j in zip(ev, jv):
                    np.testing.assert_allclose(np.asarray(j), np.asarray(e), atol=1e-6, rtol=1e-5)
            else:
                np.testing.assert_allclose(np.asarray(jv), np.asarray(ev), atol=1e-6, rtol=1e-5)


def _functional_test(
    preds: Any,
    target: Any,
    metric_functional: Callable,
    sk_metric: Callable,
    metric_args: Optional[dict] = None,
    atol: float = 1e-8,
    **kwargs_update: Any,
) -> None:
    metric_args = metric_args or {}
    metric = partial(metric_functional, **metric_args)
    num_batches = len(preds) if isinstance(preds, (list, tuple)) else preds.shape[0]
    for i in range(min(num_batches, 2)):
        batch_kwargs = {
            k: (v[i] if isinstance(v, (list, tuple)) or (hasattr(v, "shape") and len(v) == num_batches) else v)
            for k, v in kwargs_update.items()
        }
        tpu_result = metric(jnp.asarray(preds[i]), jnp.asarray(target[i]), **batch_kwargs)
        sk_result = sk_metric(preds[i], target[i], **batch_kwargs)
        _assert_allclose(tpu_result, sk_result, atol=atol)


class MetricTester:
    """Base class for all metric test classes."""

    atol: float = 1e-8

    def run_class_metric_test(
        self,
        preds: Any,
        target: Any,
        metric_class: type,
        sk_metric: Callable,
        dist_sync_on_step: bool = False,
        metric_args: Optional[dict] = None,
        check_batch: bool = True,
        check_merge: bool = True,
        check_jit: bool = True,
        atol: Optional[float] = None,
        **kwargs_update: Any,
    ) -> None:
        _class_test(
            preds,
            target,
            metric_class,
            sk_metric,
            metric_args=metric_args,
            check_batch=check_batch,
            check_merge=check_merge,
            check_jit=check_jit,
            dist_sync_on_step=dist_sync_on_step,
            atol=self.atol if atol is None else atol,
            **kwargs_update,
        )

    def run_functional_metric_test(
        self,
        preds: Any,
        target: Any,
        metric_functional: Callable,
        sk_metric: Callable,
        metric_args: Optional[dict] = None,
        atol: Optional[float] = None,
        **kwargs_update: Any,
    ) -> None:
        _functional_test(
            preds,
            target,
            metric_functional,
            sk_metric,
            metric_args=metric_args,
            atol=self.atol if atol is None else atol,
            **kwargs_update,
        )

    def run_precision_test(
        self,
        preds: Any,
        target: Any,
        metric_class: type,
        metric_functional: Callable,
        metric_args: Optional[dict] = None,
    ) -> None:
        """bf16 analog of the reference fp16 test: update/compute must not crash."""
        metric_args = metric_args or {}
        metric = metric_class(**metric_args)
        metric.set_dtype(jnp.bfloat16)
        p = jnp.asarray(preds[0])
        if jnp.issubdtype(p.dtype, jnp.floating):
            p = p.astype(jnp.bfloat16)
        metric.update(p, jnp.asarray(target[0]))
        metric.compute()

    def run_differentiability_test(
        self,
        preds: Any,
        target: Any,
        metric_class: type,
        metric_functional: Callable,
        metric_args: Optional[dict] = None,
    ) -> None:
        """jax.grad analog of the reference autograd test."""
        metric_args = metric_args or {}
        metric = metric_class(**metric_args)
        if metric.is_differentiable:
            p = jnp.asarray(preds[0], dtype=jnp.float32)
            t = jnp.asarray(target[0])

            def scalar_fn(pp):
                out = metric_functional(pp, t, **metric_args)
                if isinstance(out, (tuple, list)):
                    out = out[0]
                return jnp.sum(jnp.asarray(out))

            grad = jax.grad(scalar_fn)(p)
            assert jnp.all(jnp.isfinite(grad)), "gradient contains non-finite values"


class DummyMetric:
    pass
