"""Pairwise manhattan distance.

Behavior parity with /root/reference/torchmetrics/functional/pairwise/manhattan.py:20-85.
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.pairwise.helpers import _check_input, _reduce_distance_matrix, _zero_diagonal

Array = jax.Array


def _pairwise_manhattan_distance_update(
    x: Array, y: Optional[Array] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    distance = jnp.sum(jnp.abs(x[:, None, :] - y[None, :, :]), axis=-1)
    return _zero_diagonal(distance, zero_diagonal)


def pairwise_manhattan_distance(
    x: Array, y: Optional[Array] = None, reduction: Optional[str] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """Pairwise manhattan (L1) distance between rows of x (and y).

    Example:
        >>> import jax.numpy as jnp
        >>> x = jnp.array([[2., 3.], [3., 5.], [5., 8.]])
        >>> y = jnp.array([[1., 0.], [2., 1.]])
        >>> pairwise_manhattan_distance(x, y)
        Array([[ 4.,  2.],
               [ 7.,  5.],
               [12., 10.]], dtype=float32)
    """
    distance = _pairwise_manhattan_distance_update(x, y, zero_diagonal)
    return _reduce_distance_matrix(distance, reduction)
