"""Retrieval metrics vs sklearn / hand-rolled oracles.

Mirrors /root/reference/tests/retrieval/ in spirit: grouped queries with
random lengths, all empty_target_action modes, argument validation.
"""
import numpy as np
import pytest
from sklearn.metrics import average_precision_score as sk_ap, ndcg_score as sk_ndcg

import jax.numpy as jnp

from metrics_tpu import (
    RetrievalFallOut,
    RetrievalHitRate,
    RetrievalMAP,
    RetrievalMRR,
    RetrievalNormalizedDCG,
    RetrievalPrecision,
    RetrievalRPrecision,
    RetrievalRecall,
)
from metrics_tpu.functional import (
    retrieval_average_precision,
    retrieval_fall_out,
    retrieval_hit_rate,
    retrieval_normalized_dcg,
    retrieval_precision,
    retrieval_r_precision,
    retrieval_recall,
    retrieval_reciprocal_rank,
)

_rng = np.random.RandomState(42)
N_QUERIES = 10
# each query has 4-12 documents, with at least one positive and one negative
_indexes, _preds, _target = [], [], []
for q in range(N_QUERIES):
    n = _rng.randint(4, 13)
    t = np.zeros(n, dtype=np.int64)
    t[_rng.choice(n, _rng.randint(1, n), replace=False)] = 1
    if t.all():
        t[0] = 0
    _indexes.append(np.full(n, q))
    _preds.append(_rng.rand(n).astype(np.float32))
    _target.append(t)
INDEXES = jnp.asarray(np.concatenate(_indexes))
PREDS = jnp.asarray(np.concatenate(_preds))
TARGET = jnp.asarray(np.concatenate(_target))


def _per_query_mean(fn):
    return np.mean([fn(p, t) for p, t in zip(_preds, _target)])


def _sk_mrr(p, t):
    order = np.argsort(-p)
    pos = np.nonzero(t[order])[0]
    return 1.0 / (pos[0] + 1)


def _sk_precision_at(k):
    def fn(p, t):
        order = np.argsort(-p)[:k]
        return t[order].sum() / k
    return fn


def _sk_recall_at(k):
    def fn(p, t):
        order = np.argsort(-p)[:k]
        return t[order].sum() / t.sum()
    return fn


def _sk_hit_at(k):
    def fn(p, t):
        return float(t[np.argsort(-p)[:k]].sum() > 0)
    return fn


def _sk_fallout_at(k):
    def fn(p, t):
        neg = 1 - t
        return neg[np.argsort(-p)[:k]].sum() / neg.sum()
    return fn


def _sk_rprec(p, t):
    r = int(t.sum())
    return t[np.argsort(-p)[:r]].sum() / r


@pytest.mark.parametrize(
    "metric_class, metric_args, oracle",
    [
        (RetrievalMAP, {}, lambda: _per_query_mean(lambda p, t: sk_ap(t, p))),
        (RetrievalMRR, {}, lambda: _per_query_mean(_sk_mrr)),
        (RetrievalPrecision, {"k": 2}, lambda: _per_query_mean(_sk_precision_at(2))),
        (RetrievalRecall, {"k": 2}, lambda: _per_query_mean(_sk_recall_at(2))),
        (RetrievalHitRate, {"k": 2}, lambda: _per_query_mean(_sk_hit_at(2))),
        (RetrievalFallOut, {"k": 2}, lambda: _per_query_mean(_sk_fallout_at(2))),
        (RetrievalRPrecision, {}, lambda: _per_query_mean(_sk_rprec)),
        (
            RetrievalNormalizedDCG,
            {},
            lambda: _per_query_mean(lambda p, t: sk_ndcg(t[None, :], p[None, :])),
        ),
        (
            RetrievalNormalizedDCG,
            {"k": 3},
            lambda: _per_query_mean(lambda p, t: sk_ndcg(t[None, :], p[None, :], k=3)),
        ),
    ],
)
def test_retrieval_metric_parity(metric_class, metric_args, oracle):
    metric = metric_class(**metric_args)
    # batched updates split mid-query to exercise cross-batch grouping
    half = len(PREDS) // 2
    metric.update(PREDS[:half], TARGET[:half], indexes=INDEXES[:half])
    metric.update(PREDS[half:], TARGET[half:], indexes=INDEXES[half:])
    np.testing.assert_allclose(np.asarray(metric.compute()), oracle(), atol=1e-5)


def test_empty_target_actions():
    indexes = jnp.asarray([0, 0, 1, 1])
    preds = jnp.asarray([0.3, 0.7, 0.2, 0.8], dtype=jnp.float32)
    target = jnp.asarray([0, 1, 0, 0])  # query 1 has no positives

    for action, expected in [("neg", (1.0 + 0.0) / 2), ("pos", (1.0 + 1.0) / 2), ("skip", 1.0)]:
        m = RetrievalMAP(empty_target_action=action)
        m.update(preds, target, indexes=indexes)
        assert float(m.compute()) == pytest.approx(expected), action

    m = RetrievalMAP(empty_target_action="error")
    m.update(preds, target, indexes=indexes)
    with pytest.raises(ValueError, match="no positive"):
        m.compute()


def test_fall_out_inverted_empty_handling():
    indexes = jnp.asarray([0, 0, 1, 1])
    preds = jnp.asarray([0.3, 0.7, 0.2, 0.8], dtype=jnp.float32)
    target = jnp.asarray([0, 1, 1, 1])  # query 1 has no negatives

    m = RetrievalFallOut(empty_target_action="error")
    m.update(preds, target, indexes=indexes)
    with pytest.raises(ValueError, match="no negative"):
        m.compute()


def test_ignore_index():
    indexes = jnp.asarray([0, 0, 0])
    preds = jnp.asarray([0.3, 0.7, 0.5], dtype=jnp.float32)
    target = jnp.asarray([0, 1, -100])
    m = RetrievalMAP(ignore_index=-100)
    m.update(preds, target, indexes=indexes)
    assert float(m.compute()) == pytest.approx(1.0)


def test_invalid_args():
    with pytest.raises(ValueError):
        RetrievalMAP(empty_target_action="bad")
    with pytest.raises(ValueError):
        RetrievalMAP(ignore_index="bad")
    with pytest.raises(ValueError):
        RetrievalPrecision(k=-1)
    m = RetrievalMAP()
    with pytest.raises(ValueError):
        m.update(PREDS, TARGET, indexes=None)


def test_functional_kernels():
    p = jnp.asarray([0.2, 0.3, 0.5], dtype=jnp.float32)
    t = jnp.asarray([True, False, True])
    assert float(retrieval_average_precision(p, t)) == pytest.approx((1 / 1 + 2 / 3) / 2)
    assert float(retrieval_reciprocal_rank(p, t)) == pytest.approx(1.0)
    assert float(retrieval_precision(p, t, k=2)) == pytest.approx(0.5)
    assert float(retrieval_recall(p, t, k=2)) == pytest.approx(0.5)
    assert float(retrieval_hit_rate(p, t, k=2)) == pytest.approx(1.0)
    assert float(retrieval_fall_out(p, t, k=2)) == pytest.approx(1.0)
    assert float(retrieval_r_precision(p, t)) == pytest.approx(0.5)
    nd = retrieval_normalized_dcg(jnp.asarray([0.1, 0.2, 0.3, 4.0, 70.0]), jnp.asarray([10, 0, 0, 1, 5]))
    expected = sk_ndcg(np.asarray([[10, 0, 0, 1, 5]]), np.asarray([[0.1, 0.2, 0.3, 4.0, 70.0]]))
    np.testing.assert_allclose(np.asarray(nd), expected, atol=1e-5)

    # no-positive queries return 0
    t0 = jnp.asarray([False, False, False])
    assert float(retrieval_average_precision(p, t0)) == 0.0
    assert float(retrieval_reciprocal_rank(p, t0)) == 0.0
