"""Reference-parity sweep for the retrieval domain.

Breadth parity with /root/reference/tests/retrieval/ (the
RetrievalMetricTester parametrization, helpers.py:410-530): every metric x
k x empty_target_action over a shared ragged fixture that contains
empty-target queries, graded targets for NDCG, single-doc queries, and an
argument-validation sweep — with the reference implementation as oracle so
the empty-query policies and @k edge rules are pinned behaviorally.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from metrics_tpu.retrieval import (
    RetrievalFallOut,
    RetrievalHitRate,
    RetrievalMAP,
    RetrievalMRR,
    RetrievalNormalizedDCG,
    RetrievalPrecision,
    RetrievalRecall,
    RetrievalRPrecision,
)
from tests.helpers.reference import load_reference_module

torch = pytest.importorskip("torch")


# ragged fixture: 24 queries, 1-15 docs each, ~1/4 with no positive target,
# one single-doc query, one all-positive query
_rng = np.random.default_rng(55)
_idx_parts, _preds_parts, _target_parts = [], [], []
for q in range(24):
    n = int(_rng.integers(1, 16)) if q != 3 else 1
    t = (_rng.random(n) < 0.35).astype(np.int64)
    if q % 4 == 0:
        t[:] = 0  # empty-target query
    if q == 7:
        t[:] = 1  # all-positive query (FallOut's empty case)
    _idx_parts.append(np.full(n, q))
    _preds_parts.append(_rng.random(n).astype(np.float32))
    _target_parts.append(t)
IDX = np.concatenate(_idx_parts)
PREDS = np.concatenate(_preds_parts)
TARGET = np.concatenate(_target_parts)

# graded-relevance variant for NDCG
TARGET_GRADED = np.where(TARGET > 0, _rng.integers(1, 5, len(TARGET)), 0).astype(np.int64)


METRICS = [
    ("RetrievalMAP", RetrievalMAP, {}, False),
    ("RetrievalMRR", RetrievalMRR, {}, False),
    ("RetrievalRPrecision", RetrievalRPrecision, {}, False),
    ("RetrievalPrecision", RetrievalPrecision, {"k": 1}, False),
    ("RetrievalPrecision", RetrievalPrecision, {"k": 3}, False),
    ("RetrievalPrecision", RetrievalPrecision, {}, False),
    ("RetrievalRecall", RetrievalRecall, {"k": 1}, False),
    ("RetrievalRecall", RetrievalRecall, {"k": 3}, False),
    ("RetrievalHitRate", RetrievalHitRate, {"k": 1}, False),
    ("RetrievalHitRate", RetrievalHitRate, {"k": 3}, False),
    ("RetrievalFallOut", RetrievalFallOut, {"k": 3}, False),
    ("RetrievalNormalizedDCG", RetrievalNormalizedDCG, {"k": 3}, False),
    ("RetrievalNormalizedDCG", RetrievalNormalizedDCG, {}, True),
]
METRIC_IDS = [
    f"{name}{'-k' + str(args['k']) if 'k' in args else ''}{'-graded' if graded else ''}"
    for name, _, args, graded in METRICS
]


def _ref_retrieval(name, **kwargs):
    mod = load_reference_module("torchmetrics.retrieval")
    return getattr(mod, name)(**kwargs)


@pytest.mark.parametrize("action", ["neg", "pos", "skip"])
@pytest.mark.parametrize("name, cls, args, graded", METRICS, ids=METRIC_IDS)
def test_retrieval_reference_parity(name, cls, args, graded, action):
    """Accumulated value matches the reference metric with identical
    arguments, across every empty-query policy, fed in two uneven batches
    that split mid-query."""
    target = TARGET_GRADED if graded else TARGET
    ours = cls(empty_target_action=action, **args)
    ref = _ref_retrieval(name, empty_target_action=action, **args)

    half = len(PREDS) // 2
    for lo, hi in ((0, half), (half, len(PREDS))):
        ours.update(
            jnp.asarray(PREDS[lo:hi]), jnp.asarray(target[lo:hi]), indexes=jnp.asarray(IDX[lo:hi])
        )
        ref.update(
            torch.as_tensor(PREDS[lo:hi]),
            torch.as_tensor(target[lo:hi]),
            indexes=torch.as_tensor(IDX[lo:hi]),
        )
    np.testing.assert_allclose(
        float(ours.compute()), float(ref.compute()), atol=1e-5, err_msg=f"{name} {args} {action}"
    )


@pytest.mark.parametrize("name, cls, args, graded", METRICS[:4], ids=METRIC_IDS[:4])
def test_retrieval_error_action_raises_like_reference(name, cls, args, graded):
    ours = cls(empty_target_action="error", **args)
    ours.update(jnp.asarray(PREDS), jnp.asarray(TARGET), indexes=jnp.asarray(IDX))
    with pytest.raises(ValueError):
        ours.compute()

    ref = _ref_retrieval(name, empty_target_action="error", **args)
    ref.update(torch.as_tensor(PREDS), torch.as_tensor(TARGET), indexes=torch.as_tensor(IDX))
    with pytest.raises(ValueError):
        ref.compute()


@pytest.mark.parametrize("ignore_index", [-100, 0])
def test_retrieval_ignore_index_parity(ignore_index):
    target = TARGET.copy()
    target[::7] = ignore_index  # sprinkle ignored positions
    ours = RetrievalMAP(ignore_index=ignore_index, empty_target_action="skip")
    ref = _ref_retrieval("RetrievalMAP", ignore_index=ignore_index, empty_target_action="skip")
    ours.update(jnp.asarray(PREDS), jnp.asarray(target), indexes=jnp.asarray(IDX))
    ref.update(torch.as_tensor(PREDS), torch.as_tensor(target), indexes=torch.as_tensor(IDX))
    np.testing.assert_allclose(float(ours.compute()), float(ref.compute()), atol=1e-5)


# ---------------------------------------------------------------------------
# argument-validation sweep (RetrievalMetricTester's "arguments" checks)
# ---------------------------------------------------------------------------

ALL_CLASSES = sorted(
    {cls for _, cls, _, _ in METRICS}, key=lambda c: c.__name__
)


@pytest.mark.parametrize("cls", ALL_CLASSES, ids=[c.__name__ for c in ALL_CLASSES])
def test_retrieval_argument_validation(cls):
    with pytest.raises(ValueError, match="empty_target_action"):
        cls(empty_target_action="casual_argument")
    with pytest.raises(ValueError, match="ignore_index"):
        cls(ignore_index="not an int")

    m = cls()
    # indexes are required
    with pytest.raises(ValueError, match="`indexes`"):
        m.update(jnp.asarray([0.1, 0.2]), jnp.asarray([0, 1]), indexes=None)
    # shape mismatch
    with pytest.raises(ValueError, match="same shape"):
        m.update(jnp.asarray([0.1, 0.2]), jnp.asarray([0, 1, 1]), indexes=jnp.asarray([0, 0, 0]))
    # float indexes rejected
    with pytest.raises(ValueError, match="long integers"):
        m.update(jnp.asarray([0.1, 0.2]), jnp.asarray([0, 1]), indexes=jnp.asarray([0.0, 0.0]))
    # integer preds rejected
    with pytest.raises(ValueError, match="float"):
        m.update(jnp.asarray([1, 0]), jnp.asarray([0, 1]), indexes=jnp.asarray([0, 0]))


@pytest.mark.parametrize(
    "cls", [RetrievalPrecision, RetrievalRecall, RetrievalHitRate, RetrievalFallOut, RetrievalNormalizedDCG]
)
def test_retrieval_k_validation(cls):
    with pytest.raises(ValueError, match="`k`"):
        cls(k=-1)
    with pytest.raises(ValueError, match="`k`"):
        cls(k=0)
    with pytest.raises(ValueError, match="`k`"):
        cls(k=1.5)


def test_retrieval_non_binary_target_rejected_where_disallowed():
    m = RetrievalMAP()
    with pytest.raises(ValueError, match="binary"):
        m.update(jnp.asarray([0.1, 0.2]), jnp.asarray([0, 3]), indexes=jnp.asarray([0, 0]))
    # NDCG allows graded targets
    ndcg = RetrievalNormalizedDCG()
    ndcg.update(jnp.asarray([0.1, 0.2]), jnp.asarray([0, 3]), indexes=jnp.asarray([0, 0]))
    assert float(ndcg.compute()) >= 0.0


def test_retrieval_single_query_single_doc():
    """Degenerate layouts: one query, one doc (positive and negative)."""
    pos = RetrievalMAP()
    pos.update(jnp.asarray([0.5]), jnp.asarray([1]), indexes=jnp.asarray([0]))
    assert float(pos.compute()) == 1.0
    neg = RetrievalMAP(empty_target_action="neg")
    neg.update(jnp.asarray([0.5]), jnp.asarray([0]), indexes=jnp.asarray([0]))
    assert float(neg.compute()) == 0.0


def test_retrieval_nonconsecutive_query_ids():
    """Query ids need not be dense/consecutive (reference get_group_indexes
    contract): sparse ids give the same result as densified ones."""
    sparse = jnp.asarray([100, 100, 7, 7, 9000])
    dense = jnp.asarray([0, 0, 1, 1, 2])
    preds = jnp.asarray([0.9, 0.1, 0.8, 0.3, 0.7])
    target = jnp.asarray([1, 0, 0, 1, 1])
    a, b = RetrievalMAP(), RetrievalMAP()
    a.update(preds, target, indexes=sparse)
    b.update(preds, target, indexes=dense)
    np.testing.assert_allclose(float(a.compute()), float(b.compute()), atol=1e-6)
