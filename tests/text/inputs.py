"""Text-domain test fixtures: batched corpora with single and multiple references."""
from collections import namedtuple

TextInput = namedtuple("TextInput", ["preds", "targets"])

# machine-translation style corpus, two references per sentence
_HYP_1 = "the quick brown fox jumped over the lazy dog near the river bank"
_REF_1A = "the quick brown fox jumps over the lazy dog by the river bank"
_REF_1B = "a fast brown fox leaped over a lazy dog close to the river"

_HYP_2 = "she decided to stay home because the weather forecast predicted rain"
_REF_2A = "she chose to remain at home since rain was predicted by the forecast"
_REF_2B = "because the forecast predicted rain she decided to stay at home"

# intentional extra whitespace exercises tokenizer normalization
_HYP_3 = "the dog the   dog sat on the log "
_REF_3A = "the  dog is     on the log "
_REF_3B = "there is a   dog on the log"

_inputs_multiple_references = TextInput(
    preds=[[_HYP_1, _HYP_2], [_HYP_2, _HYP_3]],
    targets=[[[_REF_1A, _REF_1B], [_REF_2A, _REF_2B]], [[_REF_2A, _REF_2B], [_REF_3A, _REF_3B]]],
)

_inputs_single_sentence_multiple_references = TextInput(
    preds=[[_HYP_2]],
    targets=[[[_REF_2A, _REF_2B]]],
)

# speech-recognition style corpus for the error-rate family (single reference)
_inputs_error_rate_batch_size_1 = TextInput(
    preds=[["hello there world"], ["what a fine day"]],
    targets=[["hello world"], ["what a wonderfully fine day"]],
)

_inputs_error_rate_batch_size_2 = TextInput(
    preds=[
        ["i prefer lisp", "what you mean or swallow"],
        ["greetings duck", "i prefer lisp"],
    ],
    targets=[
        ["i prefer common lisp", "what do you mean, african or european swallow"],
        ["greetings world", "i prefer common lisp"],
    ],
)
