"""ClasswiseWrapper — dict-per-class output.

Behavior parity with /root/reference/torchmetrics/wrappers/classwise.py:8-60.
"""
from typing import Any, Dict, List, Optional

import jax

from metrics_tpu.core.metric import Metric

Array = jax.Array


class ClasswiseWrapper(Metric):
    """Wraps a per-class metric to return a labeled dict.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Accuracy
        >>> metric = ClasswiseWrapper(Accuracy(num_classes=3, average=None), labels=["horse", "fish", "dog"])
        >>> preds = jnp.array([0, 1, 2, 1])
        >>> target = jnp.array([0, 1, 1, 1])
        >>> sorted(metric(preds, target).keys())
        ['accuracy_dog', 'accuracy_fish', 'accuracy_horse']
    """

    #: delegates to the child metric's full eager lifecycle (telemetry,
    #: coercion); the child registry already excludes it from fusion
    __jit_unsafe__ = True

    def __init__(self, metric: Metric, labels: Optional[List[str]] = None) -> None:
        super().__init__()
        if not isinstance(metric, Metric):
            raise ValueError(f"Expected argument `metric` to be an instance of `metrics_tpu.Metric` but got {metric}")
        if labels is not None and not (isinstance(labels, list) and all(isinstance(lab, str) for lab in labels)):
            raise ValueError(f"Expected argument `labels` to either be `None` or a list of strings but got {labels}")
        self.metric = metric
        self.labels = labels

    def _convert(self, x: Array) -> Dict[str, Array]:
        name = self.metric.__class__.__name__.lower()
        if self.labels is None:
            return {f"{name}_{i}": val for i, val in enumerate(x)}
        return {f"{name}_{lab}": val for lab, val in zip(self.labels, x)}

    def _update(self, *args: Any, **kwargs: Any) -> None:
        self.metric.update(*args, **kwargs)

    def _compute(self) -> Dict[str, Array]:
        return self._convert(self.metric.compute())

    def forward(self, *args: Any, **kwargs: Any) -> Dict[str, Array]:
        return self._convert(self.metric(*args, **kwargs))

    def reset(self) -> None:
        self.metric.reset()
        super().reset()
