"""STOI tests: JAX implementation vs an INDEPENDENT loop-based numpy
implementation of the same published algorithm, plus behavioral properties.

pystoi (the reference's oracle) is not installed in this environment; two
structurally different implementations of the Taal et al. 2011 / Jensen &
Taal 2016 spec agreeing, plus the monotonicity/identity properties, stand in
for it. PESQ: the class is an injectable-scorer shell (ITU-T P.862 C library
not re-implemented — see metrics_tpu/audio/pesq.py docstring), tested for
its wiring and validation.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from metrics_tpu.audio import PerceptualEvaluationSpeechQuality, ShortTimeObjectiveIntelligibility
from metrics_tpu.functional.audio.stoi import short_time_objective_intelligibility

_EPS = np.finfo(np.float64).eps


def _np_hann(n):
    # hanning(n+2)[1:-1], written out from the definition
    return np.asarray([0.5 * (1 - np.cos(2 * np.pi * (k + 1) / (n + 1))) for k in range(n)])


def _np_resample(x, fs_in, fs_out):
    if fs_in == fs_out:
        return x
    from scipy.signal import resample_poly

    g = int(np.gcd(fs_in, fs_out))
    return resample_poly(x, fs_out // g, fs_in // g)


def _np_thirdoct(fs, nfft, num_bands, min_freq):
    freqs = np.linspace(0, fs, nfft + 1)[: nfft // 2 + 1]
    obm = np.zeros((num_bands, len(freqs)))
    for band in range(num_bands):
        center = min_freq * 2.0 ** (band / 3.0)
        f_low, f_high = center / 2 ** (1 / 6), center * 2 ** (1 / 6)
        i_low = int(np.argmin(np.abs(freqs - f_low)))
        i_high = int(np.argmin(np.abs(freqs - f_high)))
        obm[band, i_low:i_high] = 1.0
    return obm


def _np_remove_silent(x, y, dyn_range=40.0, framelen=256, hop=128):
    window = _np_hann(framelen)
    frames_x, frames_y, energies = [], [], []
    i = 0
    while i < len(x) - framelen:  # exclusive of the final boundary frame
        fx = window * x[i : i + framelen]
        fy = window * y[i : i + framelen]
        frames_x.append(fx)
        frames_y.append(fy)
        energies.append(20 * np.log10(np.linalg.norm(fx) + _EPS))
        i += hop
    if not frames_x:
        return x, y
    threshold = max(energies) - dyn_range
    kept_x = [f for f, e in zip(frames_x, energies) if e > threshold]
    kept_y = [f for f, e in zip(frames_y, energies) if e > threshold]
    out_len = (len(kept_x) - 1) * hop + framelen if kept_x else 0
    x_out, y_out = np.zeros(out_len), np.zeros(out_len)
    for i, (fx, fy) in enumerate(zip(kept_x, kept_y)):
        x_out[i * hop : i * hop + framelen] += fx
        y_out[i * hop : i * hop + framelen] += fy
    return x_out, y_out


def _numpy_stoi(deg, clean, fs, extended=False):
    """Loop-based re-derivation of the STOI spec; shares NO code with the
    library implementation (its own window/resample/octave/silence steps)."""
    x = _np_resample(np.asarray(clean, np.float64), fs, 10000)
    y = _np_resample(np.asarray(deg, np.float64), fs, 10000)
    x, y = _np_remove_silent(x, y)

    window = _np_hann(256)
    n_frames = max(-(-(len(x) - 256) // 128), 0) if len(x) > 256 else 0
    x_spec = np.stack([np.fft.rfft(window * x[i * 128 : i * 128 + 256], 512) for i in range(n_frames)])
    y_spec = np.stack([np.fft.rfft(window * y[i * 128 : i * 128 + 256], 512) for i in range(n_frames)])
    obm = _np_thirdoct(10000, 512, 15, 150.0)
    x_tob = np.sqrt(obm @ (np.abs(x_spec.T) ** 2))
    y_tob = np.sqrt(obm @ (np.abs(y_spec.T) ** 2))

    num_segments = n_frames - 30 + 1
    values = []
    for m in range(num_segments):
        xs = x_tob[:, m : m + 30]
        ys = y_tob[:, m : m + 30]
        if extended:
            def norm(seg):
                seg = seg - seg.mean(axis=1, keepdims=True)
                seg = seg / (np.linalg.norm(seg, axis=1, keepdims=True) + _EPS)
                seg = seg - seg.mean(axis=0, keepdims=True)
                return seg / (np.linalg.norm(seg, axis=0, keepdims=True) + _EPS)

            values.append(np.sum(norm(xs) * norm(ys)) / 30)
        else:
            seg_vals = []
            for j in range(15):
                alpha = np.sqrt(np.sum(xs[j] ** 2) / (np.sum(ys[j] ** 2) + _EPS))
                yp = np.minimum(alpha * ys[j], xs[j] * (1 + 10 ** (15 / 20)))
                xn = xs[j] - xs[j].mean()
                yn = yp - yp.mean()
                seg_vals.append(np.sum(xn * yn) / (np.linalg.norm(xn) * np.linalg.norm(yn) + _EPS))
            values.append(np.mean(seg_vals))
    return float(np.mean(values))


def _speechlike(rng, n, fs):
    """Modulated multi-tone with pauses — exercises silent-frame removal."""
    t = np.arange(n) / fs
    envelope = np.clip(np.sin(2 * np.pi * 2.5 * t), 0, None)
    carrier = sum(np.sin(2 * np.pi * f0 * t + rng.uniform(0, 6)) for f0 in (220, 450, 900, 1800))
    return (envelope * carrier + 0.01 * rng.standard_normal(n)).astype(np.float64)


@pytest.mark.parametrize("fs", [10000, 16000])
@pytest.mark.parametrize("extended", [False, True])
@pytest.mark.parametrize("snr_db", [20.0, 5.0])
def test_stoi_matches_independent_numpy(fs, extended, snr_db):
    rng = np.random.default_rng(0)
    clean = _speechlike(rng, 3 * fs, fs)
    noise = rng.standard_normal(len(clean))
    noise *= np.linalg.norm(clean) / (np.linalg.norm(noise) * 10 ** (snr_db / 20))
    deg = clean + noise

    got = float(short_time_objective_intelligibility(jnp.asarray(deg), jnp.asarray(clean), fs, extended))
    want = _numpy_stoi(deg, clean, fs, extended)
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_stoi_properties():
    rng = np.random.default_rng(1)
    fs = 10000
    clean = _speechlike(rng, 3 * fs, fs)
    noise = rng.standard_normal(len(clean)) * np.std(clean)

    identical = float(short_time_objective_intelligibility(jnp.asarray(clean), jnp.asarray(clean), fs))
    assert identical > 0.99  # identical signals are maximally intelligible

    scores = []
    for snr_db in (20.0, 5.0, -5.0):
        scaled = noise * np.linalg.norm(clean) / (np.linalg.norm(noise) * 10 ** (snr_db / 20))
        scores.append(
            float(short_time_objective_intelligibility(jnp.asarray(clean + scaled), jnp.asarray(clean), fs))
        )
    assert scores[0] > scores[1] > scores[2]  # monotone in SNR


def test_stoi_batched_and_class():
    rng = np.random.default_rng(2)
    fs = 10000
    clean = np.stack([_speechlike(rng, 2 * fs, fs) for _ in range(3)])
    deg = clean + 0.3 * rng.standard_normal(clean.shape) * np.std(clean)

    batched = short_time_objective_intelligibility(jnp.asarray(deg), jnp.asarray(clean), fs)
    assert batched.shape == (3,)

    metric = ShortTimeObjectiveIntelligibility(fs=fs)
    metric.update(jnp.asarray(deg[:2]), jnp.asarray(clean[:2]))
    metric.update(jnp.asarray(deg[2]), jnp.asarray(clean[2]))
    np.testing.assert_allclose(float(metric.compute()), float(jnp.mean(batched)), atol=1e-6)


def test_stoi_too_short_raises():
    with pytest.raises(ValueError, match="Not enough"):
        short_time_objective_intelligibility(jnp.zeros(500), jnp.ones(500), 10000)
    # exactly at the old inclusive boundary: still too short under the
    # exclusive pystoi frame convention
    with pytest.raises(ValueError, match="Not enough"):
        short_time_objective_intelligibility(jnp.ones(29 * 128 + 256), jnp.ones(29 * 128 + 256), 10000)
    with pytest.raises(ValueError, match="shape"):
        short_time_objective_intelligibility(jnp.zeros(1000), jnp.zeros(999), 10000)


def test_pesq_shell_wiring():
    calls = []

    def fake_pesq(ref, deg, fs, mode):
        calls.append((len(ref), fs, mode))
        return 3.5

    metric = PerceptualEvaluationSpeechQuality(fs=16000, mode="wb", pesq_fn=fake_pesq)
    metric.update(jnp.ones((2, 1600)), jnp.ones((2, 1600)))
    assert float(metric.compute()) == pytest.approx(3.5)
    assert calls == [(1600, 16000, "wb"), (1600, 16000, "wb")]

    with pytest.raises(ValueError, match="fs"):
        PerceptualEvaluationSpeechQuality(fs=44100, mode="wb", pesq_fn=fake_pesq)
    with pytest.raises(ValueError, match="mode"):
        PerceptualEvaluationSpeechQuality(fs=16000, mode="xb", pesq_fn=fake_pesq)
    with pytest.raises(ValueError, match="Wide-band"):
        PerceptualEvaluationSpeechQuality(fs=8000, mode="wb", pesq_fn=fake_pesq)
    # without an injected scorer the default resolves to the external `pesq`
    # binding when installed (bit-exact), else the in-repo P.862 engine
    from metrics_tpu.functional.audio._pesq_engine import pesq as engine_pesq
    from metrics_tpu.functional.audio.pesq import _default_pesq_fn
    from metrics_tpu.utils.imports import _PESQ_AVAILABLE

    assert PerceptualEvaluationSpeechQuality(fs=8000, mode="nb").pesq_fn is None
    if _PESQ_AVAILABLE:
        assert _default_pesq_fn() is not engine_pesq
    else:
        assert _default_pesq_fn() is engine_pesq
