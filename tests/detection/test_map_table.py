"""Fixed-capacity per-image detection table (detection/mean_ap.py) vs the
``exact=True`` list-state path — the detection mirror of
tests/retrieval/test_retrieval_table.py.

The contract under test (docs/image_detection_states.md):

* **In-window parity** — every image fits its ``det_slots``/``gt_slots``
  and the stream fits ``max_images``: compute() is bit-identical to the
  exact path on every result key (the table stores the full payload, and
  unpacking replays arrival order).
* **Reservoir determinism** — the admitted image set past ``max_images``
  is a pure function of the global image indices (deterministic hash
  keys): batch chunking never moves it, and admitted rows hold the
  COMPLETE per-image payload, so compute() equals the exact metric run
  over exactly the admitted images.
* **Capacity policy** — detections above ``det_slots`` truncate to the
  score top-k (ties to the lower index, matching `lax.top_k`); ground
  truths above ``gt_slots`` raise (silent GT truncation would bias
  recall).
* **Composition** — fused single-dispatch, ragged-shape bucketing (one
  compile), async ingest, and the 8-device mesh merge round all produce
  the same states as eager updates.
"""
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from metrics_tpu import MetricCollection
from metrics_tpu.detection import MeanAveragePrecision

# ---------------------------------------------------------------------------
# data helpers
# ---------------------------------------------------------------------------

_NEG_INF = -np.inf


def _rand_images(rng, n_images, max_det=4, max_gt=4, n_cls=3, grid=6.0):
    """Images whose boxes sit on a coarse grid with jitter, so detections
    genuinely overlap ground truths and the PR grids are non-trivial."""
    out = []
    for _ in range(n_images):
        nd = int(rng.randint(0, max_det + 1))
        ng = int(rng.randint(1, max_gt + 1))

        def boxes(k):
            xy = rng.randint(0, 4, (k, 2)).astype(np.float64) * grid + rng.rand(k, 2)
            wh = 4.0 + rng.rand(k, 2) * 4.0
            return np.concatenate([xy, xy + wh], axis=1).astype(np.float32)

        out.append(
            (
                dict(
                    boxes=boxes(nd),
                    scores=rng.rand(nd).astype(np.float32),
                    labels=rng.randint(0, n_cls, nd).astype(np.int32),
                ),
                dict(boxes=boxes(ng), labels=rng.randint(0, n_cls, ng).astype(np.int32)),
            )
        )
    return out


def _as_lists(images):
    preds = [{k: jnp.asarray(v) for k, v in p.items()} for p, _ in images]
    target = [{k: jnp.asarray(v) for k, v in t.items()} for _, t in images]
    return preds, target


def _as_padded(images, det_slots, gt_slots):
    """The padded dict batch a fused/jitted pipeline feeds directly."""
    n = len(images)
    pb = np.zeros((n, det_slots, 4), np.float32)
    ps = np.zeros((n, det_slots), np.float32)
    pl = np.zeros((n, det_slots), np.int32)
    pn = np.zeros((n,), np.int32)
    gb = np.zeros((n, gt_slots, 4), np.float32)
    gl = np.zeros((n, gt_slots), np.int32)
    gn = np.zeros((n,), np.int32)
    for i, (p, t) in enumerate(images):
        nd, ng = len(p["scores"]), len(t["labels"])
        pb[i, :nd], ps[i, :nd], pl[i, :nd], pn[i] = p["boxes"], p["scores"], p["labels"], nd
        gb[i, :ng], gl[i, :ng], gn[i] = t["boxes"], t["labels"], ng
    preds = dict(boxes=jnp.asarray(pb), scores=jnp.asarray(ps), labels=jnp.asarray(pl), n=jnp.asarray(pn))
    target = dict(boxes=jnp.asarray(gb), labels=jnp.asarray(gl), n=jnp.asarray(gn))
    return preds, target


def _exact_map(**kw):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return MeanAveragePrecision(exact=True, **kw)


def _results_equal(a, b):
    assert set(a) == set(b)
    for k in a:
        np.testing.assert_array_equal(
            np.asarray(a[k]).ravel(), np.asarray(b[k]).ravel(), err_msg=k
        )


def _admitted(table):
    """(global_idx, n_det, n_gt) for the live rows, arrival-sorted."""
    leaf = np.asarray(table)
    rows = leaf[leaf[:, 0] > _NEG_INF]
    rows = rows[np.lexsort((rows[:, 1], rows[:, 2]))]
    return rows[:, 1].astype(int), rows[:, 3].astype(int), rows[:, 4].astype(int)


# ---------------------------------------------------------------------------
# in-window parity
# ---------------------------------------------------------------------------


def test_in_window_bit_parity_with_exact():
    rng = np.random.RandomState(0)
    images = _rand_images(rng, 18)
    streaming = MeanAveragePrecision()
    exact = _exact_map()
    for lo in (0, 6, 12):
        p, t = _as_lists(images[lo : lo + 6])
        streaming.update(p, t)
        exact.update(p, t)
    _results_equal(streaming.compute(), exact.compute())


def test_xywh_format_in_window_parity():
    rng = np.random.RandomState(1)
    images = _rand_images(rng, 8)
    # re-express the xyxy helper boxes as xywh
    for p, t in images:
        for d in (p, t):
            d["boxes"] = np.concatenate(
                [d["boxes"][:, :2], d["boxes"][:, 2:] - d["boxes"][:, :2]], axis=1
            )
    streaming = MeanAveragePrecision(box_format="xywh")
    exact = _exact_map(box_format="xywh")
    p, t = _as_lists(images)
    streaming.update(p, t)
    exact.update(p, t)
    _results_equal(streaming.compute(), exact.compute())


def test_chunking_invariance_is_bitwise():
    """Identical stream, different batch splits: the table leaf itself is
    bit-identical (hash keys depend only on the global image index)."""
    rng = np.random.RandomState(2)
    images = _rand_images(rng, 24)

    def run(*cuts):
        m = MeanAveragePrecision(max_images=16)  # past capacity: 24 > 16
        lo = 0
        for hi in (*cuts, len(images)):
            p, t = _as_lists(images[lo:hi])
            m.update(p, t)
            lo = hi
        return m

    a, b, c = run(12), run(5, 9, 17), run(1, 2, 3, 23)
    assert jnp.array_equal(a.table, b.table)
    assert jnp.array_equal(a.table, c.table)
    assert int(a.images_seen) == int(b.images_seen) == 24
    _results_equal(a.compute(), b.compute())


def test_admitted_images_are_complete_past_capacity():
    """An admitted image's row carries its FULL payload (admission happens
    at first sight, whole-image), so compute() equals the exact metric run
    over exactly the admitted subset."""
    rng = np.random.RandomState(3)
    images = _rand_images(rng, 30)
    small = MeanAveragePrecision(max_images=8)
    p, t = _as_lists(images)
    small.update(p, t)

    idx, nd, ng = _admitted(small.table)
    assert len(idx) == 8 and int(small.images_seen) == 30
    for i, d, g in zip(idx, nd, ng):
        assert d == len(images[i][0]["scores"])
        assert g == len(images[i][1]["labels"])

    exact = _exact_map()
    p_sub, t_sub = _as_lists([images[i] for i in idx])
    exact.update(p_sub, t_sub)
    _results_equal(small.compute(), exact.compute())


# ---------------------------------------------------------------------------
# capacity policy
# ---------------------------------------------------------------------------


def test_det_overflow_truncates_to_score_topk():
    """150 detections into det_slots=100 (the default cap): the stored rows
    are the score top-100, bit-matching an exact metric fed the same
    host-side top-100 (stable argsort, ties to the lower index)."""
    rng = np.random.RandomState(4)
    nd = 150
    boxes = np.concatenate([rng.rand(nd, 2) * 20, 20 + rng.rand(nd, 2) * 20 + 5], 1).astype(np.float32)
    scores = rng.rand(nd).astype(np.float32)
    labels = rng.randint(0, 2, nd).astype(np.int32)
    gt = dict(boxes=boxes[:6] + 1.0, labels=labels[:6])

    m = MeanAveragePrecision()
    m.update(
        [dict(boxes=jnp.asarray(boxes), scores=jnp.asarray(scores), labels=jnp.asarray(labels))],
        [{k: jnp.asarray(v) for k, v in gt.items()}],
    )
    keep = np.sort(np.argsort(-scores, kind="stable")[:100])
    exact = _exact_map()
    exact.update(
        [dict(boxes=jnp.asarray(boxes[keep]), scores=jnp.asarray(scores[keep]), labels=jnp.asarray(labels[keep]))],
        [{k: jnp.asarray(v) for k, v in gt.items()}],
    )
    _results_equal(m.compute(), exact.compute())


def test_gt_overflow_raises_with_remedy():
    m = MeanAveragePrecision(max_detection_thresholds=[1, 4], det_slots=4, gt_slots=4)
    boxes = jnp.asarray(np.tile([[0.0, 0.0, 5.0, 5.0]], (6, 1)))
    with pytest.raises(ValueError, match="gt_slots"):
        m.update(
            [dict(boxes=boxes[:1], scores=jnp.asarray([0.5]), labels=jnp.asarray([0]))],
            [dict(boxes=boxes, labels=jnp.zeros((6,), jnp.int32))],
        )


def test_exact_mode_is_jit_unsafe_table_is_not():
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        assert MeanAveragePrecision(exact=True).__jit_unsafe__ is True
    m = MeanAveragePrecision()
    assert not getattr(m, "__jit_unsafe__", False)
    entry = MeanAveragePrecision.static_fusibility()
    assert entry is not None and entry["verdict"] == "fusible"
    assert entry["states"]["table"]["dist_reduce_fx"] == "merge"


# ---------------------------------------------------------------------------
# merge / distributed
# ---------------------------------------------------------------------------


def test_merge_states_equals_single_stream():
    rng = np.random.RandomState(5)
    images = _rand_images(rng, 16)
    kw = dict(max_images=64)
    m1, m2 = MeanAveragePrecision(**kw), MeanAveragePrecision(**kw)
    p1, t1 = _as_lists(images[:9])
    p2, t2 = _as_lists(images[9:])
    m1.update(p1, t1)
    m2.update(p2, t2)
    merged = m1.merge_states(
        {k: getattr(m1, k) for k in m1._defaults}, {k: getattr(m2, k) for k in m2._defaults}
    )
    full = MeanAveragePrecision(**kw)
    p, t = _as_lists(images)
    full.update(p, t)
    assert int(merged["images_seen"]) == 16
    _results_equal(full.compute_state(merged), full.compute())


def test_mesh_merge_round_equals_host_fold():
    from jax.sharding import Mesh, PartitionSpec as P

    from metrics_tpu.parallel.distributed import sync_pytree_in_mesh
    from metrics_tpu.utils.compat import shard_map

    kw = dict(max_images=64, det_slots=4, gt_slots=4, max_detection_thresholds=[1, 4])
    rng = np.random.RandomState(6)
    states, streams = [], []
    for r in range(8):
        m = MeanAveragePrecision(**kw)
        images = _rand_images(rng, 4)
        m.update(*_as_padded(images, 4, 4))
        states.append({k: jnp.asarray(getattr(m, k)) for k in m._defaults})
        streams.append(images)
    template = MeanAveragePrecision(**kw)
    reductions = template.state_reductions()
    stacked = {k: jnp.stack([s[k] for s in states]) for k in states[0]}
    mesh = Mesh(np.array(jax.devices()[:8]), ("rank",))

    def body(st):
        return sync_pytree_in_mesh({k: v[0] for k, v in st.items()}, reductions, "rank")

    synced = jax.jit(shard_map(body, mesh=mesh, in_specs=(P("rank"),), out_specs=P()))(stacked)
    for k in synced:
        assert jnp.array_equal(synced[k], reductions[k](stacked[k])), k
    # in-window: the synced table holds every rank's images -> fold equals
    # one metric over the union stream
    union = MeanAveragePrecision(**kw)
    for images in streams:
        union.update(*_as_padded(images, 4, 4))
    assert int(synced["images_seen"]) == 32
    _results_equal(union.compute_state(synced), union.compute())


# ---------------------------------------------------------------------------
# fused / bucketed / async composition
# ---------------------------------------------------------------------------


def _ragged_padded_batches(seed=7):
    rng = np.random.RandomState(seed)
    return [_as_padded(_rand_images(rng, n), 4, 4) for n in (3, 5, 7)]


_FUSED_KW = dict(max_images=64, det_slots=4, gt_slots=4, max_detection_thresholds=[1, 4])


def test_fused_bucketed_single_compile_bit_parity():
    fused = MetricCollection([MeanAveragePrecision(**_FUSED_KW)])
    eager = MetricCollection([MeanAveragePrecision(**_FUSED_KW)])
    handle = fused.compile_update(buckets=[8])
    for p, t in _ragged_padded_batches():
        fused.update(p, t)
        eager.update(p, t)
    assert len(handle._cache) == 1  # ONE compile across 3 ragged shapes
    assert not handle._eager_names  # nobody fell back eagerly
    _results_equal(fused.compute(), eager.compute())
    fm, em = fused["MeanAveragePrecision"], eager["MeanAveragePrecision"]
    assert jnp.array_equal(fm.table, em.table)
    assert jnp.array_equal(fm.images_seen, em.images_seen)


def test_async_ingest_bit_parity():
    a = MetricCollection([MeanAveragePrecision(**_FUSED_KW)])
    b = MetricCollection([MeanAveragePrecision(**_FUSED_KW)])
    a.compile_update_async(buckets=[8])
    for p, t in _ragged_padded_batches(8):
        a.update_async(p, t)
        b.update(p, t)
    _results_equal(a.compute(), b.compute())
