"""Shared forced-CPU virtual-device setup, imported by BOTH conftests
(repo root for doctest runs, tests/ for the suite) so the config cannot
drift between them.

A pytest plugin (jaxtyping) imports jax before conftests run, so the
platform must be set via ``jax.config.update`` (still possible until the
backend is first queried), and the XLA flag via the environment (read at
backend initialization).
"""
import os

VIRTUAL_DEVICES = 8


def setup_forced_cpu() -> None:
    if os.environ.get("METRICS_TPU_TEST_ON_TPU"):
        # escape hatch for the on-hardware runs (compiled Pallas tests in
        # tests/ops, spot parity checks): keep the real backend. The
        # device-count assert in tests/conftest.py is skipped accordingly.
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={VIRTUAL_DEVICES}"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")
