#!/usr/bin/env python
"""Fail if any ``metrics_tpu/`` module calls ``print()`` or a bare
``warnings.warn`` directly.

All user-facing output from library code must route through the rank-zero
helpers in ``metrics_tpu/utils/prints.py`` so multi-host jobs emit one copy
and logging stays filterable.

This script is now a thin alias over tracelint's **TL-PRINT** rule
(``metrics_tpu/analysis/``) so one engine owns every convention check —
same contract as before: exit 0 when clean, 1 with a ``path:line`` listing
otherwise. Run from anywhere:

    python scripts/check_no_print.py

Equivalent: ``python scripts/tracelint.py --rules TL-PRINT --no-baseline``.
"""
import pathlib
import sys

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT / "scripts"))

from tracelint import load_analysis  # noqa: E402


def main() -> int:
    load_analysis()
    from metrics_tpu.analysis import analyze_paths, get_rules

    result = analyze_paths(rules=get_rules(["TL-PRINT"]))
    if result.violations:
        sys.stderr.write(
            "raw print()/warnings.warn() calls found in metrics_tpu/ — use the"
            " rank-zero helpers from metrics_tpu/utils/prints.py instead:\n"
        )
        for v in result.violations:
            kind = "print()" if v.message.startswith("raw print") else "warnings.warn()"
            sys.stderr.write(f"  metrics_tpu/{v.path}:{v.line} ({kind})\n")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
