"""Precision-recall curve — the shared sorted-curve kernel for the exact
(curve/ranking) classification family.

Behavior parity with /root/reference/torchmetrics/functional/classification/
precision_recall_curve.py:23-343. ``_binary_clf_curve`` is the sklearn-derived
sort → dedupe-thresholds → cumsum kernel. These exact-mode functions produce
data-dependent output shapes (distinct thresholds), so they run host-eager /
outside jit; the fixed-shape jit-native alternative is the Binned* family
(metrics_tpu/classification/binned_precision_recall.py).
"""
from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.utils.prints import rank_zero_warn

Array = jax.Array


def _binary_clf_curve(
    preds: Array,
    target: Array,
    sample_weights: Optional[Sequence] = None,
    pos_label: int = 1,
) -> Tuple[Array, Array, Array]:
    """Sorted cumulative fps/tps per distinct threshold (sklearn-derived)."""
    if sample_weights is not None and not isinstance(sample_weights, jnp.ndarray):
        sample_weights = jnp.asarray(sample_weights, dtype=jnp.float32)

    if preds.ndim > target.ndim:
        preds = preds[:, 0]
    desc_score_indices = jnp.argsort(-preds)

    preds = preds[desc_score_indices]
    target = target[desc_score_indices]

    weight = sample_weights[desc_score_indices] if sample_weights is not None else 1.0

    distinct_value_indices = jnp.nonzero(preds[1:] - preds[:-1])[0]
    threshold_idxs = jnp.concatenate([distinct_value_indices, jnp.array([target.shape[0] - 1])])
    target = (target == pos_label).astype(jnp.int32)
    tps = jnp.cumsum(target * weight, axis=0)[threshold_idxs]

    if sample_weights is not None:
        fps = jnp.cumsum((1 - target) * weight, axis=0)[threshold_idxs]
    else:
        fps = 1 + threshold_idxs - tps

    return fps, tps, preds[threshold_idxs]


def _precision_recall_curve_update(
    preds: Array,
    target: Array,
    num_classes: Optional[int] = None,
    pos_label: Optional[int] = None,
) -> Tuple[Array, Array, int, Optional[int]]:
    """Canonicalize curve inputs to flat binary / (N,C) layouts.

    Reference precision_recall_curve.py:64-122.
    """
    if preds.ndim == target.ndim:
        if pos_label is None:
            pos_label = 1
        if num_classes is not None and num_classes != 1:
            # multilabel problem
            if num_classes != preds.shape[1]:
                raise ValueError(
                    f"Argument `num_classes` was set to {num_classes} in"
                    f" metric `precision_recall_curve` but detected {preds.shape[1]}"
                    " number of classes from predictions"
                )
            preds = jnp.swapaxes(preds, 0, 1).reshape(num_classes, -1).T
            target = jnp.swapaxes(target, 0, 1).reshape(num_classes, -1).T
        else:
            preds = preds.flatten()
            target = target.flatten()
            num_classes = 1
    elif preds.ndim == target.ndim + 1:
        if pos_label is not None:
            rank_zero_warn(
                "Argument `pos_label` should be `None` when running"
                f" multiclass precision recall curve. Got {pos_label}"
            )
        if num_classes != preds.shape[1]:
            raise ValueError(
                f"Argument `num_classes` was set to {num_classes} in"
                f" metric `precision_recall_curve` but detected {preds.shape[1]}"
                " number of classes from predictions"
            )
        preds = jnp.swapaxes(preds, 0, 1).reshape(num_classes, -1).T
        target = target.flatten()
    else:
        raise ValueError("preds and target must have same number of dimensions, or one additional dimension for preds")

    return preds, target, num_classes, pos_label


def _precision_recall_curve_compute_single_class(
    preds: Array,
    target: Array,
    pos_label: int,
    sample_weights: Optional[Sequence] = None,
) -> Tuple[Array, Array, Array]:
    fps, tps, thresholds = _binary_clf_curve(
        preds=preds, target=target, sample_weights=sample_weights, pos_label=pos_label
    )
    precision = tps / (tps + fps)
    recall = tps / tps[-1]

    # stop when full recall attained; reverse so recall is decreasing
    last_ind = int(jnp.nonzero(tps == tps[-1], size=1)[0][0])
    sl = slice(0, last_ind + 1)

    precision = jnp.concatenate([precision[sl][::-1], jnp.ones(1, dtype=precision.dtype)])
    recall = jnp.concatenate([recall[sl][::-1], jnp.zeros(1, dtype=recall.dtype)])
    thresholds = thresholds[sl][::-1]

    return precision, recall, thresholds


def _precision_recall_curve_compute_multi_class(
    preds: Array,
    target: Array,
    num_classes: int,
    sample_weights: Optional[Sequence] = None,
) -> Tuple[List[Array], List[Array], List[Array]]:
    precision, recall, thresholds = [], [], []
    for cls in range(num_classes):
        preds_cls = preds[:, cls]
        prc_args = dict(
            preds=preds_cls,
            target=target,
            num_classes=1,
            pos_label=cls,
            sample_weights=sample_weights,
        )
        if target.ndim > 1:
            prc_args.update(dict(target=target[:, cls], pos_label=1))
        res = precision_recall_curve(**prc_args)
        precision.append(res[0])
        recall.append(res[1])
        thresholds.append(res[2])
    return precision, recall, thresholds


def _precision_recall_curve_compute(
    preds: Array,
    target: Array,
    num_classes: int,
    pos_label: Optional[int] = None,
    sample_weights: Optional[Sequence] = None,
) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
    if num_classes == 1:
        if pos_label is None:
            pos_label = 1
        return _precision_recall_curve_compute_single_class(preds, target, pos_label, sample_weights)
    return _precision_recall_curve_compute_multi_class(preds, target, num_classes, sample_weights)


def precision_recall_curve(
    preds: Array,
    target: Array,
    num_classes: Optional[int] = None,
    pos_label: Optional[int] = None,
    sample_weights: Optional[Sequence] = None,
) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
    """Computes precision-recall pairs for different thresholds.

    Example:
        >>> import jax.numpy as jnp
        >>> pred = jnp.array([0., 1., 2., 3.])
        >>> target = jnp.array([0, 1, 1, 0])
        >>> precision, recall, thresholds = precision_recall_curve(pred, target, pos_label=1)
        >>> precision
        Array([0.6666667, 0.5      , 0.       , 1.       ], dtype=float32)
        >>> recall
        Array([1. , 0.5, 0. , 0. ], dtype=float32)
        >>> thresholds
        Array([1., 2., 3.], dtype=float32)
    """
    preds, target, num_classes, pos_label = _precision_recall_curve_update(preds, target, num_classes, pos_label)
    return _precision_recall_curve_compute(preds, target, num_classes, pos_label, sample_weights)
